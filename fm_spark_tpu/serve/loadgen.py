"""Seeded traffic-replay load generator (ISSUE 17).

Millions-of-users traffic *shapes* — diurnal ramps, flash crowds, slow
clients, retry storms — as pure functions of a seed, replayed against
the serving front door over plain HTTP. The generator is the traffic
half of the fleet chaos surface: :mod:`fm_spark_tpu.resilience.chaos`
composes these schedules with fault plans (``replica_kill``,
``fleet_dispatch``, ``serve_reload``) and the auditor grades the run
from the **tap** alone — a JSONL journal with one record per attempt
(request id, attempt number, priority class, HTTP status, outcome,
latency, the generation that scored it). Same purity contract as every
chaos schedule: ``make_schedule(shape, seed)`` is deterministic, so a
failing campaign entry IS its repro.

No dependencies beyond the stdlib: ``http.client`` for transport,
:class:`~fm_spark_tpu.utils.logging.EventLog` for the tap.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import math
import random
import threading
import time

from fm_spark_tpu import obs
from fm_spark_tpu.utils.logging import EventLog, read_events

__all__ = [
    "SHAPES",
    "TrafficEvent",
    "TrafficSchedule",
    "event_payload",
    "make_schedule",
    "run_loadgen",
    "summarize_tap",
]

#: The traffic-shape vocabulary (the chaos generator samples from it).
#: Append-only: ``SHAPES.index`` seeds each shape's rng, so reordering
#: would silently re-roll every existing schedule.
SHAPES = ("diurnal", "flash_crowd", "slow_clients", "retry_storm",
          "partition_storm")

#: Terminal attempt outcomes written to the tap. ``ok`` is the only
#: success; everything else is an explicit failure the client SAW —
#: the auditor's exactly-once invariant counts these, so a silently
#: dropped request shows up as an attempt with no terminal record.
OUTCOMES = ("ok", "shed", "rejected", "timeout", "error",
            "client_timeout")


@dataclasses.dataclass(frozen=True)
class TrafficEvent:
    """One logical client request within a schedule."""

    idx: int                 # position in the schedule (payload seed)
    t_offset_s: float        # send time relative to replay start
    req_id: str
    cls: str                 # priority class name
    rows: int
    deadline_ms: float
    slow_s: float = 0.0      # client-side stall mid-request (slow POST)
    max_retries: int = 0     # client retries on shed/error, never on ok


@dataclasses.dataclass(frozen=True)
class TrafficSchedule:
    """An ordered, seeded replay script."""

    shape: str
    seed: int
    events: tuple
    duration_s: float

    @property
    def n_requests(self) -> int:
        return len(self.events)


def make_schedule(shape: str, seed: int, *, duration_s: float = 1.5,
                  base_rps: float = 60.0, rows: int = 2,
                  deadline_ms: float = 500.0) -> TrafficSchedule:
    """Build one seeded traffic schedule. Pure function of its
    arguments — two calls with the same (shape, seed, knobs) replay
    byte-identical traffic.

    ``diurnal``       sinusoidal rate ramp over the window (the
                      compressed day): trough 30% of ``base_rps``,
                      peak 170%
    ``flash_crowd``   a quiet baseline, then ~40% into the window a
                      burst of 2-3s worth of traffic lands inside
                      ~120ms
    ``slow_clients``  moderate rate, but a seeded third of clients
                      stall mid-POST (they hold a server thread while
                      interactive traffic keeps its deadline)
    ``retry_storm``   over-capacity rate with deadlines tight enough
                      to shed, and every client retrying — the storm
                      only converges because 429s carry Retry-After
    ``partition_storm`` steady demand where EVERY client retries
                      (a parent↔replica partition surfaces as 503s,
                      and sheds as 429s — both retried, honoring the
                      door's jittered Retry-After), plus a surge at
                      ~55% of the window: the deferred traffic
                      replaying just after a canonical partition
                      window heals
    """
    if shape not in SHAPES:
        raise ValueError(f"unknown traffic shape {shape!r}; "
                         f"known: {SHAPES}")
    # SHAPES.index, not hash(): str hashing is salted per process and
    # the schedule must replay identically across processes.
    rng = random.Random((int(seed) << 8) ^ SHAPES.index(shape))
    duration_s = float(duration_s)
    events = []

    def add(t, cls, *, dl=None, slow=0.0, retries=0):
        idx = len(events)
        events.append(TrafficEvent(
            idx=idx, t_offset_s=round(max(0.0, t), 4),
            req_id=f"{shape[:2]}{int(seed)}-{idx:05d}", cls=cls,
            rows=max(1, rows), deadline_ms=float(dl or deadline_ms),
            slow_s=round(slow, 3), max_retries=int(retries)))

    def cls_for(r):
        # ~70/20/10 interactive/batch/background, seeded.
        return ("interactive" if r < 0.7
                else "batch" if r < 0.9 else "background")

    if shape == "diurnal":
        t = 0.0
        while t < duration_s:
            # Rate ramps through one compressed "day".
            frac = t / duration_s
            rate = base_rps * (1.0 + 0.7 * math.sin(
                2.0 * math.pi * (frac - 0.25)))
            rate = max(rate, 0.3 * base_rps)
            t += rng.expovariate(rate)
            if t < duration_s:
                add(t, cls_for(rng.random()))
    elif shape == "flash_crowd":
        t = 0.0
        while t < duration_s:
            t += rng.expovariate(0.4 * base_rps)
            if t < duration_s:
                add(t, cls_for(rng.random()))
        t_spike = 0.4 * duration_s
        n_spike = int(base_rps * (2.0 + rng.random()))
        for _ in range(n_spike):
            add(t_spike + rng.random() * 0.12, "interactive",
                retries=1)
        events.sort(key=lambda e: e.t_offset_s)
        events[:] = [dataclasses.replace(e, idx=i)
                     for i, e in enumerate(events)]
    elif shape == "slow_clients":
        t = 0.0
        while t < duration_s:
            t += rng.expovariate(0.8 * base_rps)
            if t >= duration_s:
                break
            if rng.random() < 0.33:
                # Slow client: stalls mid-POST for a good chunk of the
                # window, on a lenient background deadline.
                add(t, "background", dl=8.0 * deadline_ms,
                    slow=0.15 + 0.25 * rng.random())
            else:
                add(t, "interactive")
    elif shape == "retry_storm":
        t = 0.0
        while t < duration_s:
            t += rng.expovariate(1.6 * base_rps)
            if t < duration_s:
                add(t, cls_for(rng.random()),
                    dl=0.25 * deadline_ms, retries=2)
    else:  # partition_storm
        t = 0.0
        while t < duration_s:
            t += rng.expovariate(1.2 * base_rps)
            if t < duration_s:
                add(t, cls_for(rng.random()), retries=3)
        t_surge = 0.55 * duration_s
        n_surge = int(base_rps * (1.0 + rng.random()))
        for _ in range(n_surge):
            add(t_surge + rng.random() * 0.2, "interactive",
                retries=3)
        events.sort(key=lambda e: e.t_offset_s)
        events[:] = [dataclasses.replace(e, idx=i)
                     for i, e in enumerate(events)]

    return TrafficSchedule(shape=shape, seed=int(seed),
                           events=tuple(events),
                           duration_s=duration_s)


def event_payload(ev: TrafficEvent, schedule: TrafficSchedule, *,
                  nnz: int, num_features: int):
    """Deterministic feature rows for one event: seeded by (schedule
    seed, event idx), so a replayed schedule scores identical rows."""
    rng = random.Random((int(schedule.seed) << 20) ^ int(ev.idx))
    ids = [[rng.randrange(num_features) for _ in range(nnz)]
           for _ in range(ev.rows)]
    vals = [[round(rng.random(), 6) for _ in range(nnz)]
            for _ in range(ev.rows)]
    return ids, vals


def _post_predict(host: str, port: int, body: bytes, *,
                  timeout_s: float, slow_s: float = 0.0):
    """One HTTP attempt. A slow client sends headers, stalls, then the
    body — holding a server handler thread exactly the way a congested
    mobile uplink does."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)  # fmlint: disable=fleet-transport-discipline -- the loadgen IS the client: it models user traffic arriving from outside the fleet's transport boundary, so the parent-side netfault plane must not intercept it (partitions sever the parent<->replica link, not the user<->door link)
    try:
        conn.putrequest("POST", "/predict")  # fmlint: disable=trace-propagation -- client side of the trust boundary: traces are MINTED at the front door (inbound X-FM-Trace is ignored there); the response's trace id tags the tap instead
        conn.putheader("Content-Type", "application/json")
        conn.putheader("Content-Length", str(len(body)))
        conn.endheaders()
        if slow_s > 0.0:
            half = len(body) // 2
            conn.send(body[:half])
            time.sleep(slow_s)
            conn.send(body[half:])
        else:
            conn.send(body)
        resp = conn.getresponse()
        payload = resp.read()
        try:
            doc = json.loads(payload.decode() or "{}")
        except ValueError:
            doc = {}
        return resp.status, doc
    finally:
        conn.close()


_STATUS_OUTCOME = {200: "ok", 400: "rejected", 429: "shed",
                   500: "error", 503: "error", 504: "timeout"}


def run_loadgen(host: str, port: int, schedule: TrafficSchedule,
                tap_path: str, *, nnz: int, num_features: int,
                threads: int = 8, attempt_timeout_s: float = 10.0,
                time_scale: float = 1.0) -> dict:
    """Replay one schedule against a front door, journaling every
    attempt to the tap. Returns :func:`summarize_tap` of the run.

    ``time_scale`` compresses/stretches the schedule clock (drills run
    the same shape faster). Retries honor the server's Retry-After
    (capped at 100ms so a drill-sized storm converges inside its
    budget) and NEVER follow a 200 — exactly-once by construction on
    the client side; the auditor re-proves it from the tap.
    """
    tap = EventLog(tap_path)
    tap_lock = threading.Lock()
    work = list(schedule.events)
    work_lock = threading.Lock()
    t0 = time.monotonic()

    def emit(ev, attempt, status, outcome, t_send, doc):
        with tap_lock:
            tap.emit("attempt", req_id=ev.req_id, attempt=attempt,
                     cls=ev.cls, rows=ev.rows, status=status,
                     outcome=outcome,
                     latency_ms=round(
                         (time.monotonic() - t_send) * 1e3, 3),
                     gen_step=doc.get("generation_step"),
                     replica=doc.get("replica"),
                     trace=doc.get("trace"),
                     retry_after_ms=doc.get("retry_after_ms"))

    def one_event(ev):
        target = t0 + ev.t_offset_s * time_scale
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        ids, vals = event_payload(ev, schedule, nnz=nnz,
                                  num_features=num_features)
        body = json.dumps({  # fmlint: disable=eventlog-only -- HTTP request wire format, not a journal write (the tap IS an EventLog)
            "id": ev.req_id, "class": ev.cls,
            "deadline_ms": ev.deadline_ms, "ids": ids, "vals": vals,
        }).encode()
        for attempt in range(1, ev.max_retries + 2):
            t_send = time.monotonic()
            t_send_wall = time.time()
            try:
                status, doc = _post_predict(
                    host, port, body,
                    timeout_s=attempt_timeout_s, slow_s=ev.slow_s)
                outcome = _STATUS_OUTCOME.get(status, "error")
            except TimeoutError:
                status, doc, outcome = None, {}, "client_timeout"
            except OSError:
                # Connection died under us (replica kill mid-burst
                # surfaces here when the FRONT DOOR dies; a replica
                # death is absorbed by the fleet's retry): an explicit
                # client-visible failure, eligible for retry.
                status, doc, outcome = None, {}, "error"
            emit(ev, attempt, status, outcome, t_send, doc)
            if outcome == "ok" and doc.get("trace"):
                # Retroactive client-side hop: when the loadgen runs
                # in an obs-configured process, the request's full
                # round trip joins the merged trace (wall start,
                # monotonic duration).
                obs.emit_span(
                    "client/request", t_send_wall,
                    time.monotonic() - t_send,
                    trace=doc["trace"], req_id=ev.req_id,
                    attempt=attempt, cls=ev.cls)
            if outcome == "ok" or attempt > ev.max_retries:
                return
            if outcome == "rejected":
                return  # malformed stays malformed; retry is hammering
            backoff = min((doc.get("retry_after_ms") or 5.0) / 1e3,
                          0.1)
            time.sleep(backoff)

    def worker():
        while True:
            with work_lock:
                if not work:
                    return
                ev = work.pop(0)
            one_event(ev)

    pool = [threading.Thread(target=worker, name=f"loadgen-{i}",
                             daemon=True)
            for i in range(max(1, int(threads)))]
    for th in pool:
        th.start()
    for th in pool:
        th.join()
    return summarize_tap(tap_path)


def summarize_tap(tap_path: str) -> dict:
    """Aggregate one tap into the numbers bench/audits consume."""
    events = [e for e in read_events(tap_path)
              if e.get("event") == "attempt"]
    by_outcome: dict[str, int] = {}
    by_cls: dict[str, dict] = {}
    ok_lat = []
    for e in events:
        out = e.get("outcome") or "?"
        by_outcome[out] = by_outcome.get(out, 0) + 1
        c = by_cls.setdefault(e.get("cls") or "?",
                              {"attempts": 0, "ok": 0, "shed": 0})
        c["attempts"] += 1
        if out == "ok":
            c["ok"] += 1
            ok_lat.append(float(e.get("latency_ms") or 0.0))
        elif out == "shed":
            c["shed"] += 1
    ok_lat.sort()

    def pct(p):
        if not ok_lat:
            return None
        k = max(0, min(len(ok_lat) - 1,
                       int(round(p / 100.0 * (len(ok_lat) - 1)))))
        return round(ok_lat[k], 3)

    req_ids = {e.get("req_id") for e in events}
    return {
        "attempts": len(events),
        "requests": len(req_ids),
        "by_outcome": by_outcome,
        "by_class": by_cls,
        "ok_p50_ms": pct(50), "ok_p99_ms": pct(99),
    }
