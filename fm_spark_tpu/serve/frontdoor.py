"""Serving front door: stdlib HTTP transport + deadline-aware
admission control (ISSUE 17).

The PR-11 :class:`~fm_spark_tpu.serve.engine.PredictEngine` deliberately
stopped at an in-process submit/future API. This module puts a real
service on it, in the :mod:`fm_spark_tpu.obs.export` idiom (stdlib
``http.server``, no new dependencies):

``POST /predict``   score a request — JSON ``{"id", "class",
                    "deadline_ms", "ids", "vals"}`` → ``200`` with
                    scores + the generation that produced them,
                    ``429`` + ``Retry-After`` when shed, ``400`` when
                    rejected, ``504`` when the deadline expired after
                    admission, ``503`` on an explicit backend failure
``GET /healthz``    readiness + per-replica fleet state + admission
                    snapshot
``GET /metrics``    the live metrics registry (Prometheus text), which
                    carries every admission counter below

Admission control sheds **before** the coalescer: a request whose SLO
is unpayable under the current estimated wait is answered ``429``
immediately — it never consumes queue slots, batch capacity, or device
time. Priority classes are ordered (first = highest); a class's wait
estimate counts only traffic at its own priority and above, so under
pressure background traffic sheds first while interactive keeps its
deadline. Per-class queues are bounded: the queue-full shed is the
load-shedding backstop that keeps the door's memory flat under a
retry storm. Every verdict is counted (``frontdoor.accepted_total``,
``frontdoor.shed_total`` (+ per class/reason), ``frontdoor.
timeout_total``, ``frontdoor.rejected_total``, ``frontdoor.
failed_total``, ``frontdoor.answered_total``) and the chaos auditor
cross-checks the tap against these exact counters.

Admitted requests carry an absolute deadline into the engine coalescer
(:meth:`PredictEngine.submit`): the batcher stops gathering at the
batch's earliest deadline and expires queued work it can no longer
answer in time. One ``frontdoor_request`` watchdog phase guards the
admitted request end-to-end; the ``frontdoor_accept`` fault point
fires per inbound request before admission.
"""

from __future__ import annotations

import dataclasses
import http.server
import inspect
import json
import random
import socketserver
import threading
import time

from fm_spark_tpu import obs
from fm_spark_tpu.obs import export as obs_export
from fm_spark_tpu.resilience import faults, watchdog

__all__ = [
    "DEFAULT_CLASSES",
    "AdmissionController",
    "BackendError",
    "ClassSpec",
    "FrontDoor",
    "LocalBackend",
    "Verdict",
    "parse_classes",
]

#: Default priority ladder, highest first: ``name:queue_cap:
#: default_deadline_ms``. Order IS priority — interactive's wait
#: estimate ignores batch/background traffic; background queues behind
#: everyone and sheds first.
DEFAULT_CLASSES = "interactive:64:500,batch:64:2000,background:32:8000"


class BackendError(RuntimeError):
    """The backend failed an admitted request explicitly (after any
    retry policy it owns) — surfaces as a 503, never a silent drop."""


@dataclasses.dataclass(frozen=True)
class ClassSpec:
    name: str
    priority: int            # 0 = highest (position in the spec)
    queue_cap: int
    default_deadline_ms: float


def parse_classes(spec: str) -> tuple[ClassSpec, ...]:
    """Parse the ``name:cap:deadline_ms`` ladder (priority = order)."""
    out = []
    for i, part in enumerate(p for p in spec.split(",") if p.strip()):
        bits = part.strip().split(":")
        if len(bits) != 3:
            raise ValueError(
                f"class spec {part!r}: want name:queue_cap:deadline_ms")
        name, cap, dl = bits
        cap_i, dl_f = int(cap), float(dl)
        if not name or cap_i < 1 or dl_f <= 0:
            raise ValueError(f"class spec {part!r}: need a name, "
                             "cap >= 1 and deadline > 0")
        out.append(ClassSpec(name, i, cap_i, dl_f))
    if not out:
        raise ValueError(f"empty class spec {spec!r}")
    if len({c.name for c in out}) != len(out):
        raise ValueError(f"duplicate class name in {spec!r}")
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class Verdict:
    decision: str            # admitted | shed_queue | shed_deadline
    #                        # | rejected
    est_ms: float
    retry_after_ms: float = 0.0

    @property
    def admitted(self) -> bool:
        return self.decision == "admitted"


class AdmissionController:
    """Deadline-aware, priority-ordered admission.

    The wait estimate is deliberately simple and honest: an EWMA of
    observed per-request service time, multiplied by the number of
    requests already admitted at this class's priority or higher
    (they are ahead of us or indistinguishable from us), plus one
    service time for the request itself. If that exceeds the request's
    deadline the SLO is unpayable NOW — shedding is cheaper for
    everyone than queueing work we already know we will time out.
    """

    def __init__(self, classes: "str | tuple[ClassSpec, ...]"
                 = DEFAULT_CLASSES, *,
                 service_est_ms: float = 5.0, ewma_alpha: float = 0.2,
                 retry_jitter_frac: float = 0.5,
                 jitter_seed: int = 0):
        self.classes = (parse_classes(classes)
                        if isinstance(classes, str) else tuple(classes))
        self._by_name = {c.name: c for c in self.classes}
        self._lock = threading.Lock()
        self._inflight = {c.name: 0 for c in self.classes}
        self._service_ms = float(service_est_ms)
        self._alpha = float(ewma_alpha)
        if not 0.0 <= retry_jitter_frac <= 1.0:
            raise ValueError(f"retry_jitter_frac in [0,1], "
                             f"got {retry_jitter_frac}")
        #: Seeded Retry-After jitter (ISSUE 19 satellite): shed
        #: clients all backing off by the SAME deterministic hint
        #: re-synchronize into the exact burst that got them shed;
        #: each verdict's hint is stretched by a seeded factor in
        #: [1, 1+frac] so the retry wave de-clumps — reproducibly,
        #: since drills replay from seeds.
        self._retry_jitter_frac = float(retry_jitter_frac)
        self._retry_rng = random.Random(int(jitter_seed))

    def spec(self, cls: str) -> "ClassSpec | None":
        return self._by_name.get(cls)

    def estimate_ms(self, cls: str) -> float:
        """Estimated time-to-answer for a NEW request of ``cls``."""
        c = self._by_name[cls]
        with self._lock:
            ahead = sum(n for name, n in self._inflight.items()
                        if self._by_name[name].priority <= c.priority)
            return self._service_ms * (ahead + 1)

    def admit(self, cls: str, deadline_ms: "float | None") -> Verdict:
        c = self._by_name.get(cls)
        if c is None:
            obs.counter("frontdoor.rejected_total").add(1)
            return Verdict("rejected", 0.0)
        deadline_ms = float(deadline_ms if deadline_ms is not None
                            else c.default_deadline_ms)
        with self._lock:
            svc = self._service_ms
            if self._inflight[cls] >= c.queue_cap:
                decision = "shed_queue"
                # Queue is full: come back after roughly one queue
                # drain at current service speed.
                retry_after = svc * c.queue_cap
                est = svc * (c.queue_cap + 1)
            else:
                ahead = sum(
                    n for name, n in self._inflight.items()
                    if self._by_name[name].priority <= c.priority)
                est = svc * (ahead + 1)
                if est > deadline_ms:
                    decision = "shed_deadline"
                    retry_after = max(est - deadline_ms, svc)
                else:
                    self._inflight[cls] += 1
                    obs.counter("frontdoor.accepted_total").add(1)
                    obs.counter(
                        f"frontdoor.accepted_total.{cls}").add(1)
                    return Verdict("admitted", est)
        obs.counter("frontdoor.shed_total").add(1)
        obs.counter(f"frontdoor.shed_total.{cls}").add(1)
        obs.counter(f"frontdoor.{decision}_total").add(1)
        with self._lock:
            retry_after *= (1.0 + self._retry_jitter_frac
                            * self._retry_rng.random())
        return Verdict(decision, est, retry_after_ms=retry_after)

    def release(self, cls: str,
                service_ms: "float | None" = None) -> None:
        """One admitted request reached a terminal outcome; fold its
        observed service time into the estimate (successes only —
        timeouts would teach the estimator that failure is fast)."""
        with self._lock:
            if self._inflight.get(cls, 0) > 0:
                self._inflight[cls] -= 1
            if service_ms is not None and service_ms > 0:
                self._service_ms += self._alpha * (
                    float(service_ms) - self._service_ms)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "service_est_ms": round(self._service_ms, 3),
                "inflight": dict(self._inflight),
                "classes": [dataclasses.asdict(c)
                            for c in self.classes],
            }


class LocalBackend:
    """In-process backend: one :class:`PredictEngine` behind the door
    (the single-replica deployment, and the unit-test seam)."""

    def __init__(self, engine, follower=None):
        self.engine = engine
        self.follower = follower

    def score(self, ids, vals, deadline: float, trace=None):
        fut = self.engine.submit(ids, vals, deadline=deadline,
                                 trace=trace)
        out = fut.result(max(deadline - time.monotonic(), 0.001))
        return out, {"generation_step": self.engine.generation().step,
                     "replica": 0}

    def healthz(self) -> dict:
        gen = self.engine.generation()
        return {"ready": True, "n_replicas": 1,
                "replicas": [{"replica": 0, "state": "ready",
                              "generation_step": gen.step}]}

    def close(self) -> None:
        if self.follower is not None:
            self.follower.stop()
        self.engine.close()


def _json_body(doc) -> bytes:
    # HTTP response wire format — the one sanctioned json.dumps seam
    # in this module (journal writes go through EventLog).
    return (json.dumps(doc) + "\n").encode()


class _ThreadingHTTPServer(socketserver.ThreadingMixIn,
                           http.server.HTTPServer):
    daemon_threads = True
    # A slow client holds a handler thread by design (the
    # slow_clients drill); the accept loop must keep accepting.
    request_queue_size = 128


class FrontDoor:
    """The serving front door: admission control + HTTP transport over
    any backend with ``score/healthz/close``."""

    def __init__(self, backend, *, admission=None,
                 host: str = "127.0.0.1", port: int = 0,
                 journal=None, trace_sample: float = 1.0):
        self.backend = backend
        self.admission = admission or AdmissionController()
        self.journal = journal
        self.trace_sample = float(trace_sample)
        # Backends predate tracing; only thread the context through
        # score() when the signature takes it (computed once, not per
        # request).
        try:
            self._score_takes_trace = ("trace" in inspect.signature(
                backend.score).parameters)
        except (TypeError, ValueError):
            self._score_takes_trace = False
        self._host, self._want_port = host, int(port)
        self._server = None
        self._thread = None
        self._lock = threading.Lock()

    # ------------------------------------------------------ lifecycle

    def start(self) -> "FrontDoor":
        with self._lock:
            if self._server is not None:
                return self
            door = self

            class Handler(http.server.BaseHTTPRequestHandler):
                server_version = "fm-spark-frontdoor/1"

                def log_message(self, fmt, *args):
                    pass  # per-request narrative goes to the journal

                def do_GET(self):  # noqa: N802 — http.server API
                    try:
                        path = self.path.split("?", 1)[0]
                        if path == "/healthz":
                            self._reply(200, door._healthz_doc())
                        elif path == "/metrics":
                            text = obs.registry().prometheus_text()
                            rollup = getattr(door.backend,
                                             "metrics_rollup", None)
                            if rollup is not None:
                                try:
                                    text += (obs_export
                                             .render_fleet_metrics(
                                                 rollup()))
                                except Exception:  # noqa: BLE001 —
                                    # a torn replica scrape must not
                                    # fail the front door's own dump
                                    pass
                            body = text.encode()
                            self.send_response(200)
                            self.send_header(
                                "Content-Type",
                                "text/plain; version=0.0.4; "
                                "charset=utf-8")
                            self.send_header("Content-Length",
                                             str(len(body)))
                            self.end_headers()
                            self.wfile.write(body)
                        else:
                            self.send_error(
                                404, "want /predict, /healthz "
                                     "or /metrics")
                    except Exception:  # noqa: BLE001 — a broken
                        # scrape/socket must never kill the handler
                        pass

                def do_POST(self):  # noqa: N802 — http.server API
                    try:
                        if self.path.split("?", 1)[0] != "/predict":
                            self.send_error(404, "want /predict")
                            return
                        status, doc, retry_after = door._predict(
                            self.rfile, self.headers)
                        self._reply(status, doc,
                                    retry_after=retry_after)
                    except Exception:  # noqa: BLE001 — the client
                        # socket died mid-reply; the request outcome
                        # was already counted
                        pass

                def _reply(self, status, doc, retry_after=None):
                    body = _json_body(doc)
                    self.send_response(status)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length",
                                     str(len(body)))
                    if retry_after is not None:
                        # HTTP wants integer seconds; the JSON body
                        # carries the precise retry_after_ms.
                        self.send_header(
                            "Retry-After",
                            str(max(1, int(retry_after / 1e3))))
                    self.end_headers()
                    self.wfile.write(body)

            self._server = _ThreadingHTTPServer(
                (self._host, self._want_port), Handler)
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="fm-spark-frontdoor", daemon=True)
            self._thread.start()
            if self.journal is not None:
                self.journal.emit(
                    "frontdoor_start", host=self._host,
                    port=self.port,
                    classes=[c.name for c in self.admission.classes])
            return self

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def stop(self, close_backend: bool = True) -> None:
        with self._lock:
            server, thread = self._server, self._thread
            self._server = self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=10.0)
        if self.journal is not None:
            self.journal.emit("frontdoor_summary", **self.stats())
        if close_backend:
            self.backend.close()

    # ----------------------------------------------------- accounting

    def stats(self) -> dict:
        reg = obs.registry()

        def c(name):
            return int(reg.peek(name) or 0)

        return {
            "accepted": c("frontdoor.accepted_total"),
            "answered": c("frontdoor.answered_total"),
            "shed": c("frontdoor.shed_total"),
            "shed_queue": c("frontdoor.shed_queue_total"),
            "shed_deadline": c("frontdoor.shed_deadline_total"),
            "rejected": c("frontdoor.rejected_total"),
            "timeout": c("frontdoor.timeout_total"),
            "slo_burn": c("frontdoor.slo_burn_total"),
            "failed": c("frontdoor.failed_total"),
            "retries": c("frontdoor.retries_total"),
            "admission": self.admission.snapshot(),
        }

    def _healthz_doc(self) -> dict:
        doc = self.backend.healthz()
        doc["admission"] = self.admission.snapshot()
        doc["counters"] = {k: v for k, v in self.stats().items()
                           if k != "admission"}
        return doc

    # ------------------------------------------------------- predict

    def _predict(self, rfile, headers):
        """Handle one /predict. Returns (status, doc, retry_after_ms
        | None). Every path is counted; an admitted request ALWAYS
        releases its queue slot."""
        try:
            faults.inject("frontdoor_accept")
        except Exception as e:  # noqa: BLE001 — injected transport
            # fault: the client sees an explicit 500, never a hang
            obs.counter("frontdoor.failed_total").add(1)
            return 500, {"error": f"accept failed: "
                                  f"{type(e).__name__}"}, None
        try:
            n = int(headers.get("Content-Length") or 0)
            req = json.loads(rfile.read(n).decode() or "{}")
            ids, vals = req["ids"], req["vals"]
            if (not ids or not vals or len(ids) != len(vals)
                    or len(ids[0]) != len(vals[0])):
                raise ValueError("ids/vals shape mismatch")
        except Exception:  # noqa: BLE001 — malformed request
            obs.counter("frontdoor.rejected_total").add(1)
            return 400, {"error": "malformed request: want JSON "
                                  "{ids, vals, [class, deadline_ms, "
                                  "id]}"}, None
        req_id = str(req.get("id") or "")
        cls = str(req.get("class")
                  or self.admission.classes[0].name)
        deadline_ms = req.get("deadline_ms")

        # One TraceContext per sampled request, minted HERE — the
        # front door is the trust boundary; inbound X-FM-Trace headers
        # from clients are ignored. ctx None = sampled out (or tracing
        # disabled): the request runs the exact pre-trace path.
        ctx = obs.mint_trace(self.trace_sample)
        if ctx is not None:
            with obs.span("frontdoor/admit", trace=ctx.trace_id,
                          cls=cls, req_id=req_id):
                v = self.admission.admit(cls, deadline_ms)
        else:
            v = self.admission.admit(cls, deadline_ms)
        if v.decision == "rejected":
            return 400, {"id": req_id,
                         "error": f"unknown class {cls!r}"}, None
        if not v.admitted:
            return 429, {"id": req_id, "error": v.decision,
                         "retry_after_ms": round(v.retry_after_ms, 3),
                         "est_ms": round(v.est_ms, 3)
                         }, v.retry_after_ms

        spec = self.admission.spec(cls)
        dl_ms = float(deadline_ms if deadline_ms is not None
                      else spec.default_deadline_ms)
        t_in = time.monotonic()
        deadline = t_in + dl_ms / 1e3
        sp_req = (obs.span("frontdoor/request", trace=ctx.trace_id,
                           cls=cls, req_id=req_id)
                  if ctx is not None else obs.NOOP_SPAN)
        try:
            with watchdog.phase("frontdoor_request"), sp_req as sp:
                trace_kw = {}
                if ctx is not None and self._score_takes_trace:
                    # Hand downstream a context parented to THIS hop's
                    # span — the cross-process stitch point.
                    trace_kw["trace"] = ctx.child(
                        getattr(sp, "span_id", None))
                out, meta = self.backend.score(ids, vals, deadline,
                                               **trace_kw)
        except TimeoutError:
            self.admission.release(cls)
            obs.counter("frontdoor.timeout_total").add(1)
            self._count_slo_burn(cls)
            return 504, {"id": req_id,
                         "error": "deadline expired"}, None
        except Exception as e:  # noqa: BLE001 — backend failed the
            # admitted request (after its own retry policy): explicit
            # 503, counted, slot released
            self.admission.release(cls)
            obs.counter("frontdoor.failed_total").add(1)
            if self.journal is not None:
                self.journal.emit(
                    "frontdoor_backend_failed", req_id=req_id,
                    cls=cls, error=type(e).__name__)
            return 503, {"id": req_id,
                         "error": f"backend failed: "
                                  f"{type(e).__name__}"}, None
        service_ms = (time.monotonic() - t_in) * 1e3
        self.admission.release(cls, service_ms=service_ms)
        obs.counter("frontdoor.answered_total").add(1)
        if service_ms > dl_ms:
            # Answered, but late: SLO budget burned all the same.
            self._count_slo_burn(cls)
        obs.histogram("frontdoor/request_ms").observe(
            service_ms, exemplar=ctx.trace_id if ctx else None)
        doc = {"id": req_id, "scores": [float(x) for x in out],
               "generation_step": meta.get("generation_step"),
               "replica": meta.get("replica")}
        if ctx is not None:
            doc["trace"] = ctx.trace_id
        return 200, doc, None

    @staticmethod
    def _count_slo_burn(cls: str) -> None:
        """SLO burn-rate feed (ISSUE 18): one tick per request that
        missed its deadline (504, or answered late) — burn rate =
        rate(slo_burn_total) / rate(accepted_total) on any scraper."""
        obs.counter("frontdoor.slo_burn_total").add(1)
        obs.counter(f"frontdoor.slo_burn_total.{cls}").add(1)
