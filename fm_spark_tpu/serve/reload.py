"""Hot model reload from the checkpoint chain: the serving follower.

The trainer's crash-consistent chain (PR 3) already has an atomic
publish point — ``last_good.json`` advances only to manifest-verified
steps — so "deploy the newest model" is a POLL, not an RPC:
:class:`ReloadFollower` watches ``last_good`` through the read-only
:class:`~fm_spark_tpu.checkpoint.ChainFollower` (never a write on the
trainer's directory — the ISSUE 12 satellite), loads + verifies the
new generation entirely OFF the request path, and installs it via
:meth:`~fm_spark_tpu.serve.engine.PredictEngine.swap_generation` — a
single atomic reference store, so a request sees exactly one
consistent generation, never a torn mixture.

Failure is a MODE, not an exception: when a reload attempt fails
(corrupt bytes, a torn chain, an injected ``serve_reload`` fault), the
follower journals ``reload_failed``, raises the ``serve/degraded``
gauge, and KEEPS SERVING the old generation; the next poll retries
from scratch. Staleness is always measurable: the
``serve/staleness_steps`` gauge tracks ``last_good - served_step`` on
every poll, and bounded staleness after recovery is one of the chaos
auditor's serving invariants
(:func:`fm_spark_tpu.resilience.chaos.audit_serve_events`).
"""

from __future__ import annotations

import threading
import time

from fm_spark_tpu import obs
from fm_spark_tpu.checkpoint import ChainFollower
from fm_spark_tpu.resilience import faults

__all__ = ["ReloadFollower"]


class ReloadFollower:
    """Poll a checkpoint chain and hot-swap the engine's generation.

    ``opt_state_example`` pins the checkpoint's optimizer-state
    structure (``{}`` for the pure-SGD field_sparse families; the
    caller builds the optax example for families that carry one).
    ``params_example`` defaults to the engine's own current params —
    chain generations must share the serving model's structure.
    """

    def __init__(self, engine, directory: str, *,
                 poll_s: float = 2.0, journal=None,
                 params_example=None, opt_state_example=None):
        self.engine = engine
        self.poll_s = float(poll_s)
        self.journal = journal
        self.chain = ChainFollower(directory, journal=journal)
        self._params_example = (params_example if params_example
                                is not None
                                else engine.generation().params)
        self._opt_example = ({} if opt_state_example is None
                             else opt_state_example)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Outcome counters are written by the poll thread and read by
        # callers (cli serve's summary, tests): guarded — an unlocked
        # += from a direct poll_once() call racing the loop drops
        # counts (fmlint thread-lock-discipline, ISSUE 15).
        self._counter_lock = threading.Lock()
        self.reloads = 0
        self.failures = 0

    # ------------------------------------------------------------ polling

    def _emit(self, event: str, **fields) -> None:
        obs.event(event, **fields)
        if self.journal is not None:
            self.journal.emit(event, **fields)

    def _set_staleness(self, last_good: int | None,
                       served: int) -> int:
        staleness = max(int(last_good) - int(served), 0) \
            if last_good is not None else 0
        obs.gauge("serve/staleness_steps").set(staleness)
        return staleness

    def _fail(self, error: str, target_step: int,
              served: int) -> None:
        """The degraded-mode transition, in one place: count, raise
        the gauge, journal — the old generation keeps serving."""
        with self._counter_lock:
            self.failures += 1
        obs.counter("serve.reload_failures_total").add(1)
        obs.gauge("serve/degraded").set(1)
        self._emit("reload_failed", target_step=int(target_step),
                   served_step=int(served), error=error)

    @property
    def degraded(self) -> bool:
        return bool(obs.gauge("serve/degraded").value or 0)

    def poll_once(self) -> str:
        """One poll of the chain. Returns the outcome:

        ``no_checkpoint``  nothing published yet
        ``fresh``          serving the newest verified generation
        ``swapped``        a newer generation was loaded + installed
        ``stale_chain``    the chain walked back BELOW the served step
                           (newest steps all torn/corrupt/demoted) —
                           keep serving what we have
        ``demoted``        the restored generation was tombstoned
                           between restore and swap (a demotion racing
                           this reload) — refused, old generation
                           keeps serving
        ``failed``         the reload attempt itself failed — degraded
                           mode, old generation keeps serving
        """
        last_good = self.chain.last_good_step()
        served = self.engine.generation().step
        self._set_staleness(last_good, served)
        if last_good is None:
            return "no_checkpoint"
        if last_good <= served:
            return "fresh"
        with obs.span("serve/reload", target_step=int(last_good),
                      served_step=int(served)):
            try:
                # The drill hook (ISSUE 12): serve_reload faults land
                # HERE — inside the attempt, before the swap — so an
                # injected error exercises exactly the degraded path a
                # real torn chain would, and an injected exit is the
                # SIGKILL-mid-reload drill.
                faults.inject("serve_reload")
                restored = self.chain.restore(self._params_example,
                                              self._opt_example)
            except Exception as e:  # noqa: BLE001 — degraded mode IS
                # the handler: serving must outlive a failed reload
                self._fail(f"{type(e).__name__}: "
                           f"{(str(e).splitlines() or [''])[0][:200]}",
                           last_good, served)
                return "failed"
        if restored is None or restored["step"] <= served:
            # Verified chain tip is not ahead of us (torn newest steps
            # walked back past the pointer, or the tip was DEMOTED —
            # ISSUE 13's quarantined-tip case): not a failure, not a
            # swap; the staleness gauge keeps measuring the gap.
            self._fail("no verified step newer than served generation "
                       "(torn/corrupt/demoted chain tip)", last_good,
                       served)
            return "stale_chain"
        if self.chain.is_tombstoned(restored["step"]):
            # Demotion raced the reload: the tombstone landed AFTER
            # restore() walked the chain but before the swap. The
            # verdict wins — a demoted generation must never be
            # installed, even loaded-and-verified.
            obs.counter("serve.demoted_refused_total").add(1)
            self._fail(f"generation {restored['step']} was demoted "
                       "mid-reload (tombstone veto)", last_good, served)
            return "demoted"
        layout = ((restored.get("extra") or {}).get("layout")
                  or "canonical")
        if layout != "canonical":
            self._fail(f"chain holds {layout}-layout checkpoints; "
                       "serving follows canonical layouts only",
                       last_good, served)
            return "failed"
        self.engine.swap_generation(restored["params"],
                                    restored["step"])
        with self._counter_lock:
            self.reloads += 1
        obs.counter("serve.reloads_total").add(1)
        obs.gauge("serve/degraded").set(0)
        self._set_staleness(self.chain.last_good_step(),
                            restored["step"])
        return "swapped"

    # ----------------------------------------------------------- threading

    def start(self) -> "ReloadFollower":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="fm-spark-serve-reload",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            t0 = time.perf_counter()
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the poll loop
                # must never die silently; journal and keep polling
                self._emit("reload_failed",
                           error=f"poll loop: {type(e).__name__}: "
                                 f"{(str(e).splitlines() or [''])[0][:160]}")
            obs.histogram("serve/reload_poll_ms").observe(
                (time.perf_counter() - t0) * 1e3)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        self.chain.close()
