"""Low-latency online serving runtime (ISSUE 12).

The request path for the millions-of-users north star, composed from
pieces other PRs battle-tested:

- :mod:`~fm_spark_tpu.serve.engine` — the AOT micro-batched
  :class:`PredictEngine`: per-bucket executables compiled once at
  warmup through the PR-1 persistent compile cache (zero fresh XLA
  compiles on the request path), a request coalescer under an explicit
  latency budget, and an atomically swappable model generation;
- :mod:`~fm_spark_tpu.serve.reload` — the :class:`ReloadFollower`:
  hot model reload by polling the checkpoint chain's ``last_good``
  publish point through the read-only
  :class:`~fm_spark_tpu.checkpoint.ChainFollower`, with degraded mode
  (keep serving the old generation) and a bounded-staleness gauge;
- :mod:`~fm_spark_tpu.serve.frontdoor` — the production front door
  (ISSUE 17): stdlib HTTP transport + deadline-aware admission
  control (priority classes, bounded per-class queues, shed BEFORE
  the coalescer, Retry-After backpressure);
- :mod:`~fm_spark_tpu.serve.fleet` — the multi-process replica fleet:
  N engines behind one door, each hot-following the chain via its own
  read-only ``ChainFollower``, health-checked/drained/re-admitted by
  the parent, with the PR-3 elastic controller as the scale-down
  primitive;
- :mod:`~fm_spark_tpu.serve.loadgen` — the seeded traffic-replay load
  generator (diurnal ramps, flash crowds, slow clients, retry storms)
  the chaos engine composes with fault plans;
- ``bench_serve.py`` (repo root) — the latency/throughput ladder that
  stamps p50/p99 + QPS/chip into the PR-9 ledger as ``serve_bench``
  records, sentinel-gated exactly like training legs (fleet rungs are
  their own cohorts).
"""

from fm_spark_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    Generation,
    PredictEngine,
    ServeFuture,
)
from fm_spark_tpu.serve.frontdoor import (
    AdmissionController,
    BackendError,
    FrontDoor,
    LocalBackend,
    parse_classes,
)
from fm_spark_tpu.serve.reload import ReloadFollower

__all__ = [
    "DEFAULT_BUCKETS",
    "AdmissionController",
    "BackendError",
    "FrontDoor",
    "Generation",
    "LocalBackend",
    "PredictEngine",
    "ReloadFollower",
    "ServeFuture",
    "parse_classes",
]
