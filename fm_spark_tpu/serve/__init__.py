"""Low-latency online serving runtime (ISSUE 12).

The request path for the millions-of-users north star, composed from
pieces other PRs battle-tested:

- :mod:`~fm_spark_tpu.serve.engine` — the AOT micro-batched
  :class:`PredictEngine`: per-bucket executables compiled once at
  warmup through the PR-1 persistent compile cache (zero fresh XLA
  compiles on the request path), a request coalescer under an explicit
  latency budget, and an atomically swappable model generation;
- :mod:`~fm_spark_tpu.serve.reload` — the :class:`ReloadFollower`:
  hot model reload by polling the checkpoint chain's ``last_good``
  publish point through the read-only
  :class:`~fm_spark_tpu.checkpoint.ChainFollower`, with degraded mode
  (keep serving the old generation) and a bounded-staleness gauge;
- ``bench_serve.py`` (repo root) — the latency/throughput ladder that
  stamps p50/p99 + QPS/chip into the PR-9 ledger as ``serve_bench``
  records, sentinel-gated exactly like training legs.
"""

from fm_spark_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    Generation,
    PredictEngine,
    ServeFuture,
)
from fm_spark_tpu.serve.reload import ReloadFollower

__all__ = [
    "DEFAULT_BUCKETS",
    "Generation",
    "PredictEngine",
    "ReloadFollower",
    "ServeFuture",
]
