"""AOT micro-batched predict engine: the low-latency request path.

The training side runs at 1.4M samples/s/chip, but until ISSUE 12 the
repo could only ``predict`` in offline batch mode. This engine is the
millions-of-users half: a warm process answers scoring requests with
**zero fresh XLA compiles on the request path**, because every
executable it will ever dispatch is AOT ``lower().compile()``-d at
:meth:`PredictEngine.warmup` — one per padded **batch bucket** —
through the PR-1 persistent compile cache (a warm process deserializes
each in milliseconds instead of compiling).

Shape discipline is the whole trick: a request of ``n`` rows is padded
to the smallest configured bucket ``>= n``, so the engine only ever
dispatches shapes it compiled at warmup — never a fresh shape, never a
fresh compile, bounded executable count. Padding is provably free for
correctness: per-row scores are row-independent (verified bitwise in
tests — padded and unpadded executions agree exactly), and padded rows
are sliced off before any caller sees them.

Request path (the **coalescer / micro-batcher**): callers
:meth:`~PredictEngine.submit` requests of 1..bucket-max rows; a worker
thread takes the first queued request and accumulates more until the
explicit **latency budget** expires or the largest bucket fills, then
executes ONE padded batch and splits results back per request — every
request answered exactly once, each from exactly ONE model generation
(the worker reads the generation reference once per batch; see
:mod:`fm_spark_tpu.serve.reload` for the swap side of that contract).
The batch execute runs under the ``serve_request`` watchdog phase
(deadline = the SLO): an overrun becomes a structured
:class:`~fm_spark_tpu.resilience.watchdog.HangDetected` + flight dump
instead of a silently blown tail latency.

Offline batch predict (``cli predict``) rides :meth:`PredictEngine.
score` — the same bucketed AOT executables without the coalescer
thread — and is bit-identical to the pre-engine eager path.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from fm_spark_tpu import obs
from fm_spark_tpu.resilience import watchdog

__all__ = [
    "DEFAULT_BUCKETS",
    "Generation",
    "PredictEngine",
    "ServeFuture",
]

#: Default padded-batch buckets: batch-1 for pure-latency traffic up
#: through 512 rows per dispatch (one executable each; ~4x steps keep
#: the worst-case pad waste under 4x and the executable count small).
DEFAULT_BUCKETS = (1, 8, 64, 512)


class Generation:
    """One immutable served model generation. The engine holds exactly
    one reference; a swap replaces the reference, never the contents —
    the single-assignment atomicity the no-torn-swap invariant rides."""

    __slots__ = ("params", "step", "gen_id")

    def __init__(self, params, step: int, gen_id: int):
        self.params = params
        self.step = int(step)
        self.gen_id = int(gen_id)


class ServeFuture:
    """Exactly-once result slot for one submitted request."""

    __slots__ = ("_event", "_value", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc = None

    def _set(self, value) -> None:
        self._value = value
        self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("serve request not answered in time")
        if self._exc is not None:
            raise self._exc
        return self._value


class _Request:
    __slots__ = ("ids", "vals", "n", "future", "t_submit", "t_wall",
                 "deadline", "trace")

    def __init__(self, ids, vals, deadline=None, trace=None):
        self.ids = ids
        self.vals = vals
        self.n = int(ids.shape[0])
        self.future = ServeFuture()
        self.t_submit = time.perf_counter()
        #: Wall-clock twin of ``t_submit`` — the start stamp of the
        #: request's retroactive ``serve/coalesce`` link span (stored,
        #: never subtracted; durations stay monotonic).
        self.t_wall = time.time()
        #: Absolute ``time.monotonic()`` deadline (None = unbounded).
        #: Propagated by the front door (ISSUE 17) so the coalescer
        #: never HOLDS a request past its SLO waiting for batch-mates,
        #: and never SCORES one that already expired in the queue.
        self.deadline = deadline
        #: Distributed-trace context (ISSUE 18) or None: tags the
        #: request's link span + latency exemplar, and rides into the
        #: SLO-overrun capture context.
        self.trace = trace


_STOP = object()


class PredictEngine:
    """Bucketed AOT scoring over an atomically swappable generation.

    ``nnz`` pins the per-row feature width (the second input axis);
    every request must match it — a stray width would be a fresh shape,
    i.e. a compile on the request path, so it is rejected loudly
    instead. Call :meth:`warmup` once before serving (compiles — or,
    warm, deserializes — every bucket executable); then :meth:`submit`
    / :meth:`predict` for coalesced serving or :meth:`score` for
    direct offline batches.
    """

    def __init__(self, spec, params, *, nnz: int | None = None,
                 step: int = 0, buckets=DEFAULT_BUCKETS,
                 latency_budget_ms: float = 2.0, journal=None,
                 ids_dtype="int32", vals_dtype="float32"):
        import jax

        self.spec = spec
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"need >= 1 positive bucket, got {buckets}")
        self.nnz = int(nnz if nnz is not None
                       else getattr(spec, "num_fields", 0))
        if self.nnz < 1:
            raise ValueError(
                "engine needs the per-row feature width: pass nnz= "
                "(specs without num_fields cannot imply it)")
        self.latency_budget_s = max(float(latency_budget_ms), 0.0) / 1e3
        self.journal = journal
        self._ids_dtype = np.dtype(ids_dtype)
        self._vals_dtype = np.dtype(vals_dtype)
        self._jax = jax
        self._predict = jax.jit(
            lambda p, i, v: self.spec.predict(p, i, v))
        self._compiled: dict[int, object] = {}
        self._gen = Generation(jax.device_put(params), step, gen_id=0)
        # The live /healthz endpoint (ISSUE 14) reads this gauge; a
        # fresh engine that never swaps must still report what it
        # serves, not None.
        obs.gauge("serve/generation_step").set(self._gen.step)
        self._queue: queue.Queue = queue.Queue()
        self._carry: _Request | None = None
        self._worker: threading.Thread | None = None
        self._worker_lock = threading.Lock()
        self._closed = False
        self._last_slo_dump: float | None = None

    # -------------------------------------------------------- generations

    def generation(self) -> Generation:
        """The CURRENT generation reference (one atomic read — the
        same read the batch worker performs per micro-batch)."""
        return self._gen

    def swap_generation(self, params, step: int) -> Generation:
        """Install a new generation via a single reference assignment.

        The caller (the reload follower) does all loading/verification
        OFF the request path first; by the time this runs, the new
        params are fully materialized, so a concurrent batch sees
        either the old reference or the new one — never a mixture (the
        no-torn-swap contract, audited in chaos drills). Requests
        already batched against the old generation finish on it."""
        old = self._gen
        gen = Generation(self._jax.device_put(params), step,
                         gen_id=old.gen_id + 1)
        self._gen = gen  # fmlint: disable=thread-lock-discipline -- THE swap: one atomic reference store; worker reads the reference once per batch (no-torn-swap contract, chaos-audited)
        obs.counter("serve.swaps_total").add(1)
        obs.gauge("serve/generation_step").set(gen.step)
        obs.event("serve_swap", step=gen.step, gen_id=gen.gen_id,
                  from_step=old.step)
        if self.journal is not None:
            self.journal.emit("serve_swap", step=gen.step,
                              gen_id=gen.gen_id, from_step=old.step)
        return gen

    # ------------------------------------------------------------ compile

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"request of {n} rows exceeds the largest bucket "
            f"{self.buckets[-1]} (predict() chunks; submit() callers "
            "must pre-chunk)")

    def warmup(self) -> dict:
        """AOT-compile every (bucket, nnz) executable NOW — the only
        place the engine ever compiles. With a populated persistent
        compile cache this is pure deserialization (asserted via
        :func:`fm_spark_tpu.utils.compile_cache.cache_stats` in tests
        and bench_serve). Returns ``{"seconds", "buckets",
        "cache_stats"}``."""
        from fm_spark_tpu.utils import compile_cache

        jax = self._jax
        t0 = time.perf_counter()
        stats0 = compile_cache.cache_stats()
        gen = self._gen
        with obs.span("serve/warmup", buckets=list(self.buckets),
                      nnz=self.nnz):
            for b in self.buckets:
                if b in self._compiled:
                    continue
                lowered = self._predict.lower(
                    gen.params,
                    jax.ShapeDtypeStruct((b, self.nnz),
                                         self._ids_dtype),
                    jax.ShapeDtypeStruct((b, self.nnz),
                                         self._vals_dtype),
                )
                self._compiled[b] = lowered.compile()  # fmlint: disable=thread-lock-discipline -- warmup() runs before serving starts; bucket entries are add-only and never mutated after
        stats1 = compile_cache.cache_stats()
        out = {
            "seconds": round(time.perf_counter() - t0, 4),
            "buckets": list(self.buckets),
            "cache_stats": stats1,
            "fresh_compiles": stats1["misses"] - stats0["misses"],
        }
        obs.event("serve_warmup", **{k: out[k] for k in
                                     ("seconds", "fresh_compiles")})
        return out

    # ------------------------------------------------------------ execute

    def _coerce(self, ids, vals) -> tuple[np.ndarray, np.ndarray]:
        ids = np.asarray(ids)
        vals = np.asarray(vals)
        if ids.ndim != 2 or ids.shape != vals.shape:
            raise ValueError(
                f"want matching (n, {self.nnz}) ids/vals, got "
                f"{ids.shape} / {vals.shape}")
        if ids.shape[1] != self.nnz:
            raise ValueError(
                f"request width {ids.shape[1]} != engine nnz "
                f"{self.nnz} — a fresh shape would mean a fresh "
                "compile on the request path; build the engine with "
                "the request width")
        if ids.shape[0] < 1:
            raise ValueError("empty request")
        return (ids.astype(self._ids_dtype, copy=False),
                vals.astype(self._vals_dtype, copy=False))

    def _execute(self, gen: Generation, ids: np.ndarray,
                 vals: np.ndarray,
                 exec_info: "dict | None" = None) -> np.ndarray:
        """One padded-bucket dispatch on ``gen``; returns the first
        ``n`` scores as host floats. The ONLY dispatch path — spans,
        SLO watchdog, and the zero-compile property all live here.
        ``exec_info`` (out-param) receives the shared batch span's id
        + perf-clock bounds so the coalescer's per-request link spans
        can decompose wait/execute/split."""
        n = ids.shape[0]
        bucket = self._bucket_for(n)
        compiled = self._compiled.get(bucket)
        if compiled is None:
            raise RuntimeError(
                f"bucket {bucket} not compiled — call warmup() before "
                "serving (the request path never compiles)")
        pad = bucket - n
        if pad:
            ids = np.concatenate(
                [ids, np.zeros((pad, self.nnz), self._ids_dtype)])
            vals = np.concatenate(
                [vals, np.zeros((pad, self.nnz), self._vals_dtype)])
        t0 = time.perf_counter()
        with obs.span("serve/batch", rows=n, bucket=bucket,
                      gen_step=gen.step) as bsp:
            with watchdog.phase("serve_request"):
                out = np.asarray(compiled(gen.params, ids, vals))
        t1 = time.perf_counter()
        if exec_info is not None:
            exec_info.update(span_id=getattr(bsp, "span_id", None),
                             t0=t0, t1=t1)
        obs.histogram("serve/batch_ms").observe((t1 - t0) * 1e3)
        obs.counter("serve.batches_total").add(1)
        obs.counter("serve.rows_total").add(n)
        if pad:
            obs.counter("serve.padded_rows_total").add(pad)
        return out[:n]

    def score(self, ids, vals) -> np.ndarray:
        """Direct (non-coalesced) bucketed scoring — the offline batch
        path ``cli predict`` and warm ladders use. Chunks inputs wider
        than the largest bucket; output order matches input order."""
        ids, vals = self._coerce(ids, vals)
        gen = self._gen
        cap = self.buckets[-1]
        if ids.shape[0] <= cap:
            return self._execute(gen, ids, vals)
        return np.concatenate([
            self._execute(gen, ids[lo:lo + cap], vals[lo:lo + cap])
            for lo in range(0, ids.shape[0], cap)
        ])

    # ---------------------------------------------------------- coalescer

    def _ensure_worker(self) -> None:
        with self._worker_lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._run, name="fm-spark-serve-batcher",
                    daemon=True)
                self._worker.start()

    def submit(self, ids, vals, deadline: float | None = None,
               trace=None) -> ServeFuture:
        """Enqueue one request (<= bucket-max rows) for coalescing;
        returns its :class:`ServeFuture`. ``deadline`` is an absolute
        ``time.monotonic()`` timestamp: the coalescer stops gathering
        at the batch's earliest deadline, and a request that expires
        while still queued is answered with :class:`TimeoutError`
        (exactly once, never scored, never silently dropped).
        ``trace`` (a :class:`~fm_spark_tpu.obs.trace.TraceContext`)
        yields one ``serve/coalesce`` link span joining this request
        to the shared micro-batch execute span."""
        ids, vals = self._coerce(ids, vals)
        if ids.shape[0] > self.buckets[-1]:
            raise ValueError(
                f"submit() takes at most bucket-max ({self.buckets[-1]}) "
                "rows per request; use predict() to auto-chunk")
        self._ensure_worker()
        req = _Request(ids, vals, deadline=deadline, trace=trace)
        obs.counter("serve.requests_total").add(1)
        self._queue.put(req)
        return req.future

    def predict(self, ids, vals, timeout: float | None = 60.0
                ) -> np.ndarray:
        """Submit-and-wait; wide inputs are chunked to bucket-max and
        reassembled in order."""
        ids, vals = self._coerce(ids, vals)
        cap = self.buckets[-1]
        futures = [self.submit(ids[lo:lo + cap], vals[lo:lo + cap])
                   for lo in range(0, ids.shape[0], cap)]
        return np.concatenate([f.result(timeout) for f in futures])

    def _gather(self) -> list[_Request] | None:
        """Block for the first request, then accumulate under the
        latency budget / until bucket-max; ``None`` = stop."""
        first = self._carry
        self._carry = None  # fmlint: disable=thread-lock-discipline -- coalescer-thread-local carry: only the single worker thread (_run/_gather) ever touches it
        if first is None:
            first = self._queue.get()
        if first is _STOP:
            return None
        batch = [first]
        rows = first.n
        cap = self.buckets[-1]
        deadline = time.monotonic() + self.latency_budget_s
        if first.deadline is not None:
            deadline = min(deadline, first.deadline)
        while rows < cap:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is _STOP:
                # Finish this batch, then stop: queued requests are
                # answered, never dropped.
                self._queue.put(_STOP)
                break
            if rows + nxt.n > cap:
                self._carry = nxt  # fmlint: disable=thread-lock-discipline -- heads the next batch; coalescer-thread-local (single worker thread)
                break
            batch.append(nxt)
            rows += nxt.n
            if nxt.deadline is not None:
                deadline = min(deadline, nxt.deadline)
        return batch

    def _run(self) -> None:
        while True:
            batch = self._gather()
            if batch is None:
                return
            # A request whose deadline passed while it sat in the
            # queue is answered with TimeoutError NOW — scoring it
            # would spend batch capacity on an answer the client has
            # already abandoned (the front door's admission estimate
            # stays honest because expired work never reaches the
            # device).
            now = time.monotonic()
            expired = [r for r in batch
                       if r.deadline is not None and r.deadline < now]
            if expired:
                obs.counter("serve.deadline_expired_total").add(
                    len(expired))
                for r in expired:
                    r.future._set_exception(TimeoutError(
                        "request deadline expired before dispatch"))
                batch = [r for r in batch if r not in expired]
                if not batch:
                    continue
            # ONE generation read per micro-batch: every row in this
            # dispatch — and every response split from it — scores on
            # the same params (the no-torn-swap contract).
            gen = self._gen  # fmlint: disable=thread-lock-discipline -- single atomic reference read per micro-batch IS the protocol (no-torn-swap contract)
            ids = (batch[0].ids if len(batch) == 1 else
                   np.concatenate([r.ids for r in batch]))
            vals = (batch[0].vals if len(batch) == 1 else
                    np.concatenate([r.vals for r in batch]))
            exec_info: dict = {}
            try:
                out = self._execute(gen, ids, vals,
                                    exec_info=exec_info)
            except BaseException as e:  # noqa: BLE001 — every queued
                # caller must be answered (exactly once), even by the
                # failure; HangDetected and injected faults land here.
                obs.counter("serve.batch_failures_total").add(1)
                if isinstance(e, watchdog.HangDetected):
                    # SLO overrun (ISSUE 14): the serve_request phase
                    # blew its deadline. Arm a rate-limited deep
                    # capture while the slow program is resident, and
                    # dump the flight window (the capture-context
                    # satellite) — heavy evidence rate-limited like
                    # the watchdog near-miss: a sustained SLO breach
                    # at load overruns every micro-batch, and the
                    # worker must answer callers, not fsync per batch.
                    overrun = dict(phase=e.phase,
                                   deadline_s=round(e.deadline_s, 3),
                                   elapsed_s=round(e.elapsed_s, 3),
                                   rows=int(ids.shape[0]),  # fmlint: disable=jax-host-sync -- ids is a host np.ndarray (coalesced request rows), not a traced value
                                   gen_step=gen.step)
                    # The offending requests' trace ids ride the
                    # capture context verbatim into capture.json —
                    # the bundle names the traces it explains.
                    traces = [r.trace.trace_id for r in batch
                              if r.trace is not None][:8]
                    if traces:
                        overrun["traces"] = traces
                    obs.counter("serve.slo_overruns_total").add(1)
                    armed = False
                    bundle = None
                    try:
                        from fm_spark_tpu.obs import introspect

                        armed = introspect.active()
                        if armed:
                            bundle = introspect.fire(
                                "serve_slo_overrun", **overrun)
                    except Exception:
                        pass
                    now = time.monotonic()
                    throttled = (self._last_slo_dump is not None
                                 and now - self._last_slo_dump
                                 < watchdog.NEAR_MISS_DUMP_INTERVAL_S)
                    if ((armed and bundle is not None)
                            or (not armed and not throttled)):
                        self._last_slo_dump = now
                        obs.event("serve_slo_overrun", **overrun)
                        obs.flight_dump("serve_slo_overrun", **overrun)
                obs.event("serve_batch_failed",
                          error=f"{type(e).__name__}: "
                                f"{(str(e).splitlines() or [''])[0][:200]}",
                          rows=int(ids.shape[0]), gen_step=gen.step)  # fmlint: disable=jax-host-sync -- ids is a host np.ndarray; failure path, not the dispatch loop
                if self.journal is not None:
                    self.journal.emit(
                        "serve_batch_failed",
                        error=f"{type(e).__name__}", gen_step=gen.step)
                for r in batch:
                    r.future._set_exception(e)
                continue
            off = 0
            t_done = time.perf_counter()
            hist = obs.histogram("serve/request_ms")
            exec_sid = exec_info.get("span_id")
            t_exec0 = exec_info.get("t0", t_done)
            t_exec1 = exec_info.get("t1", t_done)
            for r in batch:
                r.future._set(out[off:off + r.n])
                off += r.n
                lat_ms = (t_done - r.t_submit) * 1e3
                hist.observe(lat_ms,
                             exemplar=(r.trace.trace_id
                                       if r.trace is not None
                                       else None))
                if r.trace is not None:
                    # One link span per coalesced request: the
                    # request's queue-to-split window, joined to the
                    # SHARED ``serve/batch`` span via ``exec_span``
                    # (N requests, one execute — the coalescing
                    # topology stays visible in the merged trace).
                    obs.emit_span(
                        "serve/coalesce", r.t_wall,
                        t_done - r.t_submit,
                        trace=r.trace.trace_id,
                        remote_parent=r.trace.parent_span_id,
                        exec_span=exec_sid,
                        queue_ms=round(
                            (t_exec0 - r.t_submit) * 1e3, 3),
                        exec_ms=round((t_exec1 - t_exec0) * 1e3, 3),
                        split_ms=round((t_done - t_exec1) * 1e3, 3),
                        rows=r.n)

    def close(self) -> None:
        """Stop the coalescer after answering everything queued."""
        with self._worker_lock:
            self._closed = True
            worker = self._worker
        if worker is not None and worker.is_alive():
            self._queue.put(_STOP)
            worker.join(timeout=30.0)
