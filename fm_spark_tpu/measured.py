"""Single source of truth for MEASURED on-chip rates (VERDICT r4 Weak #1).

Every projection that starts from a measured single-chip rate — the
MULTICHIP dryrun artifact, PERF analyses, ad-hoc scripts — must load the
rate from the repo-root ``MEASURED.json`` via :func:`load_measured`
instead of hard-coding it. ``bench.py`` REWRITES the ``headline`` entry
whenever a sweep lands a real number, so a stale projection constant can
no longer survive a new measurement; the provenance fields (``source``,
``date``, ``attachment``) travel with the number so downstream artifacts
can name where their input came from.

Schema (two entries, each with provenance)::

    {"headline":  {"rate_samples_per_sec_per_chip": float, "vs_baseline":
                   float|None, "variant": str, "source": str,
                   "attachment": str, "date": "YYYY-MM-DD"},
     "ffm_avazu": {"rate_samples_per_sec_per_chip": float, "source": str,
                   "date": "YYYY-MM-DD"}}
"""

from __future__ import annotations

import json
import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MEASURED_PATH = os.path.join(_REPO_ROOT, "MEASURED.json")

_FIELDS = ("rate_samples_per_sec_per_chip", "source", "date")
# Entries that must exist in a valid MEASURED.json (they have carried
# measured values since round 3).
_REQUIRED = {"headline": _FIELDS, "ffm_avazu": _FIELDS}
# Entries bench.py MAY write once measured (no carried value exists yet,
# so their absence is valid). "serving" is bench_serve.py's headline
# (ISSUE 12): scored rows/s/chip through the bucketed AOT request path,
# promoted through the same sentinel keep-best gate as training legs.
_OPTIONAL = {"deepfm_criteo": _FIELDS, "fm_kaggle": _FIELDS,
             "serving": _FIELDS}
_KNOWN = {**_REQUIRED, **_OPTIONAL}


def load_measured(path: str | None = None) -> dict:
    """Load and validate MEASURED.json. Fails loudly — no silent default:
    a missing/invalid file means the provenance chain is broken and any
    projection made from a guessed rate would be exactly the stale-constant
    failure mode this module exists to kill."""
    p = path or MEASURED_PATH
    with open(p) as f:
        data = json.load(f)
    for key in _REQUIRED:
        if key not in data:
            raise ValueError(f"MEASURED.json missing entry {key!r}")
    for key, entry in data.items():
        fields = _KNOWN.get(key)
        if fields is None:
            raise ValueError(f"MEASURED.json unknown entry {key!r}")
        for field in fields:
            if field not in entry:
                raise ValueError(
                    f"MEASURED.json entry {key!r} missing field {field!r}")
        rate = entry["rate_samples_per_sec_per_chip"]
        if not (isinstance(rate, (int, float)) and rate > 0):
            raise ValueError(
                f"MEASURED.json {key}: bad rate {rate!r}")
    return data


def update_entry(key: str, rate: float, variant: str, source: str,
                 attachment: str, date: str,
                 vs_baseline: float | None = None,
                 path: str | None = None) -> None:
    """Rewrite one entry (called by bench.py on a successful sweep),
    preserving the other entries and their provenance."""
    if key not in _KNOWN:
        raise ValueError(f"unknown MEASURED.json entry {key!r}")
    p = path or MEASURED_PATH
    try:
        with open(p) as f:
            data = json.load(f)
    except FileNotFoundError:
        data = {}  # first-ever measurement: start a fresh file
    # Any other read/parse failure propagates: silently rewriting a
    # corrupt file would discard the other entries and their
    # provenance — the destructive version of the stale-constant bug.
    entry = {
        "rate_samples_per_sec_per_chip": float(rate),
        "variant": variant,
        "source": source,
        "attachment": attachment,
        "date": date,
    }
    if key == "headline":
        entry["vs_baseline"] = vs_baseline
    data[key] = entry
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    os.replace(tmp, p)


def update_headline(rate: float, vs_baseline: float | None,
                    variant: str, source: str, attachment: str,
                    date: str, path: str | None = None) -> None:
    update_entry("headline", rate, variant, source, attachment, date,
                 vs_baseline=vs_baseline, path=path)
