"""Structured per-step metrics logging.

The reference logs per-iteration loss via log4j and relies on the Spark UI
for counters (SURVEY.md §5). Rebuild: JSONL records to stdout and/or a file
— {step, loss, auc, samples_per_sec_per_chip, grad_norm, ...} — cheap to
parse, diffable, and the driver's bench harness consumes one-line JSON.
"""

from __future__ import annotations

import json
import sys
import time


class MetricsLogger:
    """Writes one JSON object per line; tracks wall-clock samples/sec."""

    def __init__(self, path: str | None = None, stream=None, n_chips: int = 1):
        self._fh = open(path, "a") if path else None
        self._stream = stream if stream is not None else sys.stdout
        self._n_chips = max(n_chips, 1)
        self._t0 = None
        self._paused = 0.0

    def log(self, step: int, samples: int = 0, **metrics) -> dict:
        now = time.perf_counter()
        record = {"step": step, "ts": time.time()}
        if samples:
            if self._t0 is not None:
                # ``samples`` covers exactly the window since the previous
                # samples-bearing log — pair it with THIS window's
                # duration (minus recorded pauses), never a stale count.
                dt = now - self._t0 - self._paused
                rate = samples / dt if dt > 0 else 0.0
                record["samples_per_sec"] = round(rate, 2)
                record["samples_per_sec_per_chip"] = round(rate / self._n_chips, 2)
            self._t0 = now
            self._paused = 0.0
        for k, v in metrics.items():
            record[k] = float(v) if hasattr(v, "__float__") else v
        line = json.dumps(record)
        if self._stream is not None:
            print(line, file=self._stream, flush=True)
        if self._fh is not None:
            self._fh.write(line + "\n")
            self._fh.flush()
        return record

    def add_pause(self, seconds: float):
        """Exclude a non-training interval (eval pass, checkpoint stall)
        from the current samples/sec window — correct whatever the
        alignment between pause and log cadence."""
        self._paused += max(float(seconds), 0.0)

    def set_n_chips(self, n_chips: int):
        """Re-normalize the per-chip rate denominator — the elastic
        mesh-shrink path (resilience/elastic.py) calls this after a
        degraded run sheds capacity, so ``samples_per_sec_per_chip``
        stays an honest per-surviving-chip figure."""
        self._n_chips = max(int(n_chips), 1)

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class EventLog:
    """Append-only JSONL health-event journal (resilience subsystem).

    One JSON object per line — ``{"ts": ..., "event": "...", ...}`` —
    written by :class:`fm_spark_tpu.resilience.Supervisor` for every
    state transition (attempt / failure / probe / backoff /
    circuit_open / recovered), so a round's failure handling is a
    machine-readable artifact instead of scattered stderr prose.
    Separate from :class:`MetricsLogger`: health events are sparse,
    schema'd by ``event``, and must never interleave with a consumer's
    stdout result stream — the default sink is a file only.

    Best-effort by contract: a journal write must never take down the
    operation it is narrating (same policy as bench.py's incremental
    artifact writes).
    """

    def __init__(self, path: str | None = None, stream=None):
        self._fh = open(path, "a") if path else None
        self._stream = stream

    def emit(self, event: str, **fields) -> dict:
        record = {"ts": round(time.time(), 3), "event": event, **fields}
        try:
            line = json.dumps(record)
            if self._stream is not None:
                print(line, file=self._stream, flush=True)
            if self._fh is not None:
                self._fh.write(line + "\n")
                self._fh.flush()
        except (OSError, TypeError, ValueError):
            # TypeError included: an unserializable field (a numpy/jax
            # scalar) must degrade to a dropped event, not abort the
            # recovery path being narrated.
            pass
        return record

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def read_events(path: str) -> list[dict]:
    """Parse an :class:`EventLog` JSONL file (tools + tests); unparseable
    lines (a torn tail write) are skipped, not fatal."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out
