"""Structured per-step metrics logging.

The reference logs per-iteration loss via log4j and relies on the Spark UI
for counters (SURVEY.md §5). Rebuild: JSONL records to stdout and/or a file
— {step, loss, auc, samples_per_sec_per_chip, grad_norm, ...} — cheap to
parse, diffable, and the driver's bench harness consumes one-line JSON.
"""

from __future__ import annotations

import json
import sys
import time

from fm_spark_tpu.utils import durable


class MetricsLogger:
    """Writes one JSON object per line; tracks wall-clock samples/sec.

    Since ISSUE 7 this is a thin facade over the process-wide metrics
    registry (:mod:`fm_spark_tpu.obs.metrics`): the samples/sec window
    math and the JSONL transport live here, but every figure a ``log``
    call computes is also published as a registry instrument
    (``train.samples_total`` counter, ``train.samples_per_sec`` /
    ``train.samples_per_sec_per_chip`` / ``train.n_chips`` gauges, and
    a ``train.<metric>`` gauge per numeric keyword) — so snapshots,
    the Prometheus dump, and the bench ``telemetry`` block see the
    same numbers the stdout stream prints.
    """

    def __init__(self, path: str | None = None, stream=None, n_chips: int = 1):
        # Lazy import: utils.logging is imported by obs.trace (the
        # EventLog sink), so a module-level import here would cycle.
        from fm_spark_tpu.obs import metrics as obs_metrics

        self._fh = open(path, "a") if path else None
        self._stream = stream if stream is not None else sys.stdout
        self._n_chips = max(n_chips, 1)
        self._t0 = None
        self._paused = 0.0
        self._registry = obs_metrics.registry()
        self._c_samples = self._registry.counter("train.samples_total")
        self._g_rate = self._registry.gauge("train.samples_per_sec")
        self._g_rate_chip = self._registry.gauge(
            "train.samples_per_sec_per_chip")
        self._g_chips = self._registry.gauge("train.n_chips")
        self._g_chips.set(self._n_chips)

    def log(self, step: int, samples: int = 0, **metrics) -> dict:
        now = time.perf_counter()
        record = {"step": step, "ts": time.time()}
        if samples:
            self._c_samples.add(samples)
            if self._t0 is not None:
                # ``samples`` covers exactly the window since the previous
                # samples-bearing log — pair it with THIS window's
                # duration (minus recorded pauses), never a stale count.
                dt = now - self._t0 - self._paused
                rate = samples / dt if dt > 0 else 0.0
                record["samples_per_sec"] = round(rate, 2)
                record["samples_per_sec_per_chip"] = round(rate / self._n_chips, 2)
                self._g_rate.set(record["samples_per_sec"])
                self._g_rate_chip.set(record["samples_per_sec_per_chip"])
            self._t0 = now
            self._paused = 0.0
        for k, v in metrics.items():
            record[k] = float(v) if hasattr(v, "__float__") else v
            if isinstance(record[k], (int, float)):
                self._registry.gauge(f"train.{k}").set(record[k])
        line = json.dumps(record)
        if self._stream is not None:
            print(line, file=self._stream, flush=True)
        if self._fh is not None:
            # Observability tier (ISSUE 20): best-effort through the
            # durable seam — a dead metrics file degrades telemetry
            # (counted), never the training step being logged.
            durable.append_line(self._fh, line, path_class="obs",
                                best_effort=True)
        return record

    def add_pause(self, seconds: float):
        """Exclude a non-training interval (eval pass, checkpoint stall)
        from the current samples/sec window — correct whatever the
        alignment between pause and log cadence."""
        self._paused += max(float(seconds), 0.0)

    def set_n_chips(self, n_chips: int):
        """Re-normalize the per-chip rate denominator — the elastic
        mesh-shrink path (resilience/elastic.py) calls this after a
        degraded run sheds capacity, so ``samples_per_sec_per_chip``
        stays an honest per-surviving-chip figure."""
        self._n_chips = max(int(n_chips), 1)
        self._g_chips.set(self._n_chips)

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class EventLog:
    """Append-only JSONL health-event journal (resilience subsystem).

    One JSON object per line — ``{"ts": ..., "event": "...", ...}`` —
    written by :class:`fm_spark_tpu.resilience.Supervisor` for every
    state transition (attempt / failure / probe / backoff /
    circuit_open / recovered), so a round's failure handling is a
    machine-readable artifact instead of scattered stderr prose.
    Separate from :class:`MetricsLogger`: health events are sparse,
    schema'd by ``event``, and must never interleave with a consumer's
    stdout result stream — the default sink is a file only.

    Best-effort by contract: a journal write must never take down the
    operation it is narrating (same policy as bench.py's incremental
    artifact writes).

    ``mirror_to_flight=True`` additionally records every emitted event
    into the flight-recorder ring (:mod:`fm_spark_tpu.obs`) so the
    last-N crash window carries the health narrative — the ISSUE 7
    consolidation wiring for health journals. Never set it on an
    EventLog the obs plane itself writes through (the trace sink):
    that would loop every span back into the ring twice.
    """

    def __init__(self, path: str | None = None, stream=None,
                 mirror_to_flight: bool = False,
                 path_class: str = "obs"):
        self._fh = open(path, "a") if path else None
        self._stream = stream
        self._mirror = bool(mirror_to_flight)
        # The durable-seam scoping class (ISSUE 20): journals are
        # ``obs`` by default; the quarantine dead-letter log declares
        # ``quarantine`` so a schedule can fail it independently.
        self._path_class = str(path_class)

    def emit(self, event: str, **fields) -> dict:
        record = {"ts": round(time.time(), 3), "event": event, **fields}
        try:
            line = json.dumps(record)
            if self._stream is not None:
                print(line, file=self._stream, flush=True)
            if self._fh is not None:
                # Best-effort through the durable seam (the
                # observability tier of the ISSUE 20 degradation
                # policy): failures are counted, never raised.
                durable.append_line(self._fh, line,
                                    path_class=self._path_class,
                                    best_effort=True)
        except (OSError, TypeError, ValueError):
            # TypeError included: an unserializable field (a numpy/jax
            # scalar) must degrade to a dropped event, not abort the
            # recovery path being narrated.
            pass
        if self._mirror:
            try:
                from fm_spark_tpu import obs

                obs.event(event, ts=record["ts"], **fields)
            except Exception:
                pass
        return record

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def read_events(path: str) -> list[dict]:
    """Parse an :class:`EventLog` JSONL file (tools + tests); unparseable
    lines (a torn tail write) are skipped, not fatal."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out
