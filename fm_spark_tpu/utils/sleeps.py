"""Designed-sleep scaling (ISSUE 17 satellite).

Fault drills deliberately SLEEP — supervisor retry backoffs, the bench
parent's between-attempt backoff — and those sleeps dominate the
wall-clock of the fault-injection suite (tests/test_bench_faults.py)
while proving nothing by themselves: the assertions are about
*behavior* (events journaled, retries counted, verdicts classified),
never about how long the process waited. ``FM_SPARK_TEST_SLEEP_SCALE``
scales every designed sleep multiplicatively (the fault tests set
0.25; unset = 1.0 = production timing).

Scope discipline: the knob scales ONLY sleeps that are design choices.
It must never scale measured durations, deadlines a test asserts on,
or the watchdog's hang-detection windows — shrinking those would change
the behavior under test, not just the wait for it.
"""

from __future__ import annotations

import os

ENV = "FM_SPARK_TEST_SLEEP_SCALE"


def sleep_scale(default: float = 1.0) -> float:
    """The designed-sleep multiplier: ``FM_SPARK_TEST_SLEEP_SCALE``
    parsed as a float, clamped to [0, 1] — scaling sleeps UP is never
    what a test wants, and production leaves the env unset."""
    val = os.environ.get(ENV, "").strip()
    if not val:
        return float(default)
    try:
        scale = float(val)
    except ValueError:
        return float(default)
    return min(max(scale, 0.0), 1.0)


def scaled(seconds: float) -> float:
    """``seconds * sleep_scale()`` — for designed-sleep call sites."""
    return float(seconds) * sleep_scale()
