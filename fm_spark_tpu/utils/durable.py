"""The durable-write seam: every byte the repo promises to keep (ISSUE 20).

Before this module, each durability-critical writer hand-rolled its own
write-tmp-fsync-rename (checkpoint manifests, flight compaction,
introspection bundles) or bare append (perf ledger, EventLog journals,
flight spool) — correct individually, but un-injectable collectively:
no single place where a hostile disk could be simulated, so none of the
repo's durability claims were ever tested against ENOSPC, EIO, torn
renames, or fsync stalls. This module is that single place. Checkpoint
manifests / tombstones / ``last_good``, the obs ledger + flight spool +
EventLog journals (including the quarantine dead-letter log), the embed
cold-store write-back, and the compile-cache breadcrumb all route their
durable bytes through these functions, and
:mod:`fm_spark_tpu.resilience.iofaults` injects at exactly four points:
``io_write`` (payload bytes), ``io_fsync`` (file/dir fsync),
``io_rename`` (atomic publish), ``io_read`` (durable read) — each
scopable by the PATH CLASS the call site declares (``ckpt``, ``obs``,
``embed``, ``cache``, ``quarantine``).

Tier discipline (the degradation policy, ISSUE 20):

- **best-effort** (``best_effort=True`` — the observability tier):
  a failed write is COUNTED (``io.write_failed_total`` +
  ``io.write_failed.<class>_total`` counters, ``obs/io_degraded``
  gauge, an ``io_write_failed`` flight event) and swallowed; the
  function returns False. Training/serving bytes must be provably
  unchanged by any number of these failures — pinned by the
  byte-identical-params chaos test.
- **fail-loud** (the default — the checkpoint/tombstone tier): the
  ``OSError`` propagates after being counted; the CALLER owns retry /
  emergency-GC / walk-back policy (checkpoint.Checkpointer's bounded
  backoff + ``CheckpointIOError``).
- **reads verify-then-walk-back**: :func:`read_bytes` honors
  ``io_read`` (EIO and short reads); callers that restore state treat
  a failed/torn read as "this generation is bad, walk back", never a
  crash loop.

Failure accounting is also mirrored in an in-process dict
(:func:`io_failure_counts`) so artifact-only auditors and tests can
assert on it without a configured obs registry.
"""

from __future__ import annotations

import json
import os
import threading

__all__ = [
    "append_line",
    "append_line_path",
    "atomic_write_bytes",
    "atomic_write_json",
    "atomic_write_lines",
    "atomic_write_text",
    "fsync_dir",
    "io_failure_counts",
    "read_bytes",
    "read_json",
    "reset_failure_counts",
]

_lock = threading.Lock()
_failures: dict[str, int] = {}

# Lazy iofaults binding: this module is imported from obs internals
# (metrics, flight, introspect) whose package init must not be forced
# through resilience's package init mid-import (supervisor/watchdog
# import obs back). Resolved once, at the first durable operation —
# by then every package involved has finished importing.
_iofaults = None


def _io():
    global _iofaults
    if _iofaults is None:
        from fm_spark_tpu.resilience import iofaults

        _iofaults = iofaults
    return _iofaults

# Reentrancy guard: noting a failure emits a flight event, which
# appends to the spool THROUGH this module — if that append also fails
# (an obs-wide fault window), the inner failure is counted but must not
# recurse into another event emission.
_tls = threading.local()


def io_failure_counts() -> dict:
    """In-process write-failure counts by path class (plus ``total``).
    The registry-free mirror of the ``io.write_failed*`` counters."""
    with _lock:
        out = dict(_failures)
    out.setdefault("total", 0)
    return out


def reset_failure_counts() -> None:
    """Zero the in-process failure mirror (test isolation)."""
    with _lock:
        _failures.clear()


def _note_failure(path_class: "str | None", phase: str,
                  best_effort: bool) -> None:
    cls = path_class or "unscoped"
    with _lock:
        _failures["total"] = _failures.get("total", 0) + 1
        _failures[cls] = _failures.get(cls, 0) + 1
        if best_effort:
            # Best-effort failures are the DEGRADED-mode count (the
            # swallowed ones); fail-loud failures surface to a caller
            # who owns them. Auditors key the gauge contract on this.
            _failures["best_effort"] = _failures.get(
                "best_effort", 0) + 1
    try:
        from fm_spark_tpu import obs

        obs.counter("io.write_failed_total").add(1)
        obs.counter(f"io.write_failed.{cls}_total").add(1)
        if best_effort:
            # The degraded-observability signal: some telemetry since
            # this run started is missing from disk. Sticky by design —
            # a doctor must see that the record has holes even after
            # the disk heals.
            obs.gauge("obs/io_degraded").set(1.0)
        if not getattr(_tls, "noting", False):
            _tls.noting = True
            try:
                obs.event("io_write_failed", path_class=cls,
                          phase=phase, best_effort=bool(best_effort))
            finally:
                _tls.noting = False
    except Exception:
        pass


def _write_payload(f, data: bytes, path_class: "str | None") -> None:
    """One injectable payload write: ``io_write`` may fail it outright
    or tear it after K bytes (the torn tmp is never published — the
    atomic protocol's whole point)."""
    budget = _io().on_write(path_class)
    if budget is not None and budget < len(data):
        f.write(data[:budget])
        f.flush()
        raise OSError(5, f"[iofault] torn write after {budget} bytes")
    f.write(data)


def atomic_write_bytes(path: str, data: bytes, *,
                       path_class: "str | None" = None,
                       best_effort: bool = False,
                       sync_dir: bool = False) -> bool:
    """Write-tmp-fsync-rename: ``data`` is either fully at ``path`` or
    not there at all, never torn. ``sync_dir=True`` additionally fsyncs
    the parent directory after the publish (the rename itself made
    durable — checkpoint pointer writes use this). Returns True on
    success; False only in ``best_effort`` mode."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            _write_payload(f, data, path_class)
            f.flush()
            _io().on_fsync(path_class)
            os.fsync(f.fileno())
        _io().on_rename(path_class)
        os.replace(tmp, path)
        if sync_dir:
            fsync_dir(os.path.dirname(path) or ".", path_class)
    except OSError:
        _note_failure(path_class, "atomic_write", best_effort)
        if best_effort:
            return False
        raise
    return True


def atomic_write_text(path: str, text: str, **kw) -> bool:
    return atomic_write_bytes(path, text.encode("utf-8"), **kw)


def atomic_write_json(path: str, obj, *, default=None, **kw) -> bool:
    return atomic_write_text(path, json.dumps(obj, default=default),
                             **kw)


def atomic_write_lines(path: str, lines, **kw) -> bool:
    """Atomically publish an entire line file (flight-spool
    compaction). The payload is one write — a torn budget tears the
    TMP, never the published file."""
    body = "".join(line.rstrip("\n") + "\n" for line in lines)
    return atomic_write_text(path, body, **kw)


def append_line(fh, line: str, *,
                path_class: "str | None" = None,
                best_effort: bool = False) -> bool:
    """Guarded append of one line to an open handle: the injectable
    form of ``fh.write(line + "\\n"); fh.flush()``. A ``torn_write:K``
    rule really does leave K bytes of a torn line on disk — readers of
    append-only logs must (and do) skip unparseable lines. Returns
    True on success; False only in ``best_effort`` mode."""
    data = line.rstrip("\n") + "\n"
    try:
        budget = _io().on_write(path_class)
        if budget is not None and budget < len(data):
            fh.write(data[:budget])
            fh.flush()
            raise OSError(
                5, f"[iofault] torn append after {budget} bytes")
        fh.write(data)
        fh.flush()
    except (OSError, ValueError):
        # ValueError: write to a closed handle — the append-log
        # equivalent of a dead disk, same degradation path.
        _note_failure(path_class, "append", best_effort)
        if best_effort:
            return False
        raise
    return True


def append_line_path(path: str, line: str, *,
                     path_class: "str | None" = None,
                     best_effort: bool = False) -> bool:
    """Open-append-close form of :func:`append_line` for writers
    without a persistent handle (the perf ledger). Open failures
    (EROFS, EIO at open) take the same accounting path as write
    failures."""
    try:
        fh = open(path, "a")
    except OSError:
        _note_failure(path_class, "open", best_effort)
        if best_effort:
            return False
        raise
    try:
        return append_line(fh, line, path_class=path_class,
                           best_effort=best_effort)
    finally:
        fh.close()


def fsync_dir(path: str, path_class: "str | None" = None) -> None:
    """fsync a DIRECTORY: makes a completed rename itself durable
    (POSIX renames are not, until the containing dir is synced)."""
    _io().on_fsync(path_class)
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def read_bytes(path: str, *,
               path_class: "str | None" = None) -> bytes:
    """Durable read with ``io_read`` injection: EIO raises, a
    ``torn_write:K`` budget delivers only the first K bytes (a short
    read). Restore-side callers treat both as "walk back", so the
    injection exercises the verify-then-walk-back tier end to end."""
    budget = _io().on_read(path_class)
    with open(path, "rb") as f:
        data = f.read()
    if budget is not None and budget < len(data):
        return data[:budget]
    return data


def read_json(path: str, *, path_class: "str | None" = None):
    return json.loads(read_bytes(path, path_class=path_class))
