"""Streaming on-device evaluation metrics: logloss and histogram AUC.

The reference computes final AUC/logloss in example driver code with
whole-dataset arrays (SURVEY.md §5 "Metrics"); at 45M-1TB scale the rebuild
needs a streaming formulation that lives on device and reduces with ``psum``
(SURVEY.md §7 hard part 4: "fixed-bin histogram AUC on device, psum'd, not
sklearn").

Design: scores are squashed to probabilities p ∈ [0,1]; positives and
negatives each accumulate a fixed-bin histogram of p. AUC is then the
probability a random positive outranks a random negative, computed exactly
from the two histograms up to bin-width resolution (ties within a bin count
half, the standard mid-rank convention). All state is a small pytree of
device arrays — psum over any mesh axis composes correctly because every
field is a plain sum.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


DEFAULT_BINS = 4096


class MetricsState(NamedTuple):
    """Additive metric accumulators (every field psum-safe)."""

    pos_hist: jax.Array   # [bins] count of positives per probability bin
    neg_hist: jax.Array   # [bins]
    loss_sum: jax.Array   # scalar Σ per-example loss
    count: jax.Array      # scalar number of examples
    sq_err_sum: jax.Array  # scalar Σ (ŷ − y)² (regression RMSE support)


def init_metrics(bins: int = DEFAULT_BINS) -> MetricsState:
    z = jnp.zeros((), jnp.float32)
    return MetricsState(
        pos_hist=jnp.zeros((bins,), jnp.float32),
        neg_hist=jnp.zeros((bins,), jnp.float32),
        loss_sum=z,
        count=z,
        sq_err_sum=z,
    )


def update_metrics(
    state: MetricsState,
    scores: jax.Array,
    labels: jax.Array,
    per_example_loss: jax.Array,
    weights: jax.Array | None = None,
    predictions: jax.Array | None = None,
) -> MetricsState:
    """Fold a batch of raw scores into the accumulators (jit/psum friendly).

    ``weights`` masks padded examples (0 ⇒ ignore), enabling fixed-shape
    final batches. ``predictions`` (default: the raw scores) feeds the
    squared-error accumulator, so regression RMSE reflects the clipped
    outputs the model actually serves.
    """
    bins = state.pos_hist.shape[0]
    if weights is None:
        weights = jnp.ones_like(labels)
    if predictions is None:
        predictions = scores
    w = weights.astype(jnp.float32)
    p = jax.nn.sigmoid(scores)
    idx = jnp.clip((p * bins).astype(jnp.int32), 0, bins - 1)
    is_pos = (labels > 0.5).astype(jnp.float32) * w
    is_neg = (labels <= 0.5).astype(jnp.float32) * w
    pos_hist = state.pos_hist.at[idx].add(is_pos)
    neg_hist = state.neg_hist.at[idx].add(is_neg)
    err = (predictions - labels) * w
    return MetricsState(
        pos_hist=pos_hist,
        neg_hist=neg_hist,
        loss_sum=state.loss_sum + jnp.sum(per_example_loss * w),
        count=state.count + jnp.sum(w),
        sq_err_sum=state.sq_err_sum + jnp.sum(err * err),
    )


def finalize_metrics(state: MetricsState) -> dict:
    """Histograms → {auc, logloss, rmse, count}. Small; fine on host or device.

    AUC: P(score_pos > score_neg) + ½·P(tie), summing over bin pairs via the
    cumulative negative mass below each bin.
    """
    pos, neg = state.pos_hist, state.neg_hist
    p_total = jnp.sum(pos)
    n_total = jnp.sum(neg)
    neg_below = jnp.cumsum(neg) - neg  # negatives strictly below each bin
    wins = jnp.sum(pos * (neg_below + 0.5 * neg))
    denom = jnp.maximum(p_total * n_total, 1.0)
    auc = jnp.where(p_total * n_total > 0, wins / denom, jnp.float32(0.5))
    count = jnp.maximum(state.count, 1.0)
    return {
        "auc": auc,
        "logloss": state.loss_sum / count,
        "rmse": jnp.sqrt(state.sq_err_sum / count),
        "count": state.count,
    }
