"""Honor an explicit ``JAX_PLATFORMS=cpu`` request on a machine whose TPU
plugin misbehaves.

Two distinct failure modes, both observed on this session's tunneled
attachment (PERF.md round-5 notes):

1. The plugin ignores the ``JAX_PLATFORMS`` env var and grabs the device
   anyway — fixed by ``jax.config.update("jax_platforms", "cpu")`` before
   backend init.
2. When the attachment is DEAD, the plugin's backend factory hangs forever
   inside ``jax.devices()`` — even with the config pinned to cpu (observed
   2026-07-31: the factory initializes regardless and never returns). The
   only in-process fix is to deregister the factory before first backend
   init; tests/benches that asked for cpu never want the real chip, so
   that is always safe for them.

Private-API use (``xla_bridge._backend_factories``) is deliberate and
best-effort: on a jax version where the attribute moves, we degrade to
mode-1 behavior rather than erroring.
"""

from __future__ import annotations

import os

__all__ = ["force_cpu_platform"]


def force_cpu_platform(only_if_env: bool = True) -> bool:
    """If ``JAX_PLATFORMS=cpu`` is requested (or unconditionally with
    ``only_if_env=False``), pin jax to the cpu backend and drop the
    session's axon TPU factory so a dead attachment cannot hang init.

    Returns True when the cpu pin was applied. Call BEFORE the first
    ``jax.devices()``/jit; a no-op (False) when the env var asks for a
    real platform.
    """
    if only_if_env and os.environ.get("JAX_PLATFORMS", "").strip() != "cpu":
        return False

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        return False  # backend already initialized — use what exists
    try:
        from jax._src import xla_bridge

        # Drop every plugin factory, not just this session's "axon": the
        # caller pinned cpu, so no accelerator factory may run — and any of
        # them (axon today, a differently-named plugin elsewhere) can hang
        # init when its device is unreachable. "tpu" must SURVIVE even
        # though it is never initialized here: jax derives
        # ``known_platforms()`` from this dict, and Pallas registers tpu
        # MLIR lowerings at import — removing the factory turns every
        # Pallas import into NotImplementedError("unknown platform tpu").
        for name in list(xla_bridge._backend_factories):
            if name not in ("cpu", "tpu"):
                xla_bridge._backend_factories.pop(name, None)
    except Exception:
        pass
    return True
