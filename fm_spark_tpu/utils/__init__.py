"""Cross-cutting utilities: metrics, structured logging, profiling,
and the persistent-compile-cache warm-start switch (compile_cache)."""

from fm_spark_tpu.utils import compile_cache  # noqa: F401
from fm_spark_tpu.utils.metrics import (  # noqa: F401
    MetricsState,
    init_metrics,
    update_metrics,
    finalize_metrics,
)
from fm_spark_tpu.utils.logging import MetricsLogger  # noqa: F401
