"""Cross-cutting utilities: metrics, structured logging, profiling."""

from fm_spark_tpu.utils.metrics import (  # noqa: F401
    MetricsState,
    init_metrics,
    update_metrics,
    finalize_metrics,
)
from fm_spark_tpu.utils.logging import MetricsLogger  # noqa: F401
