"""Persistent XLA compilation cache: the warm-start fast path.

Why this exists (ISSUE 1 / VERDICT r5 "What's weak" #1): on this
session's flaky TPU attachment, backend init + the first XLA compile of
the fused train step costs minutes — longer than a flapping attachment
stays healthy — so BENCH_r03–r05 all timed out with null artifacts even
though the step itself runs at 1.14× the target. The step *programs*
are deterministic functions of (spec, TrainConfig, batch shape), so a
SECOND process should never pay XLA again: jax's persistent compilation
cache serializes every compiled executable to disk keyed by the lowered
HLO + compile options + platform version, and a warm process
deserializes in milliseconds instead of recompiling.

This module is the repo's single switch for that cache:

- :func:`enable` points jax at a repo-local cache directory and drops
  the min-size/min-compile-time thresholds to zero so EVERY executable
  is cached (the defaults skip sub-second compiles — exactly the wrong
  call for a bench that must survive short attachment windows, and for
  the CPU tests that pin this behavior).
- :func:`enable_from_env` is the zero-flag wiring for production loops:
  ``FM_SPARK_COMPILE_CACHE=<dir>`` (or ``=1`` for the default repo-local
  dir) turns the cache on without touching any call site.
- :func:`cache_stats` exposes hit/miss counts (via jax's monitoring
  events) plus on-disk entry count and bytes, so tests can assert the
  warm-start contract — "a warm process performs ZERO fresh XLA
  compilations" — instead of trusting wall-clock.

Call :func:`enable` BEFORE the first jit compile; enabling later still
covers all subsequent compiles (earlier ones are simply not cached).
The cache composes with the AOT entries (:func:`fm_spark_tpu.sparse.
precompile_field_sparse_step` and friends): an AOT ``.compile()``
populates the same cache the later jit dispatch reads.
"""

from __future__ import annotations

import os
import threading

__all__ = [
    "DEFAULT_ENV",
    "cache_stats",
    "default_cache_dir",
    "enable",
    "enable_from_env",
    "is_enabled",
    "reset_stats",
]

#: Environment switch read by :func:`enable_from_env`: a directory path,
#: or ``1``/``true`` for :func:`default_cache_dir`.
DEFAULT_ENV = "FM_SPARK_COMPILE_CACHE"

# Repo root = two levels above the package (utils/ -> fm_spark_tpu/ ->
# repo). Repo-local by design: the cache travels with the checkout, so
# tpu_watch.sh's CPU-side pre-warm and a later on-chip bench see the
# same directory without any coordination.
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_DIR = os.path.join(_REPO_ROOT, ".jax_compile_cache")

# jax monitoring event names (jax/_src/compiler.py): one *request* per
# compile that consults the cache, one *hit* per executable served from
# it. misses = requests − hits, i.e. fresh XLA compilations.
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_REQUEST_EVENT = "/jax/compilation_cache/compile_requests_use_cache"

_lock = threading.Lock()
_state = {"dir": None, "hits": 0, "requests": 0, "listener": False}


def default_cache_dir() -> str:
    """The cache directory used when none is given: ``$FM_SPARK_COMPILE_
    CACHE`` if it names a path, else ``<repo>/.jax_compile_cache``.
    Boolean spellings (on OR off) are switches, never paths — an
    operator who exported the falsy form and then passes an explicit
    ``--compile-cache`` flag gets the repo-local default, not a
    directory literally named ``0``."""
    env = os.environ.get(DEFAULT_ENV, "").strip()
    if env and env.lower() not in ("1", "true", "yes", "on",
                                   "0", "false", "no", "off"):
        return env
    return DEFAULT_DIR


def _on_event(event: str, **_kw) -> None:
    if event == _HIT_EVENT:
        with _lock:
            _state["hits"] += 1
    elif event == _REQUEST_EVENT:
        with _lock:
            _state["requests"] += 1


def enable(cache_dir: str | None = None) -> str:
    """Enable jax's persistent compilation cache at ``cache_dir``
    (default: :func:`default_cache_dir`). Idempotent; returns the
    resolved absolute path. Safe to call before OR after backend init —
    only compiles issued after the call are covered."""
    path = os.path.abspath(cache_dir or default_cache_dir())
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # Cache EVERYTHING: the default thresholds skip small/fast compiles,
    # but warm-start correctness (zero fresh compilations) needs every
    # executable the step dispatch will ask for — including the tiny
    # device_put/convert helpers that precede the fused step.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_enable_compilation_cache", True)
    os.makedirs(path, exist_ok=True)
    # Provenance breadcrumb through the durable seam (ISSUE 20, the
    # ``cache`` path class): which process last enabled the cache, and
    # with which jax — the first thing to check when a "warm" start
    # recompiles. Best-effort: a cache on a failing disk still works
    # as a cache.
    from fm_spark_tpu.utils import durable

    durable.atomic_write_json(
        os.path.join(path, "cache_meta.json"),
        {"dir": path, "pid": os.getpid(),
         "jax_version": getattr(jax, "__version__", None)},
        path_class="cache", best_effort=True)
    try:
        # jax latches "is the cache used?" at the FIRST compile of the
        # process; a process that compiled anything before enable()
        # (e.g. a training script that warmed up before opting in)
        # would silently never write an entry. Resetting the latch
        # makes enable() effective at any point; the file cache lazily
        # re-initializes from the same directory on the next compile.
        # Private API, best-effort — same policy as _install_listener.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass
    with _lock:
        _state["dir"] = path
    _install_listener()
    return path


def enable_from_env() -> str | None:
    """Enable the cache iff ``FM_SPARK_COMPILE_CACHE`` is set (a path,
    or ``1`` for the default dir; the conventional falsy spellings
    ``0/false/no/off`` mean OFF, not "a directory named 0"); returns
    the dir or None. The no-flag wiring: training loops call this so
    an operator can warm-start any entry point without new CLI
    plumbing."""
    val = os.environ.get(DEFAULT_ENV, "").strip()
    if not val or val.lower() in ("0", "false", "no", "off"):
        return None
    return enable()


def is_enabled() -> bool:
    return _state["dir"] is not None


def _install_listener() -> None:
    """Register the monitoring listener once. Private-API use
    (``jax._src.monitoring``) is deliberate and best-effort, same policy
    as utils/cpuguard.py: if the module moves, hit/miss counters stay at
    zero and :func:`cache_stats` still reports the on-disk truth."""
    with _lock:
        if _state["listener"]:
            return
        _state["listener"] = True
    try:
        from jax._src import monitoring

        monitoring.register_event_listener(_on_event)
    except Exception:
        pass


def reset_stats() -> None:
    """Zero the in-process hit/miss counters (on-disk entries are
    untouched). Tests use this to isolate the compile they measure."""
    with _lock:
        _state["hits"] = 0
        _state["requests"] = 0


def cache_stats() -> dict:
    """Counters + on-disk footprint::

        {"enabled": bool, "dir": str|None,
         "requests": int, "hits": int, "misses": int,
         "entries": int, "bytes": int}

    ``misses`` = compile requests served by a fresh XLA compilation this
    process; the warm-start contract is ``misses == 0`` on a populated
    cache. ``entries`` counts serialized executables (the ``*-cache``
    files of jax's LRU file cache; ``-atime`` bookkeeping is excluded).
    """
    with _lock:
        d = _state["dir"]
        hits, requests = _state["hits"], _state["requests"]
    entries = 0
    nbytes = 0
    if d and os.path.isdir(d):
        for root, _dirs, files in os.walk(d):
            for f in files:
                if f.endswith("-atime"):
                    continue
                entries += 1
                try:
                    nbytes += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
    return {
        "enabled": d is not None,
        "dir": d,
        "requests": requests,
        "hits": hits,
        "misses": max(0, requests - hits),
        "entries": entries,
        "bytes": nbytes,
    }
