"""fmlint JAX hazard pass: host syncs, jit side effects, unfenced timing.

The throughput headline (1,422,411 samples/s/chip, MEASURED.json)
depends on the step loop staying ASYNC: the jitted step returns at
dispatch time and the device pipelines ahead of the host. One stray
``float(loss)`` per step serializes host and device and the headline
dies silently — nothing errors, the number just halves. Three rules:

``jax-host-sync``
    Inside ``for``/``while`` loop bodies of the hot-path files
    (:data:`HOT_FILES` — train.py, sparse.py, parallel/,
    serve/engine.py), flag the device→host synchronization spellings:
    ``float(...)``/``int(subscript)`` of a non-constant,
    ``.item()``, ``.block_until_ready()``/``jax.block_until_ready``,
    ``jax.device_get``, and ``np.asarray``/``np.array`` (``jnp.*`` is
    device-side and exempt). The DELIBERATE fences — the per-window
    loss fetch that IS the measurement boundary (PR 7), the first-step
    compile fence — carry reasoned suppressions; anything else is a
    stray sync on the hot path. Comprehensions don't count as loops
    (a post-loop summary comprehension is not the step loop).

``jax-jit-side-effect``
    Python-side effects inside functions handed to ``jax.jit`` /
    ``pmap`` / ``shard_map`` run at TRACE time (once, or worse,
    per-retrace) — not per step: ``print``, journal ``.emit(...)``,
    and ``obs.*`` registry calls inside jitted bodies are bugs in
    every direction and are flagged package-wide.

``jax-unfenced-timing``
    The PR-7 rule, now enforced: a timing window (two or more
    ``perf_counter``/``monotonic``/``time.time`` calls) inside a hot
    loop body that also dispatches step work must contain a fence
    between the first and last timing call — otherwise it measures
    enqueue latency, not device time, on an async backend.
"""

from __future__ import annotations

import ast
import fnmatch

from .core import Finding, call_name, rule, walk_with_func

#: The hot-path surface (repo-relative; fnmatch patterns): every file
#: whose loop bodies the async-dispatch discipline protects.
HOT_FILES = (
    "fm_spark_tpu/train.py",
    "fm_spark_tpu/sparse.py",
    "fm_spark_tpu/online.py",
    "fm_spark_tpu/parallel/*.py",
    "fm_spark_tpu/serve/engine.py",
)

#: Callables that force a device→host sync (dotted-name terminals).
FENCE_ATTR_CALLS = frozenset({"item", "block_until_ready"})
FENCE_DOTTED = frozenset({"jax.block_until_ready", "jax.device_get",
                          "np.asarray", "np.array", "numpy.asarray",
                          "numpy.array"})

TIMING_CALLS = frozenset({"time.perf_counter", "time.monotonic",
                          "time.time", "perf_counter", "monotonic"})

#: Side-effect spellings banned inside jitted bodies.
JIT_BANNED_PREFIXES = ("obs.",)
JIT_BANNED_CALLS = frozenset({"print"})
JIT_BANNED_ATTRS = frozenset({"emit"})

#: What counts as "dispatching step work" for the timing rule: a call
#: whose terminal name mentions a step, or a compiled-executable call.
DISPATCH_MARKERS = ("step", "compiled")


def hot_files(ctx):
    out = []
    seen = set()
    for sf in ctx.package_files():
        for pat in HOT_FILES:
            if fnmatch.fnmatch(sf.rel, pat) and sf.rel not in seen:
                seen.add(sf.rel)
                out.append(sf)
    return out


def _is_sync_call(node: ast.Call) -> str | None:
    """The host-sync spelling this call is, or None."""
    name = call_name(node)
    if name in FENCE_DOTTED:
        return name
    if (isinstance(node.func, ast.Attribute)
            and node.func.attr in FENCE_ATTR_CALLS
            and not name.startswith(("jnp.", "jax.numpy."))):
        return f".{node.func.attr}()"
    if name == "float" and node.args and not isinstance(
            node.args[0], ast.Constant):
        return "float(...)"
    if (name == "int" and node.args
            and isinstance(node.args[0], ast.Subscript)):
        return "int(...)"
    return None


def _is_fence(node: ast.Call) -> bool:
    return _is_sync_call(node) is not None


def _is_timing(node: ast.Call) -> bool:
    return call_name(node) in TIMING_CALLS


def _is_dispatch(node: ast.Call) -> bool:
    term = call_name(node).rsplit(".", 1)[-1].lower()
    return any(m in term for m in DISPATCH_MARKERS)


def _loops_with_func(tree):
    """Yield ``(loop_node, enclosing_function)`` for every for/while."""
    for node, func in walk_with_func(tree):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            yield node, func


def _walk_no_comprehensions(node):
    """Walk a loop body without descending into comprehensions or
    nested function defs (their bodies are not the loop's hot path —
    a generator consumed later is not a per-iteration sync)."""
    yield node
    if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                         ast.GeneratorExp, ast.FunctionDef,
                         ast.AsyncFunctionDef, ast.Lambda)):
        return
    for child in ast.iter_child_nodes(node):
        yield from _walk_no_comprehensions(child)


@rule("jax-host-sync",
      "no device→host sync (float/int/.item/block_until_ready/"
      "np.asarray/device_get) inside hot-path loop bodies — the step "
      "loop must stay async; deliberate fences carry a reasoned "
      "suppression (ISSUE 15)")
def jax_host_sync(ctx):
    out = []
    for sf in hot_files(ctx):
        tree = sf.tree
        if tree is None:
            continue
        seen_lines = set()
        for loop, func in _loops_with_func(tree):
            for stmt in loop.body + getattr(loop, "orelse", []):
                for node in _walk_no_comprehensions(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    spelling = _is_sync_call(node)
                    if spelling is None:
                        continue
                    key = (node.lineno, node.col_offset)
                    if key in seen_lines:   # nested loops: flag once
                        continue
                    seen_lines.add(key)
                    out.append(Finding(
                        "jax-host-sync", sf.rel, node.lineno,
                        f"host sync {spelling} inside a hot-path loop "
                        "body — the step loop must stay async "
                        "(dispatch, don't fetch); if this IS the "
                        "fence, say so in a suppression reason",
                        func or ""))
    return out


def _jitted_bodies(tree):
    """(body root, display name) for every function this module hands
    to jax.jit/pmap/shard_map: decorated defs, jit(f) over local defs,
    and inline jit(lambda ...)."""
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    compilers = ("jit", "pmap", "shard_map")

    def is_compiler(call_or_name) -> bool:
        if isinstance(call_or_name, ast.Call):
            name = call_name(call_or_name)
        elif isinstance(call_or_name, (ast.Name, ast.Attribute)):
            c = ast.Call(func=call_or_name, args=[], keywords=[])
            name = call_name(c)
        else:
            return False
        term = name.rsplit(".", 1)[-1]
        return term in compilers

    out = []
    seen = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                target = deco
                if (isinstance(deco, ast.Call)
                        and call_name(deco).rsplit(".", 1)[-1]
                        == "partial" and deco.args):
                    target = deco.args[0]
                if is_compiler(target):
                    if id(node) not in seen:
                        seen.add(id(node))
                        out.append((node, node.name))
        elif isinstance(node, ast.Call) and is_compiler(node):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name) and arg.id in defs:
                    d = defs[arg.id]
                    if id(d) not in seen:
                        seen.add(id(d))
                        out.append((d, d.name))
                elif isinstance(arg, ast.Lambda):
                    out.append((arg, "<lambda>"))
    return out


@rule("jax-jit-side-effect",
      "no print / journal .emit / obs.* registry calls inside "
      "functions handed to jax.jit/pmap/shard_map — trace-time "
      "side effects fire once (or per retrace), never per step "
      "(ISSUE 15)")
def jax_jit_side_effect(ctx):
    out = []
    for sf in ctx.package_files():
        tree = sf.tree
        if tree is None:
            continue
        for body, name in _jitted_bodies(tree):
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node)
                bad = None
                if cname in JIT_BANNED_CALLS:
                    bad = cname
                elif cname.startswith(JIT_BANNED_PREFIXES):
                    bad = cname
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in JIT_BANNED_ATTRS):
                    bad = f".{node.func.attr}()"
                if bad is not None:
                    out.append(Finding(
                        "jax-jit-side-effect", sf.rel, node.lineno,
                        f"Python side effect {bad} inside jitted "
                        f"function {name!r} runs at trace time, not "
                        "per step — hoist it out of the compiled "
                        "body", name))
    return out


@rule("jax-unfenced-timing",
      "a timing window around dispatched step work in a hot loop must "
      "contain a fence (block_until_ready/float/.item/np.asarray) "
      "between its timing calls — else it measures enqueue latency, "
      "not device time (the PR-7 rule, enforced; ISSUE 15)")
def jax_unfenced_timing(ctx):
    out = []
    for sf in hot_files(ctx):
        tree = sf.tree
        if tree is None:
            continue
        flagged = set()
        for loop, func in _loops_with_func(tree):
            timing, fences, dispatches = [], [], []
            for stmt in loop.body + getattr(loop, "orelse", []):
                for node in _walk_no_comprehensions(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    if _is_timing(node):
                        timing.append(node.lineno)
                    elif _is_fence(node):
                        fences.append(node.lineno)
                    elif _is_dispatch(node):
                        dispatches.append(node.lineno)
            if len(timing) < 2 or not dispatches:
                continue
            lo, hi = min(timing), max(timing)
            if any(lo <= f <= hi for f in fences):
                continue
            if any(lo <= d <= hi for d in dispatches):
                key = (sf.rel, hi)
                if key not in flagged:
                    flagged.add(key)
                    out.append(Finding(
                        "jax-unfenced-timing", sf.rel, hi,
                        "timing window around a step dispatch with no "
                        "fence between the timing calls — on an async "
                        "backend this measures enqueue, not the step; "
                        "fence at the window boundary "
                        "(jax.block_until_ready / the loss fetch)",
                        func or ""))
    return out
