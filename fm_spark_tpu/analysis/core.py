"""fmlint core: rule registry, finding model, suppressions, baseline.

The pluggable static-analysis framework (ISSUE 15). The previous
enforcement surface — six hand-rolled AST checks in one 610-line
``tools/resilience_lint.py`` — had no way to add a rule without editing
the monolith, no suppression mechanism, and no baseline, so every new
strict rule had to land green-or-never. This package splits the three
concerns the monolith fused:

- **Rules** are small functions registered with the :func:`rule`
  decorator; each receives a :class:`Context` (cached parsed sources)
  and returns :class:`Finding` objects. Rule modules
  (:mod:`.rules_obs`, :mod:`.rules_threads`, :mod:`.rules_jax`) never
  touch the driver.
- **Suppressions** are inline, per-line, and carry a REQUIRED written
  reason: ``# fmlint: disable=<rule>[,<rule>] -- <reason>``. A bare
  disable (no reason) does NOT suppress and is itself a finding
  (``suppression-hygiene``), as is a disable naming an unknown rule —
  conventions stay enforced *and explained* at the site that bends
  them.
- **The baseline** (``fmlint_baseline.json``, committed) holds
  per-(rule, file) finding COUNTS, so a strict new rule can land while
  its existing debt burns down: a run fails only when some (rule, file)
  cell exceeds its baselined count; cells below it are reported as
  burn-down (and ``--write-baseline`` shrinks the file).

Everything here is stdlib-only and uses relative imports, so
``tools/fmlint.py`` can load the package by path without importing the
jax-heavy top-level package — the lint must run from a bare checkout.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Default committed baseline location (repo root).
BASELINE_FILE = "fmlint_baseline.json"

#: The meta-rule findings of which are never suppressible (a
#: suppression that silences the suppression checker is a hole, not a
#: convention).
SUPPRESSION_RULE = "suppression-hygiene"

_DISABLE_RE = re.compile(
    r"#\s*fmlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(\s*--\s*(\S.*?))?\s*$")


# ------------------------------------------------------------------ findings

@dataclasses.dataclass(frozen=True)
class Finding:
    """One analysis finding, anchored to ``path:line``."""

    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str
    func: str = ""     # enclosing function ('' = module level)

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        ctx = self.func or "<module>"
        return f"{self.path}:{self.line} [{ctx}] {self.rule}: {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ------------------------------------------------------------------ registry

@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    doc: str
    fn: object  # Callable[[Context], Iterable[Finding]]


RULES: dict[str, Rule] = {}


def rule(rule_id: str, doc: str):
    """Register an analysis rule. ``doc`` is the one-line glossary entry
    (README's rule table renders it); the decorated function takes a
    :class:`Context` and yields/returns :class:`Finding` objects."""
    if not re.fullmatch(r"[a-z0-9]+(-[a-z0-9]+)*", rule_id):
        raise ValueError(f"rule id {rule_id!r} must be kebab-case")

    def deco(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        RULES[rule_id] = Rule(rule_id, doc.strip(), fn)
        return fn

    return deco


def all_rules() -> list[Rule]:
    return [RULES[k] for k in sorted(RULES)]


# ------------------------------------------------------------------- context

@dataclasses.dataclass(frozen=True)
class Suppression:
    rules: tuple
    reason: str | None   # None = bare disable (does not suppress)
    line: int


class SourceFile:
    """One parsed source file (lazy AST; a syntax error is recorded,
    not raised — the driver reports it as a ``parse-error`` finding)."""

    def __init__(self, repo: str, rel: str):
        self.rel = rel.replace(os.sep, "/")
        self.path = os.path.join(repo, rel)
        with open(self.path) as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self._tree = None
        self._suppressions: dict[int, Suppression] | None = None
        self.parse_error: str | None = None

    @property
    def tree(self) -> ast.AST | None:
        if self._tree is None and self.parse_error is None:
            try:
                self._tree = ast.parse(self.source, filename=self.rel)
            except SyntaxError as e:
                self.parse_error = f"line {e.lineno}: {e.msg}"
        return self._tree

    def suppressions(self) -> dict[int, Suppression]:
        # Memoized like the AST: the driver asks once per finding.
        if self._suppressions is None:
            out = {}
            for i, text in enumerate(self.lines, start=1):
                m = _DISABLE_RE.search(text)
                if not m:
                    continue
                rules = tuple(r.strip() for r in m.group(1).split(","))
                reason = m.group(3)
                out[i] = Suppression(rules, reason, i)
            self._suppressions = out
        return self._suppressions


class Context:
    """Shared state one analysis run hands every rule: the repo root
    and a parse cache. Rules pick their own scope through the file
    accessors; missing directories yield empty lists so rules behave
    on synthetic fixture repos."""

    def __init__(self, repo: str | None = None):
        self.repo = os.path.abspath(repo or REPO)
        self._cache: dict[str, SourceFile] = {}

    def file(self, rel: str) -> SourceFile | None:
        rel = rel.replace(os.sep, "/")
        sf = self._cache.get(rel)
        if sf is None:
            path = os.path.join(self.repo, rel)
            if not os.path.isfile(path):
                return None
            sf = self._cache[rel] = SourceFile(self.repo, rel)
        return sf

    def files_under(self, rel_dir: str, recursive: bool = True
                    ) -> list[SourceFile]:
        root = os.path.join(self.repo, rel_dir)
        if not os.path.isdir(root):
            return []
        out = []
        if recursive:
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fname), self.repo)
                        out.append(self.file(rel))
        else:
            for fname in sorted(os.listdir(root)):
                if fname.endswith(".py"):
                    out.append(self.file(os.path.join(rel_dir, fname)))
        return [f for f in out if f is not None]

    def package_files(self) -> list[SourceFile]:
        """Every module of the library package (``fm_spark_tpu/``)."""
        return self.files_under("fm_spark_tpu")

    def root_files(self) -> list[SourceFile]:
        """Repo-root scripts (``bench*.py`` & friends)."""
        out = []
        if os.path.isdir(self.repo):
            for fname in sorted(os.listdir(self.repo)):
                if fname.endswith(".py"):
                    out.append(self.file(fname))
        return [f for f in out if f is not None]

    def tests_blob(self) -> str:
        """All tier-1 test sources, concatenated — the coverage rules'
        string-scan anchor (plans/phases/triggers are strings, so the
        name appearing in a test file IS the exercise anchor)."""
        texts = []
        for sf in self.files_under("tests", recursive=False):
            if os.path.basename(sf.rel).startswith("test_"):
                texts.append(sf.source)
        return "\n".join(texts)

    def suppression_at(self, rel: str, line: int) -> Suppression | None:
        sf = self.file(rel)
        if sf is None:
            return None
        return sf.suppressions().get(line)


# -------------------------------------------------------------------- driver

def run_rules(ctx: Context | None = None,
              rules: list[str] | None = None
              ) -> tuple[list[Finding], list[tuple[Finding, str]]]:
    """Run ``rules`` (default: all registered) over ``ctx``.

    Returns ``(active, suppressed)`` — ``suppressed`` pairs each
    silenced finding with its written reason. A finding is suppressed
    only by a REASONED disable comment on its own line naming its rule;
    :data:`SUPPRESSION_RULE` findings are never suppressible.
    """
    ctx = ctx or Context()
    selected = ([RULES[r] for r in rules] if rules is not None
                else all_rules())
    active: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    for r in selected:
        for f in r.fn(ctx):
            sup = (None if f.rule == SUPPRESSION_RULE
                   else ctx.suppression_at(f.path, f.line))
            if (sup is not None and sup.reason
                    and f.rule in sup.rules):
                suppressed.append((f, sup.reason))
            else:
                active.append(f)
    active.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    suppressed.sort(key=lambda p: (p[0].path, p[0].line, p[0].rule))
    return active, suppressed


# ------------------------------------------------------------------ baseline

def counts_of(findings: list[Finding]) -> dict[str, dict[str, int]]:
    out: dict[str, dict[str, int]] = {}
    for f in findings:
        out.setdefault(f.rule, {})
        out[f.rule][f.path] = out[f.rule].get(f.path, 0) + 1
    return out


def load_baseline(path: str) -> dict[str, dict[str, int]]:
    """Per-(rule, file) baselined counts; a missing file is an empty
    baseline (every finding is new)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    counts = doc.get("counts", {}) if isinstance(doc, dict) else {}
    return {str(r): {str(p): int(n) for p, n in files.items()}
            for r, files in counts.items() if isinstance(files, dict)}


def write_baseline_counts(path: str,
                          counts: dict[str, dict[str, int]]) -> dict:
    """Atomically write ``counts`` as the baseline (dropping empty
    cells/rules so the file shrinks as debt burns down)."""
    counts = {r: {p: int(n) for p, n in files.items() if n}
              for r, files in counts.items()}
    counts = {r: files for r, files in counts.items() if files}
    doc = {
        "version": 1,
        "comment": ("fmlint baseline: per-(rule, file) finding counts "
                    "tolerated while they burn down. A run fails only "
                    "on counts ABOVE these; run tools/fmlint.py "
                    "--write-baseline after paying debt down."),
        "counts": counts,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return doc


def write_baseline(path: str, findings: list[Finding]) -> dict:
    return write_baseline_counts(path, counts_of(findings))


def compare_to_baseline(findings: list[Finding],
                        baseline: dict[str, dict[str, int]]) -> dict:
    """Split findings into new-vs-baselined per (rule, file) cell.

    A cell with MORE findings than its baseline count fails — all the
    cell's findings are listed (line drift makes "which exact one is
    new" unknowable from counts, and listing the cell is what a human
    needs anyway). Cells at-or-under budget are tracked; cells under
    budget are the burn-down report.
    """
    counts = counts_of(findings)
    new: list[Finding] = []
    baselined = 0
    burned: list[dict] = []
    for rule_id, files in counts.items():
        for path, n in files.items():
            allowed = baseline.get(rule_id, {}).get(path, 0)
            if n > allowed:
                new.extend(f for f in findings
                           if f.rule == rule_id and f.path == path)
            else:
                baselined += n
                if n < allowed:
                    burned.append({"rule": rule_id, "path": path,
                                   "baseline": allowed, "current": n})
    for rule_id, files in baseline.items():
        for path, allowed in files.items():
            if allowed and counts.get(rule_id, {}).get(path, 0) == 0:
                burned.append({"rule": rule_id, "path": path,
                               "baseline": allowed, "current": 0})
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    burned.sort(key=lambda b: (b["rule"], b["path"]))
    return {"new": new, "baselined": baselined, "burned_down": burned}


# -------------------------------------------------------------------- report

def analyze(repo: str | None = None, baseline_path: str | None = None,
            rules: list[str] | None = None,
            run_id: str | None = None) -> dict:
    """One full analysis run → the JSON-ready report dict.

    ``ok`` is the gate: True iff no (rule, file) cell exceeds its
    baselined count. The report carries everything a renderer
    (``run_doctor``'s Static-analysis section, CI logs) needs: the
    rule glossary, per-cell counts, new findings, reasoned
    suppressions, and the burn-down ledger.
    """
    ctx = Context(repo)
    if baseline_path is None:
        baseline_path = os.path.join(ctx.repo, BASELINE_FILE)
    findings, suppressed = run_rules(ctx, rules=rules)
    baseline = load_baseline(baseline_path)
    cmp = compare_to_baseline(findings, baseline)
    return {
        "version": 1,
        "tool": "fmlint",
        "run_id": run_id,
        "repo": ctx.repo,
        "rules": {r.id: r.doc for r in all_rules()},
        "counts": counts_of(findings),
        "total_findings": len(findings),
        "new": [f.to_dict() for f in cmp["new"]],
        "baselined_total": cmp["baselined"],
        "burned_down": cmp["burned_down"],
        "baseline_path": baseline_path,
        "suppressed": [dict(f.to_dict(), reason=reason)
                       for f, reason in suppressed],
        "ok": not cmp["new"],
    }


def write_report(report: dict, out_dir: str,
                 filename: str = "fmlint.json") -> str | None:
    """Atomically write the report into ``out_dir`` (an
    ``artifacts/obs/<run_id>/`` run directory by convention) so
    ``run_doctor``/``obs_report`` render analysis regressions next to
    perf ones. Best-effort: a lint must never die on report IO."""
    try:
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, filename)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path
    except OSError:
        return None


# ---------------------------------------------------------------- ast helpers

def call_name(node: ast.Call) -> str:
    """Dotted name of the called object, best-effort ('' if dynamic)."""
    parts = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


def walk_with_func(tree: ast.AST):
    """Yield ``(node, enclosing_function_name)`` for every node —
    the shared walk rules use to report the enclosing def."""
    stack: list[tuple[ast.AST, str | None]] = [(tree, None)]
    while stack:
        node, func = stack.pop()
        yield node, func
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        for child in ast.iter_child_nodes(node):
            stack.append((child, func))


def parse_errors(files) -> list[Finding]:
    """``parse-error`` findings for unparseable sources (the driver
    must report a broken file, not silently skip it)."""
    out = []
    for sf in files:
        sf.tree  # force the parse
        if sf.parse_error:
            out.append(Finding("parse-error", sf.rel, 1,
                               f"unparseable source: {sf.parse_error}"))
    return out
