"""fmlint thread-safety pass: lock discipline + thread lifecycle.

The repo runs five long-lived thread populations (supervisor probes,
prefetcher producers, the serve coalescer, the reload follower,
watchdog monitors, the metrics HTTP server) against shared mutable
state, and until ISSUE 15 the only enforcement was code review. Two
rules:

``thread-lock-discipline``
    For every class that starts a ``threading.Thread`` on one of its
    own bound methods (``target=self._run``) — plus the explicitly
    listed :data:`EXTRA_SHARED_CLASSES`, objects handed across threads
    without spawning one — infer the **shared mutable attributes**:
    ``self.X`` written outside ``__init__`` and touched from both the
    thread domain (methods reachable from the thread target via
    ``self.m()`` calls) and the caller domain (everything else). Flag
    every unlocked write to such an attribute, and every unlocked read
    whose domain is disjoint from all writers' domains (a same-domain
    read races only with itself). "Locked" is lexical: inside ``with
    self.<lock>`` where ``<lock>`` was assigned a ``threading.Lock/
    RLock/Condition``, or inside a method only ever called under one
    (``_foo_locked`` idiom — propagated to a fixpoint). Attributes
    that ARE locks, or are built once in ``__init__`` from an
    inherently thread-safe type (``queue.Queue``, ``threading.Event``,
    …), are exempt. A spawning class with shared mutable state and NO
    lock at all gets one finding per attribute.

    Deliberately-lock-free designs (the serve engine's atomic
    generation reference) are exactly what reasoned inline
    suppressions are for — the reason documents the protocol.

``thread-lifecycle``
    Every ``threading.Thread(...)`` (and ``threading.Timer``) must be
    ``daemon=True`` or have a ``join`` on its shutdown path (same
    class, or same function for local threads) — a forgotten
    non-daemon thread turns clean process exit into a hang.

Known blind spot, by design: attributes read only by OTHER objects
(``follower.reloads`` from a test) have no in-class read site, so
cross-object races are out of scope — the pass trades that recall for
running on plain ASTs with near-zero false positives.
"""

from __future__ import annotations

import ast

from .core import Finding, call_name, rule

#: Lock-like factory terminals: an attr assigned one of these is a
#: lock (its ``with self.X`` blocks dominate) and itself exempt.
LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})

#: Inherently thread-safe containers/primitives: an attr built ONCE in
#: __init__ from one of these is exempt (its methods synchronize).
SAFE_FACTORIES = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "Event", "Semaphore", "BoundedSemaphore", "Barrier", "deque",
    "local",
}) | LOCK_FACTORIES

#: (file rel-path, class name) pairs analyzed even though they spawn no
#: thread themselves — objects the runtime hands across threads: the
#: metrics instruments (every worker thread adds), the flight recorder
#: (producer threads record, signal handlers dump).
EXTRA_SHARED_CLASSES = (
    ("fm_spark_tpu/obs/metrics.py", "Counter"),
    ("fm_spark_tpu/obs/metrics.py", "Gauge"),
    ("fm_spark_tpu/obs/metrics.py", "Histogram"),
    ("fm_spark_tpu/obs/metrics.py", "MetricsRegistry"),
    ("fm_spark_tpu/obs/flight.py", "FlightRecorder"),
)


class _Access:
    __slots__ = ("method", "line", "write", "locked")

    def __init__(self, method, line, write, locked):
        self.method = method
        self.line = line
        self.write = write
        self.locked = locked


class _ClassInfo:
    """One class's thread-relevant facts, collected in a single walk."""

    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.methods = {n.name: n for n in node.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        self.lock_attrs: set[str] = set()
        self.safe_attrs: set[str] = set()
        self._assign_methods: dict[str, set] = {}
        self.calls: dict[str, set] = {m: set() for m in self.methods}
        # method -> [(caller, locked?)] for every in-class call site
        self.call_sites: dict[str, list] = {}
        self.accesses: dict[str, list] = {}     # attr -> [_Access]
        self.spawn_targets: set[str] = set()
        self.thread_calls: list = []  # (line, method, daemonized)

    def analyze(self):
        # Pass 1: lock/safe attrs (need them before judging "locked").
        for mname, mnode in self.methods.items():
            for node in ast.walk(mnode):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    self._assign_methods.setdefault(
                        attr, set()).add(mname)
                    if isinstance(node.value, ast.Call):
                        term = call_name(node.value).rsplit(".", 1)[-1]
                        if term in LOCK_FACTORIES:
                            self.lock_attrs.add(attr)
                        elif (term in SAFE_FACTORIES
                              and mname == "__init__"):
                            self.safe_attrs.add(attr)
        # An attr reassigned outside __init__ is not a stable safe
        # object; one reassigned to a non-factory loses lock status
        # conservatively only if never a lock (keep lock if ever one).
        self.safe_attrs = {
            a for a in self.safe_attrs
            if self._assign_methods.get(a) == {"__init__"}
        }
        # Pass 2: accesses / calls / spawns, with a lexical lock stack.
        for mname, mnode in self.methods.items():
            self._walk_method(mname, mnode)

    def _walk_method(self, mname, mnode):
        def locked_with(node):
            if not isinstance(node, ast.With):
                return False
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr in self.lock_attrs:
                    return True
            return False

        def visit(node, locked):
            if locked_with(node):
                locked = True
            if isinstance(node, ast.Call):
                name = call_name(node)
                term = name.rsplit(".", 1)[-1]
                if term in ("Thread", "Timer"):
                    target_attr = None
                    daemonized = any(
                        kw.arg == "daemon"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in node.keywords)
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target_attr = _self_attr(kw.value)
                    if term == "Thread" and target_attr in self.methods:
                        self.spawn_targets.add(target_attr)
                    self.thread_calls.append(
                        (node.lineno, mname, daemonized))
                mcall = _self_method_call(node)
                if mcall in self.methods:
                    self.calls[mname].add(mcall)
                    self.call_sites.setdefault(mcall, []).append(
                        (mname, locked))
            attr_hit = _self_attr(node)
            if attr_hit is not None:
                write = isinstance(getattr(node, "ctx", None),
                                   (ast.Store, ast.Del))
                self.accesses.setdefault(attr_hit, []).append(
                    _Access(mname, node.lineno, write, locked))
            # Container mutation through the attr counts as a write:
            # self.x[k] = v  /  del self.x[k]
            if (isinstance(node, ast.Subscript)
                    and isinstance(getattr(node, "ctx", None),
                                   (ast.Store, ast.Del))):
                attr = _self_attr(node.value)
                if attr is not None:
                    self.accesses.setdefault(attr, []).append(
                        _Access(mname, node.lineno, True, locked))
            for child in ast.iter_child_nodes(node):
                visit(child, locked)

        for stmt in mnode.body:
            visit(stmt, False)

    # ------------------------------------------------------------ domains

    def reach(self, roots) -> set:
        seen = set()
        stack = [r for r in roots if r in self.methods]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(self.calls.get(m, ()))
        return seen

    def lock_dominated_methods(self) -> set:
        """Methods every in-class call site of which holds a lock —
        their bodies count as locked (the ``_foo_locked`` idiom),
        iterated to a fixpoint so a dominated caller dominates its
        callees."""
        dominated: set = set()
        changed = True
        while changed:
            changed = False
            for m in self.methods:
                if m in dominated:
                    continue
                sites = self.call_sites.get(m)
                if not sites:
                    continue
                if all(locked or caller in dominated
                       for caller, locked in sites):
                    dominated.add(m)
                    changed = True
        return dominated


def _self_attr(node) -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _self_method_call(node: ast.Call) -> str | None:
    return _self_attr(node.func)


def _classes(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node


def _is_thread_join(node: ast.Call) -> bool:
    """A ``.join(...)`` call that plausibly joins a thread — i.e. NOT
    ``os.path.join`` / ``"sep".join`` / ``sep.join``, which would
    silently exempt whole modules from the lifecycle rule."""
    recv = node.func.value
    if isinstance(recv, ast.Constant):
        return False                       # "".join(...)
    dotted = call_name(ast.Call(func=recv, args=[], keywords=[])) \
        if isinstance(recv, (ast.Name, ast.Attribute)) else ""
    last = dotted.rsplit(".", 1)[-1].lower()
    if "path" in dotted.lower() or last in ("sep", "linesep", "os"):
        return False                       # os.path.join & kin
    return True


@rule("thread-lock-discipline",
      "shared mutable attributes of thread-spawning (or listed "
      "cross-thread) classes must be accessed under the class's lock "
      "— lock-free protocols need a reasoned suppression (ISSUE 15)")
def thread_lock_discipline(ctx):
    out = []
    extra = {(rel, cls) for rel, cls in EXTRA_SHARED_CLASSES}
    for sf in ctx.package_files():
        tree = sf.tree
        if tree is None:
            continue
        for cnode in _classes(tree):
            info = _ClassInfo(cnode)
            info.analyze()
            spawning = bool(info.spawn_targets)
            listed = (sf.rel, cnode.name) in extra
            if not spawning and not listed:
                continue
            dominated = info.lock_dominated_methods()
            if spawning:
                thread_reach = info.reach(info.spawn_targets)
                caller_reach = info.reach(
                    m for m in info.methods
                    if m not in info.spawn_targets and m != "__init__")
            else:
                # Handed-across-threads class: any two methods can run
                # concurrently — one shared domain on both sides.
                thread_reach = caller_reach = set(info.methods)

            def domains(method):
                d = set()
                if method in thread_reach:
                    d.add("thread")
                if method in caller_reach:
                    d.add("caller")
                return d

            for attr, accs in sorted(info.accesses.items()):
                if (attr in info.lock_attrs
                        or attr in info.safe_attrs
                        or attr.startswith("__")):
                    continue
                accs = [a for a in accs if a.method != "__init__"]
                if not accs:
                    continue
                writes = [a for a in accs if a.write]
                if not writes:
                    continue
                touched = set()
                for a in accs:
                    touched |= domains(a.method)
                if not ("thread" in touched and "caller" in touched):
                    continue
                if not info.lock_attrs:
                    w = writes[0]
                    out.append(Finding(
                        "thread-lock-discipline", sf.rel, w.line,
                        f"class {cnode.name} starts a thread and "
                        f"mutates self.{attr} across thread domains "
                        "with no lock attribute at all — add a "
                        "threading.Lock or document the lock-free "
                        "protocol with a reasoned suppression",
                        w.method))
                    continue
                write_domains = set()
                for w in writes:
                    write_domains |= domains(w.method)
                for a in accs:
                    if a.locked or a.method in dominated:
                        continue
                    if a.write:
                        out.append(Finding(
                            "thread-lock-discipline", sf.rel, a.line,
                            f"write to shared attribute self.{attr} "
                            f"of {cnode.name} (touched from thread "
                            "and caller domains) outside `with "
                            f"self.{sorted(info.lock_attrs)[0]}`",
                            a.method))
                    elif not (domains(a.method) & write_domains):
                        out.append(Finding(
                            "thread-lock-discipline", sf.rel, a.line,
                            f"read of self.{attr} in {cnode.name}."
                            f"{a.method} races writes from the other "
                            "thread domain and holds no lock",
                            a.method))
    return out


@rule("thread-lifecycle",
      "every thread the package starts is daemon=True or joined on "
      "the shutdown path — a forgotten non-daemon thread turns clean "
      "exit into a hang (ISSUE 15)")
def thread_lifecycle(ctx):
    out = []
    for sf in ctx.package_files():
        tree = sf.tree
        if tree is None:
            continue
        # Scope = enclosing class if any, else enclosing function,
        # else module: a join anywhere in the scope clears its threads.
        def scan(scope_node, scope_name):
            spawns = []
            joins = False
            daemon_assign = False

            def visit(node, func):
                nonlocal joins, daemon_assign
                if isinstance(node, ast.ClassDef) and node is not scope_node:
                    scan(node, node.name)
                    return
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    func = node.name
                if isinstance(node, ast.Call):
                    term = call_name(node).rsplit(".", 1)[-1]
                    if term in ("Thread", "Timer"):
                        daemonized = any(
                            kw.arg == "daemon"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is True
                            for kw in node.keywords)
                        spawns.append((node.lineno, func, daemonized,
                                       term))
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr == "join"
                          and _is_thread_join(node)):
                        joins = True
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and tgt.attr == "daemon"
                                and isinstance(node.value, ast.Constant)
                                and node.value.value is True):
                            daemon_assign = True
                for child in ast.iter_child_nodes(node):
                    visit(child, func)

            for child in ast.iter_child_nodes(scope_node):
                visit(child, None)
            for line, func, daemonized, term in spawns:
                if daemonized or daemon_assign or joins:
                    continue
                out.append(Finding(
                    "thread-lifecycle", sf.rel, line,
                    f"{term} started without daemon=True and no join "
                    f"anywhere in {scope_name or 'the module'} — a "
                    "non-daemon thread with no shutdown join hangs "
                    "clean process exit", func or ""))

        scan(tree, None)
    return out
