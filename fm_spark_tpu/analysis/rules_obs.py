"""fmlint rules migrated from the ``tools/resilience_lint.py`` monolith.

Every rule the monolith hand-rolled (ISSUEs 4–14) now registers through
the :func:`fm_spark_tpu.analysis.core.rule` decorator; the monolith
survives only as a thin compatibility shim over this registry. The
rules (ids are what ``# fmlint: disable=`` names):

``eventlog-only``        strict scope: no print/json.dump/sys.std* in
                         resilience/, serve/, the ingest stream modules
``bare-print``           library-wide: no bare ``print()`` outside CLI
``pallas-fallback``      kernel modules raise PallasUnavailable, never
                         assert / bare ValueError
``wallclock-duration``   durations use perf_counter/monotonic, never
                         ``time.time()`` in a subtraction
``leg-provenance``       bench.py's leg_record carries run_id+fingerprint
``registry-coverage``    every fault point / watchdog phase / introspect
                         trigger appears in at least one tier-1 test
``trace-propagation``    outbound HTTP requests from serve/ carry the
                         X-FM-Trace context header (ISSUE 18)
``fleet-transport-discipline`` serve/ opens replica connections only
                         through the netfault-aware transport, never
                         raw http.client/socket (ISSUE 19)
``durable-write-discipline`` checkpoint.py / obs/ / embed/ write
                         durable artifacts only through utils/durable,
                         never raw open-for-write or os.rename
                         (ISSUE 20)
``parse-error``          every scanned source must parse

Plus the framework's own meta-rule, ``suppression-hygiene``: a
``# fmlint: disable=`` comment with no ``-- reason`` does not suppress
and is itself a finding, as is one naming a rule that does not exist.
"""

from __future__ import annotations

import ast
import os

from .core import Finding, call_name, parse_errors, rule, walk_with_func

# --------------------------------------------------------------- scope config

#: The strict EventLog-only surface: resilience/ and serve/ entirely,
#: plus the ingest-stream modules whose quarantine/abort transitions
#: carry the same machine-readability contract (ISSUEs 5/6/13).
STRICT_DIRS = ("fm_spark_tpu/resilience", "fm_spark_tpu/serve")
STRICT_EXTRA_FILES = (
    "fm_spark_tpu/data/stream.py",
    "fm_spark_tpu/data/native_stream.py",
    "fm_spark_tpu/native/__init__.py",
    "fm_spark_tpu/online.py",
)

#: (basename, enclosing function) pairs exempt from the JSON-write rule
#: — faults.py::_next_count persists cross-process occurrence COUNTERS,
#: bookkeeping the injection harness needs before a journal can exist.
EVENTLOG_ALLOWLIST = {
    ("faults.py", "_next_count"),
    # HTTP wire-format seams (ISSUE 17): request/response bodies and
    # the replica port file are protocol payloads, not journal events
    # — each module funnels its json.dumps through exactly one helper.
    ("frontdoor.py", "_json_body"),
    ("fleet.py", "_json_body"),
}

#: Top-level library modules whose stdout IS their interface.
CLI_EXEMPT = frozenset({"cli.py", "cli_levers.py", "__main__.py"})

KERNEL_DIR = "fm_spark_tpu/ops"
KERNEL_PREFIX = "pallas_"

LEG_RECORD_REQUIRED_KEYS = ("run_id", "fingerprint")

#: (registry kind, module holding it, literal name) — the coverage
#: rule's anchors: a registered point/phase/trigger no tier-1 test
#: names is a recovery/capture path that can rot silently.
COVERAGE_REGISTRIES = (
    ("fault point", "fm_spark_tpu/resilience/faults.py", "KNOWN_POINTS"),
    ("watchdog phase", "fm_spark_tpu/resilience/watchdog.py",
     "KNOWN_PHASES"),
    ("introspection trigger", "fm_spark_tpu/obs/introspect.py",
     "TRIGGERS"),
)


def _strict_files(ctx):
    out = []
    for d in STRICT_DIRS:
        out.extend(ctx.files_under(d, recursive=False))
    for rel in STRICT_EXTRA_FILES:
        sf = ctx.file(rel)
        if sf is not None:
            out.append(sf)
    return out


# --------------------------------------------------------------------- rules

@rule("parse-error",
      "every scanned source file must parse — a broken file is a "
      "finding, never a silently shrunk scan")
def parse_error_rule(ctx):
    return parse_errors(ctx.package_files() + ctx.root_files())


@rule("eventlog-only",
      "resilience/serve/ingest state transitions go through "
      "utils/logging.EventLog — no print, no ad-hoc json.dump, no "
      "sys.stdout/stderr writes (ISSUE 4/5/12)")
def eventlog_only(ctx):
    out = []
    for sf in _strict_files(ctx):
        tree = sf.tree
        if tree is None:
            continue
        base = os.path.basename(sf.rel)
        for node, func in walk_with_func(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name == "print":
                out.append(Finding(
                    "eventlog-only", sf.rel, node.lineno,
                    "bare print() — emit a journal event "
                    "(utils/logging.EventLog) instead", func or ""))
            elif name in ("json.dump", "json.dumps"):
                if (base, func) not in EVENTLOG_ALLOWLIST:
                    out.append(Finding(
                        "eventlog-only", sf.rel, node.lineno,
                        f"ad-hoc JSON write ({name}) — state "
                        "transitions go through EventLog, not "
                        "hand-rolled JSON", func or ""))
            elif name in ("sys.stdout.write", "sys.stderr.write"):
                out.append(Finding(
                    "eventlog-only", sf.rel, node.lineno,
                    f"direct {name} — emit a journal event instead",
                    func or ""))
    return out


@rule("bare-print",
      "no bare print() anywhere in library code — numbers go to the "
      "metrics registry, narrative to EventLog/spans; CLI modules "
      "exempt (ISSUE 7)")
def bare_print(ctx):
    out = []
    for sf in ctx.package_files():
        base = os.path.basename(sf.rel)
        if (base in CLI_EXEMPT
                and os.path.dirname(sf.rel) == "fm_spark_tpu"):
            continue
        tree = sf.tree
        if tree is None:
            continue
        for node, func in walk_with_func(tree):
            if (isinstance(node, ast.Call)
                    and call_name(node) == "print"
                    and not any(kw.arg == "file"
                                for kw in node.keywords)):
                out.append(Finding(
                    "bare-print", sf.rel, node.lineno,
                    "bare print() in library code — use MetricsLogger/"
                    "EventLog/obs APIs (fm_spark_tpu.obs) instead",
                    func or ""))
    return out


@rule("pallas-fallback",
      "Pallas kernel modules raise ops.PallasUnavailable — never "
      "assert, never bare ValueError — so fused_embed='auto' can "
      "degrade to the XLA path (ISSUE 8)")
def pallas_fallback(ctx):
    out = []
    for sf in ctx.files_under(KERNEL_DIR, recursive=False):
        if not os.path.basename(sf.rel).startswith(KERNEL_PREFIX):
            continue
        tree = sf.tree
        if tree is None:
            continue
        for node, func in walk_with_func(tree):
            if isinstance(node, ast.Assert):
                out.append(Finding(
                    "pallas-fallback", sf.rel, node.lineno,
                    "assert in a Pallas kernel module — raise "
                    "ops.PallasUnavailable so fused_embed='auto' can "
                    "degrade to the XLA path instead of dying",
                    func or ""))
            elif (isinstance(node, ast.Raise)
                  and isinstance(node.exc, ast.Call)):
                f = node.exc.func
                name = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else "")
                if name == "ValueError":
                    out.append(Finding(
                        "pallas-fallback", sf.rel, node.lineno,
                        "bare ValueError in a Pallas kernel module — "
                        "raise ops.PallasUnavailable (the structured "
                        "fallback signal fused_embed='auto' pins)",
                        func or ""))
    return out


def _time_aliases(tree: ast.AST) -> tuple[set, set]:
    """The file's actual names for the time module and ``time.time``
    itself — ``import time as t`` / ``from time import time as now``
    must not evade the duration rule."""
    mods = {"time", "_time"}
    funcs = {"time"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mods.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    funcs.add(a.asname or a.name)
    return mods, funcs


def _is_wallclock_call(node, mods, funcs) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in funcs
    if isinstance(f, ast.Attribute) and f.attr == "time":
        return isinstance(f.value, ast.Name) and f.value.id in mods
    return False


@rule("wallclock-duration",
      "time.time() inside a subtraction is a wall-clock DURATION — "
      "measured intervals go through time.perf_counter()/"
      "time.monotonic(); wall-clock is for timestamps (ISSUE 9)")
def wallclock_duration(ctx):
    out = []
    for sf in ctx.package_files():
        tree = sf.tree
        if tree is None:
            continue
        mods, funcs = _time_aliases(tree)
        for node, func in walk_with_func(tree):
            hit = None
            if (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and (_is_wallclock_call(node.left, mods, funcs)
                         or _is_wallclock_call(node.right, mods,
                                               funcs))):
                hit = node
            elif (isinstance(node, ast.AugAssign)
                  and isinstance(node.op, ast.Sub)
                  and _is_wallclock_call(node.value, mods, funcs)):
                hit = node
            if hit is not None:
                out.append(Finding(
                    "wallclock-duration", sf.rel, hit.lineno,
                    "time.time() in a subtraction — durations go "
                    "through time.perf_counter()/time.monotonic(), "
                    "wall-clock is for timestamps only", func or ""))
    return out


@rule("leg-provenance",
      "bench.py's per-leg sweep record must carry run_id + fingerprint "
      "— a leg untraceable to its run/cohort is the hand-adjudicated "
      "number the perf ledger retires (ISSUE 9)")
def leg_provenance(ctx):
    sf = ctx.file("bench.py")
    if sf is None or sf.tree is None:
        return [Finding(
            "leg-provenance", "bench.py", 1,
            "bench.py missing or unparseable — the sweep's per-leg "
            "provenance contract has no anchor to lint")]
    out = []
    found = False
    for node, func in walk_with_func(sf.tree):
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "leg_record"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            continue
        found = True
        keys = {k.value for k in node.value.keys
                if isinstance(k, ast.Constant)}
        missing = [k for k in LEG_RECORD_REQUIRED_KEYS if k not in keys]
        if missing:
            out.append(Finding(
                "leg-provenance", sf.rel, node.lineno,
                f"leg_record literal missing provenance key(s) "
                f"{missing} — every bench leg record must carry "
                "run_id + fingerprint", func or ""))
    if not found:
        out.append(Finding(
            "leg-provenance", sf.rel, 1,
            "no leg_record dict literal found — the sweep's per-leg "
            "provenance contract has no anchor to lint"))
    return out


def _literal_entries(sf, literal: str) -> tuple[list[str], int] | None:
    """(string entries, line) of a module-level tuple/list assignment
    named ``literal``, AST-extracted — no package import, so the lint
    runs from a bare checkout."""
    if sf is None or sf.tree is None:
        return None
    for node in ast.walk(sf.tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == literal
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return ([e.value for e in node.value.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)], node.lineno)
    return None


@rule("registry-coverage",
      "every fault point (KNOWN_POINTS), watchdog phase "
      "(KNOWN_PHASES), and introspection trigger (TRIGGERS) must "
      "appear in at least one tier-1 test — an unexercised recovery/"
      "capture path rots silently (ISSUE 10/12/14)")
def registry_coverage(ctx):
    out = []
    blob = ctx.tests_blob()
    for kind, rel, literal in COVERAGE_REGISTRIES:
        sf = ctx.file(rel)
        got = _literal_entries(sf, literal)
        if got is None or not got[0]:
            out.append(Finding(
                "registry-coverage", rel, 1,
                f"no {literal} literal found — the {kind} registry "
                "has no anchor to check coverage against"))
            continue
        entries, line = got
        for entry in entries:
            if entry not in blob:
                out.append(Finding(
                    "registry-coverage", rel, line,
                    f"{kind} {entry!r} ({literal}) is exercised by no "
                    "test under tests/ — a new entry must ship with "
                    "at least one tier-1 test that names it"))
    return out


#: The distributed-trace context header (ISSUE 18) and the
#: ``http.client`` methods that put a request on the wire. The rule is
#: scoped to ``fm_spark_tpu/serve/`` — the only package that makes
#: process-to-process HTTP calls on the request path.
TRACE_HEADER_NAME = "X-FM-Trace"
TRACE_CLIENT_METHODS = ("request", "putrequest")


@rule("trace-propagation",
      "every outbound HTTP request from fm_spark_tpu/serve/ "
      "(http.client .request()/.putrequest()) must carry the "
      "X-FM-Trace context header — an unpropagated hop tears the "
      "distributed trace in half (ISSUE 18)")
def trace_propagation(ctx):
    out = []
    for sf in ctx.files_under("fm_spark_tpu/serve", recursive=False):
        tree = sf.tree
        if tree is None:
            continue
        # Per innermost enclosing function: does it reference the
        # header (the literal, or obs.TRACE_HEADER by name)? Collect
        # first, judge after — walk order is not source order.
        refs: set = set()
        calls: list = []
        for node, func in walk_with_func(tree):
            key = func or ""
            if (isinstance(node, ast.Constant)
                    and node.value == TRACE_HEADER_NAME):
                refs.add(key)
            elif ((isinstance(node, ast.Name)
                   and node.id == "TRACE_HEADER")
                  or (isinstance(node, ast.Attribute)
                      and node.attr == "TRACE_HEADER")):
                refs.add(key)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in TRACE_CLIENT_METHODS):
                calls.append((node, key))
        for node, key in calls:
            if key not in refs:
                out.append(Finding(
                    "trace-propagation", sf.rel, node.lineno,
                    f".{node.func.attr}() puts an HTTP request on "
                    f"the wire with no {TRACE_HEADER_NAME} reference "
                    "in the enclosing function — forward the trace "
                    "context (obs.TRACE_HEADER) so the hop stitches, "
                    "or suppress with the reason this call sits on a "
                    "trust boundary", key))
    return out


#: Raw-transport constructors banned in ``fm_spark_tpu/serve/``
#: (ISSUE 19): a connection opened outside the netfault-aware seam
#: (resilience/netfaults.FaultyHTTPConnection via ConnectionPool /
#: ``_http_json``) is a transport path no partition schedule can
#: reach — chaos coverage silently shrinks. The loadgen's client-side
#: connection sits OUTSIDE the fleet's transport boundary and carries
#: a reasoned suppression.
TRANSPORT_BANNED = (
    "http.client.HTTPConnection", "HTTPConnection",
    "http.client.HTTPSConnection", "HTTPSConnection",
    "socket.create_connection", "socket.socket",
)


@rule("fleet-transport-discipline",
      "fm_spark_tpu/serve/ must open replica connections through the "
      "netfault-aware transport (netfaults.FaultyHTTPConnection via "
      "ConnectionPool/_http_json) — raw http.client/socket connects "
      "bypass the fault plane, so partition chaos cannot reach them "
      "(ISSUE 19)")
def fleet_transport_discipline(ctx):
    out = []
    for sf in ctx.files_under("fm_spark_tpu/serve", recursive=False):
        tree = sf.tree
        if tree is None:
            continue
        for node, func in walk_with_func(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in TRANSPORT_BANNED:
                out.append(Finding(
                    "fleet-transport-discipline", sf.rel, node.lineno,
                    f"raw {name}() — the netfault plane cannot "
                    "intercept this connection; route it through "
                    "ConnectionPool/_http_json (or suppress with the "
                    "reason this path sits outside the fleet's "
                    "transport boundary)", func or ""))
    return out


#: The durable-artifact surface (ISSUE 20): every byte these trees
#: promise to keep must be written through the injectable seam
#: (:mod:`fm_spark_tpu.utils.durable`) — a raw ``open(.., "w")`` or
#: ``os.rename``/``os.replace`` is a write no disk-fault schedule can
#: reach, so crash-consistency coverage silently shrinks. Appends
#: (mode ``"a"``) are allowed raw at open time: the seam wraps the
#: per-line write (``durable.append_line``), not the handle.
DURABLE_DIRS = ("fm_spark_tpu/obs", "fm_spark_tpu/embed")
DURABLE_EXTRA_FILES = ("fm_spark_tpu/checkpoint.py",)
DURABLE_BANNED_RENAMES = ("os.rename", "os.replace")


def _durable_files(ctx):
    out = []
    for d in DURABLE_DIRS:
        out.extend(ctx.files_under(d, recursive=True))
    for rel in DURABLE_EXTRA_FILES:
        sf = ctx.file(rel)
        if sf is not None:
            out.append(sf)
    return out


def _open_write_mode(node: ast.Call) -> "str | None":
    """The literal mode of an ``open()`` call iff it opens for
    (over)write — ``w``/``wb``/``w+``/``x`` variants. Appends and
    reads return None; so does a non-literal mode (can't judge it
    statically, and every in-scope call site uses literals)."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if (isinstance(mode, ast.Constant) and isinstance(mode.value, str)
            and mode.value.lstrip("br").startswith(("w", "x"))):
        return mode.value
    return None


@rule("durable-write-discipline",
      "checkpoint.py, fm_spark_tpu/obs/, and fm_spark_tpu/embed/ "
      "write durable artifacts only through utils/durable "
      "(atomic_write_*/append_line*) — raw open(.., 'w') and "
      "os.rename/os.replace bypass the io-fault seam, so no disk "
      "schedule can reach them (ISSUE 20)")
def durable_write_discipline(ctx):
    out = []
    for sf in _durable_files(ctx):
        tree = sf.tree
        if tree is None:
            continue
        for node, func in walk_with_func(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name == "open":
                mode = _open_write_mode(node)
                if mode is not None:
                    out.append(Finding(
                        "durable-write-discipline", sf.rel,
                        node.lineno,
                        f"raw open(.., {mode!r}) on the durable "
                        "surface — write through utils/durable "
                        "(atomic_write_bytes/text/json) so io-fault "
                        "schedules can reach it, or suppress with "
                        "the reason these bytes are not a durability "
                        "promise", func or ""))
            elif name in DURABLE_BANNED_RENAMES:
                out.append(Finding(
                    "durable-write-discipline", sf.rel, node.lineno,
                    f"raw {name}() on the durable surface — the "
                    "atomic publish belongs to utils/durable."
                    "atomic_write_* (injectable at io_rename), or "
                    "suppress with the reason this rename is not a "
                    "durable publish", func or ""))
    return out


@rule("suppression-hygiene",
      "every `# fmlint: disable=<rule>` needs `-- <reason>` and must "
      "name a registered rule — bare or misspelled disables are "
      "findings, never silencers (ISSUE 15)")
def suppression_hygiene(ctx):
    from .core import RULES

    out = []
    for sf in ctx.package_files() + ctx.root_files():
        for line, sup in sf.suppressions().items():
            if sup.reason is None:
                out.append(Finding(
                    "suppression-hygiene", sf.rel, line,
                    "bare suppression: `# fmlint: disable=` without "
                    "`-- <reason>` suppresses nothing — state why the "
                    "convention bends here"))
            for rid in sup.rules:
                if rid not in RULES:
                    out.append(Finding(
                        "suppression-hygiene", sf.rel, line,
                        f"suppression names unknown rule {rid!r} — "
                        "check the rule glossary (README 'Static "
                        "analysis')"))
    return out
