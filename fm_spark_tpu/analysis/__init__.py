"""fmlint — the repo's pluggable static-analysis framework (ISSUE 15).

Importing this package registers every shipped rule; see
:mod:`fm_spark_tpu.analysis.core` for the framework (rule registry,
findings, inline suppressions with required reasons, the committed
baseline, JSON reports into ``artifacts/obs/<run_id>/``),
:mod:`.rules_obs` for the rules migrated from ``tools/
resilience_lint.py``, :mod:`.rules_threads` for the thread-safety /
lock-discipline pass, and :mod:`.rules_jax` for the JAX host-sync /
tracer-hazard pass. ``tools/fmlint.py`` is the CLI; the old
``tools/resilience_lint.py`` survives as a compatibility shim.

Stdlib-only on purpose: the CLI loads this package by file path so a
bare checkout (no jax) can lint itself.
"""

from .core import (  # noqa: F401
    BASELINE_FILE,
    Context,
    Finding,
    RULES,
    Rule,
    SUPPRESSION_RULE,
    all_rules,
    analyze,
    compare_to_baseline,
    counts_of,
    load_baseline,
    rule,
    run_rules,
    write_baseline,
    write_baseline_counts,
    write_report,
)
from . import rules_jax, rules_obs, rules_threads  # noqa: F401

__all__ = [
    "BASELINE_FILE",
    "Context",
    "Finding",
    "RULES",
    "Rule",
    "SUPPRESSION_RULE",
    "all_rules",
    "analyze",
    "compare_to_baseline",
    "counts_of",
    "load_baseline",
    "rule",
    "run_rules",
    "write_baseline",
    "write_baseline_counts",
    "write_report",
]
