"""Full-batch L-BFGS training — the reference's second optimizer.

The lineage ships an ``FMWithLBFGS`` next to ``FMWithSGD`` (SURVEY.md §2
row 5, §0.2 checklist), built on MLlib's ``LBFGS`` optimizer: full-batch
gradients, ``numCorrections`` history pairs, ``convergenceTol`` stopping.
Rebuild: ``optax.lbfgs`` (memory_size = numCorrections, zoom linesearch)
with the whole optimization as ONE compiled ``lax.while_loop`` program —
no per-iteration host round-trip, the TPU-native answer to MLlib's
driver-mediated aggregate-per-iteration loop (SURVEY.md §3.1).

L2 regularization enters the *objective* (MLlib's squaredL2Updater-style
``loss + (r/2)·‖θ‖²``, with the (r0, r1, r2) triple applied per group),
not the gradient post-hoc — L-BFGS needs objective and gradient consistent
for its linesearch and curvature pairs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from fm_spark_tpu.ops import losses as losses_lib
from fm_spark_tpu.train import TrainConfig


def make_objective(spec, config: TrainConfig, ids, vals, labels, weights):
    """Full-batch regularized objective ``f(params) -> scalar``."""
    per_example_loss = losses_lib.loss_fn(spec.loss)
    wsum = jnp.maximum(jnp.sum(weights), 1.0)
    reg_of = {"w0": config.reg_bias, "w": config.reg_linear,
              "v": config.reg_factors, "mlp": config.reg_factors,
              "vw": config.reg_factors}

    def objective(params):
        scores = spec.scores(params, ids, vals)
        data_loss = jnp.sum(per_example_loss(scores, labels) * weights) / wsum

        def one(path, p):
            top = str(getattr(path[0], "key", getattr(path[0], "idx", path[0])))
            r = reg_of.get(top)
            if r is None:
                raise ValueError(f"no regularization group for param {top!r}")
            if r == 0.0:
                return jnp.zeros((), jnp.float32)
            return 0.5 * r * jnp.sum(jnp.square(p.astype(jnp.float32)))

        reg = sum(
            jax.tree_util.tree_leaves(
                jax.tree_util.tree_map_with_path(one, params)
            )
        )
        return data_loss + reg

    return objective


def fit_lbfgs(
    spec,
    params,
    ids,
    vals,
    labels,
    weights=None,
    *,
    config: TrainConfig | None = None,
    num_iterations: int = 100,
    num_corrections: int = 10,
    convergence_tol: float = 1e-6,
):
    """Minimize the full-batch objective from ``params``; returns
    ``(params, info)`` where info has the final loss, gradient norm, and
    iteration count. Stops at ``num_iterations`` or when the relative
    objective decrease falls below ``convergence_tol`` (MLlib semantics).
    """
    config = config or TrainConfig()
    ids = jnp.asarray(ids)
    vals = jnp.asarray(vals)
    labels = jnp.asarray(labels)
    weights = (
        jnp.ones(labels.shape, jnp.float32)
        if weights is None
        else jnp.asarray(weights)
    )
    objective = make_objective(spec, config, ids, vals, labels, weights)
    opt = optax.lbfgs(memory_size=num_corrections)

    value_and_grad = optax.value_and_grad_from_state(objective)

    # carry = (params, state, i, prev, cur) with prev/cur the objective at
    # the params of the previous/current iterate — ``cur`` is f(params)
    # BEFORE this body's update, so consecutive bodies see consecutive
    # objective values and the relative-decrease test is meaningful.
    def cond(carry):
        _, _, i, prev, cur = carry
        rel = jnp.where(
            jnp.isfinite(prev),
            jnp.abs(prev - cur) / jnp.maximum(jnp.abs(prev), 1e-12),
            jnp.inf,
        )
        return jnp.logical_and(i < num_iterations,
                               jnp.logical_or(i < 1, rel > convergence_tol))

    def body(carry):
        params, state, i, _, cur = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(
            grad, state, params, value=value, grad=grad, value_fn=objective
        )
        params = optax.apply_updates(params, updates)
        return params, state, i + 1, cur, value

    @jax.jit
    def run(params):
        state = opt.init(params)
        carry = (params, state, jnp.int32(0), jnp.float32(jnp.inf),
                 jnp.float32(jnp.inf))
        params, state, i, _, _ = jax.lax.while_loop(cond, body, carry)
        value, grad = jax.value_and_grad(objective)(params)
        return params, {
            "loss": value,
            "grad_norm": optax.global_norm(grad),
            "iterations": i,
        }

    params, info = run(params)
    return params, {k: float(v) for k, v in info.items()}
