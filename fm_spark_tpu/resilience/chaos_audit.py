"""Serving-invariant auditor (ISSUE 12) — standalone and import-free.

Split out of :mod:`fm_spark_tpu.resilience.chaos` (which re-exports it)
so jax-light tools can load this file BY PATH without importing the
package: ``tools/run_doctor.py`` audits every serve run it renders, and
the doctor's import-light contract (PR 9) is exactly why the ledger and
sentinel live in standalone-loadable modules too.
"""

from __future__ import annotations

__all__ = ["audit_serve_events"]


def _violation(invariant: str, detail: str) -> dict:
    return {"invariant": invariant, "detail": detail}


def audit_serve_events(events: list[dict], *,
                       final_staleness: int | None = None,
                       staleness_bound: int = 0,
                       rc: int | None = None,
                       allowed_rcs=(0,),
                       tombstoned_steps=()) -> list[dict]:
    """Serving invariants over a run's event stream (ISSUE 12) —
    flight-ring records (``kind``) and journal records (``event``)
    both read. Empty list = green. The contracts:

    - **no_torn_swap** — every observed ``serve_swap`` advances the
      generation monotonically (step strictly up, ``gen_id`` by
      exactly one): a regressed or duplicated generation means a
      request could have seen a mixture of model states;
    - **no_tombstoned_generation** (ISSUE 13) — no swap ever installed
      a DEMOTED generation: pass the chain's tombstoned step set and
      any ``serve_swap`` to one of them is a violation — the
      continuous-learning guarantee that a drift-judged-bad model was
      never scored with, asserted from artifacts alone;
    - **staleness_bounded** — after recovery the served generation is
      within ``staleness_bound`` steps of the chain's published tip
      (``final_staleness`` from the ``serve/staleness_steps`` gauge);
    - **rc_discipline** — a drilled serving process ends with an
      expected rc (0, the watchdog's HANG_EXIT_RC, or the injected
      exit code — never an unexplained death).

    Degraded mode is additionally held to its journaling contract:
    every ``reload_failed`` names the step it kept serving.
    """
    v: list[dict] = []
    stones = {int(s) for s in tombstoned_steps}
    last_step: int | None = None
    last_gen: int | None = None
    seen_swaps: set = set()
    for e in events:
        kind = e.get("kind") or e.get("event")
        if kind == "serve_swap":
            step, gid = e.get("step"), e.get("gen_id")
            if step is None:
                v.append(_violation(
                    "no_torn_swap",
                    "serve_swap event missing its generation step"))
                continue
            # One swap can reach the stream via two transports (the
            # journal AND its flight-ring mirror): an event identical
            # in (step, gen_id, from_step) is the same swap observed
            # twice, not a duplicated swap. A REAL duplicate (same
            # gen_id, different step — or vice versa) still trips the
            # monotonicity checks below.
            key = (step, gid, e.get("from_step"))
            if key in seen_swaps:
                continue
            seen_swaps.add(key)
            if step in stones:
                v.append(_violation(
                    "no_tombstoned_generation",
                    f"swap installed step {step}, which carries a "
                    "demotion tombstone — a drift-judged-bad "
                    "generation was served"))
            if last_step is not None and step <= last_step:
                v.append(_violation(
                    "no_torn_swap",
                    f"swap to step {step} after step {last_step} — "
                    "generations must advance monotonically"))
            if (gid is not None and last_gen is not None
                    and gid != last_gen + 1):
                v.append(_violation(
                    "no_torn_swap",
                    f"gen_id jumped {last_gen} -> {gid} — a swap was "
                    "lost or duplicated"))
            last_step = step
            last_gen = gid if gid is not None else last_gen
        elif kind == "reload_failed":
            if e.get("served_step") is None and "poll loop" not in str(
                    e.get("error", "")):
                v.append(_violation(
                    "degraded_journaled",
                    "reload_failed event does not name the generation "
                    "it kept serving"))
    if final_staleness is not None and final_staleness > staleness_bound:
        v.append(_violation(
            "staleness_bounded",
            f"served generation {final_staleness} step(s) behind the "
            f"published chain tip (bound {staleness_bound}) after "
            "recovery"))
    if rc is not None and rc not in tuple(allowed_rcs):
        v.append(_violation(
            "rc_discipline",
            f"serving process exited rc={rc}; expected one of "
            f"{tuple(allowed_rcs)}"))
    return v
