"""Serving-invariant auditor (ISSUE 12) — standalone and import-free.

Split out of :mod:`fm_spark_tpu.resilience.chaos` (which re-exports it)
so jax-light tools can load this file BY PATH without importing the
package: ``tools/run_doctor.py`` audits every serve run it renders, and
the doctor's import-light contract (PR 9) is exactly why the ledger and
sentinel live in standalone-loadable modules too.
"""

from __future__ import annotations

__all__ = ["audit_disk", "audit_fleet", "audit_serve_events"]


def _violation(invariant: str, detail: str) -> dict:
    return {"invariant": invariant, "detail": detail}


def audit_serve_events(events: list[dict], *,
                       final_staleness: int | None = None,
                       staleness_bound: int = 0,
                       rc: int | None = None,
                       allowed_rcs=(0,),
                       tombstoned_steps=()) -> list[dict]:
    """Serving invariants over a run's event stream (ISSUE 12) —
    flight-ring records (``kind``) and journal records (``event``)
    both read. Empty list = green. The contracts:

    - **no_torn_swap** — every observed ``serve_swap`` advances the
      generation monotonically (step strictly up, ``gen_id`` by
      exactly one): a regressed or duplicated generation means a
      request could have seen a mixture of model states;
    - **no_tombstoned_generation** (ISSUE 13) — no swap ever installed
      a DEMOTED generation: pass the chain's tombstoned step set and
      any ``serve_swap`` to one of them is a violation — the
      continuous-learning guarantee that a drift-judged-bad model was
      never scored with, asserted from artifacts alone;
    - **staleness_bounded** — after recovery the served generation is
      within ``staleness_bound`` steps of the chain's published tip
      (``final_staleness`` from the ``serve/staleness_steps`` gauge);
    - **rc_discipline** — a drilled serving process ends with an
      expected rc (0, the watchdog's HANG_EXIT_RC, or the injected
      exit code — never an unexplained death).

    Degraded mode is additionally held to its journaling contract:
    every ``reload_failed`` names the step it kept serving.
    """
    v: list[dict] = []
    stones = {int(s) for s in tombstoned_steps}
    last_step: int | None = None
    last_gen: int | None = None
    seen_swaps: set = set()
    for e in events:
        kind = e.get("kind") or e.get("event")
        if kind == "serve_swap":
            step, gid = e.get("step"), e.get("gen_id")
            if step is None:
                v.append(_violation(
                    "no_torn_swap",
                    "serve_swap event missing its generation step"))
                continue
            # One swap can reach the stream via two transports (the
            # journal AND its flight-ring mirror): an event identical
            # in (step, gen_id, from_step) is the same swap observed
            # twice, not a duplicated swap. A REAL duplicate (same
            # gen_id, different step — or vice versa) still trips the
            # monotonicity checks below.
            key = (step, gid, e.get("from_step"))
            if key in seen_swaps:
                continue
            seen_swaps.add(key)
            if step in stones:
                v.append(_violation(
                    "no_tombstoned_generation",
                    f"swap installed step {step}, which carries a "
                    "demotion tombstone — a drift-judged-bad "
                    "generation was served"))
            if last_step is not None and step <= last_step:
                v.append(_violation(
                    "no_torn_swap",
                    f"swap to step {step} after step {last_step} — "
                    "generations must advance monotonically"))
            if (gid is not None and last_gen is not None
                    and gid != last_gen + 1):
                v.append(_violation(
                    "no_torn_swap",
                    f"gen_id jumped {last_gen} -> {gid} — a swap was "
                    "lost or duplicated"))
            last_step = step
            last_gen = gid if gid is not None else last_gen
        elif kind == "reload_failed":
            if e.get("served_step") is None and "poll loop" not in str(
                    e.get("error", "")):
                v.append(_violation(
                    "degraded_journaled",
                    "reload_failed event does not name the generation "
                    "it kept serving"))
    if final_staleness is not None and final_staleness > staleness_bound:
        v.append(_violation(
            "staleness_bounded",
            f"served generation {final_staleness} step(s) behind the "
            f"published chain tip (bound {staleness_bound}) after "
            "recovery"))
    if rc is not None and rc not in tuple(allowed_rcs):
        v.append(_violation(
            "rc_discipline",
            f"serving process exited rc={rc}; expected one of "
            f"{tuple(allowed_rcs)}"))
    return v


def audit_disk(*, committed_steps=(), tombstoned_steps=(),
               last_good_step: "int | None" = None,
               restored_step: "int | None" = None,
               expected_surviving=None,
               io_failures: "dict | None" = None,
               degraded_gauge: "float | None" = None,
               params_match: "bool | None" = None,
               spool_seqs=None,
               events: "list[dict] | None" = None) -> list[dict]:
    """Storage-fault invariants (ISSUE 20), graded from artifacts
    alone: the chain reader's view (``committed_steps`` = manifest-
    verified, ``tombstoned_steps``, ``last_good_step``, and
    ``restored_step`` = where a FRESH reader actually landed after the
    plan cleared), the durable seam's failure accounting
    (``io_failures`` = :func:`fm_spark_tpu.utils.durable.
    io_failure_counts` or the ``io.write_failed*`` counters,
    ``degraded_gauge`` = ``obs/io_degraded``), the golden-vs-drilled
    params fingerprint comparison (``params_match``), and the flight
    spool's ``seq`` column. Empty list = green. The contracts:

    - **last_good_loadable** — whenever any committed, non-demoted
      generation exists, ``last_good_step`` names one of them: never
      None, never a tombstoned step, never a step without a verified
      manifest. Disk faults may stall the pointer, never corrupt it.
    - **chain_never_broken** — after the fault plan clears, a fresh
      reader walks the chain to the NEWEST committed non-demoted step
      (torn/short reads walk back, they never crash-loop and never
      land past a demotion).
    - **demotion_atomic** — when the drill demoted (``expected_
      surviving`` = steps that must outlive it), the tombstone set is
      exactly the complement: no expected survivor demoted, no
      condemned step left standing — a torn rename mid-demotion is
      all-or-nothing.
    - **degradation_signaled** — best-effort (obs-tier) write failures
      leave a trail: the failure counts are nonzero AND the
      ``obs/io_degraded`` gauge is raised. Silent telemetry loss is
      the one degradation this plane forbids.
    - **obs_degraded_harmless** — the drilled run's final params are
      byte-identical to the golden run's (``params_match``): no obs
      write failure ever leaked into training bytes.
    - **spool_seq_continuous** — flight ``seq`` values on disk are
      strictly increasing (gaps are legal — a failed best-effort
      append loses that record from DISK, not from the ring — but a
      regressed or duplicated seq means a restart forked the stream).
    """
    v: list[dict] = []
    committed = {int(s) for s in committed_steps}
    stones = {int(s) for s in tombstoned_steps}
    good = committed - stones
    if good:
        if last_good_step is None:
            v.append(_violation(
                "last_good_loadable",
                f"no last_good pointer while committed non-demoted "
                f"steps {sorted(good)} exist"))
        elif int(last_good_step) in stones:
            v.append(_violation(
                "last_good_loadable",
                f"last_good names step {last_good_step}, which "
                "carries a demotion tombstone"))
        elif int(last_good_step) not in committed:
            v.append(_violation(
                "last_good_loadable",
                f"last_good names step {last_good_step}, which has "
                f"no verified manifest (committed: {sorted(committed)})"))
        if restored_step is not None and int(restored_step) != max(good):
            v.append(_violation(
                "chain_never_broken",
                f"fresh reader landed on step {restored_step} after "
                f"the plan cleared; the newest committed non-demoted "
                f"step is {max(good)}"))
    elif restored_step is not None:
        v.append(_violation(
            "chain_never_broken",
            f"fresh reader restored step {restored_step} but no "
            "committed non-demoted step exists"))
    if expected_surviving is not None:
        keep = {int(s) for s in expected_surviving}
        wrongly_demoted = sorted(keep & stones)
        left_standing = sorted((committed - keep) - stones)
        if wrongly_demoted:
            v.append(_violation(
                "demotion_atomic",
                f"steps {wrongly_demoted} were expected to survive "
                "the demotion but carry tombstones"))
        if left_standing:
            v.append(_violation(
                "demotion_atomic",
                f"condemned steps {left_standing} have no tombstone — "
                "the demotion tore"))
    fails = dict(io_failures or {})
    # The gauge contract binds the BEST-EFFORT tier (swallowed
    # failures); fail-loud failures surface to a caller who owns them
    # and need no ambient flag.
    n_fail = int(fails.get("best_effort") or 0)
    if n_fail and (degraded_gauge is None or degraded_gauge < 1.0):
        v.append(_violation(
            "degradation_signaled",
            f"{n_fail} best-effort write failure(s) swallowed but the "
            f"obs/io_degraded gauge reads {degraded_gauge!r} — "
            "telemetry loss must leave a visible mark"))
    if params_match is False:
        v.append(_violation(
            "obs_degraded_harmless",
            "drilled final params differ from the golden run's — an "
            "obs-tier disk fault leaked into training bytes"))
    if spool_seqs is not None:
        seqs = [int(s) for s in spool_seqs]
        for a, b in zip(seqs, seqs[1:]):
            if b <= a:
                v.append(_violation(
                    "spool_seq_continuous",
                    f"flight spool seq regressed {a} -> {b} — a "
                    "restart forked the event stream"))
                break
    return v


def audit_fleet(tap_events: list[dict], counters: dict, *,
                expected_requests: int | None = None,
                tombstoned_steps=(),
                replica_events: "dict[int, list[dict]] | None" = None,
                staleness_bound: int = 0,
                fleet_events: "list[dict] | None" = None,
                partition_victim: "int | None" = None,
                max_autoscale_decisions: "int | None" = None,
                max_direction_changes: int = 1) -> list[dict]:
    """Fleet/traffic invariants over a load-replay run (ISSUE 17),
    graded from artifacts alone: the loadgen **tap** (one record per
    attempt: ``req_id``/``attempt``/``outcome``/``gen_step``), the
    front door's counter snapshot (its ``frontdoor_summary`` journal
    event / ``FrontDoor.stats()``), and — optionally — each replica's
    serve journal (re-audited via :func:`audit_serve_events`).
    Empty list = green. The contracts:

    - **exactly_once_responses** — every scheduled request reached a
      terminal outcome at least once, no (req_id, attempt) was
      answered twice, and no req_id got more than one ``ok`` (a
      client only retries failures, so a double-ok means a dead
      replica's in-flight request was BOTH replayed and delivered);
    - **accepted_accounting** — the door's books close:
      ``accepted == answered + timeout + failed`` (an admitted
      request that vanished from the counters was silently dropped);
    - **shed_accounting** — the tap's observed ``shed`` outcomes
      equal the admission controller's ``shed`` counter, and
      ``shed == shed_queue + shed_deadline`` (the backpressure the
      clients experienced IS the backpressure the door accounted);
    - **no_tombstoned_generation** — no attempt was ever answered by
      a demoted generation (the tap carries the scoring generation);
      replica journals are additionally held to the full serve
      invariants (torn swaps, staleness after recovery).

    Partition-chaos extensions (ISSUE 19), graded from the fleet's
    own ``fleet_health.jsonl`` slice (``fleet_events``):

    - **partition_not_a_crash** — a replica partitioned away from the
      parent (``partition_victim``) was suspected -> drained ->
      readmitted through the normal green-poll gate, and NEVER
      respawn-killed: after its first ``replica_drained`` there is a
      ``replica_ready`` with no ``replica_spawn``/``replica_down``
      in between (the process stayed alive; only the LINK failed);
    - **autoscale_converged** — the autoscaler's journaled
      ``autoscale_decision`` events are bounded
      (``max_autoscale_decisions``) and do not flap: at most
      ``max_direction_changes`` grow<->shrink direction reversals.
    """
    v: list[dict] = []
    stones = {int(s) for s in tombstoned_steps}
    attempts = [e for e in tap_events
                if (e.get("event") or e.get("kind")) == "attempt"]
    seen: dict = {}
    ok_by_req: dict = {}
    n_shed = 0
    for e in attempts:
        rid, att = e.get("req_id"), e.get("attempt")
        out = e.get("outcome")
        key = (rid, att)
        if key in seen:
            v.append(_violation(
                "exactly_once_responses",
                f"request {rid} attempt {att} recorded twice — an "
                "in-flight request was answered more than once"))
        seen[key] = out
        if out == "ok":
            ok_by_req[rid] = ok_by_req.get(rid, 0) + 1
        elif out == "shed":
            n_shed += 1
        gs = e.get("gen_step")
        if gs is not None and int(gs) in stones:
            v.append(_violation(
                "no_tombstoned_generation",
                f"request {rid} was scored by demoted generation "
                f"{gs}"))
    for rid, n_ok in ok_by_req.items():
        if n_ok > 1:
            v.append(_violation(
                "exactly_once_responses",
                f"request {rid} answered ok {n_ok} times — retried "
                "after a success (double-scored to the client)"))
    if expected_requests is not None:
        got = len({rid for rid, _ in seen})
        if got != int(expected_requests):
            v.append(_violation(
                "exactly_once_responses",
                f"{got} of {expected_requests} scheduled requests "
                "reached a terminal outcome — the rest were "
                "silently dropped"))
    acc = int(counters.get("accepted") or 0)
    closed = (int(counters.get("answered") or 0)
              + int(counters.get("timeout") or 0)
              + int(counters.get("failed") or 0))
    if acc != closed:
        v.append(_violation(
            "accepted_accounting",
            f"accepted={acc} but answered+timeout+failed={closed} — "
            f"{acc - closed} admitted request(s) have no terminal "
            "outcome on the door's books"))
    shed = int(counters.get("shed") or 0)
    shed_split = (int(counters.get("shed_queue") or 0)
                  + int(counters.get("shed_deadline") or 0))
    if shed != shed_split:
        v.append(_violation(
            "shed_accounting",
            f"shed={shed} != shed_queue+shed_deadline={shed_split}"))
    if n_shed != shed:
        v.append(_violation(
            "shed_accounting",
            f"clients observed {n_shed} shed response(s) but the "
            f"admission controller counted {shed}"))
    for idx, events in (replica_events or {}).items():
        staleness = None
        for e in events:
            if (e.get("event") or e.get("kind")) == "replica_state":
                staleness = e.get("staleness_steps", staleness)
        # A SIGKILLed replica's respawn restarts the generation
        # sequence from the base model, so the monotonic-swap audit
        # holds WITHIN an incarnation, not across the journal: split
        # at each ``replica_start``. Tombstone/degraded contracts hold
        # for every segment regardless.
        segments: list[list[dict]] = [[]]
        for e in events:
            if (e.get("event") or e.get("kind")) == "replica_start":
                segments.append([])
            segments[-1].append(e)
        live = [s for s in segments if s]
        for inc, seg in enumerate(live):
            for viol in audit_serve_events(
                    seg, tombstoned_steps=stones,
                    final_staleness=(staleness
                                     if inc == len(live) - 1 else None),
                    staleness_bound=staleness_bound):
                viol = dict(viol)
                viol["detail"] = (f"replica {idx} incarnation {inc}: "
                                  f"{viol['detail']}")
                v.append(viol)
    fev = fleet_events or []
    if partition_victim is not None:
        vic = int(partition_victim)
        timeline = [(e.get("event") or e.get("kind")) for e in fev
                    if e.get("replica") == vic]
        try:
            first_drain = timeline.index("replica_drained")
        except ValueError:
            first_drain = None
        if first_drain is None:
            v.append(_violation(
                "partition_not_a_crash",
                f"replica {vic} was the partition victim but was "
                "never drained — the fault plane did not reach the "
                "health poller"))
        else:
            after = timeline[first_drain + 1:]
            if "replica_ready" not in after:
                v.append(_violation(
                    "partition_not_a_crash",
                    f"replica {vic} was drained but never readmitted "
                    "after the partition healed"))
            else:
                upto = after[:after.index("replica_ready")]
                bad = [k for k in upto
                       if k in ("replica_spawn", "replica_down")]
                if bad:
                    v.append(_violation(
                        "partition_not_a_crash",
                        f"replica {vic} saw {bad} between drain and "
                        "readmission — a partitioned-but-alive "
                        "replica was treated as a crash"))
    if max_autoscale_decisions is not None:
        actions = [e.get("action") for e in fev
                   if (e.get("event") or e.get("kind"))
                   == "autoscale_decision"]
        if len(actions) > int(max_autoscale_decisions):
            v.append(_violation(
                "autoscale_converged",
                f"{len(actions)} autoscale decisions "
                f"(bound {max_autoscale_decisions}) — the policy did "
                "not converge"))
        flips = sum(1 for a, b in zip(actions, actions[1:])
                    if a != b)
        if flips > int(max_direction_changes):
            v.append(_violation(
                "autoscale_converged",
                f"autoscaler flapped: {flips} grow<->shrink "
                f"reversals (bound {max_direction_changes}) in "
                f"{actions}"))
    return v
