"""Deterministic fault injection: the observed TPU failure modes, on demand.

The flaky attachment's failure modes (VERDICT r3–r5; bench.py's
reliability notes) are: backend init that HANGS forever, init that fails
fast (child exits rc=3), mid-step device loss, SIGTERM landing mid-sweep,
and a pathologically slow first compile. None of them could be produced
on demand, so none of the recovery paths had a repeatable test. This
module injects exactly those faults at named points, deterministically,
on any backend (CPU included) — no jax import, no accelerator required.

Usage — a fault PLAN is a ``;``-separated list of rules::

    <point>@<occurrence>=<action>[:<param>]

    FM_SPARK_FAULTS="backend_init@1=hang:300;sweep_leg@2=device_loss"

means: the 1st time any process hits the ``backend_init`` injection
point, sleep 300 s (an init hang — the watchdog's job to catch); the 2nd
time any process hits ``sweep_leg``, raise :class:`InjectedDeviceLoss`.

Actions: ``hang[:secs]`` (sleep; default 3600 — something else must kill
it, that is the point), ``sleep:secs`` (slow compile/step), ``exit[:rc]``
(``os._exit``; ``exit:3`` = the observed init-failure child rc),
``device_loss`` (raise :class:`InjectedDeviceLoss`), ``error`` (raise
:class:`FaultInjected`), ``sigterm`` (``os.kill(self, SIGTERM)``).

Occurrences are counted PER POINT. In-process by default; when
``FM_SPARK_FAULTS_STATE=<file>`` names a JSON file, counters persist
across processes (flock-serialized), so a scenario like "hang the FIRST
child's init, then lose the device on the SECOND child's 2nd sweep leg"
is expressible even though the bench parent respawns children.

Production code calls :func:`inject` at its fault points; with no active
plan that is a single ``is None`` check. Tests either set the env vars on
a subprocess or call :func:`activate`/:func:`clear` in-process.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import signal
import time

__all__ = [
    "ACTIONS",
    "ENV_PLAN",
    "ENV_STATE",
    "IO_ACTIONS",
    "IO_PATH_CLASSES",
    "IO_POINTS",
    "KNOWN_POINTS",
    "NET_ACTIONS",
    "NET_POINTS",
    "FaultInjected",
    "FaultPlan",
    "InjectedDeviceLoss",
    "activate",
    "clear",
    "current_plan",
    "inject",
    "is_device_loss",
]

#: Environment variables read lazily at the first :func:`inject` call.
ENV_PLAN = "FM_SPARK_FAULTS"
ENV_STATE = "FM_SPARK_FAULTS_STATE"

#: Production injection points (the registry the fault-matrix test
#: pins, tests/test_resilience.py). Device/runtime faults: backend
#: init, per-sweep-leg, per-train-step, the health probe, and the
#: checkpoint commit window. Data faults (ISSUE 5): ``ingest_truncate``
#: fires per chunk read in data/stream.ShardReader (a failing/truncated
#: shard read), ``ingest_corrupt`` fires per record before parse in
#: StreamBatches (an injected ``error`` there IS a corrupt record and
#: takes the active quarantine/strict policy path). Serving (ISSUE 12):
#: ``serve_reload`` fires at the start of each hot-reload attempt in
#: serve/reload.py — an ``error`` there exercises the degraded-serving
#: path (old generation keeps serving), an ``exit`` is the
#: SIGKILL-during-reload drill. Continuous learning (ISSUE 13):
#: ``online_eval`` fires at the start of each day's time-ordered eval
#: pass in online.py (a fault there is a drift-sentry-adjacent failure
#: — e.g. an alarm racing a checkpoint commit), and ``ckpt_demote``
#: fires INSIDE checkpoint.Checkpointer's demotion window — after the
#: durable tombstone write, before the ``last_good`` republish — so an
#: ``exit`` there is the SIGKILL-mid-demotion drill and an ``error``
#: exercises the stale-pointer-but-vetoed recovery path. Tiered
#: embedding store (ISSUE 16): ``embed_prefetch`` fires once per bucket
#: staging attempt on the prefetch PRODUCER thread
#: (embed/store.TieredStore.stage) — a ``device_loss`` there is the
#: device-dies-mid-prefetch chaos drill, and the auditor's contract is
#: that the dirty-mask flush keeps a post-restore run bit-identical to
#: a clean one; ``embed_evict`` fires at the START of each eviction's
#: dirty-bucket flush window (before the cold write-back and version
#: bump), so an ``exit`` there is the kill-mid-eviction drill — the
#: merged checkpoint view never depended on the in-flight flush.
#: Serving fleet (ISSUE 17): ``frontdoor_accept`` fires once per
#: inbound request in serve/frontdoor.py BEFORE admission control (an
#: ``error`` there is a transport-layer failure the client sees as an
#: explicit 500 — never a silent drop), ``fleet_dispatch`` fires in
#: the front door's fleet backend before each replica dispatch (an
#: ``error`` exercises the retry-once-on-a-live-replica path), and
#: ``replica_kill`` fires inside each REPLICA process per scored
#: request (serve/fleet.py) — an ``exit`` there is the
#: SIGKILL-mid-burst drill: the parent sees the connection die and
#: must answer the in-flight request exactly once elsewhere.
#: Network fault plane (ISSUE 19): ``net_connect`` / ``net_send`` /
#: ``net_recv`` fire in the parent's replica transport
#: (serve/fleet.py's ConnectionPool + ``_http_json`` — dispatch,
#: health poller, and metrics scraper all route through it) and take
#: the socket-level actions below (``refuse``, ``blackhole``,
#: ``slow_ms``, ``truncate_after``, ``reset``). They are interpreted
#: by :mod:`fm_spark_tpu.resilience.netfaults`, not :func:`inject`,
#: and uniquely support PEER SCOPING (``net_connect.replica-1``) and
#: occurrence RANGES (``@3-9=``) so a schedule can partition the
#: parent away from ONE replica for a bounded window while that
#: replica stays healthy — the failure the process-kill model cannot
#: express.
#: Storage fault plane (ISSUE 20): ``io_write`` / ``io_fsync`` /
#: ``io_rename`` / ``io_read`` fire at the durable-write seam
#: (:mod:`fm_spark_tpu.utils.durable` — every checkpoint manifest,
#: tombstone, obs ledger/spool/journal, embed cold-store write-back,
#: and compile-cache breadcrumb routes through it) and take the
#: disk-level actions below (``eio``, ``enospc``, ``torn_write:K``,
#: ``readonly``, plus the shared ``slow_ms:N``). They are interpreted
#: by :mod:`fm_spark_tpu.resilience.iofaults`, not :func:`inject`, and
#: support PATH-CLASS scoping (``io_write.ckpt`` / ``io_write.obs``)
#: analogous to net peer scoping, so a schedule can fail ONLY the
#: checkpoint commits while observability keeps writing, or vice
#: versa. ``ckpt_gc`` fires inside checkpoint.Checkpointer's
#: emergency-GC window (after the journal entry, before deletions
#: complete) — an ``exit`` there is the SIGKILL-during-emergency-GC
#: drill; recovery must land on a loadable ``last_good``.
KNOWN_POINTS = (
    "backend_init",
    "sweep_leg",
    "train_step",
    "probe",
    "ckpt_commit",
    "ingest_corrupt",
    "ingest_truncate",
    "serve_reload",
    "online_eval",
    "ckpt_demote",
    "embed_prefetch",
    "embed_evict",
    "frontdoor_accept",
    "replica_kill",
    "fleet_dispatch",
    "net_connect",
    "net_send",
    "net_recv",
    "io_write",
    "io_fsync",
    "io_rename",
    "io_read",
    "ckpt_gc",
)

#: The network points and their socket-level action vocabulary
#: (ISSUE 19). Net actions are only valid on ``net_*`` points (and
#: vice versa peer scoping is only valid there); they are interpreted
#: by :mod:`fm_spark_tpu.resilience.netfaults` at the transport seam.
NET_POINTS = ("net_connect", "net_send", "net_recv")
NET_ACTIONS = ("refuse", "blackhole", "slow_ms", "truncate_after",
               "reset")

#: The storage points and their disk-level action vocabulary
#: (ISSUE 20). IO actions are only valid on ``io_*`` points;
#: ``slow_ms`` is shared with the net plane (a slow fsync and a slow
#: link are the same latency primitive). Interpreted by
#: :mod:`fm_spark_tpu.resilience.iofaults` at the durable-write seam.
IO_POINTS = ("io_write", "io_fsync", "io_rename", "io_read")
IO_ACTIONS = ("eio", "enospc", "torn_write", "readonly")

#: The path classes an ``io_*`` point may scope to (``io_write.ckpt``).
#: Unlike net peer scopes (free-form replica names), path classes are a
#: closed vocabulary — each names one durability tier declared at a
#: :mod:`fm_spark_tpu.utils.durable` call site — so a typo'd class is a
#: plan that silently never fires and is rejected eagerly.
IO_PATH_CLASSES = ("ckpt", "obs", "embed", "cache", "quarantine")

#: The action vocabulary (public since ISSUE 10: the chaos schedule
#: generator samples from it, and the eager-validation error cites it).
ACTIONS = ("hang", "sleep", "exit", "device_loss", "error", "sigterm",
           *NET_ACTIONS, *IO_ACTIONS)
_ACTIONS = ACTIONS

#: Actions that must carry a numeric parameter (``slow_ms:N`` in
#: milliseconds, ``truncate_after:K`` / ``torn_write:K`` in bytes).
_PARAM_REQUIRED = ("slow_ms", "truncate_after", "torn_write")

#: Occurrence-range expansion bound: ``point@1-512=...`` is the widest
#: window one rule may cover (a wider one is almost certainly a typo).
_MAX_RANGE = 512


class FaultInjected(RuntimeError):
    """An injected generic failure (action ``error``)."""


class InjectedDeviceLoss(FaultInjected):
    """An injected mid-step device loss.

    The message mimics the runtime-error text a real detachment produces
    so string-matching consumers exercise the same path either way.
    """

    def __init__(self, point: str, occurrence: int):
        super().__init__(
            f"INTERNAL: device lost / attachment detached "
            f"(injected fault at {point}#{occurrence})"
        )


@dataclasses.dataclass(frozen=True)
class _Rule:
    point: str
    occurrence: int
    action: str
    param: str | None

    def fire(self, count: int) -> None:
        if self.action == "hang":
            time.sleep(float(self.param) if self.param else 3600.0)
        elif self.action == "sleep":
            time.sleep(float(self.param or 1.0))
        elif self.action == "exit":
            os._exit(int(self.param or 1))
        elif self.action == "device_loss":
            raise InjectedDeviceLoss(self.point, count)
        elif self.action == "error":
            raise FaultInjected(
                f"injected failure at {self.point}#{count}"
            )
        elif self.action == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)


class FaultPlan:
    """A parsed set of injection rules, matched at :func:`inject` points."""

    def __init__(self, rules: list[_Rule]):
        self._rules: dict[tuple[str, int], _Rule] = {
            (r.point, r.occurrence): r for r in rules
        }
        self.points = {r.point for r in rules}

    @classmethod
    def from_spec(cls, spec: str,
                  points: "tuple[str, ...] | None" = KNOWN_POINTS
                  ) -> "FaultPlan":
        """Parse a plan, validating it EAGERLY (ISSUE 10 satellite): an
        unknown point or action used to surface only when (never) the
        point fired — a typo'd plan silently tested nothing. Both are
        rejected up front with the registry/action set in the error.
        ``points=None`` disables the registry check (harness-internal
        plans over synthetic points).

        ISSUE 19 grammar extensions, for the network fault plane:
        ``net_*`` points accept a PEER SCOPE (``net_connect.replica-1``
        — fires only on that peer's transport, with its own occurrence
        counter), and any rule accepts an occurrence RANGE
        (``point@3-9=action`` expands to one rule per occurrence) so a
        bounded partition window is one rule, not seven.
        """
        rules = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            m = re.fullmatch(
                r"(?P<point>[\w.-]+)@(?P<n>\d+)(?:-(?P<n2>\d+))?="
                r"(?P<action>[a-z_]+)(?::(?P<param>[\w.+-]+))?",
                entry,
            )
            if m is None:
                raise ValueError(
                    f"bad fault rule {entry!r} (want "
                    "point@occurrence[-occurrence]=action[:param])"
                )
            if m["action"] not in _ACTIONS:
                raise ValueError(
                    f"unknown fault action {m['action']!r} "
                    f"(know {_ACTIONS})"
                )
            point = m["point"]
            base = point.split(".", 1)[0]
            if points is not None and point not in points:
                # A dotted point is a peer-scoped NET point
                # (``net_connect.replica-1``) or a path-class-scoped
                # IO point (``io_write.ckpt``); scoping any other
                # point is as much a typo as an unknown one.
                if not ("." in point
                        and (base in NET_POINTS or base in IO_POINTS)
                        and base in points):
                    raise ValueError(
                        f"unknown fault point {point!r} — a rule "
                        "naming a point nothing injects would silently "
                        f"never fire (known points: {tuple(points)}; "
                        f"actions: {_ACTIONS})"
                    )
                if (base in IO_POINTS
                        and point[len(base) + 1:] not in IO_PATH_CLASSES):
                    raise ValueError(
                        f"unknown io path class in {point!r} — io "
                        "points scope to the durable-seam path classes "
                        f"{IO_PATH_CLASSES}, not free-form names"
                    )
            if (m["action"] in NET_ACTIONS and base not in NET_POINTS
                    and not (m["action"] == "slow_ms"
                             and base in IO_POINTS)):
                raise ValueError(
                    f"net action {m['action']!r} on non-network point "
                    f"{point!r} — socket-level actions only make "
                    f"sense at {NET_POINTS} (see resilience/netfaults)"
                )
            if m["action"] in IO_ACTIONS and base not in IO_POINTS:
                raise ValueError(
                    f"io action {m['action']!r} on non-storage point "
                    f"{point!r} — disk-level actions only make sense "
                    f"at {IO_POINTS} (see resilience/iofaults)"
                )
            if (m["action"] in _PARAM_REQUIRED
                    and not (m["param"] or "").replace(".", "").isdigit()):
                raise ValueError(
                    f"action {m['action']!r} needs a numeric "
                    f"parameter (got {m['param']!r}) — e.g. "
                    "slow_ms:50 or truncate_after:64"
                )
            first, last = int(m["n"]), int(m["n2"] or m["n"])
            if last < first or last - first >= _MAX_RANGE:
                raise ValueError(
                    f"bad occurrence range {first}-{last} in "
                    f"{entry!r} (want first <= last, width < "
                    f"{_MAX_RANGE})"
                )
            for n in range(first, last + 1):
                rules.append(_Rule(point, n, m["action"], m["param"]))
        return cls(rules)

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        spec = os.environ.get(ENV_PLAN, "").strip()
        return cls.from_spec(spec) if spec else None

    def rule_for(self, point: str, count: int) -> _Rule | None:
        return self._rules.get((point, count))


# Module state: the active plan (None until loaded; False = "looked at
# the env, nothing there" so inject() stays one comparison on the hot
# path) and the in-process occurrence counters.
_plan: FaultPlan | None | bool = None
_counts: dict[str, int] = {}


def activate(plan: "FaultPlan | str") -> FaultPlan:
    """Install a plan in-process (tests); resets occurrence counters."""
    global _plan
    if isinstance(plan, str):
        plan = FaultPlan.from_spec(plan)
    _plan = plan
    _counts.clear()
    return plan


def clear() -> None:
    """Drop the active plan AND forget the env lookup, so a later
    :func:`inject` re-reads the environment (test isolation)."""
    global _plan
    _plan = None
    _counts.clear()


def _next_count(point: str) -> int:
    """Increment and return this point's occurrence counter — in the
    shared state file when ``FM_SPARK_FAULTS_STATE`` is set (counts
    survive process respawn), else in-process."""
    path = os.environ.get(ENV_STATE, "").strip()
    if not path:
        _counts[point] = _counts.get(point, 0) + 1
        return _counts[point]
    import fcntl

    with open(path, "a+") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        f.seek(0)
        raw = f.read().strip()
        data = json.loads(raw) if raw else {}
        data[point] = int(data.get(point, 0)) + 1
        f.seek(0)
        f.truncate()
        json.dump(data, f)
        f.flush()
        return data[point]


def current_plan() -> "FaultPlan | None":
    """The active plan, loading the environment lazily on first use —
    the same resolution :func:`inject` performs, exposed so the
    network fault plane (:mod:`fm_spark_tpu.resilience.netfaults`) can
    consult the SAME plan and occurrence counters from the transport
    seam."""
    global _plan
    if _plan is None:
        _plan = FaultPlan.from_env() or False
    return None if _plan is False else _plan


def inject(point: str) -> None:
    """Fault point: a no-op without an active plan; with one, the
    matching rule for this point's Nth occurrence fires (sleep / raise /
    exit / signal). Call sites name the observable failure surface —
    see :data:`KNOWN_POINTS` for the registry (device/runtime faults
    plus the streaming-ingest data faults). ``net_*`` points are NOT
    injected here — :mod:`fm_spark_tpu.resilience.netfaults` interprets
    their socket-level actions at the transport seam."""
    plan = current_plan()
    if plan is None:
        return
    if point not in plan.points:
        return
    count = _next_count(point)
    rule = plan.rule_for(point, count)
    if rule is not None:
        rule.fire(count)


# Substrings (lowercased) that mark a runtime error as a lost/unhealthy
# device attachment rather than a program bug. Conservative: drawn from
# the failure text observed on this attachment plus PJRT/XLA's
# device-loss vocabulary. A compile error or a shape mismatch must NEVER
# match — retrying those burns the whole deadline re-crashing.
_DEVICE_LOSS_MARKERS = (
    "device lost",
    "device is lost",
    "data_loss",
    "attachment detached",
    "unable to initialize backend",
    "failed to enqueue",
    "device unavailable",
    "tpu driver",
    "socket closed",
    "connection reset",
    "transport closed",
    "halted execution",
)


def is_device_loss(exc: BaseException) -> bool:
    """Is this exception a lost/unhealthy device attachment (injected or
    real)? The supervisor's retryability test: device loss is transient
    by definition here (the attachment flaps); anything else is a
    program error and must propagate."""
    if isinstance(exc, InjectedDeviceLoss):
        return True
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        return False
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(marker in text for marker in _DEVICE_LOSS_MARKERS)
