"""Device-fault supervision: retry/backoff runtime + fault injection.

Why this subsystem exists (ISSUE 2 / VERDICT r5 "What's weak" #1): a
flaky TPU attachment nulled three consecutive driver bench rounds, and
every defense against it was ad-hoc — retry/probe logic in bash
(``tpu_watch.sh``), hand-rolled watchdogs in ``bench.py``, and no way to
exercise any failure path (init hang, rc=3 init failure, mid-step device
loss, SIGTERM mid-sweep) deterministically in tests. This package makes
failure handling a tested subsystem:

- :mod:`fm_spark_tpu.resilience.faults` — deterministic, env/flag-driven
  fault injection (CPU-backend testable) simulating every observed
  failure mode, so each recovery path has a repeatable test.
- :mod:`fm_spark_tpu.resilience.supervisor` — the retry/timeout/backoff
  state machine (bounded exponential backoff + deterministic jitter,
  cheap device-enumeration health probe, circuit-breaker escalation)
  emitting a structured health-event JSONL journal
  (:class:`fm_spark_tpu.utils.logging.EventLog`).
- :mod:`fm_spark_tpu.resilience.elastic` — degraded-mode policy on top
  of the supervisor (ISSUE 4): N identical consecutive failures are
  classified PERMANENT (a dead attachment, not a flap), and the
  :class:`ElasticController` sheds capacity — shrink the mesh 8→4→2→1,
  restore the last good checkpoint under the new sharding, renormalize
  per-chip metrics — instead of burning the deadline re-probing.
- :mod:`fm_spark_tpu.resilience.watchdog` — per-phase deadline
  watchdogs (ISSUE 10): the ingest chunk read, the checkpoint commit
  window, and the train-step window each get a budget, and a hang
  becomes a structured :class:`~fm_spark_tpu.resilience.watchdog
  .HangDetected` + flight dump (or a bounded hard exit) instead of a
  stuck process.
- :mod:`fm_spark_tpu.resilience.chaos` — the chaos campaign engine
  (ISSUE 10): seeded multi-fault schedule generation over the
  ``faults`` registry, a system-wide invariant auditor over short
  drilled training runs, and automatic schedule minimization
  (delta-debugging a failing plan down to a minimal reproducible
  string). Driven by ``tools/chaos_drill.py`` and the tier-1 bounded
  soak in tests/test_chaos.py.

Consumers: ``bench.py`` (per-leg supervision + ``--resume-sweep``),
``FMTrainer.fit`` (device-loss → checkpoint resume with loss
continuity), and ``tools/tpu_watch.py`` (the supervised attachment
watcher that replaced the bash poll loop).
"""

from fm_spark_tpu.resilience import faults, watchdog
from fm_spark_tpu.resilience.elastic import (
    ElasticController,
    ElasticExhausted,
    classify_failures,
)
from fm_spark_tpu.resilience.faults import (
    FaultInjected,
    FaultPlan,
    InjectedDeviceLoss,
    inject,
    is_device_loss,
)
from fm_spark_tpu.resilience.supervisor import (
    BackoffPolicy,
    CircuitOpen,
    RetriesExhausted,
    Supervisor,
    device_probe,
)
from fm_spark_tpu.resilience.watchdog import HangDetected

__all__ = [
    "BackoffPolicy",
    "CircuitOpen",
    "ElasticController",
    "ElasticExhausted",
    "FaultInjected",
    "FaultPlan",
    "HangDetected",
    "InjectedDeviceLoss",
    "RetriesExhausted",
    "Supervisor",
    "classify_failures",
    "device_probe",
    "faults",
    "inject",
    "is_device_loss",
    "watchdog",
]
