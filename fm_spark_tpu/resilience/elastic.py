"""Elastic degraded-mode policy: permanent-fault classification + mesh shrink.

PR 2's supervisor treats every device loss as TRANSIENT: probe, back
off, retry, and circuit-break when the attachment keeps dying. That is
the right policy for a flap — and exactly the wrong one for a dead
attachment: BENCH_r05 burned its whole deadline re-probing a chip that
exited rc=3 six times in a row, identically, and still produced an
error-only artifact. The missing classification is the one a human
operator applies instantly: *the same failure, N times in a row, is not
a flap — the capacity is gone.* This module encodes it:

- :func:`classify_failures` — the pure classifier over failure
  descriptions (numerals normalized so ``within 126s`` ≡ ``within
  125s``): the last N identical ⇒ ``"permanent"``, else
  ``"transient"``. Shared by the supervisor (in-process exceptions) and
  bench.py's parent retry loop (child exit diagnostics) so the two
  layers can never disagree about what "identical" means.
- :class:`ElasticController` — the degraded-mode state machine: given a
  permanent classification it SHRINKS the device set (halving toward
  ``min_devices``, bounded by ``max_shrinks``) instead of dying, so the
  caller rebuilds a smaller mesh (``make_mesh(devices=...)`` /
  ``make_field_mesh(devices=...)``), restores the last good checkpoint
  under the new sharding (the canonical checkpoint layout is
  topology-portable by construction — host trees, re-placed at
  resume), and keeps training on 8→4→2→1 chips. Every transition is
  journaled through :class:`~fm_spark_tpu.utils.logging.EventLog`
  (``fault_classified`` / ``mesh_shrink`` / ``elastic_exhausted``), and
  :meth:`summary` feeds the ``degraded``/``chips``/``shrinks`` block
  that result artifacts carry so a degraded rate can never masquerade
  as a full-mesh one.

Which devices survive: a dead attachment does not announce its identity
— in-process, jax keeps enumerating the pre-fault device list. The
controller therefore shrinks by CAPACITY, keeping a prefix of the
current enumeration; on a backend whose re-enumeration does drop dead
devices, pass the fresh list via ``devices=`` at construction. What the
shrink buys is not device forensics but a smaller gang: fewer chips
that must all be healthy at once, and per-chip metrics renormalized so
the degraded run's throughput stays comparable.

No jax import at module scope: bench.py's PARENT process uses the
classifier on child exit diagnostics and must stay cheap.
"""

from __future__ import annotations

import re

__all__ = [
    "ElasticController",
    "ElasticExhausted",
    "classify_failures",
    "normalize_failure",
]

#: Default "N identical consecutive failures ⇒ permanent" threshold,
#: matched to the supervisor's default breaker_threshold so the breaker
#: opening and the classification flipping happen on the same failure.
PERMANENT_THRESHOLD = 3

_NUMERALS = re.compile(r"(rc=\d+)|(\d+(?:\.\d+)?)")


class ElasticExhausted(RuntimeError):
    """The controller cannot shrink further (``min_devices`` reached or
    ``max_shrinks`` spent): degraded mode is out of capacity to shed,
    so the permanent fault propagates to the caller."""


def normalize_failure(description: str) -> str:
    """Collapse numerals so two descriptions differing only in measured
    values (``within 126s`` vs ``within 125s``, occurrence counters,
    timestamps) compare as the SAME failure mode. Exit codes are the
    one numeral that IS identity — ``rc=1`` (a program bug) and
    ``rc=3`` (the init-watchdog exit) are different failure modes, so
    ``rc=<n>`` survives normalization verbatim."""
    return _NUMERALS.sub(lambda m: m.group(1) or "#", str(description))


def classify_failures(failures, threshold: int = PERMANENT_THRESHOLD
                      ) -> str:
    """``"permanent"`` iff the last ``threshold`` failure descriptions
    are present and identical after :func:`normalize_failure`, else
    ``"transient"``. Pure and dependency-free — callable from the bench
    parent before any backend work."""
    tail = [normalize_failure(f) for f in list(failures)[-threshold:]]
    if threshold > 0 and len(tail) == threshold and len(set(tail)) == 1:
        return "permanent"
    return "transient"


class ElasticController:
    """Degraded-mode device-capacity state machine.

    Usage (the shape every consumer follows — FMTrainer.fit, the CLI's
    field-sharded retry wrapper, bench.py's per-leg loop)::

        elastic = ElasticController(journal=journal, max_shrinks=3)
        ...
        cls = elastic.note_failure("train", exc)       # journal + classify
        if cls == "permanent":
            devices = elastic.shrink("train")          # 8 -> 4 (or raises)
            mesh = make_field_mesh(len(devices), devices=devices)
            # restore last-good checkpoint, re-place on the new mesh
    """

    def __init__(self, devices=None, max_shrinks: int = 3,
                 min_devices: int = 1,
                 identical_threshold: int = PERMANENT_THRESHOLD,
                 journal=None):
        if min_devices < 1:
            raise ValueError(f"min_devices must be >= 1, got {min_devices}")
        self._devices = list(devices) if devices is not None else None
        self.max_shrinks = int(max_shrinks)
        self.min_devices = int(min_devices)
        self.identical_threshold = int(identical_threshold)
        self.journal = journal
        self.shrinks = 0
        self._failures: list[str] = []

    # ------------------------------------------------------------ events

    def _emit(self, event: str, **fields) -> None:
        if self.journal is not None:
            self.journal.emit(event, **fields)

    # ------------------------------------------------------------ devices

    def devices(self) -> list:
        """The current surviving device set (lazily enumerated from jax
        on first use when not given at construction)."""
        if self._devices is None:
            import jax

            self._devices = list(jax.devices())
        return list(self._devices)

    @property
    def n_chips(self) -> int:
        return len(self.devices())

    @property
    def degraded(self) -> bool:
        return self.shrinks > 0

    # ------------------------------------------------------ classification

    def note_failure(self, op: str, exc) -> str:
        """Record one failure (an exception or a description string) and
        return its classification. Transient failures accumulate; the
        ``identical_threshold``-th identical consecutive one flips the
        verdict to ``"permanent"`` (the caller then decides to shrink)."""
        if isinstance(exc, BaseException):
            first = (str(exc).splitlines() or [""])[0]
            desc = f"{type(exc).__name__}: {first[:200]}"
        else:
            desc = str(exc)
        if self._failures and (normalize_failure(desc)
                               != normalize_failure(self._failures[-1])):
            # A DIFFERENT failure mode restarts the identical run: only
            # consecutive repeats of one mode mean "permanently dead".
            self._failures.clear()
        self._failures.append(desc)
        verdict = classify_failures(self._failures,
                                    self.identical_threshold)
        self._emit("fault_classified", op=op, classification=verdict,
                   identical_failures=len(self._failures),
                   error=desc)
        return verdict

    def note_success(self) -> None:
        """Real progress clears the failure run (a later fault starts a
        fresh classification window)."""
        self._failures.clear()

    # ------------------------------------------------------------- shrink

    def can_shrink(self) -> bool:
        return (self.shrinks < self.max_shrinks
                and self.n_chips > self.min_devices)

    def shrink(self, op: str = "train") -> list:
        """Halve the device set (floored at ``min_devices``) and return
        the survivors; raises :class:`ElasticExhausted` when no capacity
        is left to shed. Journals the ``mesh_shrink`` transition."""
        devices = self.devices()
        if not self.can_shrink():
            self._emit("elastic_exhausted", op=op, chips=len(devices),
                       shrinks=self.shrinks,
                       max_shrinks=self.max_shrinks)
            raise ElasticExhausted(
                f"{op}: cannot shrink below {len(devices)} device(s) "
                f"(shrinks={self.shrinks}/{self.max_shrinks}, "
                f"min_devices={self.min_devices})"
            )
        survivors = devices[:max(self.min_devices, len(devices) // 2)]
        self._devices = survivors
        self.shrinks += 1
        self._failures.clear()
        self._emit("mesh_shrink", op=op, from_chips=len(devices),
                   to_chips=len(survivors), shrinks=self.shrinks,
                   max_shrinks=self.max_shrinks)
        return list(survivors)

    # ------------------------------------------------------------ summary

    def summary(self) -> dict:
        """The provenance block degraded artifacts carry: whether the
        run shrank, how often, and the chip count its per-chip metrics
        are normalized to."""
        return {"degraded": self.degraded, "chips": self.n_chips,
                "shrinks": self.shrinks}
