"""Per-phase deadline watchdogs: convert a hang into a detected fault.

The supervisor already solves one hang (``device_probe``'s thread-join
timeout caught the observed ``jax.devices()`` init hang); this module
generalizes that move to every phase a chaos drill can freeze (ISSUE
10): the ingest chunk read, the checkpoint commit/verify window, and
the per-step train window. A hang is the one failure mode with no
exception to classify — without a deadline it destroys its own
evidence by simply never returning — so each guarded phase gets a
budget, and overrunning it produces a STRUCTURED ending instead of a
stuck process:

- a ``hang_detected`` journal/flight event naming the phase, its
  deadline, and the observed elapsed time;
- an atomic flight-recorder dump (:func:`fm_spark_tpu.obs.flight_dump`)
  so the last-N window survives whatever happens next;
- then, per the configured action: ``raise`` — :class:`HangDetected`
  raised at phase exit (for hangs that eventually return, e.g. an
  injected finite ``hang:secs`` fault — deterministic, thread-free,
  the in-process chaos drill mode), or ``exit`` — a daemon monitor
  thread hard-exits the process with :data:`HANG_EXIT_RC` while the
  hung thread is still stuck (for real never-returning hangs; the
  chaos engine's subprocess respawn loop treats that rc as a detected
  hang, not an unexplained death).

Configuration: in-process via :func:`configure`, or by environment for
subprocess drills::

    FM_SPARK_WATCHDOG="ingest_chunk=2;ckpt_commit=10;step_window=30"
    FM_SPARK_WATCHDOG_ACTION=exit        # or: raise

Unconfigured, :func:`phase` returns a shared no-op context manager —
one dict miss per guarded call, nothing armed, no thread (the same
disabled-path contract as the obs plane).
"""

from __future__ import annotations

import os
import threading
import time

from fm_spark_tpu import obs
from fm_spark_tpu.obs.introspect import NEAR_MISS_FRACTION

__all__ = [
    "ENV_ACTION",
    "ENV_SPEC",
    "HANG_EXIT_RC",
    "KNOWN_PHASES",
    "NEAR_MISS_FRACTION",
    "HangDetected",
    "WatchdogTable",
    "active",
    "clear",
    "configure",
    "phase",
]

ENV_SPEC = "FM_SPARK_WATCHDOG"
ENV_ACTION = "FM_SPARK_WATCHDOG_ACTION"

#: The rc a hard-exit watchdog dies with — distinct from every rc the
#: fault injector can produce, so a supervising parent can tell "hang
#: detected and bounded" from "crashed for an unexplained reason".
HANG_EXIT_RC = 87

#: Minimum seconds between two near-miss flight dumps of the same phase
#: when NO capture engine is armed (armed, the engine's own rate
#: limiter gates the heavy evidence): a steady-state phase living at
#: 85% of its deadline must never fsync a full dump per occurrence.
NEAR_MISS_DUMP_INTERVAL_S = 30.0

#: Guarded production phases (the registry the chaos auditor samples
#: deadlines for): the shard reader's chunk read (data/stream.py), the
#: checkpoint manifest-commit window (checkpoint.py), one training
#: step including its batch fetch (train.py), one serving micro-batch
#: execute — deadline = the SLO — in the predict engine
#: (serve/engine.py, ISSUE 12), and one day's time-ordered eval pass
#: in the continuous-learning loop (online.py, ISSUE 13) — a hang
#: there would silently stall the drift sentry while training keeps
#: publishing generations. ``frontdoor_request`` (ISSUE 17) guards one
#: ADMITTED request end-to-end through the serving front door
#: (serve/frontdoor.py): admission → dispatch → response write;
#: deadline = the front door's worst acceptable response time, so a
#: wedged replica or a stuck backend surfaces as a structured hang
#: instead of a silently open socket.
KNOWN_PHASES = ("ingest_chunk", "ckpt_commit", "step_window",
                "serve_request", "online_eval", "frontdoor_request")

_ACTIONS = ("raise", "exit")


class HangDetected(RuntimeError):
    """A guarded phase overran its deadline — the structured verdict a
    hang converts into (the generalization of the supervisor's
    init-probe timeout)."""

    def __init__(self, phase: str, deadline_s: float, elapsed_s: float):
        self.phase = str(phase)
        self.deadline_s = float(deadline_s)
        self.elapsed_s = float(elapsed_s)
        super().__init__(
            f"phase {self.phase!r} overran its {self.deadline_s:g}s "
            f"deadline (observed {self.elapsed_s:.3f}s) — hang detected"
        )


class _Noop:
    """Shared disabled-path context manager (allocation-free)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


def parse_spec(spec: str) -> dict[str, float]:
    """Parse ``phase=secs;phase=secs`` (the :data:`ENV_SPEC` grammar);
    unknown phases are rejected eagerly — same policy as the fault
    plan's point validation (ISSUE 10 satellite)."""
    out: dict[str, float] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, secs = entry.partition("=")
        name = name.strip()
        if not sep or name not in KNOWN_PHASES:
            raise ValueError(
                f"bad watchdog entry {entry!r} (want phase=secs with "
                f"phase in {KNOWN_PHASES})"
            )
        out[name] = float(secs)
        if out[name] <= 0:
            raise ValueError(
                f"watchdog deadline for {name!r} must be > 0, "
                f"got {out[name]!r}"
            )
    return out


class _PhaseGuard:
    """One armed phase entry: deadline bookkeeping on enter/exit."""

    __slots__ = ("_table", "phase", "deadline_s", "_t0", "_token")

    def __init__(self, table: "WatchdogTable", phase: str,
                 deadline_s: float):
        self._table = table
        self.phase = phase
        self.deadline_s = deadline_s
        self._t0 = 0.0
        self._token = None

    def __enter__(self):
        self._t0 = time.monotonic()
        self._token = self._table._arm(self.phase, self._t0,
                                       self.deadline_s)
        return self

    def __exit__(self, exc_type, exc, tb):
        elapsed = time.monotonic() - self._t0
        self._table._disarm(self._token)
        if elapsed > self.deadline_s:
            # The phase DID return (a finite hang) — emit the same
            # structured evidence the exit-mode monitor would have, and
            # in raise mode surface the verdict unless a real exception
            # is already unwinding (never mask the primary failure).
            self._table._note_overrun(self.phase, self.deadline_s,
                                      elapsed)
            if self._table.action == "raise" and exc_type is None:
                raise HangDetected(self.phase, self.deadline_s, elapsed)
        elif elapsed > NEAR_MISS_FRACTION * self.deadline_s:
            # Near-miss (ISSUE 14): the phase survived but spent >80%
            # of its budget — the last observable moment BEFORE a hang
            # verdict, so this is where the deep capture arms (an
            # actual overrun either raises out or hard-exits; by then
            # the evidence window is closing, not open).
            self._table._note_near_miss(self.phase, self.deadline_s,
                                        elapsed)
        return False


class WatchdogTable:
    """A set of phase deadlines plus the machinery that enforces them.

    ``action='raise'`` is thread-free and deterministic: the overrun is
    detected at phase exit (finite hangs only). ``action='exit'``
    additionally runs a daemon monitor thread that hard-exits the
    process (:data:`HANG_EXIT_RC`) when any armed phase passes its
    deadline — the only way out of a phase that never returns. Events
    are journaled best-effort (``journal`` is any EventLog-shaped
    object) and always mirrored to the obs flight ring.
    """

    def __init__(self, deadlines: dict[str, float],
                 action: str = "raise", journal=None,
                 exit_rc: int = HANG_EXIT_RC, poll_s: float = 0.05,
                 _exit=os._exit):
        if action not in _ACTIONS:
            raise ValueError(
                f"watchdog action must be one of {_ACTIONS}, "
                f"got {action!r}"
            )
        self.deadlines = {str(k): float(v) for k, v in deadlines.items()}
        self.action = action
        self.journal = journal
        self.exit_rc = int(exit_rc)
        self._poll_s = float(poll_s)
        self._exit = _exit
        self._lock = threading.Lock()
        self._armed: dict[int, tuple[str, float, float]] = {}
        self._next_token = 0
        self._monitor: threading.Thread | None = None
        self._stop = threading.Event()
        self.hangs_detected = 0
        self.near_misses = 0
        self._last_near_dump: dict[str, float] = {}

    # ----------------------------------------------------------- arming

    def phase(self, name: str):
        limit = self.deadlines.get(name)
        if limit is None:
            return _NOOP
        return _PhaseGuard(self, name, limit)

    def _arm(self, name: str, t0: float, limit: float):
        if self.action != "exit":
            return None
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._armed[token] = (name, t0, t0 + limit)
            if self._monitor is None or not self._monitor.is_alive():
                self._stop.clear()
                self._monitor = threading.Thread(
                    target=self._watch, name="fm-spark-watchdog",
                    daemon=True)
                self._monitor.start()
        return token

    def _disarm(self, token) -> None:
        if token is None:
            return
        with self._lock:
            self._armed.pop(token, None)

    # --------------------------------------------------------- verdicts

    def _note_overrun(self, name: str, limit: float,
                      elapsed: float) -> None:
        # Under the table lock: the exit-mode monitor thread and a
        # raise-mode phase exit (caller thread) can both note overruns
        # — an unlocked += here drops counts (fmlint
        # thread-lock-discipline, ISSUE 15).
        with self._lock:
            self.hangs_detected += 1
        fields = dict(phase=name, deadline_s=round(limit, 3),
                      elapsed_s=round(elapsed, 3), action=self.action)
        if self.journal is not None:
            try:
                self.journal.emit("hang_detected", **fields)
            except Exception:
                pass
        try:
            obs.event("hang_detected", **fields)
            obs.counter("resilience.hangs_detected_total").add(1)
            obs.flight_dump("hang_detected", **fields)
        except Exception:
            pass

    def _note_near_miss(self, name: str, limit: float,
                        elapsed: float) -> None:
        """A phase finished past :data:`NEAR_MISS_FRACTION` of its
        deadline (ISSUE 14): count it, arm a rate-limited deep capture
        while the near-hanging program is still resident, and journal
        + flight-dump the context (the satellite — a capture always
        has its flight window). The HEAVY evidence (journal line,
        fsync'd dump) is rate-limited — a steady-state phase at 85% of
        its deadline near-misses every occurrence, and the watchdog
        must observe that, not fsync per step: with a capture engine
        armed, its limiter decides (a suppressed fire suppresses the
        dump); unarmed, a per-phase monotonic throttle does."""
        # Same locking as _note_overrun: any thread exiting a guarded
        # phase (serve worker, main loop) lands here concurrently.
        with self._lock:
            self.near_misses += 1
        fields = dict(phase=name, deadline_s=round(limit, 3),
                      elapsed_s=round(elapsed, 3),
                      frac=round(elapsed / limit, 3))
        try:
            obs.counter("resilience.near_misses_total").add(1)
        except Exception:
            pass
        armed = False
        bundle = None
        try:
            from fm_spark_tpu.obs import introspect

            armed = introspect.active()
            if armed:
                bundle = introspect.fire("watchdog_near_miss", **fields)
        except Exception:
            pass
        if armed and bundle is None:
            return  # the engine's rate limiter suppressed this one
        if not armed:
            now = time.monotonic()
            with self._lock:
                last = self._last_near_dump.get(name)
                if last is not None and \
                        now - last < NEAR_MISS_DUMP_INTERVAL_S:
                    return
                self._last_near_dump[name] = now
        if self.journal is not None:
            try:
                self.journal.emit("watchdog_near_miss", **fields)
            except Exception:
                pass
        try:
            obs.event("watchdog_near_miss", **fields)
            obs.flight_dump("watchdog_near_miss", **fields)
        except Exception:
            pass

    def _watch(self) -> None:
        while not self._stop.wait(self._poll_s):
            now = time.monotonic()
            fired = None
            with self._lock:
                for name, t0, deadline in self._armed.values():
                    if now > deadline:
                        fired = (name, deadline - t0, now - t0)
                        break
            if fired is None:
                continue
            # The hung thread is still stuck inside the phase: dump the
            # evidence from here, then hard-exit — a detected, bounded,
            # journaled ending instead of an eternal hang.
            self._note_overrun(*fired)
            self._exit(self.exit_rc)
            return  # test doubles for _exit return instead of dying

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            self._armed.clear()
            monitor = self._monitor
            self._monitor = None
        if monitor is not None:
            # Joined on the shutdown path (ISSUE 15 thread-lifecycle
            # audit): daemon or not, a monitor left spinning between
            # configure() cycles leaks one poll thread per table.
            monitor.join(timeout=5.0)


# Module state, faults.py-style: None = env not looked at yet; False =
# looked, nothing configured (phase() stays one comparison); else the
# active table.
_table: WatchdogTable | None | bool = None


def configure(deadlines: dict[str, float] | str,
              action: str = "raise", journal=None,
              **kw) -> WatchdogTable:
    """Install a watchdog table in-process (chaos drills/tests); a
    string is parsed with the :data:`ENV_SPEC` grammar."""
    global _table
    if isinstance(deadlines, str):
        deadlines = parse_spec(deadlines)
    clear()
    _table = WatchdogTable(deadlines, action=action, journal=journal,
                           **kw)
    return _table


def clear() -> None:
    """Drop the active table AND forget the env lookup, so a later
    :func:`phase` re-reads the environment (test isolation)."""
    global _table
    if isinstance(_table, WatchdogTable):
        _table.close()
    _table = None


def _load_env() -> "WatchdogTable | bool":
    spec = os.environ.get(ENV_SPEC, "").strip()
    if not spec:
        return False
    action = os.environ.get(ENV_ACTION, "exit").strip() or "exit"
    return WatchdogTable(parse_spec(spec), action=action)


def phase(name: str):
    """The production hook: a deadline-armed context manager for
    ``name``, or the shared no-op when unconfigured / not budgeted."""
    global _table
    t = _table
    if t is None:
        t = _table = _load_env()
    if t is False:
        return _NOOP
    return t.phase(name)


def active(name: str | None = None) -> bool:
    """Is a watchdog configured (optionally: with a budget for
    ``name``)? Cheap enough to latch outside hot loops."""
    global _table
    t = _table
    if t is None:
        t = _table = _load_env()
    if t is False:
        return False
    return True if name is None else name in t.deadlines
