"""Deterministic network-fault plane for the serving fleet (ISSUE 19).

The PR-17 fleet is only robust to faults the process model can express:
replicas die and the parent notices. Real fleets fail at the NETWORK —
partitions, slow links, half-open connections, truncated responses —
and none of those kill a process. This module makes them injectable,
deterministically, at the parent's single transport seam
(serve/fleet.py's :class:`ConnectionPool` + ``_http_json``, which
dispatch, the health poller, and the metrics scraper all route
through), using the SAME plan grammar, env vars, and cross-process
occurrence counters as :mod:`fm_spark_tpu.resilience.faults`.

Points (registered in ``faults.KNOWN_POINTS``) and their actions::

    net_connect     per TCP dial           refuse | blackhole[:cap_s]
    net_send        per request write      | slow_ms:N | reset
    net_recv        per response read      | truncate_after:K (recv)

- ``refuse``          ConnectionRefusedError (connect) / reset (send)
- ``reset``           ConnectionResetError at that phase
- ``blackhole``       sleep min(caller timeout, cap) then time out —
                      packets into the void, the partition primitive
- ``slow_ms:N``       add N ms of link latency, then proceed
- ``truncate_after:K`` deliver only the first K response-body bytes,
                      then kill the connection (``net_recv`` only —
                      on ``net_send``/``net_connect`` it degrades to
                      ``reset``: a half-written request is a dead
                      connection the server never parsed)

Peer scoping: ``net_connect.replica-1@1-8=refuse`` fires only on
transport to the peer labeled ``replica-1`` (its own occurrence
counter), so a schedule can partition the parent away from ONE replica
— which stays healthy and must be suspected -> drained -> readmitted,
never respawn-killed. Unscoped rules count occurrences fleet-wide.
Occurrence ranges (``@first-last=``) make a bounded partition window
one rule; after the window the link heals by construction.

Phase discipline (the exactly-once contract, ISSUE 19 satellite):
``net_connect``/``net_send`` faults strike BEFORE the request reached
the replica — retrying elsewhere is safe. ``net_recv`` faults strike
AFTER the replica may have executed; ``_http_json`` classifies them via
:class:`TransportFailure` and a failure after response bytes arrived is
never replayed on another replica.
"""

from __future__ import annotations

import http.client
import socket
import threading
import time

from fm_spark_tpu.resilience import faults
from fm_spark_tpu.utils import sleeps

__all__ = [
    "BLACKHOLE_CAP_S",
    "FaultyHTTPConnection",
    "TransportFailure",
    "check",
    "on_connect",
    "on_recv",
    "on_send",
]

#: Default ceiling on a blackhole's sleep (scaled by
#: ``FM_SPARK_TEST_SLEEP_SCALE``): a blackhole emulates "packets
#: vanish until the caller's timeout", and the sleep is bounded by
#: min(caller timeout, cap) so a drill never waits minutes to prove a
#: timeout fired.
BLACKHOLE_CAP_S = 5.0

#: In-process occurrence counting is shared across the health thread
#: and every dispatch thread; faults' in-proc counter dict is not
#: locked (its points fire from one thread each), so the net plane
#: serializes its own counter consumption.
_count_lock = threading.Lock()


class TransportFailure(OSError):
    """A classified replica-transport failure (ISSUE 19 satellite).

    ``phase`` is where the underlying failure struck — ``connect``
    (dial), ``send`` (request write), or ``recv`` (response read) —
    and ``bytes_received`` is > 0 once any response bytes (status
    line/headers/body) arrived. :attr:`retry_safe` is the exactly-once
    gate: a connect/send failure means the replica never saw the
    request; a recv failure with zero bytes means it died before
    answering (the PR-17 kill-mid-burst semantics); a recv failure
    AFTER response bytes arrived means the replica executed and
    answered — replaying that request on another replica would score
    it twice.
    """

    def __init__(self, message: str, *, phase: str,
                 bytes_received: int = 0):
        super().__init__(message)
        self.phase = phase
        self.bytes_received = int(bytes_received)

    @property
    def retry_safe(self) -> bool:
        return self.phase != "recv" or self.bytes_received == 0


def check(point: str, peer: "str | None" = None):
    """The matching rule for this transport event, or None.

    Consults the ACTIVE faults plan (env or ``faults.activate``).
    A peer-scoped rule set (``point.peer``) is consulted first with
    its own occurrence counter; the unscoped point counts fleet-wide.
    Both counters only advance when the plan names their key — an
    inactive plane is one ``is None`` check, same as ``inject``.
    """
    plan = faults.current_plan()
    if plan is None:
        return None
    scoped = unscoped = None
    with _count_lock:
        # Both counters advance on every event their key is planned
        # for — "this peer's Nth dial" and "the fleet's Nth dial"
        # stay independently meaningful; the peer-scoped rule wins
        # when both match.
        if peer is not None:
            key = f"{point}.{peer}"
            if key in plan.points:
                scoped = plan.rule_for(key, faults._next_count(key))
        if point in plan.points:
            unscoped = plan.rule_for(point, faults._next_count(point))
    return scoped if scoped is not None else unscoped


def _strike(rule, phase: str, timeout_s: "float | None") -> "int | None":
    """Take a rule's action at a transport phase. Raises the
    socket-level error the action emulates, sleeps for latency
    actions, or returns a byte budget for ``truncate_after`` on recv
    (the caller owns the response bytes to truncate). Non-net actions
    (``sleep``/``error``/``exit``...) fall through to the generic
    :meth:`faults._Rule.fire`."""
    a = rule.action
    where = f"{rule.point}#{rule.occurrence}"
    if a == "refuse":
        if phase == "connect":
            raise ConnectionRefusedError(
                f"[netfault] connection refused ({where})")
        raise ConnectionResetError(
            f"[netfault] connection refused mid-{phase} ({where})")
    if a == "reset":
        raise ConnectionResetError(
            f"[netfault] connection reset during {phase} ({where})")
    if a == "blackhole":
        cap = sleeps.scaled(float(rule.param)
                            if rule.param else BLACKHOLE_CAP_S)
        time.sleep(min(timeout_s, cap)
                   if timeout_s is not None else cap)
        raise socket.timeout(
            f"[netfault] {phase} blackholed ({where})")
    if a == "slow_ms":
        time.sleep(float(rule.param) / 1e3)
        return None
    if a == "truncate_after":
        if phase == "recv":
            return int(rule.param)
        # A truncated dial/request is a connection the server never
        # parsed a full request from: dead, nothing executed.
        raise ConnectionResetError(
            f"[netfault] {phase} truncated ({where})")
    rule.fire(rule.occurrence)
    return None


def on_connect(peer: "str | None",
               timeout_s: "float | None" = None) -> None:
    """``net_connect`` — fires per TCP dial (pool fresh dials, the
    pool-less health/metrics probes)."""
    rule = check("net_connect", peer)
    if rule is not None:
        _strike(rule, "connect", timeout_s)


def on_send(peer: "str | None",
            timeout_s: "float | None" = None) -> None:
    """``net_send`` — fires per request write, BEFORE bytes leave.
    Every failure raised here is send-phase: the replica never saw
    the request, so a retry elsewhere is exactly-once safe."""
    rule = check("net_send", peer)
    if rule is not None:
        _strike(rule, "send", timeout_s)


def on_recv(peer: "str | None",
            timeout_s: "float | None" = None) -> "int | None":
    """``net_recv`` — fires per response read. Returns a byte budget
    when the rule is ``truncate_after:K`` (the caller delivers only K
    body bytes then treats the connection as dead); raises the
    emulated socket error otherwise."""
    rule = check("net_recv", peer)
    if rule is None:
        return None
    return _strike(rule, "recv", timeout_s)


class FaultyHTTPConnection(http.client.HTTPConnection):
    """An ``http.client.HTTPConnection`` whose dial routes through the
    fault plane — THE sanctioned way to open a replica connection from
    serve code (fmlint's ``fleet-transport-discipline`` rule bans raw
    connects in ``fm_spark_tpu/serve/`` precisely so a partition
    schedule can reach every transport path)."""

    def __init__(self, host: str, port: int, *,
                 peer: "str | None" = None, timeout=None):
        if timeout is None:
            super().__init__(host, port)
        else:
            super().__init__(host, port, timeout=timeout)
        self.peer = peer

    def connect(self):
        on_connect(self.peer,
                   self.timeout if isinstance(self.timeout, (int, float))
                   else None)
        return super().connect()
