"""Deterministic storage-fault plane for the durable-write seam (ISSUE 20).

PR 19 made the network injectable; this module is its storage sibling.
Every durability claim the repo audits — checkpoint chain walk-back,
atomic tombstone demotion, ledger/sentinel history, flight-spool
seq-continuity, the embed cold store, quarantine dead-letters — assumed
the filesystem never fails. In production the disk fails MORE often
than the network: ENOSPC mid-commit, EIO on an append, torn renames,
multi-second fsync stalls. This module makes exactly those failures
injectable, deterministically, at the single durable-write seam
(:mod:`fm_spark_tpu.utils.durable`, which checkpoint manifests /
tombstones / ``last_good``, the obs ledger + flight spool + EventLog
journals, the embed cold-store write-back, the quarantine dead-letter
path, and the compile-cache breadcrumb all route through), using the
SAME plan grammar, env vars, and occurrence counters as
:mod:`fm_spark_tpu.resilience.faults`.

Points (registered in ``faults.KNOWN_POINTS``) and their actions::

    io_write    per durable payload write    eio | enospc | readonly
    io_fsync    per file/dir fsync           | torn_write:K | slow_ms:N
    io_rename   per atomic rename publish
    io_read     per durable read

- ``eio``          OSError(EIO) — a failing append / write / read
- ``enospc``       OSError(ENOSPC) — disk full at that phase
- ``readonly``     OSError(EROFS) — the filesystem flipped read-only
- ``torn_write:K`` write only the first K bytes, then EIO (the torn
                   write/short read primitive; on ``io_read`` it is a
                   short read — deliver K bytes then stop; on
                   ``io_rename``/``io_fsync`` it degrades to ``eio``:
                   a torn publish is a failed publish)
- ``slow_ms:N``    add N ms of disk latency, then proceed (scaled by
                   ``FM_SPARK_TEST_SLEEP_SCALE`` so slow-disk drills
                   stay inside the tier-1 wall clock)

Path-class scoping: ``io_write.ckpt@1-8=enospc`` fires only on writes
whose durable call site declared the ``ckpt`` class (its own occurrence
counter), so a schedule can fail ONLY checkpoint commits while the obs
plane keeps writing — or fail ONLY observability and prove training
bytes are unchanged. Unscoped rules count occurrences disk-wide.
Classes in use: ``ckpt``, ``obs``, ``embed``, ``cache``, ``quarantine``
(:data:`PATH_CLASSES`, canonically ``faults.IO_PATH_CLASSES``). Unlike
net peer scopes (free-form replica names), the class vocabulary is
CLOSED — a typo'd class would be a plan that silently never fires, so
``faults.FaultPlan.from_spec`` rejects unknown classes eagerly.

Tier discipline lives in :mod:`fm_spark_tpu.utils.durable`, not here:
this module only decides WHETHER a given disk event fails and HOW; the
seam decides what a failure means (best-effort obs degradation vs
fail-loud checkpoint retry).
"""

from __future__ import annotations

import errno
import threading
import time

from fm_spark_tpu.resilience import faults
from fm_spark_tpu.utils import sleeps

__all__ = [
    "PATH_CLASSES",
    "check",
    "on_fsync",
    "on_read",
    "on_rename",
    "on_write",
]

#: The path-class vocabulary durable call sites declare (scoping keys
#: like ``io_write.ckpt``). Closed set, validated eagerly by
#: ``faults.FaultPlan.from_spec`` — see module docstring.
PATH_CLASSES = faults.IO_PATH_CLASSES

#: Occurrence counting is shared across the checkpoint writer thread,
#: obs emitters, and any drill thread; faults' in-proc counter dict is
#: not locked (its points fire from one thread each), so the storage
#: plane serializes its own counter consumption — same policy as
#: netfaults.
_count_lock = threading.Lock()


def check(point: str, path_class: "str | None" = None):
    """The matching rule for this disk event, or None.

    Consults the ACTIVE faults plan (env or ``faults.activate``).
    A class-scoped rule set (``point.class``) is consulted first with
    its own occurrence counter; the unscoped point counts disk-wide.
    Both counters only advance when the plan names their key — an
    inactive plane is one ``is None`` check, same as ``inject``.
    """
    plan = faults.current_plan()
    if plan is None:
        return None
    scoped = unscoped = None
    with _count_lock:
        # Both counters advance on every event their key is planned
        # for — "this class's Nth write" and "the disk's Nth write"
        # stay independently meaningful; the class-scoped rule wins
        # when both match.
        if path_class is not None:
            key = f"{point}.{path_class}"
            if key in plan.points:
                scoped = plan.rule_for(key, faults._next_count(key))
        if point in plan.points:
            unscoped = plan.rule_for(point, faults._next_count(point))
    return scoped if scoped is not None else unscoped


def _strike(rule, phase: str) -> "int | None":
    """Take a rule's action at a disk phase. Raises the ``OSError`` the
    action emulates, sleeps for latency actions, or returns a byte
    budget for ``torn_write`` on write/read (the caller owns the bytes
    to tear). Non-io actions (``sleep``/``error``/``exit``...) fall
    through to the generic :meth:`faults._Rule.fire`."""
    a = rule.action
    where = f"{rule.point}#{rule.occurrence}"
    if a == "eio":
        raise OSError(errno.EIO,
                      f"[iofault] I/O error during {phase} ({where})")
    if a == "enospc":
        raise OSError(errno.ENOSPC,
                      f"[iofault] no space left during {phase} ({where})")
    if a == "readonly":
        raise OSError(errno.EROFS,
                      f"[iofault] read-only file system at {phase} "
                      f"({where})")
    if a == "slow_ms":
        # Designed sleep: a slow-disk drill proves latency TOLERANCE,
        # not latency itself — FM_SPARK_TEST_SLEEP_SCALE applies
        # (ISSUE 20 satellite).
        time.sleep(sleeps.scaled(float(rule.param) / 1e3))
        return None
    if a == "torn_write":
        if phase in ("write", "read"):
            return int(rule.param)
        # A torn rename/fsync has no partial-byte semantics: the
        # publish simply failed.
        raise OSError(errno.EIO,
                      f"[iofault] {phase} torn ({where})")
    rule.fire(rule.occurrence)
    return None


def on_write(path_class: "str | None" = None) -> "int | None":
    """``io_write`` — fires per durable payload write. Returns a byte
    budget when the rule is ``torn_write:K`` (the caller writes only
    the first K bytes then raises EIO — the crash-consistency
    primitive); raises the emulated ``OSError`` otherwise."""
    rule = check("io_write", path_class)
    if rule is None:
        return None
    return _strike(rule, "write")


def on_fsync(path_class: "str | None" = None) -> None:
    """``io_fsync`` — fires per file/directory fsync (the stall
    point of real disks)."""
    rule = check("io_fsync", path_class)
    if rule is not None:
        _strike(rule, "fsync")


def on_rename(path_class: "str | None" = None) -> None:
    """``io_rename`` — fires per atomic rename publish
    (``os.replace`` of tmp onto final). A failure here strikes AFTER
    the payload is durable but BEFORE it is visible — the exact window
    torn-publish drills need."""
    rule = check("io_rename", path_class)
    if rule is not None:
        _strike(rule, "rename")


def on_read(path_class: "str | None" = None) -> "int | None":
    """``io_read`` — fires per durable read. Returns a byte budget
    when the rule is ``torn_write:K`` (deliver only K bytes — a short
    read the verify-then-walk-back tier must survive); raises the
    emulated ``OSError`` otherwise."""
    rule = check("io_read", path_class)
    if rule is None:
        return None
    return _strike(rule, "read")
