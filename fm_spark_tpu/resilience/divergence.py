"""Divergence guard: NaN/Inf and loss-spike detection with rollback.

A numeric blowup is the third run-killer this subsystem covers (after
transient flaps and permanent device loss): one bad batch or an
optimizer excursion turns the loss to NaN, the NaN writes into the
tables on the very next step, and every checkpoint from then on
snapshots poisoned state — by the time a human reads the metrics, the
run is unsalvageable. The guard makes that cost ONE CHECKPOINT WINDOW:

- :meth:`DivergenceGuard.check` watches every fetched training loss.
  Non-finite is divergence, full stop. A finite loss is a SPIKE when it
  exceeds ``spike_factor`` × the median of the trailing window (the
  median is robust to the window itself containing the start of the
  blowup; no trigger until ``min_history`` losses are banked, so warmup
  noise cannot fire it).
- On detection it raises :class:`DivergenceDetected`;
  ``FMTrainer.fit`` catches it BEFORE the step's state can reach a
  checkpoint, restores ``last_good`` (the crash-consistent chain,
  checkpoint.py), and resumes with a REDUCED STEP BUDGET — the run now
  targets the last step before the spike. Deterministic pipelines
  replay the same batches, so retrying through the same poison batch
  would diverge identically forever; stopping just short converts a
  blowup into a complete, slightly-shorter run with verified-good
  final state (the loss at the restored step is bit-identical to the
  pre-spike value, by the same replay contract as kill-and-resume).
- ``max_rollbacks`` bounds the policy: a loss landscape that keeps
  spiking at new places is a modeling problem, not a robustness one,
  and propagates after the budget is spent.

Maximize mode (ISSUE 13): the same trailing-median machinery watches a
HIGHER-IS-BETTER metric — the online protocol's day-over-day eval AUC —
with ``mode="max"``: detection fires when a finite value DROPS below
``trailing median / spike_factor`` (the mirror of the loss-spike test;
``spike_factor`` is sized near 1 for AUC, e.g. 1.1 ≈ a 9% relative
drop). The ``min_history`` floor applies in both directions, so a short
eval series — the first days of an online run — can never trip the
spike/drop test; only non-finite values are unconditional. This is the
concept-drift sentry: the trainer did not blow up, the WORLD changed
under it, and the verdict routes into the same rollback budget.

Every decision is journaled through
:class:`~fm_spark_tpu.utils.logging.EventLog` (``divergence_detected``
/ ``divergence_rollback``) — the lint in tools/resilience_lint.py holds
this module to the same no-bare-print contract as the rest of the
subsystem.
"""

from __future__ import annotations

import math
from collections import deque

__all__ = ["DivergenceDetected", "DivergenceGuard"]


class DivergenceDetected(RuntimeError):
    """Raised by :meth:`DivergenceGuard.check` at the first diverged
    loss; carries the step and value so the rollback can journal them
    and truncate the resumed budget to ``step - 1``."""

    def __init__(self, step: int, loss: float, reason: str):
        super().__init__(
            f"divergence at step {step}: loss={loss!r} ({reason})"
        )
        self.step = int(step)
        self.loss = float(loss)
        self.reason = reason


class DivergenceGuard:
    """Opt-in training-loop monitor (see module docstring).

    ``spike_factor``: a finite loss > factor × trailing-median is a
    spike (``mode="min"``, the default); with ``mode="max"`` (a
    higher-is-better metric, e.g. eval AUC) a finite value < trailing
    median ÷ factor is a DROP — the concept-drift direction.
    ``window``/``min_history``: trailing-median shape; no verdict of
    either direction before ``min_history`` values are banked. On
    detection :meth:`check` raises; the trainer calls
    :meth:`note_rollback` once per recovery — it returns the truncated
    step target and raises the original detection when the rollback
    budget is spent.
    """

    def __init__(self, spike_factor: float = 10.0, window: int = 16,
                 min_history: int = 3, max_rollbacks: int = 2,
                 journal=None, mode: str = "min"):
        if spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {spike_factor}"
            )
        if mode not in ("min", "max"):
            raise ValueError(
                f"mode must be 'min' (lower-is-better, loss) or 'max' "
                f"(higher-is-better, AUC), got {mode!r}"
            )
        self.spike_factor = float(spike_factor)
        self.mode = mode
        self.min_history = max(int(min_history), 1)
        self.max_rollbacks = int(max_rollbacks)
        self.journal = journal
        self.rollbacks = 0
        self._recent: deque[float] = deque(maxlen=max(int(window), 2))

    def _emit(self, event: str, **fields) -> None:
        if self.journal is not None:
            self.journal.emit(event, **fields)

    def _baseline(self) -> float | None:
        if len(self._recent) < self.min_history:
            return None
        ordered = sorted(self._recent)
        return ordered[len(ordered) // 2]

    def baseline(self) -> float | None:
        """The current trailing median (None until ``min_history``
        values are banked) — exposed for the drift-score gauge the
        online loop publishes alongside each verdict."""
        return self._baseline()

    def history(self) -> list[float]:
        """The banked trailing window, oldest first — the durable half
        of the sentry's state: the online loop persists it in each
        checkpoint's ``extra`` so a killed-and-resumed run re-seeds
        the window and its drift verdicts replay exactly."""
        return list(self._recent)

    def seed_history(self, values) -> None:
        """Re-seed the trailing window from a checkpoint (see
        :meth:`history`); replaces whatever was banked."""
        self._recent.clear()
        for v in values:
            self._recent.append(float(v))

    def check(self, step: int, loss: float) -> None:
        """Bank a healthy loss, or raise :class:`DivergenceDetected`.

        Call with every fetched loss BEFORE it can be logged or reach a
        checkpoint snapshot — the poisoned step's state must never be
        savable.
        """
        loss = float(loss)
        reason = None
        if not math.isfinite(loss):
            reason = ("non-finite loss" if self.mode == "min"
                      else "non-finite metric")
        else:
            baseline = self._baseline()
            if baseline is not None and self.mode == "min" and (
                    loss > self.spike_factor * max(baseline, 1e-12)):
                reason = (f"loss spike: {loss:.6g} > {self.spike_factor}x "
                          f"trailing median {baseline:.6g}")
            elif (baseline is not None and self.mode == "max"
                    and baseline > 0
                    and loss < baseline / self.spike_factor):
                # The drift direction: the metric is higher-is-better
                # and fell past the mirrored factor of its own trailing
                # median — the world moved, not the optimizer.
                reason = (f"metric drop: {loss:.6g} < trailing median "
                          f"{baseline:.6g} / {self.spike_factor}")
        if reason is not None:
            self._emit("divergence_detected", step=step, loss=repr(loss),
                       reason=reason, rollbacks=self.rollbacks,
                       mode=self.mode)
            raise DivergenceDetected(step, loss, reason)
        self._recent.append(loss)

    def note_rollback(self, detected: DivergenceDetected,
                      restored_step: int) -> int:
        """Account one rollback; returns the reduced step target (stop
        just before the diverging step). Re-raises the detection when
        ``max_rollbacks`` is exhausted. Clears the trailing window — the
        replayed losses re-bank from the restored point."""
        if self.rollbacks >= self.max_rollbacks:
            self._emit("divergence_rollback_exhausted",
                       step=detected.step, rollbacks=self.rollbacks)
            raise detected
        self.rollbacks += 1
        self._recent.clear()
        target = max(detected.step - 1, int(restored_step))
        self._emit("divergence_rollback", step=detected.step,
                   restored_step=int(restored_step),
                   reduced_target=target, rollbacks=self.rollbacks)
        return target
