"""Divergence guard: NaN/Inf and loss-spike detection with rollback.

A numeric blowup is the third run-killer this subsystem covers (after
transient flaps and permanent device loss): one bad batch or an
optimizer excursion turns the loss to NaN, the NaN writes into the
tables on the very next step, and every checkpoint from then on
snapshots poisoned state — by the time a human reads the metrics, the
run is unsalvageable. The guard makes that cost ONE CHECKPOINT WINDOW:

- :meth:`DivergenceGuard.check` watches every fetched training loss.
  Non-finite is divergence, full stop. A finite loss is a SPIKE when it
  exceeds ``spike_factor`` × the median of the trailing window (the
  median is robust to the window itself containing the start of the
  blowup; no trigger until ``min_history`` losses are banked, so warmup
  noise cannot fire it).
- On detection it raises :class:`DivergenceDetected`;
  ``FMTrainer.fit`` catches it BEFORE the step's state can reach a
  checkpoint, restores ``last_good`` (the crash-consistent chain,
  checkpoint.py), and resumes with a REDUCED STEP BUDGET — the run now
  targets the last step before the spike. Deterministic pipelines
  replay the same batches, so retrying through the same poison batch
  would diverge identically forever; stopping just short converts a
  blowup into a complete, slightly-shorter run with verified-good
  final state (the loss at the restored step is bit-identical to the
  pre-spike value, by the same replay contract as kill-and-resume).
- ``max_rollbacks`` bounds the policy: a loss landscape that keeps
  spiking at new places is a modeling problem, not a robustness one,
  and propagates after the budget is spent.

Every decision is journaled through
:class:`~fm_spark_tpu.utils.logging.EventLog` (``divergence_detected``
/ ``divergence_rollback``) — the lint in tools/resilience_lint.py holds
this module to the same no-bare-print contract as the rest of the
subsystem.
"""

from __future__ import annotations

import math
from collections import deque

__all__ = ["DivergenceDetected", "DivergenceGuard"]


class DivergenceDetected(RuntimeError):
    """Raised by :meth:`DivergenceGuard.check` at the first diverged
    loss; carries the step and value so the rollback can journal them
    and truncate the resumed budget to ``step - 1``."""

    def __init__(self, step: int, loss: float, reason: str):
        super().__init__(
            f"divergence at step {step}: loss={loss!r} ({reason})"
        )
        self.step = int(step)
        self.loss = float(loss)
        self.reason = reason


class DivergenceGuard:
    """Opt-in training-loop monitor (see module docstring).

    ``spike_factor``: a finite loss > factor × trailing-median is a
    spike. ``window``/``min_history``: trailing-median shape. On
    detection :meth:`check` raises; the trainer calls
    :meth:`note_rollback` once per recovery — it returns the truncated
    step target and raises the original detection when the rollback
    budget is spent.
    """

    def __init__(self, spike_factor: float = 10.0, window: int = 16,
                 min_history: int = 3, max_rollbacks: int = 2,
                 journal=None):
        if spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {spike_factor}"
            )
        self.spike_factor = float(spike_factor)
        self.min_history = max(int(min_history), 1)
        self.max_rollbacks = int(max_rollbacks)
        self.journal = journal
        self.rollbacks = 0
        self._recent: deque[float] = deque(maxlen=max(int(window), 2))

    def _emit(self, event: str, **fields) -> None:
        if self.journal is not None:
            self.journal.emit(event, **fields)

    def _baseline(self) -> float | None:
        if len(self._recent) < self.min_history:
            return None
        ordered = sorted(self._recent)
        return ordered[len(ordered) // 2]

    def check(self, step: int, loss: float) -> None:
        """Bank a healthy loss, or raise :class:`DivergenceDetected`.

        Call with every fetched loss BEFORE it can be logged or reach a
        checkpoint snapshot — the poisoned step's state must never be
        savable.
        """
        loss = float(loss)
        reason = None
        if not math.isfinite(loss):
            reason = "non-finite loss"
        else:
            baseline = self._baseline()
            if baseline is not None and loss > self.spike_factor * max(
                    baseline, 1e-12):
                reason = (f"loss spike: {loss:.6g} > {self.spike_factor}x "
                          f"trailing median {baseline:.6g}")
        if reason is not None:
            self._emit("divergence_detected", step=step, loss=repr(loss),
                       reason=reason, rollbacks=self.rollbacks)
            raise DivergenceDetected(step, loss, reason)
        self._recent.append(loss)

    def note_rollback(self, detected: DivergenceDetected,
                      restored_step: int) -> int:
        """Account one rollback; returns the reduced step target (stop
        just before the diverging step). Re-raises the detection when
        ``max_rollbacks`` is exhausted. Clears the trailing window — the
        replayed losses re-bank from the restored point."""
        if self.rollbacks >= self.max_rollbacks:
            self._emit("divergence_rollback_exhausted",
                       step=detected.step, rollbacks=self.rollbacks)
            raise detected
        self.rollbacks += 1
        self._recent.clear()
        target = max(detected.step - 1, int(restored_step))
        self._emit("divergence_rollback", step=detected.step,
                   restored_step=int(restored_step),
                   reduced_target=target, rollbacks=self.rollbacks)
        return target
