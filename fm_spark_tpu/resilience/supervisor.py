"""The retry/timeout/backoff state machine for a flaky device attachment.

What used to be ad-hoc (bench.py's hand-rolled parent retry loop,
tpu_watch.sh's inlined bash backoff) is here one tested object:

- **Bounded exponential backoff + deterministic jitter**
  (:class:`BackoffPolicy`): delay doubles per consecutive failure, is
  capped, and jitters by a seeded RNG — reproducible in tests, never
  synchronized across restarts in production.
- **Cheap health probe** (:func:`device_probe`): device enumeration in a
  watchdog thread — on this attachment a dead backend HANGS
  ``jax.devices()`` rather than raising, so the probe times out instead
  of trusting an exception to arrive.
- **Circuit breaker**: after N consecutive failed operations the
  supervisor stops burning the deadline on a known-dead attachment and
  raises :class:`CircuitOpen`; a later healthy probe half-opens it for
  one trial.
- **Health-event journal**: every transition is emitted to a JSONL
  :class:`~fm_spark_tpu.utils.logging.EventLog`, so a degraded round
  leaves a machine-readable account of WHAT flapped and what the
  supervisor did about it.

Two entry points: :meth:`Supervisor.run` wraps a whole retryable
operation (a bench sweep leg); :meth:`Supervisor.recover` is the
incremental form for callers that own their loop (``FMTrainer.fit``
catches the device loss itself, then asks the supervisor to account /
probe / back off before it rebuilds state from the checkpoint).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

from fm_spark_tpu import obs
from fm_spark_tpu.resilience import faults
from fm_spark_tpu.resilience.faults import is_device_loss

__all__ = [
    "BackoffPolicy",
    "CircuitOpen",
    "RetriesExhausted",
    "Supervisor",
    "device_probe",
]


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Bounded exponential backoff: ``initial * multiplier**(k-1)``
    seconds after the k-th consecutive failure, capped at ``max_delay``,
    jittered by ±``jitter`` fraction (seeded RNG — deterministic in
    tests). ``max_attempts`` bounds one :meth:`Supervisor.run` call."""

    initial: float = 1.0
    multiplier: float = 2.0
    max_delay: float = 60.0
    jitter: float = 0.1
    max_attempts: int = 4

    def delay(self, failure_index: int, rng: random.Random | None = None
              ) -> float:
        from fm_spark_tpu.utils.sleeps import sleep_scale

        d = min(
            self.initial * self.multiplier ** max(failure_index - 1, 0),
            self.max_delay,
        ) * sleep_scale()  # designed sleep: FM_SPARK_TEST_SLEEP_SCALE
        if self.jitter and rng is not None:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(d, 0.0)


class RetriesExhausted(RuntimeError):
    """One operation failed ``max_attempts`` times; the last underlying
    exception rides as ``__cause__``."""


class CircuitOpen(RuntimeError):
    """The breaker tripped: N consecutive operations failed and the
    probe still reports the attachment unhealthy — stop retrying and
    degrade (salvage what completed) instead of burning the deadline."""


def device_probe(timeout: float = 30.0) -> bool:
    """Cheap attachment health probe: device enumeration under a
    thread-join timeout. A healthy backend answers in well under a
    second; a dead attachment HANGS the call (the observed mode), which
    the join timeout converts into ``False`` instead of a stuck
    process. The ``probe`` fault point makes the outcome injectable."""
    out: dict = {}

    def _enumerate():
        try:
            faults.inject("probe")
            import jax

            out["n"] = len(jax.devices())
        except Exception:
            out["n"] = 0

    t = threading.Thread(target=_enumerate, daemon=True)
    t.start()
    t.join(timeout)
    return bool(out.get("n"))


class Supervisor:
    """Retry/backoff/circuit-breaker runtime around device-touching work.

    State machine: ``closed`` (normal) → ``open`` after
    ``breaker_threshold`` consecutive failed operations → ``half_open``
    when a probe reports the attachment healthy again → ``closed`` on
    the next success. Every transition and retry is journaled.

    ``probe``/``sleep`` are injectable so the whole machine unit-tests
    without a device or wall-clock (tests/test_resilience.py — the
    fault-matrix suite).
    """

    def __init__(self, policy: BackoffPolicy | None = None, journal=None,
                 probe=None, probe_timeout: float = 30.0,
                 breaker_threshold: int = 3, seed: int = 0,
                 sleep=time.sleep):
        self.policy = policy or BackoffPolicy()
        self.journal = journal
        self.probe_timeout = probe_timeout
        self.breaker_threshold = breaker_threshold
        self._set_state("closed")
        self.consecutive_failures = 0
        # Cumulative failure count across the supervisor's whole life —
        # unlike consecutive_failures it survives note_success/reset, so
        # a caller can DELTA it around one operation to learn whether
        # that operation saw weather (the per-leg attachment-health
        # verdict the perf ledger's fingerprints record, ISSUE 9).
        self.total_failures = 0
        # Identity tracking for the transient-vs-permanent verdict
        # (resilience/elastic.py): a run of IDENTICAL failures (numerals
        # normalized) is the signature of a dead attachment, not a flap.
        self.last_failure: str | None = None
        self.identical_failures = 0
        self._probe = probe
        self._sleep = sleep
        self._rng = random.Random(seed)

    # ------------------------------------------------------------ events

    _BREAKER_STATES = ("closed", "half_open", "open")

    def _emit(self, event: str, **fields) -> None:
        if self.journal is not None:
            self.journal.emit(event, **fields)
        # Telemetry side-channel (ISSUE 7): failure/backoff totals as
        # registry instruments, and a flight-recorder dump at the
        # TERMINAL verdicts — the evidence a dead attachment used to
        # destroy. Best-effort by the journal contract; the journal
        # itself (mirror_to_flight) carries the event into the last-N
        # ring. (The breaker-state gauge is set by _set_state, at the
        # transition — several events fire BEFORE their transition
        # lands, so sampling self.state here would latch stale values.)
        try:
            if event == "failure":
                self.total_failures += 1
                obs.counter("resilience.failures_total").add(1)
            elif event == "backoff":
                obs.counter("resilience.backoffs_total").add(1)
            elif event == "probe":
                obs.counter("resilience.probes_total").add(1)
                if not fields.get("healthy"):
                    obs.counter("resilience.probe_failures_total").add(1)
            if event in ("circuit_open", "permanent_fault"):
                obs.flight_dump(event, **{
                    k: v for k, v in fields.items() if k != "reason"})
        except Exception:
            pass

    def _set_state(self, state: str) -> None:
        """The ONLY writer of breaker state: keeps the registry gauge
        exactly in lockstep with every transition."""
        self.state = state
        try:
            obs.gauge("resilience.breaker_state").set(
                self._BREAKER_STATES.index(state))
        except Exception:
            pass

    @staticmethod
    def _describe(exc: BaseException) -> str:
        first = (str(exc).splitlines() or [""])[0]
        return f"{type(exc).__name__}: {first[:200]}"

    def _note_failure_identity(self, exc: BaseException) -> None:
        """Track runs of identical failures (the permanent-fault
        signature — elastic.classify_failures semantics)."""
        from fm_spark_tpu.resilience.elastic import normalize_failure

        desc = self._describe(exc)
        if (self.last_failure is not None
                and normalize_failure(desc)
                == normalize_failure(self.last_failure)):
            self.identical_failures += 1
        else:
            self.identical_failures = 1
        self.last_failure = desc

    def permanent(self, threshold: int | None = None) -> bool:
        """Is the current failure run classified PERMANENT — the same
        failure, ``threshold`` (default: ``breaker_threshold``) times in
        a row? The elastic controller's shrink trigger; a mixed failure
        run keeps the transient verdict (keep retrying/backing off)."""
        t = self.breaker_threshold if threshold is None else threshold
        return self.identical_failures >= max(t, 1)

    def health_verdict(self) -> str:
        """The attachment-health verdict this supervisor's journal
        currently supports — what the perf ledger stamps into a
        measurement's fingerprint (ISSUE 9): ``down`` when the breaker
        is open or the failure run classifies permanent, ``flaky``
        while a failure streak is live, else ``healthy``. Per-operation
        weather is the caller's delta over :attr:`total_failures`."""
        if self.state == "open" or self.permanent():
            return "down"
        if self.consecutive_failures:
            return "flaky"
        return "healthy"

    def reset(self, op: str = "op") -> None:
        """Re-arm the breaker after the caller changed the world (an
        elastic mesh shrink): the new, smaller gang deserves a fresh
        failure budget. Journaled — a silent reset would make the
        health journal's consecutive counts unexplainable."""
        self._emit("supervisor_reset", op=op,
                   after_failures=self.consecutive_failures)
        self.consecutive_failures = 0
        self.identical_failures = 0
        self.last_failure = None
        self._set_state("closed")

    # ------------------------------------------------------------- probe

    def probe(self) -> bool:
        """Run the health probe (injected or the default device
        enumeration); an exception counts as unhealthy."""
        fn = self._probe or (lambda: device_probe(self.probe_timeout))
        with obs.span("resilience/probe") as sp:
            try:
                healthy = bool(fn())
            except Exception:
                healthy = False
            sp.set(healthy=healthy)
        self._emit("probe", healthy=healthy)
        return healthy

    # ----------------------------------------------------------- breaker

    def _check_circuit(self, op: str) -> None:
        if self.state != "open":
            return
        if self.probe():
            self._set_state("half_open")
            self._emit("circuit_half_open", op=op)
            return
        self._emit("circuit_rejected", op=op)
        raise CircuitOpen(
            f"{op}: circuit open after {self.consecutive_failures} "
            "consecutive failed operations and an unhealthy probe"
        )

    def _note_op_failure(self, op: str) -> None:
        self.consecutive_failures += 1
        if (self.state != "open"
                and self.consecutive_failures >= self.breaker_threshold):
            self._set_state("open")
            self._emit("circuit_open", op=op,
                       consecutive_failures=self.consecutive_failures,
                       permanent=self.permanent())

    def note_success(self, op: str = "op") -> None:
        """Close the circuit and zero the consecutive-failure count
        (called automatically by :meth:`run`; loop owners call it after
        real post-recovery progress)."""
        if self.consecutive_failures or self.state != "closed":
            self._emit("recovered", op=op,
                       after_failures=self.consecutive_failures)
        self.consecutive_failures = 0
        self.identical_failures = 0
        self.last_failure = None
        self._set_state("closed")

    # --------------------------------------------------------- run/recover

    def run(self, fn, op: str = "op", retryable=is_device_loss):
        """Run ``fn()`` with up to ``policy.max_attempts`` tries.

        Only exceptions passing ``retryable`` (default:
        :func:`is_device_loss` — the subsystem's reason to exist) are
        retried; everything else propagates immediately, because
        retrying a program bug just re-crashes until the deadline.
        Exhaustion raises :class:`RetriesExhausted` and counts one
        operation failure toward the breaker.
        """
        self._check_circuit(op)
        last: BaseException | None = None
        for attempt in range(1, self.policy.max_attempts + 1):
            self._emit("attempt", op=op, attempt=attempt)
            try:
                result = fn()
            except Exception as e:  # noqa: BLE001 — classified below
                if not retryable(e):
                    self._emit("failure", op=op, attempt=attempt,
                               error=self._describe(e), retryable=False)
                    raise
                last = e
                # Drop the traceback NOW: its frames pin the failed
                # attempt's locals (multi-GB tables in a bench leg)
                # through the probe, the backoff sleep, and the next
                # attempt's fresh init — exactly the two-resident-sets
                # condition retries must avoid.
                last.__traceback__ = None
                self._note_failure_identity(e)
                self._emit("failure", op=op, attempt=attempt,
                           error=self._describe(e), retryable=True)
                if attempt == self.policy.max_attempts:
                    break
                if self.permanent():
                    # N identical consecutive failures: the attachment
                    # is DEAD, not flapping — re-probing and re-sleeping
                    # the remaining attempts only burns the deadline
                    # (the BENCH_r05 failure mode). Exhaust now; the
                    # elastic controller decides whether to shrink.
                    self._emit("permanent_fault", op=op,
                               identical_failures=self.identical_failures,
                               skipped_attempts=(self.policy.max_attempts
                                                 - attempt))
                    break
                healthy = self.probe()
                delay = self.policy.delay(attempt, self._rng)
                self._emit("backoff", op=op, attempt=attempt,
                           delay_s=round(delay, 3), healthy=healthy)
                with obs.span("resilience/backoff", op=op,
                              delay_s=round(delay, 3)):
                    self._sleep(delay)
            else:
                self.note_success(op)
                return result
        self._note_op_failure(op)
        raise RetriesExhausted(
            f"{op}: {self.policy.max_attempts} attempts failed "
            f"(last: {self._describe(last)})"
        ) from last

    def recover(self, op: str, exc: BaseException) -> None:
        """Account one caught device-loss failure for a caller that owns
        its retry loop (``FMTrainer.fit``): journal it, trip the breaker
        at the threshold (raises :class:`CircuitOpen` — training cannot
        make progress on an attachment that keeps dying), else probe and
        back off before the caller rebuilds from its checkpoint."""
        self.consecutive_failures += 1
        self._note_failure_identity(exc)
        self._emit("failure", op=op, error=self._describe(exc),
                   retryable=True,
                   consecutive_failures=self.consecutive_failures)
        if self.consecutive_failures >= self.breaker_threshold:
            self._set_state("open")
            self._emit("circuit_open", op=op,
                       consecutive_failures=self.consecutive_failures,
                       permanent=self.permanent())
            raise CircuitOpen(
                f"{op}: {self.consecutive_failures} consecutive device "
                "losses — escalating instead of thrashing the checkpoint"
            ) from exc
        # The probe and backoff below each carry their own span: this
        # is the wall-clock the trainer excludes from its throughput
        # window (logger.add_pause), so the spans make it attributable.
        healthy = self.probe()
        delay = self.policy.delay(self.consecutive_failures, self._rng)
        self._emit("backoff", op=op, delay_s=round(delay, 3),
                   healthy=healthy)
        with obs.span("resilience/backoff", op=op,
                      delay_s=round(delay, 3)):
            self._sleep(delay)
