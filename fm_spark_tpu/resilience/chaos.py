"""Chaos campaign engine: seeded multi-fault schedules, a system-wide
invariant auditor, and automatic schedule minimization (ISSUE 10).

The resilience stack (supervisor, elastic/checkpoint chain, exactly-once
ingest) was only ever exercised by hand-authored SINGLE-fault scenarios,
but production faults arrive in combinations — a device loss during a
checkpoint commit while the quarantine breaker's window is nearly full.
This module is the missing harness layer on top of
:mod:`fm_spark_tpu.resilience.faults`'s ``KNOWN_POINTS`` registry:

- :class:`ScheduleGenerator` — seeded sampling of multi-rule fault
  plans (the existing ``point@occurrence=action[:param]`` grammar),
  with scenario weights biased toward the nastiest interleavings:
  fault-during-recovery storms, faults inside the ``ckpt_commit``
  torn-save window, and corruption bursts pressed against the
  bad-record breaker window. Every schedule is a pure function of its
  seed — a verdict names the seed, and the seed replays the plan.

- :func:`run_schedule` — one short supervised training drill (the
  production ``FMTrainer.fit`` + ``StreamBatches`` + ``Checkpointer``
  + ``Supervisor`` stack, CPU-sized) executed under a schedule, with
  stubbed sleeps so a campaign costs compute, not wall-clock.
  :func:`write_worker` / the subprocess runner cover the
  process-fatal actions (``exit``/``sigterm``/never-returning hangs)
  plus cross-process occurrence counters via ``FM_SPARK_FAULTS_STATE``.

- :func:`audit` — the invariant auditor, judging from artifacts alone:
  exactly-once record stream (the drilled tap bit-identical to the
  clean run's, or to a pure-Python oracle for quarantine schedules),
  checkpoint-chain integrity (a fresh ``last_good`` walk-back must
  restore, never a torn state), loss continuity and final-state
  identity after every recovery, health-journal/flight monotonicity,
  hang liveness (the :mod:`~fm_spark_tpu.resilience.watchdog`
  verdicts), breaker-abort discipline, and quarantine accounting.

- :func:`minimize` — delta-debugs a failing schedule down to a minimal
  reproducible plan string (greedy ddmin over rules; every candidate
  re-runs the drill, so the minimal plan is *verified* failing).

- :func:`run_campaign` — N seeded schedules under a time budget,
  producing one machine-readable verdict dict (``tools/chaos_drill.py``
  writes it to ``artifacts/obs/<run_id>/chaos_verdict.json``;
  ``tools/run_doctor.py`` renders it). The tier-1 bounded soak in
  tests/test_chaos.py runs this deterministically every round.

The regression-canary hook (``DrillConfig.break_restore``) deliberately
breaks the resume path — restore stops rewinding the stream cursor — so
the suite can prove the auditor CATCHES a broken recovery and the
minimizer reduces the catch to a 1–2 rule plan.
"""

from __future__ import annotations

import dataclasses
import os
import random
import subprocess
import sys
import threading
import time
import zlib

from fm_spark_tpu.resilience import faults, watchdog
from fm_spark_tpu.utils.logging import EventLog, read_events

__all__ = [
    "DrillConfig",
    "DrillResult",
    "Schedule",
    "ScheduleGenerator",
    "audit",
    "audit_disk",
    "audit_fleet",
    "audit_serve_events",
    "build_shards",
    "disk_schedule",
    "fleet_schedule",
    "golden_run",
    "minimize",
    "oracle_tap",
    "partition_schedule",
    "run_campaign",
    "run_disk_campaign",
    "run_disk_schedule",
    "run_fleet_campaign",
    "run_fleet_schedule",
    "run_gc_kill_drill",
    "run_partition_campaign",
    "run_partition_schedule",
    "run_schedule",
    "serve_schedule",
    "write_worker",
]

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

#: Fault→watchdog phase mapping for hang scenarios.
_HANG_PHASE = {"ingest_truncate": "ingest_chunk",
               "ckpt_commit": "ckpt_commit",
               "train_step": "step_window"}

#: Hang drills: injected sleep vs armed deadline. The margin (6x over
#: the deadline, and the deadline 10x over a normal CPU step) keeps the
#: verdict deterministic on a loaded CI host.
_HANG_SLEEP_S = 0.3
_HANG_DEADLINE_S = 0.05


@dataclasses.dataclass(frozen=True)
class DrillConfig:
    """One drill's workload shape — small enough that a campaign of ~25
    schedules fits a tier-1 budget, big enough to cross three epochs,
    several checkpoint commits, and every recovery path."""

    steps: int = 18
    batch_size: int = 16
    num_features: int = 128
    rank: int = 4
    max_nnz: int = 3
    n_shards: int = 3
    rows_per_shard: int = 32
    chunk_bytes: int = 64
    save_every: int = 6
    seed: int = 7
    learning_rate: float = 0.1
    guard_window: int = 32
    guard_min_records: int = 16
    #: Regression canary (ISSUE 10 acceptance): when True, the drilled
    #: batch source's ``restore()`` no longer rewinds the stream cursor
    #: — the exact bug class the exactly-once invariant exists to
    #: catch. Never set outside canary tests/drills.
    break_restore: bool = False
    #: Subprocess drills only: the worker's flight-recorder ring size
    #: (small so the spool's 2N compaction threshold is reachable
    #: inside a short drill).
    flight_capacity: int = 256

    @property
    def total_rows(self) -> int:
        return self.n_shards * self.rows_per_shard


@dataclasses.dataclass(frozen=True)
class Schedule:
    """One seeded multi-fault plan plus the audit contract it carries.

    ``stream_comparable``: no rule consumes records, so the drilled tap
    must be bit-identical to the clean run's. ``oracle_comparable``:
    quarantine-only rules with no recovery — the tap must match the
    pure-Python :func:`oracle_tap`. ``expects`` is the outcome verdict
    the auditor holds the run to (``completed`` / ``hang_detected`` /
    ``ingest_aborted``).
    """

    seed: int
    scenario: str
    rules: tuple[str, ...]
    expects: str = "completed"
    stream_comparable: bool = True
    oracle_comparable: bool = False
    max_bad_frac: float = 1.0

    @property
    def plan(self) -> str:
        return ";".join(self.rules)

    def validate(self) -> "Schedule":
        faults.FaultPlan.from_spec(self.plan)  # eager registry check
        return self


class ScheduleGenerator:
    """Deterministic seeded sampler over multi-fault scenarios.

    ``schedule(seed)`` is a pure function of the seed: the same seed
    always yields the same plan, which is what makes a chaos verdict
    replayable ("seed 17 failed" IS the repro). Weights are biased
    toward the interleavings the single-fault suites never compose:

    ======================  ==============================================
    ``commit_loss``          device loss inside the ``ckpt_commit``
                             torn-save window (± a mid-step loss)
    ``recovery_storm``       consecutive losses — the second fault lands
                             DURING recovery of the first (± a probe
                             fault while the breaker is arming)
    ``truncate_loss``        device loss on the shard chunk read (± a
                             mid-step loss): ingest-side recovery
    ``corrupt_burst``        scattered corruption through quarantine,
                             below the breaker threshold
    ``ingest_abort``         a corruption burst pressed into one breaker
                             window — the run must abort LOUDLY
    ``hang``                 a finite hang at one guarded phase — the
                             deadline watchdog must convert it into a
                             structured ``HangDetected``
    ``compound``             corruption + device loss + commit-window
                             loss in one plan
    ======================  ==============================================
    """

    _SCENARIOS = (
        ("commit_loss", 18),
        ("recovery_storm", 18),
        ("corrupt_burst", 16),
        ("truncate_loss", 14),
        ("hang", 12),
        ("ingest_abort", 12),
        ("compound", 10),
    )

    def __init__(self, cfg: DrillConfig | None = None):
        self.cfg = cfg or DrillConfig()

    def _pick_scenario(self, rng: random.Random) -> str:
        total = sum(w for _, w in self._SCENARIOS)
        roll = rng.random() * total
        for name, w in self._SCENARIOS:
            roll -= w
            if roll < 0:
                return name
        return self._SCENARIOS[-1][0]

    def schedule(self, seed: int) -> Schedule:
        rng = random.Random(int(seed))
        cfg = self.cfg
        scenario = self._pick_scenario(rng)
        mid = max(cfg.steps - 2, 2)
        if scenario == "commit_loss":
            rules = [f"ckpt_commit@{rng.randint(1, 2)}=device_loss"]
            if rng.random() < 0.7:
                rules.append(
                    f"train_step@{rng.randint(2, mid)}=device_loss")
            sched = Schedule(seed, scenario, tuple(rules))
        elif scenario == "recovery_storm":
            k = rng.randint(2, mid - 1)
            rules = [f"train_step@{k}=device_loss",
                     f"train_step@{k + 1}=device_loss"]
            if rng.random() < 0.4:
                rules.append("probe@1=device_loss")
            sched = Schedule(seed, scenario, tuple(rules))
        elif scenario == "truncate_loss":
            rules = [f"ingest_truncate@{rng.randint(2, 10)}=device_loss"]
            if rng.random() < 0.5:
                rules.append(
                    f"train_step@{rng.randint(2, mid)}=device_loss")
            sched = Schedule(seed, scenario, tuple(rules))
        elif scenario == "corrupt_burst":
            n = rng.randint(1, 3)
            occs = sorted(rng.sample(range(2, 140), n))
            rules = [f"ingest_corrupt@{o}=error" for o in occs]
            sched = Schedule(seed, scenario, tuple(rules),
                             stream_comparable=False,
                             oracle_comparable=True, max_bad_frac=0.5)
        elif scenario == "ingest_abort":
            # The breaker-pressure interleaving: a burst of consecutive
            # corrupt records inside ONE trailing window, past the
            # configured rate — silent continuation here would mean
            # training on a truncated/garbage shard.
            start = rng.randint(cfg.guard_min_records + 2, 80)
            n = rng.randint(5, 8)
            rules = [f"ingest_corrupt@{start + i}=error"
                     for i in range(n)]
            sched = Schedule(seed, scenario, tuple(rules),
                             expects="ingest_aborted",
                             stream_comparable=False, max_bad_frac=0.1)
        elif scenario == "hang":
            point = rng.choice(tuple(_HANG_PHASE))
            occ = {"ingest_truncate": rng.randint(1, 5),
                   "ckpt_commit": 1,
                   "train_step": rng.randint(2, mid)}[point]
            rules = [f"{point}@{occ}=hang:{_HANG_SLEEP_S}"]
            sched = Schedule(seed, scenario, tuple(rules),
                             expects="hang_detected",
                             stream_comparable=False)
        else:  # compound
            rules = [f"ingest_corrupt@{rng.randint(2, 100)}=error",
                     f"train_step@{rng.randint(2, mid)}=device_loss"]
            if rng.random() < 0.5:
                rules.append(
                    f"ckpt_commit@{rng.randint(1, 2)}=device_loss")
            if rng.random() < 0.3:
                rules.append(
                    f"ingest_corrupt@{rng.randint(101, 200)}=error")
            sched = Schedule(seed, scenario, tuple(rules),
                             stream_comparable=False, max_bad_frac=0.5)
        return sched.validate()

    def sample(self, seeds) -> list[Schedule]:
        return [self.schedule(s) for s in seeds]


# ---------------------------------------------------------------- workload


def build_shards(shard_dir: str, cfg: DrillConfig) -> list[str]:
    """Deterministic libsvm text shards: row ``n`` (global, 0-based)
    carries first feature id ``n+1`` (1-based in the file), so the
    drilled tap — the first 0-based id of every admitted row — IS the
    global record index, and exactly-once is directly readable."""
    os.makedirs(shard_dir, exist_ok=True)
    paths = []
    for s in range(cfg.n_shards):
        path = os.path.join(shard_dir, f"shard{s}.svm")
        lines = []
        for r in range(cfg.rows_per_shard):
            n = s * cfg.rows_per_shard + r
            second = cfg.rows_per_shard * cfg.n_shards + 1 + (n % 31)
            lines.append(f"{n % 2} {n + 1}:1.0 {second}:0.5\n")
        with open(path, "w") as f:
            f.write("".join(lines))
        paths.append(path)
    return paths


class _TapSource:
    """Batch-source wrapper recording the COMMITTED record stream (the
    first feature id of every trained row, one line per batch) — the
    artifact the exactly-once invariant compares.

    The tap length rides the cursor (``tap_len``) and restore truncates
    the recording: batches emitted after the checkpoint a recovery
    rewound to were never committed into the final state, so keeping
    them would make an honest replay read as a duplicate. (Extra cursor
    keys are ignored by ``StreamBatches.restore`` by design.)

    ``break_restore`` is the regression canary: restore stops rewinding
    the wrapped source — exactly the resume bug the auditor must
    catch."""

    def __init__(self, source, break_restore: bool = False):
        self._source = source
        self._break = bool(break_restore)
        self.lines: list[str] = []

    @property
    def guard(self):
        return self._source.guard

    def next_batch(self):
        ids, vals, labels, w = self._source.next_batch()
        self.lines.append(
            ",".join(str(int(x)) for x in ids[w > 0][:, 0]))
        return ids, vals, labels, w

    def state(self):
        return dict(self._source.state(), tap_len=len(self.lines))

    def restore(self, s):
        if self._break:
            return  # canary: the cursor silently stays wherever it was
        self._source.restore(s)
        del self.lines[int(s.get("tap_len", 0)):]

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_batch()


@dataclasses.dataclass
class DrillResult:
    """Everything the auditor needs, collected from one drilled run."""

    outcome: str
    error: str | None
    steps_done: int
    loss_history: list
    params_sums: dict | None
    tap: list
    cursor: dict | None
    counters: dict
    duration_s: float
    workdir: str
    health_path: str
    deadletter_path: str
    ckpt_dir: str
    rcs: tuple = ()
    resumed_at: tuple = ()


def _params_sums(params) -> dict:
    """Per-leaf crc32 identity of a params tree (the byte-level
    final-state fingerprint the identity invariant compares)."""
    import jax
    import numpy as np

    out = {}
    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in leaves:
        arr = np.ascontiguousarray(np.asarray(leaf))
        out[jax.tree_util.keystr(path)] = (
            f"{arr.dtype.str}:{arr.shape}:{zlib.crc32(arr.tobytes()):08x}"
        )
    return out


def _classify_outcome(exc: BaseException) -> str:
    from fm_spark_tpu.data.stream import IngestAborted
    from fm_spark_tpu.resilience.supervisor import (
        CircuitOpen,
        RetriesExhausted,
    )

    if isinstance(exc, watchdog.HangDetected):
        return "hang_detected"
    if isinstance(exc, IngestAborted):
        return "ingest_aborted"
    if isinstance(exc, CircuitOpen):
        return "circuit_open"
    if isinstance(exc, RetriesExhausted):
        return "retries_exhausted"
    return f"error:{type(exc).__name__}"


def run_schedule(schedule: "Schedule | str", cfg: DrillConfig,
                 workdir: str, shard_paths=None) -> DrillResult:
    """Run one drill in-process under ``schedule``'s fault plan.

    The drilled stack is the production one: ``ShardReader`` +
    ``RecordGuard(quarantine)`` + ``StreamBatches`` feeding
    ``FMTrainer.fit`` with a crash-consistent ``Checkpointer`` and a
    ``Supervisor`` (stubbed sleep, real probe machinery). Hang
    schedules additionally arm the deadline watchdog in ``raise`` mode
    (deterministic, thread-free). Fault state is module-local and
    cleared on exit, so drills compose with any caller.
    """
    import jax
    from fm_spark_tpu import models
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data.stream import (
        RecordGuard,
        ShardReader,
        StreamBatches,
        line_parser,
    )
    from fm_spark_tpu.resilience.supervisor import BackoffPolicy, Supervisor
    from fm_spark_tpu.train import FMTrainer, TrainConfig
    from fm_spark_tpu.utils.logging import MetricsLogger

    if isinstance(schedule, str):
        schedule = Schedule(seed=-1, scenario="adhoc",
                            rules=tuple(r for r in schedule.split(";")
                                        if r.strip()))
    os.makedirs(workdir, exist_ok=True)
    if shard_paths is None:
        shard_paths = build_shards(os.path.join(workdir, "shards"), cfg)
    ck_dir = os.path.join(workdir, "ck")
    q_dir = os.path.join(workdir, "q")
    health_path = os.path.join(workdir, "health.jsonl")
    journal = EventLog(health_path)

    spec = models.FMSpec(num_features=cfg.num_features, rank=cfg.rank,
                         init_std=0.05)
    config = TrainConfig(num_steps=cfg.steps, batch_size=cfg.batch_size,
                         learning_rate=cfg.learning_rate,
                         lr_schedule="constant", log_every=1,
                         seed=cfg.seed)
    guard = RecordGuard("quarantine", quarantine_dir=q_dir,
                        max_bad_frac=schedule.max_bad_frac,
                        window=cfg.guard_window,
                        min_records=cfg.guard_min_records,
                        journal=journal)
    source = _TapSource(
        StreamBatches(ShardReader(shard_paths,
                                  chunk_bytes=cfg.chunk_bytes),
                      line_parser("libsvm"), cfg.batch_size,
                      cfg.max_nnz, guard=guard,
                      num_features=cfg.num_features),
        break_restore=cfg.break_restore)
    ck = Checkpointer(ck_dir, save_every=cfg.save_every,
                      async_save=False, journal=journal)
    sup = Supervisor(
        policy=BackoffPolicy(initial=0.01, jitter=0.0, max_delay=0.05),
        journal=journal, probe_timeout=10.0, breaker_threshold=8,
        sleep=lambda s: None)

    trainer = FMTrainer(spec, config)
    # Drills are quiet: metrics go to a per-drill file, not stdout
    # (25 schedules x 18 steps of JSON would drown a campaign log).
    trainer.logger.close()
    trainer.logger = MetricsLogger(
        path=os.path.join(workdir, "metrics.jsonl"))
    trainer.logger._stream = None

    hang_rules = [r for r in schedule.rules if "=hang" in r]
    if hang_rules:
        # Warm the jitted step BEFORE arming deadlines: the first call
        # compiles (hundreds of ms on CPU), which must never read as a
        # hang. Donated inputs are re-initialized deterministically.
        import numpy as np

        b, s = cfg.batch_size, cfg.max_nnz
        trainer._train_step(trainer.params, trainer.opt_state,
                            np.zeros((b, s), np.int32),
                            np.zeros((b, s), np.float32),
                            np.zeros((b,), np.float32),
                            np.zeros((b,), np.float32))
        trainer.params = spec.init(jax.random.key(config.seed))
        trainer.opt_state = trainer.optimizer.init(trainer.params)
        deadlines = {_HANG_PHASE[r.split("@", 1)[0]]: _HANG_DEADLINE_S
                     for r in hang_rules}
        watchdog.configure(deadlines, action="raise", journal=journal)

    t0 = time.perf_counter()
    outcome, error = "completed", None
    try:
        faults.clear()
        if schedule.plan:
            faults.activate(schedule.plan)
        trainer.fit(source, checkpointer=ck, supervisor=sup)
    except Exception as e:  # noqa: BLE001 — the outcome IS the verdict
        outcome = _classify_outcome(e)
        error = f"{type(e).__name__}: {(str(e).splitlines() or [''])[0][:200]}"
    finally:
        faults.clear()
        if hang_rules:
            watchdog.clear()
        try:
            ck.close()
        except Exception:
            pass
        guard.close()
        journal.close()
        trainer.logger.close()

    return DrillResult(
        outcome=outcome, error=error, steps_done=trainer.step_count,
        loss_history=list(trainer.loss_history),
        params_sums=(_params_sums(trainer.params)
                     if outcome == "completed" else None),
        tap=list(source.lines),
        cursor=(dict(source.state()) if outcome == "completed" else None),
        counters=guard.counters(),
        duration_s=time.perf_counter() - t0,
        workdir=workdir, health_path=health_path,
        deadletter_path=os.path.join(
            q_dir, "deadletter.jsonl"),
        ckpt_dir=ck_dir,
    )


def golden_run(cfg: DrillConfig, workdir: str,
               shard_paths=None) -> DrillResult:
    """The clean (no-fault) reference run every comparable invariant is
    judged against."""
    clean = dataclasses.replace(cfg, break_restore=False)
    return run_schedule(Schedule(seed=-1, scenario="golden", rules=()),
                        clean, workdir, shard_paths=shard_paths)


# ----------------------------------------------------------------- oracle


def oracle_tap(schedule: Schedule, cfg: DrillConfig) -> list[str]:
    """Pure-Python prediction of the admitted record stream for a
    quarantine-only schedule (no recovery/kill rules): the ``k``-th
    parse attempt is quarantined iff the plan names occurrence ``k``.
    Replays ``StreamBatches``'s batch/epoch mechanics exactly —
    fixed-size batches, the epoch's final partial batch emitted padded
    — without jax, so the oracle cannot inherit a bug from the code
    under audit."""
    bad = set()
    for rule in schedule.rules:
        point, _, rest = rule.partition("@")
        if point == "ingest_corrupt":
            bad.add(int(rest.split("=", 1)[0]))
    taps: list[str] = []
    batch: list[int] = []
    k = 0
    while len(taps) < cfg.steps:
        for n in range(cfg.total_rows):  # one epoch, in stream order
            k += 1
            if k in bad:
                continue
            batch.append(n)
            if len(batch) == cfg.batch_size:
                taps.append(",".join(map(str, batch)))
                batch = []
                if len(taps) == cfg.steps:
                    return taps
        if batch:  # the epoch's final partial batch, padded at runtime
            taps.append(",".join(map(str, batch)))
            batch = []
    return taps


# ---------------------------------------------------------------- auditor


def _violation(invariant: str, detail: str) -> dict:
    return {"invariant": invariant, "detail": detail}


def _audit_chain(result: DrillResult, cfg: DrillConfig) -> list[dict]:
    """The checkpoint chain must restore through ``last_good`` without
    ever yielding a torn state — checked with a FRESH Checkpointer, the
    way a real recovery would."""
    import jax
    from fm_spark_tpu import models
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.train import TrainConfig, make_optimizer

    out: list[dict] = []
    if not os.path.isdir(result.ckpt_dir):
        return out
    ck = Checkpointer(result.ckpt_dir, save_every=cfg.save_every,
                      async_save=False)
    try:
        if ck.latest_step() is None:
            return out  # the run died before any commit — nothing owed
        spec = models.FMSpec(num_features=cfg.num_features,
                             rank=cfg.rank, init_std=0.05)
        params = spec.init(jax.random.key(cfg.seed))
        opt_state = make_optimizer(
            TrainConfig(num_steps=cfg.steps, batch_size=cfg.batch_size,
                        learning_rate=cfg.learning_rate,
                        lr_schedule="constant")).init(params)
        try:
            restored = ck.restore(params, opt_state)
        except Exception as e:  # noqa: BLE001 — a broken chain IS the finding
            out.append(_violation(
                "chain_integrity",
                f"last_good walk-back failed: {type(e).__name__}: "
                f"{(str(e).splitlines() or [''])[0][:160]}"))
            return out
        last_good = ck.last_good_step()
        if restored is None:
            out.append(_violation("chain_integrity",
                                  "steps exist but restore returned None"))
        elif last_good is not None and restored["step"] < last_good:
            out.append(_violation(
                "chain_integrity",
                f"restored step {restored['step']} behind last_good "
                f"{last_good} — the pointer vouches for a state the "
                "chain cannot produce"))
    finally:
        try:
            ck.close()
        except Exception:
            pass
    return out


def _audit_journal(result: DrillResult) -> list[dict]:
    """Every journal line must parse and timestamps must be
    monotonically non-decreasing (a torn tail is only legal after an
    uncatchable kill, which the in-process drill never performs)."""
    out: list[dict] = []
    try:
        with open(result.health_path) as f:
            raw = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        return out
    events = read_events(result.health_path)
    if len(events) != len(raw):
        out.append(_violation(
            "journal_monotonic",
            f"{len(raw) - len(events)} unparseable journal line(s) in "
            "an uninterrupted run"))
    ts = [e.get("ts") for e in events if isinstance(e.get("ts"),
                                                    (int, float))]
    if any(b < a for a, b in zip(ts, ts[1:])):
        out.append(_violation("journal_monotonic",
                              "journal timestamps went backwards"))
    return out


def audit(schedule: Schedule, result: DrillResult,
          golden: DrillResult, cfg: DrillConfig) -> list[dict]:
    """Every violated invariant, as ``{"invariant", "detail"}`` dicts
    (empty = the schedule is green). Which invariants apply follows
    from the schedule's contract — see :class:`Schedule`."""
    v: list[dict] = []
    events = read_events(result.health_path)
    kinds = [e.get("event") for e in events]

    if result.outcome != schedule.expects:
        v.append(_violation(
            "completion",
            f"expected outcome {schedule.expects!r}, got "
            f"{result.outcome!r} ({result.error})"))
    elif schedule.expects == "completed":
        if result.steps_done != cfg.steps:
            v.append(_violation(
                "completion",
                f"run ended at step {result.steps_done} of {cfg.steps}"))
        if any(not (x == x and abs(x) < float("inf"))
               for x in result.loss_history):
            v.append(_violation("completion",
                                "non-finite loss in a completed run"))

    if schedule.stream_comparable and schedule.expects == "completed":
        if result.tap != golden.tap:
            first = next((i for i, (a, b) in
                          enumerate(zip(result.tap, golden.tap))
                          if a != b), min(len(result.tap),
                                          len(golden.tap)))
            v.append(_violation(
                "exactly_once_stream",
                f"record stream diverges from the clean run at batch "
                f"{first} ({len(result.tap)} vs {len(golden.tap)} "
                "batches) — records replayed or skipped"))
        if result.loss_history != golden.loss_history:
            v.append(_violation(
                "loss_continuity",
                "loss curve differs from the clean run after recovery"))
        if (result.params_sums is not None
                and result.params_sums != golden.params_sums):
            v.append(_violation(
                "state_identity",
                "final params differ byte-wise from the clean run"))
        if result.cursor is not None and golden.cursor is not None:
            if result.cursor != golden.cursor:
                v.append(_violation(
                    "state_identity",
                    f"final cursor {result.cursor} != clean "
                    f"{golden.cursor}"))

    if schedule.oracle_comparable and schedule.expects == "completed":
        expected = oracle_tap(schedule, cfg)
        if result.tap != expected:
            first = next((i for i, (a, b) in
                          enumerate(zip(result.tap, expected))
                          if a != b), min(len(result.tap),
                                          len(expected)))
            v.append(_violation(
                "exactly_once_oracle",
                f"admitted stream diverges from the quarantine oracle "
                f"at batch {first}"))

    # Quarantine accounting: the guard's counters, the dead-letter
    # journal, and the checkpointed cursor must tell one story. The
    # dead-letter journal is APPEND-ONLY across recovery rollbacks
    # (a record quarantined before a rollback keeps its dead letter
    # even though the counter honestly rewinds with the cursor), so
    # the journal bounds the counter from above; without any rollback
    # they must be equal.
    dead = read_events(result.deadletter_path)
    n_dead = sum(1 for e in dead if e.get("event") == "bad_record")
    rolled_back = any(k in ("failure", "supervisor_reset")
                      for k in kinds)
    n_bad = result.counters.get("bad", 0)
    if (n_bad > n_dead) or (not rolled_back and n_bad != n_dead):
        v.append(_violation(
            "quarantine_accounting",
            f"guard counted {n_bad} bad vs {n_dead} dead-letter "
            f"record(s) (rolled_back={rolled_back})"))
    if result.cursor is not None:
        for key in ("ok", "bad"):
            if result.cursor.get(key) != result.counters.get(key):
                v.append(_violation(
                    "quarantine_accounting",
                    f"cursor {key}={result.cursor.get(key)} vs guard "
                    f"{key}={result.counters.get(key)}"))

    if schedule.expects == "hang_detected":
        if "hang_detected" not in kinds:
            v.append(_violation(
                "hang_detection",
                "no hang_detected journal event — the watchdog verdict "
                "left no machine-readable trace"))
    if schedule.expects == "ingest_aborted":
        aborted = ("ingest_aborted" in kinds
                   or any(e.get("event") == "ingest_aborted"
                          for e in dead))
        if not aborted:
            v.append(_violation(
                "abort_detection",
                "breaker tripped without an ingest_aborted journal "
                "event"))

    v.extend(_audit_chain(result, cfg))
    v.extend(_audit_journal(result))
    return v


# -------------------------------------------------------------- minimizer


def minimize(rules, fails) -> tuple[str, ...]:
    """Greedy ddmin over a failing schedule's rules: repeatedly drop
    any single rule whose removal keeps ``fails(plan)`` true, until no
    rule can be dropped. Every candidate is re-run, so the returned
    minimal plan is VERIFIED still-failing — the reproducible repro the
    verdict publishes with its seed."""
    cur = list(rules)
    changed = True
    while changed and len(cur) > 1:
        changed = False
        for i in range(len(cur)):
            cand = cur[:i] + cur[i + 1:]
            if fails(";".join(cand)):
                cur = cand
                changed = True
                break
    return tuple(cur)


# --------------------------------------------------------------- campaign


class _MinimizeBudgetExhausted(RuntimeError):
    """The campaign budget ran out mid-ddmin; minimization is aborted
    (recorded on the failure entry), never silently overrun."""


def run_campaign(seeds, cfg: DrillConfig | None = None,
                 base_dir: str | None = None,
                 time_budget_s: float | None = None,
                 per_schedule_timeout_s: float | None = None,
                 minimize_failures: bool = True,
                 journal: EventLog | None = None) -> dict:
    """Run one seeded campaign: golden run, then every seed's schedule,
    audited; failing schedules are delta-debugged to a minimal plan.

    Bounded: ``time_budget_s`` caps the whole campaign (schedules past
    the budget are recorded as skipped, never silently dropped), and
    ``per_schedule_timeout_s`` flags any drill that overran its slice
    (in-process drills cannot be preempted, so the flag is the audit
    signal). Returns the machine-readable verdict dict that
    ``tools/chaos_drill.py`` persists as ``chaos_verdict.json``.
    """
    import tempfile

    cfg = cfg or DrillConfig()
    base_dir = base_dir or tempfile.mkdtemp(prefix="chaos_")
    os.makedirs(base_dir, exist_ok=True)
    gen = ScheduleGenerator(cfg)
    t0 = time.perf_counter()

    def emit(event, **fields):
        if journal is not None:
            journal.emit(event, **fields)

    shard_paths = build_shards(os.path.join(base_dir, "shards"), cfg)
    emit("campaign_start", seeds=list(map(int, seeds)),
         steps=cfg.steps, canary=cfg.break_restore)
    golden = golden_run(cfg, os.path.join(base_dir, "golden"),
                        shard_paths=shard_paths)
    if golden.outcome != "completed":
        raise RuntimeError(
            f"golden (no-fault) drill failed: {golden.error} — the "
            "workload itself is broken; no schedule verdict is "
            "meaningful")

    entries: list[dict] = []
    failures: list[dict] = []
    budget_exhausted = False
    for seed in seeds:
        elapsed = time.perf_counter() - t0
        if time_budget_s is not None and elapsed > time_budget_s:
            budget_exhausted = True
            entries.append({"seed": int(seed), "plan": None,
                            "scenario": None,
                            "verdict": "skipped_budget",
                            "violations": []})
            continue
        sched = gen.schedule(seed)
        workdir = os.path.join(base_dir, f"s{int(seed)}")
        result = run_schedule(sched, cfg, workdir,
                              shard_paths=shard_paths)
        violations = audit(sched, result, golden, cfg)
        overran = (per_schedule_timeout_s is not None
                   and result.duration_s > per_schedule_timeout_s)
        if overran:
            violations.append(_violation(
                "schedule_timeout",
                f"drill took {result.duration_s:.2f}s > "
                f"{per_schedule_timeout_s:.2f}s slice"))
        entry = {
            "seed": int(seed),
            "scenario": sched.scenario,
            "plan": sched.plan,
            "expects": sched.expects,
            "outcome": result.outcome,
            "verdict": "green" if not violations else "failed",
            "violations": violations,
            "duration_s": round(result.duration_s, 3),
            "quarantined": result.counters.get("bad", 0),
        }
        emit("schedule_verdict", **{k: entry[k] for k in
                                    ("seed", "scenario", "plan",
                                     "verdict", "outcome")})
        if violations:
            failure = dict(entry)
            if minimize_failures:
                rerun_idx = [0]

                def _fails(plan: str, _seed=seed, _sched=sched) -> bool:
                    # ddmin re-runs are bounded by the SAME campaign
                    # budget as the schedules themselves — a minimize
                    # pass must not silently double the advertised
                    # wall-clock.
                    if (time_budget_s is not None
                            and time.perf_counter() - t0
                            > time_budget_s):
                        raise _MinimizeBudgetExhausted()
                    rerun_idx[0] += 1
                    cand = dataclasses.replace(
                        _sched, rules=tuple(
                            r for r in plan.split(";") if r))
                    r = run_schedule(
                        cand, cfg,
                        os.path.join(base_dir,
                                     f"s{int(_seed)}_min{rerun_idx[0]}"),
                        shard_paths=shard_paths)
                    return bool(audit(cand, r, golden, cfg))

                try:
                    minimal = minimize(sched.rules, _fails)
                    failure["minimized_plan"] = ";".join(minimal)
                    failure["minimized_rules"] = len(minimal)
                    entry["minimized_plan"] = failure["minimized_plan"]
                except _MinimizeBudgetExhausted:
                    budget_exhausted = True
                    failure["minimize_aborted_budget"] = True
            failures.append(failure)
        entries.append(entry)

    verdict = {
        "engine": "chaos-campaign/1",
        "seeds": [int(s) for s in seeds],
        "config": {
            "steps": cfg.steps, "batch_size": cfg.batch_size,
            "shards": cfg.n_shards,
            "rows_per_shard": cfg.rows_per_shard,
            "save_every": cfg.save_every, "canary": cfg.break_restore,
        },
        "n_schedules": len(entries),
        "n_green": sum(e["verdict"] == "green" for e in entries),
        "n_failed": len(failures),
        "n_skipped": sum(e["verdict"] == "skipped_budget"
                         for e in entries),
        "all_green": (not failures and not budget_exhausted
                      and bool(entries)),
        "budget_s": time_budget_s,
        "budget_exhausted": budget_exhausted,
        "total_s": round(time.perf_counter() - t0, 3),
        "schedules": entries,
        "failures": failures,
    }
    emit("campaign_end", all_green=verdict["all_green"],
         n_failed=verdict["n_failed"], total_s=verdict["total_s"])
    return verdict


# ------------------------------------------------------- subprocess drills

#: Worker script for process-fatal actions (exit / sigterm / real
#: never-returning hangs / SIGKILL from the parent): the same workload
#: as :func:`run_schedule` driven as a child process, with the fault
#: plan arriving via FM_SPARK_FAULTS and cross-process occurrence
#: counters via FM_SPARK_FAULTS_STATE. Emits one JSON line per step
#: (the parent's kill trigger) plus ``resumed_at`` / ``done`` markers.
_WORKER_TEMPLATE = '''\
import json, os, sys, zlib

os.environ.setdefault("JAX_PLATFORMS", "cpu")
(workdir, steps, batch_size, save_every, flight_capacity,
 max_bad_frac, seed, attempt) = sys.argv[1:9]
steps, batch_size, seed = int(steps), int(batch_size), int(seed)

import numpy as np
import jax
from fm_spark_tpu import models, obs
from fm_spark_tpu.checkpoint import Checkpointer
from fm_spark_tpu.data.stream import (RecordGuard, ShardReader,
                                      StreamBatches, line_parser)
from fm_spark_tpu.resilience import faults
from fm_spark_tpu.resilience.supervisor import BackoffPolicy, Supervisor
from fm_spark_tpu.train import FMTrainer, TrainConfig
from fm_spark_tpu.utils.logging import EventLog

obs.configure(os.path.join(workdir, "obs"), run_id="chaos-drill",
              flight_capacity=int(flight_capacity),
              install_signals=True)
faults.inject("backend_init")   # the init-window fault point

shard_dir = os.path.join(workdir, "shards")
paths = sorted(os.path.join(shard_dir, f)
               for f in os.listdir(shard_dir))
journal = EventLog(os.path.join(workdir, "health.jsonl"),
                   mirror_to_flight=True)
guard = RecordGuard("quarantine",
                    quarantine_dir=os.path.join(workdir, "q"),
                    max_bad_frac=float(max_bad_frac), window=32,
                    min_records=16, journal=journal)


class Tap:
    # Batch-index-prefixed, append-per-batch (SIGKILL-durable) record
    # tap; the index rides the cursor so a resumed attempt continues
    # numbering where the checkpoint left off.
    def __init__(self, source, path):
        self._source = source
        self._path = path
        self._idx = 0

    def next_batch(self):
        ids, vals, labels, w = self._source.next_batch()
        with open(self._path, "a") as f:
            f.write(str(self._idx) + ":" + ",".join(
                str(int(x)) for x in ids[w > 0][:, 0]))
            f.write("\\n")
        self._idx += 1
        return ids, vals, labels, w

    def state(self):
        return dict(self._source.state(), tap_len=self._idx)

    def restore(self, s):
        self._source.restore(s)
        self._idx = int(s.get("tap_len", 0))

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_batch()


ck = Checkpointer(os.path.join(workdir, "ck"),
                  save_every=int(save_every), async_save=False,
                  journal=journal)
sup = Supervisor(policy=BackoffPolicy(initial=0.01, jitter=0.0,
                                      max_delay=0.05),
                 journal=journal, probe=lambda: True,
                 breaker_threshold=8, sleep=lambda s: None)
print(json.dumps({"resumed_at": int(ck.last_good_step() or 0)}),
      flush=True)
batches = Tap(
    StreamBatches(ShardReader(paths, chunk_bytes=64),
                  line_parser("libsvm"), batch_size, 3, guard=guard,
                  num_features=128),
    os.path.join(workdir, f"tap_{attempt}.txt"))
spec = models.FMSpec(num_features=128, rank=4, init_std=0.05)
config = TrainConfig(num_steps=steps, batch_size=batch_size,
                     learning_rate=0.1, lr_schedule="constant",
                     log_every=1, seed=seed)
trainer = FMTrainer(spec, config)
trainer.fit(batches, checkpointer=ck, supervisor=sup)
ck.close()
sums = {}
for path, leaf in jax.tree_util.tree_flatten_with_path(
        trainer.params)[0]:
    arr = np.ascontiguousarray(np.asarray(leaf))
    sums[jax.tree_util.keystr(path)] = (
        f"{arr.dtype.str}:{arr.shape}:{zlib.crc32(arr.tobytes()):08x}")
print(json.dumps({"done": trainer.step_count,
                  "counters": guard.counters(),
                  "cursor": batches.state(), "params_sums": sums,
                  "loss_history": trainer.loss_history}), flush=True)
obs.shutdown()
'''


def write_worker(workdir: str) -> str:
    path = os.path.join(workdir, "chaos_worker.py")
    with open(path, "w") as f:
        f.write(_WORKER_TEMPLATE)
    return path


def run_schedule_subproc(plan: str, cfg: DrillConfig, workdir: str, *,
                         attempts: int = 4, timeout_s: float = 120.0,
                         kill_at_step: int | None = None,
                         kill_signal: int | None = None,
                         watchdog_spec: str | None = None,
                         expected_rcs=(0,)) -> DrillResult:
    """Drive the worker as a supervised child-process chain: spawn,
    optionally SIGKILL it at a step (first attempt only), respawn while
    it dies with an EXPECTED rc, and collect the artifacts for
    :func:`audit`-style checks. Cross-process fault occurrences ride
    ``FM_SPARK_FAULTS_STATE`` so "hang the FIRST attempt's read, not
    every attempt's" stays expressible across respawns.

    rc discipline is itself an invariant: an attempt ending with an rc
    outside ``expected_rcs`` ∪ {the kill signal, watchdog
    :data:`~fm_spark_tpu.resilience.watchdog.HANG_EXIT_RC`} fails the
    drill with outcome ``rc_violation``.
    """
    import json as _json  # read-only (json.loads); writes stay EventLog

    import signal as _signal

    os.makedirs(workdir, exist_ok=True)
    build_shards(os.path.join(workdir, "shards"), cfg)
    worker = write_worker(workdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FM_SPARK_OBS_DIR="none",
               PYTHONPATH=_REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               FM_SPARK_FAULTS=plan,
               FM_SPARK_FAULTS_STATE=os.path.join(workdir,
                                                  "faults_state.json"))
    env.pop("FM_SPARK_WATCHDOG", None)
    env.pop("FM_SPARK_WATCHDOG_ACTION", None)
    if watchdog_spec:
        env["FM_SPARK_WATCHDOG"] = watchdog_spec
        env["FM_SPARK_WATCHDOG_ACTION"] = "exit"
    kill_sig = (int(kill_signal) if kill_signal is not None
                else int(_signal.SIGKILL))
    allowed = set(expected_rcs) | {watchdog.HANG_EXIT_RC,
                                   -int(_signal.SIGTERM)}
    if kill_at_step is not None:
        allowed.add(-kill_sig)

    import threading

    t0 = time.perf_counter()
    rcs: list[int] = []
    resumed: list[int] = []
    done: dict | None = None
    outcome, error = "incomplete", None
    for attempt in range(attempts):
        argv = [sys.executable, worker, workdir, str(cfg.steps),
                str(cfg.batch_size), str(cfg.save_every),
                str(cfg.flight_capacity), "1.0", str(cfg.seed),
                str(attempt)]
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                text=True, cwd=_REPO, env=env)
        killed = False
        # The per-attempt timeout must bound a SILENT child too (a
        # hang at an unbudgeted point emits nothing, and a blocking
        # readline would wait on it forever): a timer thread kills the
        # child at the deadline, which unblocks the stdout iteration.
        timed_out = threading.Event()

        def _deadline_kill(p=proc, flag=timed_out):
            flag.set()
            try:
                p.kill()
            except OSError:
                pass

        timer = threading.Timer(timeout_s, _deadline_kill)
        timer.daemon = True
        timer.start()
        try:
            for line in proc.stdout:
                try:
                    rec = _json.loads(line)
                except ValueError:
                    continue
                if "resumed_at" in rec:
                    resumed.append(int(rec["resumed_at"]))
                if "done" in rec:
                    done = rec
                if (kill_at_step is not None and not killed
                        and attempt == 0
                        and rec.get("step", -1) >= kill_at_step):
                    os.kill(proc.pid, kill_sig)
                    killed = True
            proc.wait(timeout=30)
        finally:
            timer.cancel()
            proc.stdout.close()
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        rcs.append(proc.returncode)
        if timed_out.is_set():
            outcome = "attempt_timeout"
            error = f"attempt {attempt} exceeded {timeout_s}s"
            break
        # rc discipline applies to EVERY attempt, the completing one
        # included: a worker that printed its done marker and then
        # died in teardown still violated the exit contract.
        if proc.returncode not in allowed:
            outcome = "rc_violation"
            error = (f"attempt {attempt} exited rc={proc.returncode}, "
                     f"allowed {sorted(allowed)}")
            break
        if done is not None:
            outcome = "completed"
            break
    if outcome == "incomplete":
        error = f"no completion in {attempts} attempt(s); rcs={rcs}"

    tap: list[str] = []
    for attempt in range(attempts):
        path = os.path.join(workdir, f"tap_{attempt}.txt")
        if os.path.isfile(path):
            with open(path) as f:
                tap.append(f.read())
    return DrillResult(
        outcome=outcome, error=error,
        steps_done=int((done or {}).get("done", 0)),
        loss_history=list((done or {}).get("loss_history", [])),
        params_sums=(done or {}).get("params_sums"),
        tap=tap,  # raw per-attempt tap texts; stitch with stitch_taps()
        cursor=(done or {}).get("cursor"),
        counters=dict((done or {}).get("counters", {})),
        duration_s=time.perf_counter() - t0,
        workdir=workdir,
        health_path=os.path.join(workdir, "health.jsonl"),
        deadletter_path=os.path.join(workdir, "q", "deadletter.jsonl"),
        ckpt_dir=os.path.join(workdir, "ck"),
        rcs=tuple(rcs), resumed_at=tuple(resumed),
    )


def stitch_taps(result: DrillResult) -> list[str]:
    """Reconstruct the EFFECTIVE record stream of a killed-and-resumed
    drill chain from the batch-index-prefixed per-attempt taps: for
    each batch index the LAST write wins (a later attempt re-emitting
    an index means the earlier emission was rolled back with the
    checkpoint — never committed). The result must be contiguous from
    batch 0 and bit-identical to the clean run's tap: that is the
    exactly-once verdict across process deaths. A torn final line (a
    SIGKILL mid-append) is tolerated exactly once per attempt file."""
    effective: dict[int, str] = {}
    for text in result.tap:
        lines = text.splitlines()
        for j, line in enumerate(lines):
            idx, sep, payload = line.partition(":")
            if not sep or not idx.isdigit():
                if j == len(lines) - 1:
                    continue  # torn tail from a kill mid-append
                raise ValueError(f"malformed tap line {line!r}")
            effective[int(idx)] = payload
    if not effective:
        return []
    if sorted(effective) != list(range(max(effective) + 1)):
        raise ValueError(
            f"tap indices not contiguous: {sorted(effective)[:8]}...")
    return [effective[i] for i in range(max(effective) + 1)]


# ------------------------------------------------------- serving (ISSUE 12)

#: The serving-path watchdog phase a hang drill arms (deadline = SLO).
_SERVE_PHASE = "serve_request"


def serve_schedule(seed: int) -> Schedule:
    """Seeded serving-path fault schedule (ISSUE 12): compositions of
    trainer-side ``ckpt_commit`` faults (a torn publish window under an
    active reload follower) and ``serve_reload`` faults (reload
    failure → degraded serving; ``exit`` = the SIGKILL-mid-reload
    drill). Same purity contract as :meth:`ScheduleGenerator.schedule`:
    the plan is a pure function of the seed, so a failing seed IS its
    repro. The serve drill harness (tests/test_serve.py) runs these
    against the production engine/follower/checkpointer stack and holds
    the run to :func:`audit_serve_events`."""
    rng = random.Random(int(seed))
    scenario = rng.choice(
        ("reload_fail", "commit_fault", "reload_storm", "compound"))
    if scenario == "reload_fail":
        rules = [f"serve_reload@{rng.randint(1, 2)}=error"]
    elif scenario == "commit_fault":
        rules = [f"ckpt_commit@{rng.randint(1, 2)}=error"]
        if rng.random() < 0.5:
            rules.append(f"serve_reload@{rng.randint(1, 2)}=error")
    elif scenario == "reload_storm":
        rules = ["serve_reload@1=error", "serve_reload@2=error"]
    else:  # compound: publish fault pressed against a reload failure
        rules = [f"ckpt_commit@{rng.randint(1, 2)}=error",
                 f"serve_reload@{rng.randint(1, 3)}=error"]
    return Schedule(int(seed), f"serve_{scenario}", tuple(rules),
                    stream_comparable=False).validate()


# ------------------------------------------- continuous learning (ISSUE 13)

#: The drift/rollback failure class joins the chaos surface: seeded
#: schedules over the ``online_eval`` / ``ckpt_demote`` / ``ckpt_commit``
#: / ``ingest_corrupt`` points, drilled against the PRODUCTION online
#: loop (online.run_online + FMTrainer + StreamBatches + Checkpointer)
#: with a planted label-flip drift, and audited from artifacts alone.

#: Tier-1 drift drill seeds (tools/chaos_drill.py runs the same five).
DRIFT_TIER1_SEEDS = (0, 1, 2, 3, 4)

_DRIFT_SCENARIOS = ("clean_drift", "eval_fault", "commit_fault",
                    "demote_fault", "rollback_corruption")


@dataclasses.dataclass(frozen=True)
class DriftDrillConfig:
    """Online-loop drill shape: enough days for the sentry's
    ``min_history`` floor to clear before the planted drift day, small
    enough that five schedules fit the tier-1 budget."""

    days: int = 6
    rows_per_day: int = 192
    batch_size: int = 16
    num_features: int = 128
    nnz: int = 3
    rank: int = 4
    drift_day: int = 4           # labels flip from this day on
    seed: int = 11
    learning_rate: float = 0.2
    drop_factor: float = 1.15
    min_history: int = 3
    max_rollbacks: int = 2
    attempts: int = 4


def build_drift_days(cfg: DriftDrillConfig, shard_dir: str):
    """Deterministic time-ordered day set with a planted concept
    drift: synthetic planted-FM CTR days whose labels FLIP from
    ``drift_day`` on. Returns ``(days, shard_paths)`` — in-memory
    arrays (the eval side) and one libsvm text shard per day (the
    streaming train side; ids written 1-based per libsvm convention,
    so the parsed stream round-trips the array ids exactly)."""
    from fm_spark_tpu import online
    from fm_spark_tpu.data import synthetic_ctr

    ids, vals, labels = synthetic_ctr(
        cfg.days * cfg.rows_per_day, cfg.num_features, cfg.nnz,
        rank=cfg.rank, seed=cfg.seed)
    days = online.flip_labels(
        online.split_days(ids, vals, labels, cfg.days), cfg.drift_day)
    os.makedirs(shard_dir, exist_ok=True)
    paths = []
    for k, (di, dv, dl) in enumerate(days):
        path = os.path.join(shard_dir, f"day{k}.svm")
        with open(path, "w") as f:
            for r in range(len(dl)):
                feats = " ".join(f"{int(di[r, j]) + 1}:{dv[r, j]:g}"
                                 for j in range(cfg.nnz))
                f.write(f"{int(dl[r])} {feats}\n")
        paths.append(path)
    return days, paths


def drift_schedule(seed: int) -> Schedule:
    """Seeded drift/rollback fault schedule — scenario chosen by
    ``seed % 5`` so the five tier-1 seeds cover the whole class, rule
    parameters drawn from the seeded rng; a pure function of the seed
    like every other schedule here.

    ``clean_drift``          no faults: the rollback protocol itself
    ``eval_fault``           ``online_eval`` error — the eval pass
                             dies; the resumed run must REPLAY the
                             missed eval (durable sentry state), so a
                             crash can never skip a drift check
    ``commit_fault``         ``ckpt_commit`` error — a drift-adjacent
                             save dies in its verify window
    ``demote_fault``         ``ckpt_demote`` error — the demotion
                             crashes AFTER the tombstone, BEFORE the
                             pointer republish (the nastiest window)
    ``rollback_corruption``  quarantine-policy ingest corruption under
                             the drifted days — rollback must compose
                             with dirty ingest accounting
    """
    rng = random.Random(int(seed))
    scenario = _DRIFT_SCENARIOS[int(seed) % len(_DRIFT_SCENARIOS)]
    if scenario == "clean_drift":
        rules: tuple = ()
    elif scenario == "eval_fault":
        rules = (f"online_eval@{rng.randint(1, 5)}=error",)
    elif scenario == "commit_fault":
        rules = (f"ckpt_commit@{rng.randint(2, 6)}=error",)
    elif scenario == "demote_fault":
        rules = ("ckpt_demote@1=error",)
    else:  # rollback_corruption
        n = rng.randint(2, 4)
        occs = sorted(rng.sample(range(5, 400), n))
        rules = tuple(f"ingest_corrupt@{o}=error" for o in occs)
    return Schedule(int(seed), f"drift_{scenario}", rules,
                    stream_comparable=(scenario != "rollback_corruption"),
                    max_bad_frac=0.5).validate()


class _DayTap:
    """Per-day durable batch tap for the online drill: one
    ``day:index:ids`` line appended per consumed batch (last write
    wins on re-runs, like the subprocess tap)."""

    def __init__(self, source, day: int, path: str):
        self._source, self._day, self._path = source, day, path
        self._idx = 0

    @property
    def guard(self):
        return getattr(self._source, "guard", None)

    def next_batch(self):
        ids, vals, labels, w = self._source.next_batch()
        with open(self._path, "a") as f:
            f.write(f"{self._day}:{self._idx}:" + ",".join(
                str(int(x)) for x in ids[w > 0][:, 0]) + "\n")
        self._idx += 1
        return ids, vals, labels, w

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_batch()


@dataclasses.dataclass
class DriftResult:
    """One drilled online run's artifacts for :func:`audit_drift`."""

    outcome: str
    error: str | None
    attempts: int
    summary: dict | None
    taps: dict
    params_sums: dict | None
    tombstones: list
    last_good: int | None
    counters: dict
    workdir: str
    health_path: str
    deadletter_path: str
    ckpt_dir: str


def _read_day_taps(path: str) -> dict:
    """Last-write-wins per-(day, batch) tap reconstruction — a day
    retrained after a crash replays the same deterministic stream, so
    the effective map must match the clean run's exactly."""
    taps: dict = {}
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return taps
    for line in lines:
        day, _, rest = line.partition(":")
        idx, _, payload = rest.partition(":")
        if not (day.isdigit() and idx.isdigit()):
            continue
        taps.setdefault(int(day), {})[int(idx)] = payload
    return {d: [m[i] for i in sorted(m)] for d, m in taps.items()}


def run_drift_schedule(schedule: "Schedule | str",
                       cfg: DriftDrillConfig, workdir: str,
                       shard_state=None) -> DriftResult:
    """Drill the PRODUCTION continuous-learning loop under a fault
    plan: time-ordered libsvm day shards stream through
    ``StreamBatches`` + quarantine ``RecordGuard`` into
    ``online.run_online`` (FMTrainer, crash-consistent Checkpointer,
    maximize-mode drift sentry), with a planted label-flip drift so
    EVERY schedule exercises the demotion/rollback path. A fault that
    kills the run is followed by a fresh-process-style resume (new
    trainer/checkpointer over the same chain + durable sentry state),
    up to ``cfg.attempts`` — the in-process analog of the respawn
    chain, with fault occurrence counters carried across attempts."""
    import jax  # noqa: F401  (the trainer needs a backend)

    from fm_spark_tpu import models, online
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data.stream import (
        RecordGuard,
        ShardReader,
        StreamBatches,
        line_parser,
    )
    from fm_spark_tpu.train import FMTrainer, TrainConfig
    from fm_spark_tpu.utils.logging import MetricsLogger

    if isinstance(schedule, str):
        schedule = Schedule(seed=-1, scenario="adhoc",
                            rules=tuple(r for r in schedule.split(";")
                                        if r.strip()))
    os.makedirs(workdir, exist_ok=True)
    if shard_state is None:
        shard_state = build_drift_days(
            cfg, os.path.join(workdir, "shards"))
    days, shard_paths = shard_state
    ck_dir = os.path.join(workdir, "ck")
    q_dir = os.path.join(workdir, "q")
    tap_path = os.path.join(workdir, "tap.txt")
    health_path = os.path.join(workdir, "health.jsonl")
    journal = EventLog(health_path)

    guards: list = []

    def day_source(k, _default):
        """Replace the online loop's in-memory day source with the
        PRODUCTION streaming stack over day ``k``'s text shard —
        quarantine guard (the ``ingest_corrupt`` surface) + durable
        per-batch tap."""
        guard = RecordGuard("quarantine", quarantine_dir=q_dir,
                            max_bad_frac=schedule.max_bad_frac,
                            window=64, min_records=32,
                            journal=journal)
        guards.append(guard)
        src = StreamBatches(
            ShardReader([shard_paths[k]], chunk_bytes=512),
            line_parser("libsvm"), cfg.batch_size, cfg.nnz,
            guard=guard, num_features=cfg.num_features)
        return _DayTap(src, k, tap_path)
    spec = models.FMSpec(num_features=cfg.num_features, rank=cfg.rank,
                         init_std=0.05)
    tconfig = TrainConfig(num_steps=0, batch_size=cfg.batch_size,
                          learning_rate=cfg.learning_rate,
                          lr_schedule="constant", optimizer="ftrl",
                          log_every=10_000, seed=cfg.seed)

    faults.clear()
    if schedule.plan:
        faults.activate(schedule.plan)
    outcome, error, summary = "incomplete", None, None
    attempts = 0
    try:
        for attempt in range(cfg.attempts):
            attempts = attempt + 1
            trainer = FMTrainer(spec, tconfig)
            trainer.logger.close()
            trainer.logger = MetricsLogger(
                path=os.path.join(workdir, "metrics.jsonl"))
            trainer.logger._stream = None
            ck = Checkpointer(ck_dir, save_every=10**9,
                              async_save=False, journal=journal)
            sentry = online.drift_guard(
                drop_factor=cfg.drop_factor,
                min_history=cfg.min_history,
                max_rollbacks=cfg.max_rollbacks, journal=journal)
            try:
                summary = online.run_online(
                    trainer, days, ck, sentry=sentry,
                    journal=journal, batch_tap=day_source)
                outcome = "completed"
            except Exception as e:  # noqa: BLE001 — the outcome IS
                # the verdict; the next attempt is the recovery
                outcome = _classify_outcome(e)
                error = (f"{type(e).__name__}: "
                         f"{(str(e).splitlines() or [''])[0][:200]}")
            finally:
                try:
                    ck.close()
                except Exception:
                    pass
                trainer.logger.close()
            if outcome == "completed":
                break
    finally:
        faults.clear()
        for g in guards:
            g.close()
        journal.close()

    total = {"ok": 0, "bad": 0}
    for g in guards:
        c = g.counters()
        total["ok"] += c.get("ok", 0)
        total["bad"] += c.get("bad", 0)
    from fm_spark_tpu.checkpoint import ChainFollower

    follower = ChainFollower(ck_dir)
    tombstones = sorted(follower.tombstoned_steps())
    last_good = follower.last_good_step()
    follower.close()
    return DriftResult(
        outcome=outcome, error=error, attempts=attempts,
        summary=summary, taps=_read_day_taps(tap_path),
        params_sums=(_params_sums(trainer.params)
                     if outcome == "completed" else None),
        tombstones=tombstones, last_good=last_good,
        counters=total, workdir=workdir, health_path=health_path,
        deadletter_path=os.path.join(q_dir, "deadletter.jsonl"),
        ckpt_dir=ck_dir,
    )


def audit_drift(schedule: Schedule, result: DriftResult,
                golden: DriftResult, cfg: DriftDrillConfig) -> list[dict]:
    """The continuous-learning invariants, judged from artifacts alone
    (empty list = green):

    - **completion** — the run completes within the attempt budget and
      every eval day 1..D-1 was judged;
    - **rollback** — the planted drift fired the sentry and the
      offending generation was demoted (for stream-comparable
      schedules, at exactly the first drifted eval day);
    - **exactly_once_stream** — the effective per-day record stream
      (last-write-wins across crash re-runs) is bit-identical to the
      clean drilled run's: records are neither replayed into nor
      skipped from the committed state, rollbacks included;
    - **state_identity** — final params byte-identical to the clean
      run (faults may change WHEN things happened, never the model);
    - **chain_consistency** — a fresh read-only follower restores a
      verified, NON-tombstoned step equal to the published
      ``last_good``; every demoted step is tombstoned; the pointer
      never vouches for a vetoed generation;
    - **quarantine_accounting** — corruption schedules: every
      quarantined record has a dead letter.
    """
    v: list[dict] = []
    if result.outcome != "completed":
        v.append(_violation(
            "completion",
            f"{result.outcome} after {result.attempts} attempt(s): "
            f"{result.error}"))
        return v
    summary = result.summary or {}
    # Eval coverage spans ATTEMPTS (a killed run's early evals live in
    # its journal, not the final attempt's summary) — the journal is
    # the durable record the invariant reads.
    eval_days = {e.get("eval_day")
                 for e in read_events(result.health_path)
                 if e.get("event") == "quality_eval"}
    eval_days |= {e.get("eval_day") for e in summary.get("days", [])}
    want = set(range(1, cfg.days))
    if not want <= eval_days:
        v.append(_violation(
            "completion",
            f"eval days {sorted(want - eval_days)} never judged"))
    # Rollback evidence spans attempts too: a fault that kills the run
    # AFTER the rollback leaves the final attempt's summary with
    # rollbacks=0 while the journal durably records the demotion — the
    # journal, not the last summary, is what the invariant reads.
    rollback_events = [e for e in read_events(result.health_path)
                       if e.get("event") == "online_rollback"]
    if not (summary.get("rollbacks") or rollback_events):
        v.append(_violation(
            "rollback",
            "planted label-flip drift never fired the sentry"))
    if schedule.stream_comparable and rollback_events:
        first_eval = int(rollback_events[0].get("day", -2)) + 1
        if first_eval != cfg.drift_day:
            v.append(_violation(
                "rollback",
                f"first rollback at eval day {first_eval}, expected "
                f"the first drifted day {cfg.drift_day}"))
        if result.taps != golden.taps:
            bad_days = sorted(d for d in set(result.taps)
                              | set(golden.taps)
                              if result.taps.get(d)
                              != golden.taps.get(d))
            v.append(_violation(
                "exactly_once_stream",
                f"effective record stream diverges from the clean "
                f"run on day(s) {bad_days[:4]} — records replayed "
                "or skipped across recovery/rollback"))
        if (result.params_sums is not None
                and result.params_sums != golden.params_sums):
            v.append(_violation(
                "state_identity",
                "final params differ byte-wise from the clean run"))
    if result.last_good is None:
        v.append(_violation("chain_consistency",
                            "no last_good published after completion"))
    elif result.last_good in set(result.tombstones):
        v.append(_violation(
            "chain_consistency",
            f"last_good {result.last_good} is tombstoned — the "
            "pointer vouches for a vetoed generation"))
    demoted = set(summary.get("demoted_steps") or [])
    if not demoted <= set(result.tombstones):
        v.append(_violation(
            "chain_consistency",
            f"demoted steps {sorted(demoted - set(result.tombstones))} "
            "carry no tombstone"))
    # A fresh follower must restore exactly the published generation.
    import jax
    from fm_spark_tpu import models
    from fm_spark_tpu.checkpoint import ChainFollower
    from fm_spark_tpu.train import TrainConfig, make_optimizer

    spec = models.FMSpec(num_features=cfg.num_features, rank=cfg.rank,
                         init_std=0.05)
    params = spec.init(jax.random.key(cfg.seed))
    opt_ex = make_optimizer(TrainConfig(
        optimizer="ftrl", learning_rate=cfg.learning_rate)).init(params)
    follower = ChainFollower(result.ckpt_dir)
    try:
        restored = follower.restore(params, opt_ex)
        if restored is None:
            v.append(_violation("chain_consistency",
                                "fresh follower restored nothing"))
        elif restored["step"] != result.last_good:
            v.append(_violation(
                "chain_consistency",
                f"follower restored step {restored['step']} != "
                f"last_good {result.last_good}"))
    finally:
        follower.close()
    if not schedule.stream_comparable:
        dead = read_events(result.deadletter_path)
        n_dead = sum(1 for e in dead if e.get("event") == "bad_record")
        if result.counters.get("bad", 0) > n_dead:
            v.append(_violation(
                "quarantine_accounting",
                f"guards counted {result.counters.get('bad')} bad "
                f"record(s) vs {n_dead} dead letter(s)"))
        if result.counters.get("bad", 0) == 0 and schedule.rules:
            v.append(_violation(
                "quarantine_accounting",
                "corruption rules active but nothing was quarantined"))
    v.extend(_audit_journal(result))
    return v


#: Worker for the hard-kill demotion drill: builds nothing, just runs
#: one demotion over an existing chain — the ``ckpt_demote`` fault
#: (via FM_SPARK_FAULTS) lands between the tombstone write and the
#: pointer republish, so an ``exit`` there IS the SIGKILL-mid-demotion
#: window.
_DEMOTE_WORKER = '''\
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from fm_spark_tpu.checkpoint import Checkpointer
ck = Checkpointer(sys.argv[1], save_every=1, async_save=False)
demoted = ck.demote_newer_than(int(sys.argv[2]),
                               reason="drill drift verdict")
ck.close()
import json
print(json.dumps({"demoted": demoted}))
'''


def run_demote_kill_drill(workdir: str, *, exit_rc: int = 23) -> dict:
    """The SIGKILL-at-any-point-during-demotion drill (ISSUE 13
    acceptance): a subprocess demotes the chain's newest saves and is
    hard-killed INSIDE the demotion window — after the (atomic, range)
    tombstone write, before the ``last_good`` republish. The audit
    then proves, from artifacts alone, that the chain recovered
    consistent: every reader lands on the PRE-DRIFT save even while
    the pointer is stale, and the recovery re-run repairs the pointer
    idempotently. Returns ``{"violations": [...], "rcs": [...]}``."""
    import numpy as np

    from fm_spark_tpu.checkpoint import ChainFollower, Checkpointer

    os.makedirs(workdir, exist_ok=True)
    ck_dir = os.path.join(workdir, "ck")
    ck = Checkpointer(ck_dir, save_every=1, async_save=False)
    for s in (1, 2, 3):
        ck.save(s, {"w": np.arange(4, dtype=np.float32) * s}, {},
                force=True)
    ck.close()
    worker = os.path.join(workdir, "demote_worker.py")
    with open(worker, "w") as f:
        f.write(_DEMOTE_WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FM_SPARK_OBS_DIR="none",
               PYTHONPATH=_REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               FM_SPARK_FAULTS=f"ckpt_demote@1=exit:{exit_rc}")
    v: list[dict] = []
    rcs = []
    proc = subprocess.run([sys.executable, worker, ck_dir, "1"],
                          cwd=_REPO, env=env, capture_output=True,
                          timeout=120)
    rcs.append(proc.returncode)
    if proc.returncode != exit_rc:
        v.append(_violation(
            "rc_discipline",
            f"demotion worker exited rc={proc.returncode}, expected "
            f"the injected {exit_rc}"))
    follower = ChainFollower(ck_dir)
    try:
        ex = {"w": np.zeros(4, np.float32)}
        if follower.tombstoned_steps() != {2, 3}:
            v.append(_violation(
                "chain_consistency",
                f"tombstones {sorted(follower.tombstoned_steps())} "
                "after the kill; the range stone must veto {2, 3} "
                "atomically"))
        restored = follower.restore(ex, {})
        if restored is None or restored["step"] != 1:
            v.append(_violation(
                "chain_consistency",
                f"reader restored "
                f"{restored and restored['step']} mid-demotion; must "
                "land on the pre-drift save 1 even with the pointer "
                "stale"))
    finally:
        follower.close()
    # Recovery: the re-run must be idempotent AND repair the pointer.
    env.pop("FM_SPARK_FAULTS")
    proc2 = subprocess.run([sys.executable, worker, ck_dir, "1"],
                           cwd=_REPO, env=env, capture_output=True,
                           timeout=120)
    rcs.append(proc2.returncode)
    if proc2.returncode != 0:
        v.append(_violation(
            "rc_discipline",
            f"recovery demotion re-run exited rc={proc2.returncode}: "
            f"{proc2.stderr.decode()[-200:]}"))
    ck2 = Checkpointer(ck_dir, save_every=1, async_save=False)
    try:
        if ck2.last_good_step() != 1:
            v.append(_violation(
                "chain_consistency",
                f"last_good {ck2.last_good_step()} after recovery; "
                "the pointer must republish at the pre-drift save 1"))
    finally:
        ck2.close()
    return {"violations": v, "rcs": rcs}


def run_drift_campaign(seeds=DRIFT_TIER1_SEEDS,
                       cfg: DriftDrillConfig | None = None,
                       base_dir: str | None = None) -> list[dict]:
    """The drift/rollback half of the chaos campaign: golden drilled
    run first (the planted drift WITH no faults), then every seed's
    schedule audited against it. Returns chaos_verdict-style entries
    (``tools/chaos_drill.py`` merges them into its verdict)."""
    import tempfile

    cfg = cfg or DriftDrillConfig()
    base_dir = base_dir or tempfile.mkdtemp(prefix="drift_")
    os.makedirs(base_dir, exist_ok=True)
    shard_state = build_drift_days(cfg, os.path.join(base_dir,
                                                     "shards"))
    golden = run_drift_schedule(
        Schedule(seed=-1, scenario="drift_golden", rules=()),
        cfg, os.path.join(base_dir, "golden"), shard_state=shard_state)
    if golden.outcome != "completed" or not (
            golden.summary or {}).get("rollbacks"):
        raise RuntimeError(
            f"golden drift drill failed ({golden.outcome}: "
            f"{golden.error}; rollbacks="
            f"{(golden.summary or {}).get('rollbacks')}) — the online "
            "workload itself is broken; no schedule verdict is "
            "meaningful")
    entries = []
    for seed in seeds:
        sched = drift_schedule(seed)
        t0 = time.perf_counter()
        result = run_drift_schedule(
            sched, cfg, os.path.join(base_dir, f"d{int(seed)}"),
            shard_state=shard_state)
        violations = audit_drift(sched, result, golden, cfg)
        # Rollback/demotion accounting spans ATTEMPTS (the journal),
        # not just the final attempt's summary — same policy as the
        # auditor's rollback invariant.
        journal_rollbacks = sum(
            1 for e in read_events(result.health_path)
            if e.get("event") == "online_rollback")
        entries.append({
            "seed": int(seed), "scenario": sched.scenario,
            "plan": sched.plan, "expects": "completed",
            "outcome": result.outcome,
            "verdict": "green" if not violations else "failed",
            "violations": violations,
            "duration_s": round(time.perf_counter() - t0, 3),
            "rollbacks": max((result.summary or {}).get("rollbacks")
                             or 0, journal_rollbacks),
            "demoted": sorted(set(
                (result.summary or {}).get("demoted_steps") or [])
                | set(result.tombstones)),
        })
    return entries


# ------------------------------------------- serving fleet (ISSUE 17)

#: Fleet/traffic drills: seeded compositions of millions-of-users
#: traffic SHAPES (serve/loadgen.py) with replica kills, dispatch
#: faults, and publish/demote races, run against a REAL multi-process
#: fleet (serve/fleet.py behind serve/frontdoor.py) and graded from
#: artifacts alone by :func:`chaos_audit.audit_fleet`.

#: Tier-1 fleet drill seeds (tools/chaos_drill.py folds the same three
#: into its default bounded campaign; soak runs five).
FLEET_TIER1_SEEDS = (0, 1, 2)
FLEET_SOAK_SEEDS = (0, 1, 2, 3, 4)

_FLEET_SCENARIOS = ("kill_flash_crowd", "retry_storm_demote",
                    "slow_client_shed", "dispatch_fault", "compound")


@dataclasses.dataclass(frozen=True)
class FleetSchedule:
    """One seeded fleet/traffic drill: a loadgen shape composed with
    parent-side fault rules, an optional mid-burst replica SIGKILL
    (fired after ``kill_after_ok`` answered requests), and an optional
    publish+demote race pressed against the replicas' reload pollers.
    Pure function of the seed, like every schedule here."""

    seed: int
    scenario: str
    shape: str
    rules: tuple = ()
    kill_after_ok: "int | None" = None
    demote_race: bool = False
    expects: str = "completed"

    @property
    def plan(self) -> str:
        return ";".join(self.rules)

    def validate(self) -> "FleetSchedule":
        faults.FaultPlan.from_spec(self.plan)
        from fm_spark_tpu.serve import loadgen

        if self.shape not in loadgen.SHAPES:
            raise ValueError(f"unknown traffic shape {self.shape!r}")
        return self


def fleet_schedule(seed: int) -> FleetSchedule:
    """Seeded fleet/traffic schedule — scenario chosen by ``seed % 5``
    so the tier-1 seeds cover the class, parameters drawn from the
    seeded rng.

    ``kill_flash_crowd``    SIGKILL a replica mid-flash-crowd: every
                            accepted request still answered exactly
                            once (retry-once against a live replica)
    ``retry_storm_demote``  a retry storm while the trainer publishes
                            AND demotes a generation under the
                            replicas' reload pollers: the demoted
                            generation never scores
    ``slow_client_shed``    slow clients hold handler threads while
                            interactive traffic keeps its deadline —
                            the deadline shed fires before the
                            coalescer
    ``dispatch_fault``      injected ``fleet_dispatch`` errors: the
                            retry-once path answers the request
                            elsewhere
    ``compound``            flash crowd + dispatch fault + replica
                            kill + demote race at once
    """
    rng = random.Random(int(seed))
    scenario = _FLEET_SCENARIOS[int(seed) % len(_FLEET_SCENARIOS)]
    shape, rules, kill, demote = "diurnal", [], None, False
    if scenario == "kill_flash_crowd":
        shape = "flash_crowd"
        kill = rng.randint(4, 12)
    elif scenario == "retry_storm_demote":
        shape = "retry_storm"
        demote = True
    elif scenario == "slow_client_shed":
        shape = "slow_clients"
        if rng.random() < 0.5:
            rules.append(
                f"frontdoor_accept@{rng.randint(2, 8)}=error")
    elif scenario == "dispatch_fault":
        shape = "diurnal"
        rules.append(f"fleet_dispatch@{rng.randint(1, 6)}=error")
    else:  # compound
        shape = "flash_crowd"
        rules.append(f"fleet_dispatch@{rng.randint(2, 8)}=error")
        kill = rng.randint(6, 14)
        demote = rng.random() < 0.7
    return FleetSchedule(int(seed), f"fleet_{scenario}", shape,
                         tuple(rules), kill_after_ok=kill,
                         demote_race=demote).validate()


@dataclasses.dataclass(frozen=True)
class FleetDrillConfig:
    """Fleet drill shape: small enough that a campaign over one shared
    two-replica fleet fits tier-1, hot enough that shed/kill/retry
    paths actually fire."""

    n_replicas: int = 2
    num_features: int = 256
    num_fields: int = 4
    bucket: int = 64
    rank: int = 4
    init_std: float = 0.1
    buckets: str = "1,4"
    latency_budget_ms: float = 2.0
    reload_poll_s: float = 0.15
    duration_s: float = 1.2
    base_rps: float = 50.0
    rows: int = 2
    deadline_ms: float = 2500.0
    classes: str = ("interactive:32:2500,batch:16:4000,"
                    "background:8:8000")
    threads: int = 8
    spawn_timeout_s: float = 300.0
    converge_timeout_s: float = 30.0
    #: > 0 arms the bidirectional autoscaler (serve/autoscale.py)
    #: with this replica ceiling — the partition campaign runs with
    #: it on so scale-up can race a partition; the plain fleet
    #: campaign keeps it off (fixed-size fleet, PR-17 semantics).
    autoscale_max: int = 0


def build_fleet_stack(cfg: FleetDrillConfig, base_dir: str) -> dict:
    """Build the shared drill stack: model dir, checkpoint chain (one
    verified step), a running N-replica fleet behind a front door.
    Returns the context dict the schedule runner mutates (chain step
    counter, tombstones). Caller owns ``ctx['door'].stop()``."""
    import jax

    from fm_spark_tpu import models
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.serve.fleet import Fleet
    from fm_spark_tpu.serve.frontdoor import (AdmissionController,
                                              FrontDoor)

    os.makedirs(base_dir, exist_ok=True)
    spec = models.FieldFMSpec(
        num_features=cfg.num_features, num_fields=cfg.num_fields,
        bucket=cfg.bucket, rank=cfg.rank, init_std=cfg.init_std)
    params = spec.init(jax.random.key(0))
    model_dir = os.path.join(base_dir, "model")
    models.save_model(model_dir, spec, params)
    chain_dir = os.path.join(base_dir, "chain")
    ck = Checkpointer(chain_dir, save_every=1, async_save=False)
    ck.save(1, params, {}, None, force=True)
    ck.wait()
    journal = EventLog(os.path.join(base_dir, "fleet_health.jsonl"))
    autoscaler = None
    if cfg.autoscale_max:
        from fm_spark_tpu.serve.autoscale import Autoscaler

        # Drill-tempo policy: the health poll is 0.25s, so 2 sustain
        # ticks = 0.5s of sustained shed before a grow, and a 24-tick
        # cooldown (~6s) guarantees the bounded-decision audit even
        # over a converge window.
        autoscaler = Autoscaler(
            min_replicas=cfg.n_replicas,
            max_replicas=max(cfg.autoscale_max, cfg.n_replicas),
            sustain_ticks=2, cooldown_ticks=24, journal=journal)
    fleet = Fleet(
        model_dir, n_replicas=cfg.n_replicas, chain_dir=chain_dir,
        work_dir=os.path.join(base_dir, "work"), journal=journal,
        buckets=cfg.buckets, latency_budget_ms=cfg.latency_budget_ms,
        reload_poll_s=cfg.reload_poll_s,
        compile_cache_dir=os.path.join(base_dir, "compile_cache"),
        spawn_timeout_s=cfg.spawn_timeout_s,
        autoscaler=autoscaler)
    fleet.start()
    door = FrontDoor(
        fleet, admission=AdmissionController(cfg.classes),
        journal=journal).start()
    return {"spec": spec, "params": params, "ck": ck,
            "chain_dir": chain_dir, "model_dir": model_dir,
            "fleet": fleet, "door": door, "journal": journal,
            "base_dir": base_dir, "step": 1, "tombstones": set()}


def _fleet_stats_delta(before: dict, after: dict) -> dict:
    return {k: int(after.get(k) or 0) - int(before.get(k) or 0)
            for k in ("accepted", "answered", "shed", "shed_queue",
                      "shed_deadline", "rejected", "timeout",
                      "failed", "retries")}


def _sigstop_publish_demote(ctx) -> int:
    """The demote race, made deterministic: SIGSTOP every replica (the
    reload pollers cannot observe the intermediate state), publish a
    new generation, demote it immediately, SIGCONT. Every poller then
    sees the tombstone before it could possibly swap — the veto path
    is exercised on every schedule instead of winning a wall-clock
    race."""
    import signal as _signal

    ck = ctx["ck"]
    fleet = ctx["fleet"]
    step = ctx["step"] + 1
    stopped = []
    for rep in fleet.replicas:
        if rep.proc is not None and rep.proc.poll() is None:
            try:
                os.kill(rep.proc.pid, _signal.SIGSTOP)
                stopped.append(rep.proc.pid)
            except OSError:
                pass
    try:
        ck.save(step, ctx["params"], {}, None, force=True)
        ck.wait()
        ck.demote(step, reason="fleet drill demote race")
    finally:
        for pid in stopped:
            try:
                os.kill(pid, _signal.SIGCONT)
            except OSError:
                pass
    ctx["step"] = step
    ctx["tombstones"].add(step)
    return step


def run_fleet_schedule(sched: FleetSchedule, cfg: FleetDrillConfig,
                       ctx: dict, out_dir: str) -> dict:
    """Run one fleet schedule against the shared stack and audit it
    from artifacts alone. Returns a chaos_verdict-style entry."""
    from fm_spark_tpu import obs as _obs
    from fm_spark_tpu.serve import loadgen

    os.makedirs(out_dir, exist_ok=True)
    door = ctx["door"]
    fleet = ctx["fleet"]
    schedule = loadgen.make_schedule(
        sched.shape, sched.seed, duration_s=cfg.duration_s,
        base_rps=cfg.base_rps, rows=cfg.rows,
        deadline_ms=cfg.deadline_ms)
    tap_path = os.path.join(out_dir, "tap.jsonl")
    before = door.stats()
    killed = None
    stop_watch = threading.Event()

    def kill_watcher():
        """SIGKILL a ready replica once ``kill_after_ok`` answers have
        landed — mid-burst by construction."""
        reg = _obs.registry()
        base = int(reg.peek("frontdoor.answered_total") or 0)
        while not stop_watch.wait(0.01):
            done = int(reg.peek("frontdoor.answered_total") or 0)
            if done - base >= sched.kill_after_ok:
                with fleet._lock:
                    ready = [r for r in fleet.replicas
                             if r.state == "ready"
                             and r.proc is not None]
                if ready:
                    rep = ready[sched.seed % len(ready)]
                    try:
                        os.kill(rep.proc.pid, 9)
                        nonlocal killed
                        killed = rep.idx
                    except OSError:
                        pass
                return

    watcher = None
    if sched.kill_after_ok is not None:
        watcher = threading.Thread(target=kill_watcher,
                                   name="fleet-kill-watcher",
                                   daemon=True)
        watcher.start()
    demoted_step = None
    t0 = time.perf_counter()
    if sched.plan:
        faults.activate(sched.plan)
    try:
        if sched.demote_race:
            # Fire the race ~mid-replay from a timer so traffic is in
            # flight when the publish+demote lands.
            race_timer = threading.Timer(
                0.4 * cfg.duration_s,
                lambda: ctx.update(
                    _race_step=_sigstop_publish_demote(ctx)))
            race_timer.start()
        loadgen.run_loadgen(
            "127.0.0.1", door.port, schedule, tap_path,
            nnz=cfg.num_fields, num_features=cfg.num_features,
            threads=cfg.threads)
        if sched.demote_race:
            race_timer.join()
            demoted_step = ctx.pop("_race_step", None)
    finally:
        faults.clear()
        stop_watch.set()
        if watcher is not None:
            watcher.join(timeout=5.0)
    # Close the books: every admitted request must reach a terminal
    # outcome before the counter snapshot is meaningful.
    deadline = time.monotonic() + cfg.converge_timeout_s
    while time.monotonic() < deadline:
        snap = door.admission.snapshot()
        if not any(snap["inflight"].values()):
            break
        time.sleep(0.05)
    violations = []
    # Recovery + convergence: after a kill, the fleet must re-admit a
    # respawned replica through the readiness gate, and every live
    # replica must converge to the same non-tombstoned tip.
    tip = ctx["step"] if not ctx["tombstones"] else max(
        s for s in range(1, ctx["step"] + 1)
        if s not in ctx["tombstones"])
    recovered_s = None
    t_rec = time.monotonic()
    while time.monotonic() - t_rec < cfg.converge_timeout_s:
        h = fleet.healthz()
        live = [r for r in h["replicas"] if r["state"] != "retired"]
        if (live and all(r["state"] == "ready" for r in live)
                and all(r["generation_step"] == tip for r in live)):
            recovered_s = time.monotonic() - t_rec
            break
        time.sleep(0.05)
    if recovered_s is None:
        h = fleet.healthz()
        states = [(r.get("replica"), r.get("state"),
                   r.get("generation_step")) for r in h["replicas"]]
        violations.append({
            "invariant": "staleness_bounded",
            "detail": f"fleet did not converge to tip {tip} within "
                      f"{cfg.converge_timeout_s:.0f}s: {states}"})
    counters = _fleet_stats_delta(before, door.stats())
    tap_events = read_events(tap_path)
    replica_events = {}
    for rep in fleet.replicas:
        jpath = os.path.join(fleet.work_dir,
                             f"replica_{rep.idx}.jsonl")
        if os.path.exists(jpath):
            replica_events[rep.idx] = read_events(jpath)
    violations.extend(audit_fleet(
        tap_events, counters,
        expected_requests=schedule.n_requests,
        tombstoned_steps=ctx["tombstones"],
        replica_events=replica_events))
    summary = loadgen.summarize_tap(tap_path)
    return {
        "seed": sched.seed, "scenario": sched.scenario,
        "plan": sched.plan, "expects": sched.expects,
        "outcome": "completed",
        "verdict": "green" if not violations else "failed",
        "violations": violations,
        "duration_s": round(time.perf_counter() - t0, 3),
        "traffic": {"shape": sched.shape,
                    "requests": schedule.n_requests,
                    **{k: summary["by_outcome"].get(k, 0)
                       for k in ("ok", "shed", "error", "timeout")}},
        "killed_replica": killed,
        "demoted_step": demoted_step,
        "recovery_s": (round(recovered_s, 3)
                       if recovered_s is not None else None),
        "counters": counters,
    }


def run_fleet_campaign(seeds=FLEET_TIER1_SEEDS,
                       cfg: "FleetDrillConfig | None" = None,
                       base_dir: "str | None" = None) -> list[dict]:
    """The fleet/traffic half of the chaos campaign: one shared
    two-replica fleet, every seed's schedule replayed against it
    (faults cleared between schedules; counter deltas audited per
    schedule). Returns chaos_verdict-style entries."""
    import tempfile

    cfg = cfg or FleetDrillConfig()
    base_dir = base_dir or tempfile.mkdtemp(prefix="fleet_drill_")
    ctx = build_fleet_stack(cfg, base_dir)
    entries = []
    try:
        for seed in seeds:
            sched = fleet_schedule(seed)
            entries.append(run_fleet_schedule(
                sched, cfg, ctx,
                os.path.join(base_dir, f"f{int(seed)}")))
    finally:
        ctx["door"].stop()
        ctx["ck"].close()
    return entries


# ------------------------------------ partition chaos (ISSUE 19)

#: Partition drills: the network-fault plane
#: (resilience/netfaults.py) composed with traffic shapes — the
#: scenario the process-kill model cannot express: the parent loses
#: the LINK to a replica whose process stays perfectly healthy.
#: Graded by the partition extensions of :func:`audit_fleet`
#: (partition_not_a_crash, autoscale_converged) on top of the usual
#: exactly-once/closed-books contracts.

PARTITION_TIER1_SEEDS = (0, 1, 2)

_PARTITION_SCENARIOS = ("partition_flash_crowd", "slow_link_reload",
                        "truncate_retry_storm",
                        "scaleup_race_partition")


@dataclasses.dataclass(frozen=True)
class PartitionSchedule:
    """One seeded partition drill: net-fault rules (peer-scoped
    occurrence windows over ``net_connect``/``net_send``/``net_recv``)
    composed with a loadgen shape, optionally with a mid-replay chain
    publish pressed through the slow link. ``victim`` names the
    replica the parent is partitioned from (None: the fault is
    fleet-wide, not a partition). Pure function of the seed."""

    seed: int
    scenario: str
    shape: str
    rules: tuple = ()
    victim: "int | None" = None
    publish_mid_replay: bool = False
    expects: str = "completed"

    @property
    def plan(self) -> str:
        return ";".join(self.rules)

    def validate(self) -> "PartitionSchedule":
        faults.FaultPlan.from_spec(self.plan)
        from fm_spark_tpu.serve import loadgen

        if self.shape not in loadgen.SHAPES:
            raise ValueError(f"unknown traffic shape {self.shape!r}")
        return self


def partition_schedule(seed: int,
                       n_replicas: int = 2) -> PartitionSchedule:
    """Seeded partition drill — scenario by ``seed % 4``, parameters
    from the seeded rng (same purity contract as every schedule: the
    failing entry IS its repro).

    ``partition_flash_crowd``   the parent loses one replica's link
                                (dials refused, writes reset) right as
                                a flash crowd lands: accepted traffic
                                retries onto the surviving replica,
                                the victim is drained then readmitted
                                after heal — never respawned
    ``slow_link_reload``        one replica's response reads gain tens
                                of ms of injected latency while the
                                trainer publishes a new generation:
                                the fleet converges to the tip anyway
    ``truncate_retry_storm``    fleet-wide response truncations under
                                a retry storm: a truncated response is
                                recv-phase — NEVER replayed on another
                                replica (the 503 goes back to the
                                client, whose own retry keeps the
                                books exactly-once)
    ``scaleup_race_partition``  a partition_storm sheds hard enough to
                                wake the autoscaler while one replica
                                is partitioned away: grow races drain,
                                and the decision log must stay bounded
    """
    rng = random.Random(0x5EED ^ (int(seed) << 4))
    scenario = _PARTITION_SCENARIOS[int(seed)
                                    % len(_PARTITION_SCENARIOS)]
    victim: "int | None" = rng.randrange(max(1, int(n_replicas)))
    publish = False
    if scenario == "partition_flash_crowd":
        shape = "flash_crowd"
        # Window sized in OCCURRENCES (each health poll consumes one
        # dial, each dispatch write one send): wide enough that the
        # victim is reliably drained mid-crowd; the runner's
        # faults.clear() after replay is the heal.
        k = rng.randint(20, 32)
        rules = (f"net_connect.replica-{victim}@1-{k}=refuse",
                 f"net_send.replica-{victim}@1-{k}=reset")
    elif scenario == "slow_link_reload":
        shape = "diurnal"
        ms = rng.choice((20, 40, 60))
        k = rng.randint(12, 24)
        rules = (f"net_recv.replica-{victim}@1-{k}=slow_ms:{ms}",)
        victim = None   # slow, not severed: no drain is required
        publish = True
    elif scenario == "truncate_retry_storm":
        shape = "retry_storm"
        cut = rng.choice((5, 16, 48))
        occs = sorted(rng.sample(range(3, 40), 3))
        rules = tuple(f"net_recv@{n}=truncate_after:{cut}"
                      for n in occs)
        victim = None   # fleet-wide recv faults, not a partition
    else:  # scaleup_race_partition
        shape = "partition_storm"
        k = rng.randint(20, 32)
        rules = (f"net_connect.replica-{victim}@1-{k}=refuse",
                 f"net_send.replica-{victim}@1-{k}=reset")
    return PartitionSchedule(int(seed), scenario, shape,
                             tuple(rules), victim=victim,
                             publish_mid_replay=publish).validate()


def _publish_step(ctx) -> int:
    """Publish one new (non-demoted) generation mid-replay: the
    reload traffic a slow link must carry without wedging the
    follower."""
    ck = ctx["ck"]
    step = ctx["step"] + 1
    ck.save(step, ctx["params"], {}, None, force=True)
    ck.wait()
    ctx["step"] = step
    return step


def run_partition_schedule(sched: PartitionSchedule,
                           cfg: FleetDrillConfig, ctx: dict,
                           out_dir: str) -> dict:
    """Run one partition schedule against the shared stack; grade it
    from artifacts alone (tap + counters + the run's own slice of
    ``fleet_health.jsonl``)."""
    from fm_spark_tpu.serve import loadgen

    os.makedirs(out_dir, exist_ok=True)
    door = ctx["door"]
    fleet = ctx["fleet"]
    journal_path = os.path.join(ctx["base_dir"],
                                "fleet_health.jsonl")
    n_journal0 = len(read_events(journal_path))
    schedule = loadgen.make_schedule(
        sched.shape, sched.seed, duration_s=cfg.duration_s,
        base_rps=cfg.base_rps, rows=cfg.rows,
        deadline_ms=cfg.deadline_ms)
    tap_path = os.path.join(out_dir, "tap.jsonl")
    before = door.stats()
    published_step = None
    t0 = time.perf_counter()
    faults.activate(sched.plan)
    try:
        pub_timer = None
        if sched.publish_mid_replay:
            pub_timer = threading.Timer(
                0.4 * cfg.duration_s,
                lambda: ctx.update(_pub_step=_publish_step(ctx)))
            pub_timer.start()
        loadgen.run_loadgen(
            "127.0.0.1", door.port, schedule, tap_path,
            nnz=cfg.num_fields, num_features=cfg.num_features,
            threads=cfg.threads)
        if pub_timer is not None:
            pub_timer.join()
            published_step = ctx.pop("_pub_step", None)
    finally:
        # The heal: whatever occurrence window is left, the plan
        # clears here — readmission is graded below.
        faults.clear()
    deadline = time.monotonic() + cfg.converge_timeout_s
    while time.monotonic() < deadline:
        snap = door.admission.snapshot()
        if not any(snap["inflight"].values()):
            break
        time.sleep(0.05)
    violations = []
    tip = ctx["step"] if not ctx["tombstones"] else max(
        s for s in range(1, ctx["step"] + 1)
        if s not in ctx["tombstones"])
    healed_s = None
    t_rec = time.monotonic()
    while time.monotonic() - t_rec < cfg.converge_timeout_s:
        h = fleet.healthz()
        live = [r for r in h["replicas"]
                if r["state"] not in ("retired", "parked")]
        if (live and all(r["state"] == "ready" for r in live)
                and all(r["generation_step"] == tip for r in live)):
            healed_s = time.monotonic() - t_rec
            break
        time.sleep(0.05)
    if healed_s is None:
        h = fleet.healthz()
        states = [(r.get("replica"), r.get("state"),
                   r.get("generation_step")) for r in h["replicas"]]
        violations.append({
            "invariant": "partition_not_a_crash",
            "detail": f"fleet did not heal to tip {tip} within "
                      f"{cfg.converge_timeout_s:.0f}s of the plan "
                      f"clearing: {states}"})
    counters = _fleet_stats_delta(before, door.stats())
    replica_events = {}
    for rep in fleet.replicas:
        jpath = os.path.join(fleet.work_dir,
                             f"replica_{rep.idx}.jsonl")
        if os.path.exists(jpath):
            replica_events[rep.idx] = read_events(jpath)
    fleet_events = read_events(journal_path)[n_journal0:]
    violations.extend(audit_fleet(
        read_events(tap_path), counters,
        expected_requests=schedule.n_requests,
        tombstoned_steps=ctx["tombstones"],
        replica_events=replica_events,
        fleet_events=fleet_events,
        partition_victim=sched.victim,
        max_autoscale_decisions=(3 if fleet.autoscaler is not None
                                 else None)))
    summary = loadgen.summarize_tap(tap_path)
    n_decisions = sum(
        1 for e in fleet_events
        if (e.get("event") or e.get("kind")) == "autoscale_decision")
    return {
        "seed": sched.seed, "scenario": sched.scenario,
        "plan": sched.plan, "expects": sched.expects,
        "outcome": "completed",
        "verdict": "green" if not violations else "failed",
        "violations": violations,
        "duration_s": round(time.perf_counter() - t0, 3),
        "traffic": {"shape": sched.shape,
                    "requests": schedule.n_requests,
                    **{k: summary["by_outcome"].get(k, 0)
                       for k in ("ok", "shed", "error", "timeout")}},
        "victim": sched.victim,
        "published_step": published_step,
        "autoscale_decisions": n_decisions,
        "healed_s": (round(healed_s, 3)
                     if healed_s is not None else None),
        "counters": counters,
    }


def run_partition_campaign(seeds=PARTITION_TIER1_SEEDS,
                           cfg: "FleetDrillConfig | None" = None,
                           base_dir: "str | None" = None
                           ) -> list[dict]:
    """The partition half of the fleet chaos campaign: one shared
    fleet WITH the autoscaler armed (scale-up must be able to race a
    partition), every seed's schedule replayed against it, faults
    cleared between schedules."""
    import tempfile

    cfg = cfg or FleetDrillConfig(autoscale_max=3)
    base_dir = base_dir or tempfile.mkdtemp(prefix="partition_drill_")
    ctx = build_fleet_stack(cfg, base_dir)
    entries = []
    try:
        for seed in seeds:
            sched = partition_schedule(seed,
                                       n_replicas=cfg.n_replicas)
            entries.append(run_partition_schedule(
                sched, cfg, ctx,
                os.path.join(base_dir, f"p{int(seed)}")))
    finally:
        ctx["door"].stop()
        ctx["ck"].close()
    return entries


# --------------------------------------------------------------------
# Storage-fault drills (ISSUE 20): the disk plane over the durable seam.

DISK_TIER1_SEEDS = (0, 1, 2, 3, 4)

_DISK_SCENARIOS = ("enospc_ckpt_commit", "torn_rename_demote",
                   "slow_disk_day_save", "eio_flight_compact",
                   "readonly_obs_flip")


@dataclasses.dataclass(frozen=True)
class DiskSchedule:
    """One seeded disk drill: ``io_*`` rules (path-class-scoped
    occurrence windows over the durable seam) composed with a
    checkpoint-chain shape — setup saves, an optional demotion
    (optionally UNDER the plan, racing a chain follower), then final
    saves with the plan armed. Pure function of the seed."""

    seed: int
    scenario: str
    rules: tuple = ()
    setup_saves: int = 3
    final_saves: int = 1
    demote_cut: "int | None" = None
    demote_armed: bool = False
    arm_at_start: bool = False
    expects: str = "completed"

    @property
    def plan(self) -> str:
        return ";".join(self.rules)

    def validate(self) -> "DiskSchedule":
        if self.rules:
            faults.FaultPlan.from_spec(self.plan)
        return self


def disk_schedule(seed: int) -> DiskSchedule:
    """Seeded disk drill — scenario by ``seed % 5``, parameters from
    the seeded rng (same purity contract as every schedule: the
    failing entry IS its repro).

    ``enospc_ckpt_commit``  the disk fills exactly at the next
                            checkpoint commit, with demoted
                            generations sitting on it: the emergency
                            GC journals its intent, frees the
                            tombstoned steps, and the SAME commit
                            retries through — loud failure only if
                            the disk is full of live data
    ``torn_rename_demote``  the atomic rename publishing a demotion's
                            range tombstone fails mid-demotion while
                            a serve-reload follower restores
                            concurrently: the follower sees the old
                            tip or the walk-back target, NEVER a torn
                            pointer or a condemned step
    ``slow_disk_day_save``  multi-tick fsync stalls land on the
                            day-boundary save: slower, never wronger
                            (latency scaled by FM_SPARK_TEST_SLEEP_
                            SCALE)
    ``eio_flight_compact``  an EIO burst lands mid flight-spool
                            compaction: the ring keeps recording, the
                            append handle is re-established, on-disk
                            seqs never regress, training bytes are
                            byte-identical to the golden run
    ``readonly_obs_flip``   the filesystem flips read-only under the
                            WHOLE obs plane: every telemetry write
                            fails best-effort, counted and flagged
                            (``obs/io_degraded``), and the final
                            params are byte-identical to golden
    """
    rng = random.Random(0xD15C ^ (int(seed) << 4))
    scenario = _DISK_SCENARIOS[int(seed) % len(_DISK_SCENARIOS)]
    if scenario == "enospc_ckpt_commit":
        # One ENOSPC: the emergency GC frees the demoted generations
        # and the retry lands. Two: the disk is "full of live data"
        # even after GC — the loud CheckpointIOError is the DESIGNED
        # outcome, classified by the supervisor, never a silent loss.
        k = rng.randint(1, 2)
        return DiskSchedule(
            int(seed), scenario,
            (f"io_write.ckpt@1-{k}=enospc",),
            demote_cut=1,
            expects=("completed" if k == 1
                     else "checkpoint_io_error")).validate()
    if scenario == "torn_rename_demote":
        rule = rng.choice(("io_rename.ckpt@1=eio",
                           f"io_rename.ckpt@1=torn_write:"
                           f"{rng.choice((3, 9, 17))}"))
        return DiskSchedule(
            int(seed), scenario, (rule,),
            final_saves=0, demote_cut=1,
            demote_armed=True).validate()
    if scenario == "slow_disk_day_save":
        ms = rng.choice((40, 80, 120))
        k = rng.randint(2, 4)
        return DiskSchedule(
            int(seed), scenario,
            (f"io_fsync.ckpt@1-{k}=slow_ms:{ms}",)).validate()
    if scenario == "eio_flight_compact":
        lo = rng.randint(6, 12)
        hi = lo + rng.randint(10, 30)
        return DiskSchedule(
            int(seed), scenario,
            (f"io_write.obs@{lo}-{hi}=eio",),
            setup_saves=4, final_saves=0,
            arm_at_start=True).validate()
    # readonly_obs_flip
    return DiskSchedule(
        int(seed), scenario,
        ("io_write.obs@1-512=readonly",),
        setup_saves=4, final_saves=0,
        arm_at_start=True).validate()


def _disk_step(params: dict, step: int) -> dict:
    """One deterministic numpy 'train step': pure function of
    (params, step), with NO dependence on the obs/disk plane — the
    byte-identity invariant's whole point."""
    import numpy as np

    w = params["w"]
    return {"w": (w * np.float32(0.75)
                  + np.sin(np.arange(w.size, dtype=np.float32)
                           * np.float32(step))).astype(np.float32)}


def run_disk_schedule(sched: DiskSchedule, workdir: str,
                      golden_sums: "dict | None" = None) -> dict:
    """Run one disk schedule against a fresh lightweight stack
    (Checkpointer + FlightRecorder + EventLog journal over numpy
    params — the durable surface without a jax trainer) and grade it
    from artifacts alone via :func:`audit_disk`."""
    import numpy as np

    from fm_spark_tpu import obs
    from fm_spark_tpu.checkpoint import (
        ChainFollower,
        Checkpointer,
        CheckpointIOError,
    )
    from fm_spark_tpu.obs.flight import FlightRecorder, read_spool
    from fm_spark_tpu.utils import durable

    os.makedirs(workdir, exist_ok=True)
    ck_dir = os.path.join(workdir, "ck")
    obs_dir = os.path.join(workdir, "obs")
    os.makedirs(obs_dir, exist_ok=True)
    spool_path = os.path.join(obs_dir, "flight_spool.jsonl")
    journal_path = os.path.join(obs_dir, "events.jsonl")
    # Small capacity: 4 ticks/step compacts the spool every other
    # step, so compaction itself sits inside every fault window.
    flight = FlightRecorder(capacity=8, spool_path=spool_path)
    journal = EventLog(journal_path)
    ck = Checkpointer(ck_dir, save_every=1, max_to_keep=16,
                      async_save=False, journal=journal)
    fails0 = dict(durable.io_failure_counts())
    params = {"w": np.zeros(16, np.float32)}
    example = {"w": np.zeros(16, np.float32)}
    step = 0
    outcome, err = "completed", None
    follower_samples: list = []
    t0 = time.perf_counter()

    def _tick(s: int) -> dict:
        p = _disk_step(params, s)
        for i in range(4):
            flight.record("disk_drill_tick", step=s, i=i)
        journal.emit("disk_drill_step", step=s)
        ck.save(s, p, {}, force=True)
        return p

    try:
        if sched.arm_at_start and sched.rules:
            faults.activate(sched.plan)
        for _ in range(sched.setup_saves):
            step += 1
            params = _tick(step)
        if sched.demote_cut is not None:
            stop = threading.Event()
            sampler = None
            if sched.demote_armed:
                faults.activate(sched.plan)

                def _poll() -> None:
                    # The racing serve reload: a follower restoring
                    # WHILE the demotion's stone publish is failing.
                    fol = ChainFollower(ck_dir)
                    ex = {"w": np.zeros(16, np.float32)}
                    try:
                        while not stop.is_set():
                            r = fol.restore(ex, {})
                            follower_samples.append(
                                None if r is None else int(r["step"]))
                            time.sleep(0.002)
                    finally:
                        fol.close()

                sampler = threading.Thread(target=_poll, daemon=True)
                sampler.start()
            try:
                ck.demote_newer_than(sched.demote_cut,
                                     reason=f"disk drill "
                                            f"{sched.scenario}")
            finally:
                stop.set()
                if sampler is not None:
                    sampler.join(timeout=30)
        if sched.final_saves and not sched.arm_at_start and sched.rules:
            faults.activate(sched.plan)
        for _ in range(sched.final_saves):
            step += 1
            params = _tick(step)
    except CheckpointIOError as e:
        outcome, err = "checkpoint_io_error", str(e)
    except OSError as e:
        outcome, err = f"oserror:{e.errno}", str(e)
    finally:
        # The heal: whatever occurrence window is left, the plan
        # clears here — recovery is graded below.
        faults.clear()
        try:
            ck.close()
        except Exception:
            pass
    # Post-heal: the obs plane must still accept writes (the append
    # handle was re-established), and a FRESH reader grades the chain.
    flight.record("disk_drill_healed", step=step)
    journal.emit("disk_drill_healed", step=step)
    fails = {k: v - fails0.get(k, 0)
             for k, v in durable.io_failure_counts().items()}
    follower = ChainFollower(ck_dir)
    try:
        committed = sorted(follower._manifest_steps())
        stones = follower.tombstoned_steps()
        last_good = follower.last_good_step()
        restored = follower.restore(example, {})
        restored_step = (None if restored is None
                         else int(restored["step"]))
    finally:
        follower.close()
    gauges = obs.registry().snapshot().get("gauges", {})
    if sched.demote_cut is not None:
        surviving = {sched.demote_cut}
        if sched.expects == "completed":
            # Post-demotion saves only commit when the run completes;
            # a designed-loud failure leaves just the walk-back target.
            surviving |= set(range(sched.setup_saves + 1,
                                   sched.setup_saves
                                   + sched.final_saves + 1))
    else:
        surviving = set(range(1, step + 1))
    sums = _params_sums(params)
    violations = audit_disk(
        committed_steps=committed, tombstoned_steps=stones,
        last_good_step=last_good, restored_step=restored_step,
        expected_surviving=surviving,
        io_failures=fails,
        degraded_gauge=gauges.get("obs/io_degraded"),
        params_match=(None if golden_sums is None
                      else sums == golden_sums),
        spool_seqs=[r["seq"] for r in read_spool(spool_path)
                    if "seq" in r])
    if sched.demote_armed:
        # The race's own invariant: every concurrent restore landed on
        # the old tip or the walk-back target — never a condemned step,
        # never nothing.
        allowed = {sched.setup_saves, sched.demote_cut}
        bad = sorted({s for s in follower_samples
                      if s not in allowed}, key=str)
        if bad or not follower_samples:
            violations.append(_violation(
                "chain_never_broken",
                f"racing follower observed restores {bad or '(none)'} "
                f"mid-demotion; only {sorted(allowed)} are "
                "consistent states"))
    if outcome != sched.expects:
        violations.append(_violation(
            "outcome_expected",
            f"outcome {outcome!r} (expected {sched.expects!r})"
            + (f": {err}" if err else "")))
    if (any("io_write.obs" in r for r in sched.rules)
            and not fails.get("obs")):
        violations.append(_violation(
            "degradation_signaled",
            "plan targets the obs path class but no obs write "
            "failure was recorded — the fault never reached the "
            "durable seam"))
    events = read_events(journal_path)
    kinds = [e.get("event") or e.get("kind") for e in events]
    return {
        "seed": sched.seed, "scenario": sched.scenario,
        "plan": sched.plan, "expects": sched.expects,
        "outcome": outcome, "error": err,
        "verdict": "green" if not violations else "failed",
        "violations": violations,
        "duration_s": round(time.perf_counter() - t0, 3),
        "last_good": last_good, "restored_step": restored_step,
        "committed_steps": committed,
        "tombstoned_steps": sorted(stones),
        "io_failures": fails,
        "io_retries": kinds.count("ckpt_io_retry"),
        "emergency_gcs": kinds.count("ckpt_emergency_gc"),
        "follower_samples": sorted(
            {s for s in follower_samples}, key=str),
        "steps_done": step,
        "params_sums": sums,
    }


_GC_WORKER = '''\
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from fm_spark_tpu.checkpoint import Checkpointer
from fm_spark_tpu.resilience import faults
ck_dir, plan, target = sys.argv[1], sys.argv[2], int(sys.argv[3])
ck = Checkpointer(ck_dir, save_every=1, max_to_keep=16,
                  async_save=False)
if ck.last_good_step() is None:
    for s in (1, 2, 3):
        ck.save(s, {"w": np.arange(4, dtype=np.float32) * s}, {},
                force=True)
    ck.demote_newer_than(1, reason="gc drill drift verdict")
if plan:
    faults.activate(plan)
ck.save(target, {"w": np.arange(4, dtype=np.float32) * target}, {},
        force=True)
ck.close()
print("gc drill save", target, "ok")
'''


def run_gc_kill_drill(workdir: str, *, exit_rc: int = 29) -> dict:
    """The SIGKILL-during-emergency-GC drill (ISSUE 20 acceptance): a
    subprocess hits ENOSPC at a checkpoint commit with demoted
    generations on disk, and is hard-killed INSIDE the emergency GC —
    after the ``ckpt_emergency_gc`` intent event, before any deletion
    (the ``ckpt_gc`` fault point). The audit proves, from artifacts
    alone, that every reader still lands on a loadable ``last_good``,
    and that a recovery re-run completes a later commit cleanly.
    Returns ``{"violations": [...], "rcs": [...]}``."""
    import numpy as np

    from fm_spark_tpu.checkpoint import ChainFollower

    os.makedirs(workdir, exist_ok=True)
    ck_dir = os.path.join(workdir, "ck")
    worker = os.path.join(workdir, "gc_worker.py")
    with open(worker, "w") as f:
        f.write(_GC_WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FM_SPARK_OBS_DIR="none",
               PYTHONPATH=_REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    plan = f"io_write.ckpt@1=enospc;ckpt_gc@1=exit:{exit_rc}"
    v: list[dict] = []
    rcs = []
    proc = subprocess.run([sys.executable, worker, ck_dir, plan, "4"],
                          cwd=_REPO, env=env, capture_output=True,
                          timeout=180)
    rcs.append(proc.returncode)
    if proc.returncode != exit_rc:
        v.append(_violation(
            "rc_discipline",
            f"gc worker exited rc={proc.returncode}, expected the "
            f"injected {exit_rc}: {proc.stderr.decode()[-300:]}"))
    ex = {"w": np.zeros(4, np.float32)}
    follower = ChainFollower(ck_dir)
    try:
        restored = follower.restore(ex, {})
        v.extend(audit_disk(
            committed_steps=follower._manifest_steps(),
            tombstoned_steps=follower.tombstoned_steps(),
            last_good_step=follower.last_good_step(),
            restored_step=(None if restored is None
                           else int(restored["step"]))))
        if restored is None or restored["step"] != 1:
            v.append(_violation(
                "chain_never_broken",
                f"reader restored "
                f"{restored and restored['step']} after the mid-GC "
                "kill; must land on the pre-drift save 1"))
    finally:
        follower.close()
    # Recovery: a clean re-run commits the NEXT step; the torn step-4
    # commit (orbax data, no manifest) stays invisible to readers.
    proc2 = subprocess.run([sys.executable, worker, ck_dir, "", "5"],
                           cwd=_REPO, env=env, capture_output=True,
                           timeout=180)
    rcs.append(proc2.returncode)
    if proc2.returncode != 0:
        v.append(_violation(
            "rc_discipline",
            f"recovery re-run exited rc={proc2.returncode}: "
            f"{proc2.stderr.decode()[-300:]}"))
    follower2 = ChainFollower(ck_dir)
    try:
        restored2 = follower2.restore(ex, {})
        v.extend(audit_disk(
            committed_steps=follower2._manifest_steps(),
            tombstoned_steps=follower2.tombstoned_steps(),
            last_good_step=follower2.last_good_step(),
            restored_step=(None if restored2 is None
                           else int(restored2["step"])),
            expected_surviving={1, 5}))
        if follower2.last_good_step() != 5:
            v.append(_violation(
                "last_good_loadable",
                f"last_good {follower2.last_good_step()} after "
                "recovery; the re-run's commit must republish at 5"))
    finally:
        follower2.close()
    return {"violations": v, "rcs": rcs}


def run_disk_campaign(seeds=DISK_TIER1_SEEDS,
                      base_dir: "str | None" = None,
                      include_kill_drill: bool = True) -> list[dict]:
    """The storage half of the chaos campaign: golden run first (the
    identical stack, no faults — the byte-identity baseline), then
    every seed's schedule against a FRESH stack, then the
    SIGKILL-during-emergency-GC subprocess drill. Returns
    chaos_verdict-style entries."""
    import tempfile

    base_dir = base_dir or tempfile.mkdtemp(prefix="disk_drill_")
    golden = run_disk_schedule(
        DiskSchedule(-1, "golden", (), setup_saves=4, final_saves=0),
        os.path.join(base_dir, "golden"))
    golden["scenario"] = "golden"
    entries = [golden]
    for seed in seeds:
        sched = disk_schedule(seed)
        # Byte-identity only compares runs that took the same number
        # of steps AND expect to complete them; designed-loud or
        # shorter schedules are graded on chain invariants alone.
        total = sched.setup_saves + sched.final_saves
        comparable = (sched.expects == "completed"
                      and total == golden["steps_done"])
        entries.append(run_disk_schedule(
            sched, os.path.join(base_dir, f"d{int(seed)}"),
            golden_sums=(golden["params_sums"]
                         if comparable else None)))
    if include_kill_drill:
        kill = run_gc_kill_drill(os.path.join(base_dir, "gc_kill"))
        entries.append({
            "seed": None, "scenario": "gc_kill_recovery",
            "plan": "io_write.ckpt@1=enospc;ckpt_gc@1=exit:29",
            "expects": "killed_then_recovered",
            "outcome": "killed_then_recovered",
            "verdict": ("green" if not kill["violations"]
                        else "failed"),
            "violations": kill["violations"],
            "rcs": kill["rcs"],
        })
    return entries


#: Re-export: the auditor lives in the standalone, import-free
#: :mod:`fm_spark_tpu.resilience.chaos_audit` so jax-light tools
#: (tools/run_doctor.py) can load it BY PATH without importing the
#: package; the chaos API keeps its name here.
from fm_spark_tpu.resilience.chaos_audit import (  # noqa: E402
    audit_disk,
    audit_fleet,
    audit_serve_events,
)
