"""Per-coordinate adaptive optimizers for hashed-sparse CTR training.

Continuous CTR training (ROADMAP item 5 / ISSUE 13) is where plain SGD
stops being the reference answer: hashed feature frequencies span five
orders of magnitude, so a single global learning rate either burns the
head ids or never moves the tail. The standard fixes — per-coordinate
AdaGrad and FTRL-Proximal (McMahan et al., "Ad Click Prediction: a View
from the Trenches") — keep one or two scalar slots PER COORDINATE and
derive each coordinate's own step size from its accumulated gradient
history. This module provides both, in two forms that share one set of
update rules:

- **Dense optax form** (:func:`ftrl`): a ``GradientTransformation`` for
  the generic optax train step (strategy ``single``/``dp``/``row``) —
  ``train.make_optimizer`` routes ``TrainConfig.optimizer='ftrl'`` here,
  so ``cli train --optimizer ftrl`` works everywhere the dense step
  does, and the z/n slots ride checkpoints inside ``opt_state`` like
  any optax state. AdaGrad's dense form stays ``optax.adagrad`` (it
  predates this module).

- **Sparse row form** (:func:`make_sparse_adaptive_step`): the fused
  flat-FM analog of ``sparse.make_sparse_sgd_step``, riding the SAME
  dedup/scatter machinery (:func:`fm_spark_tpu.ops.scatter._dedup`'s
  segment sums + out-of-range-sentinel set-semantics writes): per-batch
  gradients are segment-summed per unique id, the touched rows AND
  their slot rows are gathered once, updated with the per-coordinate
  rule, and written back with one set per unique id — the slot tables
  never see a dense gradient. Dense parameter slots (the bias ``w0``)
  are deliberately EXCLUDED from the sparse slot set and keep plain
  SGD: one scalar does not need a frequency-adaptive schedule, and
  excluding it keeps the slot pytree exactly table-shaped.

Laziness contract: both rules are exactly lazy — a coordinate whose
batch gradient is zero is bit-unchanged (AdaGrad: ``n`` unchanged so
the step is 0; FTRL: ``z``/``n`` unchanged and the closed form
reproduces the stored weight, because :func:`ftrl_init_z` chooses the
initial ``z`` so the closed form equals the spec's init). The sparse
step therefore matches the dense transformation on every touched
coordinate and leaves untouched rows alone — pinned in
tests/test_optim.py.

FTRL has no use for the global ``lr_schedule``: its per-coordinate
``(beta + √n)/alpha`` IS the schedule (``alpha`` = the configured
learning rate), so the dense form ignores the schedule field rather
than mis-applying a second decay on top.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "FtrlState",
    "adagrad_rows",
    "ftrl",
    "ftrl_init_z",
    "ftrl_rows",
    "init_adaptive_slots",
    "make_sparse_adaptive_step",
]

ADAPTIVE_OPTIMIZERS = ("ftrl", "adagrad")

#: AdaGrad's denominator floor (outside the sqrt — the McMahan paper's
#: form, NOT optax.adagrad's inside-the-sqrt initial accumulator).
ADAGRAD_EPS = 1e-8


# ------------------------------------------------------ per-row update rules


def adagrad_rows(rows, n, g, lr: float):
    """Per-coordinate AdaGrad on gathered rows.

    ``rows``/``n``/``g`` are [U, w] (or any matching shape): current
    weights, accumulated squared gradients, and this batch's summed
    gradient per coordinate. Returns ``(new_rows, new_n)`` in fp32.
    """
    g = g.astype(jnp.float32)
    n_new = n.astype(jnp.float32) + g * g
    step = lr * g / (jnp.sqrt(n_new) + ADAGRAD_EPS)
    return rows.astype(jnp.float32) - step, n_new


def ftrl_init_z(w0, alpha: float, beta: float):
    """The initial ``z`` that makes FTRL's closed form reproduce the
    spec's init (``n``=0, l1=0): ``w = -z·alpha/beta`` ⇒ ``z =
    -w·beta/alpha``. Without this, FTRL zeroes every coordinate on
    first touch — which kills FM factors outright (zero factors have
    zero interaction gradient and never recover)."""
    return -jnp.asarray(w0, jnp.float32) * (beta / alpha)


def ftrl_rows(rows, z, n, g, alpha: float, beta: float,
              l1: float, l2: float):
    """Per-coordinate FTRL-Proximal on gathered rows.

    The McMahan et al. update: ``σ = (√(n+g²) − √n)/α``, ``z += g −
    σ·w``, ``n += g²``, and the weight is the closed-form proximal
    solution of the accumulated problem. Returns ``(new_rows, new_z,
    new_n)`` in fp32. Exactly lazy: ``g = 0`` leaves all three
    unchanged (the closed form is a pure function of ``z``/``n``).
    """
    w = rows.astype(jnp.float32)
    g = g.astype(jnp.float32)
    z = z.astype(jnp.float32)
    n = n.astype(jnp.float32)
    n_new = n + g * g
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / alpha
    z_new = z + g - sigma * w
    shrunk = jnp.sign(z_new) * jnp.maximum(jnp.abs(z_new) - l1, 0.0)
    denom = (beta + jnp.sqrt(n_new)) / alpha + l2
    return -shrunk / denom, z_new, n_new


# ------------------------------------------------------- dense (optax) form


class FtrlState(NamedTuple):
    """FTRL-Proximal per-coordinate slots (fp32, param-shaped)."""

    z: object
    n: object


def ftrl(alpha: float, beta: float = 1.0, l1: float = 0.0,
         l2: float = 0.0, l2_by_group: dict | None = None):
    """FTRL-Proximal as an optax ``GradientTransformation``.

    ``init`` seeds ``z`` from the incoming params via
    :func:`ftrl_init_z` so initialization survives the first touch;
    ``update`` returns ``new_w − w`` deltas (optax convention), cast to
    the gradient dtype. Per-coordinate slots are fp32 regardless of the
    param/compute dtype — slot precision is what the schedule is made
    of.

    L2 composition rule: the config's MLlib-style ``reg_*`` triple must
    NEVER be folded into the gradients FTRL sees — ``(g + λw)`` would
    corrupt the per-coordinate ``z``/``n`` statistics (the schedule
    itself). Instead ``l2_by_group`` maps top-level param groups
    (``w0``/``w``/``v``/``mlp`` — the :func:`~fm_spark_tpu.train
    ._group_reg` table) onto FTRL's own PROXIMAL l2 term, which is the
    rule's native, closed-form way of carrying L2; ``make_optimizer``
    routes the triple here and the dense train steps skip their
    gradient-side reg for FTRL. Unknown groups are an error — silently
    unregularized parameters are worse than a crash.
    """
    import optax

    if alpha <= 0:
        raise ValueError(f"ftrl needs alpha > 0, got {alpha}")

    def _l2_at(path) -> float:
        if l2_by_group is None:
            return l2
        top = path[0]
        key = str(getattr(top, "key", getattr(top, "idx", top)))
        if key not in l2_by_group:
            raise ValueError(
                f"no FTRL l2 group for param {key!r} "
                f"(know {sorted(l2_by_group)})")
        return float(l2_by_group[key]) + l2

    def init_fn(params):
        z = jax.tree_util.tree_map(
            lambda p: ftrl_init_z(p, alpha, beta), params)
        n = jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
        return FtrlState(z=z, n=n)

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("ftrl is a proximal rule; it needs params")

        # Three tree_maps re-running the rule per output; XLA CSEs the
        # shared subexpressions under jit, and it keeps the pytrees
        # honest (no tuple-leaf transpose tricks).
        def pick(i):
            return jax.tree_util.tree_map_with_path(
                lambda path, g, z, n, p: ftrl_rows(
                    p, z, n, g, alpha, beta, l1, _l2_at(path))[i],
                updates, state.z, state.n, params)

        deltas = jax.tree_util.tree_map_with_path(
            lambda path, g, z, n, p: (
                ftrl_rows(p, z, n, g, alpha, beta, l1,
                          _l2_at(path))[0]
                - p.astype(jnp.float32)).astype(g.dtype),
            updates, state.z, state.n, params)
        return deltas, FtrlState(z=pick(1), n=pick(2))

    return optax.GradientTransformation(init_fn, update_fn)


# ------------------------------------------------- sparse (scatter-path) form


def init_adaptive_slots(optimizer: str, spec, params) -> dict:
    """Slot pytree for :func:`make_sparse_adaptive_step` — one fp32
    table per SPARSE param table (``v``, and ``w`` when the spec uses
    the linear term); the dense ``w0`` slot is excluded by design.
    Checkpoint this dict as the step's ``opt_state`` — it rides
    save/restore like any other state tree."""
    if optimizer not in ADAPTIVE_OPTIMIZERS:
        raise ValueError(
            f"unknown adaptive optimizer {optimizer!r} "
            f"(know {ADAPTIVE_OPTIMIZERS})")
    slots: dict = {}
    tables = {"v": params["v"]}
    if spec.use_linear:
        tables["w"] = params["w"]
    for name, t in tables.items():
        if optimizer == "adagrad":
            slots[name] = {"n": jnp.zeros(t.shape, jnp.float32)}
        else:
            slots[name] = {
                "z": jnp.zeros(t.shape, jnp.float32),
                "n": jnp.zeros(t.shape, jnp.float32),
            }
    return slots


def seed_ftrl_slots(slots: dict, params, alpha: float,
                    beta: float) -> dict:
    """Re-seed FTRL ``z`` slots from the CURRENT param tables (fresh
    start only — restored slots already carry their history)."""
    out = dict(slots)
    for name in out:
        out[name] = dict(out[name],
                         z=ftrl_init_z(params[name], alpha, beta))
    return out


def make_sparse_adaptive_step(spec, config, *, beta: float = 1.0,
                              l1: float = 0.0, l2: float = 0.0):
    """Fused sparse per-coordinate-optimizer step for the flat FM
    family — ``sparse.make_sparse_sgd_step``'s adaptive sibling.

    Returns ``step(params, slots, ids, vals, labels, weights) →
    (params, slots, loss)`` with donated params/slots. The backward is
    the same analytic per-row rule as the SGD step; the write-back
    rides the dedup half of the scatter path: duplicate ids are
    segment-summed (``ops.scatter._dedup``) so each unique coordinate
    sees its TOTAL batch gradient exactly once — adaptive rules are
    read-modify-write and double-counting a duplicate id would double
    its schedule, not just its step — and both the row and its slot
    row(s) are written with one set-semantics scatter through the same
    out-of-range-sentinel mask the SGD dedup mode uses. ``w0`` (the
    dense slot) keeps plain constant-lr SGD.

    Regularization: ``l1``/``l2`` are FTRL's built-in proximal terms;
    the config's ``reg_*`` triple is rejected (two L2 paths silently
    composing would be worse than a crash).
    """
    import functools

    from fm_spark_tpu.models.fm import FMSpec
    from fm_spark_tpu.ops import losses as losses_lib
    from fm_spark_tpu.ops.scatter import _dedup

    if type(spec) is not FMSpec:
        raise ValueError(
            "the sparse adaptive step supports the flat FM family only "
            "(the fused field families keep their SGD scatter bodies)")
    if config.optimizer not in ADAPTIVE_OPTIMIZERS:
        raise ValueError(
            f"make_sparse_adaptive_step handles {ADAPTIVE_OPTIMIZERS}; "
            f"config.optimizer={config.optimizer!r}")
    if config.reg_bias or config.reg_linear or config.reg_factors:
        raise ValueError(
            "the adaptive step rejects the reg_* triple: FTRL carries "
            "its own proximal l1/l2 and AdaGrad pairs with explicit "
            "weight decay, not lazy L2 — configure l1/l2 here instead")
    from fm_spark_tpu.sparse import _reject_embed_tier_require

    # TieredTrainer builds THIS step over its hot-tier window with
    # embed_tier neutralized to 'off'; a bare 'require' here means the
    # caller skipped the tiered trainer.
    _reject_embed_tier_require(config, "the bare sparse adaptive step "
                               "(drive it through embed.TieredTrainer)")
    per_example_loss = losses_lib.loss_fn(spec.loss)
    cd = spec.cdtype
    alpha = float(config.learning_rate)
    is_ftrl = config.optimizer == "ftrl"

    def rule(rows, slot, g):
        if is_ftrl:
            new_rows, z_new, n_new = ftrl_rows(
                rows, slot["z"], slot["n"], g, alpha, beta, l1, l2)
            return new_rows, {"z": z_new, "n": n_new}
        new_rows, n_new = adagrad_rows(rows, slot["n"], g, alpha)
        return new_rows, {"n": n_new}

    def sparse_apply(table, slot, flat_ids, flat_g):
        """One table's dedup-scatter adaptive update: segment-sum the
        per-lane grads, gather + update + set-write the unique rows
        (non-run-start lanes route to the drop sentinel)."""
        n_rows = table.shape[0]
        sid, summed, run_start, _ = _dedup(flat_ids, flat_g)
        g_u = jnp.where(run_start[..., None] if summed.ndim > 1
                        else run_start, summed, 0.0)
        rows = table[sid].astype(jnp.float32)
        slot_rows = {k: s[sid] for k, s in slot.items()}
        new_rows, new_slot_rows = rule(rows, slot_rows, g_u)
        oob = jnp.where(run_start, sid, n_rows)
        table = table.at[oob].set(new_rows.astype(table.dtype),
                                  mode="drop")
        slot = {k: slot[k].at[oob].set(new_slot_rows[k], mode="drop")
                for k in slot}
        return table, slot

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, slots, ids, vals, labels, weights):
        w0, w, v = params["w0"], params["w"], params["v"]
        vals_c = vals.astype(cd)
        rows = v[ids].astype(cd)                       # [B, nnz, k]
        xv = rows * vals_c[..., None]
        s = jnp.sum(xv, axis=1)                        # [B, k]
        sum_sq = jnp.sum(xv * xv, axis=(1, 2))
        scores = 0.5 * (jnp.sum(s * s, axis=1) - sum_sq)
        if spec.use_linear:
            scores = scores + jnp.sum(w[ids].astype(cd) * vals_c, axis=1)
        if spec.use_bias:
            scores = scores + w0.astype(cd)
        wsum = jnp.maximum(jnp.sum(weights), 1.0)

        def batch_loss(sc):
            return jnp.sum(per_example_loss(sc, labels) * weights) / wsum

        loss, dscores = jax.value_and_grad(batch_loss)(scores)
        # The reference's analytic per-row rule (BASELINE.json:5).
        g_rows = (dscores[:, None, None] * vals_c[..., None]
                  * (s[:, None, :] - xv))
        flat_ids = ids.reshape(-1)
        v, slots_v = sparse_apply(
            v, slots["v"], flat_ids,
            g_rows.reshape(-1, g_rows.shape[-1]).astype(jnp.float32))
        slots = dict(slots, v=slots_v)
        if spec.use_linear:
            g_w = (dscores[:, None] * vals_c).reshape(-1)
            w, slots_w = sparse_apply(w, slots["w"], flat_ids,
                                      g_w.astype(jnp.float32))
            slots = dict(slots, w=slots_w)
        if spec.use_bias:
            # Dense slot, deliberately excluded from the adaptive set:
            # plain constant-lr SGD on the scalar bias.
            w0 = w0 - alpha * jnp.sum(dscores)
        return {"w0": w0, "w": w, "v": v}, slots, loss

    return step
