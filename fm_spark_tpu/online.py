"""Continuous-learning loop: time-ordered train/eval with a drift
sentry and coordinated rollback (ISSUE 13; ROADMAP item 5).

CTR systems retrain continuously, and the failure mode of continuous
learning is quiet: the world drifts, the freshly trained model is worse
than yesterday's, and the serving fleet hot-loads it anyway. This
module closes the loop the repo already has all the pieces for:

- **Time-ordered protocol**: the classic day-N/day-N+1 split — train
  on day ``k``'s records, then evaluate on day ``k+1``'s records the
  model has NEVER seen (never a random split; temporal leakage would
  flatter exactly the drifted models this loop exists to catch). Eval
  AUC streams through the on-device histogram accumulators
  (:mod:`fm_spark_tpu.utils.metrics`) — incremental, never a
  whole-day score materialization.

- **Provenance**: every day's eval lands in the
  :class:`~fm_spark_tpu.obs.ledger.PerfLedger` as a ``quality_eval``
  record — its own ``leg`` namespace, so quality cohorts never mix
  with ``bench_leg``/``serve_bench`` throughput cohorts — judged by
  the regression :class:`~fm_spark_tpu.obs.sentinel.Sentinel` against
  the cohort's trailing band before it is appended.

- **Drift sentry**: a
  :class:`~fm_spark_tpu.resilience.divergence.DivergenceGuard` in
  ``mode="max"`` watches the AUC series — the same trailing-median
  machinery that catches loss blowups, mirrored for a
  higher-is-better metric, with the ``min_history`` floor keeping the
  first short days from ever tripping it.

- **Coordinated rollback**: a drift verdict DEMOTES the offending
  day's checkpoints (:meth:`~fm_spark_tpu.checkpoint.Checkpointer
  .demote_newer_than` — durable tombstones, ``last_good`` republished
  at the pre-drift save, crash-consistent at every kill point) and
  restores the pre-drift weights; the step axis keeps advancing past
  the tombstoned frontier so no step number is ever reused and a
  serving follower's generation monotonicity holds. The follower
  (serve/reload.py) refuses tombstoned generations outright, so the
  bad model can never be hot-loaded even if the alarm fires mid-reload.

The loop checkpoints at DAY granularity: one verified save per trained
day (plus the step-0 anchor save, so a drift verdict on the very first
day still has a rollback target), with the day index and cumulative
record count in the save's ``extra`` — the online cursor a resumed or
rolled-back run continues from.
"""

from __future__ import annotations

import time

import numpy as np

from fm_spark_tpu import obs
from fm_spark_tpu.resilience import faults, watchdog
from fm_spark_tpu.resilience.divergence import (
    DivergenceDetected,
    DivergenceGuard,
)

__all__ = ["drift_guard", "flip_labels", "run_online", "split_days"]

#: quality_eval ledger-leg prefix (cohort isolation from bench legs).
QUALITY_LEG_PREFIX = "quality/"


def drift_guard(drop_factor: float = 1.15, window: int = 8,
                min_history: int = 3, max_rollbacks: int = 2,
                journal=None) -> DivergenceGuard:
    """The online loop's concept-drift sentry: a maximize-mode
    :class:`DivergenceGuard` sized for AUC (a ``drop_factor`` of 1.15
    fires on a ~13% relative drop — outside early-training
    day-over-day improvement noise, far inside a label-flip drift; the
    ``min_history`` floor of 3 keeps the first, still-climbing days
    from tripping it)."""
    return DivergenceGuard(spike_factor=drop_factor, window=window,
                           min_history=min_history,
                           max_rollbacks=max_rollbacks,
                           journal=journal, mode="max")


def split_days(ids, vals, labels, n_days: int) -> list[tuple]:
    """Split one time-ordered dataset into ``n_days`` contiguous day
    slices (the synthetic stand-in for dated Criteo/Avazu shards).
    Order is preserved — this is a TEMPORAL split, never a shuffle."""
    n = len(labels)
    if n_days < 2:
        raise ValueError("online protocol needs >= 2 days "
                         "(day N trains, day N+1 evaluates)")
    if n < n_days:
        raise ValueError(f"{n} rows cannot fill {n_days} days")
    edges = np.linspace(0, n, n_days + 1).astype(int)
    return [(ids[a:b], vals[a:b], labels[a:b])
            for a, b in zip(edges[:-1], edges[1:])]


def flip_labels(days: list[tuple], from_day: int) -> list[tuple]:
    """The planted-drift drill lever, in ONE place (cli
    ``--drift-inject``, bench_quality ``--online-smoke``, and the
    chaos drift drills all inject drift through this): flip every
    label of day ``from_day`` onward — the sharpest possible concept
    drift, guaranteed far outside any sane sentry threshold."""
    return [(i, v, (1.0 - l).astype(np.float32)
             if k >= int(from_day) else l)
            for k, (i, v, l) in enumerate(days)]


def _day_steps(day, batch_size: int) -> int:
    return max(1, len(day[2]) // int(batch_size))


def run_online(trainer, days, checkpointer, *, sentry=None,
               journal=None, ledger=None, leg=None, fingerprint=None,
               run_id=None, batch_tap=None) -> dict:
    """Run the continuous-learning protocol over time-ordered days.

    ``trainer`` is a constructed :class:`~fm_spark_tpu.train.FMTrainer`
    (any optimizer — the per-coordinate FTRL/AdaGrad families are the
    intended ones); ``days`` a list of ``(ids, vals, labels)`` arrays
    in time order; ``checkpointer`` the crash-consistent chain the
    serving follower watches. ``sentry`` defaults to
    :func:`drift_guard`. ``ledger``/``leg``/``fingerprint``/``run_id``
    enable ``quality_eval`` provenance records (all four required
    together — the ledger refuses unattributable rows by design).
    ``batch_tap`` (drills) wraps each day's batch source.

    Returns a summary dict: per-day records (step, auc, sentinel
    verdict, rollback marker), total rollbacks, demoted steps, and the
    final ``last_good``. Raises :class:`DivergenceDetected` when the
    sentry's rollback budget is exhausted — persistent drift is a
    modeling/data problem the operator must see, not absorb.
    """
    from fm_spark_tpu.data import Batches, iterate_once
    from fm_spark_tpu.train import evaluate_params

    if len(days) < 2:
        raise ValueError("online protocol needs >= 2 time-ordered "
                         "days (day N trains, day N+1 evaluates)")
    if ledger is not None and not (leg and fingerprint and run_id):
        raise ValueError(
            "quality_eval provenance needs leg, fingerprint and run_id "
            "alongside the ledger (unattributable records are refused)")
    sentry = sentry or drift_guard(journal=journal)
    if sentry.mode != "max":
        raise ValueError(
            "the online drift sentry watches AUC (higher-is-better); "
            "pass a DivergenceGuard with mode='max'")
    sentinel = None
    if ledger is not None:
        from fm_spark_tpu.obs.sentinel import Sentinel

        sentinel = Sentinel(ledger)

    def emit(event, **fields):
        obs.event(event, **fields)
        if journal is not None:
            journal.emit(event, **fields)

    cfg = trainer.config
    batch_size = int(cfg.batch_size)
    day_records: list[dict] = []
    demoted_all: list[int] = []
    state = {"rollbacks": 0, "records": 0}

    def day_save(day_idx: int, evals_done: int) -> None:
        """One verified day-boundary save; ``extra`` carries the
        online cursor AND the sentry's trailing window — the durable
        state a killed run resumes the protocol from."""
        checkpointer.save(trainer.step_count, trainer.params,
                          trainer.opt_state, None,
                          {"online_day": day_idx,
                           "online_records": state["records"],
                           "online_evals_done": evals_done,
                           "online_auc_history": sentry.history()},
                          force=True)
        checkpointer.wait()

    def eval_and_judge(k_eval: int, pre_day_step: int) -> dict:
        """Evaluate day ``k_eval`` with the current model (streamed
        AUC), record provenance, run the drift sentry, and perform the
        coordinated rollback on a verdict. Returns the day entry."""
        nxt = days[k_eval]
        with watchdog.phase("online_eval"):
            faults.inject("online_eval")
            with obs.span("online/eval_day", day=k_eval):
                metrics = evaluate_params(
                    trainer.spec, trainer.params,
                    iterate_once(*nxt, min(batch_size, len(nxt[2]))),
                    step=trainer._eval_step)
        auc = float(metrics["auc"])
        base = sentry.baseline()
        drift_score = ((base - auc) / base
                       if base is not None and base > 0 else 0.0)
        obs.gauge("online/auc").set(auc)
        obs.gauge("online/drift_score").set(round(drift_score, 6))
        obs.counter("online.days_total").add(1)
        verdict = None
        if ledger is not None:
            record = {
                "kind": "quality_eval", "leg": leg, "run_id": run_id,
                "fingerprint": fingerprint, "value": auc,
                "day": k_eval, "step": trainer.step_count,
                "metrics": {m: round(float(x), 6)
                            for m, x in metrics.items()},
            }
            verdict = sentinel.observe(record).get("verdict")
        entry = {"day": k_eval - 1, "eval_day": k_eval,
                 "step": trainer.step_count, "auc": round(auc, 6),
                 "logloss": round(float(metrics["logloss"]), 6),
                 "drift_score": round(drift_score, 6),
                 "sentinel": verdict, "rolled_back": False}
        emit("quality_eval", **{f: entry[f] for f in
                                ("day", "eval_day", "step", "auc",
                                 "drift_score", "sentinel")})
        try:
            sentry.check(trainer.step_count, auc)
        except DivergenceDetected as e:
            # ---- coordinated rollback: demote the drifted day's
            # saves (durable tombstones, last_good republished at the
            # pre-drift save — crash-consistent at every kill point),
            # restore the pre-drift weights, and keep the step axis
            # moving past the tombstoned frontier (a demoted step
            # number is never reused: serving generation monotonicity
            # depends on it). note_rollback accounts the budget and
            # re-raises when it is spent.
            demoted = checkpointer.demote_newer_than(
                pre_day_step,
                reason=f"drift verdict at eval day {k_eval}: {e.reason}")
            restored = checkpointer.restore(trainer.params,
                                            trainer.opt_state)
            if restored is None:
                raise
            sentry.note_rollback(e, restored["step"])
            state["rollbacks"] += 1
            demoted_all.extend(demoted)
            trainer.params = restored["params"]
            trainer.opt_state = restored["opt_state"]
            trainer.step_count = max(
                trainer.step_count,
                checkpointer.tombstone_frontier()) + 1
            obs.counter("online.rollbacks_total").add(1)
            # Republish the restored state as a NEW generation just
            # past the frontier: the chain's tip is good again (the
            # serving follower converges forward, never back), and a
            # kill landing after the rollback resumes at the next
            # day with the pre-drift weights — the same place the
            # uninterrupted run continues from.
            day_save(k_eval - 1, evals_done=k_eval)
            entry["rolled_back"] = True
            entry["demoted_steps"] = demoted
            emit("online_rollback", day=k_eval - 1, demoted=demoted,
                 restored_step=int(restored["step"]),
                 republished_step=trainer.step_count,
                 rollbacks=state["rollbacks"])
        return entry

    # ---- resume: day cursor + step axis past the tombstoned frontier
    start_day = 0
    restored = checkpointer.restore(trainer.params, trainer.opt_state)
    if restored is not None:
        trainer.params = restored["params"]
        trainer.opt_state = restored["opt_state"]
        extra = restored.get("extra") or {}
        start_day = int(extra.get("online_day", -1)) + 1
        state["records"] = int(extra.get("online_records", 0))
        evals_done = int(extra.get("online_evals_done",
                                   max(start_day - 1, 0)))
        sentry.seed_history(extra.get("online_auc_history") or [])
        # Time never rewinds past a demoted save: resuming after a
        # kill that landed mid-rollback must keep the step axis ahead
        # of the tombstoned frontier, or the next day's save would
        # collide with a vetoed step number.
        trainer.step_count = max(int(restored["step"]),
                                 checkpointer.tombstone_frontier())
        emit("online_resume", start_day=start_day,
             step=trainer.step_count, evals_done=evals_done)
        if 1 <= start_day <= len(days) - 1 and evals_done < start_day:
            # The restored save's eval never completed (or its banked
            # verdict died with the process): replay it BEFORE
            # training, so a kill between save and eval can never
            # skip a drift check — the sentry series is bit-identical
            # to the uninterrupted run's.
            pre = trainer.step_count - _day_steps(days[start_day - 1],
                                                  batch_size)
            day_records.append(eval_and_judge(start_day, max(pre, 0)))
    else:
        # Step-0 anchor: the rollback target for a drift verdict on
        # the very first trained day.
        checkpointer.save(0, trainer.params, trainer.opt_state, None,
                          {"online_day": -1, "online_records": 0,
                           "online_evals_done": 0,
                           "online_auc_history": []},
                          force=True)
        checkpointer.wait()
    emit("online_start", start_day=start_day,
         step=trainer.step_count, days=len(days), run_id=run_id)

    for k in range(start_day, len(days) - 1):
        day = days[k]
        pre_day_step = trainer.step_count
        steps = _day_steps(day, batch_size)
        source = Batches(*day, min(batch_size, len(day[2])),
                         seed=cfg.seed + k)
        if batch_tap is not None:
            source = batch_tap(k, source)
        with obs.span("online/train_day", day=k, steps=steps):
            trainer.fit(source, num_steps=steps)
        state["records"] += len(day[2])
        day_save(k, evals_done=k)
        day_records.append(eval_and_judge(k + 1, pre_day_step))

    summary = {
        "days_trained": len(day_records),
        "rollbacks": state["rollbacks"],
        "demoted_steps": demoted_all,
        "final_step": trainer.step_count,
        "last_good": checkpointer.last_good_step(),
        "records_seen": state["records"],
        "days": day_records,
        "ts": round(time.time(), 3),
    }
    emit("online_end", days_trained=summary["days_trained"],
         rollbacks=state["rollbacks"],
         last_good=summary["last_good"])
    return summary
