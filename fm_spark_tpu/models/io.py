"""Final-model save/load — parity with ``FMModel.save/load``.

The reference durably saves only the final model (weights + metadata;
SURVEY.md §3.4-§3.5 — mid-training fault tolerance is Spark lineage, and
the rebuild's richer story lives in :mod:`fm_spark_tpu.checkpoint`). Format
here: a directory with ``spec.json`` (model family + hyperparams) and
``params.npz`` (flat arrays). The format is self-describing so a model can
be reloaded without knowing its family in advance.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import numpy as np


_FAMILIES = {}


def _family_name(spec) -> str:
    return type(spec).__name__


def _register_families():
    # Deferred import to avoid a cycle models.io <-> models.__init__.
    from fm_spark_tpu.models.fm import FMSpec
    from fm_spark_tpu.models.ffm import FFMSpec
    from fm_spark_tpu.models.deepfm import DeepFMSpec
    from fm_spark_tpu.models.field_deepfm import FieldDeepFMSpec
    from fm_spark_tpu.models.field_fm import FieldFMSpec
    from fm_spark_tpu.models.field_ffm import FieldFFMSpec

    _FAMILIES.update(
        FMSpec=FMSpec,
        FFMSpec=FFMSpec,
        DeepFMSpec=DeepFMSpec,
        FieldDeepFMSpec=FieldDeepFMSpec,
        FieldFMSpec=FieldFMSpec,
        FieldFFMSpec=FieldFFMSpec,
    )


def save_model(path: str, spec, params: dict) -> None:
    """Write spec.json + params.npz under ``path`` (a directory)."""
    os.makedirs(path, exist_ok=True)
    meta = {"family": _family_name(spec), "spec": dataclasses.asdict(spec)}
    # JSON can't hold inf; the regression clip defaults are ±inf.
    for key in ("min_target", "max_target"):
        if key in meta["spec"] and not np.isfinite(meta["spec"][key]):
            meta["spec"][key] = None
    flat = {}
    dtypes = {}
    leaves = jax.tree_util.tree_leaves_with_path(params)
    for keypath, leaf in leaves:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in keypath)
        arr = np.asarray(leaf)
        dtypes[name] = str(arr.dtype) if arr.dtype.kind != "V" else str(leaf.dtype)
        if arr.dtype.kind == "V":
            # npz can't store ml_dtypes (bfloat16 → raw '|V2', unloadable);
            # widen to float32 for storage and restore the dtype on load.
            arr = np.asarray(jax.numpy.asarray(leaf).astype(jax.numpy.float32))
        flat[name] = arr
    meta["param_dtypes"] = dtypes
    with open(os.path.join(path, "spec.json"), "w") as f:
        json.dump(meta, f, indent=2)
    np.savez(os.path.join(path, "params.npz"), **flat)


def load_model(path: str):
    """Read back ``(spec, params)`` written by :func:`save_model`."""
    _register_families()
    with open(os.path.join(path, "spec.json")) as f:
        meta = json.load(f)
    spec_kwargs = dict(meta["spec"])
    import math

    if spec_kwargs.get("min_target") is None:
        spec_kwargs["min_target"] = -math.inf
    if spec_kwargs.get("max_target") is None:
        spec_kwargs["max_target"] = math.inf
    if "mlp_dims" in spec_kwargs:
        spec_kwargs["mlp_dims"] = tuple(spec_kwargs["mlp_dims"])
    spec = _FAMILIES[meta["family"]](**spec_kwargs)
    with np.load(os.path.join(path, "params.npz")) as npz:
        flat = {k: npz[k] for k in npz.files}
    # Rebuild the nested pytree from an example structure.
    example = jax.eval_shape(spec.init, jax.random.key(0))
    leaves_with_path = jax.tree_util.tree_leaves_with_path(example)
    treedef = jax.tree_util.tree_structure(example)
    dtypes = meta.get("param_dtypes", {})
    ordered = []
    for keypath, _ in leaves_with_path:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in keypath)
        arr = jax.numpy.asarray(flat[name])
        want = dtypes.get(name)
        if want and str(arr.dtype) != want:
            arr = arr.astype(want)
        ordered.append(arr)
    params = jax.tree_util.tree_unflatten(treedef, ordered)
    return spec, params
