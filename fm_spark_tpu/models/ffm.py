"""Field-aware FM model family (reference config 4, BASELINE.json:10).

V is ``[n, F, k]``: one latent vector per (feature, field) pair; the
interaction uses the opposite slot's field (SURVEY.md §2 row 6).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fm_spark_tpu.models import base
from fm_spark_tpu.ops import ffm as ffm_ops


@dataclasses.dataclass(frozen=True)
class FFMSpec(base.ModelSpec):
    """FFM hyperparameters. ``num_fields`` is the fixed slot count (nnz)."""

    num_fields: int = 0

    def __post_init__(self):
        super().__post_init__()
        if self.num_fields <= 0:
            raise ValueError("FFMSpec requires num_fields > 0")

    def init(self, rng: jax.Array) -> dict:
        params = base.init_linear_terms(rng, self)
        params["v"] = (
            jax.random.normal(
                rng,
                (self.num_features, self.num_fields, self.rank),
                dtype=jnp.float32,
            )
            * self.init_std
        ).astype(self.pdtype)
        return params

    def scores(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        return ffm_ops.ffm_scores(
            params["w0"] if self.use_bias else jnp.zeros((), jnp.float32),
            params["w"] if self.use_linear else jnp.zeros_like(params["w"]),
            params["v"],
            ids,
            vals,
            compute_dtype=self.cdtype,
        )

    def predict(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        return base.predict_from_scores(self, self.scores(params, ids, vals))
