"""Shared model-spec scaffolding and the task-switch prediction link.

The reference's ``FMModel.predict`` applies a task switch: classification →
sigmoid (threshold left to the caller), regression → clip predictions to the
[min, max] seen at training time (SURVEY.md §2 row 4, §3.2). That switch
lives here, shared by all model families.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Static model hyperparameters, hashable for use as a jit static arg.

    Mirrors the reference's ``train()`` parameterization (SURVEY.md §1 L5):
    ``dim=(k0, k1, k2)`` → (use_bias, use_linear, rank); ``initStd`` →
    ``init_std``; task switch; regression min/max clip.
    """

    num_features: int
    rank: int
    task: str = "classification"          # 'classification' | 'regression'
    loss: str | None = None       # 'logistic'|'squared'|'hinge'; None ⇒ by task
    use_bias: bool = True                 # dim k0
    use_linear: bool = True               # dim k1
    init_std: float = 0.01
    min_target: float = -math.inf        # regression clip, learned from data
    max_target: float = math.inf
    param_dtype: str = "float32"          # storage dtype for the big tables
    compute_dtype: str = "float32"        # accumulation dtype

    # Field-partitioned subclasses override to True: their tables take
    # FIELD-LOCAL ids in [0, bucket) and data layers must convert
    # per-field-offset global ids first (cli._field_local).
    field_local_ids = False

    def __post_init__(self):
        if self.task not in ("classification", "regression"):
            raise ValueError(f"unknown task {self.task!r}")
        # The reference's task switch ties the loss to the task; keep that
        # as the default and fail at construction, not first training step.
        if self.loss is None:
            object.__setattr__(
                self,
                "loss",
                "logistic" if self.task == "classification" else "squared",
            )
        from fm_spark_tpu.ops import losses

        losses.loss_fn(self.loss)
        if self.task == "regression" and self.loss in ("logistic", "hinge"):
            raise ValueError(
                f"{self.loss} loss expects {{0,1}} labels; use "
                "loss='squared' (or leave loss unset) for task='regression'"
            )

    @property
    def pdtype(self):
        return jnp.dtype(self.param_dtype)

    @property
    def cdtype(self):
        return jnp.dtype(self.compute_dtype)


def predict_from_scores(spec: ModelSpec, scores: jax.Array) -> jax.Array:
    """Raw scores → predictions per the reference's task switch."""
    if spec.task == "classification":
        return jax.nn.sigmoid(scores)
    lo = spec.min_target if spec.min_target > -math.inf else None
    hi = spec.max_target if spec.max_target < math.inf else None
    if lo is None and hi is None:
        return scores
    return jnp.clip(scores, lo, hi)


def init_linear_terms(rng: jax.Array, spec: ModelSpec) -> dict:
    """Bias + linear weights, zero-initialized like the reference (w=0, w0=0)."""
    del rng
    return {
        "w0": jnp.zeros((), dtype=jnp.float32),
        "w": jnp.zeros((spec.num_features,), dtype=spec.pdtype),
    }
