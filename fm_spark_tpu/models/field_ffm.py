"""Field-partitioned FFM: the CTR-scale TPU layout of the FFM table.

Same motivation as :mod:`fm_spark_tpu.models.field_fm` (measured XLA
gather/scatter cliffs on monolithic tables — PERF.md), applied to the
field-aware model (reference config 4, BASELINE.json:10): instead of one
``[n, F, k]`` tensor, each field owns a ``[bucket, F·k (+1)]`` table whose
row packs the feature's F per-target-field factor vectors (and, fused in
the last column, its linear weight) — so the hot path stays ONE gather and
ONE scatter per field per step, identical in index-op count to FieldFM,
with F·k-wide rows (row width is nearly free once the index is paid,
PERF.md fact 2).

Encoding matches FieldFM: field-local ids ``[B, F]`` with the fixed
slot==field CTR layout (one active feature per field). Equivalence with
the flat :class:`FFMSpec` under the offset embedding is property-tested.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fm_spark_tpu.models import base


@dataclasses.dataclass(frozen=True)
class FieldFFMSpec(base.ModelSpec):
    """FFM with one packed sub-table per field.

    ``num_fields`` fields with ``bucket`` hashed rows each;
    ``num_features = num_fields * bucket``. Row layout of table f:
    columns ``[j*k : (j+1)*k]`` hold the factor vector used when the
    feature interacts with field ``j``; column ``F*k`` is the linear
    weight (``fused_linear``).
    """

    num_fields: int = 0
    bucket: int = 0
    fused_linear: bool = True

    # Tables take FIELD-LOCAL ids (see FieldFMSpec).
    field_local_ids = True

    def __post_init__(self):
        super().__post_init__()
        if self.num_fields <= 0 or self.bucket <= 0:
            raise ValueError("FieldFFMSpec requires num_fields > 0 and bucket > 0")
        if self.num_features != self.num_fields * self.bucket:
            raise ValueError(
                f"num_features ({self.num_features}) must equal "
                f"num_fields*bucket ({self.num_fields * self.bucket})"
            )
        if not self.fused_linear:
            raise ValueError("FieldFFMSpec ships the fused layout only")

    @property
    def table_width(self) -> int:
        return self.num_fields * self.rank + 1

    def init(self, rng: jax.Array) -> dict:
        f, k = self.num_fields, self.rank
        keys = jax.random.split(rng, f)
        tables = []
        for i in range(f):
            v = (
                jax.random.normal(keys[i], (self.bucket, f * k), jnp.float32)
                * self.init_std
            ).astype(self.pdtype)
            tables.append(
                jnp.concatenate(
                    [v, jnp.zeros((self.bucket, 1), self.pdtype)], axis=1
                )
            )
        return {"w0": jnp.zeros((), jnp.float32), "vw": tables}

    def gather_rows(self, params: dict, ids: jax.Array):
        """One gather per field → list of F ``[B, F·k+1]`` rows."""
        cd = self.cdtype
        return [
            params["vw"][f][ids[:, f]].astype(cd)
            for f in range(self.num_fields)
        ]

    def _sel(self, rows, vals_c):
        """``sel[b, i, j, :] = v[id_i, field j] * x_i`` — the [B,F,F,k]
        interaction tensor (x folded in), shared by scores and the fused
        step's backward."""
        f, k = self.num_fields, self.rank
        factors = jnp.stack(
            [r[:, : f * k].reshape(-1, f, k) for r in rows], axis=1
        )  # [B, i(owner), j(target), k]
        return factors * vals_c[:, :, None, None]

    def scores(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        if ids.shape[1] != self.num_fields:
            raise ValueError(
                f"batch has {ids.shape[1]} slots, spec has {self.num_fields} fields"
            )
        cd = self.cdtype
        f, k = self.num_fields, self.rank
        vals_c = vals.astype(cd)
        rows = self.gather_rows(params, ids)
        sel = self._sel(rows, vals_c)
        a = jnp.sum(sel * jnp.swapaxes(sel, 1, 2), axis=-1)  # [B, F, F]
        diag = jnp.trace(a, axis1=1, axis2=2)
        score = 0.5 * (jnp.sum(a, axis=(1, 2)) - diag)
        if self.use_linear:
            score = score + sum(
                r[:, f * k] * vals_c[:, i] for i, r in enumerate(rows)
            )
        if self.use_bias:
            score = score + params["w0"].astype(cd)
        return score

    def predict(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        return base.predict_from_scores(self, self.scores(params, ids, vals))

    # -- layout conversion (testing / interop with the flat FFMSpec) -------

    def flat_spec(self):
        from fm_spark_tpu.models.ffm import FFMSpec

        kwargs = dataclasses.asdict(self)
        kwargs.pop("bucket")
        kwargs.pop("fused_linear")
        return FFMSpec(**kwargs)

    def to_flat_params(self, params: dict) -> dict:
        f, k = self.num_fields, self.rank
        return {
            "w0": params["w0"],
            "w": jnp.concatenate([t[:, f * k] for t in params["vw"]]),
            "v": jnp.concatenate(
                [t[:, : f * k].reshape(-1, f, k) for t in params["vw"]],
                axis=0,
            ),
        }

    def to_global_ids(self, ids) -> jax.Array:
        offs = jnp.arange(self.num_fields, dtype=jnp.int32) * self.bucket
        return ids + offs[None, :]
