"""libFM text model format — import/export with the reference lineage.

The spark-libFM family descends from Rendle's libFM, whose ``--save_model``
text format is the de-facto interchange for FM weights (SURVEY.md §5
"Checkpoint / resume": an import/export path for the reference's
final-model format, so models can be cross-validated between the reference
and this framework). Layout (sections present iff dim k0/k1/k2 enable
them)::

    #global bias W0
    <w0>
    #unary interactions Wj
    <one weight per line, feature-major>
    #pairwise interactions Vj,f
    <k space-separated factors per line, feature-major>

Export flattens FieldFM layouts to the plain [n, k] table first; import
always yields a flat :class:`~fm_spark_tpu.models.fm.FMSpec`.
"""

from __future__ import annotations

import numpy as np

_BIAS_HDR = "#global bias W0"
_UNARY_HDR = "#unary interactions Wj"
_PAIR_HDR = "#pairwise interactions Vj,f"


def save_libfm(path: str, spec, params: dict) -> None:
    """Write ``params`` in libFM text format (sections per dim triple)."""
    from fm_spark_tpu.models.field_fm import FieldFMSpec
    from fm_spark_tpu.models.fm import FMSpec

    if isinstance(spec, FieldFMSpec):
        params = spec.to_flat_params(params)
    elif not isinstance(spec, FMSpec):
        # FFM's [n, F, k] factors and DeepFM's MLP have no libFM
        # representation — refusing beats silently dropping weights.
        raise ValueError(
            f"libFM format holds plain FM models only, not "
            f"{type(spec).__name__}"
        )
    w0 = float(np.asarray(params["w0"]))
    w = np.asarray(params["w"], np.float64)
    v = np.asarray(params["v"], np.float64)
    with open(path, "w") as f:
        if spec.use_bias:
            f.write(f"{_BIAS_HDR}\n{w0:.17g}\n")
        if spec.use_linear:
            f.write(_UNARY_HDR + "\n")
            f.writelines(f"{x:.17g}\n" for x in w)
        f.write(_PAIR_HDR + "\n")
        for row in v:
            f.write(" ".join(f"{x:.17g}" for x in row) + "\n")


def load_libfm(path: str, task: str = "classification", **spec_kwargs):
    """Read a libFM text model → ``(FMSpec, params)``.

    ``spec_kwargs`` pass through to :class:`FMSpec` (e.g. regression
    min/max clip). Missing sections → the corresponding dim flag off.
    """
    import jax.numpy as jnp

    from fm_spark_tpu.models.fm import FMSpec

    with open(path) as f:
        lines = [ln.rstrip("\n") for ln in f]

    sections: dict[str, list[str]] = {}
    current = None
    for ln in lines:
        if ln.startswith("#"):
            current = ln
            sections[current] = []
        elif current is not None and ln.strip():
            sections[current].append(ln)

    if _PAIR_HDR not in sections:
        raise ValueError(f"{path}: missing {_PAIR_HDR!r} section")
    v = np.asarray(
        [[float(x) for x in ln.split()] for ln in sections[_PAIR_HDR]],
        np.float32,
    )
    n, rank = v.shape
    use_bias = _BIAS_HDR in sections
    use_linear = _UNARY_HDR in sections
    w0 = float(sections[_BIAS_HDR][0]) if use_bias else 0.0
    if use_linear:
        w = np.asarray([float(ln) for ln in sections[_UNARY_HDR]], np.float32)
        if w.shape[0] != n:
            raise ValueError(
                f"{path}: {w.shape[0]} unary weights but {n} factor rows"
            )
    else:
        w = np.zeros((n,), np.float32)

    spec = FMSpec(
        num_features=n, rank=rank, task=task,
        use_bias=use_bias, use_linear=use_linear, **spec_kwargs,
    )
    params = {
        "w0": jnp.asarray(w0, jnp.float32),
        "w": jnp.asarray(w, spec.pdtype),
        "v": jnp.asarray(v, spec.pdtype),
    }
    return spec, params
