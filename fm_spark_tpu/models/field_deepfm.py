"""Field-partitioned DeepFM: the CTR-scale TPU layout of DeepFM.

Same architecture as :class:`~fm_spark_tpu.models.deepfm.DeepFMSpec`
(Guo et al., IJCAI 2017 — FM and deep head SHARE the embedding; score =
y_fm + y_deep; reference stretch config, BASELINE.json:11), but the
shared embedding uses the measured CTR layout of
:class:`~fm_spark_tpu.models.field_fm.FieldFMSpec`: one sub-table per
field, linear weight fused into column ``rank``, field-local ids. That
makes the embedding side eligible for the fused sparse-SGD scatter
update (sparse.py) — the flat ``DeepFMSpec`` + dense optax path
materializes a dense [10M, k] gradient AND two Adam moment tables per
step, which is the measured ~94k samples/sec/chip slow path (PERF.md).

The training split (sparse.make_field_deepfm_sparse_step): embedding
tables update via analytic sparse scatter-SGD (lazy L2), while the MLP
+ bias — the only dense, non-embedding parameters — update with the
configured optax optimizer (Adam for config 5).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fm_spark_tpu.models import base
from fm_spark_tpu.models.field_fm import FieldFMSpec


@dataclasses.dataclass(frozen=True)
class FieldDeepFMSpec(base.ModelSpec):
    """DeepFM over field-partitioned embedding tables.

    ``num_fields`` fields × ``bucket`` hashed rows each; the MLP input is
    ``num_fields * rank`` (concatenated value-scaled rows). The linear
    weight is fused into column ``rank`` of each table (one gather per
    field serves the FM term, the linear term, AND the deep head).
    """

    num_fields: int = 0
    bucket: int = 0
    mlp_dims: tuple = (400, 400, 400)

    def __post_init__(self):
        super().__post_init__()
        if self.num_fields <= 0 or self.bucket <= 0:
            raise ValueError(
                "FieldDeepFMSpec requires num_fields > 0 and bucket > 0"
            )
        if self.num_features != self.num_fields * self.bucket:
            raise ValueError(
                f"num_features ({self.num_features}) must equal "
                f"num_fields*bucket ({self.num_fields * self.bucket})"
            )

    # Table layout identical to FieldFMSpec(fused_linear=True); tables
    # take FIELD-LOCAL ids (see FieldFMSpec).
    fused_linear = True
    field_local_ids = True

    @property
    def table_width(self) -> int:
        return self.rank + 1

    def init(self, rng: jax.Array) -> dict:
        k_emb, k_mlp = jax.random.split(rng)
        field_spec = self._field_fm_spec()
        params = field_spec.init(k_emb)
        dims = (self.num_fields * self.rank, *self.mlp_dims, 1)
        keys = jax.random.split(k_mlp, len(dims) - 1)
        layers = []
        for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
            scale = jnp.sqrt(2.0 / d_in)  # He init for the relu stack
            layers.append({
                "kernel": jax.random.normal(keys[i], (d_in, d_out),
                                            jnp.float32) * scale,
                "bias": jnp.zeros((d_out,), jnp.float32),
            })
        params["mlp"] = layers
        return params

    def _field_fm_spec(self) -> FieldFMSpec:
        return FieldFMSpec(
            num_features=self.num_features, rank=self.rank,
            num_fields=self.num_fields, bucket=self.bucket,
            task=self.task, loss=self.loss, use_bias=self.use_bias,
            use_linear=self.use_linear, init_std=self.init_std,
            param_dtype=self.param_dtype,
            min_target=self.min_target, max_target=self.max_target,
        )

    def gather_rows(self, params: dict, ids: jax.Array):
        """One gather per field → list of F ``[B, rank+1]`` rows."""
        cd = self.cdtype
        return [params["vw"][f][ids[:, f]].astype(cd)
                for f in range(self.num_fields)]

    def deep_scores(self, mlp, h: jax.Array) -> jax.Array:
        """The MLP head over ``h = concat(xv) [B, F*rank]`` → ``[B]``."""
        cd = self.cdtype
        n_hidden = len(self.mlp_dims)
        for li, layer in enumerate(mlp):
            h = h @ layer["kernel"].astype(cd) + layer["bias"].astype(cd)
            if li < n_hidden:
                h = jax.nn.relu(h)
        return h[:, 0]

    def scores(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        if ids.shape[1] != self.num_fields:
            raise ValueError(
                f"batch has {ids.shape[1]} slots, spec has "
                f"{self.num_fields} fields"
            )
        cd = self.cdtype
        vals_c = vals.astype(cd)
        rows = self.gather_rows(params, ids)
        k = self.rank
        xvs = [r[:, :k] * vals_c[:, f : f + 1] for f, r in enumerate(rows)]
        s = sum(xvs)
        sum_sq = sum(jnp.sum(x * x, axis=1) for x in xvs)
        score = 0.5 * (jnp.sum(s * s, axis=1) - sum_sq)
        if self.use_linear:
            score = score + sum(
                r[:, k] * vals_c[:, f] for f, r in enumerate(rows)
            )
        if self.use_bias:
            score = score + params["w0"].astype(cd)
        h = jnp.concatenate(xvs, axis=1)                  # [B, F*k]
        return score + self.deep_scores(params["mlp"], h)

    def predict(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        return base.predict_from_scores(self, self.scores(params, ids, vals))
