"""DeepFM: FM plus a deep MLP head over concatenated embedding rows.

The reference's stretch config (BASELINE.json:11 — "FM + 3-layer MLP on
Criteo-1TB … a new JAX nn head"; it does NOT exist in the reference,
SURVEY.md §0.1). Architecture follows Guo et al., *DeepFM* (IJCAI 2017):
the FM component and the deep component SHARE the embedding table V; the
deep input is the concatenation of the nnz gathered rows; the final score
is ``y_fm + y_deep``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fm_spark_tpu.models import base
from fm_spark_tpu.ops import fm as fm_ops


@dataclasses.dataclass(frozen=True)
class DeepFMSpec(base.ModelSpec):
    """DeepFM hyperparameters.

    ``num_fields`` fixes the slot count (the MLP input is num_fields·rank);
    ``mlp_dims`` are the hidden widths of the 3-layer head.
    """

    num_fields: int = 0
    mlp_dims: tuple = (400, 400, 400)

    def __post_init__(self):
        super().__post_init__()
        if self.num_fields <= 0:
            raise ValueError("DeepFMSpec requires num_fields > 0")

    def init(self, rng: jax.Array) -> dict:
        k_emb, *k_mlp = jax.random.split(rng, 2 + len(self.mlp_dims))
        params = base.init_linear_terms(rng, self)
        params["v"] = (
            jax.random.normal(
                k_emb, (self.num_features, self.rank), dtype=jnp.float32
            )
            * self.init_std
        ).astype(self.pdtype)
        dims = (self.num_fields * self.rank, *self.mlp_dims, 1)
        layers = []
        # split(rng, 2 + len(mlp_dims)) left exactly one key per layer in
        # k_mlp (len(mlp_dims) hidden + 1 output).
        for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
            # He init for the relu stack; output layer included (d_out=1).
            scale = jnp.sqrt(2.0 / d_in)
            kw = k_mlp[i]
            layers.append(
                {
                    "kernel": jax.random.normal(kw, (d_in, d_out), jnp.float32)
                    * scale,
                    "bias": jnp.zeros((d_out,), jnp.float32),
                }
            )
        params["mlp"] = layers
        return params

    def scores(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        if ids.shape[1] != self.num_fields:
            raise ValueError(
                f"batch has nnz={ids.shape[1]} slots but the MLP input was "
                f"sized for num_fields={self.num_fields}"
            )
        cd = self.cdtype
        vals_c = vals.astype(cd)
        # One shared gather: both the FM term and the deep head consume the
        # same value-scaled rows (padded slots with val == 0 contribute
        # nothing to either component).
        xv = params["v"][ids].astype(cd) * vals_c[..., None]   # [B, nnz, k]
        y_fm = fm_ops.fm_interaction_from_xv(xv)
        if self.use_linear:
            y_fm = y_fm + jnp.sum(params["w"][ids].astype(cd) * vals_c, axis=1)
        if self.use_bias:
            y_fm = y_fm + params["w0"].astype(cd)
        h = xv.reshape(xv.shape[0], -1)                   # [B, nnz*k]
        n_hidden = len(self.mlp_dims)
        for li, layer in enumerate(params["mlp"]):
            h = h @ layer["kernel"].astype(cd) + layer["bias"].astype(cd)
            if li < n_hidden:
                h = jax.nn.relu(h)
        return y_fm + h[:, 0]

    def predict(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        return base.predict_from_scores(self, self.scores(params, ids, vals))
