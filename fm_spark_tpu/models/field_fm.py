"""Field-partitioned FM: the CTR-scale TPU layout of the FM table.

Measured on TPU v5e (see bench.py): XLA gathers/scatters into one
monolithic ``[10M, k]`` table are per-index latency-bound (~50ms per 5M
gathered rows) and scatter falls off a cliff beyond ~512k rows (~1s/step).
Splitting the table into one sub-table per Criteo-style field — each below
the fast-path thresholds — makes the same math ~7× faster: the model IS the
reference's FM (BASELINE.json:5), only the parameter layout is TPU-native.

Encoding: ids are FIELD-LOCAL, shape ``[B, F]`` with ``ids[:, f] ∈
[0, bucket_f)``; the hashed feature space is the disjoint union of the
per-field buckets (exactly how Criteo/Avazu hashing is done per field —
SURVEY.md §2 row 7). Equivalence with the flat ``FMSpec`` under the offset
embedding ``global_id = Σ_{g<f} bucket_g + local_id`` is property-tested.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fm_spark_tpu.models import base
from fm_spark_tpu.ops import fm as fm_ops


@dataclasses.dataclass(frozen=True)
class FieldFMSpec(base.ModelSpec):
    """FM with one sub-table per field.

    ``num_fields`` fields, each with ``bucket`` hashed rows (uniform for
    now); ``num_features`` is derived as ``num_fields * bucket``.
    """

    num_fields: int = 0
    bucket: int = 0
    # Store the linear weight as column `rank` of each factor table so the
    # forward/backward does ONE gather/scatter per field instead of two —
    # the per-index op cost dominates on TPU (see module docstring), so
    # halving index ops is ~2× on the hot path.
    fused_linear: bool = True

    # Tables take FIELD-LOCAL ids in [0, bucket) — data layers must
    # convert per-field-offset globals (cli._field_local; the CLI gates
    # key on this flag).
    field_local_ids = True

    # Physical table orientation: "row" = [bucket, width] (default),
    # "col" = TRANSPOSED [width, bucket]. TPU tiling pads the minor dim
    # to 128 lanes, so a width-65 row-layout table physically occupies
    # ~2x its nominal bytes — and the measured big-table gather cost
    # tracks PHYSICAL operand bytes (PERF.md round-2 "transpose" probe:
    # column-gather from the col layout is ~2.3x cheaper at bf16, with
    # donated scatter cost unchanged). The col layout pairs with the
    # compact sparse path, which transposes only the tiny [w, cap]
    # unique-row buffer back to row orientation, leaving every downstream
    # computation unchanged.
    table_layout: str = "row"

    def __post_init__(self):
        super().__post_init__()
        if self.num_fields <= 0 or self.bucket <= 0:
            raise ValueError("FieldFMSpec requires num_fields > 0 and bucket > 0")
        if self.num_features != self.num_fields * self.bucket:
            raise ValueError(
                f"num_features ({self.num_features}) must equal "
                f"num_fields*bucket ({self.num_fields * self.bucket})"
            )
        if self.table_layout not in ("row", "col"):
            raise ValueError(
                f"table_layout must be 'row' or 'col', got "
                f"{self.table_layout!r}"
            )
        if self.table_layout == "col" and not self.fused_linear:
            raise ValueError("table_layout='col' requires fused_linear=True")

    @property
    def table_width(self) -> int:
        return self.rank + 1 if self.fused_linear else self.rank

    def init(self, rng: jax.Array) -> dict:
        keys = jax.random.split(rng, self.num_fields)
        factors = [
            (jax.random.normal(keys[f], (self.bucket, self.rank), jnp.float32)
             * self.init_std).astype(self.pdtype)
            for f in range(self.num_fields)
        ]
        if self.fused_linear:
            # Column `rank` is the linear weight w, zero-initialized like
            # the reference. Col layout: identical values, transposed
            # storage — row/col models from the same key are bitwise
            # equivalent under transpose.
            vw = [
                jnp.concatenate(
                    [v, jnp.zeros((self.bucket, 1), self.pdtype)], axis=1
                )
                for v in factors
            ]
            if self.table_layout == "col":
                vw = [t.T for t in vw]
            return {"w0": jnp.zeros((), jnp.float32), "vw": vw}
        return {
            "w0": jnp.zeros((), jnp.float32),
            "w": [jnp.zeros((self.bucket,), self.pdtype)
                  for _ in range(self.num_fields)],
            "v": factors,
        }

    def gather_rows(self, params: dict, ids: jax.Array):
        """One gather per field → list of F ``[B, width]`` rows (compute dtype)."""
        cd = self.cdtype
        tables = params["vw"] if self.fused_linear else params["v"]
        if self.table_layout == "col":
            return [
                tables[f][:, ids[:, f]].astype(cd).T
                for f in range(self.num_fields)
            ]
        return [tables[f][ids[:, f]].astype(cd) for f in range(self.num_fields)]

    def scores(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        if ids.shape[1] != self.num_fields:
            raise ValueError(
                f"batch has {ids.shape[1]} slots, spec has {self.num_fields} fields"
            )
        cd = self.cdtype
        vals_c = vals.astype(cd)
        rows = self.gather_rows(params, ids)
        k = self.rank
        xvs = [r[:, :k] * vals_c[:, f : f + 1] for f, r in enumerate(rows)]
        xv = jnp.stack(xvs, axis=1)                       # [B, F, k]
        score = fm_ops.fm_interaction_from_xv(xv)
        if self.use_linear:
            if self.fused_linear:
                lin = sum(
                    r[:, k] * vals_c[:, f] for f, r in enumerate(rows)
                )
            else:
                lin = sum(
                    params["w"][f][ids[:, f]].astype(cd) * vals_c[:, f]
                    for f in range(self.num_fields)
                )
            score = score + lin
        if self.use_bias:
            score = score + params["w0"].astype(cd)
        return score

    def predict(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        return base.predict_from_scores(self, self.scores(params, ids, vals))

    # -- layout conversion (testing / interop with the flat FMSpec) --------

    def flat_spec(self):
        from fm_spark_tpu.models.fm import FMSpec

        kwargs = dataclasses.asdict(self)
        kwargs.pop("num_fields")
        kwargs.pop("bucket")
        kwargs.pop("fused_linear")
        kwargs.pop("table_layout")
        return FMSpec(**kwargs)

    def to_flat_params(self, params: dict) -> dict:
        """Concatenate per-field tables into the flat [N, k] layout."""
        if self.fused_linear:
            k = self.rank
            vw = params["vw"]
            if self.table_layout == "col":
                vw = [t.T for t in vw]
            return {
                "w0": params["w0"],
                "w": jnp.concatenate([t[:, k] for t in vw]),
                "v": jnp.concatenate([t[:, :k] for t in vw], axis=0),
            }
        return {
            "w0": params["w0"],
            "w": jnp.concatenate(params["w"]),
            "v": jnp.concatenate(params["v"], axis=0),
        }

    def to_global_ids(self, ids) -> jax.Array:
        """Field-local ids → flat global ids (offset embedding)."""
        offs = jnp.arange(self.num_fields, dtype=jnp.int32) * self.bucket
        return ids + offs[None, :]
