"""Model families: FM, FFM, DeepFM.

Parity targets (SURVEY.md §2): the reference ships `FMModel` (+`FMWithSGD`)
and an FFM config; DeepFM is the stretch config requiring a new nn head
(BASELINE.json:10-11). Each model here is a frozen spec dataclass + pure
``init`` / ``scores`` / ``predict`` functions over a param pytree — the
idiomatic JAX shape of the reference's model classes.
"""

from fm_spark_tpu.models.base import ModelSpec, predict_from_scores  # noqa: F401
from fm_spark_tpu.models.fm import FMSpec  # noqa: F401
from fm_spark_tpu.models.ffm import FFMSpec  # noqa: F401
from fm_spark_tpu.models.deepfm import DeepFMSpec  # noqa: F401
from fm_spark_tpu.models.field_deepfm import FieldDeepFMSpec  # noqa: F401
from fm_spark_tpu.models.field_fm import FieldFMSpec  # noqa: F401
from fm_spark_tpu.models.field_ffm import FieldFFMSpec  # noqa: F401
from fm_spark_tpu.models.io import save_model, load_model  # noqa: F401
from fm_spark_tpu.models.libfm_io import save_libfm, load_libfm  # noqa: F401


def build(spec):
    """Return the model functions for a spec: ``(init, scores)``."""
    return spec.init, spec.scores
