"""The order-2 factorization machine model family.

Parity target: the reference's ``FMModel`` — holds (w0, w, V), predicts via
the O(k·nnz) identity, initializes V ~ N(0, initStd²) and w = 0, w0 = 0
(SURVEY.md §2 rows 1-2, §3.1). The ``dim=(k0,k1,k2)`` triple of the
reference's ``train()`` maps to (use_bias, use_linear, rank).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fm_spark_tpu.models import base
from fm_spark_tpu.ops import fm as fm_ops


@dataclasses.dataclass(frozen=True)
class FMSpec(base.ModelSpec):
    """FM hyperparameters; see :class:`~fm_spark_tpu.models.base.ModelSpec`."""

    def init(self, rng: jax.Array) -> dict:
        """V ~ N(0, init_std²), w = 0, w0 = 0 — the reference's init."""
        params = base.init_linear_terms(rng, self)
        params["v"] = (
            jax.random.normal(rng, (self.num_features, self.rank), dtype=jnp.float32)
            * self.init_std
        ).astype(self.pdtype)
        return params

    def scores(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        """Raw batched scores; bias/linear terms gated by dim=(k0,k1,·).

        Gating happens by omitting the term from the graph entirely, so the
        gradient w.r.t. a disabled term is exactly zero (the reference
        simply never updates those weights).
        """
        return fm_ops.fm_scores(
            params["w0"] if self.use_bias else jnp.zeros((), jnp.float32),
            params["w"] if self.use_linear else jnp.zeros_like(params["w"]),
            params["v"],
            ids,
            vals,
            self.cdtype,
        )

    def predict(self, params: dict, ids: jax.Array, vals: jax.Array) -> jax.Array:
        return base.predict_from_scores(self, self.scores(params, ids, vals))
