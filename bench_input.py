"""Input-pipeline benchmark: packed dir -> StreamingBatches -> device.

Measures the part bench.py deliberately excludes (its data is
device-resident): sustained host-side feed rate from a Criteo-shaped
packed directory through the production loader stack, against the
north-star requirement of ~1.25M samples/sec/chip (BASELINE.md; SURVEY.md
S7 hard part #1 — "input pipeline at 10M samples/s" across 8 chips).

Prints ONE JSON line with the end-to-end rate (loader + field-local id
conversion + host->device transfer, prefetched), plus stderr rows for
each pipeline stage so regressions are attributable:

  stage 1  PackedBatches        memmap read + chunk-shuffled gather
  stage 2  + field_local        the FieldFM id conversion (cli layer)
  stage 3  + device_put         blocking transfer, no prefetch
  stage 4  + Prefetcher         stage 3 with the producer thread hiding
                                assembly+transfer behind the consumer

Synthesizes its own packed data (one-time, reused across runs via
--data-dir) so it never depends on real Criteo being present.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

METRIC = "input_pipeline_samples_per_sec"
TARGET_PER_CHIP = 10_000_000 / 8


def _log(msg):
    print(f"bench_input: {msg}", file=sys.stderr, flush=True)


def synthesize_packed(path: str, rows: int, num_fields: int = 39,
                      bucket: int = 1 << 18, seed: int = 0,
                      chunk: int = 1 << 20) -> None:
    """Write a Criteo-shaped packed dir (per-field-offset ids, int8
    labels, store_vals=False — the criteo.preprocess layout)."""
    from fm_spark_tpu.data import PackedWriter

    rng = np.random.default_rng(seed)
    offs = (np.arange(num_fields, dtype=np.int64) * bucket)[None, :]
    with PackedWriter(path, num_fields, store_vals=False) as w:
        for start in range(0, rows, chunk):
            n = min(chunk, rows - start)
            ids = (rng.integers(0, bucket, size=(n, num_fields),
                                dtype=np.int64) + offs).astype(np.int32)
            labels = (rng.random(n) < 0.25).astype(np.int8)
            w.append(ids, labels)


def _rate(make_iter, seconds: float, batch: int,
          consume=lambda b: None) -> float:
    """Sustained samples/sec of ``next(it)`` + ``consume(batch)``."""
    it = make_iter()
    # Warm the first batch (memmap page-in, jit of nothing, thread spin-up).
    consume(next(it))
    n = 0
    t0 = time.perf_counter()
    while (dt := time.perf_counter() - t0) < seconds:
        consume(next(it))
        n += batch
    rate = n / dt
    if hasattr(it, "close"):
        it.close()
    return rate


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4_000_000,
                    help="synthetic dataset size (rows)")
    ap.add_argument("--batch", type=int, default=1 << 17,
                    help="batch size (matches bench.py's headline)")
    ap.add_argument("--seconds", type=float, default=8.0,
                    help="measurement window per stage")
    ap.add_argument("--data-dir", default="/tmp/fmtpu_bench_input",
                    help="packed dir to create/reuse")
    ap.add_argument("--prefetch-depth", type=int, default=4)
    ap.add_argument("--compact-cap", type=int, default=0, dest="compact_cap",
                    help="with --host-dedup: measure the COMPACT aux "
                         "(ops/scatter.compact_aux) at this static "
                         "per-field capacity instead of the full-B aux")
    ap.add_argument("--host-dedup", action="store_true", dest="host_dedup",
                    help="add the DedupAuxBatches stage (per-batch argsort "
                         "+ segment maps on the host) — the feed-rate cost "
                         "of TrainConfig.host_dedup")
    args = ap.parse_args()
    if args.compact_cap and not args.host_dedup:
        ap.error("--compact-cap requires --host-dedup")

    num_fields, bucket = 39, 1 << 18

    meta = os.path.join(args.data_dir, "meta.json")
    need = True
    if os.path.exists(meta):
        with open(meta) as f:
            need = json.load(f).get("num_examples") != args.rows
    if need:
        _log(f"synthesizing {args.rows} rows into {args.data_dir}...")
        t0 = time.perf_counter()
        import shutil

        if os.path.isdir(args.data_dir):
            shutil.rmtree(args.data_dir)
        synthesize_packed(args.data_dir, args.rows, num_fields, bucket)
        _log(f"synthesized in {time.perf_counter() - t0:.1f}s")

    # Full cpu guard (not just the config pin): with the attachment
    # dead, the plugin factory hangs jax.devices() even under
    # JAX_PLATFORMS=cpu — utils/cpuguard drops the factory first.
    from fm_spark_tpu.utils.cpuguard import force_cpu_platform

    force_cpu_platform()
    import jax

    dev = jax.devices()[0]
    _log(f"device: {dev.device_kind}")

    from fm_spark_tpu.cli import StreamingBatches
    from fm_spark_tpu.data import PackedBatches, PackedDataset, Prefetcher

    ds = PackedDataset(args.data_dir)

    def raw():
        return PackedBatches(ds, args.batch, seed=1)

    def with_field_local_unfused():
        # The pre-round-5 production path: conversion as a second
        # full-batch pass in the StreamingBatches wrapper. Kept as a
        # stage so the fused win stays attributable.
        return StreamingBatches(PackedBatches(ds, args.batch, seed=1),
                                bucket=bucket)

    def with_field_local():
        # The production path: conversion fused into the (native when
        # available) row gather inside PackedBatches.
        return PackedBatches(ds, args.batch, seed=1, bucket=bucket)

    def put_block(b):
        jax.block_until_ready(jax.device_put(b))

    from fm_spark_tpu.data import DedupAuxBatches

    source = (
        (lambda: DedupAuxBatches(with_field_local(),
                                 cap=args.compact_cap))
        if args.host_dedup else with_field_local
    )
    from fm_spark_tpu import native

    _log(f"native gather: {native.gather_available()}")
    stages = [
        ("packed_batches", raw, lambda b: None),
        ("+field_local_unfused", with_field_local_unfused, lambda b: None),
        ("+field_local", with_field_local, lambda b: None),
    ]
    if args.host_dedup:
        stages.append(("+dedup_aux", source, lambda b: None))
    stages += [
        ("+device_put", source, put_block),
        ("+prefetcher", lambda: Prefetcher(source(),
                                           depth=args.prefetch_depth,
                                           device_put=True),
         lambda b: jax.block_until_ready(b)),
    ]
    rates = {}
    for name, make, consume in stages:
        r = _rate(make, args.seconds, args.batch, consume)
        rates[name] = r
        _log(f"{name:16s} {r:12.0f} samples/s "
             f"({r / TARGET_PER_CHIP:.2f}x one chip's need)")

    end_to_end = rates["+prefetcher"]
    print(json.dumps({
        "metric": METRIC,
        "value": round(end_to_end, 1),
        "unit": "samples/sec",
        "vs_baseline": round(end_to_end / TARGET_PER_CHIP, 4),
        "stages": {k: round(v, 1) for k, v in rates.items()},
    }))


if __name__ == "__main__":
    main()
