"""Input-pipeline benchmark: packed dir -> StreamingBatches -> device.

Measures the part bench.py deliberately excludes (its data is
device-resident): sustained host-side feed rate from a Criteo-shaped
packed directory through the production loader stack, against the
north-star requirement of ~1.25M samples/sec/chip (BASELINE.md; SURVEY.md
S7 hard part #1 — "input pipeline at 10M samples/s" across 8 chips).

Prints ONE JSON line with the end-to-end rate (loader + field-local id
conversion + host->device transfer, prefetched), plus stderr rows for
each pipeline stage so regressions are attributable:

  stage 1  PackedBatches        memmap read + chunk-shuffled gather
  stage 2  + field_local        the FieldFM id conversion (cli layer)
  stage 3  + device_put         blocking transfer, no prefetch
  stage 4  + Prefetcher         stage 3 with the producer thread hiding
                                assembly+transfer behind the consumer

Streaming-ingest ladder (ISSUE 6 — raw dirty-tolerant TEXT, not the
preprocessed binary; the rates that close ROADMAP open item 2):

  stream_py                 StreamBatches, per-line Python parse (the
                            PR-4 hardened path — round-9's ~1.2k rows/s)
  stream_native             NativeStreamBatches, C++ chunk parse with
                            identical guard/cursor semantics
  stream_native+prefetch    + Prefetcher producer thread parsing chunk
                            N+1 while batch N is consumed, device_put
                            double-buffered

A ``streaming_rows_per_sec`` block lands in the output JSON so the win
stays attributable against the in-memory ``packed_batches`` stage.

Synthesizes its own packed data AND text shards (one-time, reused
across runs via --data-dir) so it never depends on real Criteo being
present.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

METRIC = "input_pipeline_samples_per_sec"
TARGET_PER_CHIP = 10_000_000 / 8


def _log(msg):
    print(f"bench_input: {msg}", file=sys.stderr, flush=True)


def synthesize_packed(path: str, rows: int, num_fields: int = 39,
                      bucket: int = 1 << 18, seed: int = 0,
                      chunk: int = 1 << 20) -> None:
    """Write a Criteo-shaped packed dir (per-field-offset ids, int8
    labels, store_vals=False — the criteo.preprocess layout)."""
    from fm_spark_tpu.data import PackedWriter

    rng = np.random.default_rng(seed)
    offs = (np.arange(num_fields, dtype=np.int64) * bucket)[None, :]
    with PackedWriter(path, num_fields, store_vals=False) as w:
        for start in range(0, rows, chunk):
            n = min(chunk, rows - start)
            ids = (rng.integers(0, bucket, size=(n, num_fields),
                                dtype=np.int64) + offs).astype(np.int32)
            labels = (rng.random(n) < 0.25).astype(np.int8)
            w.append(ids, labels)


def synthesize_tsv_fast(path: str, rows: int, seed: int = 0,
                        vocab_per_field: int = 1000,
                        missing_rate: float = 0.05,
                        chunk: int = 100_000) -> None:
    """Criteo-shaped synthetic TSV, vectorized (data/criteo.py's
    synthesize_tsv is a per-value Python loop — fine for 6k bench rows,
    too slow for the multi-million-row streaming ladder)."""
    from fm_spark_tpu.data.criteo import NUM_CAT, NUM_INT

    rng = np.random.default_rng(seed)
    with open(path, "wb") as f:
        for start in range(0, rows, chunk):
            n = min(chunk, rows - start)
            label = (rng.random(n) < 0.25).astype(np.int8)
            ints = (rng.zipf(1.5, size=(n, NUM_INT)) - 1).astype(np.int64)
            cats = rng.zipf(1.3, size=(n, NUM_CAT)) % vocab_per_field
            miss = rng.random((n, NUM_INT + NUM_CAT)) < missing_rate
            out = []
            for r in range(n):
                cols = [b"1" if label[r] else b"0"]
                cols += [b"" if miss[r, c] else str(ints[r, c]).encode()
                         for c in range(NUM_INT)]
                cols += [b"" if miss[r, NUM_INT + c] else
                         b"%08x" % int(cats[r, c]) for c in range(NUM_CAT)]
                out.append(b"\t".join(cols))
            f.write(b"\n".join(out) + b"\n")


def _text_shards(data_dir: str, rows: int, n_shards: int = 3):
    """Create/reuse the streaming ladder's text shards under data_dir."""
    tdir = os.path.join(data_dir, "text")
    meta = os.path.join(tdir, "meta.json")
    paths = [os.path.join(tdir, f"shard{s}.tsv") for s in range(n_shards)]
    if os.path.exists(meta):
        with open(meta) as f:
            if json.load(f).get("rows") == rows:
                return paths
    os.makedirs(tdir, exist_ok=True)
    _log(f"synthesizing {rows} text rows into {tdir}...")
    t0 = time.perf_counter()
    per = rows // n_shards
    for s, p in enumerate(paths):
        synthesize_tsv_fast(p, per + (rows - per * n_shards
                                      if s == n_shards - 1 else 0), seed=s)
    with open(meta, "w") as f:
        json.dump({"rows": rows}, f)
    _log(f"text synthesized in {time.perf_counter() - t0:.1f}s")
    return paths


def _rate(make_iter, seconds: float, batch: int,
          consume=lambda b: None) -> float:
    """Sustained samples/sec of ``next(it)`` + ``consume(batch)``."""
    it = make_iter()
    # Warm the first batch (memmap page-in, jit of nothing, thread spin-up).
    consume(next(it))
    n = 0
    t0 = time.perf_counter()
    while (dt := time.perf_counter() - t0) < seconds:
        consume(next(it))
        n += batch
    rate = n / dt
    if hasattr(it, "close"):
        it.close()
    return rate


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4_000_000,
                    help="synthetic dataset size (rows)")
    ap.add_argument("--batch", type=int, default=1 << 17,
                    help="batch size (matches bench.py's headline)")
    ap.add_argument("--seconds", type=float, default=8.0,
                    help="measurement window per stage")
    ap.add_argument("--data-dir", default="/tmp/fmtpu_bench_input",
                    help="packed dir to create/reuse")
    ap.add_argument("--prefetch-depth", type=int, default=4)
    ap.add_argument("--compact-cap", type=int, default=0, dest="compact_cap",
                    help="with --host-dedup: measure the COMPACT aux "
                         "(ops/scatter.compact_aux) at this static "
                         "per-field capacity instead of the full-B aux")
    ap.add_argument("--host-dedup", action="store_true", dest="host_dedup",
                    help="add the DedupAuxBatches stage (per-batch argsort "
                         "+ segment maps on the host) — the feed-rate cost "
                         "of TrainConfig.host_dedup")
    ap.add_argument("--no-stream", action="store_true", dest="no_stream",
                    help="skip the streaming-ingest ladder (text "
                         "synthesis + stream_py/stream_native stages)")
    ap.add_argument("--stream-rows", type=int, default=1_500_000,
                    dest="stream_rows",
                    help="synthetic text rows for the streaming ladder "
                         "(3 shards; epochs cycle if the window drains "
                         "them)")
    ap.add_argument("--stream-py-batch", type=int, default=2048,
                    dest="stream_py_batch",
                    help="batch size for the stream_py stage only (the "
                         "pure-Python parser is ~3 orders of magnitude "
                         "slower; a headline-sized batch would blow the "
                         "measurement window)")
    ap.add_argument("--stream-py-seconds", type=float, default=6.0,
                    dest="stream_py_seconds",
                    help="measurement window for the stream_py stage")
    args = ap.parse_args()
    if args.compact_cap and not args.host_dedup:
        ap.error("--compact-cap requires --host-dedup")

    num_fields, bucket = 39, 1 << 18

    meta = os.path.join(args.data_dir, "meta.json")
    need = True
    if os.path.exists(meta):
        with open(meta) as f:
            need = json.load(f).get("num_examples") != args.rows
    if need:
        _log(f"synthesizing {args.rows} rows into {args.data_dir}...")
        t0 = time.perf_counter()
        import shutil

        if os.path.isdir(args.data_dir):
            shutil.rmtree(args.data_dir)
        synthesize_packed(args.data_dir, args.rows, num_fields, bucket)
        _log(f"synthesized in {time.perf_counter() - t0:.1f}s")

    # Full cpu guard (not just the config pin): with the attachment
    # dead, the plugin factory hangs jax.devices() even under
    # JAX_PLATFORMS=cpu — utils/cpuguard drops the factory first.
    from fm_spark_tpu.utils.cpuguard import force_cpu_platform

    force_cpu_platform()
    import jax

    dev = jax.devices()[0]
    _log(f"device: {dev.device_kind}")

    from fm_spark_tpu.cli import StreamingBatches
    from fm_spark_tpu.data import PackedBatches, PackedDataset, Prefetcher

    ds = PackedDataset(args.data_dir)

    def raw():
        return PackedBatches(ds, args.batch, seed=1)

    def with_field_local_unfused():
        # The pre-round-5 production path: conversion as a second
        # full-batch pass in the StreamingBatches wrapper. Kept as a
        # stage so the fused win stays attributable.
        return StreamingBatches(PackedBatches(ds, args.batch, seed=1),
                                bucket=bucket)

    def with_field_local():
        # The production path: conversion fused into the (native when
        # available) row gather inside PackedBatches.
        return PackedBatches(ds, args.batch, seed=1, bucket=bucket)

    def put_block(b):
        jax.block_until_ready(jax.device_put(b))

    from fm_spark_tpu.data import DedupAuxBatches

    source = (
        (lambda: DedupAuxBatches(with_field_local(),
                                 cap=args.compact_cap))
        if args.host_dedup else with_field_local
    )
    from fm_spark_tpu import native

    _log(f"native gather: {native.gather_available()}")
    stages = [
        ("packed_batches", raw, lambda b: None),
        ("+field_local_unfused", with_field_local_unfused, lambda b: None),
        ("+field_local", with_field_local, lambda b: None),
    ]
    if args.host_dedup:
        stages.append(("+dedup_aux", source, lambda b: None))
    stages += [
        ("+device_put", source, put_block),
        ("+prefetcher", lambda: Prefetcher(source(),
                                           depth=args.prefetch_depth,
                                           device_put=True),
         lambda b: jax.block_until_ready(b)),
    ]
    rates = {}
    for name, make, consume in stages:
        r = _rate(make, args.seconds, args.batch, consume)
        rates[name] = r
        _log(f"{name:16s} {r:12.0f} samples/s "
             f"({r / TARGET_PER_CHIP:.2f}x one chip's need)")

    streaming = None
    if not args.no_stream:
        # Streaming-ingest ladder (ISSUE 6): raw text through the
        # hardened ShardReader/RecordGuard path, priced per parser.
        from fm_spark_tpu.data import NativeStreamBatches, ShardReader
        from fm_spark_tpu.data.stream import StreamBatches, line_parser
        from fm_spark_tpu.data.native_stream import native_stream_supported
        from fm_spark_tpu.data.criteo import NUM_FIELDS

        paths = _text_shards(args.data_dir, args.stream_rows)
        nf = NUM_FIELDS * bucket

        def stream_py():
            return StreamBatches(
                ShardReader(paths), line_parser("criteo", bucket),
                args.stream_py_batch, NUM_FIELDS, num_features=nf)

        def stream_native():
            return NativeStreamBatches(
                ShardReader(paths, chunk_bytes=1 << 22), "criteo",
                args.batch, NUM_FIELDS, num_features=nf, bucket=bucket)

        streaming = {}
        r = _rate(stream_py, args.stream_py_seconds, args.stream_py_batch)
        streaming["stream_py"] = r
        _log(f"{'stream_py':22s} {r:12.0f} rows/s (per-line Python parse)")
        if native_stream_supported("criteo", NUM_FIELDS, bucket):
            r = _rate(stream_native, args.seconds, args.batch)
            streaming["stream_native"] = r
            _log(f"{'stream_native':22s} {r:12.0f} rows/s")
            r = _rate(
                lambda: Prefetcher(stream_native(),
                                   depth=args.prefetch_depth,
                                   device_put=True),
                args.seconds, args.batch,
                lambda b: jax.block_until_ready(b))
            streaming["stream_native+prefetch"] = r
            _log(f"{'stream_native+prefetch':22s} {r:12.0f} rows/s")
        else:
            _log("stream_native SKIPPED (native chunk parser unavailable)")
        streaming = {k: round(v, 1) for k, v in streaming.items()}
        # Exit-ratio stage (ROADMAP item 2): the in-memory PackedBatches
        # rate AT THE STREAMING BATCH SIZE is the ladder's denominator —
        # re-measured here (not reused from the samples/s ladder above)
        # so the round-10 0.075x-on-2-cores figure re-prices cleanly on
        # any host, and stamped with the cores the parse actually had.
        streaming["packed_batches"] = round(
            _rate(raw, min(args.seconds, 4.0), args.batch), 1)
        try:
            cores = len(os.sched_getaffinity(0))
        except AttributeError:  # non-Linux
            cores = os.cpu_count() or 1
        streaming["cores_used"] = cores
        best_stream = next(
            (streaming[kk] for kk in ("stream_native+prefetch",
                                      "stream_native") if kk in streaming),
            None)
        if best_stream is not None:
            streaming["speedup_vs_py"] = round(
                best_stream / streaming["stream_py"], 1)
            streaming["vs_packed_batches"] = round(
                best_stream / streaming["packed_batches"], 4)
            _log(f"{'exit ratio':22s} {streaming['vs_packed_batches']:12}"
                 f" x of in-memory PackedBatches on {cores} core(s)")

    end_to_end = rates["+prefetcher"]
    payload = {
        "metric": METRIC,
        "value": round(end_to_end, 1),
        "unit": "samples/sec",
        "vs_baseline": round(end_to_end / TARGET_PER_CHIP, 4),
        "stages": {k: round(v, 1) for k, v in rates.items()},
    }
    if streaming is not None:
        payload["streaming_rows_per_sec"] = streaming
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
