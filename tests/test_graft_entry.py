"""Driver contract: entry() compiles; dryrun_multichip runs on 8 devices."""

import sys
import os

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (256,)
    # And it lowers without executing (the driver's compile check).
    jax.jit(fn).lower(*args).compile()


@pytest.mark.slow
def test_dryrun_multichip_8(eight_devices):
    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_odd():
    __graft_entry__.dryrun_multichip(3)  # falls back to pure DP mesh
