"""Unit tests for the supervised TPU-attachment watcher
(tools/tpu_watch.py) — the point of replacing the bash loop (ISSUE 2):
its probe/backoff policy and one-time queue progression are now
testable logic, exercised here with injected probe/runner/clock so no
device, bench run, or wall-clock is involved.
"""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_watch_mod():
    spec = importlib.util.spec_from_file_location(
        "tpu_watch_tool", os.path.join(REPO, "tools", "tpu_watch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += s


def test_best_value_parses_max_and_tolerates_junk(tmp_path):
    mod = _load_watch_mod()
    p = tmp_path / "out"
    p.write_text(
        "bench: noise\n"
        '{"value": 10.0}\n'
        "{torn json\n"
        '{"value": null, "error": "x"}\n'
        '{"value": 35.5}\n'
    )
    assert mod.best_value(str(p)) == 35.5
    assert mod.best_value(str(tmp_path / "missing")) == -1.0


def _make_watch(mod, tmp_path, probe_script, values, deadline=10000.0):
    """A TpuWatch with scripted probe outcomes and per-command bench
    values; returns (watch, clock, runner_log). The probe ledger is
    pointed into the tmp dir so tests never touch the repo's real
    cross-run ledger."""
    clock = FakeClock()
    probes = list(probe_script)
    ran = []

    def probe():
        return probes.pop(0) if probes else True

    def runner(argv, timeout_s, out_path, err_path):
        name = os.path.basename(out_path)
        ran.append((argv[0], name))
        val = values(name)
        with open(out_path, "w") as f:
            if val is not None:
                f.write(json.dumps({"value": val}) + "\n")
        return 0

    watch = mod.TpuWatch(
        str(tmp_path / "out"), deadline, runner=runner, probe=probe,
        sleep=clock.sleep, clock=clock,
        policy=mod.BackoffPolicy(initial=45.0, multiplier=1.5,
                                 max_delay=180.0, jitter=0.0),
        ledger=mod.PerfLedger(str(tmp_path / "ledger.jsonl")),
        run_id="watch-test",
    )
    return watch, clock, ran


def test_watch_backs_off_while_down_then_drains_queue(tmp_path):
    mod = _load_watch_mod()
    watch, clock, ran = _make_watch(
        mod, tmp_path,
        probe_script=[False, False, False, True],
        values=lambda name: 100.0,
        deadline=1000.0,  # one healthy window, then the drained-queue
                          # sleep (1500 s) carries past the deadline
    )
    best = watch.watch()
    assert best == 100.0
    # Down-time polling backed off 45 → 67.5 → 101.25 (bounded
    # exponential, not bash's fixed 45), then the healthy window ran
    # the gfull probe, the headline sweep, and the whole one-time queue.
    events = [json.loads(ln) for ln in
              open(os.path.join(str(tmp_path / "out"), "health.jsonl"))]
    downs = [e for e in events if e["event"] == "down"]
    assert [d["next_probe_s"] for d in downs] == [45.0, 67.5, 101.2]
    names = [n for _, n in ran]
    assert names[0] == "gfull_probe.jsonl"
    assert names[1].startswith("sweep_")
    assert names[2:] == ["ffm_sweep.out", "deepfm_sweep.out",
                         "kaggle_sweep.out", "b262_sweep.out"]
    for marker, _, _ in mod.QUEUE:
        assert os.path.exists(os.path.join(str(tmp_path / "out"), marker))
    assert watch.queue_drained()
    # Keep-best copy landed.
    assert mod.best_value(
        os.path.join(str(tmp_path / "out"), "bench_sweep.out")) == 100.0
    assert any(e["event"] == "queue_advanced" for e in events)


def test_watch_keeps_best_sweep_and_halts_queue_on_flap(tmp_path):
    mod = _load_watch_mod()
    vals = {"n": 0}

    def values(name):
        if name.startswith("sweep_"):
            # Window 1 throttled (40), window 2 healthier (90).
            vals["n"] += 1
            return 40.0 if vals["n"] == 1 else 90.0
        if name == "ffm_sweep.out":
            # First try flaps (no value), later succeeds.
            vals["ffm"] = vals.get("ffm", 0) + 1
            return None if vals["ffm"] == 1 else 55.0
        return 70.0

    watch, clock, ran = _make_watch(
        mod, tmp_path, probe_script=[True, True],
        values=values, deadline=500.0,
    )
    best = watch.watch()
    # Window 1: headline ok, ffm flapped → queue halted for the window
    # (no deepfm attempt yet). Window 2: ffm retried and the queue
    # continued; the healthier sweep replaced the throttled keep-best.
    names = [n for _, n in ran]
    w1 = names[: names.index("ffm_sweep.out") + 1]
    assert "deepfm_sweep.out" not in w1
    assert names.count("ffm_sweep.out") == 2
    assert best == 90.0
    assert mod.best_value(
        os.path.join(str(tmp_path / "out"), "bench_sweep.out")) == 90.0


def test_probe_outcomes_journal_into_ledger(tmp_path):
    """ISSUE 9 satellite: every attachment probe outcome lands in the
    perf ledger's fingerprint stream — down streaks and the recovery
    are a first-class ``attachment_probe`` record series, not PERF.md
    prose."""
    mod = _load_watch_mod()
    watch, clock, ran = _make_watch(
        mod, tmp_path,
        probe_script=[False, False, True],
        values=lambda name: 50.0,
        deadline=600.0,
    )
    watch.watch()
    probes = watch.ledger.records(kind="attachment_probe")
    assert [p["value"] for p in probes] == [0.0, 0.0, 1.0]
    assert [p["streak"] for p in probes] == [1, 2, 0]
    healths = [p["fingerprint"]["attachment_health"] for p in probes]
    assert healths == ["down", "down", "healthy"]
    assert all(p["run_id"] == "watch-test" for p in probes)
    assert all(p["leg"] == "attachment" for p in probes)
    # Weather is not a cohort splitter: down and healthy probes share
    # one fingerprint key (the whole series is one comparable stream).
    assert len({p["fingerprint"]["key"] for p in probes}) == 1


def test_broken_ledger_never_kills_the_watch(tmp_path):
    """The watch outlives an unwritable ledger (best-effort contract)."""
    mod = _load_watch_mod()
    watch, clock, ran = _make_watch(
        mod, tmp_path, probe_script=[True],
        values=lambda name: 60.0, deadline=500.0)

    class Boom:
        def append(self, record):
            raise OSError("disk full")

    watch.ledger = Boom()
    assert watch.watch() == 60.0


def test_wrapper_script_delegates_to_python_watcher():
    # The historical entry point must keep working — and must no longer
    # carry its own poll loop.
    sh = open(os.path.join(REPO, "tpu_watch.sh")).read()
    assert "tools/tpu_watch.py" in sh
    assert "while" not in sh  # the bash loop is gone
