"""FieldFMSpec: layout equivalence with the flat FM and fused-step parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.sparse import make_field_sparse_sgd_step, make_sparse_sgd_step
from fm_spark_tpu.train import TrainConfig


F, BUCKET, K, B = 5, 32, 4, 16


@pytest.fixture(params=[True, False], ids=["fused", "split"])
def field_spec(request):
    return models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, fused_linear=request.param,
    )


@pytest.fixture
def batch(rng):
    ids = rng.integers(0, BUCKET, size=(B, F)).astype(np.int32)
    vals = rng.normal(size=(B, F)).astype(np.float32)
    labels = rng.integers(0, 2, B).astype(np.float32)
    return jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(labels)


def test_scores_match_flat_fm(field_spec, batch):
    ids, vals, _ = batch
    params = field_spec.init(jax.random.key(0))
    flat = field_spec.flat_spec()
    flat_params = field_spec.to_flat_params(params)
    gids = field_spec.to_global_ids(ids)
    np.testing.assert_allclose(
        field_spec.scores(params, ids, vals),
        flat.scores(flat_params, gids, vals),
        rtol=1e-5, atol=1e-6,
    )


def test_field_sparse_step_matches_flat_sparse_step(field_spec, batch):
    ids, vals, labels = batch
    config = TrainConfig(learning_rate=0.2, lr_schedule="inv_sqrt",
                         optimizer="sgd")
    params = field_spec.init(jax.random.key(1))
    # Deep copy: both steps donate their inputs, and to_flat_params shares
    # the w0 buffer with the field params.
    flat_params = jax.tree_util.tree_map(
        jnp.copy, field_spec.to_flat_params(params)
    )
    fstep = make_field_sparse_sgd_step(field_spec, config)
    sstep = make_sparse_sgd_step(field_spec.flat_spec(), config)
    w = jnp.ones((B,))
    gids = field_spec.to_global_ids(ids)
    for i in range(3):
        params, loss_f = fstep(params, jnp.int32(i), ids, vals, labels, w)
        flat_params, loss_s = sstep(flat_params, jnp.int32(i), gids, vals, labels, w)
        np.testing.assert_allclose(float(loss_f), float(loss_s), rtol=1e-6)
    merged = field_spec.to_flat_params(params)
    for key in ("w0", "w", "v"):
        np.testing.assert_allclose(
            np.asarray(merged[key]), np.asarray(flat_params[key]),
            rtol=1e-5, atol=1e-6, err_msg=key,
        )


def test_field_fm_wrong_slots_raises(field_spec, rng):
    params = field_spec.init(jax.random.key(0))
    ids = jnp.zeros((4, F + 1), jnp.int32)
    vals = jnp.ones((4, F + 1))
    with pytest.raises(ValueError, match="fields"):
        field_spec.scores(params, ids, vals)


def test_field_fm_save_load(tmp_path, field_spec, batch):
    ids, vals, _ = batch
    params = field_spec.init(jax.random.key(2))
    models.save_model(str(tmp_path / "m"), field_spec, params)
    spec2, params2 = models.load_model(str(tmp_path / "m"))
    assert spec2 == field_spec
    np.testing.assert_allclose(
        field_spec.scores(params, ids, vals), spec2.scores(params2, ids, vals),
        rtol=1e-6,
    )


def test_field_fm_validation():
    with pytest.raises(ValueError, match="num_fields"):
        models.FieldFMSpec(num_features=100, rank=2, num_fields=0, bucket=10)
    with pytest.raises(ValueError, match="must equal"):
        models.FieldFMSpec(num_features=99, rank=2, num_fields=5, bucket=10)
