"""FieldFFM: flat-FFM equivalence, fused-step gradients, save/load."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.sparse import make_field_ffm_sparse_sgd_step
from fm_spark_tpu.train import TrainConfig, make_train_step, make_optimizer


def _spec(F=4, bucket=16, k=3, **kw):
    return models.FieldFFMSpec(
        num_features=F * bucket, rank=k, num_fields=F, bucket=bucket,
        init_std=0.2, **kw,
    )


def _batch(rng, b, F, bucket):
    return (
        rng.integers(0, bucket, size=(b, F)).astype(np.int32),
        rng.uniform(0.5, 1.5, size=(b, F)).astype(np.float32),
        rng.integers(0, 2, b).astype(np.float32),
        np.ones((b,), np.float32),
    )


def test_scores_match_flat_ffm():
    rng = np.random.default_rng(0)
    spec = _spec()
    params = spec.init(jax.random.key(0))
    # Randomize linear weights too (init is zero).
    params["vw"] = [
        t.at[:, -1].set(jnp.asarray(rng.normal(size=t.shape[0]), t.dtype))
        for t in params["vw"]
    ]
    ids, vals, _, _ = _batch(rng, 32, 4, 16)
    ids, vals = jnp.asarray(ids), jnp.asarray(vals)
    want = spec.flat_spec().scores(
        spec.to_flat_params(params), spec.to_global_ids(ids), vals
    )
    got = spec.scores(params, ids, vals)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_scores_match_bruteforce_oracle():
    from fm_spark_tpu.ops.ffm import ffm_scores_dense

    rng = np.random.default_rng(1)
    spec = _spec(F=3, bucket=8, k=2)
    params = spec.init(jax.random.key(1))
    flat = spec.to_flat_params(params)
    ids, vals, _, _ = _batch(rng, 16, 3, 8)
    ids_j, vals_j = jnp.asarray(ids), jnp.asarray(vals)
    want = ffm_scores_dense(
        flat["w0"], flat["w"], flat["v"], spec.to_global_ids(ids_j), vals_j
    )
    got = spec.scores(params, ids_j, vals_j)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_fused_step_matches_autodiff_dense_path():
    """The analytic fused backward ≡ jax.grad through scores + SGD."""
    rng = np.random.default_rng(2)
    spec = _spec()
    config = TrainConfig(learning_rate=0.3, lr_schedule="inv_sqrt",
                         optimizer="sgd")
    fused = make_field_ffm_sparse_sgd_step(spec, config)
    dense = make_train_step(spec, config, make_optimizer(config))

    pa = spec.init(jax.random.key(2))
    pb = jax.tree_util.tree_map(jnp.copy, pa)
    opt_state = make_optimizer(config).init(pb)
    for i in range(3):
        ids, vals, labels, w = map(jnp.asarray, _batch(rng, 32, 4, 16))
        pa, loss_a = fused(pa, jnp.int32(i), ids, vals, labels, w)
        pb, opt_state, m = dense(pb, opt_state, ids, vals, labels, w)
        np.testing.assert_allclose(float(loss_a), float(m["loss"]), rtol=1e-5)
    for f in range(4):
        np.testing.assert_allclose(
            np.asarray(pa["vw"][f]), np.asarray(pb["vw"][f]),
            rtol=5e-4, atol=1e-6,
        )
    np.testing.assert_allclose(float(pa["w0"]), float(pb["w0"]), rtol=1e-4)


def test_fused_step_learns_planted_structure():
    rng = np.random.default_rng(3)
    F, bucket = 4, 32
    spec = _spec(F=F, bucket=bucket, k=4)
    config = TrainConfig(learning_rate=0.2, lr_schedule="constant",
                         optimizer="sgd")
    step = make_field_ffm_sparse_sgd_step(spec, config)
    params = spec.init(jax.random.key(3))
    from fm_spark_tpu.data import synthetic_ctr

    ids_g, vals, labels = synthetic_ctr(4096, F * bucket, F, seed=3)
    ids = ids_g - (np.arange(F) * bucket)[None, :].astype(np.int32)
    losses = []
    for i in range(16):
        sl = slice(i * 256, (i + 1) * 256)
        params, loss = step(
            params, jnp.int32(i), jnp.asarray(ids[sl]), jnp.asarray(vals[sl]),
            jnp.asarray(labels[sl]), jnp.ones((256,), jnp.float32),
        )
        losses.append(float(loss))
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses


def test_dedup_mode_matches_scatter_add():
    rng = np.random.default_rng(4)
    spec = _spec(F=3, bucket=8, k=2)
    base = TrainConfig(learning_rate=0.3, optimizer="sgd", reg_factors=1e-3,
                       reg_linear=1e-4)
    step_a = make_field_ffm_sparse_sgd_step(spec, base)
    step_b = make_field_ffm_sparse_sgd_step(
        spec, dataclasses.replace(base, sparse_update="dedup")
    )
    pa = spec.init(jax.random.key(4))
    pb = jax.tree_util.tree_map(jnp.copy, pa)
    for i in range(2):
        batch = tuple(map(jnp.asarray, _batch(rng, 64, 3, 8)))
        pa, la = step_a(pa, jnp.int32(i), *batch)
        pb, lb = step_b(pb, jnp.int32(i), *batch)
        np.testing.assert_allclose(float(la), float(lb), rtol=1e-6)
    for f in range(3):
        np.testing.assert_allclose(
            np.asarray(pa["vw"][f]), np.asarray(pb["vw"][f]),
            rtol=1e-4, atol=1e-6,
        )


def test_save_load_roundtrip(tmp_path):
    spec = _spec()
    params = spec.init(jax.random.key(5))
    models.save_model(str(tmp_path / "m"), spec, params)
    spec2, params2 = models.load_model(str(tmp_path / "m"))
    assert spec2 == spec
    rng = np.random.default_rng(5)
    ids, vals, _, _ = _batch(rng, 8, 4, 16)
    np.testing.assert_allclose(
        np.asarray(spec2.predict(params2, jnp.asarray(ids), jnp.asarray(vals))),
        np.asarray(spec.predict(params, jnp.asarray(ids), jnp.asarray(vals))),
        rtol=1e-6,
    )
