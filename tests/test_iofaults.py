"""Storage fault plane + durable-write seam tests (ISSUE 20).

The load-bearing contracts:

- **seeded io-fault grammar** — ``io_write``/``io_fsync``/
  ``io_rename``/``io_read`` rules (path-class-scoped, occurrence-
  ranged) parse eagerly, reject typos eagerly — including a typo'd
  path class, which unlike a net peer scope is a CLOSED vocabulary —
  and replay deterministically;
- **one seam, three tiers** — the durable helpers publish atomically
  (a torn tmp is never the published file), best-effort failures are
  counted + flagged (``obs/io_degraded``) and swallowed, fail-loud
  failures propagate to the checkpoint tier's bounded retry /
  ENOSPC-triggered emergency GC / loud :class:`CheckpointIOError`;
- **reads verify-then-walk-back** — a short ``io_read`` delivers a
  torn payload; restore-side callers (embed cold store, chain reader)
  refuse it and walk back, never crash-loop;
- **the disk campaign is green** — seeded schedules (ENOSPC mid
  checkpoint commit, torn rename mid-demotion racing a serve reload,
  slow-disk day save, EIO burst on flight compaction, read-only obs
  flip) graded by ``audit_disk`` from artifacts alone, plus the
  SIGKILL-during-emergency-GC subprocess drill and the byte-identity
  proof that an all-failing obs plane never touches training bytes.

Arming ``io_write``, ``io_fsync``, ``io_rename``, ``io_read``, and
``ckpt_gc`` here also satisfies fmlint's registry-coverage rule for
the new points.
"""

import errno
import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fm_spark_tpu import obs  # noqa: E402
from fm_spark_tpu.checkpoint import (  # noqa: E402
    ChainFollower,
    Checkpointer,
    CheckpointIOError,
)
from fm_spark_tpu.embed.store import ColdStore  # noqa: E402
from fm_spark_tpu.resilience import chaos, faults, iofaults  # noqa: E402
from fm_spark_tpu.resilience.chaos_audit import audit_disk  # noqa: E402
from fm_spark_tpu.utils import durable, sleeps  # noqa: E402
from fm_spark_tpu.utils.logging import EventLog, read_events  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults.clear()
    durable.reset_failure_counts()
    yield
    faults.clear()
    durable.reset_failure_counts()


# ------------------------------------------------ the plan grammar


def test_io_rules_expand_ranges_and_scope_path_classes():
    plan = faults.FaultPlan.from_spec(
        "io_write.ckpt@2-4=enospc;io_fsync@1=slow_ms:20;"
        "io_read@1=torn_write:8")
    for n in (2, 3, 4):
        r = plan.rule_for("io_write.ckpt", n)
        assert r is not None and r.action == "enospc"
    assert plan.rule_for("io_write.ckpt", 1) is None
    assert plan.rule_for("io_write.ckpt", 5) is None
    # The scoped key is its own point: the unscoped base never fires.
    assert plan.rule_for("io_write", 2) is None
    assert plan.rule_for("io_read", 1).param == "8"


@pytest.mark.parametrize("spec", [
    "io_write.bogus@1=eio",        # path class outside the closed set
    "io_read.replica-1@1=eio",     # net-style peer scope on an io point
    "train_step.ckpt@1=eio",       # path-class scope off an io point
    "train_step@1=enospc",         # io action off an io point
    "io_write@1=refuse",           # net action on an io point
    "io_fsync@1=slow_ms",          # missing required parameter
    "io_write@1=torn_write:lots",  # non-numeric parameter
    "io_write@9-3=eio",            # inverted range
    "io_write@1-600=eio",          # window wider than _MAX_RANGE
    "io_bogus@1=eio",              # unknown point
])
def test_io_grammar_rejects_typos_eagerly(spec):
    with pytest.raises(ValueError):
        faults.FaultPlan.from_spec(spec)


def test_slow_ms_is_shared_with_the_net_plane_but_stays_bounded():
    # A slow fsync and a slow link are the same latency primitive.
    faults.FaultPlan.from_spec("io_fsync.ckpt@1-4=slow_ms:80")
    # The SIGKILL-mid-GC drill's plan parses too (the ckpt_gc point).
    faults.FaultPlan.from_spec("io_write.ckpt@1=enospc;ckpt_gc@1=exit:29")


def test_check_advances_scoped_and_diskwide_counters():
    """"This class's Nth write" and "the disk's Nth write" count
    independently, and the class-scoped rule wins when both match."""
    faults.activate("io_write.ckpt@2=enospc;io_write@1=eio")
    # Event 1: unscoped occurrence 1 matches; scoped (occ 1) doesn't.
    assert iofaults.check("io_write", "ckpt").action == "eio"
    # Event 2: scoped occurrence 2 fires AND wins.
    assert iofaults.check("io_write", "ckpt").action == "enospc"
    assert iofaults.check("io_write", "ckpt") is None
    # A different class never consumed ckpt's counter.
    faults.activate("io_write.ckpt@1=eio")
    assert iofaults.check("io_write", "obs") is None
    assert iofaults.check("io_write", "ckpt").action == "eio"


def test_io_actions_emulate_their_errnos(monkeypatch):
    faults.activate("io_write@1=eio")
    with pytest.raises(OSError) as ei:
        iofaults.on_write()
    assert ei.value.errno == errno.EIO
    faults.activate("io_write@1=enospc")
    with pytest.raises(OSError) as ei:
        iofaults.on_write()
    assert ei.value.errno == errno.ENOSPC
    faults.activate("io_write@1=readonly")
    with pytest.raises(OSError) as ei:
        iofaults.on_write()
    assert ei.value.errno == errno.EROFS
    # torn_write returns a byte budget on write/read (the caller owns
    # the bytes to tear)...
    faults.activate("io_write@1=torn_write:7;io_read@1=torn_write:3")
    assert iofaults.on_write() == 7
    assert iofaults.on_read() == 3
    # ...and degrades to EIO on rename/fsync (a torn publish is a
    # failed publish).
    faults.activate("io_rename@1=torn_write:7;io_fsync@1=torn_write:7")
    with pytest.raises(OSError) as ei:
        iofaults.on_rename()
    assert ei.value.errno == errno.EIO
    with pytest.raises(OSError):
        iofaults.on_fsync()
    # Non-io actions on an io point fall through to the generic fire.
    faults.activate("io_write@1=error")
    with pytest.raises(faults.FaultInjected):
        iofaults.on_write()


def test_slow_ms_honors_test_sleep_scale(monkeypatch):
    """ISSUE 20 satellite: slow-disk drills prove latency TOLERANCE,
    so the designed sleep scales with FM_SPARK_TEST_SLEEP_SCALE."""
    monkeypatch.setenv(sleeps.ENV, "1.0")
    faults.activate("io_fsync@1=slow_ms:60")
    t0 = time.monotonic()
    assert iofaults.on_fsync() is None
    assert time.monotonic() - t0 >= 0.05
    monkeypatch.setenv(sleeps.ENV, "0.0")
    faults.activate("io_fsync@1=slow_ms:60")
    t0 = time.monotonic()
    assert iofaults.on_fsync() is None
    assert time.monotonic() - t0 < 0.05


# -------------------------------------------- the durable-write seam


def test_atomic_write_never_publishes_torn_bytes(tmp_path):
    path = str(tmp_path / "doc.json")
    faults.activate("io_write@1=torn_write:4")
    with pytest.raises(OSError):
        durable.atomic_write_bytes(path, b"0123456789",
                                   path_class="ckpt")
    # The torn payload hit the TMP only; the final path never appeared.
    assert not os.path.exists(path)
    assert durable.io_failure_counts()["ckpt"] == 1
    # The window exhausted: the same write now publishes whole.
    assert durable.atomic_write_bytes(path, b"0123456789",
                                      path_class="ckpt")
    with open(path, "rb") as f:
        assert f.read() == b"0123456789"


def test_rename_fault_strikes_after_payload_before_visibility(tmp_path):
    path = str(tmp_path / "doc.json")
    faults.activate("io_rename.ckpt@1=eio")
    with pytest.raises(OSError):
        durable.atomic_write_json(path, {"step": 4}, path_class="ckpt")
    assert not os.path.exists(path)


def test_best_effort_failures_are_counted_flagged_and_swallowed(tmp_path):
    path = str(tmp_path / "obs.json")
    faults.activate("io_write.obs@1=eio")
    assert durable.atomic_write_json(path, {"a": 1}, path_class="obs",
                                     best_effort=True) is False
    counts = durable.io_failure_counts()
    assert counts["total"] == 1 and counts["obs"] == 1
    assert counts["best_effort"] == 1
    assert obs.counter("io.write_failed_total").value >= 1
    assert obs.counter("io.write_failed.obs_total").value >= 1
    # Sticky degradation flag: the record has holes, the doctor must
    # see it even after the disk heals.
    snap = obs.registry().snapshot()
    assert snap["gauges"].get("obs/io_degraded") == 1.0
    # Fail-loud failures do NOT count as degraded-swallowed.
    faults.activate("io_write.ckpt@1=eio")
    with pytest.raises(OSError):
        durable.atomic_write_json(str(tmp_path / "m.json"), {},
                                  path_class="ckpt")
    assert durable.io_failure_counts()["best_effort"] == 1


def test_torn_append_leaves_partial_line_readers_skip(tmp_path):
    path = str(tmp_path / "log.jsonl")
    durable.append_line_path(path, json.dumps({"seq": 0}),
                             path_class="obs")
    faults.activate("io_write.obs@1=torn_write:5")
    assert durable.append_line_path(
        path, json.dumps({"seq": 1, "pad": "x" * 40}),
        path_class="obs", best_effort=True) is False
    # The torn fragment has no newline: the NEXT append merges into
    # the garbled line (both records lost from disk), and the one
    # after lands on a fresh line — readers skip exactly the poisoned
    # line, nothing more.
    durable.append_line_path(path, json.dumps({"seq": 2}),
                             path_class="obs")
    durable.append_line_path(path, json.dumps({"seq": 3}),
                             path_class="obs")
    from fm_spark_tpu.obs.flight import read_spool
    recs = read_spool(path)
    assert [r["seq"] for r in recs] == [0, 3]


def test_read_faults_short_read_and_eio(tmp_path):
    path = str(tmp_path / "doc.json")
    durable.atomic_write_json(path, {"step": 7}, path_class="ckpt")
    faults.activate("io_read.ckpt@1=torn_write:2")
    assert durable.read_bytes(path, path_class="ckpt") == b'{"'
    with pytest.raises(ValueError):
        faults.activate("io_read.ckpt@1=torn_write:2")
        durable.read_json(path, path_class="ckpt")
    faults.activate("io_read.ckpt@1=eio")
    with pytest.raises(OSError):
        durable.read_json(path, path_class="ckpt")
    # Healed: the payload is intact underneath.
    assert durable.read_json(path, path_class="ckpt") == {"step": 7}


# --------------------------- the checkpoint tier (fail-loud + retry)


def _ck(tmp_path, journal=None):
    return Checkpointer(str(tmp_path / "ck"), save_every=1,
                        max_to_keep=16, async_save=False,
                        journal=journal)


def test_checkpoint_absorbs_transient_eio_with_bounded_backoff(
        tmp_path, monkeypatch):
    monkeypatch.setenv(sleeps.ENV, "0.0")
    journal = EventLog(str(tmp_path / "events.jsonl"))
    ck = _ck(tmp_path, journal)
    try:
        faults.activate("io_write.ckpt@1=eio")
        ck.save(1, {"w": np.arange(4, dtype=np.float32)}, {},
                force=True)
    finally:
        faults.clear()
        ck.close()
    assert ck.last_good_step() == 1
    kinds = [e.get("event") or e.get("kind")
             for e in read_events(str(tmp_path / "events.jsonl"))]
    assert "ckpt_io_retry" in kinds


def test_enospc_triggers_journaled_emergency_gc_then_commit(
        tmp_path, monkeypatch):
    monkeypatch.setenv(sleeps.ENV, "0.0")
    journal = EventLog(str(tmp_path / "events.jsonl"))
    ck = _ck(tmp_path, journal)
    try:
        for s in (1, 2, 3):
            ck.save(s, {"w": np.arange(4, dtype=np.float32) * s}, {},
                    force=True)
        ck.demote_newer_than(1, reason="drift verdict")
        faults.activate("io_write.ckpt@1=enospc")
        ck.save(4, {"w": np.arange(4, dtype=np.float32) * 4}, {},
                force=True)
    finally:
        faults.clear()
        ck.close()
    events = read_events(str(tmp_path / "events.jsonl"))
    gc = [e for e in events
          if (e.get("event") or e.get("kind")) == "ckpt_emergency_gc"]
    assert gc and sorted(gc[0]["steps"]) == [2, 3]
    # The demoted generations' bytes are actually gone...
    for s in (2, 3):
        assert not os.path.isdir(str(tmp_path / "ck" / str(s)))
    # ...and the SAME commit retried through.
    follower = ChainFollower(str(tmp_path / "ck"))
    try:
        assert follower.last_good_step() == 4
        restored = follower.restore(
            {"w": np.zeros(4, np.float32)}, {})
        assert int(restored["step"]) == 4
    finally:
        follower.close()


def test_exhausted_retries_raise_loud_checkpoint_io_error(
        tmp_path, monkeypatch):
    monkeypatch.setenv(sleeps.ENV, "0.0")
    ck = _ck(tmp_path)
    try:
        faults.activate("io_write.ckpt@1-8=eio")
        with pytest.raises(CheckpointIOError):
            ck.save(1, {"w": np.arange(4, dtype=np.float32)}, {},
                    force=True)
    finally:
        faults.clear()
        ck.close()


# ------------------------------------ the embed cold-store write-back


def test_cold_store_write_back_round_trips_dense_and_lazy(tmp_path):
    planes = {"emb": np.arange(32, dtype=np.float32).reshape(8, 4)}
    cs = ColdStore.dense(planes, bucket_rows=2)
    d = str(tmp_path / "cold")
    os.makedirs(d)
    man = cs.write_back(d)
    assert man["lazy"] is False
    cs2 = ColdStore.read_back(d)
    np.testing.assert_array_equal(cs2.dense_plane("emb"),
                                  planes["emb"])
    # Lazy: only touched buckets persist; restore needs reattachment.
    def init_fn(plane, bucket, shape, dtype):
        return np.full(shape, bucket, dtype)

    lz = ColdStore.lazy({"emb": ((4,), np.float32)}, bucket_rows=2,
                        n_rows=8, init_fn=init_fn)
    lz.read_bucket("emb", 1)
    d2 = str(tmp_path / "cold_lazy")
    os.makedirs(d2)
    man2 = lz.write_back(d2)
    assert man2["lazy"] is True
    lz2 = ColdStore.read_back(d2)
    assert lz2.is_lazy
    np.testing.assert_array_equal(lz2.read_bucket("emb", 1),
                                  np.full((2, 4), 1, np.float32))
    # An untouched bucket needs the deterministic init back first.
    with pytest.raises(RuntimeError):
        lz2.read_bucket("emb", 3)
    lz2.reattach_init(init_fn)
    np.testing.assert_array_equal(lz2.read_bucket("emb", 3),
                                  np.full((2, 4), 3, np.float32))


def test_cold_store_manifest_last_commit_and_walk_back(tmp_path):
    planes = {"emb": np.arange(32, dtype=np.float32).reshape(8, 4)}
    cs = ColdStore.dense(planes, bucket_rows=2)
    d = str(tmp_path / "torn")
    os.makedirs(d)
    # ENOSPC mid write-back: fail-loud, and the manifest (published
    # LAST) never appears — a torn write-back is not a restorable one.
    faults.activate("io_write.embed@1=enospc")
    with pytest.raises(OSError):
        cs.write_back(d)
    faults.clear()
    assert not os.path.exists(os.path.join(d, "cold_manifest.json"))
    assert ColdStore.read_back(d) is None
    # A short read of a published store's manifest walks back too.
    d2 = str(tmp_path / "ok")
    os.makedirs(d2)
    cs.write_back(d2)
    faults.activate("io_read.embed@1=torn_write:9")
    assert ColdStore.read_back(d2) is None
    faults.clear()
    assert ColdStore.read_back(d2) is not None


# ------------------------------- the artifacts-only disk auditor


def test_audit_disk_flags_each_broken_invariant():
    assert audit_disk(committed_steps=[1, 4], tombstoned_steps=[2, 3],
                      last_good_step=4, restored_step=4,
                      expected_surviving={1, 4},
                      io_failures={"total": 0},
                      spool_seqs=[1, 2, 9]) == []
    v = audit_disk(committed_steps=[1, 2], last_good_step=None,
                   restored_step=1)
    assert any(x["invariant"] == "last_good_loadable" for x in v)
    assert any(x["invariant"] == "chain_never_broken" for x in v)
    v = audit_disk(committed_steps=[1, 2], tombstoned_steps=[2],
                   last_good_step=2, restored_step=1)
    assert any("tombstone" in x["detail"] for x in v)
    v = audit_disk(committed_steps=[1, 2, 3], tombstoned_steps=[3],
                   last_good_step=1, restored_step=1,
                   expected_surviving={1})
    assert any(x["invariant"] == "demotion_atomic"
               and "no tombstone" in x["detail"] for x in v)
    # Swallowed best-effort failures demand the gauge; fail-loud
    # failures alone do not.
    v = audit_disk(io_failures={"total": 3, "best_effort": 3},
                   degraded_gauge=None)
    assert any(x["invariant"] == "degradation_signaled" for x in v)
    assert audit_disk(io_failures={"total": 3, "ckpt": 3},
                      degraded_gauge=None) == []
    v = audit_disk(params_match=False)
    assert any(x["invariant"] == "obs_degraded_harmless" for x in v)
    v = audit_disk(spool_seqs=[1, 2, 2])
    assert any(x["invariant"] == "spool_seq_continuous" for x in v)


# ----------------------------------- seeded disk schedules + campaign


def test_disk_schedule_is_pure_and_covers_scenarios():
    seen = set()
    for seed in range(10):
        s = chaos.disk_schedule(seed)
        assert s == chaos.disk_schedule(seed)
        s.validate()
        seen.add(s.scenario)
    assert seen == set(chaos._DISK_SCENARIOS)
    # Scenario semantics: the named acceptance scenarios target the
    # path classes their invariants are about.
    enospc = chaos.disk_schedule(0)
    assert enospc.scenario == "enospc_ckpt_commit"
    assert "io_write.ckpt" in enospc.plan and "enospc" in enospc.plan
    assert enospc.demote_cut is not None
    torn = chaos.disk_schedule(1)
    assert torn.scenario == "torn_rename_demote"
    assert "io_rename.ckpt" in torn.plan and torn.demote_armed
    slow = chaos.disk_schedule(2)
    assert "io_fsync.ckpt" in slow.plan and "slow_ms" in slow.plan
    for seed in (3, 4):
        s = chaos.disk_schedule(seed)
        assert "io_write.obs" in s.plan and s.arm_at_start


def test_obs_degraded_run_is_byte_identical_to_golden(
        tmp_path, monkeypatch):
    """THE best-effort-tier proof (ISSUE 20 acceptance): with EVERY
    ``io_write.obs`` failing, the final params are byte-identical to
    the golden run's, the failures are counted, and the degradation
    gauge is raised — telemetry loss is visible, training bytes are
    untouched."""
    monkeypatch.setenv(sleeps.ENV, "0.0")
    golden = chaos.run_disk_schedule(
        chaos.DiskSchedule(-1, "golden", (), setup_saves=4,
                           final_saves=0),
        str(tmp_path / "golden"))
    assert golden["verdict"] == "green", golden["violations"]
    sched = chaos.DiskSchedule(
        -2, "readonly_obs_flip", ("io_write.obs@1-512=eio",),
        setup_saves=4, final_saves=0, arm_at_start=True)
    entry = chaos.run_disk_schedule(
        sched, str(tmp_path / "degraded"),
        golden_sums=golden["params_sums"])
    assert entry["verdict"] == "green", entry["violations"]
    assert entry["params_sums"] == golden["params_sums"]
    assert entry["io_failures"]["obs"] > 0
    assert entry["io_failures"]["best_effort"] > 0
    assert obs.counter("io.write_failed_total").value > 0
    assert obs.registry().snapshot()["gauges"].get(
        "obs/io_degraded") == 1.0


def test_disk_campaign_tier1_seeds_green(tmp_path, monkeypatch):
    """The storage half of the chaos campaign (ISSUE 20 acceptance):
    golden + every tier-1 seed, >= 4 distinct scenarios including
    ENOSPC-mid-commit and torn-rename-mid-demotion, every entry
    graded green by ``audit_disk`` from artifacts alone."""
    monkeypatch.setenv(sleeps.ENV, "0.25")
    entries = chaos.run_disk_campaign(
        base_dir=str(tmp_path), include_kill_drill=False)
    assert [e["scenario"] for e in entries[:1]] == ["golden"]
    assert [e["seed"] for e in entries[1:]] == list(
        chaos.DISK_TIER1_SEEDS)
    for e in entries:
        assert e["verdict"] == "green", (e["scenario"],
                                         e["violations"])
    scenarios = {e["scenario"] for e in entries[1:]}
    assert len(scenarios) >= 4
    assert {"enospc_ckpt_commit", "torn_rename_demote"} <= scenarios
    # The designed-loud variant (ENOSPC with the disk full of live
    # data) is graded green BECAUSE it failed loud, when drawn.
    for e in entries[1:]:
        assert e["outcome"] == e["expects"]
    # The torn-rename drill really raced a follower through the
    # demotion window.
    torn = next(e for e in entries
                if e["scenario"] == "torn_rename_demote")
    assert torn["follower_samples"]


def test_gc_kill_drill_recovers_to_loadable_last_good(tmp_path):
    """The SIGKILL-during-emergency-GC drill (ISSUE 20 acceptance):
    killed between the journaled GC intent and the deletions, every
    reader still lands on a loadable last_good, and a clean re-run
    commits the next step."""
    res = chaos.run_gc_kill_drill(str(tmp_path / "gc"), exit_rc=29)
    assert res["rcs"] == [29, 0]
    assert res["violations"] == [], res["violations"]
