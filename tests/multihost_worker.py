"""Worker script for the 2-process multi-host smoke test.

The Spark `local-cluster[2,1,1024]` idiom (SURVEY.md §4): a real
multi-process pseudo-cluster with real serialization — here two JAX
processes, `jax.distributed.initialize`, 2 fake CPU devices each, one
global `(data,)` mesh, per-host input shards, and a psum'd dp train step.
Run by tests/test_multihost.py; prints the final loss for cross-host
agreement checks.
"""

import os
import sys


def main() -> int:
    process_id = int(sys.argv[1])
    num_processes = int(sys.argv[2])
    port = sys.argv[3]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    # ---- Phase 0: the CLI `--distributed` path IS this worker's
    # distributed initializer — a tiny injected field_sparse config
    # trains end-to-end through ``cli.main`` (round 5): argument
    # plumbing, jax.distributed.initialize with the explicit triple,
    # the multi-process placement machinery, the sharded training loop,
    # and the cross-process eval, all through the real user entry
    # point. The remaining phases then reuse the initialized runtime.
    from fm_spark_tpu import cli, configs as configs_lib
    from fm_spark_tpu.configs import RunConfig

    configs_lib.CONFIGS["_mh_smoke"] = RunConfig(
        name="_mh_smoke",
        description="2-process CLI smoke config (injected by "
                    "multihost_worker; not a registered benchmark)",
        model="field_fm", dataset="synthetic", rank=4, num_fields=4,
        bucket=64, strategy="field_sparse", num_steps=4, batch_size=32,
        learning_rate=0.1, lr_schedule="constant",
    )
    rc = cli.main([
        "train", "--config", "_mh_smoke", "--synthetic", "256",
        "--distributed", "--coordinator", f"127.0.0.1:{port}",
        "--num-processes", str(num_processes),
        "--process-id", str(process_id),
    ])
    assert rc == 0, f"phase-0 CLI train rc={rc}"
    assert jax.process_count() == num_processes
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from fm_spark_tpu import models
    from fm_spark_tpu.parallel import make_parallel_train_step, param_specs
    from fm_spark_tpu.train import TrainConfig, make_optimizer

    devices = np.asarray(jax.devices()).reshape(-1, 1)  # [4] global
    mesh = Mesh(devices, ("data", "feat"))

    def make_global(arr, msh, spec_p):
        """Global array from per-process-identical host data — each
        process serves only the shard indices it owns (the multi-host
        input idiom; default-arg capture pins the array per call)."""
        a = np.asarray(arr)
        return jax.make_array_from_callback(
            a.shape, NamedSharding(msh, spec_p), lambda idx, a=a: a[idx]
        )

    num_features, nnz, b_global = 128, 4, 64
    spec = models.FMSpec(num_features=num_features, rank=4, init_std=0.05)
    config = TrainConfig(learning_rate=0.3, optimizer="sgd")
    step = make_parallel_train_step(spec, config, mesh, "dp")

    # Replicated params: same init everywhere.
    params = spec.init(jax.random.key(0))
    pspecs = param_specs(spec, "dp")
    params = jax.tree_util.tree_map(
        lambda x, s: make_global(x, mesh, s), params, pspecs
    )
    opt_state = make_optimizer(config).init(params)

    from fm_spark_tpu.data import synthetic_ctr

    # Planted-FM data, deterministic on every host; each host feeds only
    # its addressable shard (the multi-host input idiom).
    all_ids, all_vals, all_labels = synthetic_ctr(
        b_global * 10, num_features, nnz, seed=0
    )
    losses = []
    for i in range(10):
        sl = slice(i * b_global, (i + 1) * b_global)
        ids, vals, labels = all_ids[sl], all_vals[sl], all_labels[sl]
        weights = np.ones((b_global,), np.float32)
        batch = [
            make_global(arr, mesh, spec_p)
            for arr, spec_p in zip(
                (ids, vals, labels, weights),
                (P("data", None), P("data", None), P("data"), P("data")),
            )
        ]
        params, opt_state, m = step(params, opt_state, *batch)
        losses.append(float(m["loss"]))

    assert all(np.isfinite(losses)), losses
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses

    # ---- Phase 2: the field-sharded fused step across process
    # boundaries — all_to_all batch re-shard + psum of partial sums with
    # real cross-process collectives (the CTR fast path's multi-chip
    # layout, parallel/field_step.py).
    from fm_spark_tpu.parallel import (
        make_field_mesh,
        make_field_sharded_sgd_step,
        field_batch_specs,
        field_param_specs,
        pad_field_batch,
        stack_field_params,
    )

    F, bucket = 6, 32
    fspec = models.FieldFMSpec(
        num_features=F * bucket, rank=4, num_fields=F, bucket=bucket,
        init_std=0.05,
    )
    fmesh = make_field_mesh(len(jax.devices()))
    fconfig = TrainConfig(learning_rate=0.3, optimizer="sgd",
                          sparse_update="dedup")
    fstep = make_field_sharded_sgd_step(fspec, fconfig, fmesh)
    stacked = stack_field_params(fspec, fspec.init(jax.random.key(1)),
                                 fmesh.shape["feat"])
    pspecs2 = field_param_specs(fmesh)
    fparams = {
        k: make_global(v, fmesh, pspecs2[k]) for k, v in stacked.items()
    }
    fids, fvals, flabels = synthetic_ctr(b_global * 10, F * bucket, F,
                                         seed=2)
    fids = fids - (np.arange(F) * bucket)[None, :].astype(fids.dtype)
    flosses = []
    for i in range(10):
        sl = slice(i * b_global, (i + 1) * b_global)
        fb = pad_field_batch(
            (fids[sl], fvals[sl], flabels[sl],
             np.ones((b_global,), np.float32)),
            F, fmesh.shape["feat"],
        )
        gb = [
            make_global(a, fmesh, sp)
            for a, sp in zip(fb, field_batch_specs(fmesh))
        ]
        fparams, fl = fstep(fparams, jnp.int32(i), *gb)
        flosses.append(float(fl))
    assert all(np.isfinite(flosses)), flosses
    assert np.mean(flosses[-3:]) < np.mean(flosses[:3]), flosses

    # ---- Phase 3: multi-host PACKED-DATA ingestion — each process
    # streams its own row slice of a packed dir and feeds only its local
    # slice of the global batch (shard_field_batch_local), the
    # cli/cmd_train multi-host path. Data is synthesized deterministically
    # so both processes hold identical dirs without coordination.
    import tempfile

    from fm_spark_tpu.data import PackedBatches, PackedDataset, criteo
    from fm_spark_tpu.parallel import shard_field_batch_local
    from fm_spark_tpu.cli import StreamingBatches, _field_local

    Fp, bucketp = 39, 64
    with tempfile.TemporaryDirectory() as td:
        tsv = os.path.join(td, "day.tsv")
        criteo.synthesize_tsv(tsv, 512, seed=9)
        packed = os.path.join(td, "packed")
        criteo.preprocess([tsv], packed, bucketp)
        ds = PackedDataset(packed)
        per = len(ds) // num_processes
        local_bs = 64 // num_processes
        src = StreamingBatches(
            PackedBatches(ds, local_bs, seed=0,
                          row_range=(process_id * per,
                                     (process_id + 1) * per)),
            bucket=bucketp,
        )
        pspec3 = models.FieldFMSpec(
            num_features=Fp * bucketp, rank=4, num_fields=Fp,
            bucket=bucketp, init_std=0.05,
        )
        pmesh = make_field_mesh(len(jax.devices()))
        pstep = make_field_sharded_sgd_step(
            pspec3, TrainConfig(learning_rate=0.3, optimizer="sgd"), pmesh
        )
        pparams = {
            k: make_global(v, pmesh, field_param_specs(pmesh)[k])
            for k, v in stack_field_params(
                pspec3, pspec3.init(jax.random.key(3)),
                pmesh.shape["feat"],
            ).items()
        }
        plosses = []
        for i in range(6):
            b = pad_field_batch(src.next_batch(), Fp,
                                pmesh.shape["feat"])
            gb = shard_field_batch_local(b, pmesh)
            pparams, pl = pstep(pparams, jnp.int32(i), *gb)
            plosses.append(float(pl))
        assert all(np.isfinite(plosses)), plosses

        # Multi-host on-mesh eval via the local-placement path.
        from fm_spark_tpu.parallel import evaluate_field_sharded

        eids, evals_, elabels = ds.slice(np.s_[0:128])
        eids = _field_local(eids, bucketp)
        em = evaluate_field_sharded(
            pspec3, pmesh, pparams,
            [(eids, evals_, elabels.astype(np.float32),
              np.ones((128,), np.float32))],
        )
        assert float(em["count"]) == 128.0, em

        # Cross-process canonical gather (cli to_canonical's multi-host
        # path): full global tables on every host, hosts agree bitwise
        # (the digest rides the parent's string comparison).
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(pparams["vw"],
                                                     tiled=True)
        assert gathered.shape == (40, bucketp, 5), gathered.shape
        digest = round(float(np.sum(np.abs(gathered))), 4)

    # ---- Phase 4: the DEVICE-compact field-sharded step across process
    # boundaries — the compact lever's scale-out form (no host aux can
    # exist here: each process holds only its row slice). Reuses the
    # phase-2 model/mesh; the aux is built in-step from each chip's
    # owned columns after the cross-process all_to_all.
    dconfig = TrainConfig(learning_rate=0.3, optimizer="sgd",
                          sparse_update="dedup", compact_device=True,
                          compact_cap=b_global)
    dstep = make_field_sharded_sgd_step(fspec, dconfig, fmesh)
    dparams = {
        k: make_global(v, fmesh, pspecs2[k])
        for k, v in stack_field_params(
            fspec, fspec.init(jax.random.key(1)), fmesh.shape["feat"]
        ).items()
    }
    dlosses = []
    for i in range(10):
        sl = slice(i * b_global, (i + 1) * b_global)
        fb = pad_field_batch(
            (fids[sl], fvals[sl], flabels[sl],
             np.ones((b_global,), np.float32)),
            F, fmesh.shape["feat"],
        )
        gb = [
            make_global(a, fmesh, sp)
            for a, sp in zip(fb, field_batch_specs(fmesh))
        ]
        dparams, dl = dstep(dparams, jnp.int32(i), *gb)
        dlosses.append(float(dl))
    assert all(np.isfinite(dlosses)), dlosses
    # Same model/init/data as phase 2 → identical math through the
    # compact path (dedup fp32 = exact up to cumsum reassociation).
    np.testing.assert_allclose(dlosses, flosses, rtol=1e-5)

    # ---- Phase 5: the field-sharded FFM step across process
    # boundaries — the sel all_to_all (transposed cross-field blocks)
    # with real cross-process collectives (config 4's multi-chip path).
    from fm_spark_tpu.parallel import make_field_ffm_sharded_step

    ffspec = models.FieldFFMSpec(
        num_features=F * bucket, rank=3, num_fields=F, bucket=bucket,
        init_std=0.05,
    )
    ffstep = make_field_ffm_sharded_step(
        ffspec, TrainConfig(learning_rate=0.3, optimizer="sgd",
                            sparse_update="dedup"), fmesh
    )
    ffparams = {
        k: make_global(v, fmesh, pspecs2[k])
        for k, v in stack_field_params(
            ffspec, ffspec.init(jax.random.key(5)), fmesh.shape["feat"]
        ).items()
    }
    fflosses = []
    for i in range(6):
        sl = slice(i * b_global, (i + 1) * b_global)
        fb = pad_field_batch(
            (fids[sl], fvals[sl], flabels[sl],
             np.ones((b_global,), np.float32)),
            F, fmesh.shape["feat"],
        )
        gb = [
            make_global(a, fmesh, sp)
            for a, sp in zip(fb, field_batch_specs(fmesh))
        ]
        ffparams, ffl = ffstep(ffparams, jnp.int32(i), *gb)
        fflosses.append(float(ffl))
    assert all(np.isfinite(fflosses)), fflosses
    assert np.mean(fflosses[-3:]) < np.mean(fflosses[:3]), fflosses

    # ---- Phase 6: the round-4 scale-out levers across process
    # boundaries. (a) score_sharded with an fp32 wire is EXACT — same
    # model/init/data as phase 2, so the loss stream must reproduce
    # phase 2's up to scalar reassociation (the per-example dscores are
    # identical; the dscores all_gather and the loss psum are the only
    # new collectives). (b) the full lever stack (score_sharded + bf16
    # wire) trains finite and downhill.
    for tag, lcfg, check_exact in (
        ("ss_fp32", TrainConfig(learning_rate=0.3, optimizer="sgd",
                                sparse_update="dedup",
                                score_sharded=True), True),
        ("ss_bf16w", TrainConfig(learning_rate=0.3, optimizer="sgd",
                                 sparse_update="dedup",
                                 score_sharded=True,
                                 collective_dtype="bfloat16"), False),
    ):
        lstep = make_field_sharded_sgd_step(fspec, lcfg, fmesh)
        lparams = {
            k: make_global(v, fmesh, pspecs2[k])
            for k, v in stack_field_params(
                fspec, fspec.init(jax.random.key(1)),
                fmesh.shape["feat"]
            ).items()
        }
        llosses = []
        for i in range(10):
            sl = slice(i * b_global, (i + 1) * b_global)
            fb = pad_field_batch(
                (fids[sl], fvals[sl], flabels[sl],
                 np.ones((b_global,), np.float32)),
                F, fmesh.shape["feat"],
            )
            gb = [
                make_global(a, fmesh, sp)
                for a, sp in zip(fb, field_batch_specs(fmesh))
            ]
            lparams, ll = lstep(lparams, jnp.int32(i), *gb)
            llosses.append(float(ll))
        assert all(np.isfinite(llosses)), (tag, llosses)
        if check_exact:
            np.testing.assert_allclose(llosses, flosses, rtol=1e-5,
                                       err_msg=tag)
        else:
            assert np.mean(llosses[-3:]) < np.mean(llosses[:3]), (
                tag, llosses)

    # ---- Phase 7: the SHARDED steps-per-call roll across process
    # boundaries — fori inside the shard_map with cross-process
    # collectives repeating per iteration, batches assembled from
    # per-process stacked row slices (shard_field_batch_stacked_local).
    # Same model/init/data as phase 2, plain config → the roll's final
    # loss must reproduce the per-step stream's.
    from fm_spark_tpu.parallel import (
        make_field_sharded_multistep,
        shard_field_batch_stacked_local,
    )

    rcfg = TrainConfig(learning_rate=0.3, optimizer="sgd",
                       sparse_update="dedup")
    rstep = make_field_sharded_multistep(fspec, rcfg, fmesh, 5)
    rparams = {
        k: make_global(v, fmesh, pspecs2[k])
        for k, v in stack_field_params(
            fspec, fspec.init(jax.random.key(1)), fmesh.shape["feat"]
        ).items()
    }
    rlosses = []
    for call in range(2):
        stacked = []
        for i in range(call * 5, call * 5 + 5):
            sl = slice(i * b_global, (i + 1) * b_global)
            stacked.append(pad_field_batch(
                (fids[sl], fvals[sl], flabels[sl],
                 np.ones((b_global,), np.float32)),
                F, fmesh.shape["feat"],
            ))
        # Per-process local row slices of each stacked step.
        per = b_global // num_processes
        lo, hi = process_id * per, (process_id + 1) * per
        local = tuple(
            np.stack([b[i][lo:hi] for b in stacked], axis=0)
            for i in range(4)
        )
        gb = shard_field_batch_stacked_local(local, fmesh)
        rparams, rl = rstep(rparams, jnp.int32(call * 5), jnp.int32(5),
                            *gb)
        rlosses.append(float(rl))
    assert all(np.isfinite(rlosses)), rlosses
    # The roll's last loss = the per-step stream's loss at step 10
    # (phase 2 ran the same 10 batches on the same init).
    np.testing.assert_allclose(rlosses[-1], flosses[-1], rtol=1e-5)

    # ---- Phase 8 (round 5): the field-sharded DeepFM step across
    # process boundaries, replicated head vs the example-sharded head
    # (deep_sharded). With an fp32 wire the two heads compute the same
    # scores (the a2a re-route only re-shards; the deep-score gather is
    # full precision), so the loss streams must agree to reassociation
    # tolerance — run on real cross-process collectives.
    from fm_spark_tpu.parallel.deepfm_step import (
        field_deepfm_param_specs,
        make_field_deepfm_sharded_step,
        stack_field_deepfm_params,
    )

    dfspec = models.FieldDeepFMSpec(
        num_features=F * bucket, rank=3, num_fields=F, bucket=bucket,
        mlp_dims=(8, 8), init_std=0.05,
    )
    dlosses_by_flag = {}
    for flag in (False, True):
        dcfg2 = TrainConfig(learning_rate=0.05, optimizer="adam",
                            deep_sharded=flag)
        dstep2 = make_field_deepfm_sharded_step(dfspec, dcfg2, fmesh)
        dspecs = field_deepfm_param_specs(dfspec, fmesh)
        stacked0 = stack_field_deepfm_params(
            dfspec, dfspec.init(jax.random.key(11)), fmesh.shape["feat"]
        )
        dparams2 = {
            "w0": make_global(stacked0["w0"], fmesh, dspecs["w0"]),
            "vw": make_global(stacked0["vw"], fmesh, dspecs["vw"]),
            "mlp": jax.tree_util.tree_map(
                lambda x, s: make_global(x, fmesh, s),
                stacked0["mlp"], dspecs["mlp"],
            ),
        }
        dopt2 = dstep2.init_opt_state(dparams2)
        ds_losses = []
        for i in range(6):
            sl = slice(i * b_global, (i + 1) * b_global)
            fb = pad_field_batch(
                (fids[sl], fvals[sl], flabels[sl],
                 np.ones((b_global,), np.float32)),
                F, fmesh.shape["feat"],
            )
            gb = [
                make_global(a, fmesh, sp)
                for a, sp in zip(fb, field_batch_specs(fmesh))
            ]
            dparams2, dopt2, dl2 = dstep2(dparams2, dopt2,
                                          jnp.int32(i), *gb)
            ds_losses.append(float(dl2))
        assert all(np.isfinite(ds_losses)), (flag, ds_losses)
        dlosses_by_flag[flag] = ds_losses
    np.testing.assert_allclose(dlosses_by_flag[True],
                               dlosses_by_flag[False], rtol=1e-5)

    print(f"MULTIHOST_OK process={process_id} "
          f"losses={losses}+{flosses}+{plosses}+{dlosses}+{fflosses}"
          f"+{llosses}+{rlosses}+{dlosses_by_flag[True]}+digest={digest}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
