"""Worker script for the 2-process multi-host smoke test.

The Spark `local-cluster[2,1,1024]` idiom (SURVEY.md §4): a real
multi-process pseudo-cluster with real serialization — here two JAX
processes, `jax.distributed.initialize`, 2 fake CPU devices each, one
global `(data,)` mesh, per-host input shards, and a psum'd dp train step.
Run by tests/test_multihost.py; prints the final loss for cross-host
agreement checks.
"""

import os
import sys


def main() -> int:
    process_id = int(sys.argv[1])
    num_processes = int(sys.argv[2])
    port = sys.argv[3]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.process_count() == num_processes
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from fm_spark_tpu import models
    from fm_spark_tpu.parallel import make_parallel_train_step, param_specs
    from fm_spark_tpu.train import TrainConfig, make_optimizer

    devices = np.asarray(jax.devices()).reshape(-1, 1)  # [4] global
    mesh = Mesh(devices, ("data", "feat"))

    num_features, nnz, b_global = 128, 4, 64
    spec = models.FMSpec(num_features=num_features, rank=4, init_std=0.05)
    config = TrainConfig(learning_rate=0.3, optimizer="sgd")
    step = make_parallel_train_step(spec, config, mesh, "dp")

    # Replicated params: same init everywhere.
    params = spec.init(jax.random.key(0))
    pspecs = param_specs(spec, "dp")
    params = jax.tree_util.tree_map(
        lambda x, s: jax.make_array_from_callback(
            x.shape, NamedSharding(mesh, s), lambda idx: np.asarray(x)[idx]
        ),
        params, pspecs,
    )
    opt_state = make_optimizer(config).init(params)

    from fm_spark_tpu.data import synthetic_ctr

    # Planted-FM data, deterministic on every host; each host feeds only
    # its addressable shard (the multi-host input idiom).
    all_ids, all_vals, all_labels = synthetic_ctr(
        b_global * 10, num_features, nnz, seed=0
    )
    losses = []
    for i in range(10):
        sl = slice(i * b_global, (i + 1) * b_global)
        ids, vals, labels = all_ids[sl], all_vals[sl], all_labels[sl]
        weights = np.ones((b_global,), np.float32)
        batch = []
        for arr, spec_p in zip(
            (ids, vals, labels, weights),
            (P("data", None), P("data", None), P("data"), P("data")),
        ):
            sharding = NamedSharding(mesh, spec_p)
            batch.append(
                jax.make_array_from_callback(
                    arr.shape, sharding, lambda idx, a=arr: a[idx]
                )
            )
        params, opt_state, m = step(params, opt_state, *batch)
        losses.append(float(m["loss"]))

    assert all(np.isfinite(losses)), losses
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses
    print(f"MULTIHOST_OK process={process_id} losses={losses}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
