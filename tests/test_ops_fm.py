"""Property tests for the FM kernel (SURVEY.md §4 golden-value idiom):

1. O(k·nnz) identity vs brute-force O(n²) pairwise sum on random inputs.
2. jax.grad of the kernel vs numerical finite differences.
3. Partial-sum (row-sharded) decomposition vs the unsharded forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu.ops import fm as fm_ops
from fm_spark_tpu.ops import losses


def _random_problem(rng, b=16, n=50, k=8, nnz=5, pad=False):
    w0 = jnp.float32(rng.normal())
    w = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(n, k)) * 0.3, jnp.float32)
    # Distinct ids per example (matches one-hot: a feature appears once).
    ids = np.stack([rng.choice(n, size=nnz, replace=False) for _ in range(b)])
    vals = rng.normal(size=(b, nnz)).astype(np.float32)
    if pad:
        vals[:, -1] = 0.0  # padded slot must contribute nothing
    return w0, w, v, jnp.asarray(ids, jnp.int32), jnp.asarray(vals)


def _densify(ids, vals, n):
    b, nnz = ids.shape
    x = np.zeros((b, n), np.float32)
    for i in range(b):
        for j in range(nnz):
            x[i, ids[i, j]] += vals[i, j]
    return jnp.asarray(x)


@pytest.mark.parametrize("pad", [False, True])
def test_fm_scores_vs_bruteforce(rng, pad):
    w0, w, v, ids, vals = _random_problem(rng, pad=pad)
    fast = fm_ops.fm_scores(w0, w, v, ids, vals)
    dense = fm_ops.fm_scores_dense(w0, w, v, _densify(ids, vals, w.shape[0]))
    # fp32 kernel vs float64 oracle: the s²−Σv²x² identity cancels, so
    # tolerance is set by fp32 rounding of the intermediate magnitudes.
    np.testing.assert_allclose(fast, dense, rtol=1e-3, atol=5e-3)


def test_fm_grad_vs_finite_differences(rng):
    w0, w, v, ids, vals = _random_problem(rng, b=4, n=20, k=3, nnz=4)
    labels = jnp.asarray(rng.integers(0, 2, size=(4,)), jnp.float32)

    def loss(params):
        s = fm_ops.fm_scores(params["w0"], params["w"], params["v"], ids, vals)
        return jnp.mean(losses.logistic_loss(s, labels))

    params = {"w0": w0, "w": w, "v": v}
    grads = jax.grad(loss)(params)

    # eps large enough that fp32 rounding of the loss (~1e-7 abs) divided by
    # 2·eps stays well under tolerance; truncation error is O(eps²) ≈ 1e-5.
    eps = 1e-2
    # Spot-check a handful of coordinates of each param against central diffs.
    flat_v = np.asarray(v)
    touched = np.unique(np.asarray(ids))[:3]
    for i in touched:
        for f in range(3):
            vp = flat_v.copy(); vp[i, f] += eps
            vm = flat_v.copy(); vm[i, f] -= eps
            num = (
                loss({"w0": w0, "w": w, "v": jnp.asarray(vp)})
                - loss({"w0": w0, "w": w, "v": jnp.asarray(vm)})
            ) / (2 * eps)
            np.testing.assert_allclose(grads["v"][i, f], num, rtol=2e-2, atol=1e-4)
    num_w0 = (
        loss({"w0": w0 + eps, "w": w, "v": v})
        - loss({"w0": w0 - eps, "w": w, "v": v})
    ) / (2 * eps)
    np.testing.assert_allclose(grads["w0"], num_w0, rtol=1e-3, atol=1e-5)


def test_partial_terms_reconstruct_full_forward(rng):
    """Masked shard partials summed over shards == unsharded forward."""
    w0, w, v, ids, vals = _random_problem(rng, b=8, n=48, k=4, nnz=6)
    n = w.shape[0]
    shards = 4
    rows_per = n // shards
    lin = jnp.zeros((8,))
    s = jnp.zeros((8, 4))
    sq = jnp.zeros((8,))
    for si in range(shards):
        lo = si * rows_per
        lp, sp, qp = fm_ops.fm_partial_terms(
            w[lo : lo + rows_per], v[lo : lo + rows_per], ids, vals, lo, rows_per
        )
        lin, s, sq = lin + lp, s + sp, sq + qp
    combined = fm_ops.fm_scores_from_partials(w0, lin, s, sq)
    full = fm_ops.fm_scores(w0, w, v, ids, vals)
    np.testing.assert_allclose(combined, full, rtol=1e-5, atol=1e-5)


def test_bf16_table_fp32_accum_close(rng):
    w0, w, v, ids, vals = _random_problem(rng, b=32, n=64, k=16, nnz=8)
    exact = fm_ops.fm_scores(w0, w, v, ids, vals)
    approx = fm_ops.fm_scores(
        w0, w.astype(jnp.bfloat16), v.astype(jnp.bfloat16), ids, vals
    )
    assert approx.dtype == jnp.float32
    np.testing.assert_allclose(exact, approx, rtol=0.05, atol=0.05)


def test_loss_fn_lookup():
    assert losses.loss_fn("logistic") is losses.logistic_loss
    assert losses.loss_fn("hinge") is losses.hinge_loss
    with pytest.raises(ValueError):
        losses.loss_fn("absolute")


def test_hinge_loss_values():
    s = jnp.asarray([2.0, 0.5, -3.0, 0.0])
    y = jnp.asarray([1.0, 0.0, 0.0, 1.0])
    # t = {+1, -1, -1, +1}; hinge = max(0, 1 - t*s)
    np.testing.assert_allclose(
        np.asarray(losses.hinge_loss(s, y)), [0.0, 1.5, 0.0, 1.0]
    )
    # Subgradient through jax.grad is finite and zero in the flat region.
    g = jax.grad(lambda x: jnp.sum(losses.hinge_loss(x, y)))(s)
    np.testing.assert_allclose(np.asarray(g), [0.0, 1.0, 0.0, -1.0])


def test_logistic_loss_matches_stable_bce(rng):
    # Moderate logits: the naive -y·log(p) form is accurate here, while at
    # |s| ≳ 17 it saturates in fp32 — exactly why we use the stable form.
    s = jnp.asarray(rng.normal(size=(100,)) * 3, jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(100,)), jnp.float32)
    ours = losses.logistic_loss(s, y)
    p = jax.nn.sigmoid(s)
    ref = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
    np.testing.assert_allclose(ours, ref, rtol=1e-3, atol=1e-3)
    # And the stable form stays finite where the naive one wouldn't.
    extreme = losses.logistic_loss(jnp.asarray([80.0, -80.0]), jnp.asarray([0.0, 1.0]))
    assert bool(jnp.all(jnp.isfinite(extreme)))
