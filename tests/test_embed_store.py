"""Unit tests for the two-tier embedding store (ISSUE 16).

ColdStore (dense + lazy materialization), TieredStore's residency
protocol (install / LRU-by-batch evict / dirty flush / version-checked
staging / capacity guard / pure merged view), and the BucketPrefetcher
producer contract. The trainer-level bitwise differentials live in
tests/test_embed_tier.py; this file holds the protocol to its contract
one transition at a time.
"""

import numpy as np
import pytest

from fm_spark_tpu.embed import BucketPrefetcher, ColdStore, TieredStore

R = 4          # bucket_rows
N_ROWS = 32    # 8 buckets
HOT = 2        # hot-tier capacity in buckets


def make_dense(n_rows=N_ROWS, bucket_rows=R):
    """One rank-2 plane ('v') + one rank-1 plane ('w') with
    row-identifying values, so any aliasing or misplaced install is
    visible in the bytes."""
    v = (np.arange(n_rows, dtype=np.float32)[:, None]
         + np.array([0.0, 0.25], np.float32)[None, :])
    w = np.arange(n_rows, dtype=np.float32) * 10.0
    return ColdStore.dense({"v": v.copy(), "w": w.copy()}, bucket_rows)


def gather_hot(store, hot, local_ids):
    return np.asarray(hot["v"])[np.asarray(local_ids).ravel()]


# --------------------------------------------------------------- ColdStore


def test_cold_dense_bucket_roundtrip_and_copy_semantics():
    cold = make_dense()
    blk = cold.read_bucket("v", 2)
    assert blk.shape == (R, 2)
    assert np.array_equal(blk[:, 0], np.arange(8, 12, dtype=np.float32))
    # read_bucket hands out a COPY: mutating it must not reach the store.
    blk[...] = -1.0
    assert cold.read_bucket("v", 2)[0, 0] == 8.0
    cold.write_bucket("v", 2, blk)
    assert np.all(cold.read_bucket("v", 2) == -1.0)
    # Other buckets untouched by the write.
    assert cold.read_bucket("v", 3)[0, 0] == 12.0


def test_cold_dense_rejects_ragged_axis():
    with pytest.raises(ValueError, match="must divide"):
        ColdStore.dense({"v": np.zeros((30, 2), np.float32)}, R)
    with pytest.raises(ValueError, match="rows"):
        ColdStore({"v": np.zeros((32, 2), np.float32),
                   "w": np.zeros((28,), np.float32)}, R, 32)


def test_cold_lazy_materializes_on_touch_deterministically():
    calls = []

    def init(plane, bucket, shape, dtype):
        calls.append((plane, bucket))
        return np.full(shape, float(bucket), dtype)

    cold = ColdStore.lazy({"v": ((2,), np.dtype(np.float32))}, R, N_ROWS,
                          init)
    assert cold.is_lazy
    assert cold.host_bytes() == 0 and cold.touched_buckets() == 0
    a = cold.read_bucket("v", 3)
    b = cold.read_bucket("v", 3)
    assert np.array_equal(a, b) and np.all(a == 3.0)
    # Materialized once; the second read served from the held block.
    assert calls == [("v", 3)]
    assert cold.touched_buckets() == 1
    assert cold.host_bytes() == R * 2 * 4
    # Host RSS tracks the TOUCHED set, and the full axis never exists:
    with pytest.raises(ValueError, match="lazy"):
        cold.dense_plane("v")


def test_cold_lazy_write_back_overrides_init():
    cold = ColdStore.lazy({"v": ((2,), np.dtype(np.float32))}, R, N_ROWS,
                          lambda p, b, s, d: np.zeros(s, d))
    cold.write_bucket("v", 5, np.full((R, 2), 7.0, np.float32))
    assert np.all(cold.read_bucket("v", 5) == 7.0)


# -------------------------------------------------------------- TieredStore


def test_begin_batch_installs_and_translates_ids():
    cold = make_dense()
    store = TieredStore(cold, HOT)
    hot = store.init_hot()
    ids = np.array([[0, 5], [6, 1]], np.int32)  # buckets {0, 1}
    local, hot = store.begin_batch(ids, hot)
    assert local.shape == ids.shape
    # The gathered hot rows are exactly the cold rows of the global ids.
    want = np.stack([cold.read_bucket("v", g // R)[g % R]
                     for g in ids.ravel()])
    assert np.array_equal(gather_hot(store, hot, local), want)
    st = store.stats()
    assert st["misses"] == 2 and st["evictions"] == 0
    assert st["stall_ms"] > 0.0  # blocking misses are timed, not hidden


def test_capacity_guard_names_the_working_set():
    store = TieredStore(make_dense(), HOT)
    hot = store.init_hot()
    ids = np.array([0, 4, 8], np.int64)  # 3 buckets > HOT=2
    with pytest.raises(ValueError, match="working set"):
        store.begin_batch(ids, hot)


def test_lru_eviction_flushes_dirty_rows_to_cold():
    import jax.numpy as jnp

    cold = make_dense()
    store = TieredStore(cold, HOT)
    hot = store.init_hot()
    _, hot = store.begin_batch(np.array([0, 4], np.int64), hot)  # b0, b1
    # Simulate the train step's write-through: hot rows change in place.
    hot = dict(hot, v=jnp.asarray(hot["v"]) + 100.0)
    # Touch bucket 1 again so bucket 0 is strictly least-recent.
    _, hot = store.begin_batch(np.array([4], np.int64), hot)
    before = cold.read_bucket("v", 0).copy()
    _, hot = store.begin_batch(np.array([8], np.int64), hot)  # forces evict
    st = store.stats()
    assert st["evictions"] == 1 and st["bytes_d2h"] > 0
    after = cold.read_bucket("v", 0)
    # Bucket 0 (the LRU victim) took the +100 write-back; bucket 1 is
    # still resident so its cold rows are untouched.
    assert np.array_equal(after, before + 100.0)
    assert cold.read_bucket("v", 1)[0, 0] == 4.0


def test_stage_then_install_is_a_staged_hit():
    cold = make_dense()
    store = TieredStore(cold, HOT)
    hot = store.init_hot()
    assert store.stage(np.array([8, 9], np.int64)) == 1  # bucket 2
    assert store.stage(np.array([8], np.int64)) == 0     # already staged
    local, hot = store.begin_batch(np.array([8], np.int64), hot)
    st = store.stats()
    assert st["staged_hits"] == 1 and st["misses"] == 0
    assert st["hit_rate"] == 1.0
    assert gather_hot(store, hot, local)[0, 0] == 8.0


def test_stage_skips_resident_buckets():
    store = TieredStore(make_dense(), HOT)
    hot = store.init_hot()
    _, hot = store.begin_batch(np.array([0], np.int64), hot)
    assert store.stage(np.array([0, 1, 2], np.int64)) == 0


def test_stale_staged_buffer_is_discarded_not_installed():
    cold = make_dense()
    store = TieredStore(cold, HOT)
    hot = store.init_hot()
    store.stage(np.array([12], np.int64))  # bucket 3 staged at version 0
    # Simulate the race the version check exists for: bucket 3's cold
    # block advances (an eviction flush elsewhere would bump it) after
    # the producer's read but before install.
    cold.write_bucket("v", 3, np.full((R, 2), -5.0, np.float32))
    with store._lock:
        store._version[3] = store._version.get(3, 0) + 1
    local, hot = store.begin_batch(np.array([12], np.int64), hot)
    st = store.stats()
    assert st["prefetch_stale"] == 1 and st["misses"] == 1
    # The fresh post-bump rows landed, not the stale staged buffer.
    assert gather_hot(store, hot, local)[0, 0] == -5.0


def test_eviction_invalidates_staged_buffer_by_construction():
    import jax.numpy as jnp

    cold = make_dense()
    store = TieredStore(cold, HOT)
    hot = store.init_hot()
    _, hot = store.begin_batch(np.array([0, 4], np.int64), hot)
    hot = dict(hot, v=jnp.asarray(hot["v"]) + 1.0)
    store.stage(np.array([8], np.int64))          # bucket 2 staged
    _, hot = store.begin_batch(np.array([8], np.int64), hot)  # evicts b0
    assert store.stats()["staged_hits"] == 1
    # Bucket 0 was flushed (version bumped); restaging reads the
    # post-flush rows, so the next install round-trips the update.
    store.stage(np.array([0], np.int64))
    local, hot = store.begin_batch(np.array([0], np.int64), hot)
    assert gather_hot(store, hot, local)[0, 0] == 1.0


def test_merged_planes_is_pure_and_residency_independent():
    import jax.numpy as jnp

    cold = make_dense()
    store = TieredStore(cold, HOT)
    hot = store.init_hot()
    _, hot = store.begin_batch(np.array([0, 4], np.int64), hot)
    hot = dict(hot, v=jnp.asarray(hot["v"]) + 100.0,
               w=jnp.asarray(hot["w"]) + 1.0)
    cold_v_before = cold.dense_plane("v").copy()
    merged = store.merged_planes(hot)
    # Dirty resident buckets come from hot; the rest from cold.
    assert np.array_equal(merged["v"][:R], cold_v_before[:R] + 100.0)
    assert np.array_equal(merged["v"][2 * R:], cold_v_before[2 * R:])
    assert np.array_equal(merged["w"][:R],
                          np.arange(R, dtype=np.float32) * 10.0 + 1.0)
    # PURE: the live cold arrays and the dirty mask are untouched, so a
    # checkpoint save never perturbs the protocol state.
    assert np.array_equal(cold.dense_plane("v"), cold_v_before)
    merged2 = store.merged_planes(hot)
    assert np.array_equal(merged["v"], merged2["v"])


def test_restore_cold_resets_residency_and_invalidates_staging():
    cold = make_dense()
    store = TieredStore(cold, HOT)
    hot = store.init_hot()
    _, hot = store.begin_batch(np.array([0, 4], np.int64), hot)
    store.stage(np.array([8], np.int64))
    new_v = np.full((N_ROWS, 2), 9.0, np.float32)
    new_w = np.full((N_ROWS,), 9.0, np.float32)
    store.restore_cold({"v": new_v, "w": new_w})
    hot = store.init_hot()
    local, hot = store.begin_batch(np.array([0, 8], np.int64), hot)
    # Both the formerly-resident and the formerly-staged bucket re-fault
    # from the RESTORED rows, never from pre-restore buffers.
    assert np.all(gather_hot(store, hot, local) == 9.0)


def test_tiered_store_rejects_zero_capacity():
    with pytest.raises(ValueError, match="hot_buckets"):
        TieredStore(make_dense(), 0)


# ---------------------------------------------------------- BucketPrefetcher


class _ListBatches:
    """Finite (ids, vals, labels, weights) source for prefetcher tests."""

    def __init__(self, batches):
        self._batches = batches

    def __iter__(self):
        return iter(self._batches)


def _batch(ids):
    ids = np.asarray(ids, np.int32)
    return (ids, np.ones_like(ids, np.float32),
            np.zeros(len(ids), np.float32), np.ones(len(ids), np.float32))


def test_prefetcher_yields_batches_in_order_and_stages_ahead():
    store = TieredStore(make_dense(), HOT)
    hot = store.init_hot()
    batches = [_batch([0, 1]), _batch([4, 5]), _batch([4, 0])]
    pf = BucketPrefetcher(_ListBatches(batches), store, depth=2)
    seen = []
    for b in pf:
        local, hot = store.begin_batch(b[0], hot)
        seen.append(b[0])
    pf.close()
    assert [tuple(s) for s in seen] == [(0, 1), (4, 5), (4, 0)]
    st = store.stats()
    # Every install was producer-staged: zero blocking misses.
    assert st["misses"] == 0 and st["staged_hits"] == 2
    assert st["hit_rate"] == 1.0


def test_prefetcher_reraises_producer_exception():
    class Boom(Exception):
        pass

    def gen():
        yield _batch([0])
        raise Boom("upstream died")

    store = TieredStore(make_dense(), HOT)
    pf = BucketPrefetcher(gen(), store, depth=2)
    it = iter(pf)
    next(it)
    with pytest.raises(Boom):
        next(it)
    pf.close()


def test_prefetcher_close_is_idempotent_and_unblocks_producer():
    def gen():
        i = 0
        while True:  # infinite upstream — close() must still return
            yield _batch([i % N_ROWS])
            i += 1

    store = TieredStore(make_dense(), HOT)
    pf = BucketPrefetcher(gen(), store, depth=2)
    next(iter(pf))
    pf.close()
    pf.close()
    assert not pf._thread.is_alive()


def test_prefetcher_rejects_zero_depth():
    with pytest.raises(ValueError, match="depth"):
        BucketPrefetcher(_ListBatches([]), TieredStore(make_dense(), HOT),
                         depth=0)
