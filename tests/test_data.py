"""Data subsystem: hashing determinism, native==python, packed roundtrip,
parsers, per-host sharding, resumable iteration (SURVEY.md §4 parity tests
+ §7 hard part #1)."""

import os

import numpy as np
import pytest

from fm_spark_tpu import native
from fm_spark_tpu.data import avazu, criteo, hashing, libsvm, movielens
from fm_spark_tpu.data.packed import PackedBatches, PackedDataset, PackedWriter


# ---------------------------------------------------------------- hashing

def test_murmur3_known_vectors():
    assert hashing.murmur3_32(b"", 0) == 0
    assert hashing.murmur3_32(b"hello", 0) == 0x248BFA47
    assert hashing.murmur3_32(b"hello, world", 0) == 0x149BBB7F
    assert (
        hashing.murmur3_32(
            b"The quick brown fox jumps over the lazy dog", 0x9747B28C
        )
        == 0x2FA826CD
    )


def test_murmur3_u64_matches_bytes(rng):
    keys = rng.integers(0, 2**63, 200, dtype=np.uint64)
    vec = hashing.murmur3_u64(keys, seed=11)
    for i in range(0, 200, 17):
        assert int(vec[i]) == hashing.murmur3_32(keys[i].tobytes(), seed=11)


def test_field_seeding_separates_fields():
    a = hashing.hash_token(0, b"token", 1000, per_field=False)
    b = hashing.hash_token(1, b"token", 1000, per_field=False)
    assert a != b  # same token, different fields → independent ids


def test_per_field_layout_ranges(rng):
    bucket = 64
    tokens = [bytes(rng.integers(0, 255, 8, dtype=np.uint8)) for _ in range(100)]
    fields = rng.integers(0, 5, 100)
    ids = hashing.hash_tokens_batch(tokens, fields, bucket, per_field=True)
    assert np.all(ids // bucket == fields)


def test_hash_int_features_matches_scalar_spec(rng):
    vals = rng.integers(-3, 10_000, (50, 4))
    fields = np.tile(np.arange(4), (50, 1))
    missing = rng.random((50, 4)) < 0.1
    ids = hashing.hash_int_features(vals, fields, 97, missing=missing)
    for r in range(0, 50, 7):
        for f in range(4):
            if missing[r, f]:
                key = (1 << 40) + 1
            elif vals[r, f] < 0:
                key = 1 << 40
            else:
                key = int(np.floor(np.log1p(float(vals[r, f])) ** 2))
            assert ids[r, f] == hashing.hash_int_u64_spec(f, key, 97)


# ----------------------------------------------------------------- native

needs_native = pytest.mark.skipif(
    not native.available(), reason=f"native build failed: {native.build_error()}"
)


@needs_native
def test_native_murmur_matches_python(rng):
    for n in [0, 1, 2, 3, 4, 5, 7, 8, 13, 64]:
        data = bytes(rng.integers(0, 255, n, dtype=np.uint8))
        assert native.murmur3_32(data, 42) == hashing.murmur3_32(data, 42)


@needs_native
def test_native_token_batch_matches_python(rng):
    tokens = [
        bytes(rng.integers(0, 255, int(rng.integers(0, 20)), dtype=np.uint8))
        for _ in range(500)
    ]
    fields = rng.integers(0, 39, 500)
    for per_field in (True, False):
        got = native.hash_tokens_batch(tokens, fields, 1_000_000, per_field)
        want = hashing.hash_tokens_batch(tokens, fields, 1_000_000, per_field)
        np.testing.assert_array_equal(got, want)


@needs_native
def test_native_u64_batch_matches_python(rng):
    keys = rng.integers(0, 2**62, 300, dtype=np.uint64)
    fields = rng.integers(0, 39, 300)
    got = native.hash_u64_batch(keys, fields, 12345)
    h = hashing.murmur3_u64(keys, fields.astype(np.uint32)) % np.uint32(12345)
    want = h.astype(np.int64) + fields * 12345
    np.testing.assert_array_equal(got, want)


@needs_native
def test_native_criteo_parser_matches_python_oracle(tmp_path, rng):
    path = str(tmp_path / "criteo.tsv")
    criteo.synthesize_tsv(path, 200, seed=5)
    raw = open(path, "rb").read()
    ids_n, labels_n, consumed = native.parse_criteo_chunk(raw, 4096)
    assert consumed == len(raw)
    ids_p, labels_p = criteo.parse_lines(raw.splitlines(True), 4096)
    np.testing.assert_array_equal(ids_n, ids_p)
    np.testing.assert_array_equal(labels_n, labels_p)


@needs_native
def test_native_criteo_parser_rejects_malformed():
    good = b"1" + b"\t1" * 13 + b"\tcafe" * 26 + b"\n"
    for bad in [
        b"1\t5\tabc\n",                                   # wrong column count
        good.replace(b"\t1\t", b"\txy\t", 1),             # non-digit count
        b"" + good[1:],                                   # empty label
        good[:-1] + b"\textra\n",                         # extra column
    ]:
        with pytest.raises(ValueError, match="malformed"):
            native.parse_criteo_chunk(bad, 4096)
        with pytest.raises(ValueError):
            criteo.parse_lines(bad.splitlines(True), 4096)


def test_packed_batches_restore_different_chunking_raises(tmp_path):
    _write_packed(tmp_path)
    ds = PackedDataset(str(tmp_path / "ds"))
    b1 = PackedBatches(ds, 32, seed=1, chunk_size=128)
    state = b1.state()
    b2 = PackedBatches(ds, 32, seed=1, chunk_size=256)
    with pytest.raises(ValueError, match="chunk_size"):
        b2.restore(state)
    b3 = PackedBatches(ds, 32, seed=1, chunk_size=128, shuffle=False)
    with pytest.raises(ValueError, match="shuffle"):
        b3.restore(state)


@pytest.mark.parametrize("store_vals", [True, False])
def test_shuffle_packed_permutes_and_preserves_rows(tmp_path, store_vals):
    from fm_spark_tpu.data.packed import shuffle_packed

    ids, vals, labels = _write_packed(tmp_path, store_vals=store_vals)
    out = str(tmp_path / "shuffled")
    # Tiny memory budget + tiny max_open force the RECURSIVE external
    # path (more groups needed than fds allowed per level).
    shuffle_packed(str(tmp_path / "ds"), out, seed=3,
                   mem_budget_bytes=2048, chunk_rows=128, max_open=4)
    ds = PackedDataset(out)
    assert len(ds) == len(ids)
    gi, gv, gl = ds.slice(slice(None))
    # Rows are a permutation of the originals: compare as sorted records.
    def records(i, v, l):
        rec = np.concatenate(
            [i.astype(np.int64),
             np.ascontiguousarray(v, np.float32).view(np.int32)
             .astype(np.int64),
             np.asarray(l, np.float32).reshape(-1, 1).view(np.int32)
             .astype(np.int64)], axis=1
        )
        return rec[np.lexsort(rec.T)]

    np.testing.assert_array_equal(
        records(gi, gv, gl), records(ids, vals, labels.astype(np.float32))
    )
    # ...and actually shuffled (overwhelmingly unlikely to match).
    assert not np.array_equal(gi, ids)
    # Deterministic in (seed, budget shape).
    out2 = str(tmp_path / "shuffled2")
    shuffle_packed(str(tmp_path / "ds"), out2, seed=3,
                   mem_budget_bytes=2048, chunk_rows=128, max_open=4)
    gi2, _, _ = PackedDataset(out2).slice(slice(None))
    np.testing.assert_array_equal(gi, gi2)
    # No temp shards left behind.
    assert not os.path.exists(out + ".shards.tmp")


def test_shuffle_packed_in_place_refused(tmp_path):
    from fm_spark_tpu.data.packed import shuffle_packed

    _write_packed(tmp_path)
    src = str(tmp_path / "ds")
    with pytest.raises(ValueError, match="in place"):
        shuffle_packed(src, src)
    # Source untouched by the refused call.
    assert len(PackedDataset(src)) == 1000
    # Non-empty existing output dir refused (failure cleanup would
    # otherwise rmtree pre-existing data).
    occupied = tmp_path / "occupied"
    occupied.mkdir()
    (occupied / "keep.txt").write_text("precious")
    with pytest.raises(ValueError, match="not empty"):
        shuffle_packed(src, str(occupied))
    assert (occupied / "keep.txt").read_text() == "precious"


def test_shuffle_packed_failure_leaves_no_truncated_output(tmp_path,
                                                           monkeypatch):
    from fm_spark_tpu.data import packed as packed_mod

    _write_packed(tmp_path)
    src = str(tmp_path / "ds")
    out = str(tmp_path / "out")

    def boom(ds, w, *a, **k):
        # Emulate a mid-shuffle crash after a partial append.
        w.append(np.asarray(ds.ids[:10]), np.asarray(ds.labels[:10]),
                 np.asarray(ds.vals[:10]))
        raise OSError("disk full")

    monkeypatch.setattr(packed_mod, "_shuffle_into", boom)
    with pytest.raises(OSError, match="disk full"):
        packed_mod.shuffle_packed(src, out, remove_src=True)
    # No valid-looking truncated output, no leftover scratch, and the
    # source survived even though remove_src was requested.
    assert not os.path.exists(out)
    assert not os.path.exists(out + ".shards.tmp")
    assert len(PackedDataset(src)) == 1000


def test_empty_packed_dataset_clear_error(tmp_path):
    with PackedWriter(str(tmp_path / "e"), 4):
        pass
    with pytest.raises(ValueError, match="empty"):
        PackedDataset(str(tmp_path / "e"))


@needs_native
def test_native_criteo_parser_partial_chunk(tmp_path):
    path = str(tmp_path / "criteo.tsv")
    criteo.synthesize_tsv(path, 10, seed=1)
    raw = open(path, "rb").read()
    cut = len(raw) - 25  # mid-line split
    ids, labels, consumed = native.parse_criteo_chunk(raw[:cut], 4096)
    assert consumed <= cut and ids.shape[0] == labels.shape[0] == 9
    # feeding the tail completes the stream
    ids2, _, c2 = native.parse_criteo_chunk(raw[consumed:], 4096)
    assert ids.shape[0] + ids2.shape[0] == 10


# ----------------------------------------------------------------- packed

def _write_packed(tmp_path, n=1000, f=7, store_vals=True, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 5000, (n, f)).astype(np.int32)
    vals = (
        rng.random((n, f)).astype(np.float32)
        if store_vals else np.ones((n, f), np.float32)
    )
    labels = rng.integers(0, 2, n).astype(np.int8)
    with PackedWriter(str(tmp_path / "ds"), f, store_vals=store_vals) as w:
        w.append(ids[:400], labels[:400], vals[:400])
        w.append(ids[400:], labels[400:], vals[400:])
    return ids, vals, labels


@pytest.mark.parametrize("store_vals", [True, False])
def test_packed_roundtrip(tmp_path, store_vals):
    ids, vals, labels = _write_packed(tmp_path, store_vals=store_vals)
    ds = PackedDataset(str(tmp_path / "ds"))
    assert len(ds) == 1000
    gi, gv, gl = ds.slice(slice(None))
    np.testing.assert_array_equal(gi, ids)
    np.testing.assert_array_equal(gv, vals)
    np.testing.assert_array_equal(gl, labels.astype(np.float32))


def test_packed_writer_validates(tmp_path):
    w = PackedWriter(str(tmp_path / "bad"), 4)
    with pytest.raises(ValueError):
        w.append(np.zeros((2, 3), np.int32), np.zeros(2, np.int8))
    with pytest.raises(ValueError):
        w.append(np.zeros((2, 4), np.int32), np.zeros(3, np.int8))
    w.close()


def test_packed_batches_cover_epoch(tmp_path):
    _write_packed(tmp_path)
    ds = PackedDataset(str(tmp_path / "ds"))
    b = PackedBatches(ds, 128, seed=3, chunk_size=256)
    seen = []
    total_w = 0.0
    while b.epoch == 0:
        ids, vals, labels, w = next(b)
        assert ids.shape == (128, 7)
        total_w += w.sum()
        if b.epoch == 0 or b.index == 0:
            seen.append((ids, w))
    assert total_w == 1000  # every example exactly once (padding weight 0)


def test_packed_batches_resume_exact(tmp_path):
    _write_packed(tmp_path)
    ds = PackedDataset(str(tmp_path / "ds"))
    b1 = PackedBatches(ds, 64, seed=9, chunk_size=128)
    for _ in range(10):
        next(b1)
    state = b1.state()
    want = [next(b1) for _ in range(8)]
    b2 = PackedBatches(ds, 64, seed=9, chunk_size=128)
    b2.restore(state)
    got = [next(b2) for _ in range(8)]
    for (wi, wv, wl, ww), (gi, gv, gl, gw) in zip(want, got):
        np.testing.assert_array_equal(wi, gi)
        np.testing.assert_array_equal(wl, gl)


def test_packed_batches_host_shards_disjoint(tmp_path):
    _write_packed(tmp_path)
    ds = PackedDataset(str(tmp_path / "ds"))
    ranges = []
    for h in range(4):
        b = PackedBatches(ds, 32, host_index=h, num_hosts=4)
        ranges.append(set(range(b.lo, b.hi)))
    assert set().union(*ranges) == set(range(1000))
    for i in range(4):
        for j in range(i + 1, 4):
            assert not ranges[i] & ranges[j]


def test_packed_batches_wrong_restore_raises(tmp_path):
    _write_packed(tmp_path)
    ds = PackedDataset(str(tmp_path / "ds"))
    b = PackedBatches(ds, 32, seed=1)
    with pytest.raises(ValueError):
        b.restore({"epoch": 0, "index": 0, "seed": 2, "lo": b.lo, "hi": b.hi})
    b2 = PackedBatches(ds, 32, seed=1, host_index=1, num_hosts=2)
    with pytest.raises(ValueError):
        b2.restore(b.state())


# ---------------------------------------------------------------- parsers

def test_criteo_preprocess_python_vs_native(tmp_path):
    src = str(tmp_path / "c.tsv")
    criteo.synthesize_tsv(src, 300, seed=2)
    n1 = criteo.preprocess(src, str(tmp_path / "py"), 4096, use_native=False,
                           chunk_bytes=4096)
    ds_py = PackedDataset(str(tmp_path / "py"))
    assert n1 == 300 and len(ds_py) == 300
    if native.available():
        n2 = criteo.preprocess(src, str(tmp_path / "nat"), 4096,
                               use_native=True, chunk_bytes=4096)
        ds_nat = PackedDataset(str(tmp_path / "nat"))
        assert n2 == 300
        np.testing.assert_array_equal(
            np.asarray(ds_py.ids), np.asarray(ds_nat.ids)
        )
        np.testing.assert_array_equal(
            np.asarray(ds_py.labels), np.asarray(ds_nat.labels)
        )


def test_avazu_preprocess(tmp_path):
    src = str(tmp_path / "a.csv")
    avazu.synthesize_csv(src, 150, seed=4)
    n = avazu.preprocess(src, str(tmp_path / "av"), 2048)
    ds = PackedDataset(str(tmp_path / "av"))
    assert n == 150 and ds.num_fields == avazu.NUM_FIELDS
    ids, vals, labels = ds.slice(slice(None))
    assert np.all(vals == 1.0)
    assert np.all((ids // 2048) == np.arange(avazu.NUM_FIELDS))
    assert set(np.unique(labels)) <= {0.0, 1.0}


def test_movielens_load(tmp_path):
    src = str(tmp_path / "u.data")
    movielens.synthesize_ratings(src, num_users=50, num_items=80,
                                 num_ratings=600, seed=6)
    (ids, vals, labels), meta = movielens.load_ratings(src)
    assert ids.shape == (600, 2) and meta["num_features"] <= 130
    assert np.all(ids[:, 0] < meta["num_users"])
    assert np.all(ids[:, 1] >= meta["num_users"])
    assert set(np.unique(labels)) <= {0.0, 1.0}
    (_, _, reg_labels), _ = movielens.load_ratings(src, task="regression")
    assert reg_labels.min() >= 1.0 and reg_labels.max() <= 5.0


def test_libsvm_roundtrip(tmp_path, rng):
    n, s = 40, 6
    ids = np.sort(rng.integers(0, 100, (n, s)), axis=1).astype(np.int32)
    vals = rng.random((n, s)).astype(np.float32)
    vals[rng.random((n, s)) < 0.3] = 0.0  # variable nnz
    labels = rng.integers(0, 2, n).astype(np.float32)
    path = str(tmp_path / "d.svm")
    libsvm.save_libsvm(path, ids, vals, labels)
    gi, gv, gl = libsvm.load_libsvm(path, max_nnz=s)
    np.testing.assert_array_equal(gl, labels)
    # entries with val 0 were dropped on write; compare as sets per row
    for r in range(n):
        want = {(int(i), round(float(v), 5)) for i, v in zip(ids[r], vals[r]) if v != 0}
        got = {(int(i), round(float(v), 5)) for i, v in zip(gi[r], gv[r]) if v != 0}
        assert want == got


def test_libsvm_overflow_raises(tmp_path):
    path = str(tmp_path / "d.svm")
    with open(path, "w") as f:
        f.write("1 1:1 2:1 3:1\n0 1:1\n")
    with pytest.raises(ValueError):
        libsvm.load_libsvm(path, max_nnz=2)
    ids, vals, _ = libsvm.load_libsvm(path, max_nnz=2, truncate=True)
    assert ids.shape == (2, 2)


# -------------------------------------------- parser error paths (ISSUE 5)


def test_libsvm_errors_distinguish_failure_modes(tmp_path):
    """A missing label, an unparseable label, and a malformed idx:val
    pair get DISTINCT messages (they collapsed into one opaque 'bad
    libsvm line' before), each with path:lineno and the offending
    content."""
    path = str(tmp_path / "d.svm")
    with open(path, "w") as f:
        f.write("1 1:0.5\n2:1.0 3:1.0\n")   # line 2: forgot the label
    with pytest.raises(ValueError,
                       match=r"d\.svm:2: bad libsvm line \(missing label"):
        libsvm.load_libsvm(path)
    with open(path, "w") as f:
        f.write("1 1:0.5\n0 4:x\n")
    with pytest.raises(ValueError, match=r"malformed idx:val pair.*4:x"):
        libsvm.load_libsvm(path)
    with open(path, "w") as f:
        f.write("zzz 1:0.5\n")
    with pytest.raises(ValueError, match="unparseable label"):
        libsvm.load_libsvm(path)


def test_libsvm_error_includes_truncated_repr_escaped_line(tmp_path):
    path = str(tmp_path / "d.svm")
    with open(path, "wb") as f:
        f.write(b"1 1:0.5\n0 9:" + b"\xff" * 500 + b"\n")
    with pytest.raises(ValueError) as exc:
        libsvm.load_libsvm(path)
    msg = str(exc.value)
    assert "d.svm:2" in msg
    assert "\\xff" in msg          # repr-escaped, not raw bytes
    assert "bytes)" in msg         # truncation marker carries full size
    assert len(msg) < 1000         # the 500-byte line was truncated


def test_libsvm_on_error_drops_bad_lines(tmp_path):
    path = str(tmp_path / "d.svm")
    with open(path, "w") as f:
        f.write("1 1:0.5\nGARBAGE\n0 2:1.0\n")
    errs = []
    ids, vals, labels = libsvm.load_libsvm(
        path, on_error=lambda p, ln, line, reason: errs.append((ln, reason))
    )
    assert labels.shape[0] == 2            # the bad line was dropped
    assert errs and errs[0][0] == 2


def test_criteo_parse_lines_on_error_gets_path_lineno(tmp_path):
    path = str(tmp_path / "c.tsv")
    criteo.synthesize_tsv(path, 4, seed=2)
    lines = open(path, "rb").read().splitlines(True)
    lines.insert(2, b"wrong\tcolumn\tcount\n")
    errs = []
    ids, labels = criteo.parse_lines(
        lines, 4096, on_error=lambda p, ln, line, r: errs.append((p, ln, r)),
        path="day0.tsv", start_lineno=10,
    )
    assert ids.shape[0] == 4               # bad row dropped, not raised
    assert errs == [("day0.tsv", 12, "criteo line has 3 columns, want 40")]
    # A non-integer count field routes through the same path.
    good = b"1" + b"\t1" * 13 + b"\tcafe" * 26 + b"\n"
    errs.clear()
    ids, labels = criteo.parse_lines(
        [good.replace(b"\t1\t", b"\txy\t", 1)], 4096,
        on_error=lambda p, ln, line, r: errs.append(r),
    )
    assert ids.shape[0] == 0 and "bad criteo field" in errs[0]
    # Without on_error the raise survives (garbage ids beat a crash).
    with pytest.raises(ValueError):
        criteo.parse_lines([b"wrong\tcount\n"], 4096)


def test_avazu_parse_lines_on_error_gets_path_lineno(tmp_path):
    path = str(tmp_path / "a.csv")
    avazu.synthesize_csv(path, 4, seed=2)
    lines = open(path, "rb").read().splitlines(True)[1:]  # drop header
    lines.insert(1, b"short,row\n")
    bad_hour = lines[3].split(b",")
    bad_hour[2] = b"99xx9999"
    lines.append(b",".join(bad_hour))
    errs = []
    ids, labels = avazu.parse_lines(
        lines, 1 << 14,
        on_error=lambda p, ln, line, r: errs.append((p, ln, r)),
        path="a.csv", start_lineno=2,
    )
    assert ids.shape[0] == 4               # both bad rows dropped
    assert errs[0][0] == "a.csv" and errs[0][1] == 3
    assert "columns" in errs[0][2]
    assert "bad hour field" in errs[1][2]
    with pytest.raises(ValueError, match="columns"):
        avazu.parse_lines([b"short,row\n"], 1 << 14)


@pytest.mark.slow
def test_packed_end_to_end_training(tmp_path):
    """Criteo TSV → packed → PackedBatches → FMTrainer: the full L2 path."""
    import jax

    from fm_spark_tpu import models
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    src = str(tmp_path / "c.tsv")
    criteo.synthesize_tsv(src, 600, seed=8)
    bucket = 512
    criteo.preprocess(src, str(tmp_path / "pk"), bucket)
    ds = PackedDataset(str(tmp_path / "pk"))
    spec = models.FieldFMSpec(
        num_features=criteo.NUM_FIELDS * bucket, rank=4,
        num_fields=criteo.NUM_FIELDS, bucket=bucket, init_std=0.01,
    )
    config = TrainConfig(num_steps=30, batch_size=128, learning_rate=0.1,
                         optimizer="adagrad", lr_schedule="constant",
                         log_every=30)
    trainer = FMTrainer(spec, config)
    batches = PackedBatches(ds, 128, seed=1)
    trainer.fit(batches)
    assert np.isfinite(trainer.loss_history[-1])


# ------------------------------------------------- fused batch assembly


@pytest.mark.parametrize("store_vals", [True, False])
@pytest.mark.parametrize("bucket", [0, 5000])
def test_assemble_matches_slice_plus_conversion(tmp_path, store_vals,
                                                bucket):
    """assemble() == slice() + field-local conversion, whichever of the
    native / numpy paths is active (they are pinned against each other
    in test_assemble_native_bitidentical_to_fallback)."""
    _write_packed(tmp_path, store_vals=store_vals)
    ds = PackedDataset(str(tmp_path / "ds"))
    rng = np.random.default_rng(3)
    sel = rng.permutation(len(ds))[:257]
    from fm_spark_tpu.data.packed import field_local

    got_i, got_v, got_l = ds.assemble(sel, bucket=bucket)
    ref_i, ref_v, ref_l = ds.slice(sel)
    if bucket:
        ref_i = field_local(ref_i, bucket)
    np.testing.assert_array_equal(got_i, ref_i)
    np.testing.assert_array_equal(got_v, ref_v)
    np.testing.assert_array_equal(got_l, ref_l)
    assert got_l.dtype == np.float32 and got_v.dtype == np.float32
    # slice-object sel takes the same fused path
    got_i2, _, _ = ds.assemble(np.s_[10:60], bucket=bucket)
    ref_i2 = np.asarray(ds.ids[10:60])
    if bucket:
        ref_i2 = field_local(ref_i2, bucket)
    np.testing.assert_array_equal(got_i2, ref_i2)


@needs_native
@pytest.mark.parametrize("store_vals", [True, False])
def test_assemble_native_bitidentical_to_fallback(tmp_path, store_vals,
                                                  monkeypatch):
    _write_packed(tmp_path, store_vals=store_vals)
    ds = PackedDataset(str(tmp_path / "ds"))
    sel = np.random.default_rng(4).permutation(len(ds))[:300]
    nat = ds.assemble(sel, bucket=5000)
    monkeypatch.setattr(native, "gather_rows_native",
                        lambda *a, **k: None)
    ds2 = PackedDataset(str(tmp_path / "ds"))
    fall = ds2.assemble(sel, bucket=5000)
    for g, f in zip(nat, fall):
        np.testing.assert_array_equal(g, f)


@needs_native
def test_native_gather_thread_count_invariant(tmp_path):
    _write_packed(tmp_path, n=700)
    ds = PackedDataset(str(tmp_path / "ds"))
    sel = np.random.default_rng(5).permutation(700)[:256]
    outs = [
        native.gather_rows_native(ds.ids, ds.vals, ds.labels, sel,
                                  bucket=5000, n_threads=t)
        for t in (1, 3)
    ]
    for g, f in zip(outs[0], outs[1]):
        np.testing.assert_array_equal(g, f)


def test_packed_batches_bucket_fuses_the_wrapper_conversion(tmp_path):
    """PackedBatches(bucket=B) yields exactly what the pre-round-5
    StreamingBatches(.., bucket=B) wrapper produced — including the
    weight-0 padded final batch — so pushing the conversion into the
    gather changes no observable sequence."""
    from fm_spark_tpu.cli import StreamingBatches

    _write_packed(tmp_path, n=1000)
    ds = PackedDataset(str(tmp_path / "ds"))
    bucket = 5000
    fused = PackedBatches(ds, 128, seed=11, bucket=bucket)
    wrapped = StreamingBatches(PackedBatches(ds, 128, seed=11),
                               bucket=bucket)
    for _ in range(2 * (1000 // 128 + 1)):  # crosses an epoch boundary
        for got, ref in zip(next(fused), wrapped.next_batch()):
            np.testing.assert_array_equal(got, ref)


def test_packed_batches_restore_bucket_mismatch_raises(tmp_path):
    _write_packed(tmp_path)
    ds = PackedDataset(str(tmp_path / "ds"))
    state = PackedBatches(ds, 32, seed=1, bucket=100).state()
    with pytest.raises(ValueError, match="bucket"):
        PackedBatches(ds, 32, seed=1).restore(state)


def test_assemble_ones_vals_cached_and_shared(tmp_path):
    """store_vals=False dirs reuse ONE all-ones vals array across
    batches (read-only by contract) instead of refilling 4*B*F bytes
    per batch — a feed-path invariant bench_input.py relies on."""
    _write_packed(tmp_path, store_vals=False)
    ds = PackedDataset(str(tmp_path / "ds"))
    _, v1, _ = ds.assemble(np.arange(64), bucket=0)
    _, v2, _ = ds.assemble(np.arange(64, 128), bucket=0)
    assert v1 is v2
    assert v1.shape == (64, 7) and np.all(v1 == 1.0)
    # The shared array is WRITE-PROTECTED: an accidental in-place
    # mutation by any consumer raises instead of silently corrupting
    # every other batch (the read-only contract, enforced not just
    # documented).
    assert not v1.flags.writeable
    with pytest.raises(ValueError):
        v1 *= 2.0
    assert np.all(v1 == 1.0)


def test_assemble_negative_and_oob_sel_numpy_semantics(tmp_path):
    """The native kernel does no bounds checks, so the binding routes
    negative / out-of-range sel to the numpy path: -1 means last row
    (fancy-indexing wraparound), past-the-end raises IndexError —
    never a silent out-of-bounds read."""
    ids, _, labels = _write_packed(tmp_path)
    ds = PackedDataset(str(tmp_path / "ds"))
    got_i, _, got_l = ds.assemble(np.array([-1, 0]))
    np.testing.assert_array_equal(got_i[0], ids[-1])
    assert got_l[0] == np.float32(labels[-1])
    with pytest.raises(IndexError):
        ds.assemble(np.array([len(ds)]))


def test_prefetcher_wraps_packed_batches_directly(tmp_path):
    """Prefetcher's documented contract includes bare PackedBatches
    (pipeline.py docstring) — bench_input.py's +prefetcher stage relies
    on it since the fused-bucket change dropped the StreamingBatches
    wrapper."""
    from fm_spark_tpu.data import Prefetcher

    _write_packed(tmp_path)
    ds = PackedDataset(str(tmp_path / "ds"))
    direct = PackedBatches(ds, 64, seed=2, bucket=100)
    pre = Prefetcher(PackedBatches(ds, 64, seed=2, bucket=100), depth=2)
    try:
        for _ in range(5):
            for got, ref in zip(pre.next_batch(), next(direct)):
                np.testing.assert_array_equal(got, ref)
    finally:
        pre.close()


@needs_native
def test_native_gather_more_threads_than_rows(tmp_path):
    """Explicit n_threads > B leaves trailing workers with empty row
    ranges — they must not touch (or even form pointers into) the
    output. Pinned after an out-of-bounds pointer-arithmetic fix."""
    _write_packed(tmp_path, n=40)
    ds = PackedDataset(str(tmp_path / "ds"))
    sel = np.arange(5, dtype=np.int64)
    got = native.gather_rows_native(ds.ids, ds.vals, ds.labels, sel,
                                    bucket=5000, n_threads=4)
    ref = native.gather_rows_native(ds.ids, ds.vals, ds.labels, sel,
                                    bucket=5000, n_threads=1)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(g, r)


@needs_native
@pytest.mark.parametrize("f", [1, 3, 8, 64])
def test_native_gather_field_width_sweep(tmp_path, f):
    """Bit-identity across field widths: the C second-pass conversion
    has vectorized/remainder paths whose boundaries move with F (1 =
    pure remainder, 8 = exact vector, 64 = many vectors)."""
    rng = np.random.default_rng(f)
    n, bucket = 300, 1000
    ids = (rng.integers(0, bucket, (n, f))
           + np.arange(f) * bucket).astype(np.int32)
    labels = rng.integers(0, 2, n).astype(np.int8)
    with PackedWriter(str(tmp_path / "ds"), f, store_vals=False) as w:
        w.append(ids, labels)
    ds = PackedDataset(str(tmp_path / "ds"))
    sel = rng.permutation(n)[:128]
    got_i, got_v, got_l = ds.assemble(sel, bucket=bucket)
    ref_i = ids[sel] - (np.arange(f, dtype=np.int32) * bucket)[None, :]
    np.testing.assert_array_equal(got_i, ref_i)
    assert np.all(got_v == 1.0) and got_v.shape == (128, f)
    np.testing.assert_array_equal(got_l, labels[sel].astype(np.float32))
