"""Hardened streaming ingest (ISSUE 5): bounded-memory shard reading,
per-record error policies, and the exactly-once resumable cursor.

The two acceptance drills live here: the CORRUPTION drill (flip bytes
mid-shard in a synthetic multi-file dataset; quarantine finishes
training and dead-letters exactly the injected records, strict fails
with a ``path:lineno`` error, and an injected bad fraction above the
breaker threshold aborts) and the EXACTLY-ONCE drill (SIGKILL a
training run mid-epoch on a 3-shard dataset, resume from the
checkpoint, and assert the concatenated record stream and loss curve
are bit-identical to an uninterrupted run).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from fm_spark_tpu.data.stream import (
    BadRecord,
    IngestAborted,
    RecordGuard,
    ShardReader,
    StreamBatches,
    line_parser,
)
from fm_spark_tpu.utils.logging import read_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_shards(tmp_path, n_shards=3, rows=32, name="shard{}.svm"):
    """Synthetic libsvm shards; record j (global) has ids (j+1, j+2) so
    the first id column identifies records uniquely."""
    paths = []
    j = 0
    for s in range(n_shards):
        p = str(tmp_path / name.format(s))
        with open(p, "w") as f:
            for _ in range(rows):
                f.write(f"{j % 2} {j + 1}:1.5 {j + 2}:0.5\n")
                j += 1
        paths.append(p)
    return paths, j


def _corrupt(path, linenos, garbage=b"\x00garbage \xff"):
    with open(path, "rb") as f:
        lines = f.read().splitlines(keepends=True)
    for ln in linenos:
        lines[ln - 1] = garbage + b"\n"
    with open(path, "wb") as f:
        f.write(b"".join(lines))


# ------------------------------------------------------------ ShardReader


@pytest.mark.parametrize("chunk", [1, 7, 64, 1 << 20])
def test_shard_reader_walks_files_in_order_at_any_chunk_size(tmp_path,
                                                             chunk):
    paths, total = _write_shards(tmp_path)
    r = ShardReader(paths, chunk_bytes=chunk)
    seen = []
    while True:
        try:
            shard, lineno, line = r.next_line()
        except StopIteration:
            break
        seen.append(int(line.split()[1].split(b":")[0]) - 1)
    assert seen == list(range(total))
    assert r.records == total


def test_shard_reader_handles_missing_trailing_newline(tmp_path):
    p = str(tmp_path / "s.svm")
    with open(p, "w") as f:
        f.write("1 1:1.0\n0 2:1.0")  # final line unterminated
    r = ShardReader([p], chunk_bytes=5)
    assert r.next_line()[2] == b"1 1:1.0"
    assert r.next_line()[2] == b"0 2:1.0"
    with pytest.raises(StopIteration):
        r.next_line()


def test_shard_reader_cursor_roundtrips_mid_shard(tmp_path):
    paths, total = _write_shards(tmp_path)
    r1 = ShardReader(paths, chunk_bytes=11)
    for _ in range(40):  # into shard 1
        r1.next_line()
    state = r1.state()
    assert state["shard"] == 1 and state["records"] == 40
    want = [r1.next_line() for _ in range(30)]
    r2 = ShardReader(paths, chunk_bytes=1 << 16)
    r2.restore(state)
    got = [r2.next_line() for _ in range(30)]
    assert want == got


def test_shard_reader_rejects_cursor_from_different_shard_list(tmp_path):
    paths, _ = _write_shards(tmp_path)
    state = ShardReader(paths).state()
    with pytest.raises(ValueError, match="shard list changed"):
        ShardReader(paths[:2]).restore(state)


def test_shard_reader_header_prefix_skips_by_match_not_position(
        tmp_path):
    """A split(1)-sharded headered CSV carries the header in shard 0
    only — the skip must MATCH the header, never blindly eat line 1 of
    every shard (that would silently drop one real record per shard)."""
    p0 = str(tmp_path / "h0.csv")
    with open(p0, "w") as f:
        f.write("id,click,hour\nrow0\n")
    p1 = str(tmp_path / "h1.csv")  # headerless continuation shard
    with open(p1, "w") as f:
        f.write("row1\nrow2\n")
    r = ShardReader([p0, p1], header_prefix=b"id,")
    assert r.next_line()[2] == b"row0"
    assert r.next_line()[2] == b"row1"  # NOT skipped: no header match
    assert r.next_line()[2] == b"row2"
    assert r.records == 3  # headers never count as records


# ---------------------------------------------------------- StreamBatches


def test_stream_batches_epoch_coverage_padding_and_fixed_shapes(tmp_path):
    paths, total = _write_shards(tmp_path)  # 96 records
    b = StreamBatches(ShardReader(paths, chunk_bytes=17),
                      line_parser("libsvm"), 20, 3, num_features=128)
    seen = []
    for _ in range(5):  # 4 full + 1 padded partial = one epoch
        ids, vals, labels, w = b.next_batch()
        assert ids.shape == (20, 3) and w.shape == (20,)
        seen.extend(ids[w > 0][:, 0].tolist())
    assert sorted(seen) == list(range(total))  # every record exactly once
    st = b.state()
    assert st["epoch"] == 1 and st["shard"] == 0 and st["offset"] == 0
    assert st["ok"] == total
    # Epoch 2 starts over.
    ids, _, _, w = b.next_batch()
    assert ids[0, 0] == 0 and w.sum() == 20


def test_stream_batches_exactly_once_state_roundtrip(tmp_path):
    paths, _ = _write_shards(tmp_path)
    b1 = StreamBatches(ShardReader(paths, chunk_bytes=13),
                       line_parser("libsvm"), 16, 3, num_features=128)
    for _ in range(3):
        b1.next_batch()
    state = b1.state()
    want = [b1.next_batch() for _ in range(6)]  # crosses the epoch seam
    b2 = StreamBatches(ShardReader(paths, chunk_bytes=1 << 16),
                       line_parser("libsvm"), 16, 3, num_features=128)
    b2.restore(state)
    got = [b2.next_batch() for _ in range(6)]
    for a, c in zip(want, got):
        for x, y in zip(a, c):
            np.testing.assert_array_equal(x, y)
    assert b1.state() == b2.state()


def test_stream_batches_all_garbage_dataset_raises(tmp_path):
    p = str(tmp_path / "g.svm")
    with open(p, "w") as f:
        f.write("GARBAGE\n" * 5)
    guard = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q"))
    b = StreamBatches(ShardReader([p]), line_parser("libsvm"), 4, 2,
                      guard=guard)
    with pytest.raises(ValueError, match="no parseable records"):
        b.next_batch()


# ------------------------------------------------------------ RecordGuard


def test_record_guard_schema_contract(tmp_path):
    g = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q"))
    ok = lambda *row, **kw: g.admit("p", 1, b"l", *row, **kw)
    assert ok(1.0, [1, 2], [0.5, 0.5], num_features=64, max_nnz=4)
    assert not ok(float("nan"), [1], [1.0])             # non-finite label
    assert not ok(1.0, [1], [float("inf")])             # non-finite value
    assert not ok(1.0, [64], [1.0], num_features=64)    # id out of bucket
    assert not ok(1.0, [-1], [1.0])                     # negative id
    assert not ok(1.0, [1, 2, 3], [1.0] * 3, max_nnz=2)  # nnz > S
    assert g.n_ok == 1 and g.n_bad == 5
    reasons = [e["reason"] for e in read_events(g.dead_letter_path)]
    assert len(reasons) == 5
    assert any("hash bucket" in r for r in reasons)
    assert any("non-finite label" in r for r in reasons)
    assert any("non-zeros" in r for r in reasons)


def test_record_guard_strict_raises_with_context():
    g = RecordGuard("strict")
    with pytest.raises(BadRecord, match=r"day0\.tsv:7: boom"):
        g.bad("day0.tsv", 7, b"the line", "boom")


def test_record_guard_unwindowed_mode_for_bulk_loads(tmp_path):
    """The in-memory loaders report all bad lines during the parse and
    the good count in one post-parse ok_many() — out of stream order.
    windowed=False must not misread that as a 100%-bad burst (a 0.15%
    dirty file used to abort against max_bad_frac=0.1); the whole-load
    check_overall() still enforces the real rate."""
    g = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q"),
                    max_bad_frac=0.1, windowed=False)
    for i in range(150):
        g.bad("f", i + 1, b"x", "bad")     # would trip a windowed guard
    g.ok_many(99_850)
    g.check_overall()                       # 0.15% overall: fine
    g2 = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q2"),
                     max_bad_frac=0.1, windowed=False)
    for i in range(30):
        g2.bad("f", i + 1, b"x", "bad")
    g2.ok_many(70)
    with pytest.raises(IngestAborted):      # 30% overall: aborts
        g2.check_overall()


def test_stream_libsvm_comment_lines_are_skipped_not_quarantined(
        tmp_path):
    """load_libsvm silently skips '#'-comment lines; the streaming path
    must agree — a commented header is not a bad record (it used to
    raise BadRecord under strict and count toward the breaker)."""
    p = str(tmp_path / "c.svm")
    with open(p, "w") as f:
        f.write("# generated by exporter v2\n")
        f.write("1 1:1.0  # trailing comment\n")
        f.write("0 2:1.0\n")
    b = StreamBatches(ShardReader([p]), line_parser("libsvm"), 2, 2,
                      num_features=16)  # default strict guard
    ids, vals, labels, w = b.next_batch()
    assert w.sum() == 2 and b.guard.n_bad == 0
    np.testing.assert_array_equal(ids[:, 0], [0, 1])


def test_record_guard_rejects_bad_config():
    with pytest.raises(ValueError, match="policy"):
        RecordGuard("lenient")
    with pytest.raises(ValueError, match="max_bad_frac"):
        RecordGuard("quarantine", max_bad_frac=1.5)


# ------------------------------------------- acceptance: corruption drill


def test_corruption_drill_quarantine_trains_strict_raises_breaker_aborts(
        tmp_path):
    """ISSUE 5 acceptance: flip bytes mid-shard in a synthetic 3-shard
    dataset. quarantine finishes training and dead-letters EXACTLY the
    injected records; strict fails with a path:lineno error; with the
    injected fraction above --max-bad-frac the breaker aborts."""
    from fm_spark_tpu import models
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    paths, total = _write_shards(tmp_path)
    _corrupt(paths[1], [10, 17])  # mid-shard byte flips
    spec = models.FMSpec(num_features=128, rank=4, init_std=0.05)
    config = TrainConfig(num_steps=6, batch_size=16, learning_rate=0.1,
                         lr_schedule="constant", log_every=6)

    # quarantine: training finishes, dead-letter count matches exactly.
    # (prefetch=0: a read-ahead producer would legitimately consume
    # into the next epoch and re-quarantine the same lines — the exact
    # per-epoch count is only observable without read-ahead.)
    guard = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q"),
                        max_bad_frac=0.5)
    batches = StreamBatches(ShardReader(paths, chunk_bytes=37),
                            line_parser("libsvm"), 16, 3, guard=guard,
                            num_features=128)
    trainer = FMTrainer(spec, config)
    trainer.fit(batches)
    assert trainer.step_count == 6
    assert np.isfinite(trainer.loss_history[-1])
    assert guard.n_bad == 2  # exactly the injected records, once each
    assert guard.n_ok == total - 2  # one full epoch, nothing skipped
    events = read_events(guard.dead_letter_path)
    bad = [e for e in events if e["event"] == "bad_record"]
    assert len(bad) == 2
    assert all(e["path"] == paths[1] for e in bad)
    assert sorted(e["lineno"] for e in bad) == [10, 17]

    # strict: the same dataset fails loudly with path:lineno context.
    batches = StreamBatches(ShardReader(paths), line_parser("libsvm"),
                            16, 3, num_features=128)
    with pytest.raises(BadRecord, match=r"shard1\.svm:10"):
        FMTrainer(spec, config).fit(batches)

    # breaker: injected bad fraction above max_bad_frac aborts the run
    # (raised out of the producer thread through the prefetcher).
    _corrupt(paths[1], range(5, 25))  # 20/96 ≈ 21% bad
    guard = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q2"),
                        max_bad_frac=0.1, window=32, min_records=32)
    batches = StreamBatches(ShardReader(paths), line_parser("libsvm"),
                            16, 3, guard=guard, num_features=128)
    with pytest.raises(IngestAborted, match="max_bad_frac"):
        FMTrainer(spec, config).fit(batches, prefetch=2)
    aborted = [e for e in read_events(guard.dead_letter_path)
               if e["event"] == "ingest_aborted"]
    assert len(aborted) == 1 and aborted[0]["bad_frac"] > 0.1


def test_quarantine_counters_ride_the_checkpoint_cursor(tmp_path):
    """A resumed run's dead-letter ACCOUNTING continues (counters live
    in the pipeline cursor) instead of resetting to zero."""
    paths, _ = _write_shards(tmp_path)
    _corrupt(paths[0], [3])
    guard = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q"))
    b = StreamBatches(ShardReader(paths), line_parser("libsvm"), 16, 3,
                      guard=guard, num_features=128)
    b.next_batch()
    state = b.state()
    assert state["bad"] == 1 and state["ok"] == 16
    guard2 = RecordGuard("quarantine",
                         quarantine_dir=str(tmp_path / "q2"))
    b2 = StreamBatches(ShardReader(paths), line_parser("libsvm"), 16, 3,
                       guard=guard2, num_features=128)
    b2.restore(state)
    assert guard2.n_bad == 1 and guard2.n_ok == 16


# ----------------------------------------- acceptance: exactly-once drill


_KILL_CHILD = """
import json, os, sys

sys.path.insert(0, {repo!r})
from fm_spark_tpu import models
from fm_spark_tpu.checkpoint import Checkpointer
from fm_spark_tpu.data.stream import ShardReader, StreamBatches, line_parser
from fm_spark_tpu.train import FMTrainer, TrainConfig

shard_dir, ck_dir, tap_path, steps = sys.argv[1:5]
paths = sorted(os.path.join(shard_dir, f) for f in os.listdir(shard_dir))


class Tap:
    def __init__(self, source, path):
        self._source = source
        self._f = open(path, "a")

    def next_batch(self):
        ids, vals, labels, w = self._source.next_batch()
        self._f.write(",".join(str(int(x)) for x in ids[w > 0][:, 0]))
        self._f.write("\\n")
        self._f.flush()
        return ids, vals, labels, w

    def state(self):
        return self._source.state()

    def restore(self, s):
        self._source.restore(s)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_batch()


spec = models.FMSpec(num_features=128, rank=4, init_std=0.05)
config = TrainConfig(num_steps=int(steps), batch_size=16,
                     learning_rate=0.1, lr_schedule="constant",
                     log_every=1)
ck = Checkpointer(ck_dir, save_every=4, async_save=False)
batches = Tap(StreamBatches(ShardReader(paths, chunk_bytes=64),
                            line_parser("libsvm"), 16, 3,
                            num_features=128), tap_path)
trainer = FMTrainer(spec, config)
trainer.fit(batches, checkpointer=ck)
ck.close()
print(json.dumps({{"done": trainer.step_count}}), flush=True)
"""


class _Tap:
    """Parent-side batch recorder: one line per step listing the REAL
    record ids consumed — the concatenated record stream the acceptance
    criterion compares."""

    def __init__(self, source, path):
        self._source = source
        self._path = path

    def next_batch(self):
        ids, vals, labels, w = self._source.next_batch()
        with open(self._path, "a") as f:
            f.write(",".join(str(int(x)) for x in ids[w > 0][:, 0]))
            f.write("\n")
        return ids, vals, labels, w

    def state(self):
        return self._source.state()

    def restore(self, s):
        self._source.restore(s)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_batch()


def test_sigkill_mid_epoch_resume_is_exactly_once(tmp_path):
    """ISSUE 5 acceptance: SIGKILL a training run mid-epoch on a
    3-shard dataset, resume from the checkpoint, and the concatenated
    record stream and loss curve are bit-identical to an uninterrupted
    run — no record consumed twice or skipped."""
    from fm_spark_tpu import models
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    shard_dir = tmp_path / "shards"
    shard_dir.mkdir()
    paths, _ = _write_shards(shard_dir)  # 96 records, 6 steps/epoch
    steps = 24

    spec = models.FMSpec(num_features=128, rank=4, init_std=0.05)
    config = TrainConfig(num_steps=steps, batch_size=16,
                         learning_rate=0.1, lr_schedule="constant",
                         log_every=1)

    # Golden: uninterrupted run over the same stream.
    golden_tap = str(tmp_path / "tap_golden.txt")
    golden = FMTrainer(spec, config)
    golden.fit(_Tap(StreamBatches(ShardReader(paths, chunk_bytes=64),
                                  line_parser("libsvm"), 16, 3,
                                  num_features=128), golden_tap))

    # Faulted run: child is SIGKILLed once it has logged step >= 13
    # (mid-epoch 3; checkpoints every 4 steps).
    script = tmp_path / "child.py"
    script.write_text(_KILL_CHILD.format(repo=REPO))
    ck_dir = str(tmp_path / "ck")
    kill_tap = str(tmp_path / "tap_kill.txt")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, str(script), str(shard_dir), ck_dir, kill_tap,
         str(steps)],
        stdout=subprocess.PIPE, text=True, cwd=REPO, env=env,
    )
    try:
        deadline = time.time() + 240
        for line in proc.stdout:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("step", 0) >= 13 or "done" in rec:
                break
            assert time.time() < deadline, "child never reached step 13"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        proc.stdout.close()
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL

    # Resume in-process from the killed run's checkpoint chain.
    resume_tap = str(tmp_path / "tap_resume.txt")
    ck = Checkpointer(ck_dir, save_every=4, async_save=False)
    batches = _Tap(StreamBatches(ShardReader(paths, chunk_bytes=1 << 16),
                                 line_parser("libsvm"), 16, 3,
                                 num_features=128), resume_tap)
    resumed = FMTrainer(spec, config)
    resumed.fit(batches, checkpointer=ck)
    ck.close()

    # Loss curve bit-identical (restored prefix + replayed suffix).
    assert resumed.step_count == golden.step_count == steps
    assert resumed.loss_history == golden.loss_history
    np.testing.assert_array_equal(np.asarray(golden.params["v"]),
                                  np.asarray(resumed.params["v"]))

    # Concatenated record stream: the checkpointed prefix of the killed
    # run plus the resumed suffix IS the golden stream — no record
    # consumed twice, none skipped.
    golden_lines = open(golden_tap).read().splitlines()
    kill_lines = open(kill_tap).read().splitlines()
    resume_lines = open(resume_tap).read().splitlines()
    restored_step = steps - len(resume_lines)
    assert 0 < restored_step < steps  # it really resumed mid-run
    assert restored_step % 4 == 0     # from a checkpoint boundary
    assert kill_lines[:restored_step] == golden_lines[:restored_step]
    assert resume_lines == golden_lines[restored_step:]
