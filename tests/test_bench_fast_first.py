"""CI smoke for the warm-start tiered bench (ISSUE 1): ``bench.py
--fast-first`` on the CPU backend.

One subprocess covers the whole contract, kill included:

1. the sweep's FIRST leg lands its non-provisional result as an
   incrementally-persisted keep-best artifact (``legs_completed == 1``
   — written BEFORE any remaining sweep leg completes);
2. a SIGTERM mid-sweep leaves that artifact intact and parseable — an
   interrupted run never reports null when any leg completed;
3. the parent, having salvaged a result line, exits 0 (so callers
   chained on success, e.g. tpu_watch's one-time queue, still advance).

Model ``fm_kaggle`` is the smallest registered shape (39 × 32768 × 33
tables ≈ 170 MB fp32), and its default sweep has no Pallas legs — the
whole run is a few table inits + small CPU compiles.
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def test_fast_first_incremental_artifact_survives_sigterm(tmp_path):
    art = tmp_path / "art"
    kb_path = art / "keepbest_fm_kaggle.json"
    proc = subprocess.Popen(
        [sys.executable, BENCH, "--fast-first",
         "--model", "fm_kaggle", "--batch", "128", "--steps", "2",
         "--compile-cache", str(tmp_path / "cc"),
         "--artifacts-dir", str(art),
         "--attempts", "1", "--attempt-timeout", "560",
         "--total-deadline", "580", "--init-timeout", "180"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        # Wait for the FIRST leg's keep-best artifact (the fast-first
        # tier boundary); the remaining legs are still ahead.
        deadline = time.time() + 560
        kb = None
        while time.time() < deadline and proc.poll() is None:
            if kb_path.exists():
                try:
                    kb = json.loads(kb_path.read_text())
                except json.JSONDecodeError:
                    kb = None  # mid-replace; atomic rename lands whole
                if kb is not None:
                    break
            time.sleep(0.5)
        assert kb is not None, "no keep-best artifact before deadline"
        assert kb["value"] is not None and kb["value"] > 0
        assert kb["metric"].startswith("kaggle_fm_rank32")
        assert kb["legs_completed"] == 1, (
            "first persisted result must precede the remaining legs"
        )
        assert kb["t_first_result_s"] > 0
        assert "/b128" in kb["variant"]  # shape provenance stamp

        # Give the parent's stdout reader a beat to record the child's
        # result line, then kill mid-sweep.
        time.sleep(2.0)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=60)

    # Salvaged run: exit 0 with a parseable final result line.
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out[-2000:]}"
    lines = [ln for ln in out.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON line on stdout:\n{out[-2000:]}"
    final = json.loads(lines[-1])
    assert final.get("value") is not None
    assert final.get("error") is None
    # The artifact survived the kill and still parses.
    assert json.loads(kb_path.read_text())["value"] is not None
    # Every completed leg was streamed to the sweep log.
    sweep = (art / "sweep_fm_kaggle.jsonl").read_text().strip()
    assert len(sweep.splitlines()) >= 1
    for ln in sweep.splitlines():
        assert json.loads(ln)["value"] > 0
