"""Elastic degraded-mode training (ISSUE 4 tentpole): permanent-fault
classification, mesh-shrink resharding, and divergence rollback.

The CPU acceptance scenario lives at the bottom: an 8-fake-device
field-sharded run suffers a PERMANENT injected device fault (three
identical consecutive losses), shrinks to 4 devices, restores the last
good checkpoint onto the half mesh, and finishes — with final
parameters BIT-IDENTICAL to a clean resume-on-4 of the same checkpoint
(the loss-continuity contract: an elastic shrink is exactly a clean
resume, just decided by the classifier instead of an operator).
"""

import jax
import numpy as np
import pytest

from fm_spark_tpu.resilience import (
    BackoffPolicy,
    CircuitOpen,
    ElasticController,
    ElasticExhausted,
    InjectedDeviceLoss,
    RetriesExhausted,
    Supervisor,
    classify_failures,
    faults,
)
from fm_spark_tpu.resilience.divergence import (
    DivergenceDetected,
    DivergenceGuard,
)
from fm_spark_tpu.utils.logging import EventLog, read_events


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------------ classify_failures


def test_classify_identical_tail_is_permanent():
    diags = ["child exited rc=3 without a result line"] * 3
    assert classify_failures(diags) == "permanent"
    assert classify_failures(diags[:2]) == "transient"  # below threshold


def test_classify_normalizes_numerals():
    # BENCH_r05's tail: the same hang diagnosed with different measured
    # durations is the SAME failure mode.
    diags = [
        "child hung: no result within 126s (killed)",
        "child hung: no result within 125s (killed)",
        "child hung: no result within 127.5s (killed)",
    ]
    assert classify_failures(diags) == "permanent"


def test_classify_preserves_exit_codes():
    # rc=1 (program bug) vs rc=3 (init watchdog) are DIFFERENT failure
    # modes even though only a numeral distinguishes them.
    diags = ["child exited rc=1 without a result line",
             "child exited rc=3 without a result line",
             "child exited rc=3 without a result line"]
    assert classify_failures(diags) == "transient"
    assert classify_failures(
        ["child exited rc=3 without a result line"] * 3) == "permanent"


def test_classify_mixed_modes_stay_transient():
    diags = ["child exited rc=3 without a result line",
             "child hung: no result within 126s (killed)",
             "child exited rc=3 without a result line"]
    assert classify_failures(diags) == "transient"
    # A long run whose TAIL is identical classifies on the tail.
    diags += ["child exited rc=3 without a result line"] * 2
    assert classify_failures(diags) == "permanent"


# ------------------------------------------------------ ElasticController


def test_controller_shrinks_8_4_2_1_and_exhausts(tmp_path):
    journal = str(tmp_path / "j.jsonl")
    ctl = ElasticController(devices=list(range(8)), max_shrinks=3,
                            journal=EventLog(journal))
    assert not ctl.degraded and ctl.n_chips == 8
    assert ctl.shrink("train") == [0, 1, 2, 3]
    assert ctl.shrink("train") == [0, 1]
    assert ctl.shrink("train") == [0]
    assert ctl.degraded and ctl.shrinks == 3
    with pytest.raises(ElasticExhausted):
        ctl.shrink("train")
    events = read_events(journal)
    shrinks = [e for e in events if e["event"] == "mesh_shrink"]
    assert [(e["from_chips"], e["to_chips"]) for e in shrinks] == [
        (8, 4), (4, 2), (2, 1)]
    assert events[-1]["event"] == "elastic_exhausted"
    assert ctl.summary() == {"degraded": True, "chips": 1, "shrinks": 3}


def test_controller_note_failure_classifies_and_journals(tmp_path):
    journal = str(tmp_path / "j.jsonl")
    ctl = ElasticController(devices=[0, 1], journal=EventLog(journal))
    e = InjectedDeviceLoss("step", 1)
    assert ctl.note_failure("train", e) == "transient"
    assert ctl.note_failure("train", e) == "transient"
    assert ctl.note_failure("train", InjectedDeviceLoss("step", 2)) \
        == "permanent"  # numerals normalized: same mode
    # A different mode resets the identical run.
    assert ctl.note_failure("train", ValueError("shape")) == "transient"
    events = read_events(journal)
    assert [e["classification"] for e in events] == [
        "transient", "transient", "permanent", "transient"]


def test_controller_min_devices_floor():
    ctl = ElasticController(devices=list(range(6)), max_shrinks=5,
                            min_devices=2)
    assert ctl.shrink() == [0, 1, 2]
    assert ctl.shrink() == [0, 1]   # floored at min_devices, not 1
    assert not ctl.can_shrink()


# ------------------------------------- Supervisor permanent-fault verdict


def test_supervisor_tracks_identical_failures_and_skips_backoff(tmp_path):
    delays = []
    journal = str(tmp_path / "h.jsonl")
    sup = Supervisor(
        policy=BackoffPolicy(initial=1.0, jitter=0.0, max_attempts=6),
        journal=EventLog(journal), probe=lambda: False,
        breaker_threshold=3, sleep=delays.append,
    )

    def always():
        raise InjectedDeviceLoss("step", 1)

    with pytest.raises(RetriesExhausted):
        sup.run(always, op="leg")
    # Three identical failures classified PERMANENT: attempts 4..6 and
    # their backoff sleeps are SKIPPED (the BENCH_r05 budget burn).
    assert sup.permanent()
    assert len(delays) == 2
    events = [e["event"] for e in read_events(journal)]
    assert "permanent_fault" in events
    rec = next(e for e in read_events(journal)
               if e["event"] == "permanent_fault")
    assert rec["identical_failures"] == 3
    assert rec["skipped_attempts"] == 3


def test_supervisor_mixed_failures_not_permanent(tmp_path):
    sup = Supervisor(
        policy=BackoffPolicy(initial=1.0, jitter=0.0, max_attempts=3),
        probe=lambda: False, breaker_threshold=3, sleep=lambda s: None,
    )
    errors = [InjectedDeviceLoss("a", 1),
              RuntimeError("DATA_LOSS: device lost"),
              InjectedDeviceLoss("a", 1)]

    def flaky():
        raise errors.pop(0)

    with pytest.raises(RetriesExhausted):
        sup.run(flaky, op="leg")
    assert not sup.permanent()


def test_supervisor_reset_rearms_breaker(tmp_path):
    sup = Supervisor(probe=lambda: False, breaker_threshold=2,
                     sleep=lambda s: None,
                     policy=BackoffPolicy(max_attempts=1, jitter=0.0))
    for _ in range(2):
        with pytest.raises(RetriesExhausted):
            sup.run(lambda: (_ for _ in ()).throw(
                InjectedDeviceLoss("s", 0)), op="leg")
    assert sup.state == "open"
    sup.reset("leg")
    assert sup.state == "closed" and sup.consecutive_failures == 0
    assert not sup.permanent()
    assert sup.run(lambda: "ok", op="leg") == "ok"


# --------------------------------------------------------- DivergenceGuard


def test_guard_triggers_on_nonfinite_and_spike(tmp_path):
    journal = str(tmp_path / "g.jsonl")
    g = DivergenceGuard(spike_factor=10.0, min_history=3,
                        journal=EventLog(journal))
    for i, loss in enumerate([0.7, 0.69, 0.68, 0.67]):
        g.check(i, loss)
    with pytest.raises(DivergenceDetected, match="spike"):
        g.check(5, 7.0)  # 7.0 > 10x the 0.69 trailing median
    with pytest.raises(DivergenceDetected, match="non-finite"):
        g.check(6, float("nan"))
    events = read_events(journal)
    assert [e["event"] for e in events] == ["divergence_detected"] * 2


def test_guard_tolerates_noise_below_factor():
    g = DivergenceGuard(spike_factor=10.0, min_history=3)
    for i, loss in enumerate([0.7, 0.6, 0.8, 0.65, 3.0, 0.62]):
        g.check(i, loss)  # 3.0 < 10x median: banked, not a spike
    # And no trigger before min_history losses are banked.
    g2 = DivergenceGuard(spike_factor=2.0, min_history=3)
    g2.check(0, 1.0)
    g2.check(1, 100.0)  # only one banked loss: no baseline yet


def test_guard_rollback_budget_exhausts():
    g = DivergenceGuard(spike_factor=10.0, max_rollbacks=1)
    det = DivergenceDetected(7, float("inf"), "non-finite loss")
    assert g.note_rollback(det, restored_step=4) == 6
    with pytest.raises(DivergenceDetected):
        g.note_rollback(det, restored_step=4)
    assert g.rollbacks == 1


# -------------------------------- FMTrainer: divergence rollback (e2e)


class _PoisonOnce:
    """Resumable batch source that poisons the Nth FETCHED batch once
    (process-local count — the replay after rollback yields the clean
    batch, but the guard's reduced budget stops before it anyway)."""

    def __init__(self, inner, at, scale=1e12):
        self.inner, self.at, self.scale = inner, at, scale
        self.n = 0

    def state(self):
        return self.inner.state()

    def restore(self, s):
        self.inner.restore(s)

    def __iter__(self):
        return self

    def __next__(self):
        self.n += 1
        ids, vals, labels, w = next(self.inner)
        if self.n == self.at:
            vals = vals * self.scale  # loss blows up this step
        return ids, vals, labels, w


def _problem():
    from fm_spark_tpu import models
    from fm_spark_tpu.data.synthetic import synthetic_ctr
    from fm_spark_tpu.train import TrainConfig

    ids, vals, labels = synthetic_ctr(
        num_examples=256, num_features=64, nnz=5, seed=3)
    spec = models.FMSpec(num_features=64, rank=4, init_std=0.05)
    config = TrainConfig(num_steps=10, batch_size=32, learning_rate=0.1,
                         lr_schedule="constant", log_every=1)
    return spec, config, (ids, vals, labels)


def test_divergence_rollback_restores_pre_spike_state(tmp_path):
    """ISSUE 4 acceptance: the guard rolls back to last_good and resumes
    with a reduced budget; the result is bit-identical to a clean run
    stopped just before the spike."""
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data.pipeline import Batches
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    spec, config, (ids, vals, labels) = _problem()

    # Golden: a clean run of 6 steps (the spike below lands at step 7).
    import dataclasses as _dc

    golden = FMTrainer(spec, _dc.replace(config, num_steps=6))
    golden.fit(Batches(ids, vals, labels, config.batch_size, seed=7))

    journal = str(tmp_path / "h.jsonl")
    guard = DivergenceGuard(spike_factor=10.0, journal=EventLog(journal))
    ck = Checkpointer(str(tmp_path / "ck"), save_every=2,
                      async_save=False)
    trainer = FMTrainer(spec, config)
    batches = _PoisonOnce(
        Batches(ids, vals, labels, config.batch_size, seed=7), at=7)
    trainer.fit(batches, checkpointer=ck, divergence_guard=guard)
    ck.close()

    # Stopped just before the poisoned step, state bit-identical to the
    # clean 6-step run (rollback to step 6's checkpoint, replay none).
    assert trainer.step_count == 6
    assert guard.rollbacks == 1
    assert trainer.loss_history == golden.loss_history
    np.testing.assert_array_equal(
        np.asarray(golden.params["v"]), np.asarray(trainer.params["v"]))
    np.testing.assert_array_equal(
        np.asarray(golden.params["w"]), np.asarray(trainer.params["w"]))
    events = [e["event"] for e in read_events(journal)]
    assert "divergence_detected" in events
    assert "divergence_rollback" in events


def test_divergence_guard_requires_checkpointer():
    from fm_spark_tpu.data.pipeline import Batches
    from fm_spark_tpu.train import FMTrainer

    spec, config, (ids, vals, labels) = _problem()
    trainer = FMTrainer(spec, config)
    with pytest.raises(ValueError, match="divergence"):
        trainer.fit(Batches(ids, vals, labels, 32, seed=1),
                    divergence_guard=DivergenceGuard())


# ------------------------------ FMTrainer: elastic continue (single-chip)


def test_trainer_elastic_continues_past_permanent_fault(tmp_path):
    """A permanent device fault (3 identical losses -> CircuitOpen) with
    an elastic controller downgrades to a shrink + resume instead of
    killing the run; per-chip metrics renormalize to the survivors."""
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data.pipeline import Batches
    from fm_spark_tpu.train import FMTrainer

    spec, config, (ids, vals, labels) = _problem()
    golden = FMTrainer(spec, config)
    golden.fit(Batches(ids, vals, labels, config.batch_size, seed=7))

    journal = str(tmp_path / "h.jsonl")
    jlog = EventLog(journal)
    faults.activate("train_step@5=device_loss;train_step@6=device_loss;"
                    "train_step@7=device_loss")
    sup = Supervisor(policy=BackoffPolicy(initial=1.0, jitter=0.0),
                     journal=jlog, probe=lambda: True,
                     sleep=lambda s: None, breaker_threshold=3)
    elastic = ElasticController(max_shrinks=1, journal=jlog)
    ck = Checkpointer(str(tmp_path / "ck"), save_every=2,
                      async_save=False)
    # n_chips tracks the controller's fleet view, so the shrink
    # re-normalizes the per-chip metrics (a default n_chips=1 trainer
    # would keep its single-chip normalization — see fit()).
    trainer = FMTrainer(spec, config, n_chips=elastic.n_chips)
    trainer.fit(Batches(ids, vals, labels, config.batch_size, seed=7),
                checkpointer=ck, supervisor=sup, elastic=elastic)
    ck.close()

    assert trainer.step_count == golden.step_count == 10
    assert trainer.loss_history == golden.loss_history  # bit-identical
    np.testing.assert_array_equal(
        np.asarray(golden.params["v"]), np.asarray(trainer.params["v"]))
    assert elastic.degraded and elastic.shrinks == 1
    assert trainer.logger._n_chips == elastic.n_chips
    events = [e["event"] for e in read_events(journal)]
    assert "circuit_open" in events
    assert "mesh_shrink" in events
    assert "supervisor_reset" in events


def test_trainer_recovery_before_first_checkpoint_rewinds_batches(tmp_path):
    """A device loss BEFORE the first committed checkpoint must rewind
    the batch source to its pre-run cursor on retry — resuming
    mid-stream would silently skip the consumed window."""
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data.pipeline import Batches
    from fm_spark_tpu.train import FMTrainer

    spec, config, (ids, vals, labels) = _problem()
    golden = FMTrainer(spec, config)
    golden.fit(Batches(ids, vals, labels, config.batch_size, seed=7))

    faults.activate("train_step@2=device_loss")
    sup = Supervisor(policy=BackoffPolicy(initial=1.0, jitter=0.0),
                     probe=lambda: True, sleep=lambda s: None)
    # save_every far beyond the run: only the final forced save lands,
    # so the recovery at step 2 has NO checkpoint to restore.
    ck = Checkpointer(str(tmp_path / "ck"), save_every=1000,
                      async_save=False)
    trainer = FMTrainer(spec, config)
    trainer.fit(Batches(ids, vals, labels, config.batch_size, seed=7),
                checkpointer=ck, supervisor=sup)
    ck.close()

    assert trainer.step_count == golden.step_count == 10
    assert trainer.loss_history == golden.loss_history  # full replay
    np.testing.assert_array_equal(
        np.asarray(golden.params["v"]), np.asarray(trainer.params["v"]))


def test_trainer_elastic_requires_supervisor():
    from fm_spark_tpu.data.pipeline import Batches
    from fm_spark_tpu.train import FMTrainer

    spec, config, (ids, vals, labels) = _problem()
    trainer = FMTrainer(spec, config)
    with pytest.raises(ValueError, match="elastic"):
        trainer.fit(Batches(ids, vals, labels, 32, seed=1),
                    elastic=ElasticController())


def test_elastic_wrapper_progress_between_flaps_never_accumulates(
        tmp_path):
    """Three device losses SEPARATED by checkpointed progress are three
    independent flaps, not a permanent fault: the wrapper clears the
    failure run when a newer checkpoint committed since the last loss,
    so a healthy-but-flappy fleet is never shrunk."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 fake CPU devices")
    import dataclasses as _dc

    from fm_spark_tpu import cli, configs as configs_lib
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data import Batches, synthetic_ctr
    from fm_spark_tpu.data.packed import field_local

    small = _dc.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"],
        name="elflap", bucket=32, num_fields=5, rank=4,
        batch_size=64, num_steps=10,
    )
    configs_lib.CONFIGS["elflap"] = small
    try:
        cfg = configs_lib.get_config("elflap")
        ids, vals, labels = synthetic_ctr(
            512, cfg.num_features, cfg.num_fields, seed=cfg.seed)
        batches = Batches(field_local(ids, cfg.bucket), vals, labels,
                          cfg.batch_size, seed=cfg.seed)
        # Losses at inject occurrences 3, 8, 12: each retry makes >= 2
        # checkpointed steps of progress before the next loss lands.
        faults.activate("train_step@3=device_loss;"
                        "train_step@8=device_loss;"
                        "train_step@12=device_loss")
        ck = Checkpointer(str(tmp_path / "ck"), save_every=2)
        sup = Supervisor(policy=BackoffPolicy(initial=1.0, jitter=0.0),
                         probe=lambda: True, sleep=lambda s: None,
                         breaker_threshold=3)
        params, elastic = cli._fit_field_sparse_elastic(
            spec=cfg.spec(), tconfig=cfg.train_config(log_every=10),
            batches=batches, checkpointer=ck, eval_source=None,
            prefetch=0, row_shards=1, steps_per_call=1, max_shrinks=2,
            journal=None, metrics_path=None, supervisor=sup)
        ck.close()
        assert not elastic.degraded and elastic.shrinks == 0
        assert ck.last_good_step() == 10
    finally:
        faults.clear()
        del configs_lib.CONFIGS["elflap"]


# --------------------- CLI field_sparse: mesh-shrink resharding (e2e)


def test_cli_elastic_shrink_resumes_on_half_mesh(tmp_path):
    """ISSUE 4 acceptance (CPU, forced 8-device host platform): a
    permanent injected device fault mid-run shrinks the field-sharded
    mesh 8 -> 4, restores the last good checkpoint onto the survivors,
    and finishes — bit-identical to a CLEAN resume of the same
    checkpoint on 4 devices."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 fake CPU devices")
    import dataclasses as _dc

    from fm_spark_tpu import cli, configs as configs_lib
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data import Batches, synthetic_ctr
    from fm_spark_tpu.data.packed import field_local
    from fm_spark_tpu.utils.logging import MetricsLogger

    small = _dc.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"],
        name="elshrink", bucket=32, num_fields=5, rank=4,
        batch_size=64, num_steps=8,
    )
    configs_lib.CONFIGS["elshrink"] = small
    try:
        def run_cli(ckdir, steps, extra=()):
            rc = cli.main([
                "train", "--config", "elshrink", "--synthetic", "512",
                "--steps", str(steps), "--strategy", "field_sparse",
                "--checkpoint-dir", str(ckdir), "--checkpoint-every",
                "2", "--test-fraction", "0", "--log-every", "4",
                *extra,
            ])
            assert rc == 0

        def make_batches():
            cfg = configs_lib.get_config("elshrink")
            ids, vals, labels = synthetic_ctr(
                512, cfg.num_features, cfg.num_fields, seed=cfg.seed)
            if cfg.field_local_ids:
                ids = field_local(ids, cfg.bucket)
            return Batches(ids, vals, labels, cfg.batch_size,
                           seed=cfg.seed)

        # Golden: 4 steps on the full 8-device mesh (checkpointed),
        # then a CLEAN resume to 8 on an explicit 4-device half mesh.
        ck_g = tmp_path / "golden"
        run_cli(ck_g, 4)
        cfg = configs_lib.get_config("elshrink")
        spec, tconfig = cfg.spec(), cfg.train_config(log_every=4)
        ckg = Checkpointer(str(ck_g), save_every=2)
        params_golden = cli._fit_field_sparse(
            spec, tconfig, make_batches(),
            MetricsLogger(stream=None, n_chips=4), ckg,
            devices=jax.devices()[:4],
        )
        ckg.close()

        # Elastic: same run end-to-end through the CLI; steps 1-4 train
        # on 8 devices (checkpoints at 2 and 4), then three identical
        # injected device losses at step 5 classify PERMANENT and the
        # wrapper shrinks to 4 devices and resumes from step 4.
        faults.activate(
            "train_step@5=device_loss;train_step@6=device_loss;"
            "train_step@7=device_loss")
        ck_e = tmp_path / "elastic"
        run_cli(ck_e, 8, extra=("--elastic", "--max-shrinks", "2",
                                "--model-out",
                                str(tmp_path / "model")))
        faults.clear()

        from fm_spark_tpu import models as models_lib

        _, params_elastic = models_lib.load_model(str(tmp_path / "model"))
        for a, b in zip(jax.tree_util.tree_leaves(params_golden),
                        jax.tree_util.tree_leaves(params_elastic)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        events = read_events(str(ck_e / "health.jsonl"))
        names = [e["event"] for e in events]
        assert "circuit_open" in names
        assert "supervisor_reset" in names
        shrink = next(e for e in events if e["event"] == "mesh_shrink")
        assert shrink["from_chips"] == 8 and shrink["to_chips"] == 4
        assert "degraded_complete" in names
        done = next(e for e in events if e["event"] == "degraded_complete")
        assert done["degraded"] is True and done["chips"] == 4
    finally:
        del configs_lib.CONFIGS["elshrink"]


def test_guard_maximize_mode_detects_metric_drop():
    """ISSUE 13 satellite: maximize mode (higher-is-better, eval AUC)
    fires when a finite value falls below trailing-median / factor —
    the concept-drift direction — and non-finite is unconditional."""
    g = DivergenceGuard(spike_factor=1.15, min_history=3, mode="max")
    for step, auc in enumerate((0.74, 0.75, 0.73, 0.74), 1):
        g.check(step, auc)  # healthy plateau
    with pytest.raises(DivergenceDetected, match="metric drop"):
        g.check(5, 0.55)  # 0.74 / 1.15 = 0.643 > 0.55
    g2 = DivergenceGuard(spike_factor=1.15, min_history=3, mode="max")
    g2.check(1, 0.7)
    with pytest.raises(DivergenceDetected, match="non-finite"):
        g2.check(2, float("nan"))


def test_guard_maximize_min_history_floor_blocks_short_series():
    """A short eval series can never trip the drop test: the first
    ``min_history`` values bank unconditionally — in BOTH directions."""
    g = DivergenceGuard(spike_factor=1.15, min_history=3, mode="max")
    g.check(1, 0.9)
    g.check(2, 0.2)   # huge drop, but only 1 value banked: no verdict
    g.check(3, 0.15)  # still under the floor
    gmin = DivergenceGuard(spike_factor=2.0, min_history=4, mode="min")
    gmin.check(1, 1.0)
    gmin.check(2, 50.0)  # would be a 50x spike with history
    gmin.check(3, 60.0)


def test_guard_maximize_history_roundtrip_and_rollback_budget():
    g = DivergenceGuard(spike_factor=1.15, min_history=3, mode="max",
                        max_rollbacks=1)
    for step, auc in enumerate((0.7, 0.72, 0.71), 1):
        g.check(step, auc)
    assert g.history() == [0.7, 0.72, 0.71]
    g2 = DivergenceGuard(spike_factor=1.15, min_history=3, mode="max",
                         max_rollbacks=1)
    g2.seed_history(g.history())  # the durable-resume path
    with pytest.raises(DivergenceDetected) as exc:
        g2.check(4, 0.3)
    assert g2.note_rollback(exc.value, restored_step=2) >= 2
    assert g2.history() == []  # window cleared for the replay
    with pytest.raises(DivergenceDetected):  # budget spent
        g2.note_rollback(exc.value, restored_step=2)


def test_guard_rejects_unknown_mode():
    with pytest.raises(ValueError, match="mode"):
        DivergenceGuard(mode="sideways")
