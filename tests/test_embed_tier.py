"""Tiered flat-FM trainer: bitwise differentials, crash drills, levers.

The ISSUE-16 acceptance tests. The tiered path's whole claim is that it
changes WHERE rows live, never what the step computes — so every
differential here asserts ``np.array_equal`` (bitwise), not allclose:

- tiered == untiered when the hot tier fits the entire working set
  (zero evictions — the cache is pure overhead accounting);
- tiered == untiered under eviction CHURN (a drifting id window forces
  dirty flushes and re-installs mid-run), for SGD and for the
  FTRL/AdaGrad slot-table planes riding the same residency map;
- a run killed mid-eviction (``embed_evict`` fault) resumes from its
  checkpoint bit-identical to the uninterrupted run — the merged
  checkpoint view never depends on an in-flight flush;
- a device loss mid-prefetch (``embed_prefetch`` fault on the producer
  thread) surfaces, and the restart is bit-identical too.

Plus the lever plumbing: ``tier_plan`` verdicts and the
``embed_tier='require'`` reject discipline on every non-tiered factory.
"""

import dataclasses
import os

import numpy as np
import pytest

from fm_spark_tpu import models, optim, sparse
from fm_spark_tpu.checkpoint import Checkpointer
from fm_spark_tpu.embed import TIERABLE_OPTIMIZERS, TieredTrainer, tier_plan
from fm_spark_tpu.resilience import faults
from fm_spark_tpu.train import TrainConfig, make_train_step

N_FEATURES = 2048
BUCKET_ROWS = 128            # 16 buckets
N_BUCKETS = N_FEATURES // BUCKET_ROWS
NNZ = 4
BATCH = 32


def make_spec():
    return models.FMSpec(num_features=N_FEATURES, rank=4, init_std=0.05)


def make_config(optimizer="sgd", hot_buckets=4, num_steps=12,
                embed_tier="require"):
    return TrainConfig(
        num_steps=num_steps, batch_size=BATCH, learning_rate=0.1,
        optimizer=optimizer, lr_schedule="constant", log_every=1000,
        embed_tier=embed_tier, hot_rows=hot_buckets * BUCKET_ROWS,
        embed_bucket_rows=BUCKET_ROWS, seed=0,
    )


class SkewedBatches:
    """Deterministic, resumable batch source with a bucket-local window.

    Each batch's ids land in ``window`` consecutive buckets; the window
    drifts one bucket every ``drift_every`` batches. Batch ``i`` is a
    pure function of ``(seed, i)``, so a restored cursor replays the
    exact stream — the property the kill/resume drills lean on. The
    window (not uniform ids) is what keeps a batch's working set inside
    the hot tier: ``begin_batch`` hard-fails otherwise, by design.
    """

    def __init__(self, window=3, drift_every=2, seed=11):
        self.window = window
        self.drift_every = drift_every
        self.seed = seed
        self.i = 0

    def state(self):
        return {"i": self.i}

    def restore(self, st):
        self.i = int(st["i"])

    def _batch(self, i):
        rng = np.random.default_rng(np.random.SeedSequence([self.seed, i]))
        base = (i // self.drift_every) % (N_BUCKETS - self.window)
        buckets = rng.integers(base, base + self.window, (BATCH, NNZ))
        offs = rng.integers(0, BUCKET_ROWS, (BATCH, NNZ))
        ids = (buckets * BUCKET_ROWS + offs).astype(np.int32)
        vals = rng.normal(0.0, 1.0, (BATCH, NNZ)).astype(np.float32)
        labels = (rng.random(BATCH) < 0.4).astype(np.float32)
        weights = np.ones(BATCH, np.float32)
        return ids, vals, labels, weights

    def __iter__(self):
        return self

    def __next__(self):
        b = self._batch(self.i)
        self.i += 1
        return b


def untiered_run(spec, config, num_steps, **adaptive_kw):
    """The stock in-HBM trajectory over the same stream — the bitwise
    reference every tiered run is held to."""
    import jax

    cfg_off = dataclasses.replace(config, embed_tier="off")
    params = spec.init(jax.random.key(config.seed))
    src = SkewedBatches()
    losses = []
    if config.optimizer == "sgd":
        step = sparse.make_sparse_sgd_step(spec, cfg_off)
        for i in range(num_steps):
            ids, vals, labels, w = next(src)
            params, loss = step(params, i, ids, vals, labels, w)
            losses.append(float(loss))
        return params, None, losses
    slots = optim.init_adaptive_slots(config.optimizer, spec, params)
    if config.optimizer == "ftrl":
        slots = optim.seed_ftrl_slots(
            slots, params, float(config.learning_rate),
            adaptive_kw.get("beta", 1.0))
    step = optim.make_sparse_adaptive_step(spec, cfg_off, **adaptive_kw)
    for _ in range(num_steps):
        ids, vals, labels, w = next(src)
        params, slots, loss = step(params, slots, ids, vals, labels, w)
        losses.append(float(loss))
    return params, slots, losses


def assert_params_equal(tiered, reference):
    for k in ("w0", "w", "v"):
        assert np.array_equal(np.asarray(tiered[k]),
                              np.asarray(reference[k])), (
            f"tiered plane {k!r} diverged from the in-HBM reference")


def assert_slots_equal(tiered, reference):
    for table in reference:
        for slot in reference[table]:
            assert np.array_equal(np.asarray(tiered[table][slot]),
                                  np.asarray(reference[table][slot])), (
                f"slot plane {table}.{slot} diverged")


# ------------------------------------------------------ bitwise differentials


def test_tiered_sgd_bitwise_when_hot_fits_working_set():
    """Hot tier sized over the whole touched set: zero evictions, and
    the trajectory is bitwise the untiered one."""
    spec = make_spec()
    config = make_config("sgd", hot_buckets=6, num_steps=8)
    trainer = TieredTrainer(spec, config)
    src = SkewedBatches(drift_every=10 ** 9)  # static 3-bucket window
    for _ in range(8):
        trainer.step_batch(*next(src))
    assert trainer.store.stats()["evictions"] == 0

    import jax

    ref = spec.init(jax.random.key(config.seed))
    step = sparse.make_sparse_sgd_step(
        spec, dataclasses.replace(config, embed_tier="off"))
    ref_src = SkewedBatches(drift_every=10 ** 9)
    for i in range(8):
        ids, vals, labels, w = next(ref_src)
        ref, _ = step(ref, i, ids, vals, labels, w)
    assert_params_equal(trainer.merged_params(), ref)


def test_tiered_sgd_bitwise_under_eviction_churn():
    """Hot tier sized to FORCE churn (4 buckets vs a drifting window):
    evictions/flushes/re-installs happen mid-run and the result is
    still bitwise identical — with the async prefetcher in the loop."""
    spec = make_spec()
    config = make_config("sgd", hot_buckets=4, num_steps=12)
    trainer = TieredTrainer(spec, config)
    trainer.fit(SkewedBatches(), num_steps=12, prefetch=3)
    st = trainer.store.stats()
    assert st["evictions"] > 0, "churn sizing failed to force evictions"
    # The prefetcher staged re-installs ahead (staged hits, not
    # blocking misses) — that is the point of the pipeline.
    assert st["staged_hits"] > 0 and st["hit_rate"] > 0.0

    ref_params, _, ref_losses = untiered_run(spec, config, 12)
    assert_params_equal(trainer.merged_params(), ref_params)
    assert trainer.loss_history == ref_losses


@pytest.mark.parametrize("optimizer", ["ftrl", "adagrad"])
def test_tiered_adaptive_bitwise_under_churn(optimizer):
    """The FTRL/AdaGrad slot tables (z/n) ride the SAME residency map:
    params AND slots bitwise-match the untiered run under churn."""
    spec = make_spec()
    config = make_config(optimizer, hot_buckets=4, num_steps=10)
    src = SkewedBatches()
    trainer = TieredTrainer(spec, config, beta=1.0)
    for _ in range(10):
        trainer.step_batch(*next(src))
    assert trainer.store.stats()["evictions"] > 0

    ref_params, ref_slots, ref_losses = untiered_run(
        spec, config, 10, beta=1.0)
    assert_params_equal(trainer.merged_params(), ref_params)
    assert_slots_equal(trainer.merged_slots(), ref_slots)
    assert trainer.loss_history == ref_losses


# ------------------------------------------------------------- crash drills


def test_kill_mid_eviction_resumes_bitwise(tmp_path):
    """The ``embed_evict`` fault fires BEFORE an eviction's dirty
    write-back — the kill-mid-eviction window. A resumed run must land
    bitwise on the uninterrupted trajectory: the merged checkpoint view
    never depended on the in-flight flush."""
    spec = make_spec()
    config = make_config("ftrl", hot_buckets=4, num_steps=14)
    golden_params, golden_slots, golden_losses = untiered_run(
        spec, config, 14, beta=1.0)

    ckdir = str(tmp_path / "ck")
    t1 = TieredTrainer(spec, config, beta=1.0)
    ck1 = Checkpointer(ckdir, save_every=4, async_save=False)
    faults.activate("embed_evict@5=error")
    try:
        with pytest.raises(faults.FaultInjected):
            t1.fit(SkewedBatches(), num_steps=14, checkpointer=ck1)
    finally:
        faults.clear()
    killed_at = t1.step_count
    assert 0 < killed_at < 14, "fault must interrupt mid-run"
    ck1.close()
    assert os.listdir(ckdir), "no checkpoint survived the kill"
    del t1

    t2 = TieredTrainer(spec, config, beta=1.0)
    ck2 = Checkpointer(ckdir, save_every=4, async_save=False)
    t2.fit(SkewedBatches(), num_steps=14, checkpointer=ck2)
    ck2.close()
    assert t2.step_count == 14
    assert_params_equal(t2.merged_params(), golden_params)
    assert_slots_equal(t2.merged_slots(), golden_slots)
    assert t2.loss_history[-1] == golden_losses[-1]


def test_device_loss_mid_prefetch_restarts_bitwise(tmp_path):
    """Chaos drill: the ``embed_prefetch`` fault point kills the device
    on the producer thread mid-staging. The loss surfaces at the
    consumer (never swallowed), and the dirty-mask flush discipline
    keeps the restored run bit-identical to a clean one."""
    spec = make_spec()
    config = make_config("sgd", hot_buckets=4, num_steps=14)
    golden_params, _, golden_losses = untiered_run(spec, config, 14)

    ckdir = str(tmp_path / "ck")
    t1 = TieredTrainer(spec, config)
    ck1 = Checkpointer(ckdir, save_every=4, async_save=False)
    faults.activate("embed_prefetch@7=device_loss")
    try:
        with pytest.raises(faults.FaultInjected) as ei:
            t1.fit(SkewedBatches(), num_steps=14, checkpointer=ck1,
                   prefetch=2)
    finally:
        faults.clear()
    assert faults.is_device_loss(ei.value)
    assert 0 < t1.step_count < 14
    ck1.close()
    del t1

    t2 = TieredTrainer(spec, config)
    ck2 = Checkpointer(ckdir, save_every=4, async_save=False)
    t2.fit(SkewedBatches(), num_steps=14, checkpointer=ck2, prefetch=2)
    ck2.close()
    assert t2.step_count == 14
    assert_params_equal(t2.merged_params(), golden_params)
    assert t2.loss_history[-1] == golden_losses[-1]


def test_embed_fault_points_registered():
    """Both tier fault points are first-class registry members (the
    fmlint registry-coverage rule requires every point to be exercised
    by name in tests/ — this file is that exercise)."""
    assert {"embed_prefetch", "embed_evict"} <= set(faults.KNOWN_POINTS)


# ------------------------------------------------------------ lever plumbing


def test_tier_plan_verdicts():
    spec = make_spec()
    mode, reason = tier_plan(spec, make_config("sgd"), "single")
    assert mode == "tiered" and "hot" in reason
    # Every refusal names its reason — the no-silent-fallback contract.
    for config, strategy, frag in [
        (make_config("sgd", embed_tier="off"), "single", "does not ask"),
        (make_config("adam"), "single", "no tiered sparse step"),
        (make_config("sgd"), "sharded", "single-attachment"),
        (make_config("sgd", hot_buckets=0), "single", "unset"),
        (make_config("sgd", hot_buckets=N_BUCKETS), "single",
         "nothing to tier"),
    ]:
        mode, reason = tier_plan(spec, config, strategy)
        assert mode is None and frag in reason
    mode, reason = tier_plan(
        dataclasses.replace(make_config("sgd"), hot_rows=100),
        make_config("sgd"), "single")  # wrong spec type
    assert mode is None


def test_tierable_optimizers_are_the_sparse_step_families():
    assert TIERABLE_OPTIMIZERS == ("sgd", "ftrl", "adagrad")


def test_require_rejected_by_every_non_tiered_factory():
    """embed_tier='require' must fail LOUDLY everywhere except the
    tiered trainer itself — same discipline as fused_embed."""
    spec = make_spec()
    config = make_config("sgd")
    with pytest.raises(ValueError, match="TieredTrainer"):
        make_train_step(spec, config)
    with pytest.raises(ValueError, match="TieredTrainer"):
        sparse.make_sparse_sgd_step(spec, config)
    with pytest.raises(ValueError, match="TieredTrainer"):
        optim.make_sparse_adaptive_step(spec, make_config("ftrl"))
    fspec = models.FieldFMSpec(
        num_features=768, num_fields=3, bucket=256, rank=4, init_std=0.05)
    with pytest.raises(ValueError, match="TieredTrainer"):
        sparse.make_field_sparse_sgd_body(
            fspec, dataclasses.replace(config, hot_rows=256))


def test_trainer_validates_its_config():
    spec = make_spec()
    with pytest.raises(ValueError, match="auto.*require"):
        TieredTrainer(spec, make_config("sgd", embed_tier="off"))
    with pytest.raises(ValueError, match="sparse step"):
        TieredTrainer(spec, make_config("adam"))
    with pytest.raises(ValueError, match="hot_rows > 0"):
        TieredTrainer(spec, make_config("sgd", hot_buckets=0))
    with pytest.raises(ValueError, match="divide"):
        TieredTrainer(spec, dataclasses.replace(
            make_config("sgd"), hot_rows=BUCKET_ROWS + 1))
    with pytest.raises(ValueError, match="nothing to tier"):
        TieredTrainer(spec, make_config("sgd", hot_buckets=N_BUCKETS))
    fspec = models.FieldFMSpec(
        num_features=768, num_fields=3, bucket=256, rank=4, init_std=0.05)
    with pytest.raises(ValueError, match="flat FM"):
        TieredTrainer(fspec, make_config("sgd"))


def test_invalid_embed_tier_value_rejected():
    spec = make_spec()
    config = dataclasses.replace(make_config("sgd"), embed_tier="maybe")
    with pytest.raises(ValueError, match="embed_tier"):
        sparse.make_sparse_sgd_step(spec, config)
