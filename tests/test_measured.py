"""MEASURED.json single-source-of-truth contract (VERDICT r4 Weak #1).

The committed MEASURED.json must load and validate; no measured rate may
be hard-coded in the artifact-producing paths (__graft_entry__.py,
projection.py); bench.py's update path must round-trip."""

import json
import os

import pytest

from fm_spark_tpu import measured

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_committed_measured_loads():
    data = measured.load_measured()
    assert data["headline"]["rate_samples_per_sec_per_chip"] > 0
    assert data["ffm_avazu"]["rate_samples_per_sec_per_chip"] > 0
    for key in ("headline", "ffm_avazu"):
        assert data[key]["source"], key
        assert data[key]["date"], key


def test_no_hardcoded_rates_in_artifact_paths():
    """Grep-clean (VERDICT r4 next-round #3): the dryrun/projection code
    must carry no literal measured rate — only MEASURED.json may."""
    for rel in ("__graft_entry__.py", "fm_spark_tpu/parallel/projection.py"):
        src = open(os.path.join(REPO, rel)).read()
        for lit in ("1_176_031", "1176031", "700_000", "1_059_", "1059000"):
            assert lit not in src, f"hard-coded measured rate {lit} in {rel}"


def test_update_headline_roundtrip(tmp_path):
    p = str(tmp_path / "MEASURED.json")
    # Seed with an existing file so the non-headline entry is preserved.
    with open(p, "w") as f:
        json.dump({"ffm_avazu": {"rate_samples_per_sec_per_chip": 1.0,
                                 "source": "s", "date": "d"}}, f)
    measured.update_headline(
        rate=123.0, vs_baseline=0.5, variant="v", source="test",
        attachment="fake", date="2026-07-30", path=p)
    data = measured.load_measured(p)
    assert data["headline"]["rate_samples_per_sec_per_chip"] == 123.0
    assert data["headline"]["vs_baseline"] == 0.5
    assert data["ffm_avazu"]["rate_samples_per_sec_per_chip"] == 1.0


def test_update_refuses_corrupt_existing(tmp_path):
    """A corrupt existing file must raise, not be silently rewritten
    with only the headline entry (destroying ffm_avazu provenance)."""
    p = str(tmp_path / "MEASURED.json")
    with open(p, "w") as f:
        f.write("{truncated")
    with pytest.raises(ValueError):
        measured.update_headline(
            rate=1.0, vs_baseline=None, variant="v", source="s",
            attachment="a", date="d", path=p)
    assert open(p).read() == "{truncated"


def test_load_rejects_missing_entry(tmp_path):
    p = str(tmp_path / "MEASURED.json")
    with open(p, "w") as f:
        json.dump({"headline": {"rate_samples_per_sec_per_chip": 1.0,
                                "source": "s", "date": "d"}}, f)
    with pytest.raises(ValueError, match="ffm_avazu"):
        measured.load_measured(p)


def test_load_rejects_bad_rate(tmp_path):
    p = str(tmp_path / "MEASURED.json")
    with open(p, "w") as f:
        json.dump({
            "headline": {"rate_samples_per_sec_per_chip": 0,
                         "source": "s", "date": "d"},
            "ffm_avazu": {"rate_samples_per_sec_per_chip": 1.0,
                          "source": "s", "date": "d"}}, f)
    with pytest.raises(ValueError, match="bad rate"):
        measured.load_measured(p)
