"""Unit coverage for the unified telemetry plane (ISSUE 7):
fm_spark_tpu/obs — span tracing, the process-wide metrics registry,
the flight recorder — plus the MetricsLogger facade (including the
PR-3 set_n_chips renormalization, previously untested), bench.py's
degraded-leg per-chip rate renormalization, and tools/obs_report.py's
rendering (per-run layout AND the pre-obs flat back-compat layout).
"""

import importlib.util
import json
import os
import sys
import threading

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from fm_spark_tpu import obs  # noqa: E402
from fm_spark_tpu.obs.flight import FlightRecorder, read_spool  # noqa: E402
from fm_spark_tpu.obs.metrics import (  # noqa: E402
    Histogram,
    MetricsRegistry,
)
from fm_spark_tpu.obs.trace import NOOP_SPAN, Tracer  # noqa: E402
from fm_spark_tpu.utils.logging import EventLog, MetricsLogger  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def obs_dir(tmp_path):
    """A configured obs plane torn down afterwards (the plane is
    process-global; leaking a configuration would cross-talk suites)."""
    d = tmp_path / "run"
    obs.configure(str(d), run_id="test-run")
    yield d
    obs.shutdown(reason=None)


class _ListSink:
    def __init__(self):
        self.records = []

    def emit(self, event, **fields):
        self.records.append({"event": event, **fields})


# ---------------------------------------------------------------- tracing


def test_span_nesting_parent_ids_and_attrs():
    sink = _ListSink()
    tr = Tracer(sink=sink)
    with tr.span("outer", a=1):
        with tr.span("inner") as sp:
            sp.set(rows=7)
    inner, outer = sink.records  # inner exits (and emits) first
    assert inner["name"] == "inner" and outer["name"] == "outer"
    assert inner["parent_id"] == outer["span_id"]
    assert outer["parent_id"] is None
    assert inner["rows"] == 7 and outer["a"] == 1
    assert inner["dur_ms"] >= 0.0


def test_span_exception_is_recorded_and_propagates():
    sink = _ListSink()
    tr = Tracer(sink=sink)
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("x")
    assert sink.records[0]["error"] == "ValueError"


def test_disabled_tracer_returns_the_shared_noop():
    tr = Tracer(sink=_ListSink(), enabled=False)
    assert tr.span("x") is NOOP_SPAN
    # Unconfigured module API: same no-op, no error.
    obs.shutdown(reason=None)
    assert obs.span("y") is NOOP_SPAN
    assert obs.enabled() is False


def test_emit_span_is_retroactive_and_parented():
    sink = _ListSink()
    tr = Tracer(sink=sink)
    with tr.span("outer"):
        tr.emit_span("window", 123.0, 2.5, steps=50)
    window, outer = sink.records
    assert window["t_start"] == 123.0
    assert window["dur_ms"] == 2500.0
    assert window["steps"] == 50
    assert window["parent_id"] == outer["span_id"]


def test_traced_decorator_binds_at_call_time(obs_dir):
    calls = []

    @obs.traced("deco/fn")
    def fn(x):
        calls.append(x)
        return x * 2

    assert fn(3) == 6
    obs.shutdown(reason=None)
    assert fn(4) == 8  # after shutdown: plain call, no error
    assert calls == [3, 4]


def test_spans_are_thread_local_parents():
    sink = _ListSink()
    tr = Tracer(sink=sink)
    seen = {}

    def worker():
        with tr.span("t2") as sp:
            seen["parent"] = sp.parent_id

    with tr.span("main"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    # The other thread's span must NOT parent onto main's open span.
    assert seen["parent"] is None


# ---------------------------------------------------------------- metrics


def test_counter_monotonic_and_negative_rejected():
    reg = MetricsRegistry()
    c = reg.counter("x")
    c.add(2)
    c.add(0.5)
    assert c.value == 2.5
    with pytest.raises(ValueError):
        c.add(-1)


def test_registry_same_name_same_instrument_kind_conflict_raises():
    reg = MetricsRegistry()
    assert reg.counter("a") is reg.counter("a")
    with pytest.raises(TypeError):
        reg.gauge("a")


def test_histogram_percentiles_bracket_uniform_data():
    h = Histogram("lat", buckets=(1, 2, 5, 10, 20, 50, 100))
    for v in range(1, 101):  # 1..100 ms uniform
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 100 and s["min"] == 1.0 and s["max"] == 100.0
    # Fixed-bucket estimates are coarse but must bracket the truth.
    assert 40 <= s["p50"] <= 60
    assert 90 <= s["p95"] <= 100
    assert 95 <= s["p99"] <= 100
    assert s["p50"] <= s["p95"] <= s["p99"] <= s["max"]


def test_histogram_overflow_bucket_and_empty_summary():
    h = Histogram("x", buckets=(1.0,))
    assert h.summary()["p50"] is None
    h.observe(50.0)  # above every bound -> overflow bucket
    s = h.summary()
    assert s["count"] == 1 and s["p99"] == 50.0


def test_snapshot_and_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("ingest.rows_ok_total").add(3)
    reg.gauge("train.n_chips").set(4)
    reg.histogram("step_time_ms", buckets=(10.0, 100.0)).observe(42.0)
    snap = reg.snapshot()
    assert snap["counters"]["ingest.rows_ok_total"] == 3
    assert snap["gauges"]["train.n_chips"] == 4
    assert snap["histograms"]["step_time_ms"]["count"] == 1
    text = reg.prometheus_text()
    assert "# TYPE fm_spark_ingest_rows_ok_total counter" in text
    assert "fm_spark_train_n_chips 4" in text
    # Native Prometheus HISTOGRAM exposition (ISSUE 14 — the live
    # /metrics endpoint serves real scrapers): cumulative le buckets,
    # the mandatory +Inf, _sum and _count.
    assert "# TYPE fm_spark_step_time_ms histogram" in text
    assert 'fm_spark_step_time_ms_bucket{le="10"} 0' in text
    assert 'fm_spark_step_time_ms_bucket{le="100"} 1' in text
    assert 'fm_spark_step_time_ms_bucket{le="+Inf"} 1' in text
    assert "fm_spark_step_time_ms_sum 42" in text
    assert "fm_spark_step_time_ms_count 1" in text


def test_prometheus_histogram_buckets_are_cumulative_and_ordered():
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms", buckets=(1.0, 5.0, 10.0))
    for v in (0.5, 0.7, 3.0, 7.0, 50.0):
        h.observe(v)
    text = reg.prometheus_text()
    lines = [ln for ln in text.splitlines()
             if ln.startswith("fm_spark_lat_ms_bucket")]
    # One line per bound + the +Inf catch-all, cumulative counts.
    assert lines == [
        'fm_spark_lat_ms_bucket{le="1"} 2',
        'fm_spark_lat_ms_bucket{le="5"} 3',
        'fm_spark_lat_ms_bucket{le="10"} 4',
        'fm_spark_lat_ms_bucket{le="+Inf"} 5',
    ]
    assert "fm_spark_lat_ms_count 5" in text


def test_prometheus_labels_attach_and_escape():
    """Label escaping per the exposition rules (ISSUE 14 satellite):
    backslash, double-quote and newline in a label VALUE must be
    escaped, and caller labels compose with the histogram's own
    ``le``."""
    reg = MetricsRegistry()
    reg.counter("c").add(1)
    reg.histogram("h_ms", buckets=(1.0,)).observe(0.5)
    text = reg.prometheus_text(
        labels={"run_id": 'r"1\\x\ny', "host": "a"})
    assert 'fm_spark_c{run_id="r\\"1\\\\x\\ny",host="a"} 1' in text
    assert ('fm_spark_h_ms_bucket{run_id="r\\"1\\\\x\\ny",host="a",'
            'le="1"} 1') in text
    # No labels -> bare sample names, no empty {}.
    assert "fm_spark_c 1" in reg.prometheus_text()


def test_prometheus_large_counter_keeps_full_precision():
    """'%g' would quantize a 9-digit counter to 6 significant digits,
    making small increments invisible to rate() between scrapes — the
    live endpoint serves full-precision values."""
    reg = MetricsRegistry()
    reg.counter("rows_total").add(123_456_789)
    reg.gauge("g").set(123_456_789.25)
    text = reg.prometheus_text()
    assert "fm_spark_rows_total 123456789" in text
    assert "fm_spark_g 123456789.25" in text


def test_export_jsonl_appends_parseable_snapshots(tmp_path):
    reg = MetricsRegistry()
    path = str(tmp_path / "metrics.jsonl")
    reg.counter("c").add(1)
    reg.export_jsonl(path)
    reg.counter("c").add(1)
    reg.export_jsonl(path)
    with open(path) as f:
        lines = [json.loads(ln) for ln in f]
    assert [ln["counters"]["c"] for ln in lines] == [1, 2]


# ----------------------------------------------------------------- flight


def test_flight_ring_is_bounded_and_spool_compacts(tmp_path):
    spool = str(tmp_path / "flight.jsonl")
    fr = FlightRecorder(capacity=8, spool_path=spool)
    for i in range(40):
        fr.record("tick", i=i)
    events = fr.events()
    assert len(events) == 8
    assert [e["i"] for e in events] == list(range(32, 40))
    # The spool compacted (2N trigger) — bounded, never the full 40.
    on_disk = read_spool(spool)
    assert len(on_disk) <= 16
    assert on_disk[-1]["i"] == 39
    fr.close()


def test_flight_reopen_continues_seq_and_window(tmp_path):
    spool = str(tmp_path / "flight.jsonl")
    fr = FlightRecorder(capacity=4, spool_path=spool)
    for i in range(6):
        fr.record("a", i=i)
    last_seq = fr.events()[-1]["seq"]
    fr.close()
    # A retried attempt re-entering the same run dir: window continuous.
    fr2 = FlightRecorder(capacity=4, spool_path=spool)
    assert [e["i"] for e in fr2.events()] == [2, 3, 4, 5]
    rec = fr2.record("b")
    assert rec["seq"] == last_seq + 1
    fr2.close()


def test_flight_dump_is_atomic_json_with_metrics(tmp_path):
    spool = str(tmp_path / "flight.jsonl")
    fr = FlightRecorder(capacity=4, spool_path=spool)
    fr.record("x")
    path = fr.dump("unit_test", extra={"run_id": "r"})
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "unit_test" and doc["run_id"] == "r"
    assert doc["events"][0]["kind"] == "x"
    assert "metrics" in doc
    fr.close()


def test_flight_record_keeps_caller_ts(tmp_path):
    fr = FlightRecorder(capacity=4)
    rec = fr.record("mirrored", ts=123.456)
    assert rec["ts"] == 123.456


def test_read_spool_skips_torn_tail(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text('{"seq": 0, "kind": "a"}\n{"seq": 1, "ki')
    recs = read_spool(str(p))
    assert len(recs) == 1 and recs[0]["kind"] == "a"


def test_flight_compaction_enospc_reestablishes_append_and_seq(tmp_path):
    """ISSUE 20 satellite: the compaction rewrite hits injected
    ENOSPC. The recorder must come out APPENDING (handle
    re-established, counter reset — never a closed handle silently
    eating every later write), the failure must be counted as
    best-effort degradation, and after a subsequent SIGKILL-style
    abandonment the reopened spool's seq column is continuous —
    strictly increasing, no fork."""
    from fm_spark_tpu.resilience import faults
    from fm_spark_tpu.utils import durable

    spool = str(tmp_path / "flight.jsonl")
    fr = FlightRecorder(capacity=4, spool_path=spool)
    durable.reset_failure_counts()
    try:
        # Appends are obs-class occurrences 1..8; the 8th record
        # crosses the 2N threshold, so the compaction's atomic
        # rewrite is occurrence 9.
        faults.activate("io_write.obs@9=enospc")
        for i in range(8):
            fr.record("tick", i=i)
    finally:
        faults.clear()
    counts = durable.io_failure_counts()
    assert counts["obs"] == 1 and counts["best_effort"] == 1
    # The failed rewrite left the OLD spool intact and the recorder
    # appending: later records land on disk.
    assert fr._spool is not None
    n_before = len(read_spool(spool))
    fr.record("after_enospc", i=8)
    assert len(read_spool(spool)) == n_before + 1
    last_seq = fr.events()[-1]["seq"]
    # SIGKILL-style ending: no close(), no dump — just gone.
    del fr
    fr2 = FlightRecorder(capacity=4, spool_path=spool)
    rec = fr2.record("reborn")
    assert rec["seq"] == last_seq + 1
    seqs = [r["seq"] for r in read_spool(spool) if "seq" in r]
    assert seqs == sorted(set(seqs)), "spool seq forked or regressed"
    fr2.close()


# ----------------------------------------------- module facade / EventLog


def test_configure_emits_all_streams_and_shutdown_dumps(tmp_path):
    d = tmp_path / "run"
    rid = obs.configure(str(d), run_id="r42")
    assert rid == "r42" and obs.enabled() and obs.run_dir() == str(d)
    with obs.span("phase/a"):
        pass
    obs.counter("c").add(1)
    obs.event("failure", error="boom")
    obs.shutdown()  # default reason="run_end" -> snapshot + dump
    names = sorted(os.listdir(d))
    assert names == ["flight.jsonl", "flight_dump.json",
                     "metrics.jsonl", "trace.jsonl"]
    with open(d / "trace.jsonl") as f:
        span = json.loads(f.readline())
    assert span["event"] == "span" and span["name"] == "phase/a"
    with open(d / "flight_dump.json") as f:
        doc = json.load(f)
    assert doc["reason"] == "run_end"
    assert any(e["kind"] == "failure" for e in doc["events"])
    assert doc["metrics"]["counters"]["c"] == 1


def test_fault_timeline_filters_and_orders(obs_dir):
    obs.event("failure", error="x")
    obs.event("not_a_fault_kind")
    obs.event("backoff", delay_s=1.0)
    tl = obs.fault_timeline()
    assert [e["kind"] for e in tl] == ["failure", "backoff"]


def test_telemetry_block_shape(obs_dir):
    h = obs.histogram("step_time_ms")
    for v in (10.0, 20.0, 30.0):
        h.observe(v)
    obs.counter("ingest.rows_ok_total").add(5)
    obs.gauge("ingest.rows_per_sec").set(123.0)
    obs.event("ingest_aborted", frac=0.5)
    block = obs.telemetry_block()
    assert block["run_id"] == "test-run"
    assert block["step_time_ms"]["count"] == 3
    assert block["step_time_ms"]["p50"] is not None
    assert block["ingest_rows_per_sec"] == 123.0
    assert block["ingest_rows_total"] == 5
    assert block["fault_events"][-1]["kind"] == "ingest_aborted"
    json.dumps(block)  # must be JSON-ready as stamped by bench.py


def test_eventlog_mirror_to_flight_keeps_ts(obs_dir):
    log = EventLog(stream=None, mirror_to_flight=True)
    rec = log.emit("failure", error="boom")
    tl = obs.fault_timeline()
    assert tl and tl[-1]["kind"] == "failure"
    # The mirrored copy carries the journal's ORIGINAL stamp, so the
    # report's (ts, kind) de-duplication sees one transition, not two.
    assert tl[-1]["ts"] == rec["ts"]


# ------------------------------------------------------ MetricsLogger


class _Sink:
    def __init__(self):
        self.lines = []

    def write(self, s):
        self.lines.append(s)

    def flush(self):
        pass


def test_metrics_logger_publishes_registry_instruments():
    obs.registry().reset()
    logger = MetricsLogger(stream=_Sink(), n_chips=2)
    logger.log(0, samples=1000)
    logger.log(1, samples=1000, loss=0.5)
    reg = obs.registry()
    assert reg.counter("train.samples_total").value == 2000
    rate = reg.gauge("train.samples_per_sec").value
    per_chip = reg.gauge("train.samples_per_sec_per_chip").value
    assert rate is not None and per_chip == pytest.approx(rate / 2)
    assert reg.gauge("train.n_chips").value == 2
    assert reg.gauge("train.loss").value == 0.5


def test_set_n_chips_renormalizes_per_chip_rate(monkeypatch):
    """The PR-3 elastic-shrink path: after ``set_n_chips(2)`` the
    SAME global rate must report a 2x larger per-chip figure (honest
    per-SURVIVING-chip accounting), previously untested."""
    import fm_spark_tpu.utils.logging as fl

    obs.registry().reset()
    t = {"now": 100.0}
    monkeypatch.setattr(fl.time, "perf_counter", lambda: t["now"])
    logger = MetricsLogger(stream=_Sink(), n_chips=8)
    logger.log(0, samples=8000)          # arms the window
    t["now"] += 1.0
    rec8 = logger.log(1, samples=8000)   # 8000 samples/s over 8 chips
    assert rec8["samples_per_sec_per_chip"] == pytest.approx(1000.0)
    logger.set_n_chips(2)                # elastic shrink 8 -> 2
    assert obs.registry().gauge("train.n_chips").value == 2
    t["now"] += 1.0
    rec2 = logger.log(2, samples=8000)
    assert rec2["samples_per_sec"] == pytest.approx(8000.0)
    assert rec2["samples_per_sec_per_chip"] == pytest.approx(4000.0)
    # Floor: a zero/negative count clamps to 1, never divides by zero.
    logger.set_n_chips(0)
    t["now"] += 1.0
    assert logger.log(3, samples=100)["samples_per_sec_per_chip"] == \
        pytest.approx(100.0)


def test_trainer_step_time_window_instrumentation(obs_dir):
    """The trainer's ENABLED path: steady-state step time lands as the
    per-log-window mean (fenced at the window's loss fetch — per-step
    host timing would record async dispatch, not device time), the
    compile step rides train.first_step_ms + the compile_split event
    and is excluded from the steady-state histogram, and each window
    emits one retroactive train/steps span."""
    from fm_spark_tpu import models
    from fm_spark_tpu.data import Batches, synthetic_ctr
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    ids, vals, labels = synthetic_ctr(640, 50, 4, seed=0)
    spec = models.FMSpec(num_features=50, rank=2)
    config = TrainConfig(num_steps=20, batch_size=64, learning_rate=0.1,
                         log_every=10, seed=0)
    trainer = FMTrainer(spec, config)
    trainer.fit(Batches(ids, vals, labels, 64, seed=0))

    reg = obs.registry()
    first = reg.histogram("train.first_step_ms")
    assert first.count == 1
    step_hist = reg.histogram("step_time_ms")
    assert step_hist.count == 2  # two log windows; compile step excluded
    assert step_hist.max < first.max  # window mean never holds the compile

    obs.shutdown()  # flush the trace sink
    with open(obs_dir / "trace.jsonl") as f:
        spans = [json.loads(ln) for ln in f if ln.strip()]
    windows = [s for s in spans if s.get("name") == "train/steps"]
    # First window: 9 steps — its timer restarts after the compile
    # step, and the span counts only what its duration covers.
    assert [w["steps"] for w in windows] == [9, 10]
    assert all(w["dur_ms"] > 0 for w in windows)
    split = [e for e in read_spool(str(obs_dir / "flight.jsonl"))
             if e.get("kind") == "compile_split"]
    assert len(split) == 1
    assert split[0]["fresh_compiles"] >= 0


def test_bench_renormalize_results_degraded_denominator():
    """bench.py's elastic accounting: rates banked before a shrink are
    re-normalized onto the SURVIVING-chip denominator so max() ranks
    every leg on comparable per-chip figures."""
    results = [(1000.0, "a", 1.0, 0.5), (900.0, "b", 1.1, 0.6)]
    out = bench._renormalize_results(results, prev_chips=8, n_chips=4)
    assert [r for r, *_ in out] == [2000.0, 1800.0]
    # Labels/dt/loss ride through untouched.
    assert [lb for _, lb, _, _ in out] == ["a", "b"]
    # No shrink -> identity (a fresh list, not the same object).
    same = bench._renormalize_results(results, 4, 4)
    assert same == results and same is not results


# ------------------------------------------------------------ obs_report


def _load_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report_tool", os.path.join(REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_renders_a_real_run_dir(tmp_path, capsys):
    d = tmp_path / "run"
    obs.configure(str(d), run_id="report-run")
    with obs.span("train/steps", steps=10):
        pass
    obs.histogram("step_time_ms").observe(12.0)
    obs.event("failure", error="InjectedDeviceLoss")
    obs.event("backoff", delay_s=0.5)
    obs.export_snapshot()
    obs.shutdown()
    report = _load_report()
    assert report.main([str(d)]) == 0
    out = capsys.readouterr().out
    assert "report-run" in out
    assert "train/steps" in out
    assert "step_time_ms" in out
    assert "failure" in out and "backoff" in out
    # One transition = ONE timeline row (journal/flight dedup works).
    assert out.count("InjectedDeviceLoss") == 1


def test_obs_report_backcompat_flat_artifacts_layout(tmp_path, capsys):
    """Pointed at a PRE-obs artifacts dir (flat health_<model>.jsonl +
    deadletter.jsonl, no trace), the report still renders the fault
    timeline and quarantine sections (ISSUE 7 back-compat satellite)."""
    art = tmp_path / "artifacts"
    art.mkdir()
    with open(art / "health_fm.jsonl", "w") as f:
        f.write(json.dumps({"ts": 1.0, "event": "failure",
                            "error": "DeviceLost"}) + "\n")
        f.write(json.dumps({"ts": 2.0, "event": "backoff",
                            "delay_s": 3.0}) + "\n")
    with open(art / "deadletter.jsonl", "w") as f:
        f.write(json.dumps({"ts": 1.5, "event": "bad_record",
                            "reason": "label_unparseable"}) + "\n")
    report = _load_report()
    assert report.main([str(art)]) == 0
    out = capsys.readouterr().out
    assert "failure" in out and "DeviceLost" in out
    assert "Quarantine" in out and "label_unparseable" in out
    assert "no span trace" in out


def test_obs_report_latest_picks_newest_run(tmp_path, capsys):
    root = tmp_path / "obs"
    for name, ts in (("old", 100.0), ("new", 200.0)):
        d = root / name
        d.mkdir(parents=True)
        os.utime(d, (ts, ts))
    report = _load_report()
    assert report.main(["--latest", str(root)]) == 0
    assert "obs/new" in capsys.readouterr().out.replace(os.sep, "/")


def test_obs_report_run_id_selector(tmp_path, capsys):
    """ISSUE 14 satellite: ``--run-id`` picks a run by NAME — the
    mtime-based --latest is wrong while a serve daemon keeps its run
    dir hot (the OLD run the operator wants to read is not the newest
    directory)."""
    root = tmp_path / "obs"
    for name, ts in (("wanted", 100.0), ("hot-daemon", 200.0)):
        d = root / name
        d.mkdir(parents=True)
        os.utime(d, (ts, ts))
    report = _load_report()
    assert report.main(["--run-id", "wanted", str(root)]) == 0
    assert "obs/wanted" in capsys.readouterr().out.replace(os.sep, "/")
    assert report.main(["--run-id", "absent", str(root)]) == 1
    assert "absent" in capsys.readouterr().err


def test_obs_report_renders_deep_captures(tmp_path, capsys):
    """ISSUE 14: capture bundles under <run>/captures/ get a Deep
    captures section — trigger, profiler status, context, bundle
    path."""
    d = tmp_path / "run"
    d.mkdir()
    bundle = d / "captures" / "sentinel_regressed_001"
    bundle.mkdir(parents=True)
    (bundle / "capture.json").write_text(json.dumps({
        "trigger": "sentinel_regressed", "seq": 1, "run_id": "x",
        "ts": 1.0, "context": {"leg": "t", "z": -8.1},
        "profiler": {"status": "skipped: jax not loaded"},
    }))
    report = _load_report()
    assert report.main([str(d)]) == 0
    out = capsys.readouterr().out
    assert "## Deep captures (1 bundle(s))" in out
    assert "sentinel_regressed" in out and "z=-8.1" in out
    assert "profiler=skipped: jax not loaded" in out


def test_obs_report_renders_kernel_pricing(tmp_path, capsys):
    """ISSUE 9 satellite: a run dir carrying bench_kernels.py's
    kernel_pricing.json gets a pricing table in the report — measured
    ms next to the bytes-model GB/s, skips shown as skips."""
    d = tmp_path / "run"
    d.mkdir()
    with open(d / "kernel_pricing.json", "w") as f:
        json.dump({
            "tool": "bench_kernels", "backend": "tpu",
            "interpret": False,
            "kernels": [
                {"kernel": "fm_bwd_fused_pallas", "family": "fused_bwd",
                 "ms": 3.2, "bytes_moved_model": 120_000_000,
                 "model_gbps": 37.5},
                {"kernel": "ffm_sel", "family": "ffm_sel",
                 "skipped": "lane limit"},
            ]}, f)
    report = _load_report()
    assert report.main([str(d)]) == 0
    out = capsys.readouterr().out
    assert "Kernel pricing" in out and "backend=tpu" in out
    assert "fm_bwd_fused_pallas" in out and "37.50" in out
    assert "skipped: lane limit" in out
    # Interpret-mode pricing is labeled as emulation overhead.
    with open(d / "kernel_pricing.json", "w") as f:
        json.dump({"backend": "cpu", "interpret": True,
                   "kernels": [{"kernel": "k", "family": "f",
                                "ms": 1.0, "model_gbps": 2.0}]}, f)
    assert report.main([str(d)]) == 0
    assert "INTERPRET" in capsys.readouterr().out


def test_bench_kernels_prices_into_run_dir_and_ledger(tmp_path, capsys):
    """ISSUE 9: bench_kernels writes kernel_pricing.json under the run
    dir AND appends each row to the sibling cross-run ledger as a
    sentinel-judged kernel_pricing record (value = model GB/s); the
    report renders the real file."""
    import subprocess

    run_dir = tmp_path / "obs" / "runX"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_kernels.py"),
         "--scale", "64", "--families", "gather", "--iters", "1",
         "--report-dir", str(run_dir)],
        capture_output=True, text=True, cwd=REPO, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.load(open(run_dir / "kernel_pricing.json"))
    assert doc["run_id"] and len(doc["kernels"]) == 2
    ledger = [json.loads(ln) for ln in
              (tmp_path / "obs" / "ledger.jsonl").read_text()
              .splitlines()]
    pricing = [r for r in ledger if r["kind"] == "kernel_pricing"]
    assert len(pricing) == 2
    for rec in pricing:
        assert rec["leg"] == "kernel/gather"
        assert rec["run_id"] == doc["run_id"]
        assert rec["value"] > 0 and rec["unit"] == "GB/s"
        assert rec["fingerprint"]["device_kind"] == "cpu"
    # ISSUE 14: each priced row ALSO lands a cost_attribution record
    # (measured ms x bytes model) in the one kind the autotuner reads.
    cost = [r for r in ledger if r["kind"] == "cost_attribution"]
    assert len(cost) == 2
    for rec in cost:
        assert rec["leg"] == "cost/kernel/gather"
        assert rec["step_ms"] > 0 and rec["bytes_per_step"] > 0
        assert rec["unit"] == "GB/s(model)"
    assert len(ledger) == 4
    report = _load_report()
    assert report.main([str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "Kernel pricing" in out and "gather_pallas" in out


# ------------------------------------------------- device-memory gauges


def test_device_memory_snapshot_sets_gauges():
    """ISSUE 9: the watermark helper publishes the live-buffer total
    (and, where the backend provides memory_stats, the HBM in-use/peak
    pair) into the registry. On the CPU test backend live_arrays is
    the guaranteed signal."""
    import jax.numpy as jnp

    obs.registry().reset()
    keep = jnp.ones((1024,), jnp.float32)  # noqa: F841 — a live buffer
    snap = obs.device_memory_snapshot()
    assert snap is not None
    assert snap["live_buffer_bytes"] >= 4096
    assert obs.registry().gauge("device.live_buffer_bytes").value \
        == snap["live_buffer_bytes"]
    # The telemetry block carries the watermark gauges.
    block = obs.telemetry_block()
    assert block["device_memory"]["live_buffer_bytes"] \
        == snap["live_buffer_bytes"]


def test_device_memory_snapshot_without_jax_is_none(monkeypatch):
    """The helper never IMPORTS jax (the light-parent contract): with
    jax absent from sys.modules it reports None instead of importing
    a backend."""
    import sys as _sys

    monkeypatch.setitem(_sys.modules, "jax", None)
    # sys.modules.get returns None -> treated as not loaded.
    assert obs.device_memory_snapshot() is None
