"""Acceptance: the bench_embed ladder's tiny CPU smoke, end to end.

ISSUE 16's CI wiring: ``bench_embed.py --scale tiny`` runs the REAL
ladder code path (tiered trainer + prefetcher + eviction churn + the
bitwise parity differential + ledger/sentinel/cost records) over a
small feature axis, so tier-1 exercises everything but the scale. One
subprocess run, then structural asserts over its JSON result and the
ledger rows it appended.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny_run(tmp_path_factory):
    art = tmp_path_factory.mktemp("embed_art")
    out = art / "result.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_embed.py"),
         "--scale", "tiny", "--art-dir", str(art), "--out", str(out)],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert proc.returncode == 0, (
        f"bench_embed tiny smoke failed\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}")
    result = json.loads(out.read_text())
    return art, result


def test_tiny_ladder_measures_every_rung(tiny_run):
    _, result = tiny_run
    assert result["bench"] == "embed"
    assert len(result["rungs"]) == len(result["decades"]) >= 2
    for rung in result["rungs"]:
        assert rung["leg"].startswith("embed_rows_")
        assert rung["rows_per_sec"] > 0
        assert 0.0 < rung["hit_rate"] <= 1.0
        # The tiny smoke is sized to cross hot capacity: the evict/flush
        # path runs, it is not just an install benchmark.
        assert rung["evictions"] > 0
        assert rung["host_rss_bytes"] > 0


def test_tiny_ladder_asserts_bitwise_parity(tiny_run):
    _, result = tiny_run
    assert result["parity_checked"] and result["parity_ok"]
    checked = [r for r in result["rungs"] if r["parity_checked"]]
    assert checked and all(r["parity_ok"] for r in checked)


def test_tiny_ladder_bounds_host_rss_via_lazy_cold(tiny_run):
    """Rungs above --parity-max run the lazy cold store: materialized
    cold bytes must track the TOUCHED buckets, not the feature axis."""
    _, result = tiny_run
    lazy = [r for r in result["rungs"] if r["cold_mode"] == "lazy"]
    assert lazy, "tiny ladder must include a lazy (beyond-parity) rung"
    for rung in lazy:
        full_axis = rung["num_features"] * 4  # >= 4 bytes/row just for w
        assert rung["cold_host_bytes"] < full_axis
        assert rung["touched_buckets"] < rung["num_features"] // result[
            "bucket_rows"]


def test_tiny_ladder_writes_embed_bench_and_cost_records(tiny_run):
    art, result = tiny_run
    ledger = os.path.join(str(art), "obs", "ledger.jsonl")
    records = []
    with open(ledger) as f:
        for line in f:
            records.append(json.loads(line))
    embed = [r for r in records if r["kind"] == "embed_bench"]
    cost = [r for r in records if r["kind"] == "cost_attribution"]
    assert {r["leg"] for r in embed} == {
        r["leg"] for r in result["rungs"]}
    for r in embed:
        # The embed_bench cohort contract: own leg namespace, full
        # provenance, rows/s as the higher-is-better value.
        assert r["leg"].startswith("embed_rows_")
        assert r["fingerprint"]["key"]
        assert r["value"] > 0 and r["unit"] == "rows/s"
        assert "hit_rate" in r and "stall_ms" in r
    assert {r["leg"] for r in cost} == {
        f"cost/{r['leg']}" for r in result["rungs"]}
    for r in cost:
        fams = r["families"]
        assert fams["h2d_bucket_install"] > 0
        assert r["bytes_per_step"] > 0 and r["assumptions"]
