"""Per-coordinate optimizer tests (ISSUE 13): FTRL-Proximal and
AdaGrad in both forms — the dense optax transformation and the sparse
dedup/scatter row step — held to one contract:

- **exact laziness** — an untouched coordinate is BIT-unchanged (FTRL's
  closed form reproduces the stored weight because ``ftrl_init_z``
  seeds ``z`` from the init; AdaGrad's zero-gradient step is zero), so
  the sparse step equals the dense transformation on every touched
  coordinate and leaves the rest alone;
- **slots ride checkpoints** — an FMTrainer kill-and-resume with FTRL
  replays bit-identical losses (the z/n slots are opt_state like any
  other);
- **no silent fallbacks** — the fused field families keep rejecting
  adaptive optimizers; the adaptive step rejects the lazy-L2 triple.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fm_spark_tpu import models, optim
from fm_spark_tpu.train import FMTrainer, TrainConfig, make_optimizer


def _fresh(params0):
    return jax.tree_util.tree_map(jnp.array, params0)


def _data(num_features=64, B=32, nnz=3, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, num_features, size=(B, nnz)).astype(np.int32)
    vals = np.ones((B, nnz), np.float32)
    labels = (rng.random(B) < 0.5).astype(np.float32)
    w = np.ones(B, np.float32)
    return ids, vals, labels, w


def test_ftrl_zero_grad_is_a_fixpoint():
    """The init-preservation contract: with z seeded by ftrl_init_z, a
    zero gradient leaves every coordinate bit-meaningfully unchanged —
    without it FTRL zeroes FM factors on first touch and the
    interaction gradient dies forever."""
    import optax

    spec = models.FMSpec(num_features=32, rank=4, init_std=0.05)
    params = spec.init(jax.random.key(0))
    tx = make_optimizer(TrainConfig(optimizer="ftrl",
                                    learning_rate=0.1))
    st = tx.init(params)
    zero = jax.tree_util.tree_map(jnp.zeros_like, params)
    upd, _ = tx.update(zero, st, params)
    p2 = optax.apply_updates(params, upd)
    for k in params:
        np.testing.assert_allclose(np.asarray(p2[k]),
                                   np.asarray(params[k]), atol=1e-6)


def test_ftrl_l1_shrinks_small_coordinates_to_exact_zero():
    rows = jnp.ones((4, 2), jnp.float32) * 0.01
    z = jnp.zeros((4, 2))
    n = jnp.zeros((4, 2))
    g = jnp.full((4, 2), 1e-4)
    new_rows, z2, n2 = optim.ftrl_rows(rows, z, n, g, alpha=0.1,
                                       beta=1.0, l1=1.0, l2=0.0)
    assert np.all(np.asarray(new_rows) == 0.0)  # proximal hard zero
    assert np.all(np.asarray(n2) > 0)


@pytest.mark.parametrize("optimizer", ["ftrl", "adagrad"])
def test_sparse_adaptive_step_matches_dense_on_touched_rows(optimizer):
    """The sparse step rides the dedup scatter path; per-coordinate
    totals via segment sums make it equal the dense per-coordinate rule
    on every touched coordinate, while untouched rows stay bit-frozen
    (the lazy contract, mirroring the sparse-SGD step's)."""
    spec = models.FMSpec(num_features=64, rank=4, init_std=0.05,
                         use_bias=False)
    params0 = jax.tree_util.tree_map(
        np.asarray, spec.init(jax.random.key(0)))
    cfg = TrainConfig(optimizer=optimizer, learning_rate=0.1,
                      lr_schedule="constant")
    step = optim.make_sparse_adaptive_step(spec, cfg)
    slots = optim.init_adaptive_slots(optimizer, spec, _fresh(params0))
    if optimizer == "ftrl":
        slots = optim.seed_ftrl_slots(slots, _fresh(params0), 0.1, 1.0)
    ids, vals, labels, w = _data()

    # Dense per-coordinate reference: for ftrl the optax transform; for
    # adagrad the same rule applied to the dense analytic gradient.
    p_s = _fresh(params0)
    sl = slots
    if optimizer == "ftrl":
        import optax

        from fm_spark_tpu.train import make_train_step

        dstep = make_train_step(spec, cfg)
        p_d = _fresh(params0)
        o_d = make_optimizer(cfg).init(p_d)
        for _ in range(5):
            p_d, o_d, m = dstep(p_d, o_d, jnp.asarray(ids),
                                jnp.asarray(vals), jnp.asarray(labels),
                                jnp.asarray(w))
            p_s, sl, loss = step(p_s, sl, jnp.asarray(ids),
                                 jnp.asarray(vals), jnp.asarray(labels),
                                 jnp.asarray(w))
            np.testing.assert_allclose(float(m["loss"]), float(loss),
                                       rtol=2e-5)
        dense = {k: np.asarray(v) for k, v in p_d.items()}
    else:
        # numpy float64-ish dense AdaGrad over the analytic FM grad.
        from fm_spark_tpu.ops import losses as losses_lib

        per_loss = losses_lib.loss_fn(spec.loss)

        def dense_grads(p):
            def f(pt):
                scores = spec.scores(pt, jnp.asarray(ids),
                                     jnp.asarray(vals))
                per = per_loss(scores, jnp.asarray(labels)) \
                    * jnp.asarray(w)
                return jnp.sum(per) / jnp.maximum(jnp.sum(
                    jnp.asarray(w)), 1.0)

            return jax.grad(f)(p)

        p_d = _fresh(params0)
        n_acc = {k: np.zeros(np.shape(v), np.float32)
                 for k, v in params0.items() if k in ("v", "w")}
        for _ in range(5):
            g = dense_grads(p_d)
            newp = dict(p_d)
            for k in ("v", "w"):
                gk = np.asarray(g[k], np.float32)
                n_acc[k] = n_acc[k] + gk * gk
                stepk = 0.1 * gk / (np.sqrt(n_acc[k])
                                    + optim.ADAGRAD_EPS)
                newp[k] = jnp.asarray(np.asarray(p_d[k]) - stepk)
            p_d = newp
            p_s, sl, _ = step(p_s, sl, jnp.asarray(ids),
                              jnp.asarray(vals), jnp.asarray(labels),
                              jnp.asarray(w))
        dense = {k: np.asarray(v) for k, v in p_d.items()}

    touched = np.unique(ids)
    untouched = np.setdiff1d(np.arange(64), touched)
    for k in ("v", "w"):
        np.testing.assert_allclose(dense[k][touched],
                                   np.asarray(p_s[k])[touched],
                                   atol=3e-5)
        # Lazy contract: untouched rows bit-identical to the init.
        assert np.array_equal(np.asarray(p_s[k])[untouched],
                              params0[k][untouched])


def test_duplicate_ids_update_schedule_exactly_once():
    """A duplicated id within the batch must see its TOTAL gradient
    once (segment-summed), not two half-updates: adaptive rules are
    read-modify-write, and double-counting would double the
    per-coordinate schedule (n would grow twice as fast)."""
    spec = models.FMSpec(num_features=16, rank=2, init_std=0.05,
                         use_bias=False, use_linear=False)
    params0 = jax.tree_util.tree_map(
        np.asarray, spec.init(jax.random.key(1)))
    cfg = TrainConfig(optimizer="adagrad", learning_rate=0.1,
                      lr_schedule="constant")
    step = optim.make_sparse_adaptive_step(spec, cfg)
    # Batch of 2 rows activating the SAME id in one column.
    ids = np.array([[3, 7], [3, 9]], np.int32)
    vals = np.ones((2, 2), np.float32)
    labels = np.array([1.0, 0.0], np.float32)
    w = np.ones(2, np.float32)
    slots = optim.init_adaptive_slots("adagrad", spec, _fresh(params0))
    _, sl2, _ = step(_fresh(params0), slots, jnp.asarray(ids),
                     jnp.asarray(vals), jnp.asarray(labels),
                     jnp.asarray(w))
    n3 = np.asarray(sl2["v"]["n"])[3]
    assert np.all(n3 > 0)
    # n must be (g_a + g_b)^2 per coordinate — recompute analytically.
    rows = params0["v"][ids]
    xv = rows * vals[..., None]
    s = xv.sum(axis=1)
    scores = 0.5 * ((s * s).sum(-1) - (xv * xv).sum((1, 2)))
    p = 1.0 / (1.0 + np.exp(-scores))
    dsc = (p - labels) / 2.0
    g_rows = dsc[:, None, None] * vals[..., None] * (s[:, None, :] - xv)
    g3 = g_rows[0, 0] + g_rows[1, 0]  # both lanes hit id 3
    np.testing.assert_allclose(n3, g3 * g3, rtol=1e-5)


def test_ftrl_slots_ride_checkpoints_bit_identical(tmp_path):
    """Kill-and-resume continuity with per-coordinate slots: an FTRL
    FMTrainer checkpointed mid-run resumes with a loss curve
    bit-identical to the uninterrupted one — the z/n slots are
    opt_state, so the chain carries them like any other state."""
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data import Batches, synthetic_ctr

    spec = models.FMSpec(num_features=128, rank=4, init_std=0.05)
    cfg = TrainConfig(num_steps=12, batch_size=32, learning_rate=0.1,
                      lr_schedule="constant", optimizer="ftrl",
                      log_every=1)
    ids, vals, labels = synthetic_ctr(256, 128, 3, seed=5)

    def run(ck_dir, stop_at=None):
        tr = FMTrainer(spec, cfg)
        tr.logger._stream = None
        ck = Checkpointer(str(ck_dir), save_every=4, async_save=False)
        b = Batches(ids, vals, labels, 32, seed=1)
        tr.fit(b, num_steps=stop_at, checkpointer=ck) \
            if stop_at else tr.fit(b, checkpointer=ck)
        ck.close()
        return tr

    golden = run(tmp_path / "g")
    run(tmp_path / "k", stop_at=6)       # "killed" at step 6
    resumed = run(tmp_path / "k")        # resumes from the chain
    assert resumed.loss_history == golden.loss_history
    for k in golden.params:
        assert np.array_equal(np.asarray(resumed.params[k]),
                              np.asarray(golden.params[k]))


def test_adaptive_step_rejections():
    spec = models.FMSpec(num_features=16, rank=2, init_std=0.05)
    with pytest.raises(ValueError, match="adaptive"):
        optim.make_sparse_adaptive_step(
            spec, TrainConfig(optimizer="sgd"))
    with pytest.raises(ValueError, match="reg"):
        optim.make_sparse_adaptive_step(
            spec, TrainConfig(optimizer="ftrl", reg_factors=1e-4))
    ffm = models.FFMSpec(num_features=16, rank=2, num_fields=2,
                         init_std=0.05)
    with pytest.raises(ValueError, match="flat FM"):
        optim.make_sparse_adaptive_step(
            ffm, TrainConfig(optimizer="ftrl"))
    with pytest.raises(ValueError, match="unknown adaptive"):
        optim.init_adaptive_slots("sgd", spec, {})


def test_fused_field_families_still_reject_adaptive_optimizers():
    """No silent fallback: the fused field bodies are SGD scatter
    programs; an adaptive optimizer must be refused there, not
    quietly ignored."""
    from fm_spark_tpu.sparse import make_field_sparse_sgd_body

    spec = models.FieldFMSpec(num_features=8 * 4, rank=2, num_fields=4,
                              bucket=8, init_std=0.05)
    with pytest.raises(ValueError, match="SGD"):
        make_field_sparse_sgd_body(
            spec, TrainConfig(optimizer="ftrl"))
