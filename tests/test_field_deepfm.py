"""FieldDeepFM: fused hybrid step ≡ autodiff+optax; sharded ≡ single.

Config 5 (BASELINE.json:11) on the CTR layout: embedding tables update
via the analytic sparse scatter rule (FM part = the reference's
computeGradient rule, deep part through one vjp of the MLP wrt its
input), the MLP + bias via dense Adam. The references here are fully
independent: plain ``jax.grad`` through ``spec.scores`` plus an optax
update, with per-lane lazy L2 (the framework's sparse-reg semantics,
sparse.py module docstring).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.ops import losses as losses_lib
from fm_spark_tpu.sparse import make_field_deepfm_sparse_step
from fm_spark_tpu.train import TrainConfig, make_optimizer


def _spec(F=4, bucket=32, k=4, mlp=(16, 16, 16), **kw):
    return models.FieldDeepFMSpec(
        num_features=F * bucket, rank=k, num_fields=F, bucket=bucket,
        mlp_dims=mlp, init_std=0.1, **kw,
    )


def _batch(rng, b, F, bucket):
    return (
        jnp.asarray(rng.integers(0, bucket, (b, F)), jnp.int32),
        jnp.asarray(rng.uniform(0.5, 1.5, (b, F)), jnp.float32),
        jnp.asarray(rng.integers(0, 2, b), jnp.float32),
        jnp.ones((b,), jnp.float32),
    )


def _reference_step(spec, config, dense_opt, ref, ref_opt, i, batch):
    """Autodiff + optax oracle with per-lane lazy L2 on the tables."""
    ids, vals, labels, w = batch
    per_loss = losses_lib.loss_fn(spec.loss)

    def loss_f(p):
        sc = spec.scores(p, ids, vals)
        return jnp.sum(per_loss(sc, labels) * w) / jnp.maximum(
            jnp.sum(w), 1.0
        )

    lref, g = jax.value_and_grad(loss_f)(ref)
    lr = config.learning_rate
    k = spec.rank
    new_vw = []
    for f in range(spec.num_fields):
        counts = np.zeros(spec.bucket, np.float32)
        np.add.at(counts, np.asarray(ids[:, f]), np.asarray(w > 0,
                                                            np.float32))
        cm = jnp.asarray(counts)[:, None]
        reg_col = jnp.concatenate([
            jnp.full((k,), config.reg_factors),
            jnp.full((1,), config.reg_linear),
        ])
        new_vw.append(
            ref["vw"][f]
            - lr * (g["vw"][f] + cm * reg_col[None, :] * ref["vw"][f])
        )
    gd = {
        "w0": g["w0"] + config.reg_bias * ref["w0"],
        "mlp": jax.tree_util.tree_map(
            lambda gg, pp: gg + config.reg_factors * pp,
            g["mlp"], ref["mlp"],
        ),
    }
    upd, ref_opt = dense_opt.update(gd, ref_opt,
                                    {"w0": ref["w0"], "mlp": ref["mlp"]})
    nd = optax.apply_updates({"w0": ref["w0"], "mlp": ref["mlp"]}, upd)
    return {"w0": nd["w0"], "vw": new_vw, "mlp": nd["mlp"]}, ref_opt, lref


def _assert_params_close(got, ref, F):
    np.testing.assert_allclose(float(got["w0"]), float(ref["w0"]),
                               rtol=1e-4, atol=1e-7)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(got["vw"][f]), np.asarray(ref["vw"][f]),
            rtol=2e-4, atol=1e-6,
        )
    for la, lb in zip(got["mlp"], ref["mlp"]):
        np.testing.assert_allclose(np.asarray(la["kernel"]),
                                   np.asarray(lb["kernel"]),
                                   rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(la["bias"]),
                                   np.asarray(lb["bias"]),
                                   rtol=2e-4, atol=1e-6)


@pytest.mark.slow
def test_fused_step_matches_autodiff_optax():
    F, bucket = 4, 32
    spec = _spec(F, bucket)
    config = TrainConfig(learning_rate=0.05, lr_schedule="constant",
                         optimizer="adam", reg_factors=1e-3,
                         reg_linear=1e-4, reg_bias=1e-4)
    step = make_field_deepfm_sparse_step(spec, config)
    params = spec.init(jax.random.key(0))
    ref = jax.tree_util.tree_map(jnp.copy, params)
    opt_state = step.init_opt_state(params)
    dense_opt = make_optimizer(config)
    ref_opt = dense_opt.init({"w0": ref["w0"], "mlp": ref["mlp"]})
    rng = np.random.default_rng(0)
    for i in range(3):
        batch = _batch(rng, 64, F, bucket)
        params, opt_state, loss = step(params, opt_state, jnp.int32(i),
                                       *batch)
        ref, ref_opt, lref = _reference_step(spec, config, dense_opt, ref,
                                             ref_opt, i, batch)
        np.testing.assert_allclose(float(loss), float(lref), rtol=1e-5)
    _assert_params_close(params, ref, F)


@pytest.mark.slow
def test_fused_step_weighted_rows():
    # Zero-weight (epoch-padding) rows must not touch tables or head.
    F, bucket = 3, 16
    spec = _spec(F, bucket, mlp=(8, 8, 8))
    config = TrainConfig(learning_rate=0.1, lr_schedule="constant",
                         optimizer="adam", reg_factors=1e-3)
    step = make_field_deepfm_sparse_step(spec, config)
    params = spec.init(jax.random.key(1))
    ref = jax.tree_util.tree_map(jnp.copy, params)
    opt_state = step.init_opt_state(params)
    dense_opt = make_optimizer(config)
    ref_opt = dense_opt.init({"w0": ref["w0"], "mlp": ref["mlp"]})
    rng = np.random.default_rng(2)
    ids, vals, labels, w = _batch(rng, 32, F, bucket)
    w = w.at[16:].set(0.0)
    batch = (ids, vals, labels, w)
    params, opt_state, loss = step(params, opt_state, jnp.int32(0), *batch)
    ref, ref_opt, lref = _reference_step(spec, config, dense_opt, ref,
                                         ref_opt, 0, batch)
    np.testing.assert_allclose(float(loss), float(lref), rtol=1e-5)
    _assert_params_close(params, ref, F)


@pytest.mark.slow
@pytest.mark.parametrize("n_feat,num_fields", [(4, 6), (8, 5), (2, 4)])
def test_sharded_matches_single_chip(eight_devices, n_feat, num_fields):
    from fm_spark_tpu.parallel import (
        make_field_deepfm_sharded_step,
        make_field_mesh,
        pad_field_batch,
        shard_field_batch,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
        unstack_field_deepfm_params,
    )

    bucket, b = 32, 64
    spec = _spec(num_fields, bucket, k=4, mlp=(16, 16, 16))
    config = TrainConfig(learning_rate=0.05, lr_schedule="inv_sqrt",
                         optimizer="adam", reg_factors=1e-3,
                         reg_linear=1e-4, reg_bias=1e-4)
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    params = spec.init(jax.random.key(0))
    ref_params = jax.tree_util.tree_map(jnp.copy, params)

    step_sh = make_field_deepfm_sharded_step(spec, config, mesh)
    sharded = shard_field_deepfm_params(
        stack_field_deepfm_params(spec, params, n_feat), mesh
    )
    opt_sh = step_sh.init_opt_state(sharded)

    step_single = make_field_deepfm_sparse_step(spec, config)
    opt_single = step_single.init_opt_state(ref_params)

    rng = np.random.default_rng(0)
    for i in range(3):
        ids = np.asarray(rng.integers(0, bucket, (b, num_fields)),
                         np.int32)
        vals = np.asarray(rng.uniform(0.5, 1.5, (b, num_fields)),
                          np.float32)
        labels = np.asarray(rng.integers(0, 2, b), np.float32)
        w = np.ones((b,), np.float32)
        sb = shard_field_batch(
            pad_field_batch((ids, vals, labels, w), num_fields, n_feat),
            mesh,
        )
        sharded, opt_sh, loss_sh = step_sh(sharded, opt_sh, jnp.int32(i),
                                           *sb)
        ref_params, opt_single, loss_ref = step_single(
            ref_params, opt_single, jnp.int32(i),
            *map(jnp.asarray, (ids, vals, labels, w)),
        )
        np.testing.assert_allclose(float(loss_sh), float(loss_ref),
                                   rtol=1e-5)
    got = unstack_field_deepfm_params(spec, jax.device_get(sharded))
    _assert_params_close(got, jax.device_get(ref_params), num_fields)


def test_fused_deepfm_learns_synthetic():
    from fm_spark_tpu.data import synthetic_ctr

    F, bucket, b = 4, 64, 256
    spec = _spec(F, bucket, k=4, mlp=(32, 32, 32))
    config = TrainConfig(learning_rate=1e-2, lr_schedule="constant",
                         optimizer="adam")
    step = make_field_deepfm_sparse_step(spec, config)
    params = spec.init(jax.random.key(0))
    opt_state = step.init_opt_state(params)
    ids_g, vals, labels = synthetic_ctr(b * 30, F * bucket, F, seed=0)
    offs = (np.arange(F) * bucket).astype(np.int32)
    ids_l = ids_g - offs[None, :]
    losses = []
    for i in range(30):
        sl = slice(i * b, (i + 1) * b)
        params, opt_state, loss = step(
            params, opt_state, jnp.int32(i),
            jnp.asarray(ids_l[sl]), jnp.asarray(vals[sl]),
            jnp.asarray(labels[sl]), jnp.ones((b,), jnp.float32),
        )
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.01, losses


def test_spec_validation_and_io_roundtrip(tmp_path):
    with pytest.raises(ValueError, match="num_fields"):
        models.FieldDeepFMSpec(num_features=10, rank=2, num_fields=0,
                               bucket=5)
    with pytest.raises(ValueError, match="num_features"):
        models.FieldDeepFMSpec(num_features=11, rank=2, num_fields=2,
                               bucket=5)
    spec = _spec(3, 8, k=2, mlp=(4, 4, 4))
    params = spec.init(jax.random.key(3))
    models.save_model(str(tmp_path / "m"), spec, params)
    spec2, params2 = models.load_model(str(tmp_path / "m"))
    assert dataclasses.asdict(spec2) == dataclasses.asdict(spec)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 8, (16, 3)), jnp.int32)
    vals = jnp.ones((16, 3), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(spec.predict(params, ids, vals)),
        np.asarray(spec2.predict(params2, ids, vals)),
        rtol=1e-6,
    )
