"""Serving runtime tests (ISSUE 12): the AOT micro-batched predict
engine, the hot-reload seam, and the sentinel-gated bench_serve ladder.

The load-bearing contracts:

- **zero compiles on the request path** — after ``warmup()`` the
  engine never issues another compile request (asserted via the PR-1
  compile-cache stats, not wall-clock);
- **coalescer exactness** — every submitted request is answered
  exactly once, padding never leaks across requests, and the latency
  budget bounds the coalescing wait;
- **the reload seam** — a failed reload (injected ``serve_reload``
  fault, corrupt chain tip, SIGKILL mid-reload in a subprocess)
  degrades to the old generation and converges on a later poll; the
  read-only :class:`ChainFollower` NEVER mutates the trainer's chain;
- **serving invariants** — :func:`chaos.audit_serve_events` holds
  seeded serving fault schedules (``serve_schedule``) to no-torn-swap
  / bounded-staleness / rc discipline;
- **bench_serve --smoke** — the bounded CPU ladder measures p50/p99 +
  QPS through the bucketed path, lands ``serve_bench`` ledger records,
  and promotes a serving headline through the keep-best gate.

The ``serve_request`` watchdog phase (deadline = SLO) is armed and
overrun here, which also satisfies the lint's phase-coverage rule.
"""

import json
import os
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from fm_spark_tpu import models, obs
from fm_spark_tpu.checkpoint import ChainFollower, Checkpointer
from fm_spark_tpu.resilience import chaos, faults, watchdog
from fm_spark_tpu.resilience.watchdog import HangDetected
from fm_spark_tpu.serve import DEFAULT_BUCKETS, PredictEngine, ReloadFollower
from fm_spark_tpu.utils.logging import EventLog, read_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    monkeypatch.delenv(watchdog.ENV_SPEC, raising=False)
    faults.clear()
    watchdog.clear()
    yield
    faults.clear()
    watchdog.clear()


def _spec():
    return models.FieldFMSpec(num_features=4 * 64, rank=4,
                              num_fields=4, bucket=64, init_std=0.1)


def _params(spec, scale: float = 1.0):
    p = spec.init(jax.random.key(0))
    if scale != 1.0:
        p = jax.tree_util.tree_map(lambda a: a * scale, p)
    return p


def _batch(spec, n, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, spec.bucket, (n, spec.num_fields)).astype(
        np.int32)
    vals = rng.random((n, spec.num_fields)).astype(np.float32)
    return ids, vals


def _direct(spec, params, ids, vals):
    return np.asarray(spec.predict(params, jax.numpy.asarray(ids),
                                   jax.numpy.asarray(vals)))


def _engine(spec, params, buckets=(1, 4, 16), budget_ms=50.0, **kw):
    eng = PredictEngine(spec, params, buckets=buckets,
                        latency_budget_ms=budget_ms, **kw)
    eng.warmup()
    return eng


def _counter(name):
    return obs.registry().counter(name).value


# NOTE: every test that arms the persistent compile cache runs it in a
# SUBPROCESS — the same policy (and reason) as tests/test_compile_cache:
# in-process, jit's dispatch cache would mask the persistent cache, and
# on this container an in-process-armed cache additionally makes later
# drill-suite compiles segfault inside jaxlib (pre-existing, reproduced
# on the PR-10 tree with no serving code loaded). Subprocesses keep the
# warm-start assertions honest AND the suite ordering-safe.


# ------------------------------------------------------------- the engine


def test_score_matches_direct_predict_bitwise():
    """The offline path: bucketed AOT scoring (including the padding a
    non-bucket row count takes) is BIT-identical to the eager
    ``spec.predict`` — the cli-predict routing contract."""
    spec = _spec()
    params = _params(spec)
    eng = _engine(spec, params, buckets=(16,))
    try:
        for n in (1, 7, 16):  # full pad, partial pad, exact bucket
            ids, vals = _batch(spec, n, seed=n)
            assert np.array_equal(eng.score(ids, vals),
                                  _direct(spec, params, ids, vals))
    finally:
        eng.close()


def test_predict_chunks_wide_requests_and_preserves_order():
    spec = _spec()
    params = _params(spec)
    eng = _engine(spec, params, buckets=(1, 4, 16), budget_ms=1.0)
    try:
        ids, vals = _batch(spec, 40)  # 16 + 16 + 8 internal chunks
        assert np.array_equal(eng.predict(ids, vals),
                              _direct(spec, params, ids, vals))
    finally:
        eng.close()


def test_engine_rejects_fresh_shapes_and_oversize_submits():
    spec = _spec()
    params = _params(spec)
    eng = _engine(spec, params, buckets=(1, 4))
    try:
        ids, vals = _batch(spec, 2)
        with pytest.raises(ValueError, match="fresh shape"):
            eng.score(ids[:, :2], vals[:, :2])  # wrong width
        with pytest.raises(ValueError, match="bucket-max"):
            eng.submit(*_batch(spec, 8))  # > largest bucket
        with pytest.raises(ValueError, match="empty"):
            eng.score(ids[:0], vals[:0])
    finally:
        eng.close()


def test_coalescer_burst_answers_every_request_exactly_once():
    """Burst arrival: N distinct single-row requests offered
    concurrently are answered exactly once each with THEIR row's score
    (padding/coalescing never leaks across requests), in fewer
    micro-batches than requests."""
    spec = _spec()
    params = _params(spec)
    eng = _engine(spec, params, buckets=(1, 4, 16), budget_ms=100.0)
    try:
        n = 40
        ids, vals = _batch(spec, n)
        golden = _direct(spec, params, ids, vals)
        b0 = _counter("serve.batches_total")
        futures = [eng.submit(ids[i:i + 1], vals[i:i + 1])
                   for i in range(n)]
        results = [f.result(30) for f in futures]
        for i, r in enumerate(results):
            assert r.shape == (1,)
            assert np.array_equal(r, golden[i:i + 1]), i
        batches = _counter("serve.batches_total") - b0
        assert batches < n, (
            f"{batches} batches for {n} burst requests — "
            "the coalescer never coalesced")
    finally:
        eng.close()


def test_coalescer_trickle_respects_latency_budget():
    """A lone request is held at most ~the latency budget waiting for
    peers, then dispatched alone — the explicit latency/batching
    trade, bounded."""
    spec = _spec()
    params = _params(spec)
    budget_s = 0.05
    eng = _engine(spec, params, buckets=(1, 16),
                  budget_ms=budget_s * 1e3)
    try:
        ids, vals = _batch(spec, 1)
        eng.predict(ids, vals)  # first dispatch: queue drains
        t0 = time.perf_counter()
        out = eng.predict(ids, vals)
        elapsed = time.perf_counter() - t0
        assert out.shape == (1,)
        # Generous upper margin for CI jitter; the point is "bounded
        # by the budget + execute", not "a 2s stall".
        assert elapsed < budget_s + 1.0, elapsed
    finally:
        eng.close()


_ZERO_COMPILE_CHILD = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["FM_SPARK_OBS_DIR"] = "none"
from fm_spark_tpu.utils.cpuguard import force_cpu_platform
force_cpu_platform()
import numpy as np
import jax
from fm_spark_tpu import models
from fm_spark_tpu.serve import PredictEngine
from fm_spark_tpu.utils import compile_cache

compile_cache.enable(sys.argv[1])
spec = models.FieldFMSpec(num_features=4 * 64, rank=4, num_fields=4,
                          bucket=64, init_std=0.1)
params = spec.init(jax.random.key(0))
p2 = jax.tree_util.tree_map(lambda a: a * 2.0, params)
eng = PredictEngine(spec, params, buckets=(1, 4),
                    latency_budget_ms=1.0)
warm = eng.warmup()
after_warmup = compile_cache.cache_stats()
rng = np.random.default_rng(0)
ids = rng.integers(0, 64, (3, 4)).astype(np.int32)
vals = rng.random((3, 4)).astype(np.float32)
eng.score(ids, vals)
eng.predict(ids, vals)
eng.swap_generation(p2, step=1)
eng.predict(ids, vals)   # post-swap: same executables
eng.close()
stats = compile_cache.cache_stats()
print(json.dumps({
    "fresh_at_warmup": warm["fresh_compiles"],
    "requests_after_warmup": stats["requests"]
                             - after_warmup["requests"],
}))
"""


def test_request_path_zero_compile_requests_after_warmup(tmp_path):
    """The AOT contract: warmup compiles (cold) or deserializes (warm
    process) every bucket executable; afterwards NO code path issues a
    compile request — not score, not the coalescer, not a post-swap
    dispatch. Cross-process, via the persistent cache, exactly like
    the train-side warm-start tests."""
    def run():
        out = subprocess.run(
            [sys.executable, "-c", _ZERO_COMPILE_CHILD,
             str(tmp_path / "cc")],
            capture_output=True, text=True, timeout=240, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["fresh_at_warmup"] > 0   # cold cache: real compiles
    assert cold["requests_after_warmup"] == 0, (
        "the request path consulted the compiler after warmup")
    warm = run()                         # new process, same cache dir
    assert warm["fresh_at_warmup"] == 0, warm
    assert warm["requests_after_warmup"] == 0


def test_serve_request_watchdog_converts_slow_batch_to_hang_detected():
    """The SLO watchdog: the ``serve_request`` phase armed at a tight
    deadline turns a slow micro-batch into a structured HangDetected
    delivered to every coalesced caller — and the worker survives to
    serve the next request."""
    spec = _spec()
    params = _params(spec)
    eng = _engine(spec, params, buckets=(1,), budget_ms=0.0)
    try:
        real = eng._compiled[1]

        def slow(p, i, v):
            time.sleep(0.08)
            return real(p, i, v)

        eng._compiled[1] = slow
        watchdog.configure({"serve_request": 0.01}, action="raise")
        ids, vals = _batch(spec, 1)
        fut = eng.submit(ids, vals)
        with pytest.raises(HangDetected, match="serve_request"):
            fut.result(30)
        assert _counter("serve.batch_failures_total") >= 1
        # The worker thread survived the failed batch:
        watchdog.clear()
        eng._compiled[1] = real
        assert eng.predict(ids, vals).shape == (1,)
    finally:
        eng.close()


# --------------------------------------------------------- the reload seam


def test_follower_hot_swap_serves_new_generation(tmp_path):
    spec = _spec()
    params = _params(spec)
    p2 = _params(spec, scale=2.0)
    ck = Checkpointer(str(tmp_path / "chain"), save_every=1,
                      async_save=False)
    ck.save(7, p2, {}, None, force=True)
    ck.close()
    eng = _engine(spec, params, buckets=(4,), budget_ms=0.0)
    fol = ReloadFollower(eng, str(tmp_path / "chain"), poll_s=0.05,
                         opt_state_example={})
    try:
        assert fol.poll_once() == "swapped"
        assert eng.generation().step == 7
        ids, vals = _batch(spec, 4)
        assert np.array_equal(eng.score(ids, vals),
                              _direct(spec, p2, ids, vals))
        assert fol.poll_once() == "fresh"
        assert int(obs.registry().gauge(
            "serve/staleness_steps").value or 0) == 0
    finally:
        fol.stop()
        eng.close()


def test_reload_fault_degrades_then_converges(tmp_path):
    """The degraded-serving drill: an injected ``serve_reload`` fault
    fails the reload attempt — the OLD generation keeps serving, the
    failure is journaled, the degraded gauge rises — and the next
    poll (fault exhausted) converges to the new generation."""
    spec = _spec()
    params = _params(spec)
    p2 = _params(spec, scale=3.0)
    journal_path = tmp_path / "serve_health.jsonl"
    ck = Checkpointer(str(tmp_path / "chain"), save_every=1,
                      async_save=False)
    ck.save(5, p2, {}, None, force=True)
    ck.close()
    eng = _engine(spec, params, buckets=(4,), budget_ms=0.0)
    fol = ReloadFollower(eng, str(tmp_path / "chain"), poll_s=0.05,
                         journal=EventLog(str(journal_path)),
                         opt_state_example={})
    try:
        faults.activate("serve_reload@1=error")
        ids, vals = _batch(spec, 4)
        golden_old = eng.score(ids, vals)
        assert fol.poll_once() == "failed"
        # Old generation keeps serving, bit-identically:
        assert np.array_equal(eng.score(ids, vals), golden_old)
        assert fol.degraded
        events = read_events(str(journal_path))
        assert any(e["event"] == "reload_failed" for e in events)
        # Next poll: the fault plan is exhausted; serving converges.
        assert fol.poll_once() == "swapped"
        assert eng.generation().step == 5
        assert not fol.degraded
        assert np.array_equal(eng.score(ids, vals),
                              _direct(spec, p2, ids, vals))
    finally:
        fol.stop()
        eng.close()


def test_follower_refuses_demoted_tip_and_converges_forward(tmp_path):
    """ISSUE 13: a generation judged bad AFTER publish (drift verdict
    → ``demote``: durable tombstone, ``last_good`` republished) must
    never be hot-loaded — the follower reports the quarantined tip as
    a degraded poll and keeps serving the prior generation, then
    converges FORWARD when a newer good save lands."""
    spec = _spec()
    chain = tmp_path / "chain"
    journal_path = tmp_path / "serve_health.jsonl"
    ck = Checkpointer(str(chain), save_every=1, async_save=False)
    ck.save(5, _params(spec, scale=2.0), {}, None, force=True)
    ck.save(9, _params(spec, scale=3.0), {}, None, force=True)
    ck.wait()
    # The drift sentry demotes the freshly published tip before any
    # follower loads it: tombstone durable, pointer republished.
    assert ck.demote(9, reason="drift verdict") is True
    assert ck.last_good_step() == 5
    eng = _engine(spec, _params(spec), buckets=(4,), budget_ms=0.0)
    fol = ReloadFollower(eng, str(chain), poll_s=0.05,
                         journal=EventLog(str(journal_path)),
                         opt_state_example={})
    try:
        ids, vals = _batch(spec, 4)
        # Follower restores the PRE-drift generation, never 9:
        assert fol.poll_once() == "swapped"
        assert eng.generation().step == 5
        assert np.array_equal(
            eng.score(ids, vals),
            _direct(spec, _params(spec, scale=2.0), ids, vals))
        events = read_events(str(journal_path))
        assert any(e["event"] == "checkpoint_demoted_skipped"
                   and e["step"] == 9 for e in events)
        # A newer good save converges serving forward past the veto.
        ck.save(12, _params(spec, scale=4.0), {}, None, force=True)
        ck.wait()
        assert fol.poll_once() == "swapped"
        assert eng.generation().step == 12
        # The artifact-only auditor proves no tombstoned generation
        # was ever installed.
        events = read_events(str(journal_path))
        assert chaos.audit_serve_events(
            events, tombstoned_steps=ck.tombstoned_steps()) == []
    finally:
        fol.stop()
        eng.close()
        ck.close()


def test_demotion_racing_reload_is_refused(tmp_path):
    """The nastiest interleaving (ISSUE 13): the demotion lands AFTER
    the follower restored the new generation but BEFORE the swap — the
    tombstone re-check at the swap boundary must win the race."""
    spec = _spec()
    chain = tmp_path / "chain"
    ck = Checkpointer(str(chain), save_every=1, async_save=False)
    ck.save(5, _params(spec, scale=2.0), {}, None, force=True)
    ck.save(9, _params(spec, scale=3.0), {}, None, force=True)
    ck.wait()
    journal_path = tmp_path / "serve_health.jsonl"
    eng = _engine(spec, _params(spec, scale=2.0), buckets=(4,),
                  budget_ms=0.0)
    eng.swap_generation(_params(spec, scale=2.0), 5)
    fol = ReloadFollower(eng, str(chain), poll_s=0.05,
                         journal=EventLog(str(journal_path)),
                         opt_state_example={})
    orig_restore = fol.chain.restore

    def restore_then_demote(*a, **kw):
        out = orig_restore(*a, **kw)
        ck.demote(9, reason="drift verdict racing the reload")
        return out

    fol.chain.restore = restore_then_demote
    try:
        assert fol.poll_once() == "demoted"
        assert eng.generation().step == 5  # never installed 9
        assert fol.degraded
        events = read_events(str(journal_path))
        assert any(e["event"] == "reload_failed"
                   and "demoted mid-reload" in str(e.get("error"))
                   for e in events)
        assert chaos.audit_serve_events(
            events, tombstoned_steps={9}) == []
    finally:
        fol.chain.restore = orig_restore
        fol.stop()
        eng.close()
        ck.close()


def test_audit_flags_swap_to_tombstoned_generation():
    """The no_tombstoned_generation invariant is non-vacuous: a
    journal showing a swap INTO a demoted step must fail the audit."""
    events = [{"event": "serve_swap", "step": 9, "gen_id": 1,
               "from_step": 5}]
    v = chaos.audit_serve_events(events, tombstoned_steps={9})
    assert [x["invariant"] for x in v] == ["no_tombstoned_generation"]
    assert chaos.audit_serve_events(events, tombstoned_steps={7}) == []


def test_follower_torn_last_good_is_retried_not_raised(tmp_path):
    """ISSUE 13 satellite: a torn/empty ``last_good.json`` read (a
    copied or damaged chain — an atomic-replace reader never sees a
    partial write, but the file CAN be empty on disk) must surface as
    'nothing published yet' and heal on the next poll, never raise."""
    spec = _spec()
    chain = tmp_path / "chain"
    ck = Checkpointer(str(chain), save_every=1, async_save=False)
    ck.save(3, _params(spec, scale=2.0), {}, None, force=True)
    ck.wait()
    # Tear the pointer: empty file, then junk bytes.
    lg = chain / "last_good.json"
    eng = _engine(spec, _params(spec), buckets=(4,), budget_ms=0.0)
    fol = ReloadFollower(eng, str(chain), poll_s=0.05,
                         opt_state_example={})
    try:
        for torn in (b"", b'{"st'):
            lg.write_bytes(torn)
            assert fol.chain.last_good_step() is None
            assert fol.poll_once() == "no_checkpoint"
        # The trainer's next atomic replace heals the pointer; the
        # very next poll serves it.
        lg.write_bytes(json.dumps({"step": 3}).encode())
        assert fol.poll_once() == "swapped"
        assert eng.generation().step == 3
    finally:
        fol.stop()
        eng.close()
        ck.close()


def _flip_step_bytes(chain_dir, step):
    import glob

    files = [p for p in glob.glob(
        os.path.join(str(chain_dir), str(step), "state", "**", "d", "*"),
        recursive=True) if os.path.isfile(p)]
    assert files, f"no array data files under step {step}"
    for p in files:
        with open(p, "r+b") as f:
            data = bytearray(f.read())
            for i in range(min(64, len(data))):
                data[i] ^= 0xFF
            f.seek(0)
            f.write(data)


def test_follower_walks_back_past_corrupt_tip(tmp_path):
    """Torn-``last_good`` walk-back through the follower: the pointer
    names a step whose bytes rotted — the follower restores the
    next-older VERIFIED step instead (first poll), and once the served
    generation is at the verified tip, further polls report the chain
    degraded rather than re-serving stale state."""
    spec = _spec()
    chain = tmp_path / "chain"
    ck = Checkpointer(str(chain), save_every=1, async_save=False)
    ck.save(2, _params(spec, scale=2.0), {}, None, force=True)
    ck.save(4, _params(spec, scale=4.0), {}, None, force=True)
    ck.close()
    _flip_step_bytes(chain, 4)  # last_good still points at 4
    eng = _engine(spec, _params(spec), buckets=(4,), budget_ms=0.0)
    journal_path = tmp_path / "serve_health.jsonl"
    fol = ReloadFollower(eng, str(chain), poll_s=0.05,
                         journal=EventLog(str(journal_path)),
                         opt_state_example={})
    try:
        assert fol.poll_once() == "swapped"
        assert eng.generation().step == 2  # walked back past 4
        events = read_events(str(journal_path))
        # The rotted tip is journaled either as a checksum mismatch or
        # as unreadable bytes (the flip can take out orbax's own
        # metadata before the checksum pass ever runs).
        assert any(e["event"] in ("checkpoint_corrupt",
                                  "checkpoint_unreadable")
                   and e["step"] == 4 for e in events)
        # Serving is as fresh as the VERIFIED chain allows; the torn
        # tip shows up as a degraded poll, never a torn generation.
        assert fol.poll_once() == "stale_chain"
        assert fol.degraded
    finally:
        fol.stop()
        eng.close()


def test_chain_follower_never_mutates_the_chain(tmp_path):
    """The read-only satellite: a follower walk (including a failed
    verification) leaves every byte of the chain directory exactly as
    the trainer wrote it — no manifest flush, no pointer write, no
    orbax metadata."""
    import hashlib

    spec = _spec()
    chain = tmp_path / "chain"
    ck = Checkpointer(str(chain), save_every=1, async_save=False)
    ck.save(1, _params(spec), {}, None, force=True)
    ck.save(3, _params(spec, scale=2.0), {}, None, force=True)
    ck.close()
    _flip_step_bytes(chain, 3)  # force a walk-back during the follow

    def snapshot():
        out = {}
        for root, _dirs, files in os.walk(chain):
            for f in files:
                p = os.path.join(root, f)
                with open(p, "rb") as fh:
                    out[os.path.relpath(p, chain)] = hashlib.sha256(
                        fh.read()).hexdigest()
        return out

    before = snapshot()
    fol = ChainFollower(str(chain))
    assert fol.last_good_step() == 3
    restored = fol.restore(_params(spec), {})
    fol.close()
    assert restored["step"] == 1
    assert snapshot() == before, (
        "the read-only follower changed bytes in the trainer's chain")


_SIGKILL_CHILD_TIMEOUT = 240


def test_sigkill_during_reload_drill_subprocess(tmp_path):
    """SIGKILL-mid-reload: a serving process dies (injected
    ``serve_reload`` exit — the kill window is inside the reload
    attempt, before any swap) with the expected rc; the chain is
    untouched, and the NEXT serving process converges to the newest
    generation on startup. rc discipline + convergence =
    :func:`chaos.audit_serve_events`'s contract, subprocess edition."""
    spec = _spec()
    chain = tmp_path / "chain"
    model_dir = tmp_path / "model"
    models.save_model(str(model_dir), spec, _params(spec))
    ck = Checkpointer(str(chain), save_every=1, async_save=False)
    ck.save(1, _params(spec, scale=2.0), {}, None, force=True)
    ck.wait()

    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "FM_SPARK_OBS_DIR": "none",
           "FM_SPARK_FAULTS": "serve_reload@1=exit:9"}
    argv = [sys.executable, "-m", "fm_spark_tpu.cli", "serve",
            "--model", str(model_dir), "--config", "criteo1tb_fm_r64",
            "--checkpoint-dir", str(chain), "--synthetic", "64",
            "--batch-size", "4", "--buckets", "1,4",
            "--reload-poll-s", "0.1", "--repeat", "1000",
            "--latency-budget-ms", "0"]
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE, text=True,
                            cwd=REPO, env=env,
                            stderr=subprocess.DEVNULL)
    try:
        # Wait until the child is actually serving, THEN publish the
        # new generation its poll will die reloading.
        line = proc.stdout.readline()
        assert '"serving": true' in line, line
        ck.save(2, _params(spec, scale=3.0), {}, None, force=True)
        ck.wait()
        rc = proc.wait(timeout=_SIGKILL_CHILD_TIMEOUT)
    finally:
        proc.kill()
        ck.close()
    assert rc == 9, f"expected the injected exit rc, got {rc}"
    assert chaos.audit_serve_events([], rc=rc, allowed_rcs=(9,)) == []

    # The chain survived the kill untouched (the follower died inside
    # a READ), and the next serving process's first poll converges to
    # the generation the dead one never reached.
    eng = _engine(spec, _params(spec), buckets=(1, 4), budget_ms=0.0)
    fol = ReloadFollower(eng, str(chain), poll_s=0.05,
                         opt_state_example={})
    try:
        assert fol.poll_once() == "swapped"
        assert eng.generation().step == 2
        assert int(obs.registry().gauge(
            "serve/staleness_steps").value or 0) == 0
    finally:
        fol.stop()
        eng.close()


# --------------------------------------------------- serving chaos drills


def test_serve_schedules_deterministic_and_cover_serving_faults():
    seen_points = set()
    for seed in range(30):
        a = chaos.serve_schedule(seed)
        b = chaos.serve_schedule(seed)
        assert a == b, "a schedule must be a pure function of its seed"
        assert a.scenario.startswith("serve_")
        for rule in a.rules:
            seen_points.add(rule.split("@")[0])
    # The serving campaign composes BOTH halves of the tentpole drill:
    # trainer-side commit faults and reload faults.
    assert {"serve_reload", "ckpt_commit"} <= seen_points


def test_audit_serve_events_invariants():
    ok = [{"kind": "serve_swap", "step": 3, "gen_id": 1},
          {"kind": "serve_swap", "step": 5, "gen_id": 2}]
    assert chaos.audit_serve_events(ok, final_staleness=0, rc=0) == []
    # One swap seen via two transports (journal + flight mirror) is
    # NOT a torn/duplicated swap.
    mirrored = [{"kind": "serve_swap", "step": 3, "gen_id": 1,
                 "from_step": 0},
                {"event": "serve_swap", "step": 3, "gen_id": 1,
                 "from_step": 0, "ts": 1.0},
                {"kind": "serve_swap", "step": 5, "gen_id": 2,
                 "from_step": 3}]
    assert chaos.audit_serve_events(mirrored) == []
    torn = chaos.audit_serve_events(
        [{"kind": "serve_swap", "step": 5, "gen_id": 1},
         {"kind": "serve_swap", "step": 4, "gen_id": 2}])
    assert any(v["invariant"] == "no_torn_swap" for v in torn)
    skipped = chaos.audit_serve_events(
        [{"kind": "serve_swap", "step": 3, "gen_id": 1},
         {"kind": "serve_swap", "step": 5, "gen_id": 3}])
    assert any(v["invariant"] == "no_torn_swap" for v in skipped)
    stale = chaos.audit_serve_events([], final_staleness=4,
                                     staleness_bound=0)
    assert any(v["invariant"] == "staleness_bounded" for v in stale)
    bad_rc = chaos.audit_serve_events([], rc=1, allowed_rcs=(0, 87))
    assert any(v["invariant"] == "rc_discipline" for v in bad_rc)
    journaless = chaos.audit_serve_events(
        [{"kind": "reload_failed", "error": "x"}])
    assert any(v["invariant"] == "degraded_journaled"
               for v in journaless)


def test_seeded_serve_drill_campaign_green(tmp_path):
    """A bounded in-process serving chaos campaign: seeded schedules
    (commit faults + reload faults) against the production
    engine/follower/checkpointer stack. Every response under load must
    be generation-uniform, and the run must end green under
    :func:`chaos.audit_serve_events` — converged, no torn swap."""
    spec = _spec()
    ids, vals = _batch(spec, 4)
    ids[:] = ids[:1]  # identical rows: a mixed-generation response
    vals[:] = 1.0     # would be visibly non-uniform

    for seed in (1, 2, 5, 9):
        sched = chaos.serve_schedule(seed)
        workdir = tmp_path / f"s{seed}"
        workdir.mkdir()
        journal_path = workdir / "serve_health.jsonl"
        chain = workdir / "chain"
        ck = Checkpointer(str(chain), save_every=1, async_save=False)
        ck.save(1, _params(spec, scale=2.0), {}, None, force=True)
        ck.wait()
        journal = EventLog(str(journal_path))
        eng = _engine(spec, _params(spec), buckets=(4,), budget_ms=0.0,
                      journal=journal)
        fol = ReloadFollower(eng, str(chain), poll_s=0.01,
                             journal=journal, opt_state_example={})
        torn = 0
        try:
            assert fol.poll_once() == "swapped"
            faults.activate(sched.plan)
            for k in range(2, 5):  # the trainer keeps publishing
                try:
                    ck.save(k, _params(spec, scale=float(k + 1)), {},
                            None, force=True)
                    ck.wait()
                except faults.FaultInjected:
                    pass  # the trainer's problem; serving must ride on
                for _ in range(3):
                    out = eng.predict(ids, vals)
                    if not np.all(out == out[0]):
                        torn += 1
                fol.poll_once()
            faults.clear()
            # Recovery: polls with no plan active must converge (the
            # chain self-heals its pending manifests at the next save
            # boundary; give it one).
            ck.save(6, _params(spec, scale=9.0), {}, None, force=True)
            ck.wait()
            deadline = time.monotonic() + 10
            while (fol.poll_once() != "fresh"
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        finally:
            faults.clear()
            fol.stop()
            eng.close()
            ck.close()
        assert torn == 0, f"seed {seed}: mixed-generation response"
        final_staleness = int(obs.registry().gauge(
            "serve/staleness_steps").value or 0)
        events = read_events(str(journal_path))
        assert any(e["event"] == "serve_swap" for e in events), (
            "the drill never swapped — it exercised nothing")
        violations = chaos.audit_serve_events(
            events, final_staleness=final_staleness,
            staleness_bound=0, rc=0)
        assert violations == [], f"seed {seed}: {violations}"


# ------------------------------------------------------------ bench_serve


def _run_bench_serve(tmp_path, *extra):
    """One bench_serve smoke in a SUBPROCESS (it arms the persistent
    compile cache — see the module note — and subprocesses are what
    make the cold-vs-warm pair a real cross-process measurement)."""
    out_path = tmp_path / "serve_result.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_serve.py"),
         "--smoke", "--art-dir", str(tmp_path / "art"),
         "--measured-path", str(tmp_path / "MEASURED.json"),
         "--compile-cache", str(tmp_path / "cc"),
         "--requests", "12", "--out", str(out_path), *extra],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "FM_SPARK_OBS_DIR": "none"})
    assert out.returncode == 0, out.stderr[-2000:]
    with open(out_path) as f:
        return out.returncode, json.load(f)


def test_bench_serve_smoke_cold_then_warm(tmp_path):
    """The tier-1 serving leg: the bounded CPU smoke measures p50/p99
    + QPS through the bucketed AOT path, asserts zero fresh compiles
    after warmup, completes the reload-under-load drill with no torn
    swap and bounded staleness, lands ``serve_bench`` ledger records
    with full fingerprints, and seeds the MEASURED.json serving
    headline through the keep-best gate. A second (warm) process-run
    deserializes every executable: warm_start flips true."""
    from fm_spark_tpu.obs import PerfLedger

    rc, result = _run_bench_serve(tmp_path)
    assert rc == 0
    assert result["fresh_compiles_after_warmup"] == 0
    for rung in result["rungs"]:
        assert rung["p50_ms"] > 0 and rung["p99_ms"] >= rung["p50_ms"]
        assert rung["rows_per_sec"] > 0
        assert rung["sentinel"]["verdict"] in (
            "insufficient_history", "improved", "flat")
    drill = result["reload_drill"]
    assert drill["violations"] == []
    assert drill["torn_responses"] == 0
    assert drill["swaps"] >= 1
    assert drill["final_staleness_steps"] == 0
    # Ledger: one serve_bench record per rung, full provenance.
    ledger = PerfLedger(str(tmp_path / "art" / "obs" / "ledger.jsonl"))
    recs = ledger.records(kind="serve_bench", run_id=result["run_id"])
    assert len(recs) == len(result["rungs"])
    assert all(r["fingerprint"]["key"] and r["p99_ms"] is not None
               for r in recs)
    # MEASURED: the headline seeded through the gate.
    with open(tmp_path / "MEASURED.json") as f:
        measured = json.load(f)
    assert result["measured_updated"]
    assert (measured["serving"]["rate_samples_per_sec_per_chip"]
            == result["headline_rows_per_sec_per_chip"])
    assert "bench_serve.py" in measured["serving"]["source"]

    rc2, result2 = _run_bench_serve(tmp_path, "--skip-reload-drill")
    assert rc2 == 0
    assert result2["warm_start"], (
        "second run should deserialize every bucket executable from "
        "the persistent cache")
    assert result2["fresh_compiles_at_warmup"] == 0


def test_bench_serve_promote_refuses_invariant_violating_run(tmp_path):
    """A ladder whose own invariants failed (fresh compiles after
    warmup / reload-drill violation) must keep its rungs out of
    MEASURED.json no matter how good the number looks — the PERF.md
    round-16 rule. (Importing bench_serve is safe: the compile cache
    is only armed inside main().)"""
    import importlib.util

    spec_ = importlib.util.spec_from_file_location(
        "bench_serve_promote_test", os.path.join(REPO, "bench_serve.py"))
    mod = importlib.util.module_from_spec(spec_)
    sys.modules[spec_.name] = mod
    spec_.loader.exec_module(mod)
    args = SimpleNamespace(measured_path=str(tmp_path / "MEASURED.json"))
    headline = {"variant": "serve/x/b512",
                "sentinel": {"verdict": "improved"}}
    ok, reason = mod._promote(headline, 1e9, "cpu", args, run_ok=False)
    assert not ok and "invariants" in reason
    assert not os.path.exists(args.measured_path)
    ok, _ = mod._promote(headline, 1e3, "cpu", args, run_ok=True)
    assert ok and os.path.exists(args.measured_path)


def test_measured_serving_entry_schema(tmp_path):
    """The new optional MEASURED entry round-trips the validator."""
    from fm_spark_tpu.measured import load_measured, update_entry

    path = tmp_path / "MEASURED.json"
    base = json.load(open(os.path.join(REPO, "MEASURED.json")))
    with open(path, "w") as f:
        json.dump(base, f)
    update_entry("serving", rate=1234.5, variant="serve/x/b32",
                 source="bench_serve.py ladder", attachment="cpu",
                 date="2026-08-03", path=str(path))
    data = load_measured(str(path))
    assert data["serving"]["rate_samples_per_sec_per_chip"] == 1234.5


# ------------------------------------------------------------ CLI routing


def test_cli_predict_routes_through_engine_bit_identical(tmp_path):
    """The predict-routing satellite: ``cli predict`` output through
    the bucketed AOT engine is byte-identical to the pre-engine eager
    formula over the same batches."""
    from fm_spark_tpu import cli
    from fm_spark_tpu.data import iterate_once  # noqa: F401 (doc)

    spec = _spec()
    params = _params(spec)
    models.save_model(str(tmp_path / "m"), spec, params)
    out_path = tmp_path / "preds.txt"
    rc = cli.main(["predict", "--model", str(tmp_path / "m"),
                   "--synthetic", "100", "--batch-size", "32",
                   "--out", str(out_path)])
    assert rc == 0
    args = SimpleNamespace(synthetic=100, data=None, config=None,
                           batch_size=32)
    golden = []
    for bids, bvals, _, w in cli._batches_for_model(args, spec):
        preds = _direct(spec, params, bids, bvals)
        golden.extend(f"{float(p):.6g}" for p in preds[w > 0])
    assert out_path.read_text().splitlines() == golden


def test_cli_serve_smoke_from_model(tmp_path, capsys):
    """In-process ``cli serve``: warms up with the default buckets,
    answers a bounded synthetic stream, and emits the summary line
    with latency percentiles and reload accounting."""
    from fm_spark_tpu import cli

    spec = _spec()
    models.save_model(str(tmp_path / "m"), spec, _params(spec))
    rc = cli.main(["serve", "--model", str(tmp_path / "m"),
                   "--synthetic", "64", "--batch-size", "8",
                   "--buckets", "1,8", "--max-requests", "5",
                   "--latency-budget-ms", "0", "--reload-poll-s", "0"])
    assert rc == 0
    lines = capsys.readouterr().out.splitlines()
    summary = next(json.loads(ln)["serve_summary"] for ln in lines
                   if '"serve_summary"' in ln)
    assert summary["served_requests"] == 5
    assert summary["request_ms"]["count"] >= 5
    assert summary["request_ms"]["p99"] is not None
    assert summary["staleness_steps"] == 0
    assert not summary["degraded"]


def test_cli_serve_metrics_port_live_round_trip(tmp_path):
    """ISSUE 14 acceptance: while a ``cli serve`` loop is LIVE,
    ``--metrics-port`` serves valid Prometheus text on /metrics and a
    JSON liveness doc on /healthz — a curl-level HTTP round-trip from
    another process, no touching the daemon."""
    import urllib.request

    spec = _spec()
    models.save_model(str(tmp_path / "m"), spec, _params(spec))
    proc = subprocess.Popen(
        [sys.executable, "-m", "fm_spark_tpu", "serve",
         "--model", str(tmp_path / "m"),
         "--synthetic", "256", "--batch-size", "8",
         "--buckets", "1,8", "--latency-budget-ms", "0",
         "--reload-poll-s", "0", "--repeat", "1000000",
         "--obs-dir", "none", "--metrics-port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        port = None
        serving = False
        while True:
            line = proc.stdout.readline()
            if not line:
                break
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if "metrics_port" in doc:
                port = doc["metrics_port"]
            if doc.get("serving"):
                serving = True
                break
        assert port, "no metrics_port line from cli serve"
        assert serving, proc.stderr.read()[-2000:]

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=15) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        # Valid exposition text with the live serving gauges: the
        # engine published its generation before the first request.
        assert "# TYPE fm_spark_serve_generation_step gauge" in text
        assert "fm_spark_serve_generation_step 0" in text

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=15) as r:
            hz = json.loads(r.read())
        assert hz["status"] == "ok"
        assert hz["generation_step"] == 0
        assert not hz["degraded"]
    finally:
        proc.terminate()
        try:
            proc.wait(30)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_default_buckets_sane():
    assert DEFAULT_BUCKETS == (1, 8, 64, 512)
