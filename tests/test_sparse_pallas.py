"""`TrainConfig.use_pallas` routing: fused steps via the Pallas kernels
(interpret mode off-TPU) must match the XLA gather/scatter path.

The kernel internals are pinned by tests/test_pallas_fm.py; these tests
pin the *integration* — id padding/clamping, dedup-before-RMW, OOB
sentinel handling, and the gather routing inside the fused bodies.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from fm_spark_tpu import models
from fm_spark_tpu.sparse import (
    make_field_deepfm_sparse_step,
    make_field_ffm_sparse_sgd_step,
    make_field_sparse_sgd_step,
)
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B = 5, 64, 4, 48


@pytest.fixture
def batch(rng):
    # Heavy duplication within fields to exercise the dedup path the
    # update kernel requires.
    ids = rng.integers(0, 8, size=(B, F)).astype(np.int32)
    vals = rng.normal(size=(B, F)).astype(np.float32)
    labels = rng.integers(0, 2, B).astype(np.float32)
    return jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(labels)


def _spec():
    return models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, fused_linear=True,
    )


@pytest.mark.parametrize("mode", ["scatter_add", "dedup"])
def test_field_step_pallas_matches_xla(batch, mode):
    ids, vals, labels = batch
    spec = _spec()
    params = spec.init(jax.random.key(0))
    params_p = jax.tree_util.tree_map(jnp.copy, params)
    cfg = dict(learning_rate=0.2, lr_schedule="inv_sqrt", optimizer="sgd",
               sparse_update=mode)
    step_x = make_field_sparse_sgd_step(spec, TrainConfig(**cfg))
    step_p = make_field_sparse_sgd_step(
        spec, TrainConfig(use_pallas=True, **cfg)
    )
    w = jnp.ones((B,))
    for i in range(3):
        params, loss_x = step_x(params, jnp.int32(i), ids, vals, labels, w)
        params_p, loss_p = step_p(params_p, jnp.int32(i), ids, vals, labels, w)
        np.testing.assert_allclose(float(loss_p), float(loss_x), rtol=1e-5)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_p["vw"][f]), np.asarray(params["vw"][f]),
            rtol=1e-4, atol=1e-6, err_msg=f"field {f}",
        )


def test_field_step_pallas_with_zero_weight_rows(batch):
    # weights==0 rows must not move the table (masked examples still
    # occupy scatter lanes; dedup must sum their zero grads harmlessly).
    ids, vals, labels = batch
    spec = _spec()
    params = spec.init(jax.random.key(1))
    params_p = jax.tree_util.tree_map(jnp.copy, params)
    cfg = dict(learning_rate=0.3, optimizer="sgd", sparse_update="dedup")
    step_x = make_field_sparse_sgd_step(spec, TrainConfig(**cfg))
    step_p = make_field_sparse_sgd_step(
        spec, TrainConfig(use_pallas=True, **cfg)
    )
    w = jnp.asarray((np.arange(B) % 3 == 0).astype(np.float32))
    params, _ = step_x(params, jnp.int32(0), ids, vals, labels, w)
    params_p, _ = step_p(params_p, jnp.int32(0), ids, vals, labels, w)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_p["vw"][f]), np.asarray(params["vw"][f]),
            rtol=1e-4, atol=1e-6,
        )


def test_deepfm_step_pallas_matches_xla(batch):
    ids, vals, labels = batch
    spec = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, mlp_dims=(8, 8),
    )
    params = spec.init(jax.random.key(2))
    params_p = jax.tree_util.tree_map(jnp.copy, params)
    cfg = dict(learning_rate=0.05, optimizer="adam")
    step_x = make_field_deepfm_sparse_step(spec, TrainConfig(**cfg))
    step_p = make_field_deepfm_sparse_step(
        spec, TrainConfig(use_pallas=True, **cfg)
    )
    opt_x = step_x.init_opt_state(params)
    opt_p = step_p.init_opt_state(params_p)
    w = jnp.ones((B,))
    for i in range(2):
        params, opt_x, loss_x = step_x(
            params, opt_x, jnp.int32(i), ids, vals, labels, w
        )
        params_p, opt_p, loss_p = step_p(
            params_p, opt_p, jnp.int32(i), ids, vals, labels, w
        )
        np.testing.assert_allclose(float(loss_p), float(loss_x), rtol=1e-5)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_p["vw"][f]), np.asarray(params["vw"][f]),
            rtol=1e-4, atol=1e-6,
        )


def test_ffm_step_pallas_matches_xla(batch):
    ids, vals, labels = batch
    spec = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=3, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    params = spec.init(jax.random.key(4))
    params_p = jax.tree_util.tree_map(jnp.copy, params)
    cfg = dict(learning_rate=0.2, optimizer="sgd", sparse_update="dedup")
    step_x = make_field_ffm_sparse_sgd_step(spec, TrainConfig(**cfg))
    step_p = make_field_ffm_sparse_sgd_step(
        spec, TrainConfig(use_pallas=True, **cfg)
    )
    w = jnp.ones((B,))
    for i in range(2):
        params, loss_x = step_x(params, jnp.int32(i), ids, vals, labels, w)
        params_p, loss_p = step_p(params_p, jnp.int32(i), ids, vals, labels, w)
        np.testing.assert_allclose(float(loss_p), float(loss_x), rtol=1e-5)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_p["vw"][f]), np.asarray(params["vw"][f]),
            rtol=1e-4, atol=1e-6,
        )


def test_pallas_update_drops_negative_and_high_ids():
    """XLA scatter mode='drop' parity: out-of-range lanes (high sentinel
    OR negative) must not touch the table — a negative id especially must
    not corrupt row 0 via index clamping."""
    from fm_spark_tpu.ops.scatter import apply_row_updates

    table = jnp.ones((16, 4), jnp.float32)
    ids = jnp.asarray([3, -1, 16, 100, -7, 3], jnp.int32)
    delta = jnp.full((6, 4), 10.0, jnp.float32)
    got = apply_row_updates(table, ids, delta, mode="dedup", use_pallas=True)
    want = np.ones((16, 4), np.float32)
    want[3] += 20.0  # two valid lanes, deduped
    np.testing.assert_allclose(np.asarray(got), want)


def test_pallas_requires_fused_linear():
    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        fused_linear=False,
    )
    with pytest.raises(ValueError, match="fused_linear"):
        make_field_sparse_sgd_step(
            spec, TrainConfig(optimizer="sgd", use_pallas=True)
        )


@pytest.mark.parametrize("n_row", [1, 2], ids=["feat4", "feat2xrow2"])
def test_sharded_field_step_pallas_matches_single(rng, n_row):
    """use_pallas flows into the field-sharded step's gathers and shared
    update helper. The 2-D (feat, row) variant is the one that actually
    emits the bucket_local drop sentinel into the Pallas update — those
    lanes must become invalid kernel lanes (XLA mode='drop' parity)."""
    from fm_spark_tpu.parallel.field_step import (
        make_field_mesh,
        make_field_sharded_sgd_step,
        pad_field_batch,
        shard_field_batch,
        shard_field_params,
        stack_field_params,
        unstack_field_params,
    )

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices (fake CPU mesh)")
    spec = _spec()
    mesh = make_field_mesh(4, n_row=n_row)
    ids = rng.integers(0, 8, size=(B, F)).astype(np.int32)
    vals = rng.normal(size=(B, F)).astype(np.float32)
    labels = rng.integers(0, 2, B).astype(np.float32)
    w = np.ones((B,), np.float32)

    cfg = dict(learning_rate=0.2, optimizer="sgd", sparse_update="dedup")
    params0 = spec.init(jax.random.key(3))
    params_single = jax.tree_util.tree_map(jnp.copy, params0)
    step_single = make_field_sparse_sgd_step(spec, TrainConfig(**cfg))

    config_p = TrainConfig(use_pallas=True, **cfg)
    stacked = stack_field_params(spec, params0, mesh.shape["feat"])
    sharded = shard_field_params(stacked, mesh)
    step_sharded = make_field_sharded_sgd_step(spec, config_p, mesh)

    batch = pad_field_batch(
        (jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(labels),
         jnp.asarray(w)),
        spec.num_fields, mesh.shape["feat"],
    )
    sbatch = shard_field_batch(batch, mesh)
    for i in range(2):
        params_single, _ = step_single(
            params_single, jnp.int32(i), jnp.asarray(ids), jnp.asarray(vals),
            jnp.asarray(labels), jnp.asarray(w),
        )
        sharded, _ = step_sharded(sharded, jnp.int32(i), *sbatch)
    back = unstack_field_params(spec, jax.device_get(sharded))
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(back["vw"][f]), np.asarray(params_single["vw"][f]),
            rtol=1e-4, atol=1e-6, err_msg=f"field {f}",
        )
