"""``sel_blocked`` (round-5 staged FFM lever): the per-owner-field
blocked interaction must agree with the default [B, F, F, k] body up to
fp reassociation of the pair sums, on every composition it ships with
(plain/compact aux, fp32/bf16 compute), and every non-FFM factory must
reject the flag (no-silent-fallback rule)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.ops.scatter import compact_aux
from fm_spark_tpu.sparse import (
    make_field_ffm_sparse_sgd_step,
    make_field_sparse_sgd_step,
)
from fm_spark_tpu.train import TrainConfig


def _spec(F=4, bucket=16, k=3, **kw):
    return models.FieldFFMSpec(
        num_features=F * bucket, rank=k, num_fields=F, bucket=bucket,
        init_std=0.2, **kw,
    )


def _batch(rng, b, F, bucket):
    return (
        jnp.asarray(rng.integers(0, bucket, size=(b, F)).astype(np.int32)),
        jnp.asarray(rng.uniform(0.5, 1.5, size=(b, F)).astype(np.float32)),
        jnp.asarray(rng.integers(0, 2, b).astype(np.float32)),
        jnp.ones((b,), jnp.float32),
    )


def _run(spec, config, n_steps=3, seed=2, aux_for=None):
    rng = np.random.default_rng(seed)
    step = make_field_ffm_sparse_sgd_step(spec, config)
    params = spec.init(jax.random.key(0))
    params["vw"] = [
        t.at[:, -1].set(jnp.asarray(rng.normal(size=t.shape[0]), t.dtype))
        for t in params["vw"]
    ]
    loss = None
    for i in range(n_steps):
        ids, vals, labels, w = _batch(rng, 64, spec.num_fields, spec.bucket)
        aux = aux_for(ids) if aux_for else None
        params, loss = step(params, jnp.int32(i), ids, vals, labels, w, aux)
    return params, float(loss)


def _assert_close(pa, pb, rtol, atol):
    np.testing.assert_allclose(np.asarray(pa["w0"]), np.asarray(pb["w0"]),
                               rtol=rtol, atol=atol)
    for ta, tb in zip(pa["vw"], pb["vw"]):
        np.testing.assert_allclose(
            np.asarray(ta, np.float32), np.asarray(tb, np.float32),
            rtol=rtol, atol=atol,
        )


@pytest.mark.parametrize("use_linear,use_bias", [(True, True),
                                                 (False, False)])
def test_blocked_matches_default_fp32(use_linear, use_bias):
    spec = _spec(use_linear=use_linear, use_bias=use_bias)
    base = TrainConfig(learning_rate=0.1, lr_schedule="constant",
                       optimizer="sgd", reg_factors=1e-3, reg_linear=1e-4,
                       reg_bias=1e-4)
    pa, la = _run(spec, base)
    pb, lb = _run(spec, dataclasses.replace(base, sel_blocked=True))
    # Same math, different pair-sum association order.
    _assert_close(pa, pb, rtol=2e-5, atol=2e-6)
    assert abs(la - lb) < 1e-5


def test_blocked_matches_default_bf16_compute():
    spec = _spec(compute_dtype="bfloat16")
    base = TrainConfig(learning_rate=0.1, lr_schedule="constant",
                       optimizer="sgd")
    pa, _ = _run(spec, base)
    pb, _ = _run(spec, dataclasses.replace(base, sel_blocked=True))
    _assert_close(pa, pb, rtol=3e-2, atol=3e-3)


def test_blocked_composes_with_compact_host_aux():
    spec = _spec(param_dtype="bfloat16", compute_dtype="bfloat16")
    base = TrainConfig(learning_rate=0.1, lr_schedule="constant",
                       optimizer="sgd", sparse_update="dedup_sr",
                       host_dedup=True, compact_cap=64)
    aux_for = lambda ids: jax.device_put(compact_aux(np.asarray(ids), 64))
    pa, _ = _run(spec, base, aux_for=aux_for)
    pb, _ = _run(spec, dataclasses.replace(base, sel_blocked=True),
                 aux_for=aux_for)
    _assert_close(pa, pb, rtol=3e-2, atol=3e-3)


def test_blocked_composes_with_compact_device():
    spec = _spec()
    base = TrainConfig(learning_rate=0.1, lr_schedule="constant",
                       optimizer="sgd", sparse_update="dedup",
                       compact_device=True, compact_cap=64)
    pa, _ = _run(spec, base)
    pb, _ = _run(spec, dataclasses.replace(base, sel_blocked=True))
    _assert_close(pa, pb, rtol=2e-5, atol=2e-6)


def test_non_ffm_factories_reject_sel_blocked():
    cfg = TrainConfig(learning_rate=0.1, lr_schedule="constant",
                      optimizer="sgd", sel_blocked=True)
    fm = models.FieldFMSpec(num_features=64, rank=3, num_fields=4,
                            bucket=16, init_std=0.1)
    with pytest.raises(ValueError, match="sel_blocked"):
        make_field_sparse_sgd_step(fm, cfg)


def test_sharded_ffm_step_rejects_sel_blocked():
    from fm_spark_tpu.parallel import (
        make_field_ffm_sharded_step,
        make_field_mesh,
    )

    mesh = make_field_mesh(len(jax.devices()))
    with pytest.raises(ValueError, match="sel_blocked"):
        make_field_ffm_sharded_step(
            _spec(),
            TrainConfig(learning_rate=0.1, lr_schedule="constant",
                        optimizer="sgd", sel_blocked=True),
            mesh,
        )


def test_cli_lever_rejects_non_ffm():
    from fm_spark_tpu.cli_levers import _v_sel_blocked

    fm = models.FieldFMSpec(num_features=64, rank=3, num_fields=4,
                            bucket=16, init_std=0.1)
    tc = TrainConfig(learning_rate=0.1, lr_schedule="constant",
                     optimizer="sgd", sel_blocked=True)
    ctx = {"spec": fm, "n": 1, "sharded": False}
    assert "sel-blocked" in _v_sel_blocked(tc, ctx)
    ffm_ctx = {"spec": _spec(), "n": 1, "sharded": False}
    assert _v_sel_blocked(tc, ffm_ctx) is None
    assert "sel-blocked" in _v_sel_blocked(
        tc, {"spec": _spec(), "n": 8, "sharded": True}
    )


def test_dense_and_sharded_fm_factories_reject_sel_blocked():
    from fm_spark_tpu.parallel import make_field_mesh
    from fm_spark_tpu.parallel.field_step import (
        make_field_sharded_sgd_step,
    )
    from fm_spark_tpu.train import FMTrainer, TrainConfig as TC

    cfg = TC(learning_rate=0.1, lr_schedule="constant", optimizer="sgd",
             sel_blocked=True)
    with pytest.raises(ValueError, match="sel_blocked"):
        FMTrainer(_spec(), cfg).fit  # noqa: B018 — ctor builds the step
    fm = models.FieldFMSpec(num_features=64, rank=3, num_fields=4,
                            bucket=16, init_std=0.1)
    with pytest.raises(ValueError, match="sel_blocked"):
        make_field_sharded_sgd_step(
            fm, cfg, make_field_mesh(len(jax.devices()))
        )
