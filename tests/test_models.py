"""Model-family tests: init semantics, task switch, DeepFM head, save/load."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.models import base


def _batch(rng, n, b=8, nnz=4):
    ids = np.stack([rng.choice(n, size=nnz, replace=False) for _ in range(b)])
    vals = np.ones((b, nnz), np.float32)
    return jnp.asarray(ids, jnp.int32), jnp.asarray(vals)


def test_fm_init_matches_reference_semantics():
    spec = models.FMSpec(num_features=100, rank=8, init_std=0.02)
    params = spec.init(jax.random.key(0))
    assert float(params["w0"]) == 0.0
    assert not params["w"].any()
    std = float(jnp.std(params["v"]))
    assert 0.01 < std < 0.03  # ~N(0, 0.02²)


def test_fm_dim_gating(rng):
    n = 40
    ids, vals = _batch(rng, n)
    base_spec = models.FMSpec(num_features=n, rank=4)
    params = base_spec.init(jax.random.key(1))
    params["w0"] = jnp.float32(2.0)
    params["w"] = params["w"] + 1.0
    full = base_spec.scores(params, ids, vals)
    no_bias = models.FMSpec(num_features=n, rank=4, use_bias=False)
    np.testing.assert_allclose(no_bias.scores(params, ids, vals), full - 2.0, rtol=1e-5)
    no_lin = models.FMSpec(num_features=n, rank=4, use_linear=False)
    # w == 1 everywhere, vals == 1, nnz = 4 → linear term = 4.
    np.testing.assert_allclose(no_lin.scores(params, ids, vals), full - 4.0, rtol=1e-5)
    # Gradients of disabled terms are exactly zero.
    g = jax.grad(lambda p: jnp.sum(no_lin.scores(p, ids, vals)))(params)
    assert not np.asarray(g["w"]).any()


def test_regression_clip():
    spec = models.FMSpec(
        num_features=10, rank=2, task="regression", min_target=1.0, max_target=5.0
    )
    scores = jnp.asarray([-3.0, 2.0, 9.0])
    out = base.predict_from_scores(spec, scores)
    np.testing.assert_allclose(out, [1.0, 2.0, 5.0])


def test_classification_sigmoid():
    spec = models.FMSpec(num_features=10, rank=2)
    out = base.predict_from_scores(spec, jnp.asarray([0.0]))
    np.testing.assert_allclose(out, [0.5])


def test_deepfm_reduces_to_fm_plus_head(rng):
    n = 60
    ids, vals = _batch(rng, n, nnz=5)
    spec = models.DeepFMSpec(num_features=n, rank=4, num_fields=5, mlp_dims=(8, 8, 8))
    params = spec.init(jax.random.key(2))
    full = spec.scores(params, ids, vals)
    assert full.shape == (8,)
    # Zeroing the MLP output layer must recover the pure FM score.
    params_z = jax.tree_util.tree_map(lambda x: x, params)
    params_z["mlp"] = [dict(l) for l in params["mlp"]]
    params_z["mlp"][-1] = {
        "kernel": jnp.zeros_like(params["mlp"][-1]["kernel"]),
        "bias": jnp.zeros_like(params["mlp"][-1]["bias"]),
    }
    fm_spec = models.FMSpec(num_features=n, rank=4)
    fm_params = {k: params[k] for k in ("w0", "w", "v")}
    np.testing.assert_allclose(
        spec.scores(params_z, ids, vals),
        fm_spec.scores(fm_params, ids, vals),
        rtol=1e-5, atol=1e-5,
    )


def test_deepfm_padded_slots(rng):
    n = 60
    spec = models.DeepFMSpec(num_features=n, rank=4, num_fields=5, mlp_dims=(8, 8, 8))
    params = spec.init(jax.random.key(3))
    ids, vals = _batch(rng, n, nnz=5)
    vals = vals.at[:, -1].set(0.0)
    s1 = spec.scores(params, ids, vals)
    ids2 = ids.at[:, -1].set(0)
    s2 = spec.scores(params, ids2, vals)
    np.testing.assert_allclose(s1, s2, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("family", ["fm", "ffm", "deepfm"])
def test_save_load_roundtrip(tmp_path, rng, family):
    n = 30
    if family == "fm":
        spec = models.FMSpec(num_features=n, rank=4, task="regression",
                             min_target=1.0, max_target=5.0)
    elif family == "ffm":
        spec = models.FFMSpec(num_features=n, rank=4, num_fields=5)
    else:
        spec = models.DeepFMSpec(num_features=n, rank=4, num_fields=5,
                                 mlp_dims=(8, 8, 8))
    params = spec.init(jax.random.key(4))
    models.save_model(str(tmp_path / "m"), spec, params)
    spec2, params2 = models.load_model(str(tmp_path / "m"))
    assert spec2 == spec
    ids, vals = _batch(rng, n, nnz=5)
    np.testing.assert_allclose(
        spec.scores(params, ids, vals), spec2.scores(params2, ids, vals),
        rtol=1e-6, atol=1e-6,
    )
    if family == "fm":
        assert math.isfinite(spec2.min_target)


def test_bf16_save_load_roundtrip(tmp_path, rng):
    # Regression: bf16 tables used to serialize as raw '|V2' and fail to load.
    spec = models.FMSpec(num_features=20, rank=4, param_dtype="bfloat16")
    params = spec.init(jax.random.key(5))
    assert params["v"].dtype == jnp.bfloat16
    models.save_model(str(tmp_path / "m"), spec, params)
    spec2, params2 = models.load_model(str(tmp_path / "m"))
    assert params2["v"].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(params["v"], np.float32), np.asarray(params2["v"], np.float32)
    )


def test_bad_loss_fails_at_construction():
    with pytest.raises(ValueError):
        models.FMSpec(num_features=10, rank=2, loss="logloss")


def test_regression_derives_squared_loss():
    spec = models.FMSpec(num_features=10, rank=2, task="regression")
    assert spec.loss == "squared"
    assert models.FMSpec(num_features=10, rank=2).loss == "logistic"
    with pytest.raises(ValueError, match="squared"):
        models.FMSpec(num_features=10, rank=2, task="regression", loss="logistic")


def test_deepfm_slot_mismatch_raises(rng):
    spec = models.DeepFMSpec(num_features=30, rank=2, num_fields=5, mlp_dims=(4, 4, 4))
    params = spec.init(jax.random.key(0))
    ids, vals = _batch(rng, 30, nnz=6)
    with pytest.raises(ValueError, match="num_fields"):
        spec.scores(params, ids, vals)
