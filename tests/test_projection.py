"""The multi-chip projection model's structural invariants.

No hardware claim is testable here (one chip); what IS testable is the
model's arithmetic: traffic counts follow the sharded programs'
construction, the wire dtype halves activation bytes exactly, and the
score-sharded lever moves the replicated term into the divided one.
"""

import pytest

from fm_spark_tpu.parallel.projection import (
    field_sharded_costs,
    project_aggregate,
)

B, F, K, N = 131072, 39, 64, 8


def test_bf16_wire_halves_activation_bytes_only():
    for model in ("fm", "ffm", "deepfm"):
        c32 = field_sharded_costs(B, F, K, N, cap=16384, device_aux=True,
                                  model=model)["ici_bytes_per_step"]
        c16 = field_sharded_costs(B, F, K, N, cap=16384, device_aux=True,
                                  model=model,
                                  psum_dtype="bfloat16")["ici_bytes_per_step"]
        # Batch re-shard is wire-dtype-independent.
        assert c32["a2a_batch"] == c16["a2a_batch"]
        assert (c32["allgather_labels_weights"]
                == c16["allgather_labels_weights"])
        # Every activation collective halves exactly.
        for key in c32:
            if key in ("a2a_batch", "allgather_labels_weights", "total"):
                continue
            assert c16[key] * 2 == c32[key], (model, key)


def test_ffm_2d_adds_sel_row_psum():
    c1 = field_sharded_costs(B, F, K, N, model="ffm")["ici_bytes_per_step"]
    c2 = field_sharded_costs(B, F, K, N, model="ffm",
                             n_row=2)["ici_bytes_per_step"]
    assert "psum_sel_row" not in c1
    # ring factor at r=2 is 1.0 → the row psum costs exactly the full
    # sel tensor; the a2a term is unchanged.
    assert c2["psum_sel_row"] == c2["a2a_sel"] * N // (N - 1)
    assert c2["a2a_sel"] == c1["a2a_sel"]
    with pytest.raises(ValueError, match="n_row"):
        field_sharded_costs(B, F, K, N, model="fm", n_row=2)


def test_deepfm_2d_adds_h_row_psum_and_per_chip_divides_total():
    c1 = field_sharded_costs(B, F, K, N, cap=16384, device_aux=True,
                             model="deepfm")["ici_bytes_per_step"]
    c2 = field_sharded_costs(B, F, K, N, cap=16384, device_aux=True,
                             model="deepfm",
                             n_row=2)["ici_bytes_per_step"]
    assert "psum_h_row" not in c1 and c2["psum_h_row"] > 0
    # The psum runs on the per-chip [B, f_local·k] block (before the
    # feat gather), so at r=2 (ring factor 1) it is allgather_h/(N-1)·
    # ... just check it's first-order: within 2x of allgather_h/n ratio.
    assert c2["allgather_h"] == c1["allgather_h"]
    p = project_aggregate(1_000_000, B=B, F=F, k=K, n=N // 2,
                          cap=16384, device_aux=True, model="deepfm",
                          n_row=2)
    agg = p["projected_aggregate_samples_per_sec"]
    assert p["projected_per_chip_samples_per_sec"] == round(agg / N)


def test_score_sharded_moves_replicated_term():
    base = dict(B=B * N, F=F, k=K, n=N, cap=16384, device_aux=True,
                psum_dtype="bfloat16")
    rep = project_aggregate(1_176_031, **base)
    ss = project_aggregate(1_176_031, **base, score_sharded=True)
    # The lever strictly helps at n > 1 (t_rep/n < t_rep) and adds the
    # dscores all_gather to the traffic counts.
    assert (ss["projected_aggregate_samples_per_sec"]
            > rep["projected_aggregate_samples_per_sec"])
    assert "allgather_dscores" in ss["per_chip"]["ici_bytes_per_step"]
    with pytest.raises(ValueError, match="score_sharded"):
        project_aggregate(1_176_031, B=B, F=F, k=K, n=N, model="ffm",
                          score_sharded=True)


def test_replicated_term_is_undivided():
    # The round-4 honest-model correction: the replicated score term
    # sits OUTSIDE the /n bucket and scales with B. Toggling it between
    # 0 and r ms must change the projected step time by r·(B/128k)·
    # (n−1)/n — the n−1/n is what the round-3 constant-input model
    # under-counted in weak scaling.
    for b_mult in (1, 8):
        kw = dict(B=B * b_mult, F=F, k=K, n=N, cap=16384,
                  device_aux=True)
        with_rep = project_aggregate(1_176_031,
                                     replicated_score_ms_per_128k=2.0,
                                     **kw)
        without = project_aggregate(1_176_031,
                                    replicated_score_ms_per_128k=0.0,
                                    **kw)
        got = with_rep["t_projected_ms"] - without["t_projected_ms"]
        want = 2.0 * b_mult * (N - 1) / N
        assert got == pytest.approx(want, abs=0.02), b_mult


def test_inputs_echoed_for_audit():
    p = project_aggregate(1_000_000, B=B, F=F, k=K, n=N, cap=16384,
                          device_aux=True, psum_dtype="bfloat16",
                          score_sharded=True)
    for key in ("single_chip_rate", "psum_dtype", "score_sharded",
                "ici_gbps", "dispatch_ms",
                "replicated_score_ms_per_128k"):
        assert key in p["inputs"], key
