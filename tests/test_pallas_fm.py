"""Pallas row-gather/update kernels vs jnp references (interpret mode).

Interpret mode runs the kernels' DMA/semaphore semantics on CPU; the
real-chip speed A/B happens in bench variants (PERF.md), but correctness
is pinned here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu.ops import pallas_fm


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_gather_rows_matches_indexing(dtype):
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(1000, 16)), dtype)
    ids = jnp.asarray(rng.integers(0, 1000, size=512), jnp.int32)
    got = pallas_fm.gather_rows(table, ids, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(table[ids]))


def test_gather_rows_rejects_ragged():
    table = jnp.zeros((10, 8), jnp.float32)
    with pytest.raises(ValueError, match="multiple"):
        pallas_fm.gather_rows(table, jnp.zeros((100,), jnp.int32),
                              interpret=True)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_update_rows_add_unique_ids(dtype):
    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(600, 8)), dtype)
    # 512 unique ids out of 600 rows.
    ids = jnp.asarray(rng.permutation(600)[:512].astype(np.int32))
    delta = jnp.asarray(rng.normal(size=(512, 8)) * 0.1, jnp.float32)
    valid = jnp.ones((512,), jnp.int32)
    want = np.asarray(table, np.float32).copy()
    want[np.asarray(ids)] += np.asarray(delta)
    got = pallas_fm.update_rows_add(table, ids, valid,
                                    delta.astype(dtype), interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), want.astype(np.float32)
        if dtype == jnp.float32
        else np.asarray(want.astype(jnp.bfloat16), np.float32),
        rtol=1e-2, atol=1e-2,
    )


def test_update_rows_add_skips_invalid_lanes():
    rng = np.random.default_rng(2)
    table = jnp.asarray(rng.normal(size=(300, 4)), jnp.float32)
    ids_np = rng.permutation(300)[:256].astype(np.int32)
    valid_np = (rng.random(256) < 0.5).astype(np.int32)
    # Invalid lanes all point at row 0: if predication failed, row 0
    # would be clobbered many times over.
    ids_np = np.where(valid_np == 1, ids_np, 0).astype(np.int32)
    delta = jnp.asarray(rng.normal(size=(256, 4)), jnp.float32)
    want = np.asarray(table, np.float32).copy()
    for m in range(256):
        if valid_np[m]:
            want[ids_np[m]] += np.asarray(delta)[m]
    got = pallas_fm.update_rows_add(
        table, jnp.asarray(ids_np), jnp.asarray(valid_np), delta,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_update_then_gather_roundtrip():
    # The two kernels compose: gather sees the updated rows.
    rng = np.random.default_rng(3)
    table = jnp.asarray(rng.normal(size=(512, 8)), jnp.float32)
    ids = jnp.asarray(rng.permutation(512)[:256].astype(np.int32))
    delta = jnp.ones((256, 8), jnp.float32)
    valid = jnp.ones((256,), jnp.int32)
    before = pallas_fm.gather_rows(table, ids, interpret=True)
    table2 = pallas_fm.update_rows_add(table, ids, valid, delta,
                                       interpret=True)
    after = pallas_fm.gather_rows(table2, ids, interpret=True)
    np.testing.assert_allclose(
        np.asarray(after), np.asarray(before) + 1.0, rtol=1e-6
    )
