"""Collection-time guard against silently shadowed tests (ISSUE 2).

Round-5 shipped two ``def test_dp_supports_ffm_and_deepfm`` in
tests/test_parallel.py; Python keeps only the last binding, so the
stricter @slow loss-equivalence variant was NEVER COLLECTED and its
coverage silently vanished (VERDICT r5 weak #2 — flake8 F811's exact
failure mode, but this suite has no lint step in the tier-1 gate). This
test IS the lint step: it AST-parses every test module and asserts no
scope defines the same test name twice, so a shadowed test can't recur
without turning the suite red.
"""

import ast
import os

import pytest

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _test_files():
    return sorted(
        f for f in os.listdir(TESTS_DIR)
        if f.startswith("test_") and f.endswith(".py")
    )


def _duplicate_defs(scope_body, scope_name):
    """Duplicate test_*/Test* definitions within one scope body, plus
    recursion into class scopes (methods shadow within their class)."""
    seen: dict[str, int] = {}
    dups = []
    for node in scope_body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            name = node.name
            if not name.startswith("test"):
                continue
        elif isinstance(node, ast.ClassDef):
            dups.extend(
                _duplicate_defs(node.body, f"{scope_name}::{node.name}")
            )
            name = node.name
            if not name.startswith("Test"):
                continue
        else:
            continue
        if name in seen:
            dups.append(
                f"{scope_name}: {name!r} defined at line {seen[name]} "
                f"is shadowed by a redefinition at line {node.lineno} — "
                "the first definition is silently never collected; "
                "rename one of them"
            )
        seen[name] = node.lineno
    return dups


@pytest.mark.parametrize("filename", _test_files())
def test_no_duplicate_test_names(filename):
    path = os.path.join(TESTS_DIR, filename)
    with open(path) as f:
        tree = ast.parse(f.read(), filename=filename)
    dups = _duplicate_defs(tree.body, filename)
    assert not dups, "\n".join(dups)
