"""Two-process pseudo-cluster: jax.distributed + global-mesh dp step.

The `local-cluster` rung of the simulation ladder (SURVEY.md §4) above
the fake-device mesh the rest of the suite uses: real processes, real
coordinator, cross-process collectives. Skips (not fails) if the
coordinator can't come up in this sandbox.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_dp_psum_agrees():
    port = _free_port()
    script = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    # sys.path[0] for a script is tests/, not the repo root — make the
    # package importable without requiring an installed wheel.
    repo_root = os.path.dirname(os.path.dirname(script))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(script)),
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        if os.environ.get("FMTPU_REQUIRE_MULTIHOST"):
            raise AssertionError(
                "multi-process coordinator timed out and "
                "FMTPU_REQUIRE_MULTIHOST is set"
            )
        print("\nWARNING: multihost test SKIPPED (coordinator timeout) — "
              "set FMTPU_REQUIRE_MULTIHOST=1 to make this a failure",
              file=sys.stderr)
        pytest.skip("multi-process coordinator timed out in this sandbox")
    if any(p.returncode != 0 for p in procs):
        combined = "\n---\n".join(outs)
        if "UNAVAILABLE" in combined or "DEADLINE" in combined:
            if os.environ.get("FMTPU_REQUIRE_MULTIHOST"):
                raise AssertionError(
                    f"distributed init unavailable and "
                    f"FMTPU_REQUIRE_MULTIHOST is set:\n{combined[-2000:]}"
                )
            print("\nWARNING: multihost test SKIPPED (distributed init "
                  "unavailable) — set FMTPU_REQUIRE_MULTIHOST=1 to make "
                  "this a failure", file=sys.stderr)
            pytest.skip(f"distributed init unavailable here:\n{combined[-500:]}")
        raise AssertionError(f"worker failed:\n{combined[-2000:]}")
    # Both processes computed identical psum'd losses.
    lines = [
        next(l for l in out.splitlines() if l.startswith("MULTIHOST_OK"))
        for out in outs
    ]
    l0 = lines[0].split("losses=")[1]
    l1 = lines[1].split("losses=")[1]
    assert l0 == l1, f"hosts disagree: {l0} vs {l1}"
