"""Test fixture: simulate an 8-device TPU mesh on CPU.

The Spark idiom `local[*]` — whole cluster as threads in one JVM, same code
path as a real cluster — maps to XLA's forced host-device count (SURVEY.md
§4): 8 fake CPU devices exercise the identical shard_map/psum code path as a
real v5e-8. Must run before jax initializes, hence env vars at import time.
"""

import os

# Force CPU even though the session env pins JAX_PLATFORMS=axon (real TPU):
# tests need the 8-fake-device mesh and deterministic CPU numerics. Plugins
# (jaxtyping) import jax before this conftest, so setting the env var alone
# is not enough — jax.config.update works at any point before backend init.
os.environ["JAX_PLATFORMS"] = "cpu"
# The telemetry plane is ON by default in `cli train` (ISSUE 7) —
# right for production, wrong for a test suite where hundreds of
# in-process cli.main() calls would each open a run directory in the
# repo, reset the process-wide metrics registry mid-suite, and chain a
# signal handler into the pytest process. Tests that exercise the
# plane pass --obs-dir explicitly (tests/test_cli.py, test_obs*.py).
os.environ.setdefault("FM_SPARK_OBS_DIR", "none")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The session's axon PJRT plugin (sitecustomize on PYTHONPATH) registers a
# backend factory in EVERY interpreter, and when the TPU tunnel is dead its
# init hangs forever — even under JAX_PLATFORMS=cpu, taking the whole CPU
# suite down with it (observed 2026-07-31: `jax.devices()` never returns
# while the attachment flaps). Tests never want the real chip: pin cpu and
# drop the accelerator factories before the first backend init.
from fm_spark_tpu.utils.cpuguard import force_cpu_platform  # noqa: E402

force_cpu_platform()  # config pin + accelerator-factory drop

jax.config.update("jax_debug_nans", False)  # enabled per-test where useful
assert len(jax.devices()) >= 8, (
    "conftest failed to get 8 fake CPU devices — was the XLA backend "
    "initialized before conftest import?"
)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs[:8]
