"""collective_dtype='bfloat16': wire-precision collectives on the
field-sharded steps (the projection model's dominant-ICI-term lever).

The bf16 wire changes results (that is the point — halved ICI bytes for
bounded precision), so the bar here is a loose agreement band against
the fp32-wire sharded step plus hard finiteness; the QUALITY envelope at
real shapes is bench_quality.py's budget row.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.parallel import (
    make_field_mesh,
    make_field_sharded_sgd_step,
    pad_field_batch,
    shard_field_batch,
    shard_field_params,
    stack_field_params,
    unstack_field_params,
)
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B = 5, 32, 4, 64


def _spec():
    return models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )


def _batch(rng, b=B):
    return (
        rng.integers(0, BUCKET, size=(b, F)).astype(np.int32),
        rng.uniform(0.5, 1.5, size=(b, F)).astype(np.float32),
        rng.integers(0, 2, b).astype(np.float32),
        np.ones((b,), np.float32),
    )


def _run_sharded(spec, config, mesh, n_feat, batches):
    params = shard_field_params(
        stack_field_params(spec, spec.init(jax.random.key(5)), n_feat),
        mesh,
    )
    step = make_field_sharded_sgd_step(spec, config, mesh)
    for i, batch in enumerate(batches):
        sb = shard_field_batch(pad_field_batch(batch, F, n_feat), mesh)
        params, loss = step(params, jnp.int32(i), *sb)
    return unstack_field_params(spec, jax.device_get(params)), float(loss)


@pytest.mark.parametrize("n_row", [1, 2])
def test_bf16_wire_close_to_fp32(eight_devices, n_row):
    n_feat = 4
    spec = _spec()
    mesh = make_field_mesh(n_feat * n_row, devices=eight_devices,
                           n_row=n_row)
    rng = np.random.default_rng(0)
    batches = [_batch(rng) for _ in range(2)]
    base = dict(learning_rate=0.2, optimizer="sgd")
    p32, l32 = _run_sharded(spec, TrainConfig(**base), mesh, n_feat,
                            batches)
    p16, l16 = _run_sharded(
        spec, TrainConfig(**base, collective_dtype="bfloat16"), mesh,
        n_feat, batches)
    assert np.isfinite(l16)
    # bf16 wire: ~3 decimal digits of mantissa — the loss and params
    # must land inside a few bf16-epsilons of the fp32-wire run.
    assert abs(l16 - l32) <= 3e-2 * max(1.0, abs(l32))
    for f in range(F):
        np.testing.assert_allclose(
            p16["vw"][f], p32["vw"][f], rtol=0.1, atol=3e-2,
            err_msg=f"vw[{f}]")


def test_bf16_wire_ffm_and_deepfm_run(eight_devices):
    from fm_spark_tpu.parallel import make_field_ffm_sharded_step
    from fm_spark_tpu.parallel.field_step import (
        make_field_deepfm_sharded_step,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
    )

    n_feat = 4
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    rng = np.random.default_rng(1)
    batch = _batch(rng)
    config = TrainConfig(learning_rate=0.1, optimizer="sgd",
                         collective_dtype="bfloat16")

    ffm = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=2, num_fields=F, bucket=BUCKET,
        init_std=0.1)
    fstep = make_field_ffm_sharded_step(ffm, config, mesh)
    fparams = shard_field_params(
        stack_field_params(ffm, ffm.init(jax.random.key(1)), n_feat),
        mesh)
    sb = shard_field_batch(pad_field_batch(batch, F, n_feat), mesh)
    fparams, floss = fstep(fparams, jnp.int32(0), *sb)
    assert np.isfinite(float(floss))

    deep = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=2, num_fields=F, bucket=BUCKET,
        mlp_dims=(8,), init_std=0.1)
    dconfig = TrainConfig(learning_rate=0.1, optimizer="adam",
                          collective_dtype="bfloat16")
    dstep = make_field_deepfm_sharded_step(deep, dconfig, mesh)
    dparams = shard_field_deepfm_params(
        stack_field_deepfm_params(deep, deep.init(jax.random.key(2)),
                                  n_feat), mesh)
    dopt = dstep.init_opt_state(dparams)
    dparams, dopt, dloss = dstep(dparams, dopt, jnp.int32(0), *sb)
    assert np.isfinite(float(dloss))


def test_collective_dtype_rejected_where_unimplemented(eight_devices):
    from fm_spark_tpu.parallel import make_mesh, make_parallel_train_step
    from fm_spark_tpu.sparse import (
        make_field_sparse_sgd_step,
        make_sparse_sgd_step,
    )

    spec = _spec()
    config = TrainConfig(optimizer="sgd", collective_dtype="bfloat16")
    with pytest.raises(ValueError, match="collective_dtype"):
        make_field_sparse_sgd_step(spec, config)
    with pytest.raises(ValueError, match="collective_dtype"):
        make_sparse_sgd_step(models.FMSpec(num_features=64, rank=2),
                             config)
    mesh = make_mesh(4, 1, devices=eight_devices[:4])
    with pytest.raises(ValueError, match="collective_dtype"):
        make_parallel_train_step(
            models.FMSpec(num_features=64, rank=2), config, mesh, "dp")
    with pytest.raises(ValueError, match="unknown collective_dtype"):
        make_field_sharded_sgd_step(
            spec, TrainConfig(optimizer="sgd", collective_dtype="fp8"),
            make_field_mesh(4, devices=eight_devices))
