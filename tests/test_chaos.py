"""Chaos campaign engine + deadline watchdogs (ISSUE 10).

Four layers, bottom-up: the per-phase deadline watchdog units (raise
and exit modes, production wiring at the ingest chunk read), the
seeded schedule generator's determinism/validity, the TIER-1 BOUNDED
SOAK — 25 fixed-seed multi-fault schedules through the invariant
auditor, every invariant green, inside a hard time budget — and the
acceptance drills: a deliberately-broken recovery path (the
``break_restore`` canary) is caught by the auditor and minimized to a
<= 2-rule reproducible plan; a SIGKILL mid-run with spool-compaction
pressure resumes exactly-once; native<->python ingest restores across
paths under a compound ``ingest_truncate`` + ``device_loss`` schedule.
"""

import dataclasses
import os
import signal
import time

import pytest

from fm_spark_tpu.resilience import chaos, faults, watchdog
from fm_spark_tpu.resilience.watchdog import (
    HANG_EXIT_RC,
    HangDetected,
    WatchdogTable,
)
from fm_spark_tpu.utils.logging import EventLog, read_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The fixed tier-1 soak seed list (tools/chaos_drill.py runs the same
#: list): fixed so every CI round drills the SAME plans and a
#: regression bisects cleanly.
SOAK_SEEDS = tuple(range(25))
SOAK_BUDGET_S = 240.0
SOAK_PER_SCHEDULE_S = 30.0


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    monkeypatch.delenv(watchdog.ENV_SPEC, raising=False)
    monkeypatch.delenv(watchdog.ENV_ACTION, raising=False)
    faults.clear()
    watchdog.clear()
    yield
    faults.clear()
    watchdog.clear()


# ------------------------------------------------------------- watchdog


def test_watchdog_noop_when_unconfigured():
    assert not watchdog.active()
    ctx = watchdog.phase("step_window")
    ctx2 = watchdog.phase("ingest_chunk")
    assert ctx is ctx2  # the shared allocation-free no-op
    with ctx:
        pass


def test_watchdog_spec_parse_and_validation():
    assert watchdog.parse_spec("ingest_chunk=2;step_window=30.5") == {
        "ingest_chunk": 2.0, "step_window": 30.5}
    with pytest.raises(ValueError, match="phase"):
        watchdog.parse_spec("no_such_phase=2")
    with pytest.raises(ValueError):
        watchdog.parse_spec("ckpt_commit=0")
    with pytest.raises(ValueError):
        WatchdogTable({}, action="explode")


def test_watchdog_raise_mode_detects_finite_hang(tmp_path):
    journal_path = str(tmp_path / "j.jsonl")
    watchdog.configure({"ingest_chunk": 0.01}, action="raise",
                       journal=EventLog(journal_path))
    assert watchdog.active("ingest_chunk")
    assert not watchdog.active("step_window")  # unbudgeted phase
    with watchdog.phase("step_window"):
        time.sleep(0.03)  # no budget: never a verdict
    with pytest.raises(HangDetected) as exc:
        with watchdog.phase("ingest_chunk"):
            time.sleep(0.03)
    assert exc.value.phase == "ingest_chunk"
    assert exc.value.elapsed_s > exc.value.deadline_s
    events = read_events(journal_path)
    assert [e["event"] for e in events] == ["hang_detected"]
    assert events[0]["phase"] == "ingest_chunk"
    assert events[0]["deadline_s"] == 0.01


def test_watchdog_raise_mode_never_masks_primary_exception(tmp_path):
    table = watchdog.configure({"ckpt_commit": 0.01}, action="raise")
    with pytest.raises(ValueError, match="primary"):
        with watchdog.phase("ckpt_commit"):
            time.sleep(0.03)
            raise ValueError("primary")
    # The overrun is still recorded as evidence, just not raised over
    # the real failure.
    assert table.hangs_detected == 1


def test_watchdog_within_deadline_is_silent(tmp_path):
    journal_path = str(tmp_path / "j.jsonl")
    table = watchdog.configure({"ingest_chunk": 5.0}, action="raise",
                               journal=EventLog(journal_path))
    with watchdog.phase("ingest_chunk"):
        pass
    assert table.hangs_detected == 0
    assert read_events(journal_path) == []


def test_watchdog_exit_mode_monitor_bounds_a_real_hang(tmp_path):
    """Exit mode is the only way out of a phase that never returns: the
    monitor thread fires mid-phase and hard-exits with the distinct
    hang rc (stubbed here; the subprocess drill proves the real
    ``os._exit`` path end-to-end)."""
    exits = []
    journal_path = str(tmp_path / "j.jsonl")
    table = WatchdogTable({"step_window": 0.03}, action="exit",
                          journal=EventLog(journal_path),
                          poll_s=0.005, _exit=exits.append)
    with table.phase("step_window"):
        deadline = time.monotonic() + 2.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.005)  # "hung" until the monitor fires
    table.close()
    assert exits == [HANG_EXIT_RC]
    events = read_events(journal_path)
    assert events and events[0]["event"] == "hang_detected"
    assert events[0]["action"] == "exit"


def test_watchdog_env_configuration(monkeypatch):
    monkeypatch.setenv(watchdog.ENV_SPEC, "ingest_chunk=0.01")
    monkeypatch.setenv(watchdog.ENV_ACTION, "raise")
    watchdog.clear()  # force the env re-read
    with pytest.raises(HangDetected):
        with watchdog.phase("ingest_chunk"):
            time.sleep(0.03)


def test_hang_fault_at_chunk_read_is_detected_in_production_wiring(
        tmp_path):
    """The real call site: an injected finite hang on the ShardReader
    chunk read converts into HangDetected through the ``ingest_chunk``
    phase wired in data/stream.py."""
    from fm_spark_tpu.data.stream import ShardReader

    p = tmp_path / "s.svm"
    p.write_text("1 1:1.0\n0 2:1.0\n")
    watchdog.configure({"ingest_chunk": 0.02}, action="raise")
    faults.activate("ingest_truncate@1=hang:0.1")
    with pytest.raises(HangDetected, match="ingest_chunk"):
        ShardReader([str(p)]).next_line()


# ------------------------------------------------------------ generator


def test_schedule_generator_is_deterministic_and_valid():
    gen = chaos.ScheduleGenerator()
    a = gen.sample(range(40))
    b = chaos.ScheduleGenerator().sample(range(40))
    assert [s.plan for s in a] == [s.plan for s in b]
    for s in a:
        assert s.rules, "every schedule carries at least one rule"
        faults.FaultPlan.from_spec(s.plan)  # registry-valid, eagerly


def test_generator_covers_the_nasty_interleavings():
    scen = {s.scenario for s in chaos.ScheduleGenerator().sample(
        range(40))}
    # Every biased scenario class appears within a small seed range —
    # the soak really does compose faults, not rerun one shape.
    assert {"commit_loss", "recovery_storm", "corrupt_burst",
            "truncate_loss", "hang", "ingest_abort",
            "compound"} <= scen
    multi = [s for s in chaos.ScheduleGenerator().sample(range(40))
             if len(s.rules) > 1]
    assert len(multi) >= 20, "schedules must be MULTI-fault plans"


def test_oracle_matches_the_unfaulted_stream():
    cfg = chaos.DrillConfig()
    clean = chaos.Schedule(seed=-1, scenario="golden", rules=())
    taps = chaos.oracle_tap(clean, cfg)
    assert len(taps) == cfg.steps
    assert taps[0].split(",")[0] == "0"
    # 96 rows / 16 per batch: epoch boundary at batch 6 restarts ids.
    assert taps[6].split(",")[0] == "0"


# ------------------------------------------------- tier-1 bounded soak


def test_tier1_chaos_soak_25_schedules_all_invariants_green(tmp_path):
    """ISSUE 10 acceptance: the bounded tier-1 soak runs >= 25 seeded
    multi-fault schedules deterministically within its time budget with
    every invariant green."""
    verdict = chaos.run_campaign(
        SOAK_SEEDS, base_dir=str(tmp_path),
        time_budget_s=SOAK_BUDGET_S,
        per_schedule_timeout_s=SOAK_PER_SCHEDULE_S,
        minimize_failures=False)
    failing = [(e["seed"], e["scenario"], e["plan"], e["violations"])
               for e in verdict["schedules"]
               if e["verdict"] != "green"]
    assert verdict["n_schedules"] >= 25
    assert not verdict["budget_exhausted"], (
        f"soak blew its {SOAK_BUDGET_S:.0f}s budget "
        f"({verdict['total_s']:.1f}s)")
    assert verdict["all_green"], failing
    # The soak is genuinely adversarial: several scenario classes and
    # several distinct outcomes (completed / hang_detected /
    # ingest_aborted) all appear.
    scenarios = {e["scenario"] for e in verdict["schedules"]}
    outcomes = {e["outcome"] for e in verdict["schedules"]}
    assert len(scenarios) >= 5
    assert {"completed", "hang_detected", "ingest_aborted"} <= outcomes


def test_canary_broken_recovery_is_caught_and_minimized(tmp_path):
    """ISSUE 10 acceptance: a deliberately-broken recovery path (the
    restore canary stops rewinding the stream cursor) is CAUGHT by the
    auditor and delta-debugged to a <= 2-rule reproducible plan."""
    cfg = dataclasses.replace(chaos.DrillConfig(), break_restore=True)
    # Seed 3 is a recovery_storm (pinned by the deterministic
    # generator) — a stream-comparable schedule with recovery faults,
    # exactly what a broken restore must corrupt.
    sched = chaos.ScheduleGenerator(cfg).schedule(3)
    assert sched.scenario == "recovery_storm" and len(sched.rules) >= 2
    verdict = chaos.run_campaign([3], cfg=cfg, base_dir=str(tmp_path),
                                 minimize_failures=True)
    assert not verdict["all_green"]
    (failure,) = verdict["failures"]
    violated = {v["invariant"] for v in failure["violations"]}
    assert "exactly_once_stream" in violated
    assert "loss_continuity" in violated
    assert failure["minimized_rules"] <= 2
    minimized = failure["minimized_plan"]
    assert minimized and "device_loss" in minimized
    # The minimized plan is itself a valid, replayable fault plan.
    faults.FaultPlan.from_spec(minimized)


def test_campaign_budget_exhaustion_is_loud(tmp_path):
    verdict = chaos.run_campaign([1, 2, 3], base_dir=str(tmp_path),
                                 time_budget_s=0.0,
                                 minimize_failures=False)
    # The golden run spends the zero budget: every schedule is
    # recorded as skipped, and the campaign refuses to call itself
    # green.
    assert verdict["n_skipped"] == 3
    assert verdict["budget_exhausted"]
    assert not verdict["all_green"]


# -------------------------------- cross-path recovery (compound faults)


def _native_stream_ok() -> bool:
    from fm_spark_tpu.data.native_stream import native_stream_supported

    return native_stream_supported("libsvm", 3)


@pytest.mark.parametrize("first_native", [True, False])
def test_cross_path_recovery_under_compound_faults(tmp_path,
                                                   first_native):
    """ISSUE 10 satellite: a run that survives an ``ingest_truncate``
    device loss + mid-step device loss on ONE ingest path checkpoints,
    then resumes on the OTHER path (native<->python), and the combined
    record stream, loss curve, and final params are bit-identical to
    the clean run — the exactly-once cursor really is path-portable
    under compound faults."""
    if not _native_stream_ok():
        pytest.skip("libfmfast.so native stream parser unavailable")
    from fm_spark_tpu import models
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data.native_stream import make_stream_batches
    from fm_spark_tpu.data.stream import RecordGuard, ShardReader
    from fm_spark_tpu.resilience.supervisor import (
        BackoffPolicy,
        Supervisor,
    )
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    cfg = chaos.DrillConfig()
    shards = chaos.build_shards(str(tmp_path / "shards"), cfg)
    golden = chaos.golden_run(cfg, str(tmp_path / "golden"),
                              shard_paths=shards)
    spec = models.FMSpec(num_features=cfg.num_features, rank=cfg.rank,
                         init_std=0.05)
    ck_dir = str(tmp_path / "ck")

    def leg(native: bool, steps: int, plan: str):
        guard = RecordGuard(
            "quarantine",
            quarantine_dir=str(tmp_path / f"q{int(native)}"))
        source = chaos._TapSource(make_stream_batches(
            ShardReader(shards, chunk_bytes=cfg.chunk_bytes), "libsvm",
            cfg.batch_size, cfg.max_nnz, guard=guard,
            num_features=cfg.num_features,
            native_ingest=True if native else False))
        config = TrainConfig(num_steps=steps,
                             batch_size=cfg.batch_size,
                             learning_rate=cfg.learning_rate,
                             lr_schedule="constant", log_every=1,
                             seed=cfg.seed)
        ck = Checkpointer(ck_dir, save_every=cfg.save_every,
                          async_save=False)
        sup = Supervisor(policy=BackoffPolicy(initial=0.01, jitter=0.0),
                         probe=lambda: True, breaker_threshold=8,
                         sleep=lambda s: None)
        trainer = FMTrainer(spec, config)
        trainer.logger._stream = None
        faults.clear()
        if plan:
            faults.activate(plan)
        try:
            trainer.fit(source, checkpointer=ck, supervisor=sup)
        finally:
            faults.clear()
            ck.close()
        return trainer, source

    # Leg 1 on path A survives the compound schedule and commits
    # through step 12; leg 2 on path B resumes the SAME chain.
    t1, s1 = leg(first_native, steps=12,
                 plan="ingest_truncate@3=device_loss;"
                      "train_step@7=device_loss")
    assert t1.step_count == 12
    t2, s2 = leg(not first_native, steps=cfg.steps, plan="")
    assert t2.step_count == cfg.steps

    combined = s1.lines[:12] + s2.lines
    assert combined == golden.tap
    assert t2.loss_history == golden.loss_history
    assert chaos._params_sums(t2.params) == golden.params_sums
    # The stream cursor is path-portable byte-for-byte (tap_len is the
    # wrapper's own bookkeeping — leg 2 only recorded its own batches).
    final = {k: v for k, v in s2.state().items() if k != "tap_len"}
    want = {k: v for k, v in golden.cursor.items() if k != "tap_len"}
    assert final == want


# ------------------- SIGKILL during flight-spool compaction (driven by
# ------------------- the chaos engine's subprocess runner)


def test_sigkill_during_spool_compaction_is_exactly_once(tmp_path):
    """ISSUE 10 satellite: the chaos engine SIGKILLs a drill mid-run
    with the flight ring sized so the spool is compacting (2N
    threshold), respawns it, and proves (a) exactly-once: the stitched
    record stream, loss curve, and final params are bit-identical to
    the clean run; (b) the spool survived the kill parseable with a
    monotonic, duplicate-free seq; (c) the checkpoint chain restores
    through last_good."""
    cfg = chaos.DrillConfig(flight_capacity=4)
    golden = chaos.golden_run(cfg, str(tmp_path / "golden"))
    result = chaos.run_schedule_subproc(
        "", cfg, str(tmp_path / "kill"), kill_at_step=9)
    assert result.outcome == "completed", (result.error, result.rcs)
    assert result.rcs[0] == -signal.SIGKILL  # the kill really landed
    assert result.rcs[-1] == 0               # rc discipline to the end
    assert result.resumed_at[0] == 0 and result.resumed_at[1] > 0

    # (a) exactly-once across the process death.
    assert chaos.stitch_taps(result) == golden.tap
    assert result.loss_history == golden.loss_history
    assert result.params_sums == golden.params_sums

    # (b) the spool: parseable after SIGKILL, seq monotonic and
    # duplicate-free ACROSS the respawn (the recorder seeds its seq
    # from the spool tail), and genuinely compacted (bounded to ~2N
    # lines while total recorded seq ran past it).
    from fm_spark_tpu.obs import read_spool

    spool = read_spool(os.path.join(str(tmp_path / "kill"), "obs",
                                    "flight.jsonl"))
    seqs = [e["seq"] for e in spool]
    assert seqs and seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert len(seqs) <= 2 * cfg.flight_capacity
    assert max(seqs) >= len(seqs)  # older lines were compacted away

    # (c) chain integrity, judged exactly like the campaign auditor.
    assert chaos._audit_chain(result, cfg) == []


@pytest.mark.slow
def test_subproc_timeout_bounds_a_silent_hang(tmp_path):
    """A hang at a point with NO watchdog budget emits nothing — the
    per-attempt timeout must still bound it (a blocking stdout read
    alone would wait out the full 3600s default hang)."""
    cfg = chaos.DrillConfig()
    t0 = time.monotonic()
    result = chaos.run_schedule_subproc(
        "ingest_truncate@1=hang", cfg, str(tmp_path / "silent"),
        attempts=1, timeout_s=10.0)
    assert result.outcome == "attempt_timeout"
    assert time.monotonic() - t0 < 60.0


@pytest.mark.slow
def test_soak_subprocess_hang_drill_exits_hang_rc_and_resumes(tmp_path):
    """Long-mode drill (tools/chaos_drill.py --soak): a REAL
    never-returning hang on the ingest chunk read is bounded by the
    exit-mode watchdog (rc 87), journaled, and the respawned attempt
    completes the run exactly-once. (Default flight ring: a capacity-4
    ring would compact the attempt-0 hang event away before the drill
    ends — the SIGKILL test owns the compaction-pressure variant.)"""
    cfg = chaos.DrillConfig()
    golden = chaos.golden_run(cfg, str(tmp_path / "golden"))
    result = chaos.run_schedule_subproc(
        "ingest_truncate@2=hang:300", cfg, str(tmp_path / "hang"),
        watchdog_spec="ingest_chunk=1.5")
    assert result.outcome == "completed", (result.error, result.rcs)
    assert result.rcs[0] == HANG_EXIT_RC
    assert chaos.stitch_taps(result) == golden.tap
    from fm_spark_tpu.obs import read_spool

    spool = read_spool(os.path.join(str(tmp_path / "hang"), "obs",
                                    "flight.jsonl"))
    assert any(e.get("kind") == "hang_detected" for e in spool)


# ----------------------------------------------------- drill CLI verdict


def test_chaos_drill_cli_writes_verdict_and_exits_green(tmp_path,
                                                        capsys):
    import importlib.util
    import json
    import sys

    spec = importlib.util.spec_from_file_location(
        "chaos_drill_tool", os.path.join(REPO, "tools",
                                         "chaos_drill.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)

    rc = mod.main(["--schedules", "2", "--no-minimize",
                   "--work-dir", str(tmp_path / "work"),
                   "--out", str(tmp_path / "obs")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ALL GREEN" in out
    run_dirs = os.listdir(str(tmp_path / "obs"))
    assert len(run_dirs) == 1
    with open(os.path.join(str(tmp_path / "obs"), run_dirs[0],
                           "chaos_verdict.json")) as f:
        verdict = json.load(f)
    assert verdict["n_schedules"] == 2 and verdict["all_green"]
    assert verdict["run_id"] == run_dirs[0]
    assert verdict["mode"] == "bounded"
    # Every entry names its seed + plan: the verdict IS the repro.
    for e in verdict["schedules"]:
        assert e["plan"] and isinstance(e["seed"], int)


# --------------------------------- drift/rollback drills (ISSUE 13)


def test_drift_schedules_deterministic_and_cover_the_class():
    gen = [chaos.drift_schedule(s) for s in chaos.DRIFT_TIER1_SEEDS]
    again = [chaos.drift_schedule(s) for s in chaos.DRIFT_TIER1_SEEDS]
    assert [s.plan for s in gen] == [s.plan for s in again]
    scenarios = {s.scenario for s in gen}
    # The five tier-1 seeds cover the whole failure class: the clean
    # protocol, the eval crash (online_eval), the commit-window crash
    # (ckpt_commit), the mid-demotion crash (ckpt_demote), and
    # rollback under quarantine ingest corruption (ingest_corrupt).
    assert scenarios == {"drift_clean_drift", "drift_eval_fault",
                         "drift_commit_fault", "drift_demote_fault",
                         "drift_rollback_corruption"}
    for s in gen:
        s.validate()  # every plan parses against the registry


def test_tier1_drift_campaign_all_invariants_green(tmp_path):
    """ISSUE 13 acceptance: the five seeded drift/rollback schedules
    run the PRODUCTION online loop (label-flip drift, streaming day
    shards, FTRL, crash-consistent chain) under fault plans, and the
    artifact auditor proves — for every schedule — completion across
    respawns, the sentry firing at the first drifted day, demotion
    tombstones + a never-vetoed last_good, the exactly-once per-day
    record stream, and byte-identical final params vs the clean run."""
    entries = chaos.run_drift_campaign(base_dir=str(tmp_path))
    failing = [(e["seed"], e["scenario"], e["violations"])
               for e in entries if e["verdict"] != "green"]
    assert len(entries) == 5
    assert not failing, failing
    assert all(e["rollbacks"] >= 1 for e in entries)
    assert all(e["demoted"] for e in entries)
