"""Field-sharded FieldFFM (config 4's multi-chip fast path, VERDICT r2
#3): the 1-D feat-mesh step — one sel all_to_all for the transposed
cross-field blocks, single-owner table writes — must match the
single-chip fused FFM body step-for-step, with and without the compact
paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.ops.scatter import compact_aux
from fm_spark_tpu.parallel import (
    evaluate_field_sharded,
    make_field_ffm_sharded_step,
    make_field_mesh,
    pad_field_batch,
    shard_compact_aux,
    shard_field_batch,
    shard_field_params,
    stack_field_params,
    unstack_field_params,
)
from fm_spark_tpu.sparse import make_field_ffm_sparse_sgd_step
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B = 5, 32, 3, 64


def _spec(**kw):
    kw.setdefault("param_dtype", "float32")
    return models.FieldFFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, **kw
    )


def _batch(rng, b=B):
    ids = rng.integers(0, BUCKET, size=(b, F)).astype(np.int32)
    ids[:, 0] = rng.integers(0, 3, b)
    vals = rng.normal(size=(b, F)).astype(np.float32)
    labels = rng.integers(0, 2, b).astype(np.float32)
    weights = np.ones(b, np.float32)
    weights[::7] = 0.0
    return ids, vals, labels, weights


def _run_pair(rng, config, n_feat=8, steps=3, caux_builder=None,
              n_row=1, spec_kw=None, loss_rel=2e-5, param_rtol=2e-5,
              param_atol=1e-6):
    ids, vals, labels, weights = _batch(rng)
    spec = _spec(**(spec_kw or {}))
    canonical = spec.init(jax.random.key(1))
    single = make_field_ffm_sparse_sgd_step(spec, config)
    mesh = make_field_mesh(n_feat * n_row, n_row=n_row)
    sharded = make_field_ffm_sharded_step(spec, config, mesh)
    sp = shard_field_params(
        stack_field_params(spec, jax.tree.map(jnp.copy, canonical),
                           n_feat),
        mesh,
    )
    batch = pad_field_batch((ids, vals, labels, weights), F, n_feat)
    aux_single = None
    caux = None
    if caux_builder is not None:
        aux_np = caux_builder(ids)
        aux_single = tuple(jnp.asarray(a) for a in aux_np)
        caux = shard_compact_aux(aux_np, mesh, n_feat)
    for i in range(steps):
        args = (jnp.int32(i), jnp.asarray(ids), jnp.asarray(vals),
                jnp.asarray(labels), jnp.asarray(weights))
        if aux_single is not None:
            canonical, l1 = single(canonical, *args, aux_single)
        else:
            canonical, l1 = single(canonical, *args)
        sargs = (jnp.int32(i), *shard_field_batch(batch, mesh))
        if caux is not None:
            sp, l2 = sharded(sp, *sargs, caux)
        else:
            sp, l2 = sharded(sp, *sargs)
        assert float(l1) == pytest.approx(float(l2), rel=loss_rel), i
    got = unstack_field_params(spec, jax.device_get(sp))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=param_rtol, atol=param_atol,
        ),
        canonical, got,
    )


@pytest.mark.parametrize("mode", ["scatter_add", "dedup"])
def test_sharded_ffm_matches_single_chip(rng, mode):
    _run_pair(
        rng,
        TrainConfig(learning_rate=0.1, optimizer="sgd",
                    sparse_update=mode, reg_factors=1e-4,
                    reg_linear=1e-4),
    )


def test_sharded_ffm_host_compact_matches_single_chip(rng):
    _run_pair(
        rng,
        TrainConfig(learning_rate=0.1, optimizer="sgd",
                    sparse_update="dedup", host_dedup=True,
                    compact_cap=B),
        caux_builder=lambda ids: compact_aux(ids, B),
    )


def test_sharded_ffm_device_compact_matches_single_chip(rng):
    _run_pair(
        rng,
        TrainConfig(learning_rate=0.1, optimizer="sgd",
                    sparse_update="dedup", compact_device=True,
                    compact_cap=B),
    )


def test_sharded_ffm_uneven_fields(rng):
    # F=5 on 4 chips: f_pad=8, padded fields + padded sel targets must
    # stay inert.
    _run_pair(
        rng,
        TrainConfig(learning_rate=0.1, optimizer="sgd",
                    sparse_update="dedup"),
        n_feat=4,
    )


def test_sharded_ffm_eval(rng):
    ids, vals, labels, weights = _batch(rng)
    spec = _spec()
    mesh = make_field_mesh(8)
    sp = shard_field_params(
        stack_field_params(spec, spec.init(jax.random.key(1)), 8), mesh
    )
    em = evaluate_field_sharded(
        spec, mesh, sp, [(ids, vals, labels, weights)]
    )
    assert float(em["count"]) == float(weights.sum())
    # Scores must agree with the canonical single-chip forward.
    canonical = unstack_field_params(spec, jax.device_get(sp))
    want = np.asarray(
        spec.scores(canonical, jnp.asarray(ids), jnp.asarray(vals))
    )
    from fm_spark_tpu.ops import losses as losses_lib
    from fm_spark_tpu.utils import metrics as metrics_lib

    per = losses_lib.loss_fn(spec.loss)(jnp.asarray(want),
                                        jnp.asarray(labels))
    m = metrics_lib.init_metrics()
    m = metrics_lib.update_metrics(
        m, jnp.asarray(want), jnp.asarray(labels), per,
        jnp.asarray(weights),
        predictions=jax.nn.sigmoid(jnp.asarray(want)),
    )
    got = metrics_lib.finalize_metrics(m)
    assert float(em["logloss"]) == pytest.approx(float(got["logloss"]),
                                                 rel=1e-5)
    assert float(em["auc"]) == pytest.approx(float(got["auc"]), abs=1e-6)


@pytest.mark.parametrize("mode", ["scatter_add", "dedup"])
def test_sharded_ffm_2d_matches_single_chip(rng, mode):
    # Round 4 (VERDICT r3 #5): the 2-D (feat, row) FFM step — bucket
    # ranges row-sharded with ownership-masked sel partials completed
    # by one psum over row; must match single-chip step-for-step.
    _run_pair(
        rng,
        TrainConfig(learning_rate=0.1, optimizer="sgd",
                    sparse_update=mode, reg_factors=1e-4,
                    reg_linear=1e-4),
        n_feat=4, n_row=2,
    )


def test_sharded_ffm_2d_device_compact_matches_single_chip(rng):
    _run_pair(
        rng,
        TrainConfig(learning_rate=0.1, optimizer="sgd",
                    sparse_update="dedup", compact_device=True,
                    compact_cap=B),
        n_feat=4, n_row=2,
    )


def test_sharded_ffm_2d_uneven_fields_sr(rng):
    # f_pad padding + dedup_sr's per-(field, row-shard) SR key streams
    # on the 2-D mesh, bf16 storage. The streams INTENTIONALLY differ
    # from the single-chip (step, field) keys for row shards > 0 (noise
    # must not correlate across chips sharing a field), so the bar here
    # is bf16-SR-noise closeness — one rounding quantum per update —
    # not exactness; the fp32 2-D tests above pin the deterministic
    # math exactly.
    _run_pair(
        rng,
        TrainConfig(learning_rate=0.1, optimizer="sgd",
                    sparse_update="dedup_sr", reg_factors=1e-4),
        n_feat=2, n_row=2, spec_kw=dict(param_dtype="bfloat16"),
        loss_rel=3e-3, param_rtol=0.1, param_atol=3e-2,
    )


def test_sharded_ffm_2d_eval(rng):
    ids, vals, labels, weights = _batch(rng)
    spec = _spec()
    mesh = make_field_mesh(8, n_row=2)
    sp = shard_field_params(
        stack_field_params(spec, spec.init(jax.random.key(1)), 4), mesh
    )
    em = evaluate_field_sharded(
        spec, mesh, sp, [(ids, vals, labels, weights)]
    )
    assert float(em["count"]) == float(weights.sum())
    canonical = unstack_field_params(spec, jax.device_get(sp))
    want = np.asarray(
        spec.scores(canonical, jnp.asarray(ids), jnp.asarray(vals))
    )
    from fm_spark_tpu.ops import losses as losses_lib
    from fm_spark_tpu.utils import metrics as metrics_lib

    per = losses_lib.loss_fn(spec.loss)(jnp.asarray(want),
                                        jnp.asarray(labels))
    m = metrics_lib.init_metrics()
    m = metrics_lib.update_metrics(
        m, jnp.asarray(want), jnp.asarray(labels), per,
        jnp.asarray(weights),
        predictions=jax.nn.sigmoid(jnp.asarray(want)),
    )
    got = metrics_lib.finalize_metrics(m)
    assert float(em["logloss"]) == pytest.approx(float(got["logloss"]),
                                                 rel=1e-5)


def test_sharded_ffm_2d_rejects_host_compact():
    from fm_spark_tpu.parallel import make_field_ffm_sharded_body

    spec = _spec()
    mesh = make_field_mesh(8, n_row=2)
    with pytest.raises(ValueError, match="1-D"):
        make_field_ffm_sharded_body(
            spec, TrainConfig(optimizer="sgd", sparse_update="dedup",
                              host_dedup=True, compact_cap=B), mesh
        )
