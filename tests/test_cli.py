"""configs registry + CLI end-to-end (train → save → eval → predict).

The CLI is the rebuild's example-driver parity surface (SURVEY.md §2
row 8); these tests run it in-process on synthetic data, covering every
registered config's spec construction and the train/eval/predict cycle.
"""

import dataclasses
import json

import numpy as np
import pytest

from fm_spark_tpu import configs as configs_lib
from fm_spark_tpu import cli


def test_registry_has_all_five_baseline_configs():
    names = set(configs_lib.CONFIGS)
    assert names == {
        "movielens_fm_r8",
        "criteo_kaggle_fm_r32",
        "criteo1tb_fm_r64",
        "avazu_ffm_r16",
        "criteo1tb_deepfm",
    }


@pytest.mark.parametrize("name", sorted(configs_lib.CONFIGS))
def test_every_config_builds_a_spec(name):
    cfg = configs_lib.get_config(name)
    spec = cfg.spec(1000 if cfg.bucket <= 0 else None)
    assert spec.rank == cfg.rank
    tc = cfg.train_config(num_steps=3)
    assert tc.num_steps == 3


def test_field_local_id_conversion_covers_every_field_model():
    # Regression (round-2 review): the id-conversion gate must key on the
    # single field_local_ids predicate — a field-partitioned model missed
    # by a hardcoded name tuple trains on silently-clamped ids.
    import argparse

    for model in ("field_fm", "field_ffm", "field_deepfm"):
        cfg = dataclasses.replace(
            configs_lib.CONFIGS["criteo1tb_fm_r64"],
            name=f"t_{model}", model=model, bucket=64, num_fields=5,
            rank=4,
        )
        assert cfg.field_local_ids
        args = argparse.Namespace(synthetic=300, data=None)
        ids, vals, labels, _ = cli.load_dataset(cfg, args)
        assert ids.max() < cfg.bucket, (
            f"{model}: ids not field-local — would clamp into table edge"
        )
        spec = cfg.spec()
        assert getattr(spec, "field_local_ids", False)
    # Non-field models keep global/dense ids.
    assert not configs_lib.CONFIGS["movielens_fm_r8"].field_local_ids
    assert not configs_lib.CONFIGS["criteo_kaggle_fm_r32"].field_local_ids


def test_flagship_config_uses_fused_scale_out_not_dense_row():
    # VERDICT r1 #7: the at-scale CTR path is the fused field-sharded
    # step; the dense-gradient 'row' strategy must not be presented as
    # config 3's scale-out.
    cfg = configs_lib.get_config("criteo1tb_fm_r64")
    assert cfg.strategy == "field_sparse"
    assert "row-shards" in cfg.description or "--row-shards" in cfg.description
    assert "fallback" in cfg.description


def test_get_config_overrides_and_unknown():
    cfg = configs_lib.get_config("movielens_fm_r8", batch_size=64)
    assert cfg.batch_size == 64
    assert configs_lib.get_config("movielens_fm_r8").batch_size != 64 or True
    with pytest.raises(KeyError):
        configs_lib.get_config("nope")


def test_cli_list_configs(capsys):
    assert cli.main(["list-configs"]) == 0
    out = capsys.readouterr().out
    for name in configs_lib.CONFIGS:
        assert name in out


def _train_eval_predict(tmp_path, config_name, capsys, steps="30"):
    model_dir = str(tmp_path / "model")
    rc = cli.main([
        "train", "--config", config_name, "--synthetic", "2000",
        "--steps", steps, "--batch-size", "256", "--model-out", model_dir,
        "--log-every", "10",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    eval_line = [l for l in out.splitlines() if '"eval"' in l][-1]
    metrics = json.loads(eval_line)["eval"]
    assert np.isfinite(metrics["logloss"])

    assert cli.main([
        "eval", "--model", model_dir, "--config", config_name,
        "--synthetic", "500",
    ]) == 0
    m = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert 0.0 <= m["auc"] <= 1.0

    pred_file = tmp_path / "preds.txt"
    assert cli.main([
        "predict", "--model", model_dir, "--config", config_name,
        "--synthetic", "500", "--out", str(pred_file),
    ]) == 0
    preds = np.loadtxt(pred_file)
    assert preds.shape[0] == 500
    assert np.all((preds >= 0) & (preds <= 1))
    return metrics


def test_cli_train_fm_single(tmp_path, capsys):
    _train_eval_predict(tmp_path, "movielens_fm_r8", capsys)


def test_cli_train_field_sparse(tmp_path, capsys):
    # criteo1tb_fm_r64 at full shape is too big for CPU tests; shrink it
    # via a temporary registry entry exercising the same code path.
    small = dataclasses.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"],
        name="criteo_small", bucket=64, num_fields=5,
    )
    configs_lib.CONFIGS["criteo_small"] = small
    try:
        _train_eval_predict(tmp_path, "criteo_small", capsys)
    finally:
        del configs_lib.CONFIGS["criteo_small"]


@pytest.mark.slow
def test_cli_train_field_deepfm(tmp_path, capsys):
    # Config 5's CTR fast path (field-partitioned embedding + dense Adam
    # head), shrunk; exercises the sharded deepfm loop on the fake mesh
    # including model save/eval/predict roundtrip.
    small = dataclasses.replace(
        configs_lib.CONFIGS["criteo1tb_deepfm"],
        name="deepfm_small", bucket=64, num_fields=5, rank=4,
        mlp_dims=(16, 16, 16),
    )
    configs_lib.CONFIGS["deepfm_small"] = small
    try:
        _train_eval_predict(tmp_path, "deepfm_small", capsys)
    finally:
        del configs_lib.CONFIGS["deepfm_small"]


def test_cli_train_dp(tmp_path, capsys):
    small = dataclasses.replace(
        configs_lib.CONFIGS["criteo_kaggle_fm_r32"],
        name="kaggle_small", bucket=64, num_fields=5, rank=4,
    )
    configs_lib.CONFIGS["kaggle_small"] = small
    try:
        rc = cli.main([
            "train", "--config", "kaggle_small", "--synthetic", "2000",
            "--steps", "10", "--batch-size", "256", "--log-every", "5",
        ])
        assert rc == 0
    finally:
        del configs_lib.CONFIGS["kaggle_small"]


def test_cli_train_row_sharded(tmp_path, capsys):
    small = dataclasses.replace(
        configs_lib.CONFIGS["criteo_kaggle_fm_r32"],
        name="row_small", bucket=64, num_fields=4, rank=4, strategy="row",
    )
    configs_lib.CONFIGS["row_small"] = small
    try:
        rc = cli.main([
            "train", "--config", "row_small", "--synthetic", "1000",
            "--steps", "8", "--batch-size", "256", "--log-every", "4",
        ])
        assert rc == 0
    finally:
        del configs_lib.CONFIGS["row_small"]


def test_cli_train_ffm_and_deepfm(tmp_path, capsys):
    for base_name, small_kw in [
        ("avazu_ffm_r16", dict(bucket=32, num_fields=4, rank=4)),
        ("criteo1tb_deepfm",
         dict(bucket=32, num_fields=4, rank=4, mlp_dims=(16, 16, 16),
              strategy="single")),
    ]:
        small = dataclasses.replace(
            configs_lib.CONFIGS[base_name], name="tiny", **small_kw
        )
        configs_lib.CONFIGS["tiny"] = small
        try:
            rc = cli.main([
                "train", "--config", "tiny", "--synthetic", "1000",
                "--steps", "10", "--batch-size", "128", "--log-every", "5",
            ])
            assert rc == 0
        finally:
            del configs_lib.CONFIGS["tiny"]


def test_cli_train_movielens_file(tmp_path, capsys):
    # A real ratings file through the movielens loader path.
    rng = np.random.default_rng(0)
    path = tmp_path / "u.data"
    rows = [
        f"{rng.integers(1, 50)}\t{rng.integers(1, 80)}\t"
        f"{rng.integers(1, 6)}\t0"
        for _ in range(1000)
    ]
    path.write_text("\n".join(rows) + "\n")
    model_dir = str(tmp_path / "model")
    rc = cli.main([
        "train", "--config", "movielens_fm_r8", "--data", str(path),
        "--steps", "30", "--batch-size", "128", "--model-out", model_dir,
        "--log-every", "10",
    ])
    assert rc == 0


def test_cli_field_sparse_checkpoint_resume(tmp_path, capsys):
    # Kill-and-resume through the CLI fast path: run 1 stops at 10 steps,
    # run 2 (same flags, more steps) must resume from the checkpoint.
    small = dataclasses.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"],
        name="ck_small", bucket=64, num_fields=5,
    )
    configs_lib.CONFIGS["ck_small"] = small
    ck = str(tmp_path / "ck")
    common = [
        "train", "--config", "ck_small", "--synthetic", "1000",
        "--batch-size", "128", "--log-every", "5",
        "--checkpoint-dir", ck, "--checkpoint-every", "5",
        "--test-fraction", "0",
    ]
    try:
        assert cli.main(common + ["--steps", "10"]) == 0
        capsys.readouterr()
        assert cli.main(common + ["--steps", "14"]) == 0
        out = capsys.readouterr().out
        steps = [json.loads(l)["step"] for l in out.splitlines()
                 if '"step"' in l]
        # Resumed run must start past step 10, not from 1.
        assert min(steps) > 10
    finally:
        del configs_lib.CONFIGS["ck_small"]


def test_libfm_rejects_ffm():
    import jax
    import pytest as _pytest

    from fm_spark_tpu import models as m
    from fm_spark_tpu.models.libfm_io import save_libfm

    spec = m.FFMSpec(num_features=8, rank=2, num_fields=2)
    params = spec.init(jax.random.key(0))
    with _pytest.raises(ValueError, match="plain FM"):
        save_libfm("/tmp/x.libfm", spec, params)


@pytest.mark.slow
def test_compat_positional_train_signatures():
    from fm_spark_tpu.compat import FFMWithSGD, FMWithLBFGS
    from fm_spark_tpu.data import synthetic_ctr

    data = synthetic_ctr(300, 60, 3, seed=0)
    m1 = FMWithLBFGS.train(data, "classification", 5)
    m2 = FFMWithSGD.train(data, "classification", 5, 0.1)
    assert m1.predict(data[0][:4], data[1][:4]).shape == (4,)
    assert m2.predict(data[0][:4], data[1][:4]).shape == (4,)


@pytest.mark.slow
def test_cli_preprocess_and_packed_streaming_train(tmp_path, capsys):
    from fm_spark_tpu.data import criteo

    raw = tmp_path / "day0.tsv"
    criteo.synthesize_tsv(str(raw), 600, seed=0)
    small = dataclasses.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"],
        name="packed_small", bucket=64, num_fields=39,
    )
    configs_lib.CONFIGS["packed_small"] = small
    packed = str(tmp_path / "packed")
    try:
        assert cli.main([
            "preprocess", "--config", "packed_small",
            "--input", str(raw), "--out-dir", packed,
        ]) == 0
        capsys.readouterr()
        model_dir = str(tmp_path / "model")
        assert cli.main([
            "train", "--config", "packed_small", "--data", packed,
            "--steps", "10", "--batch-size", "64", "--log-every", "5",
            "--model-out", model_dir, "--test-fraction", "0.2",
        ]) == 0
        out = capsys.readouterr().out
        assert '"saved"' in out
        # --test-fraction on packed data must produce holdout metrics.
        eval_line = [l for l in out.splitlines() if '"eval"' in l][-1]
        assert np.isfinite(json.loads(eval_line)["eval"]["logloss"])
        # Shapes must match: saved model evals on spec-derived synthetic.
        assert cli.main([
            "eval", "--model", model_dir, "--synthetic", "200",
        ]) == 0
        capsys.readouterr()
        # And on the packed dir itself (streaming finite pass).
        assert cli.main([
            "eval", "--model", model_dir, "--config", "packed_small",
            "--data", packed,
        ]) == 0
        m = json.loads(capsys.readouterr().out.splitlines()[-1])
        assert m["count"] == 600.0
        pred_file = tmp_path / "p.txt"
        assert cli.main([
            "predict", "--model", model_dir, "--config", "packed_small",
            "--data", packed, "--out", str(pred_file),
        ]) == 0
        assert np.loadtxt(pred_file).shape[0] == 600
    finally:
        del configs_lib.CONFIGS["packed_small"]


def test_cli_eval_data_requires_config(tmp_path, capsys):
    model_dir = str(tmp_path / "model")
    assert cli.main([
        "train", "--config", "movielens_fm_r8", "--synthetic", "500",
        "--steps", "5", "--batch-size", "128", "--model-out", model_dir,
        "--test-fraction", "0", "--log-every", "5",
    ]) == 0
    with pytest.raises(SystemExit, match="needs --config"):
        cli.main(["eval", "--model", model_dir, "--data", "/tmp/nope"])


def test_eval_every_field_sparse_strategy(capsys):
    # Periodic eval must work in the non-FMTrainer loops too.
    small = dataclasses.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"],
        name="ee_small", bucket=64, num_fields=5,
    )
    configs_lib.CONFIGS["ee_small"] = small
    try:
        rc = cli.main([
            "train", "--config", "ee_small", "--synthetic", "2000",
            "--steps", "24", "--batch-size", "256", "--log-every", "8",
            "--eval-every", "8",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        eval_lines = [l for l in out.splitlines() if "eval_auc" in l]
        assert len(eval_lines) == 3  # steps 8, 16, 24
    finally:
        del configs_lib.CONFIGS["ee_small"]


def test_field_sparse_capability_guards():
    """The _FIELD_CAPS table drives every field_sparse guard: requests a
    family's steps can't serve must hard-fail (never silently fall back)
    — one test per capability column."""
    import pytest

    def run(name, base, extra, small_kw, batch=128):
        small = dataclasses.replace(
            configs_lib.CONFIGS[base], name=name,
            strategy="field_sparse", **small_kw
        )
        configs_lib.CONFIGS[name] = small
        bs = [] if batch is None else ["--batch-size", str(batch)]
        try:
            return cli.main([
                "train", "--config", name, "--synthetic", "512",
                "--steps", "4", *bs, *extra,
            ])
        finally:
            del configs_lib.CONFIGS[name]

    ffm_kw = dict(bucket=32, num_fields=4, rank=4)
    deepfm_kw = dict(bucket=32, num_fields=4, rank=4,
                     mlp_dims=(8, 8))
    # FFM 2-D row sharding is supported since round 4 (sel partials
    # completed by one psum over `row` — field_step._ffm_field_forward).
    assert run("g1", "avazu_ffm_r16", ["--row-shards", "2"], ffm_kw) == 0
    # steps-per-call rolls the SHARDED FM/FFM steps too since round 4
    # (fori inside the shard_map); on the 8-fake-device env this runs
    # the sharded FFM roll end-to-end.
    assert run("g2", "avazu_ffm_r16", ["--steps-per-call", "2"],
               ffm_kw) == 0
    # Sharded DeepFM takes the DEVICE-built compact aux (round 3) but
    # still rejects the host-built one.
    assert run("g3", "criteo1tb_deepfm",
               ["--compact-device", "--compact-cap", "64",
                "--sparse-update", "dedup"], deepfm_kw) == 0
    with pytest.raises(SystemExit, match="not supported"):
        run("g3b", "criteo1tb_deepfm",
            ["--host-dedup", "--compact-cap", "64",
             "--sparse-update", "dedup"], deepfm_kw)
    # Host-built compact aux + --row-shards (2-D) cannot compose.
    fm_kw = dict(bucket=64, num_fields=4, rank=4)
    with pytest.raises(SystemExit, match="compact-device"):
        run("g4", "criteo1tb_fm_r64",
            ["--host-dedup", "--compact-cap", "64", "--sparse-update",
             "dedup", "--row-shards", "2"], fm_kw)
    # Sharded device-compact FFM is SUPPORTED — must run clean.
    assert run("g5", "avazu_ffm_r16",
               ["--compact-device", "--compact-cap", "128",
                "--sparse-update", "dedup"], ffm_kw) == 0
    # DeepFM on the 2-D (feat, row) mesh with the device-built compact
    # aux (round 3) — must run clean, eval included.
    assert run("g6", "criteo1tb_deepfm",
               ["--row-shards", "2", "--compact-device",
                "--compact-cap", "128", "--sparse-update", "dedup",
                "--eval-every", "2", "--test-fraction", "0.2"],
               deepfm_kw) == 0
    # HOST-built compact aux on the sharded (1-D, single-process) FM
    # step — the DedupAuxBatches→stack_compact_aux producer chain the
    # round-4 refactor touched; must run clean end-to-end.
    assert run("g7", "criteo1tb_fm_r64",
               ["--host-dedup", "--compact-cap", "128",
                "--sparse-update", "dedup"], fm_kw) == 0
    # Round-4 levers end-to-end: bf16 wire + score-sharded on the
    # sharded FM step, with weak-scaling batch sizing (global batch =
    # per-chip x 8 fake devices).
    assert run("g8", "criteo1tb_fm_r64",
               ["--collective-dtype", "bfloat16", "--score-sharded",
                "--batch-per-chip", "16"], fm_kw, batch=None) == 0
    with pytest.raises(SystemExit, match="exclusive"):
        run("g9", "criteo1tb_fm_r64",
            ["--batch-per-chip", "16"], fm_kw)
    # Round-5 lever: the example-sharded deep head on the sharded
    # DeepFM step (with bf16 wire) — must run clean end-to-end; FM has
    # no deep head, so the registry guard must hard-fail it.
    assert run("g10", "criteo1tb_deepfm",
               ["--deep-sharded", "--collective-dtype", "bfloat16"],
               deepfm_kw) == 0
    with pytest.raises(SystemExit, match="deep-sharded"):
        run("g11", "criteo1tb_fm_r64", ["--deep-sharded"], fm_kw)
    # Round-5 composed kernels through the CLI registry: --gfull-fused
    # alone and composed with --segtotal-pallas over the device-built
    # compact aux (the measured 1.356M headline combination's scale-out
    # form, PERF.md round-5 table) — must run clean end-to-end.
    assert run("g12", "criteo1tb_fm_r64", ["--gfull-fused"], fm_kw) == 0
    assert run("g13", "criteo1tb_fm_r64",
               ["--gfull-fused", "--segtotal-pallas", "--compact-device",
                "--compact-cap", "128", "--sparse-update", "dedup"],
               fm_kw) == 0


def test_help_renders_for_every_subcommand(capsys):
    # argparse expands help strings with %-formatting at RENDER time, so
    # an unescaped literal % in any flag's help crashes --help for the
    # whole subcommand (round 5: the --gfull-fused lever help's "~+8%"
    # broke `train --help` with "%o format: an integer is required").
    # Render every subcommand's help to pin this class of regression.
    for sub in ("train", "eval", "predict", "preprocess", "list-configs"):
        with pytest.raises(SystemExit) as exc:
            cli.build_parser().parse_args([sub, "--help"])
        assert exc.value.code == 0
        assert capsys.readouterr().out  # non-empty rendered help


def test_distributed_flag_plumbs_initialize(monkeypatch):
    # --distributed must call jax.distributed.initialize BEFORE any
    # backend work: bare flag -> auto-detect (no kwargs); explicit
    # triple -> passed through; partial triple / orphan flags -> hard
    # fail (a partial triple would auto-detect against the wrong
    # cluster). The hook is exercised directly; cmd_train's call
    # ORDERING (init before the first backend touch) is pinned in
    # test_distributed_init_precedes_backend_touch.
    import jax

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))

    def parse(extra):
        return cli.build_parser().parse_args(
            ["train", "--config", "movielens_fm_r8", "--synthetic", "64"]
            + extra)

    from fm_spark_tpu.cli import _maybe_init_distributed

    _maybe_init_distributed(parse([]))
    assert calls == []  # no flag -> no init

    _maybe_init_distributed(parse(["--distributed"]))
    assert calls == [{}]  # auto-detect form

    calls.clear()
    _maybe_init_distributed(parse(
        ["--distributed", "--coordinator", "127.0.0.1:1234",
         "--num-processes", "2", "--process-id", "1"]))
    assert calls == [{"coordinator_address": "127.0.0.1:1234",
                      "num_processes": 2, "process_id": 1}]

    with pytest.raises(SystemExit):
        _maybe_init_distributed(parse(
            ["--distributed", "--coordinator", "127.0.0.1:1234"]))
    with pytest.raises(SystemExit):
        _maybe_init_distributed(parse(["--num-processes", "2"]))


def test_distributed_init_precedes_backend_touch():
    # On a pod slice, jax.distributed.initialize must run before the
    # backend initializes (a single-process backend init first would
    # break multi-host). Pin the cmd_train ordering structurally: the
    # hook call appears before the first backend-touching call.
    import inspect

    src = inspect.getsource(cli.cmd_train)
    hook = src.index("_maybe_init_distributed(args)")
    for touch in ("device_count", "process_count", "jax.devices"):
        if touch in src:
            assert hook < src.index(touch), touch


def test_readme_multihost_exemplar_validates():
    # The README "Multi-host" quick-start command must parse and pass
    # the lever validator — a lever rename or a new validation rule
    # that breaks the documented command should fail here, not in a
    # user's pod job. The command is EXTRACTED from README.md (not
    # hand-copied), so an edit to either side re-validates the pair;
    # only host-environment flags (--data, --checkpoint-dir) are
    # swapped for --synthetic.
    import os
    import re
    import shlex

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "README.md")) as f:
        text = f.read()
    # Continuation lines first ([^\n]*\\\n repeated), then the final
    # line — the naive [^\n]*(?:\\\n...)* form never extends past the
    # first line (the zero-iteration group already succeeds, and greedy
    # quantifiers don't backtrack to lengthen a match).
    cmds = [m.group(0).replace("\\\n", " ") for m in re.finditer(
        r"python -m fm_spark_tpu\.cli train(?:[^\n]*\\\n)*[^\n]*", text)]
    dist = [c for c in cmds if "--distributed" in c]
    assert len(dist) == 1, "expected exactly one --distributed exemplar"
    argv = shlex.split(dist[0])[3:]  # drop 'python -m fm_spark_tpu.cli'
    cleaned, i = [], 0
    while i < len(argv):
        if argv[i] in ("--data", "--checkpoint-dir"):
            i += 2
            continue
        cleaned.append(argv[i])
        i += 1
    args = cli.build_parser().parse_args(cleaned + ["--synthetic", "64"])
    assert args.distributed
    from fm_spark_tpu.cli import _lever_overrides
    from fm_spark_tpu.cli_levers import check_levers_any

    cfg = configs_lib.get_config(args.config)
    tconfig = cfg.train_config(**_lever_overrides(args))
    assert check_levers_any(tconfig) is None
    assert tconfig.compact_device and tconfig.score_sharded
    assert tconfig.collective_dtype == "bfloat16"


def test_cap_advise_bounds_and_format(tmp_path, capsys):
    """cap-advise's recommendation must bound the observed per-field
    unique count with headroom, stay a 512 multiple (segtotal tile),
    and never exceed the batch size."""
    import json as json_lib

    from fm_spark_tpu.cli import build_parser
    from fm_spark_tpu.data import PackedWriter

    rng = np.random.default_rng(0)
    n, f, bucket = 3000, 5, 200
    ids = (rng.integers(0, bucket, size=(n, f))
           + np.arange(f) * bucket).astype(np.int32)
    labels = rng.integers(0, 2, n).astype(np.int8)
    with PackedWriter(str(tmp_path / "pk"), f, store_vals=False) as w:
        w.append(ids, labels)
    args = build_parser().parse_args([
        "cap-advise", "--data", str(tmp_path / "pk"),
        "--batch-size", "256", "--batches", "4",
    ])
    assert args.fn(args) == 0
    out = json_lib.loads(capsys.readouterr().out.strip())
    rec = out["recommended_compact_cap"]
    assert rec % 512 == 0 or rec == 256  # tile-rounded unless batch-capped
    assert rec <= 256
    assert out["max_unique_per_field_overall"] <= 256
    assert len(out["per_field_max"]) == f
    assert max(out["per_field_max"]) == out["max_unique_per_field_overall"]
    if rec % 512:
        # Sub-tile batch: the note must not claim tile rounding.
        assert "NOT tile-aligned" in out["note"]


def test_cap_advise_clamp_note_matches_value(tmp_path, capsys):
    """When the recommendation is clamped to a non-512-multiple batch
    size, the note must stop claiming tile rounding (ADVICE r5) — and
    the clamp itself must stay batch_size, the only value that bounds
    ANY future batch's unique count unconditionally (rounding down to
    the tile could dip under a future batch the scan never saw)."""
    import json as json_lib

    from fm_spark_tpu.cli import build_parser
    from fm_spark_tpu.data import PackedWriter

    rng = np.random.default_rng(1)
    n, f, bucket = 4000, 5, 1000
    # Per-field unique count near 500 at batch 1000 (each residue
    # class has 8 copies in the file): with headroom 0.5 the unclamped
    # recommendation exceeds the batch for any plausible chunk-shuffled
    # coverage (≥ ~342 unique), so the clamp path is deterministic.
    ids = ((np.arange(n)[:, None] % 500)
           + np.arange(f) * bucket).astype(np.int32)
    labels = rng.integers(0, 2, n).astype(np.int8)
    with PackedWriter(str(tmp_path / "pk"), f, store_vals=False) as w:
        w.append(ids, labels)
    args = build_parser().parse_args([
        "cap-advise", "--data", str(tmp_path / "pk"),
        "--batch-size", "1000", "--batches", "3", "--headroom", "0.5",
    ])
    assert args.fn(args) == 0
    out = json_lib.loads(capsys.readouterr().out.strip())
    overall = out["max_unique_per_field_overall"]
    assert 342 <= overall <= 500
    # Clamped to the batch (no batch of 1000 rows can exceed 1000
    # uniques), and the note says so instead of claiming the tile.
    assert out["recommended_compact_cap"] == 1000
    assert "NOT tile-aligned" in out["note"]
    assert "rounded to the segtotal 512 tile" not in out["note"]


def test_row_scale_guard_predicate():
    # ISSUE 2 satellite (VERDICT r5 next-round #8): the ≥1M-feature
    # row-strategy guardrail points the user at the fused path.
    assert cli.check_row_scale("row", 999_999) is None
    assert cli.check_row_scale("field_sparse", 10_000_000) is None
    assert cli.check_row_scale("dp", 10_000_000) is None
    msg = cli.check_row_scale("row", 1_000_000)
    assert msg is not None
    assert "field_sparse" in msg and "--force" in msg


def test_cli_row_at_scale_hard_fails_without_force():
    with pytest.raises(SystemExit, match="field_sparse"):
        cli.main([
            "train", "--config", "criteo1tb_fm_r64", "--strategy", "row",
            "--synthetic", "128", "--steps", "1", "--test-fraction", "0",
        ])


def test_cli_row_at_scale_warns_with_force(monkeypatch, capsys):
    # --force downgrades the guardrail to a stderr warning; the fit
    # itself is stubbed (a 10M-feature dense-row step is exactly what
    # the guard exists to prevent on this box).
    ran = {}
    monkeypatch.setattr(
        cli, "_fit_parallel",
        lambda *a, **k: ran.setdefault("fit", True) and None,
    )
    rc = cli.main([
        "train", "--config", "criteo1tb_fm_r64", "--strategy", "row",
        "--synthetic", "128", "--steps", "1", "--test-fraction", "0",
        "--force",
    ])
    assert rc == 0 and ran["fit"]
    err = capsys.readouterr().err
    assert "warning:" in err and "field_sparse" in err


def test_cli_supervise_requires_single_and_checkpoint_dir():
    with pytest.raises(SystemExit, match="--supervise requires"):
        cli.main([
            "train", "--config", "movielens_fm_r8", "--synthetic", "128",
            "--steps", "1", "--test-fraction", "0", "--supervise",
        ])


def test_cli_supervised_train_recovers_from_device_loss(tmp_path, capsys):
    # End-to-end CLI wiring of the resilience subsystem: a device loss
    # mid-run is recovered via the checkpoint (the continuity assertion
    # itself lives in tests/test_resilience.py) and journaled to
    # <checkpoint-dir>/health.jsonl.
    from fm_spark_tpu.resilience import faults

    faults.activate("train_step@4=device_loss")
    try:
        rc = cli.main([
            "train", "--config", "movielens_fm_r8", "--synthetic", "256",
            "--steps", "6", "--batch-size", "64", "--test-fraction", "0",
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--checkpoint-every", "2", "--supervise", "--prefetch", "0",
        ])
    finally:
        faults.clear()
    assert rc == 0
    from fm_spark_tpu.utils.logging import read_events

    events = [e["event"]
              for e in read_events(str(tmp_path / "ck" / "health.jsonl"))]
    assert "failure" in events and "backoff" in events
    assert "recovered" in events


# ------------------------------------------- streaming text ingest (ISSUE 5)


def _dirty_shards(tmp_path, n_shards=2, rows=60, bad_lines=(6,)):
    from fm_spark_tpu.data import criteo

    paths = []
    for s in range(n_shards):
        p = str(tmp_path / f"s{s}.tsv")
        criteo.synthesize_tsv(p, rows, seed=s)
        paths.append(p)
    with open(paths[-1], "rb") as f:
        lines = f.read().splitlines(keepends=True)
    for ln in bad_lines:
        lines[ln - 1] = b"\x00garbage line\n"
    with open(paths[-1], "wb") as f:
        f.write(b"".join(lines))
    return paths


def test_cli_streaming_text_quarantine_trains_and_dead_letters(tmp_path,
                                                               capsys):
    """--data with a comma-separated shard list streams raw dirty text;
    quarantine policy finishes the run and dead-letters the corrupt
    line with path:lineno."""
    from fm_spark_tpu.utils.logging import read_events

    paths = _dirty_shards(tmp_path)
    qdir = str(tmp_path / "quar")
    rc = cli.main([
        "train", "--config", "criteo_kaggle_fm_r32",
        "--data", ",".join(paths),
        "--steps", "5", "--batch-size", "16", "--test-fraction", "0",
        "--data-policy", "quarantine", "--quarantine-dir", qdir,
        "--log-every", "5",
    ])
    assert rc == 0
    bad = [e for e in read_events(qdir + "/deadletter.jsonl")
           if e["event"] == "bad_record"]
    assert len(bad) == 1
    assert bad[0]["path"] == paths[-1] and bad[0]["lineno"] == 6
    # The run's summary metrics line carries the quarantine accounting.
    out = capsys.readouterr().out
    assert any('"bad_records": 1' in l for l in out.splitlines())


def test_cli_streaming_text_strict_fails_with_path_lineno(tmp_path):
    from fm_spark_tpu.data.stream import BadRecord

    paths = _dirty_shards(tmp_path)
    with pytest.raises(BadRecord, match=r"s1\.tsv:6"):
        cli.main([
            "train", "--config", "criteo_kaggle_fm_r32",
            "--data", ",".join(paths),
            "--steps", "5", "--batch-size", "16", "--test-fraction", "0",
        ])


def test_cli_streaming_text_breaker_aborts_above_max_bad_frac(tmp_path):
    from fm_spark_tpu.data.stream import IngestAborted

    paths = _dirty_shards(tmp_path, bad_lines=tuple(range(5, 35)))
    with pytest.raises(IngestAborted, match="max_bad_frac"):
        cli.main([
            "train", "--config", "criteo_kaggle_fm_r32",
            "--data", ",".join(paths),
            "--steps", "8", "--batch-size", "16", "--test-fraction", "0",
            "--data-policy", "quarantine",
            "--quarantine-dir", str(tmp_path / "quar"),
            "--max-bad-frac", "0.1",
        ])


def test_cli_streaming_native_ingest_quarantines_identically(tmp_path,
                                                             capsys):
    """--native-ingest routes the same shard list through the C++ chunk
    parser: identical quarantine accounting in the summary line, and an
    automatic fallback (with a stderr notice) when the native parser is
    unavailable."""
    from unittest import mock

    from fm_spark_tpu import native
    from fm_spark_tpu.utils.logging import read_events

    if not native.stream_parse_available("criteo"):
        pytest.skip(f"native chunk parser unavailable: "
                    f"{native.build_error()}")
    paths = _dirty_shards(tmp_path)
    qdir = str(tmp_path / "quar")
    argv = [
        "train", "--config", "criteo_kaggle_fm_r32",
        "--data", ",".join(paths),
        "--steps", "5", "--batch-size", "16", "--test-fraction", "0",
        "--data-policy", "quarantine", "--quarantine-dir", qdir,
        "--log-every", "5", "--native-ingest", "--prefetch", "0",
    ]
    assert cli.main(argv) == 0
    bad = [e for e in read_events(qdir + "/deadletter.jsonl")
           if e["event"] == "bad_record"]
    assert len(bad) == 1
    assert bad[0]["path"] == paths[-1] and bad[0]["lineno"] == 6
    out = capsys.readouterr()
    assert any('"bad_records": 1' in l for l in out.out.splitlines())
    assert "fell back" not in out.err
    # .so unavailable: same command falls back to the Python parser and
    # says so, instead of failing.
    with mock.patch.object(native, "stream_parse_available",
                           lambda dataset: False):
        assert cli.main(argv + ["--quarantine-dir",
                                str(tmp_path / "quar2")]) == 0
    assert "fell back" in capsys.readouterr().err


def test_cli_quarantine_defaults_into_obs_run_dir(tmp_path, capsys):
    """ISSUE 7 consolidation: without --quarantine-dir the dead-letter
    journal joins the run's other telemetry under <obs-dir>/<run_id>/,
    and the run id is echoed as the first JSON line."""
    import os

    from fm_spark_tpu.utils.logging import read_events

    paths = _dirty_shards(tmp_path)
    obs_root = tmp_path / "obs"
    assert cli.main([
        "train", "--config", "criteo_kaggle_fm_r32",
        "--data", ",".join(paths), "--steps", "5",
        "--batch-size", "16", "--test-fraction", "0",
        "--data-policy", "quarantine", "--log-every", "5",
        "--obs-dir", str(obs_root),
    ]) == 0
    out = capsys.readouterr().out
    run_line = json.loads(next(
        l for l in out.splitlines() if '"obs_dir"' in l))
    assert run_line["run_id"] in run_line["obs_dir"]
    dead = read_events(os.path.join(run_line["obs_dir"],
                                    "deadletter.jsonl"))
    bad = [e for e in dead if e["event"] == "bad_record"]
    assert len(bad) == 1
    assert bad[0]["path"] == paths[-1] and bad[0]["lineno"] == 6
    # The run's other streams landed beside it, one directory per run.
    names = set(os.listdir(run_line["obs_dir"]))
    assert {"trace.jsonl", "flight.jsonl", "deadletter.jsonl"} <= names


def test_cli_streaming_text_guards(tmp_path):
    paths = _dirty_shards(tmp_path, bad_lines=())
    # quarantine without a dead-letter destination: since ISSUE 7 the
    # journal defaults into the per-run obs dir; with the telemetry
    # plane off there is nowhere to land, so it stays a config error.
    with pytest.raises(SystemExit, match="quarantine-dir"):
        cli.main([
            "train", "--config", "criteo_kaggle_fm_r32",
            "--data", ",".join(paths), "--steps", "2",
            "--batch-size", "16", "--test-fraction", "0",
            "--data-policy", "quarantine", "--obs-dir", "none",
        ])
    # streaming holds out no eval split: an implicit test fraction must
    # hard-fail, never silently train on 100% while reporting nothing.
    with pytest.raises(SystemExit, match="test-fraction"):
        cli.main([
            "train", "--config", "criteo_kaggle_fm_r32",
            "--data", ",".join(paths), "--steps", "2",
            "--batch-size", "16",
        ])
    # a missing shard names itself.
    with pytest.raises(SystemExit, match="missing shard"):
        cli.main([
            "train", "--config", "criteo_kaggle_fm_r32",
            "--data", paths[0] + ",/nonexistent/x.tsv", "--steps", "2",
            "--batch-size", "16", "--test-fraction", "0",
        ])


@pytest.mark.slow
def test_cli_streaming_checkpoint_resume_continues_cursor(tmp_path,
                                                          capsys):
    """The streaming cursor rides the CLI checkpoint path: a second
    invocation with the same --checkpoint-dir resumes and finishes the
    remaining steps instead of replaying from scratch."""
    paths = _dirty_shards(tmp_path, bad_lines=())
    ck = str(tmp_path / "ck")
    common = [
        "train", "--config", "criteo_kaggle_fm_r32",
        "--data", ",".join(paths), "--batch-size", "16",
        "--test-fraction", "0", "--checkpoint-dir", ck,
        "--checkpoint-every", "2", "--log-every", "1", "--prefetch", "0",
    ]
    assert cli.main(common + ["--steps", "4"]) == 0
    first = capsys.readouterr().out
    assert cli.main(common + ["--steps", "8"]) == 0
    second = capsys.readouterr().out
    steps_logged = [json.loads(l)["step"] for l in second.splitlines()
                    if l.startswith('{"step"')]
    # Resumed at 5, not 1 — the cursor (and step count) came back.
    assert min(steps_logged) == 5 and max(steps_logged) == 8
