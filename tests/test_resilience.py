"""Fault-matrix tests for the resilience subsystem (ISSUE 2).

Every observed attachment failure mode — init hang (rc=3 via the bench
watchdog, covered in tests/test_bench_faults.py), init failure, mid-step
device loss, SIGTERM — maps to a deterministic injection here, and every
supervisor transition (retry, backoff delay, probe, circuit open /
half-open / recovery) plus the health-event journal contents is asserted
on the CPU backend. The end-to-end training recovery (device loss →
checkpoint resume with loss continuity) lives at the bottom.
"""

import json
import os

import numpy as np
import pytest

from fm_spark_tpu.resilience import (
    BackoffPolicy,
    CircuitOpen,
    FaultPlan,
    InjectedDeviceLoss,
    RetriesExhausted,
    Supervisor,
    faults,
    is_device_loss,
)
from fm_spark_tpu.resilience.faults import FaultInjected
from fm_spark_tpu.utils.logging import EventLog, read_events


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Isolate every test from ambient fault plans and shared state."""
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------------------- faults.py


def test_fault_spec_parses_points_and_occurrences():
    plan = FaultPlan.from_spec(
        "backend_init@1=hang:300;sweep_leg@2=device_loss;"
        "train_step@7=error;probe@1=exit:3"
    )
    assert plan.points == {"backend_init", "sweep_leg", "train_step",
                           "probe"}
    assert plan.rule_for("sweep_leg", 2).action == "device_loss"
    assert plan.rule_for("sweep_leg", 1) is None
    assert plan.rule_for("backend_init", 1).param == "300"


@pytest.mark.parametrize("bad", [
    "nonsense", "point@=hang", "point@1=", "point@1=not_an_action",
    "point=hang",
])
def test_fault_spec_rejects_malformed_rules(bad):
    with pytest.raises(ValueError):
        FaultPlan.from_spec(bad)


def test_fault_spec_rejects_unknown_points_eagerly():
    """ISSUE 10 satellite: a typo'd point used to be accepted and then
    silently never fire — now it is rejected at parse/activate time
    with the registry and action set in the error."""
    with pytest.raises(ValueError, match=r"unknown fault point"):
        FaultPlan.from_spec("trian_step@1=device_loss")
    with pytest.raises(ValueError) as exc:
        faults.activate("no_such_point@2=error")
    msg = str(exc.value)
    for point in faults.KNOWN_POINTS:
        assert point in msg  # the error lists the whole registry
    for action in faults.ACTIONS:
        assert action in msg  # ... and the action vocabulary
    # Harness-internal plans over synthetic points stay expressible.
    plan = FaultPlan.from_spec("synthetic_pt@1=error", points=None)
    assert plan.points == {"synthetic_pt"}


def test_inject_fires_at_exact_occurrence_only():
    faults.activate("train_step@3=device_loss")
    faults.inject("train_step")
    faults.inject("train_step")
    with pytest.raises(InjectedDeviceLoss):
        faults.inject("train_step")
    faults.inject("train_step")  # occurrence 4: past the rule, quiet
    faults.inject("probe")  # unrelated point never fires


def test_inject_noop_without_plan():
    faults.inject("anything")  # must be a cheap no-op, not an error


def test_occurrence_counters_survive_process_respawn(tmp_path,
                                                     monkeypatch):
    """The cross-process state file: a bench parent respawns its child,
    and 'hang the FIRST init, not every init' must stay expressible."""
    state = tmp_path / "state.json"
    monkeypatch.setenv(faults.ENV_STATE, str(state))
    faults.activate("backend_init@1=error")
    with pytest.raises(FaultInjected):
        faults.inject("backend_init")
    # "New process": fresh in-memory counters, same state file.
    faults.activate("backend_init@1=error")
    faults.inject("backend_init")  # persistent occurrence 2 — no fire
    assert json.loads(state.read_text())["backend_init"] == 2


def test_env_plan_loaded_lazily(monkeypatch):
    monkeypatch.setenv(faults.ENV_PLAN, "sweep_leg@1=device_loss")
    faults.clear()  # force the env re-read
    with pytest.raises(InjectedDeviceLoss):
        faults.inject("sweep_leg")


def test_is_device_loss_classification():
    assert is_device_loss(InjectedDeviceLoss("p", 1))
    assert is_device_loss(RuntimeError(
        "INTERNAL: Unable to initialize backend 'tpu'"))
    assert is_device_loss(RuntimeError("DATA_LOSS: device lost"))
    # Program bugs must NOT classify as device loss — retrying them
    # burns the whole deadline re-crashing.
    assert not is_device_loss(ValueError("shape mismatch [8] vs [4]"))
    assert not is_device_loss(KeyboardInterrupt())
    assert not is_device_loss(SystemExit(3))


# ------------------------------------------- ingest fault points (ISSUE 5)


def test_ingest_fault_points_registered_and_deterministic():
    """The two data-fault points join the registry and behave exactly
    like the device faults: per-point occurrence counters, fire at the
    exact Nth occurrence only."""
    from fm_spark_tpu.resilience.faults import KNOWN_POINTS

    assert {"ingest_corrupt", "ingest_truncate"} <= set(KNOWN_POINTS)
    faults.activate(
        "ingest_corrupt@2=error;ingest_truncate@3=device_loss")
    faults.inject("ingest_corrupt")
    with pytest.raises(FaultInjected):
        faults.inject("ingest_corrupt")
    faults.inject("ingest_corrupt")  # past the rule — quiet again
    faults.inject("ingest_truncate")  # counters are PER POINT
    faults.inject("ingest_truncate")
    with pytest.raises(InjectedDeviceLoss):
        faults.inject("ingest_truncate")


def test_ingest_occurrence_counters_survive_process_respawn(
        tmp_path, monkeypatch):
    state = tmp_path / "state.json"
    monkeypatch.setenv(faults.ENV_STATE, str(state))
    faults.activate("ingest_corrupt@2=error")
    faults.inject("ingest_corrupt")
    faults.activate("ingest_corrupt@2=error")  # "new process"
    with pytest.raises(FaultInjected):
        faults.inject("ingest_corrupt")
    assert json.loads(state.read_text())["ingest_corrupt"] == 2


def test_ingest_fault_points_wired_into_stream_layer(tmp_path):
    """The production call sites reach the named points: the shard
    reader's chunk read fires ``ingest_truncate``; the batcher's
    per-record hook fires ``ingest_corrupt`` and the injected error
    takes the active policy path like any corrupt record (strict raise
    with path:lineno / quarantine + dead-letter)."""
    from fm_spark_tpu.data.stream import (
        BadRecord,
        RecordGuard,
        ShardReader,
        StreamBatches,
        line_parser,
    )

    p = tmp_path / "s.svm"
    p.write_text("".join(f"1 {i + 1}:1.0\n" for i in range(8)))
    faults.activate("ingest_truncate@1=error")
    with pytest.raises(FaultInjected):
        ShardReader([str(p)]).next_line()
    faults.activate("ingest_corrupt@3=error")
    b = StreamBatches(ShardReader([str(p)]), line_parser("libsvm"), 4, 2)
    with pytest.raises(BadRecord, match=r"s\.svm:3"):
        b.next_batch()
    faults.activate("ingest_corrupt@3=error")
    guard = RecordGuard("quarantine",
                        quarantine_dir=str(tmp_path / "q"))
    b2 = StreamBatches(ShardReader([str(p)]), line_parser("libsvm"),
                       4, 2, guard=guard)
    b2.next_batch()
    b2.next_batch()
    assert guard.n_bad == 1 and guard.n_ok == 7
    events = read_events(guard.dead_letter_path)
    assert len(events) == 1 and events[0]["lineno"] == 3
    assert "injected" in events[0]["reason"]


# --------------------------------------------------------- BackoffPolicy


def test_backoff_delay_is_bounded_exponential(monkeypatch):
    # Pin the designed-sleep knob off: this test asserts EXACT delays.
    monkeypatch.delenv("FM_SPARK_TEST_SLEEP_SCALE", raising=False)
    p = BackoffPolicy(initial=2.0, multiplier=2.0, max_delay=30.0,
                      jitter=0.0, max_attempts=8)
    assert [p.delay(k) for k in (1, 2, 3, 4, 5, 6)] == [
        2.0, 4.0, 8.0, 16.0, 30.0, 30.0]


def test_backoff_delay_respects_test_sleep_scale(monkeypatch):
    """ISSUE 17 satellite: FM_SPARK_TEST_SLEEP_SCALE shrinks every
    designed backoff multiplicatively (the fault suite asserts
    behavior, not wall-clock), clamps to [0, 1], and ignores junk."""
    p = BackoffPolicy(initial=8.0, multiplier=2.0, max_delay=30.0,
                      jitter=0.0)
    monkeypatch.setenv("FM_SPARK_TEST_SLEEP_SCALE", "0.25")
    assert [p.delay(k) for k in (1, 2, 3)] == [2.0, 4.0, 7.5]
    monkeypatch.setenv("FM_SPARK_TEST_SLEEP_SCALE", "5.0")
    assert p.delay(1) == 8.0  # clamped: never scales sleeps UP
    monkeypatch.setenv("FM_SPARK_TEST_SLEEP_SCALE", "not-a-number")
    assert p.delay(1) == 8.0
    from fm_spark_tpu.utils.sleeps import scaled, sleep_scale

    monkeypatch.setenv("FM_SPARK_TEST_SLEEP_SCALE", "0.5")
    assert sleep_scale() == 0.5
    assert scaled(10.0) == 5.0


def test_backoff_jitter_is_seeded_deterministic(monkeypatch):
    import random

    monkeypatch.delenv("FM_SPARK_TEST_SLEEP_SCALE", raising=False)
    p = BackoffPolicy(initial=10.0, jitter=0.1)
    a = [p.delay(1, random.Random(7)) for _ in range(3)]
    b = [p.delay(1, random.Random(7)) for _ in range(3)]
    assert a == b
    assert all(9.0 <= d <= 11.0 for d in a)
    assert a[0] != 10.0  # jitter actually applied


# ------------------------------------------------------------ Supervisor


def _supervisor(tmp_path, *, probe=True, max_attempts=3,
                breaker_threshold=3):
    delays = []
    journal_path = str(tmp_path / "health.jsonl")
    sup = Supervisor(
        policy=BackoffPolicy(initial=1.0, multiplier=2.0, jitter=0.0,
                             max_attempts=max_attempts),
        journal=EventLog(journal_path),
        probe=(probe if callable(probe) else (lambda: probe)),
        breaker_threshold=breaker_threshold,
        sleep=delays.append,
    )
    return sup, delays, journal_path


def test_run_retries_device_loss_then_succeeds(tmp_path):
    sup, delays, journal = _supervisor(tmp_path)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise InjectedDeviceLoss("step", calls["n"])
        return "ok"

    assert sup.run(flaky, op="leg") == "ok"
    assert calls["n"] == 3
    assert delays == [1.0, 2.0]  # exponential, per consecutive failure
    assert sup.state == "closed" and sup.consecutive_failures == 0
    events = [e["event"] for e in read_events(journal)]
    assert events == ["attempt", "failure", "probe", "backoff",
                      "attempt", "failure", "probe", "backoff",
                      "attempt"]
    rec = read_events(journal)[1]
    assert rec["op"] == "leg" and rec["retryable"] is True
    assert "InjectedDeviceLoss" in rec["error"]


def test_run_does_not_retry_program_errors(tmp_path):
    sup, delays, journal = _supervisor(tmp_path)
    calls = {"n": 0}

    def buggy():
        calls["n"] += 1
        raise ValueError("shape mismatch")

    with pytest.raises(ValueError):
        sup.run(buggy, op="leg")
    assert calls["n"] == 1 and delays == []
    assert read_events(journal)[-1]["retryable"] is False


def test_run_exhaustion_raises_with_cause_and_counts_op_failure(tmp_path):
    sup, delays, _ = _supervisor(tmp_path, max_attempts=2)

    def always():
        raise InjectedDeviceLoss("step", 0)

    with pytest.raises(RetriesExhausted) as exc:
        sup.run(always, op="leg")
    assert isinstance(exc.value.__cause__, InjectedDeviceLoss)
    assert len(delays) == 1  # no backoff after the final attempt
    assert sup.consecutive_failures == 1


def test_circuit_opens_after_consecutive_op_failures(tmp_path):
    sup, _, journal = _supervisor(tmp_path, probe=False, max_attempts=1,
                                  breaker_threshold=2)

    def always():
        raise InjectedDeviceLoss("step", 0)

    for _ in range(2):
        with pytest.raises(RetriesExhausted):
            sup.run(always, op="leg")
    assert sup.state == "open"
    # Open + unhealthy probe: the operation is rejected WITHOUT running.
    ran = {"n": 0}
    with pytest.raises(CircuitOpen):
        sup.run(lambda: ran.__setitem__("n", 1), op="leg")
    assert ran["n"] == 0
    events = [e["event"] for e in read_events(journal)]
    assert "circuit_open" in events and "circuit_rejected" in events


def test_circuit_half_opens_on_healthy_probe_and_closes_on_success(
        tmp_path):
    health = {"ok": False}
    sup, _, journal = _supervisor(tmp_path,
                                  probe=lambda: health["ok"],
                                  max_attempts=1, breaker_threshold=1)
    with pytest.raises(RetriesExhausted):
        sup.run(lambda: (_ for _ in ()).throw(
            InjectedDeviceLoss("s", 0)), op="leg")
    assert sup.state == "open"
    health["ok"] = True  # attachment recovered
    assert sup.run(lambda: "back", op="leg") == "back"
    assert sup.state == "closed" and sup.consecutive_failures == 0
    events = [e["event"] for e in read_events(journal)]
    assert "circuit_half_open" in events and "recovered" in events


def test_recover_backs_off_then_circuit_breaks(tmp_path):
    sup, delays, journal = _supervisor(tmp_path, breaker_threshold=3)
    exc = InjectedDeviceLoss("train", 1)
    sup.recover("train", exc)
    sup.recover("train", exc)
    assert delays == [1.0, 2.0]
    with pytest.raises(CircuitOpen):
        sup.recover("train", exc)
    events = [e["event"] for e in read_events(journal)]
    assert events.count("backoff") == 2
    assert events[-1] == "circuit_open"


def test_event_log_roundtrip_and_best_effort(tmp_path):
    path = str(tmp_path / "j.jsonl")
    log = EventLog(path)
    log.emit("probe", healthy=True)
    log.emit("backoff", delay_s=1.5, op="leg:x")
    log.close()
    with open(path, "a") as f:
        f.write("{torn line\n")  # a torn tail write must not break reads
    events = read_events(path)
    assert len(events) == 2
    assert events[0]["event"] == "probe" and events[0]["ts"] > 0
    assert events[1]["delay_s"] == 1.5


def test_device_probe_healthy_on_cpu_and_injectable():
    from fm_spark_tpu.resilience import device_probe

    assert device_probe(timeout=60.0) is True
    faults.activate("probe@1=device_loss")
    assert device_probe(timeout=60.0) is False


# ------------------------------- end-to-end: training device-loss resume


def test_train_device_loss_resumes_with_loss_continuity(tmp_path):
    """ISSUE 2 acceptance: a training run that loses its device mid-run
    resumes from checkpoint with step-count and loss continuity — the
    faulted run's logged losses are EXACTLY the uninterrupted run's
    (same pipeline cursor replay as kill-and-resume)."""
    from fm_spark_tpu import models
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data.pipeline import Batches
    from fm_spark_tpu.data.synthetic import synthetic_ctr
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    ids, vals, labels = synthetic_ctr(
        num_examples=256, num_features=64, nnz=5, seed=3)
    spec = models.FMSpec(num_features=64, rank=4, init_std=0.05)
    config = TrainConfig(num_steps=10, batch_size=32, learning_rate=0.1,
                         lr_schedule="constant", log_every=1)

    golden = FMTrainer(spec, config)
    golden.fit(Batches(ids, vals, labels, config.batch_size, seed=7))

    # Faulted run: device loss at the 6th step call; checkpoints every
    # 2 steps, so recovery resumes from step 4 and replays 5..10.
    faults.activate("train_step@6=device_loss")
    sup = Supervisor(
        policy=BackoffPolicy(initial=1.0, jitter=0.0),
        journal=EventLog(str(tmp_path / "health.jsonl")),
        probe=lambda: True, sleep=lambda s: None,
    )
    ck = Checkpointer(str(tmp_path / "ck"), save_every=2,
                      async_save=False)
    trainer = FMTrainer(spec, config)
    trainer.fit(Batches(ids, vals, labels, config.batch_size, seed=7),
                checkpointer=ck, supervisor=sup)
    ck.close()

    assert trainer.step_count == golden.step_count == 10
    assert trainer.loss_history == golden.loss_history  # bit-identical
    np.testing.assert_array_equal(
        np.asarray(golden.params["v"]), np.asarray(trainer.params["v"]))
    events = [e["event"] for e in
              read_events(str(tmp_path / "health.jsonl"))]
    assert "failure" in events and "backoff" in events
    assert "recovered" in events  # note_success after the resumed run


def test_supervised_fit_requires_checkpointer():
    from fm_spark_tpu import models
    from fm_spark_tpu.data.pipeline import Batches
    from fm_spark_tpu.data.synthetic import synthetic_ctr
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    ids, vals, labels = synthetic_ctr(
        num_examples=64, num_features=32, nnz=4, seed=0)
    spec = models.FMSpec(num_features=32, rank=2)
    trainer = FMTrainer(spec, TrainConfig(num_steps=2, batch_size=32))
    with pytest.raises(ValueError, match="supervised training"):
        trainer.fit(Batches(ids, vals, labels, 32, seed=1),
                    supervisor=Supervisor(probe=lambda: True,
                                          sleep=lambda s: None))


def test_checkpointer_reopen_preserves_committed_state(tmp_path):
    import jax

    from fm_spark_tpu import models
    from fm_spark_tpu.checkpoint import Checkpointer

    spec = models.FMSpec(num_features=16, rank=2)
    params = spec.init(jax.random.key(0))
    ck = Checkpointer(str(tmp_path / "ck"), save_every=1,
                      async_save=False)
    ck.save(5, params, {}, {"epoch": 0}, {"loss_history": [0.7]})
    ck.reopen()  # the device-loss recovery path
    restored = ck.restore(params, {})
    assert restored["step"] == 5
    assert restored["extra"]["loss_history"] == [0.7]
    ck.close()
