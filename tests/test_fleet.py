"""Serving front-door + fleet tests (ISSUE 17): deadline-aware
admission, the multi-process replica fleet, seeded traffic replay, and
the fleet chaos auditor.

The load-bearing contracts:

- **shed before the coalescer** — an unpayable request is refused at
  admission (429 + Retry-After) and the backend's ``score`` is NEVER
  called for it; the shed counters the door reports are the sheds the
  clients observed;
- **exactly-once under replica loss** — a ``replica_kill`` fault
  (SIGKILL-equivalent ``os._exit`` mid-request, injected INSIDE the
  replica process) loses zero accepted requests: the fleet's dispatch
  retry answers each on a surviving replica exactly once, the dead
  replica is re-admitted after ``/healthz`` readiness, and
  :func:`chaos.audit_fleet` proves all of it from the tap alone;
- **parent-side dispatch faults** — an injected ``fleet_dispatch``
  error is absorbed by the retry (counted, answered);
- **seeded replay purity** — ``make_schedule`` and ``fleet_schedule``
  are pure functions of their seed, so a failing campaign entry IS
  its repro;
- **concurrent followers converge** — N independent ReloadFollowers
  polling one chain while the trainer advances + demotes all converge
  to the same non-tombstoned tip, and the read-only followers never
  write a byte into the trainer's chain.

The ``frontdoor_accept`` fault point and the ``frontdoor_request``
watchdog phase are armed here, which also satisfies the lint's
registry-coverage rule.
"""

import hashlib
import http.client
import json
import os
import time

import jax
import numpy as np
import pytest

from fm_spark_tpu import models, obs
from fm_spark_tpu.checkpoint import Checkpointer
from fm_spark_tpu.resilience import chaos, faults, watchdog
from fm_spark_tpu.resilience.chaos_audit import audit_fleet
from fm_spark_tpu.serve import (
    AdmissionController,
    FrontDoor,
    LocalBackend,
    PredictEngine,
    ReloadFollower,
    parse_classes,
)
from fm_spark_tpu.serve import loadgen
from fm_spark_tpu.serve.fleet import Fleet
from fm_spark_tpu.utils.logging import EventLog, read_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Re-admission budget after a replica death: respawn + (cached)
#: warmup on a contended CI box. Generous on purpose — the assertion
#: is THAT the replica comes back, not how fast; bench_serve measures.
_READMIT_TIMEOUT_S = 240.0


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    monkeypatch.delenv(watchdog.ENV_SPEC, raising=False)
    faults.clear()
    watchdog.clear()
    yield
    faults.clear()
    watchdog.clear()


def _spec():
    return models.FieldFMSpec(num_features=4 * 64, rank=4,
                              num_fields=4, bucket=64, init_std=0.1)


def _params(spec, scale: float = 1.0):
    p = spec.init(jax.random.key(0))
    if scale != 1.0:
        p = jax.tree_util.tree_map(lambda a: a * scale, p)
    return p


def _post(port: int, doc, path: str = "/predict",
          timeout_s: float = 30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout_s)
    try:
        body = doc if isinstance(doc, (bytes, str)) else json.dumps(doc)
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        payload = resp.read().decode()
        headers = dict(resp.getheaders())
        return resp.status, json.loads(payload or "{}"), headers
    finally:
        conn.close()


def _get(port: int, path: str, timeout_s: float = 10.0):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def _predict_doc(spec, rows: int = 2, *, cls="interactive",
                 deadline_ms=8000.0, req_id="r0"):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, spec.bucket,
                       (rows, spec.num_fields)).astype(int).tolist()
    vals = rng.random((rows, spec.num_fields)).astype(float).tolist()
    return {"id": req_id, "class": cls, "deadline_ms": deadline_ms,
            "ids": ids, "vals": vals}


def _stats_delta(before: dict, after: dict) -> dict:
    return {k: after[k] - before[k] for k in after
            if k != "admission"}


class _CountingBackend:
    """Unit-test seam: counts ``score`` calls so shed-before-backend
    is assertable, answers instantly with a fixed generation."""

    def __init__(self, gen_step: int = 1):
        self.calls = 0
        self.gen_step = gen_step

    def score(self, ids, vals, deadline):
        self.calls += 1
        return ([0.0] * len(ids),
                {"generation_step": self.gen_step, "replica": 0})

    def healthz(self):
        return {"ready": True, "n_replicas": 1,
                "replicas": [{"replica": 0, "state": "ready",
                              "generation_step": self.gen_step}]}

    def close(self):
        pass


# ------------------------------------------------- admission control


def test_parse_classes_priority_is_spec_order():
    classes = parse_classes("interactive:64:500,batch:64:2000,"
                            "background:32:8000")
    assert [c.name for c in classes] == ["interactive", "batch",
                                         "background"]
    assert [c.priority for c in classes] == [0, 1, 2]
    assert classes[2].queue_cap == 32
    assert classes[0].default_deadline_ms == 500.0


@pytest.mark.parametrize("bad", [
    "",                          # empty spec
    "interactive:64",            # missing deadline
    "interactive:0:500",         # cap < 1
    "interactive:8:0",           # deadline <= 0
    ":8:500",                    # nameless
    "a:8:500,a:8:500",           # duplicate name
])
def test_parse_classes_rejects_malformed_specs(bad):
    with pytest.raises(ValueError):
        parse_classes(bad)


def test_admission_queue_cap_sheds_with_retry_after():
    adm = AdmissionController("interactive:2:500", service_est_ms=5.0)
    assert adm.admit("interactive", 500).admitted
    assert adm.admit("interactive", 500).admitted
    v = adm.admit("interactive", 500)
    assert v.decision == "shed_queue" and not v.admitted
    assert v.retry_after_ms > 0
    assert adm.snapshot()["inflight"]["interactive"] == 2
    adm.release("interactive")
    assert adm.admit("interactive", 500).admitted


def test_admission_sheds_unpayable_deadline_by_priority():
    """The deadline estimate is priority-aware: background queues
    behind everyone, interactive only behind itself — so under a
    background backlog the SAME deadline sheds background traffic
    while interactive still clears."""
    adm = AdmissionController("interactive:8:500,background:8:8000",
                              service_est_ms=10.0)
    for _ in range(4):
        assert adm.admit("background", 8000).admitted
    hi = adm.admit("interactive", 25.0)
    assert hi.admitted, hi  # est = 10ms * (0 ahead + 1) <= 25ms
    lo = adm.admit("background", 25.0)
    assert lo.decision == "shed_deadline"  # 10ms * 6 > 25ms
    assert lo.est_ms > 25.0
    assert lo.retry_after_ms >= 10.0


def test_admission_unknown_class_rejected_and_ewma_learns():
    adm = AdmissionController("interactive:8:500",
                              service_est_ms=100.0)
    assert adm.admit("nope", 500).decision == "rejected"
    assert adm.admit("interactive", 500).admitted
    adm.release("interactive", service_ms=10.0)
    assert adm.snapshot()["service_est_ms"] < 100.0


# ------------------------------------------------------ traffic replay


def test_make_schedule_is_pure_and_shapes_differ():
    for shape in loadgen.SHAPES:
        a = loadgen.make_schedule(shape, 3)
        b = loadgen.make_schedule(shape, 3)
        assert a == b  # frozen dataclasses: byte-identical replay
        assert a.events and a.shape == shape
        assert loadgen.make_schedule(shape, 4) != a
    # The payload is part of the purity contract too.
    sched = loadgen.make_schedule("diurnal", 1)
    p1 = loadgen.event_payload(sched.events[0], sched, nnz=4,
                               num_features=256)
    p2 = loadgen.event_payload(sched.events[0], sched, nnz=4,
                               num_features=256)
    assert p1 == p2


def test_schedule_shapes_encode_their_stress():
    diurnal = loadgen.make_schedule("diurnal", 0, deadline_ms=500)
    storm = loadgen.make_schedule("retry_storm", 0, deadline_ms=500)
    slow = loadgen.make_schedule("slow_clients", 0)
    # The storm over-offers with tighter deadlines and retries.
    assert storm.n_requests > diurnal.n_requests
    assert (max(e.deadline_ms for e in storm.events)
            < min(e.deadline_ms for e in diurnal.events))
    assert all(e.max_retries > 0 for e in storm.events)
    # A seeded third of slow clients stall mid-POST.
    stalled = [e for e in slow.events if e.slow_s > 0]
    assert stalled and len(stalled) < slow.n_requests


def test_fleet_schedule_is_pure_and_valid():
    seen = set()
    for seed in range(10):
        a = chaos.fleet_schedule(seed)
        assert a == chaos.fleet_schedule(seed)
        a.validate()
        assert a.shape in loadgen.SHAPES
        seen.add(a.scenario)
    assert seen == {f"fleet_{s}" for s in chaos._FLEET_SCENARIOS}


# -------------------------------------------- front door over HTTP


@pytest.fixture(scope="module")
def _eng():
    spec = _spec()
    eng = PredictEngine(spec, _params(spec), buckets=(1, 4),
                        latency_budget_ms=5.0)
    eng.warmup()
    yield spec, eng
    eng.close()


def test_frontdoor_sheds_before_the_backend_scores():
    """The tentpole invariant: a shed request NEVER reaches the
    backend — no coalescer slot, no compute, an explicit 429 with
    Retry-After. Both shed modes, then an admit to prove the door
    still works."""
    backend = _CountingBackend()
    door = FrontDoor(backend, admission=AdmissionController(
        "interactive:1:500", service_est_ms=50.0)).start()
    try:
        before = door.stats()
        # Unpayable deadline: est 50ms > 10ms — shed at admission.
        status, doc, headers = _post(door.port, _predict_doc(
            _spec(), deadline_ms=10.0))
        assert status == 429 and doc["error"] == "shed_deadline"
        assert doc["retry_after_ms"] > 0
        assert "Retry-After" in headers
        assert backend.calls == 0
        # Queue full: occupy the single slot, then knock again.
        assert door.admission.admit("interactive", 1000).admitted
        status, doc, _ = _post(door.port, _predict_doc(
            _spec(), deadline_ms=1000.0))
        assert status == 429 and doc["error"] == "shed_queue"
        assert backend.calls == 0
        door.admission.release("interactive")
        # And the door still answers payable traffic.
        status, doc, _ = _post(door.port, _predict_doc(
            _spec(), deadline_ms=1000.0))
        assert status == 200 and backend.calls == 1
        delta = _stats_delta(before, door.stats())
        assert delta["shed"] == 2
        assert delta["shed_queue"] == 1 and delta["shed_deadline"] == 1
        assert delta["answered"] == 1
    finally:
        door.stop()


def test_frontdoor_rejects_malformed_and_unknown_class():
    backend = _CountingBackend()
    door = FrontDoor(backend).start()
    try:
        status, doc, _ = _post(door.port, b"{not json")
        assert status == 400 and "malformed" in doc["error"]
        bad = _predict_doc(_spec(), cls="no-such-class")
        status, doc, _ = _post(door.port, bad)
        assert status == 400 and "unknown class" in doc["error"]
        assert backend.calls == 0
    finally:
        door.stop()


def test_frontdoor_accept_fault_is_an_explicit_500():
    """The ``frontdoor_accept`` drill point: an injected transport
    fault surfaces as a counted 500 — never a hang, never a silent
    drop — and the next request is clean."""
    backend = _CountingBackend()
    door = FrontDoor(backend).start()
    try:
        before = door.stats()
        faults.activate("frontdoor_accept@1=error")
        status, doc, _ = _post(door.port, _predict_doc(_spec()))
        assert status == 500 and "accept failed" in doc["error"]
        assert backend.calls == 0
        status, _, _ = _post(door.port, _predict_doc(_spec()))
        assert status == 200
        delta = _stats_delta(before, door.stats())
        assert delta["failed"] == 1 and delta["answered"] == 1
    finally:
        faults.clear()
        door.stop()


def test_frontdoor_deadline_propagates_to_engine_504(_eng):
    """An admitted request whose deadline expires inside the engine
    comes back as a 504 under the armed ``frontdoor_request`` watchdog
    phase, with the admission slot released."""
    spec, eng = _eng
    watchdog.configure("frontdoor_request=30")
    # alpha=0 pins the estimate: the first (successful) request must
    # not teach the EWMA a real service time, or the tiny-deadline
    # request below would be shed at admission instead of admitted.
    door = FrontDoor(LocalBackend(eng),
                     admission=AdmissionController(
                         service_est_ms=0.01, ewma_alpha=0.0)).start()
    try:
        before = door.stats()
        status, doc, _ = _post(door.port, _predict_doc(
            spec, deadline_ms=4000.0))
        assert status == 200 and len(doc["scores"]) == 2
        assert doc["generation_step"] == eng.generation().step
        # est 0.01ms admits it; a 0.05ms deadline then expires in
        # the coalescer before any dispatch.
        status, doc, _ = _post(door.port, _predict_doc(
            spec, deadline_ms=0.05))
        assert status == 504 and "deadline expired" in doc["error"]
        delta = _stats_delta(before, door.stats())
        assert delta["answered"] == 1 and delta["timeout"] == 1
        snap = door.admission.snapshot()
        assert all(n == 0 for n in snap["inflight"].values())
    finally:
        door.stop(close_backend=False)
        watchdog.clear()


def test_frontdoor_healthz_and_metrics(_eng):
    spec, eng = _eng
    door = FrontDoor(LocalBackend(eng)).start()
    try:
        status, body = _get(door.port, "/healthz")
        doc = json.loads(body)
        assert status == 200 and doc["ready"]
        assert doc["counters"]["accepted"] >= 0
        assert doc["admission"]["classes"]
        status, body = _get(door.port, "/metrics")
        assert status == 200 and "frontdoor" in body
    finally:
        door.stop(close_backend=False)


def test_loadgen_replay_audits_green(tmp_path):
    """A small seeded replay against the door: every scheduled request
    reaches a terminal outcome, the books close, and the fleet auditor
    grades the run green from the tap + counter deltas alone."""
    backend = _CountingBackend(gen_step=3)
    door = FrontDoor(backend, admission=AdmissionController(
        service_est_ms=0.5)).start()
    tap = str(tmp_path / "tap.jsonl")
    try:
        before = door.stats()
        sched = loadgen.make_schedule("diurnal", 0, duration_s=0.3,
                                      base_rps=40.0, rows=2,
                                      deadline_ms=4000.0)
        summary = loadgen.run_loadgen(
            "127.0.0.1", door.port, sched, tap, nnz=4,
            num_features=256, threads=4)
        assert summary["requests"] == sched.n_requests
        assert summary["by_outcome"].get("ok") == sched.n_requests
        delta = _stats_delta(before, door.stats())
        assert delta["answered"] == sched.n_requests
        violations = audit_fleet(
            read_events(tap), delta,
            expected_requests=sched.n_requests,
            tombstoned_steps=())
        assert violations == []
    finally:
        door.stop()


# ------------------------------------------------- the fleet auditor


def _tap(*recs):
    out = []
    for i, (rid, attempt, outcome, gen) in enumerate(recs):
        out.append({"event": "attempt", "req_id": rid,
                    "attempt": attempt, "outcome": outcome,
                    "gen_step": gen, "ts": float(i)})
    return out


def _counters(**kw):
    base = {k: 0 for k in ("accepted", "answered", "shed",
                           "shed_queue", "shed_deadline", "rejected",
                           "timeout", "failed", "retries")}
    base.update(kw)
    return base


def test_audit_fleet_green_on_clean_books():
    tap = _tap(("a", 1, "ok", 2), ("b", 1, "shed", None),
               ("b", 2, "ok", 2))
    counters = _counters(accepted=2, answered=2, shed=1,
                         shed_deadline=1)
    assert audit_fleet(tap, counters, expected_requests=2,
                       tombstoned_steps=(3,)) == []


def test_audit_fleet_flags_double_answer_and_drops():
    # Same (req_id, attempt) twice: an in-flight request answered
    # twice after a replica death.
    tap = _tap(("a", 1, "ok", 2), ("a", 1, "ok", 2))
    v = audit_fleet(tap, _counters(accepted=2, answered=2))
    assert any(x["invariant"] == "exactly_once_responses" for x in v)
    # Two ok's across attempts: retried after a success.
    tap = _tap(("a", 1, "ok", 2), ("a", 2, "ok", 2))
    v = audit_fleet(tap, _counters(accepted=2, answered=2))
    assert any("answered ok 2 times" in x["detail"] for x in v)
    # A scheduled request with no terminal outcome: silently dropped.
    v = audit_fleet(_tap(("a", 1, "ok", 2)),
                    _counters(accepted=1, answered=1),
                    expected_requests=2)
    assert any("silently dropped" in x["detail"] for x in v)


def test_audit_fleet_flags_open_books_and_shed_mismatch():
    tap = _tap(("a", 1, "ok", 2))
    v = audit_fleet(tap, _counters(accepted=2, answered=1))
    assert any(x["invariant"] == "accepted_accounting" for x in v)
    v = audit_fleet(tap, _counters(accepted=1, answered=1, shed=2,
                                   shed_queue=1))
    kinds = [x["invariant"] for x in v]
    assert kinds.count("shed_accounting") == 2  # split AND tap
    v = audit_fleet(tap, _counters(accepted=1, answered=1))
    assert v == []


def test_audit_fleet_flags_tombstoned_generation():
    tap = _tap(("a", 1, "ok", 4))
    v = audit_fleet(tap, _counters(accepted=1, answered=1),
                    tombstoned_steps=(4,))
    assert any(x["invariant"] == "no_tombstoned_generation"
               for x in v)


def test_audit_fleet_splits_replica_journal_at_incarnations():
    """A SIGKILLed replica's respawn restarts its generation sequence
    from the base model — monotonicity holds WITHIN an incarnation,
    never across the journal."""
    journal = [
        {"event": "replica_start", "replica": 0},
        {"event": "serve_swap", "step": 5, "gen_id": 2},
        {"event": "replica_start", "replica": 0},   # respawn
        {"event": "serve_swap", "step": 5, "gen_id": 2},  # re-reload
    ]
    counters = _counters()
    assert audit_fleet([], counters, replica_events={0: journal}) == []
    torn = [  # same incarnation, step going backwards: torn swap
        {"event": "replica_start", "replica": 0},
        {"event": "serve_swap", "step": 5, "gen_id": 2},
        {"event": "serve_swap", "step": 3, "gen_id": 3},
    ]
    v = audit_fleet([], counters, replica_events={0: torn})
    assert any(x["invariant"] == "no_torn_swap" for x in v)
    assert all("incarnation" in x["detail"] for x in v)


# ------------------------------------- the fleet, for real (processes)


def _wait_ready(fleet, want: int, timeout_s: float) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        docs = fleet.healthz()["replicas"]
        if sum(1 for d in docs if d["state"] == "ready") >= want:
            return
        time.sleep(0.25)
    raise AssertionError(
        f"fleet did not reach {want} ready replicas within "
        f"{timeout_s:.0f}s: {fleet.healthz()['replicas']}")


def test_fleet_sigkill_drill_loses_nothing_and_readmits(tmp_path):
    """THE acceptance drill: ``replica_kill`` (an ``os._exit`` inside
    whichever replica serves the Nth scored request — SIGKILL as seen
    from the parent) mid-burst loses zero accepted requests; every
    request is answered exactly once or failed explicitly, the dead
    replica is re-admitted after ``/healthz`` readiness, and a
    parent-side ``fleet_dispatch`` fault is absorbed by the same
    retry. Audited from the tap + counter deltas + replica journals."""
    spec = _spec()
    model_dir = str(tmp_path / "model")
    models.save_model(model_dir, spec, _params(spec, 0.1))
    state = str(tmp_path / "faults_state.json")
    health_path = str(tmp_path / "fleet_health.jsonl")
    journal = EventLog(health_path)
    fleet = Fleet(
        model_dir, n_replicas=2, work_dir=str(tmp_path / "work"),
        journal=journal, buckets="1,4",
        compile_cache_dir=str(tmp_path / "cache"),
        spawn_timeout_s=300.0,
        # The drill plan rides the REPLICA environment: the 4th scored
        # request across the fleet (shared cross-process fault state)
        # kills its replica mid-handling.
        replica_env={faults.ENV_PLAN: "replica_kill@4=exit:9",
                     faults.ENV_STATE: state})
    fleet.start()
    door = FrontDoor(fleet, admission=AdmissionController(
        "interactive:32:8000,batch:16:8000,background:8:9000",
        service_est_ms=2.0), journal=journal).start()
    tap = str(tmp_path / "tap.jsonl")
    try:
        before = door.stats()
        sched = loadgen.make_schedule(
            "flash_crowd", 5, duration_s=0.6, base_rps=30.0,
            rows=2, deadline_ms=8000.0)
        assert sched.n_requests > 4  # the kill fires mid-burst
        summary = loadgen.run_loadgen(
            "127.0.0.1", door.port, sched, tap,
            nnz=spec.num_fields, num_features=spec.num_features,
            threads=6, attempt_timeout_s=60.0)
        delta = _stats_delta(before, door.stats())
        # Zero lost: every scheduled request answered exactly once.
        assert summary["by_outcome"].get("ok") == sched.n_requests
        assert delta["answered"] == sched.n_requests
        assert delta["retries"] >= 1  # the kill was absorbed in-flight
        replica_events = {}
        for rep in fleet.replicas:
            jpath = os.path.join(fleet.work_dir,
                                 f"replica_{rep.idx}.jsonl")
            if os.path.exists(jpath):
                replica_events[rep.idx] = read_events(jpath)
        violations = audit_fleet(
            read_events(tap), delta,
            expected_requests=sched.n_requests,
            tombstoned_steps=(), replica_events=replica_events)
        assert violations == []
        # The parent saw the death (rc=9, the injected exit code) ...
        downs = [e for e in read_events(health_path)
                 if e.get("event") == "replica_down"]
        assert any(e.get("rc") == 9 for e in downs), downs
        # ... and the replica is re-admitted: /healthz readiness,
        # then it serves again.
        _wait_ready(fleet, 2, _READMIT_TIMEOUT_S)
        assert fleet.healthz()["ready"]
        status, doc, _ = _post(door.port, _predict_doc(spec))
        assert status == 200
        # Parent-side dispatch fault: first attempt errors, the retry
        # answers — the client never sees the hiccup.
        before = door.stats()
        faults.activate("fleet_dispatch@1=error")
        status, doc, _ = _post(door.port, _predict_doc(spec))
        assert status == 200
        delta = _stats_delta(before, door.stats())
        assert delta["retries"] >= 1 and delta["answered"] == 1
    finally:
        faults.clear()
        door.stop()


def test_fleet_chaos_campaign_green(tmp_path):
    """Two seeded fleet schedules (kill-mid-flash-crowd, retry-storm
    + demote race) against one shared two-replica fleet: completed,
    audited green, with a measured recovery for the kill scenario."""
    entries = chaos.run_fleet_campaign(seeds=(0, 1),
                                       base_dir=str(tmp_path))
    assert [e["seed"] for e in entries] == [0, 1]
    for e in entries:
        assert e["outcome"] == "completed"
        assert e["verdict"] == "green", e["violations"]
        assert e["traffic"]["requests"] > 0
    kill = entries[0]
    assert kill["scenario"] == "fleet_kill_flash_crowd"
    assert kill["killed_replica"] is not None
    assert kill["recovery_s"] is not None and kill["recovery_s"] > 0
    storm = entries[1]
    assert storm["scenario"] == "fleet_retry_storm_demote"
    assert storm["demoted_step"] is not None


# ------------------------- N concurrent followers, one trainer chain


def test_concurrent_chain_followers_converge_nontombstoned(tmp_path):
    """Three independent ReloadFollowers (each with its own engine)
    poll ONE chain while the trainer advances and demotes. All three
    converge to the same non-tombstoned tip, none ever installs the
    deterministically-demoted step, and a byte-hash audit proves the
    read-only followers never wrote into the trainer's chain."""
    spec = _spec()
    params = _params(spec)
    chain_dir = str(tmp_path / "chain")
    ck = Checkpointer(chain_dir, save_every=1, async_save=False)
    ck.save(1, params, {}, None, force=True)
    ck.wait()

    journals = [EventLog(str(tmp_path / f"f{i}.jsonl"))
                for i in range(3)]
    # One journal per follower, shared with its engine: serve_swap is
    # the ENGINE's event, reload_failed the follower's — the audit
    # reads both from the same stream.
    engines = [PredictEngine(spec, params, buckets=(1,),
                             journal=journals[i]) for i in range(3)]
    followers = [
        ReloadFollower(eng, chain_dir, poll_s=0.02,
                       journal=journals[i])
        for i, eng in enumerate(engines)]
    try:
        # Deterministic demote: published, tombstoned, and only THEN
        # polled — every follower must refuse step 2.
        assert [f.poll_once() for f in followers] == ["swapped"] * 3
        ck.save(2, params, {}, None, force=True)
        ck.wait()
        ck.demote(2, reason="drill")
        for f in followers:
            assert f.poll_once() in ("fresh", "stale_chain")
        assert [e.generation().step for e in engines] == [1, 1, 1]

        # Concurrent: trainer advances while all three poll freely.
        for f in followers:
            f.start()
        for step in (3, 4, 5):
            ck.save(step, params, {}, None, force=True)
            ck.wait()
            time.sleep(0.05)
        stones = set(ck.tombstoned_steps())
        ck.close()

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if all(e.generation().step == 5 for e in engines):
                break
            time.sleep(0.05)
        steps = [e.generation().step for e in engines]
        assert steps == [5, 5, 5], steps
        assert 5 not in stones and 2 in stones

        # Byte-hash audit: the chain after the trainer's last write,
        # then several more poll rounds, must be bit-identical — the
        # followers are read-only.
        def snapshot():
            out = {}
            for root, _, files in os.walk(chain_dir):
                for name in files:
                    p = os.path.join(root, name)
                    with open(p, "rb") as fh:
                        out[os.path.relpath(p, chain_dir)] = (
                            hashlib.sha256(fh.read()).hexdigest())
            return out

        before = snapshot()
        time.sleep(0.3)  # ~15 poll rounds across 3 followers
        assert snapshot() == before
    finally:
        for f in followers:
            f.stop()
        for eng in engines:
            eng.close()
    # Every follower's journal passes the serve audit against the
    # demoted set: no torn swap, never a tombstoned generation.
    for i in range(3):
        events = read_events(str(tmp_path / f"f{i}.jsonl"))
        swaps = [e for e in events if e.get("event") == "serve_swap"]
        assert swaps and swaps[-1]["step"] == 5
        assert chaos.audit_serve_events(
            events, tombstoned_steps={2}) == []
