"""Interpret-mode pins for the Pallas sorted-run segment-total kernel
(ops/pallas_segsum.py, VERDICT r4 #2a) and its compact_apply/step
integration behind TrainConfig.segtotal_pallas."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.ops.pallas_segsum import segment_totals
from fm_spark_tpu.train import TrainConfig


def _oracle(seg, x, cap):
    out = np.zeros((cap, x.shape[1]), np.float64)
    m = seg < cap
    np.add.at(out, seg[m], x[m].astype(np.float64))
    return out.astype(np.float32)


@pytest.mark.parametrize("b,cap", [(100, 16), (2048, 64), (5000, 512),
                                   (512, 512)])
def test_segment_totals_matches_oracle(b, cap):
    rng = np.random.default_rng(b + cap)
    seg = np.sort(rng.integers(0, cap, b)).astype(np.int32)
    x = rng.normal(size=(b, 9)).astype(np.float32)
    got = np.asarray(segment_totals(jnp.asarray(x), jnp.asarray(seg),
                                    cap, interpret=True))
    np.testing.assert_allclose(got, _oracle(seg, x, cap), rtol=1e-5,
                               atol=1e-5)


def test_segment_totals_long_run_spans_tiles():
    """One segment spanning many 512-lane tiles accumulates exactly
    through the resident window read-modify-write."""
    b, cap = 4096, 8
    seg = np.zeros(b, np.int32)
    seg[-5:] = 3
    x = np.ones((b, 4), np.float32)
    got = np.asarray(segment_totals(jnp.asarray(x), jnp.asarray(seg),
                                    cap, interpret=True))
    np.testing.assert_allclose(got, _oracle(seg, x, cap), rtol=1e-6)


def test_segment_totals_overflow_dropped():
    """Segment ids >= cap (device-aux overflow) land in the trimmed
    trash region, never a real segment — the masked-drop contract."""
    b, cap = 1500, 32
    rng = np.random.default_rng(0)
    seg = np.sort(rng.integers(0, cap + 40, b)).astype(np.int32)
    x = rng.normal(size=(b, 5)).astype(np.float32)
    got = np.asarray(segment_totals(jnp.asarray(x), jnp.asarray(seg),
                                    cap, interpret=True))
    np.testing.assert_allclose(got, _oracle(seg, x, cap), rtol=1e-5,
                               atol=1e-5)


F, BUCKET, K, B = 4, 64, 4, 256


def _spec():
    return models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )


def _batch(rng):
    return (
        jnp.asarray(rng.integers(0, BUCKET, (B, F)), jnp.int32),
        jnp.asarray(rng.uniform(0.5, 1.5, (B, F)), jnp.float32),
        jnp.asarray(rng.integers(0, 2, B), jnp.float32),
        jnp.ones((B,), jnp.float32),
    )


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
def test_step_matches_blocked_prefix(mode):
    """The full fused compact step with segtotal_pallas matches the
    blocked-prefix step to fp32-reassociation tolerance (dedup_sr uses
    the same SR keys, so rounding decisions only differ where the
    segment sums' last-ulp differs)."""
    from fm_spark_tpu.sparse import make_field_sparse_sgd_step

    spec = _spec()
    base = dict(learning_rate=0.2, optimizer="sgd", reg_linear=1e-4,
                reg_factors=1e-4, sparse_update=mode,
                compact_device=True, compact_cap=B)
    rng = np.random.default_rng(7)
    batch = _batch(rng)
    outs = {}
    for flag in (False, True):
        config = TrainConfig(segtotal_pallas=flag, **base)
        step = make_field_sparse_sgd_step(spec, config)
        params = spec.init(jax.random.key(0))
        params, loss = step(params, jnp.int32(0), *batch)
        outs[flag] = (jax.device_get(params), float(loss))
    np.testing.assert_allclose(outs[True][1], outs[False][1], rtol=1e-6)
    np.testing.assert_allclose(outs[True][0]["vw"], outs[False][0]["vw"],
                               rtol=1e-5, atol=1e-6)


def test_requires_compact_path():
    from fm_spark_tpu.sparse import make_field_sparse_sgd_step
    from fm_spark_tpu.train import make_train_step

    with pytest.raises(ValueError, match="segtotal_pallas"):
        make_field_sparse_sgd_step(
            _spec(), TrainConfig(segtotal_pallas=True)
        )
    with pytest.raises(ValueError, match="segtotal_pallas"):
        make_train_step(models.FMSpec(num_features=64, rank=4),
                        TrainConfig(segtotal_pallas=True))


def test_sharded_step_composes(eight_devices):
    """segtotal_pallas inside the field-sharded step (device-compact,
    2-D mesh) — runs and matches the non-kernel sharded step."""
    from fm_spark_tpu.parallel import (
        make_field_mesh,
        make_field_sharded_sgd_step,
        pad_field_batch,
        shard_field_batch,
        shard_field_params,
        stack_field_params,
    )

    spec = _spec()
    mesh = make_field_mesh(4, devices=eight_devices[:4], n_row=2)
    rng = np.random.default_rng(3)
    batch = pad_field_batch(tuple(np.asarray(a) for a in _batch(rng)),
                            F, 2)
    outs = {}
    for flag in (False, True):
        config = TrainConfig(learning_rate=0.2, optimizer="sgd",
                             sparse_update="dedup_sr",
                             compact_device=True, compact_cap=B,
                             segtotal_pallas=flag)
        step = make_field_sharded_sgd_step(spec, config, mesh)
        params = shard_field_params(
            stack_field_params(spec, spec.init(jax.random.key(1)), 2),
            mesh,
        )
        params, loss = step(params, jnp.int32(0),
                            *shard_field_batch(batch, mesh))
        outs[flag] = (jax.device_get(params), float(loss))
    np.testing.assert_allclose(outs[True][1], outs[False][1], rtol=1e-6)
    np.testing.assert_allclose(outs[True][0]["vw"], outs[False][0]["vw"],
                               rtol=1e-5, atol=1e-6)
