"""Network-fault plane + bidirectional autoscaler tests (ISSUE 19).

The load-bearing contracts:

- **seeded net-fault grammar** — ``net_connect``/``net_send``/
  ``net_recv`` rules (peer-scoped, occurrence-ranged) parse eagerly,
  reject typos eagerly, and replay deterministically — the partition
  schedule IS its repro;
- **exactly-once at the transport seam** — a ``net_recv`` fault after
  response bytes arrived is NEVER replayed on a fresh connection
  (``TransportFailure.retry_safe``); connect/send faults and
  zero-byte recv faults retry transparently — the PR-17 kill-mid-burst
  semantics survive the network fault plane;
- **bounded autoscaling** — grow needs SUSTAINED shed, shrink needs
  idle padding with zero shed, every decision starts a cooldown, and
  the summary/journal expose flapping for the auditor;
- **partition is not a crash** — the seed-0 acceptance drill
  partitions one replica mid-flash-crowd: accepted traffic retries
  onto the survivor, the victim is drained then READMITTED after the
  plan clears (process alive the whole time, zero respawns), and
  ``audit_fleet`` proves it from artifacts alone.

Arming ``net_connect``, ``net_send``, and ``net_recv`` here also
satisfies fmlint's registry-coverage rule for the new points.
"""

import http.server
import json
import socket
import threading
import time

import pytest

from fm_spark_tpu import obs
from fm_spark_tpu.resilience import chaos, faults, netfaults
from fm_spark_tpu.resilience.chaos_audit import audit_fleet
from fm_spark_tpu.resilience.netfaults import TransportFailure
from fm_spark_tpu.serve import AdmissionController, loadgen
from fm_spark_tpu.serve import fleet as fleet_mod
from fm_spark_tpu.serve.autoscale import Autoscaler
from fm_spark_tpu.utils.logging import read_events


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------ the plan grammar


def test_net_rules_expand_ranges_and_scope_peers():
    plan = faults.FaultPlan.from_spec(
        "net_connect.replica-1@3-9=refuse;net_send@1=reset;"
        "net_recv@2=truncate_after:16")
    for n in range(3, 10):
        r = plan.rule_for("net_connect.replica-1", n)
        assert r is not None and r.action == "refuse"
    assert plan.rule_for("net_connect.replica-1", 2) is None
    assert plan.rule_for("net_connect.replica-1", 10) is None
    # The scoped key is its own point: the unscoped base never fires.
    assert plan.rule_for("net_connect", 3) is None
    assert plan.rule_for("net_recv", 2).param == "16"


@pytest.mark.parametrize("spec", [
    "train_step.replica-1@1=error",   # peer scope off a net point
    "train_step@1=refuse",            # net action off a net point
    "net_recv@1=slow_ms",             # missing required parameter
    "net_recv@1=truncate_after:lots", # non-numeric parameter
    "net_connect@9-3=refuse",         # inverted range
    "net_connect@1-600=refuse",       # window wider than _MAX_RANGE
    "net_bogus@1=refuse",             # unknown point
])
def test_net_grammar_rejects_typos_eagerly(spec):
    with pytest.raises(ValueError):
        faults.FaultPlan.from_spec(spec)


def test_check_advances_scoped_and_fleetwide_counters():
    """"This peer's Nth dial" and "the fleet's Nth dial" count
    independently, and the peer-scoped rule wins when both match."""
    faults.activate("net_connect.replica-1@2=refuse;"
                    "net_connect@1=blackhole")
    # Event 1: unscoped occurrence 1 matches; scoped (occ 1) doesn't.
    assert netfaults.check("net_connect", "replica-1").action == (
        "blackhole")
    # Event 2: scoped occurrence 2 fires AND wins.
    assert netfaults.check("net_connect", "replica-1").action == (
        "refuse")
    assert netfaults.check("net_connect", "replica-1") is None
    # A different peer never consumed replica-1's counter.
    faults.activate("net_connect.replica-1@1=refuse")
    assert netfaults.check("net_connect", "replica-0") is None
    assert netfaults.check("net_connect", "replica-1").action == (
        "refuse")


def test_transport_failure_retry_safe_gate():
    assert TransportFailure("x", phase="connect").retry_safe
    assert TransportFailure("x", phase="send").retry_safe
    # Recv with zero bytes: the replica died before answering (the
    # PR-17 kill semantics) — replay is safe.
    assert TransportFailure("x", phase="recv",
                            bytes_received=0).retry_safe
    # Recv AFTER bytes arrived: the replica answered — never replay.
    assert not TransportFailure("x", phase="recv",
                                bytes_received=1).retry_safe


def test_net_actions_emulate_their_socket_errors():
    faults.activate("net_connect@1=refuse")
    with pytest.raises(ConnectionRefusedError):
        netfaults.on_connect(None)
    faults.activate("net_send@1=refuse")
    with pytest.raises(ConnectionResetError):
        netfaults.on_send(None)
    faults.activate("net_send@1=reset")
    with pytest.raises(ConnectionResetError):
        netfaults.on_send(None)
    # truncate_after returns a byte budget on recv only; on send it
    # degrades to a dead connection (nothing the server parsed).
    faults.activate("net_recv@1=truncate_after:7")
    assert netfaults.on_recv(None) == 7
    faults.activate("net_send@1=truncate_after:7")
    with pytest.raises(ConnectionResetError):
        netfaults.on_send(None)
    # slow_ms injects latency then PROCEEDS.
    faults.activate("net_recv@1=slow_ms:30")
    t0 = time.monotonic()
    assert netfaults.on_recv(None) is None
    assert time.monotonic() - t0 >= 0.025
    # blackhole sleeps min(caller timeout, cap) then times out.
    faults.activate("net_connect@1=blackhole")
    t0 = time.monotonic()
    with pytest.raises(socket.timeout):
        netfaults.on_connect(None, timeout_s=0.05)
    assert 0.03 <= time.monotonic() - t0 < 2.0
    # Non-net actions on a net point fall through to the generic fire.
    faults.activate("net_send@1=error")
    with pytest.raises(faults.FaultInjected):
        netfaults.on_send(None)


# ------------------------- the transport seam, against a live server


class _ReplicaStub(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length") or 0))
        with self.server.count_lock:
            self.server.handled += 1
            n = self.server.handled
        body = json.dumps({"ok": True, "n": n}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


@pytest.fixture()
def _stub():
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                          _ReplicaStub)
    srv.handled = 0
    srv.count_lock = threading.Lock()
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv, srv.server_address[1]
    srv.shutdown()
    srv.server_close()


def _dispatch(port, pool=None, peer=None, timeout_s=10.0):
    return fleet_mod._http_json("127.0.0.1", port, "POST", "/predict",
                                body={"x": 1}, timeout_s=timeout_s,
                                pool=pool, peer=peer)


def test_send_fault_on_reused_socket_retries_fresh_once(_stub):
    """A send-phase fault means the replica never saw the request:
    the pooled dispatch retries ONCE on a fresh dial and the client
    never sees the hiccup."""
    srv, port = _stub
    pool = fleet_mod.ConnectionPool("127.0.0.1", port,
                                    peer="replica-0")
    try:
        st, _ = _dispatch(port, pool=pool, peer="replica-0")
        assert st == 200 and srv.handled == 1  # parks the socket
        faults.activate("net_send@1=reset")
        st, doc = _dispatch(port, pool=pool, peer="replica-0")
        assert st == 200 and doc["ok"]
        # The struck attempt died before any bytes left: exactly one
        # MORE request reached the server, on the retry dial.
        assert srv.handled == 2
    finally:
        pool.close()


def test_recv_fault_after_response_bytes_is_never_replayed(_stub):
    """THE exactly-once pin (ISSUE 19 satellite): before this PR the
    pooled retry replayed ANY reused-socket failure — including a recv
    failure after the replica had executed and answered, which scores
    the request twice. A truncated response must fail upward instead,
    with the phase/bytes evidence attached."""
    srv, port = _stub
    pool = fleet_mod.ConnectionPool("127.0.0.1", port,
                                    peer="replica-0")
    try:
        st, _ = _dispatch(port, pool=pool, peer="replica-0")
        assert st == 200 and srv.handled == 1  # parks the socket
        faults.activate("net_recv@1=truncate_after:2")
        with pytest.raises(TransportFailure) as ei:
            _dispatch(port, pool=pool, peer="replica-0")
        assert ei.value.phase == "recv"
        assert ei.value.bytes_received > 0
        assert not ei.value.retry_safe
        # The replica executed the truncated request ONCE — and the
        # buggy replay (a third server-side execution) never happened.
        assert srv.handled == 2
        # The poisoned socket was closed, not parked; the next
        # dispatch dials fresh and works.
        assert pool._idle == []
        st, _ = _dispatch(port, pool=pool, peer="replica-0")
        assert st == 200 and srv.handled == 3
    finally:
        pool.close()


def test_fresh_socket_fault_propagates_without_retry(_stub):
    """The one-retry budget is for STALE REUSE only: a fresh dial's
    failure is real and goes upward (the fleet's cross-replica retry
    owns it, with its own exactly-once gate)."""
    srv, port = _stub
    pool = fleet_mod.ConnectionPool("127.0.0.1", port,
                                    peer="replica-0")
    try:
        faults.activate("net_connect@1=refuse")
        with pytest.raises(TransportFailure) as ei:
            _dispatch(port, pool=pool, peer="replica-0")
        assert ei.value.phase == "connect" and ei.value.retry_safe
        assert srv.handled == 0
    finally:
        pool.close()


def test_blackhole_window_heals_by_construction(_stub):
    """An occurrence-ranged blackhole IS a bounded partition: dials
    time out (bounded by the caller's timeout) for the window, then
    the link heals with no operator action."""
    srv, port = _stub
    faults.activate("net_connect@1-2=blackhole")
    for _ in range(2):
        t0 = time.monotonic()
        with pytest.raises(TransportFailure) as ei:
            _dispatch(port, timeout_s=0.1)
        assert ei.value.phase == "connect"
        assert time.monotonic() - t0 < 2.0  # capped by timeout_s
    st, doc = _dispatch(port, timeout_s=5.0)  # window exhausted
    assert st == 200 and doc["ok"] and srv.handled == 1


def test_connection_pool_survives_concurrent_hammering(_stub):
    """Six threads share one pool: every dispatch lands exactly once,
    the idle shelf never exceeds its bound, and at least some
    dispatches ride parked sockets."""
    srv, port = _stub
    pool = fleet_mod.ConnectionPool("127.0.0.1", port, max_idle=3,
                                    peer="replica-0")
    reused = obs.counter("fleet.dispatch_reused_connection_total")
    c0 = reused.value
    errors = []

    def worker():
        try:
            for _ in range(8):
                st, doc = _dispatch(port, pool=pool, peer="replica-0")
                assert st == 200 and doc["ok"]
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        assert errors == []
        assert srv.handled == 48
        assert len(pool._idle) <= pool.max_idle
        assert reused.value > c0
    finally:
        pool.close()


# ------------------------------------------- the autoscaling policy


class _Journal:
    def __init__(self):
        self.events = []

    def emit(self, event, **kw):
        self.events.append({"event": event, **kw})


def _tick(a, *, shed=0, accepted=0, rows=0, padded=0, n_ready=2,
          n_live=2):
    return a.tick(shed_total=shed, accepted_total=accepted,
                  rows_total=rows, padded_rows_total=padded,
                  n_ready=n_ready, n_live=n_live)


def test_autoscaler_rejects_nonsense_knobs():
    for kw in ({"min_replicas": 0}, {"max_replicas": 1,
                                     "min_replicas": 2},
               {"grow_shed_frac": 1.5}, {"shrink_fill": -0.1}):
        with pytest.raises(ValueError):
            Autoscaler(**kw)


def test_autoscaler_grows_only_on_sustained_shed_then_cools_down():
    j = _Journal()
    a = Autoscaler(min_replicas=1, max_replicas=4, sustain_ticks=2,
                   cooldown_ticks=3, journal=j)
    assert _tick(a) is None                       # baseline only
    assert _tick(a, shed=10, accepted=10) is None  # streak 1
    assert _tick(a, shed=20, accepted=20) == "grow"
    (ev,) = j.events
    assert ev["event"] == "autoscale_decision"
    assert ev["action"] == "grow" and ev["to_n"] == 3
    assert ev["shed_frac"] == 0.5
    # Cooldown: three ticks of heavy shed accrue NOTHING...
    for shed in (30, 40, 50):
        assert _tick(a, shed=shed, accepted=shed) is None
    # ...then pressure must re-sustain from scratch.
    assert _tick(a, shed=60, accepted=60) is None
    assert _tick(a, shed=70, accepted=70) == "grow"
    assert a.summary()["grows"] == 2


def test_autoscaler_shrinks_on_idle_padding_and_honors_bounds():
    a = Autoscaler(min_replicas=1, max_replicas=4, sustain_ticks=1,
                   cooldown_ticks=0)
    assert _tick(a) is None
    # Mostly-padding batches with zero shed: the shrink signal.
    assert _tick(a, rows=2, padded=98) == "shrink"
    # At the floor the same signal holds instead.
    assert _tick(a, rows=4, padded=196, n_ready=1) is None
    # At the ceiling sustained shed holds instead of growing.
    b = Autoscaler(min_replicas=1, max_replicas=2, sustain_ticks=1,
                   cooldown_ticks=0)
    assert _tick(b) is None
    assert _tick(b, shed=10, accepted=0, n_live=2) is None
    # The dead band between the hysteresis edges resets streaks.
    c = Autoscaler(sustain_ticks=2, cooldown_ticks=0)
    assert _tick(c) is None
    assert _tick(c, shed=10, accepted=10) is None         # streak 1
    assert _tick(c, accepted=20, rows=100, padded=0) is None  # band
    assert _tick(c, shed=20, accepted=30) is None  # streak 1 again


def test_autoscaler_summary_counts_direction_changes():
    a = Autoscaler(min_replicas=1, max_replicas=4, sustain_ticks=1,
                   cooldown_ticks=0)
    _tick(a)
    assert _tick(a, shed=10, n_live=2) == "grow"
    assert _tick(a, shed=10, accepted=10, rows=1, padded=99,
                 n_ready=3, n_live=3) == "shrink"
    assert _tick(a, shed=20, accepted=10, n_live=2) == "grow"
    s = a.summary()
    assert s["grows"] == 2 and s["shrinks"] == 1
    assert s["direction_changes"] == 2
    assert [d[0] for d in s["decisions"]] == ["grow", "shrink",
                                              "grow"]


# ------------------------------------- seeded partition schedules


def test_partition_schedule_is_pure_and_covers_scenarios():
    seen = set()
    for seed in range(8):
        a = chaos.partition_schedule(seed)
        assert a == chaos.partition_schedule(seed)
        a.validate()
        assert a.shape in loadgen.SHAPES
        seen.add(a.scenario)
        if a.victim is not None:
            assert f"replica-{a.victim}" in a.plan
    assert seen == set(chaos._PARTITION_SCENARIOS)
    # Scenario semantics: a severed link names its victim; slow links
    # and fleet-wide truncation are faults, not partitions.
    flash = chaos.partition_schedule(0)
    assert flash.scenario == "partition_flash_crowd"
    assert flash.victim is not None and "refuse" in flash.plan
    slow = chaos.partition_schedule(1)
    assert slow.scenario == "slow_link_reload"
    assert slow.victim is None and slow.publish_mid_replay
    assert "slow_ms" in slow.plan
    trunc = chaos.partition_schedule(2)
    assert trunc.victim is None and "truncate_after" in trunc.plan


def test_partition_storm_shape_retries_everything():
    sched = loadgen.make_schedule("partition_storm", 0,
                                  duration_s=1.0, base_rps=40.0)
    assert sched.n_requests > 0
    assert all(e.max_retries >= 3 for e in sched.events)
    # The mid-replay surge exists: offered rate is front-loaded
    # around 55% of the window.
    mid = [e for e in sched.events
           if 0.5 <= e.t_offset_s / 1.0 <= 0.8]
    assert len(mid) > 0.3 * sched.n_requests


# ----------------------------- the auditor's partition extensions


def _counters(**kw):
    base = {k: 0 for k in ("accepted", "answered", "shed",
                           "shed_queue", "shed_deadline", "rejected",
                           "timeout", "failed", "retries")}
    base.update(kw)
    return base


def _fev(*pairs):
    return [{"event": ev, "replica": rep} for ev, rep in pairs]


def test_audit_fleet_partition_victim_timeline():
    ok = _fev(("replica_drained", 1), ("replica_ready", 1))
    assert audit_fleet([], _counters(), fleet_events=ok,
                       partition_victim=1) == []
    # Never drained: the fault plane missed the health poller.
    v = audit_fleet([], _counters(),
                    fleet_events=_fev(("replica_ready", 1)),
                    partition_victim=1)
    assert any(x["invariant"] == "partition_not_a_crash"
               and "never drained" in x["detail"] for x in v)
    # Drained, never readmitted after heal.
    v = audit_fleet([], _counters(),
                    fleet_events=_fev(("replica_drained", 1)),
                    partition_victim=1)
    assert any("never readmitted" in x["detail"] for x in v)
    # Respawned between drain and readmission: a live replica was
    # treated as a crash — the respawn budget was wasted.
    crashed = _fev(("replica_drained", 1), ("replica_down", 1),
                   ("replica_spawn", 1), ("replica_ready", 1))
    v = audit_fleet([], _counters(), fleet_events=crashed,
                    partition_victim=1)
    assert any("treated as a crash" in x["detail"] for x in v)
    # Another replica's crash does not implicate the victim.
    other = ok + _fev(("replica_down", 0), ("replica_spawn", 0),
                      ("replica_ready", 0))
    assert audit_fleet([], _counters(), fleet_events=other,
                       partition_victim=1) == []


def test_audit_fleet_bounds_autoscale_decisions_and_flapping():
    def _dec(*actions):
        return [{"event": "autoscale_decision", "action": a}
                for a in actions]

    assert audit_fleet([], _counters(),
                       fleet_events=_dec("grow", "grow"),
                       max_autoscale_decisions=3) == []
    v = audit_fleet([], _counters(),
                    fleet_events=_dec("grow", "grow", "grow", "grow"),
                    max_autoscale_decisions=3)
    assert any(x["invariant"] == "autoscale_converged"
               and "did not converge" in x["detail"] for x in v)
    v = audit_fleet([], _counters(),
                    fleet_events=_dec("grow", "shrink", "grow"),
                    max_autoscale_decisions=3)
    assert any("flapped" in x["detail"] for x in v)


# ------------------------------------ seeded Retry-After de-clumping


def test_retry_after_jitter_is_seeded_and_bounded():
    def sheds(seed, n=6):
        adm = AdmissionController("interactive:1:500",
                                  service_est_ms=50.0,
                                  retry_jitter_frac=0.5,
                                  jitter_seed=seed)
        out = []
        for _ in range(n):
            v = adm.admit("interactive", 10.0)  # unpayable: est 50ms
            assert v.decision == "shed_deadline"
            out.append(v.retry_after_ms)
        return out

    a, b = sheds(7), sheds(7)
    assert a == b, "same seed, same de-clumping: drills replay"
    assert sheds(8) != a
    base = AdmissionController("interactive:1:500",
                               service_est_ms=50.0,
                               retry_jitter_frac=0.0)
    flat = base.admit("interactive", 10.0).retry_after_ms
    assert all(flat <= x <= 1.5 * flat for x in a)
    assert len(set(a)) > 1, "the hint VARIES — waves de-clump"
    with pytest.raises(ValueError):
        AdmissionController(retry_jitter_frac=1.5)


# ----------------------- the acceptance drill (a real fleet, seed 0)


def test_partition_flash_crowd_drill_green(tmp_path):
    """THE acceptance drill (ISSUE 19): seed 0 severs the parent's
    link to one replica (dials refused, writes reset) right as a
    flash crowd lands. Accepted traffic retries onto the survivor,
    the victim is suspected -> drained -> readmitted once the plan's
    occurrence window clears, and ``audit_fleet`` grades all of it —
    exactly-once across partition + retry, closed books, zero
    respawns spent on a live process, bounded autoscale decisions —
    from the tap + counters + journal slice alone. Reproducible from
    the seed: the schedule printed in a failing entry IS the repro."""
    cfg = chaos.FleetDrillConfig(autoscale_max=3)
    sched = chaos.partition_schedule(0, n_replicas=cfg.n_replicas)
    assert sched.scenario == "partition_flash_crowd"
    assert sched == chaos.partition_schedule(0,
                                             n_replicas=cfg.n_replicas)
    ctx = chaos.build_fleet_stack(cfg, str(tmp_path))
    try:
        entry = chaos.run_partition_schedule(
            sched, cfg, ctx, str(tmp_path / "p0"))
    finally:
        ctx["door"].stop()
        ctx["ck"].close()
    assert entry["outcome"] == "completed"
    assert entry["verdict"] == "green", entry["violations"]
    assert entry["victim"] == sched.victim
    assert entry["healed_s"] is not None
    assert entry["traffic"]["requests"] > 0
    # Direct journal check, independent of the auditor: the victim
    # was drained and readmitted with its PROCESS never dying — the
    # partition cost zero respawns.
    events = read_events(str(tmp_path / "fleet_health.jsonl"))
    vic = [e["event"] for e in events
           if e.get("replica") == sched.victim and "event" in e]
    assert "replica_drained" in vic
    assert vic.index("replica_drained") < len(vic) - 1
    assert "replica_ready" in vic[vic.index("replica_drained"):]
    assert "replica_down" not in vic


@pytest.mark.slow
def test_partition_campaign_all_tier1_seeds_green(tmp_path):
    """The full partition half of the chaos campaign: every tier-1
    seed against ONE shared autoscaler-armed fleet, faults cleared
    between schedules, every entry green."""
    entries = chaos.run_partition_campaign(base_dir=str(tmp_path))
    assert ([e["seed"] for e in entries]
            == list(chaos.PARTITION_TIER1_SEEDS))
    for e in entries:
        assert e["outcome"] == "completed"
        assert e["verdict"] == "green", (e["seed"], e["violations"])
        assert e["traffic"]["requests"] > 0
    assert entries[0]["scenario"] == "partition_flash_crowd"
    assert entries[0]["healed_s"] is not None
    assert entries[1]["published_step"] is not None
